"""Headline benchmark (supervisor + child).

Primary metric: event-backtest throughput on the reference's own golden
workload — the shipped 20-ticker x ~2,728-minute panel that takes the
reference's Python event loop 18.4 s (~148 bar-groups/s, measured; BASELINE
.md) on one CPU core.  Same features, same scores, same fills; ours is the
jit-compiled panel engine.

Also reported (in "extra"): the north-star J x K grid — all 16
Jegadeesh-Titman cells on a 3000-stock x 60-year monthly panel in one
compiled call (target < 10 s on a v5e-8; BASELINE.json) — plus a
flops/bytes model of the grid so "fast" is quantified, and the on-platform
golden trade count vs the 28,020-trade reference fingerprint.

Robustness (rounds 1-3 failure modes): the TPU ('axon') backend in this
image can raise UNAVAILABLE *or hang* at init, and it FLAPS — up in
~25-minute windows, down (hanging) between them, so any fixed number of
probes can land entirely inside an outage (round 3: both probes hung and
the round's official record silently degraded to CPU).  The supervisor
therefore

  1. probes backend init in a subprocess with a hard timeout,
  2. runs the real benchmark in a child pinned to the chosen platform,
  3. secures the JSON line with a CPU fallback child (reduced grid size,
     recorded in extra) when the accelerator is down,
  4. then spends ALL remaining budget in a probe/sleep loop waiting for a
     tunnel window, escalating to the accelerator the moment one opens,
  5. persists any successful on-chip capture to BENCH_TPU_LAST.json; when
     every live TPU attempt fails, the most recent verified on-chip
     record is attached under extra.tpu_last_verified with
     "provenance": "session-cached" instead of silently reporting CPU,
  6. ALWAYS prints exactly one JSON line on stdout, with every probe
     attempt (UTC timestamp + exact backend error) recorded in extra:
     {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

No metric in extra is ever a bare null: anything unmeasured carries a
reason string ("skipped: ..." / "not applicable: ...") instead.

Record size discipline (round 4 lost its official record to this): the
driver parses bench stdout through a 2,000-char tail window, and the full
record outgrew it ("parsed": null in BENCH_r04.json).  The supervisor
therefore splits the output: the FULL record — probes, errors, every grid
leg, the histrank comparison — is written to a committed file at the repo
root (BENCH_FULL_${CSMOM_ROUND}.json, default r05), and stdout's single
line is a compact HEADLINE built by _headline(): metric/value/unit/
vs_baseline plus a fixed, size-bounded extra that points at the full
record.  _headline() hard-caps its serialized length at HEADLINE_MAX_CHARS
(pinned by a unit test) and degrades by dropping extra detail, never the
four driver-required fields.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# the golden/demo workload constants (reference data path, ticker universe,
# grid J/K canon, panel sizes) live in csmom_tpu.compile.workloads — shared
# with `csmom warmup` so bench and the AOT pass cannot drift apart
BASELINE_GROUPS_PER_SEC = 148.3  # measured: 18.4 s / 2,728 datetime groups
GOLDEN_TRADES = 28_020           # results/trades.csv fingerprint (SURVEY §2 row 17)
GOLDEN_TRADE_TOL = 4             # documented f32 tolerance: ~2 of 54k threshold
                                 # crossings sit within one f32 ulp of 1e-5
NORTH_STAR_TARGET_S = 10.0       # BASELINE.json: 16-cell grid, 3000x60yr, <10s

# One total wall-clock budget, spent top-down so the CPU fallback always has
# room to run and print its JSON line before any external (driver) timeout:
# probe <= 150s, default-platform child <= what's left minus the CPU
# reserve, CPU child <= what's left.
TOTAL_BUDGET_S = int(os.environ.get("CSMOM_BENCH_BUDGET", "1500"))
PROBE_TIMEOUT_S = int(os.environ.get("CSMOM_BENCH_PROBE_TIMEOUT", "150"))
CPU_RESERVE_S = 570   # observed CPU child wall: ~130s cold.  Sized so that
                      # after a TPU attempt burns its whole slice, the CPU
                      # fallback's own timeout (this minus the ~270s probe-loop
                      # reserve) still clears that wall with its deadline
                      # watchdog margin to spare — the fallback must produce a
                      # FULL record, not a watchdog partial
if os.environ.get("CSMOM_BENCH_SMOKE"):
    # a smoke child still compiles the headline pipeline and the reduced
    # grid (~60-90 s measured warm-ish, worse on a cold machine), so the
    # reserve shrinks to a cold-smoke-child size, not to nothing — the
    # full-size reserve would starve every attempt out of a
    # rehearsal-sized budget
    CPU_RESERVE_S = 240
_DEADLINE = time.monotonic() + TOTAL_BUDGET_S
_CHILD_T0 = time.monotonic()  # child-process start, for its own sub-budget

# Smoke mode (CSMOM_BENCH_SMOKE=1): the full pipeline shape — probe,
# child, headline, grid leg, deadline guard, record split — with every
# optional heavyweight leg skipped (with a reason, never silently).  This
# is what `csmom rehearse` drives so a CPU-only machine can rehearse every
# fault in minutes, and it is honest about itself in the record.
SMOKE = bool(os.environ.get("CSMOM_BENCH_SMOKE"))
SMOKE_REASON = "skipped: smoke mode (CSMOM_BENCH_SMOKE=1 — rehearsal runs " \
               "the pipeline, not the workload)"


def _chaos(point: str, **ctx):
    """Chaos checkpoint (csmom_tpu.chaos): a no-op — one environ lookup,
    no imports — unless a fault plan OR telemetry is armed, so a fully
    disarmed supervisor stays package-import-free and the measurement
    path stays unperturbed.  Armed telemetry routes through the real
    checkpoint so every chaos site doubles as a timeline event."""
    env = os.environ
    if "CSMOM_FAULT_PLAN" not in env and env.get("CSMOM_TELEMETRY",
                                                 "0") in ("", "0"):
        return None
    from csmom_tpu.chaos.inject import checkpoint

    return checkpoint(point, **ctx)


# -- run telemetry (csmom_tpu.obs) -------------------------------------------
#
# Default ON: the TELEMETRY_<round>.json sidecar is part of a round's
# evidence exactly like the FULL record — phases (warmup/probe/compile/
# row/land), span walls, and the metrics snapshot, readable via `csmom
# timeline <round>` instead of reconstructed from prints.  CSMOM_TELEMETRY=0
# disarms the whole layer (span() collapses to a shared no-op; the
# supervisor then never imports the package), which is the knob the
# <1%-overhead acceptance check flips.  The event stream is a scratch
# JSONL in tmp that supervisor and children (env inheritance) append to;
# the committed artifact is the assembled sidecar.

# (obs module, root span, owned scratch-stream path or None) once armed
_TEL = None


def _tel_start():
    """Arm supervisor telemetry (unless CSMOM_TELEMETRY=0) and open the
    run's root span.  The arming decision is the shared
    obs.spans.arm_policy: an operator-provided env contract is honored,
    not clobbered; only a blank env gets the default tmp scratch stream
    (which _tel_finish deletes once the sidecar has landed)."""
    global _TEL
    if os.environ.get("CSMOM_TELEMETRY", "") == "0":
        return  # before the package import: a disarmed supervisor stays light
    import tempfile

    from csmom_tpu import obs

    default = os.path.join(
        tempfile.gettempdir(),
        f"csmom_telemetry_{ROUND}_{os.getpid()}.jsonl",
    )
    col = (obs.spans.current_collector() if obs.armed() else
           obs.arm_policy("bench-supervisor", default_path=default,
                          run_id=ROUND))
    if col is None:
        return
    root = obs.span("bench.supervisor", root=True)
    root.__enter__()
    _TEL = (obs, root, default if col.path == default else None)


def _tel_span(name: str, **attrs):
    """A supervisor-side span; a no-op context manager when disarmed."""
    if _TEL is None:
        import contextlib

        return contextlib.nullcontext()
    return _TEL[0].span(name, **attrs)


def _tel_finish(out_dir: str):
    """Close the root span and land the TELEMETRY sidecar (the shared
    obs.timeline finish sequence: full stream file, child metrics
    outrank ours, disarm, never raise).  Returns the sidecar name or a
    reason string — telemetry failure must never cost the headline."""
    global _TEL
    if _TEL is None:
        return "not captured: telemetry disarmed (CSMOM_TELEMETRY=0)"
    obs, root, owned_stream = _TEL
    _TEL = None
    # the landing step is about to run with the collector closed, so its
    # breadcrumb goes in NOW — "reached the land step" must be readable
    # off the timeline even when the record write itself dies (the chaos
    # bench.land faults)
    obs.point("bench.land", record=FULL_RECORD_NAME)
    root.__exit__(None, None, None)
    from csmom_tpu.obs import metrics as obs_metrics
    from csmom_tpu.obs import timeline as obs_tl

    try:
        fallback = obs_metrics.snapshot()
    except Exception:
        fallback = None
    # our own default arming (owned scratch stream, run id = ROUND) may
    # overwrite the round's sidecar across reruns; an operator-armed run
    # carries a foreign run id and must not clobber committed evidence
    name = obs_tl.finish_and_write(out_dir, fallback_metrics=fallback,
                                   overwrite=owned_stream is not None)
    if owned_stream and name.startswith("TELEMETRY_"):
        # our scratch stream is fully represented by the landed sidecar;
        # an operator-provided stream (or a failed landing) is kept
        try:
            os.remove(owned_stream)
        except OSError:
            pass
    return name


def _remaining() -> float:
    return max(30.0, _DEADLINE - time.monotonic())


# ---------------------------------------------------------------- child ----
#
# The child's input builders (golden event panels, packed grid panels) and
# its jitted entry wrappers live in csmom_tpu.compile.{workloads,entries} —
# shared with `csmom warmup` so the AOT pass and the bench child compile
# byte-identical HLO and the serialized-executable cache connects them.


def child_main():
    import jax

    # Persistent compile cache: tunneled-TPU compiles are the dominant cost
    # of a child (r4: they alone overran the attempt's external timeout), and
    # they are identical across attempts — let a partial first attempt pay
    # for a complete second one.  Shared with `csmom warmup` and the
    # scaling/phases capture scripts ("bench" dir); separate from the test
    # tier's cache, whose shapes are deliberately tiny.  min_compile_s=0
    # mirrors the warmup's floor: every fresh compile is persisted AND the
    # cache-write counter becomes an exact in-window fresh-compile count.
    from csmom_tpu.utils.jit_cache import enable_persistent_cache

    # None when CSMOM_JIT_CACHE=0: the hit/miss events never fire then, so
    # all cache-derived counts below must degrade to a reason string, not 0
    _cache_dir = enable_persistent_cache("bench", min_compile_s=0.0)

    if os.environ.get("CSMOM_BENCH_FORCE_CPU"):
        # env JAX_PLATFORMS=cpu is set too, but this image's sitecustomize can
        # capture env before us; config.update is the post-import override
        jax.config.update("jax_platforms", "cpu")

    from csmom_tpu.backtest.event import event_backtest
    from csmom_tpu.compile import workloads as wl
    from csmom_tpu.registry import entry_factory
    from csmom_tpu.utils.profiling import compile_stats

    # the hot-entry factories come from the engine registry (ISSUE 9):
    # the same lru-shared callables `csmom warmup` lowers, fetched by
    # registered name instead of a per-module import list
    grid_scalar_fn = entry_factory("grid.jk")
    batched_event_fn = entry_factory("event.panel")

    # telemetry: join the supervisor's event stream (env contract) — or
    # stay disarmed, in which case every span below is the shared no-op
    from csmom_tpu import obs
    from csmom_tpu.obs import metrics as obs_metrics

    obs.arm_from_env("bench-child")
    # registered before any leg runs: a record showing rows_landed=0 must
    # mean "no leg completed", never "counting not wired"
    obs_metrics.counter("bench.rows_landed")
    _root_sp = obs.span("bench.child", root=True)
    _root_sp.__enter__()

    platform, on_cpu, dtype = wl.bench_platform(jax)
    _stats0 = compile_stats()  # child-lifetime base for the compile totals

    # per-leg compile accounting: the first (compiling) call of every leg
    # runs through here so the FULL record carries each shape's compile
    # wall and whether it was served from the serialized-executable cache
    # (cache floor 0 above makes fresh_compiles an exact count)
    _LEGS: dict = {}

    def _compiled_leg(name: str, first_call):
        _chaos("bench.compile", leg=name)
        b = compile_stats()
        t0 = time.perf_counter()
        with obs.span("bench.compile", leg=name):
            first_call()
        d = compile_stats().delta(b)
        rec = {"compile_wall_s": round(time.perf_counter() - t0, 4)}
        if _cache_dir is not None:
            rec["served_from_cache"] = d.cache_hits
            rec["fresh_compiles"] = d.cache_misses
        else:
            rec["cache_accounting"] = ("not measurable: persistent cache "
                                       "disabled (CSMOM_JIT_CACHE=0)")
        _LEGS[name] = rec

    # Child sub-budget: on a flapping tunnel the supervisor may catch a
    # window with only a few minutes left, so every optional leg yields to
    # the budget (with a recorded reason) rather than running the child off
    # the end of the window.  Priority: event headline -> north-star rank
    # grid -> everything else.
    _child_budget = float(os.environ.get("CSMOM_BENCH_CHILD_BUDGET", "0") or 0)

    def _child_left() -> float:
        if not _child_budget:
            return float("inf")
        return _child_budget - (time.monotonic() - _CHILD_T0)

    def _r4(x):
        """A measured wall rounds; a skip/fail reason string passes through."""
        return round(x, 4) if isinstance(x, float) else x

    # Deadline guard (r4 failure mode: the TPU child overran its external
    # timeout — tunneled compiles are slow — and was SIGKILLed, losing the
    # already-measured headline and with it the round's on-chip record).
    # _PROG is filled progressively as legs complete; at the deadline the
    # guard dumps whatever is measured as an explicitly-partial record so
    # the supervisor still gets a parseable on-platform line.  Anchored to
    # _CHILD_T0 (process start): jax init time must count against the
    # budget, not extend it past the external SIGKILL.
    from csmom_tpu.utils.deadline import deadline_guard

    _PROG: dict = {}

    def _partial_line():
        if "value" not in _PROG:
            return None  # headline not yet measured: nothing worth a line
        ex = dict(_PROG.get("extra", {}))
        ex["partial"] = (
            "child deadline hit before every leg completed; unmeasured "
            "legs are absent (watchdog dump, not a full record)"
        )
        return json.dumps({
            "metric": "intraday_event_backtest_bar_groups_per_sec",
            "value": _PROG["value"],
            "unit": "bar_groups/s",
            "vs_baseline": _PROG["vs_baseline"],
            "extra": ex,
        })

    _finish = deadline_guard(
        "CSMOM_BENCH_CHILD_BUDGET", _partial_line, t0=_CHILD_T0
    )

    # Timing discipline: every timed rep fetches a scalar result to host
    # (see csmom_tpu.utils.profiling.fetch — block_until_ready does not
    # reliably sync on the tunneled backend).  The tiny-op RTT is the floor
    # such walls cannot go under, and is itself reported in extra.
    from csmom_tpu.utils.profiling import fetch, measure_rtt

    rtt_s = measure_rtt(dtype)

    # -- golden event workload (the headline metric) ------------------------
    price, valid, score, adv, vol, n_trades = wl.golden_event_inputs(dtype)
    n_bars = int(np.asarray(valid).any(axis=0).sum())

    # Raw repeat samples (perf-ledger contract): every timed leg records
    # its PER-REP walls, not only the mean — `csmom ledger diff/gate`
    # needs the sample distribution to put a bootstrap CI behind a
    # regression verdict instead of a bare delta.  Keyed by the same
    # extra field name as the leg's aggregate, so the ledger joins them
    # without a mapping table.  Lives in the FULL record only (the
    # headline digest has a fixed key set and never carries lists).
    _SAMPLES: dict = {}

    def _timed_reps(n: int, one_rep):
        """``(mean_wall, per_rep_walls)`` of n reps, each individually
        timed — the tuple keeps a leg's samples structurally tied to its
        mean, so a failed leg can never leave stale samples behind for
        the next key to pick up."""
        walls = []
        for _ in range(n):
            t0 = time.perf_counter()
            one_rep()
            walls.append(time.perf_counter() - t0)
        return sum(walls) / n, [round(w, 6) for w in walls]

    run = lambda: fetch(event_backtest(price, valid, score, adv, vol).total_pnl)
    _compiled_leg("event.golden", run)  # compile (or cache load)
    reps = 20
    with obs.span("bench.row", row="event.golden", reps=reps):
        dt, _SAMPLES["event_backtest_wall_s"] = _timed_reps(reps, run)
    obs_metrics.counter("bench.rows_landed").inc()
    groups_per_sec = n_bars / dt
    _PROG.update({
        "value": round(groups_per_sec, 1),
        "vs_baseline": round(groups_per_sec / BASELINE_GROUPS_PER_SEC, 1),
        "extra": {
            "platform": platform,
            "device_kind": str(jax.devices()[0].device_kind),
            "workload": f"golden 20x{n_bars} minute panel, "
                        f"{n_trades} trades ({np.dtype(dtype).name})",
            "tiny_op_rtt_s": round(rtt_s, 6),
            "event_backtest_wall_s": round(dt, 6),
            "golden_trades": n_trades,
            "golden_trades_ref": GOLDEN_TRADES,
            "golden_ok": abs(n_trades - GOLDEN_TRADES) <= GOLDEN_TRADE_TOL,
        },
    })
    # live reference: legs recorded after this point (and the final compile
    # totals) show up in a watchdog partial dump too
    _PROG["extra"]["compile_legs"] = _LEGS
    _PROG["extra"]["samples"] = _SAMPLES  # live dict: grid legs append
    _PROG["extra"]["samples_note"] = (
        "per-rep raw walls (s) keyed by the matching aggregate field — "
        "the ledger's bootstrap-CI regression input (obs.regress)"
    )
    # measured-row boundary: the headline is in _PROG, the grid legs are
    # not — the r5 chaos plans (hang / expired deadline / SIGKILL between
    # rows) all fire here, and the invariant is that the headline above
    # still lands in a partial record
    _chaos("bench.row", row="headline")
    _stall = float(os.environ.get("CSMOM_BENCH_STALL_S", "0") or 0)
    if _stall:  # test hook: a tunnel that hangs right after the headline —
        time.sleep(_stall)  # the watchdog must turn this into a partial dump

    # -- north-star grid: 16 cells; full 3000 x 60yr on the accelerator,
    #    reduced (recorded) on the CPU fallback so the fallback still
    #    completes inside the driver timeout --------------------------------
    if on_cpu:
        # 512 stocks x 15 yr; 5 reps (was 2): the ledger's bootstrap CI
        # needs >= 5 raw samples to back a verdict, and the reduced grid
        # is cheap enough that 3 extra reps cost ~1 s
        (A, T), grid_reps = wl.REDUCED_GRID, 5
    else:
        (A, T), grid_reps = wl.NORTH_STAR_GRID, 5  # the north-star workload
    # At-scale data path: the panel is fed from the packed binary cache
    # (memmapped [A, T] .npy — csmom_tpu.panel.pack) through the SAME
    # builder `csmom warmup` runs (csmom_tpu.compile.workloads), so the
    # pack synthesis, ingest, and month-aggregation compiles are all warm
    # by the time a window opens.  pack_ingest_s is the measured disk ->
    # host wall for the full panel — the number that replaces a CSV parse
    # at 150x the reference's scale.
    pm, mm, M, pack_ingest_s = wl.grid_month_inputs(A, T, dtype)
    Js = np.asarray(wl.GRID_JS)
    Ks = np.asarray(wl.GRID_KS)

    # the grid entry wrappers (scalar reduction INSIDE the jit, so each
    # timed rep is one dispatch + one 4-byte fetch) are the shared
    # compile.entries callables — the exact functions the AOT manifest
    # compiles, hence identical HLO and guaranteed cache connection
    def timed(mode, impl="xla", sample_key=None):
        """One timed grid leg; ``sample_key`` is the extra field its
        aggregate lands in, so the per-rep samples are recorded under
        the SAME name at the same call site — no side table to desync."""
        gfn = grid_scalar_fn(wl.GRID_JS, wl.GRID_KS, wl.GRID_SKIP, mode, impl)
        _compiled_leg(f"grid16.{mode}.{impl}@{A}x{M}",
                      lambda: fetch(gfn(pm, mm)))  # compile + warm the tunnel
        with obs.span("bench.row", row=f"grid16.{mode}.{impl}",
                      reps=grid_reps):
            dt, walls = _timed_reps(grid_reps, lambda: fetch(gfn(pm, mm)))
        if sample_key is not None:
            _SAMPLES[sample_key] = walls
        obs_metrics.counter("bench.rows_landed").inc()
        return dt

    def timed_or_reason(mode, impl="xla", floor_s=120.0, sample_key=None):
        """Run a grid leg if the child budget allows, else a reason string."""
        if SMOKE:
            return SMOKE_REASON
        left = _child_left()
        if left < floor_s:
            return (f"skipped: child budget too small for this leg "
                    f"({int(left)}s left < {int(floor_s)}s floor)")
        try:
            return timed(mode, impl, sample_key=sample_key)
        except Exception as e:
            return f"failed: {type(e).__name__}: {e}"[:200]

    # the north-star number itself is never budget-gated: it is the reason
    # the child exists, and the supervisor only launches a child when at
    # least the child minimum is left
    grid_rank_s = timed("rank", sample_key="grid16_rank_s")
    _chaos("bench.row", row="grid16.rank")
    _PROG["extra"].update({
        "grid16_rank_s": round(grid_rank_s, 4),
        "grid_workload": f"16 cells, {A} stocks x {T} days ({M} months)",
        "grid_is_north_star_size": (A, T) == wl.NORTH_STAR_GRID,
        "north_star_met": bool(
            (A, T) == wl.NORTH_STAR_GRID and grid_rank_s < NORTH_STAR_TARGET_S
        ),
        "pack_ingest_s": round(pack_ingest_s, 4),
    })
    grid_qcut_s = timed_or_reason("qcut", sample_key="grid16_qcut_s")
    _PROG["extra"]["grid16_qcut_s"] = _r4(grid_qcut_s)
    # MXU-form cohort aggregation (membership^T @ returns cross table)
    grid_matmul_s = timed_or_reason("rank", "matmul",
                                    sample_key="grid16_rank_matmul_s")
    _PROG["extra"]["grid16_rank_matmul_s"] = _r4(grid_matmul_s)
    # the fused Pallas cohort kernel only makes sense compiled on the TPU;
    # off-TPU it runs in interpreter mode (correctness tests), far too slow
    # to time at this scale
    grid_pallas_s = (
        "skipped: cpu platform (pallas kernel compiles only on tpu; "
        "interpreter mode is a correctness harness, not timeable at scale)"
        if on_cpu else timed_or_reason(
            "rank", "pallas", sample_key="grid16_rank_pallas_s")
    )
    # bf16-operand MXU form: reduced-precision throughput mode, only
    # meaningful on the accelerator
    grid_bf16_s = (
        "skipped: cpu platform (bf16 MXU operands are a tpu fast path)"
        if on_cpu else timed_or_reason(
            "rank", "matmul_bf16",
            sample_key="grid16_rank_matmul_bf16_s")
    )
    _PROG["extra"]["grid16_rank_pallas_s"] = _r4(grid_pallas_s)
    _PROG["extra"]["grid16_rank_matmul_bf16_s"] = _r4(grid_bf16_s)

    # On the accelerator the single-run event wall is dominated by the
    # tunnel round trip (dt ~ rtt_s), which measures the link, not the
    # chip.  A vmapped batch of B independent backtests amortizes the RTT
    # over B runs — the chip's actual throughput for parameter sweeps /
    # bootstrap batches, reported separately and labeled as such.  Runs
    # AFTER the north-star grid: it is an optional leg and must not burn
    # budget the grid needs (r4: the TPU child died before the grid).
    batched_per_run_s = None
    batched_skip_reason = (
        "skipped: cpu platform (the batched variant exists to amortize the "
        "TPU tunnel RTT; on CPU the single-run wall already measures compute)"
    )
    if not on_cpu and _child_left() < 150:
        batched_skip_reason = (
            "skipped: child budget too small after the grid legs "
            f"({int(_child_left())}s left < 150s floor)"
        )
    elif not on_cpu:
        import jax.numpy as jnp

        B = 32
        # perturb scores per batch lane so no degenerate dedup is possible
        bscore = score[None] * (
            1.0 + 1e-4 * jnp.arange(B, dtype=score.dtype)[:, None, None]
        )
        bat = batched_event_fn(B)  # the shared (manifest-compiled) wrapper
        try:
            _compiled_leg(f"event.batched{B}",
                          lambda: fetch(bat(price, valid, bscore, adv, vol)))
            with obs.span("bench.row", row=f"event.batched{B}"):
                breps = 5
                dt_b, bwalls = _timed_reps(
                    breps, lambda: fetch(bat(price, valid, bscore, adv, vol))
                )
                batched_per_run_s = dt_b / B
            _SAMPLES["event_batched_per_run_s"] = [
                round(w / B, 8) for w in bwalls
            ]
            obs_metrics.counter("bench.rows_landed").inc()
        except Exception as e:  # record the why, keep the headline metric
            batched_skip_reason = (
                f"failed: {type(e).__name__}: {e}"[:200]
            )
    if batched_per_run_s is not None:
        _PROG["extra"]["event_batched_per_run_s"] = round(batched_per_run_s, 6)

    # CPU fallback: additionally time ONE rep of the full north-star-size
    # grid when the child's budget allows — proves full-size compile+memory
    # and bounds the TPU expectation (VERDICT r2 item 3)
    full_rank_s = full_matmul_s = None
    child_left = _child_left()  # inf when unbudgeted (standalone child runs)
    if SMOKE:
        full_rank_s = full_matmul_s = SMOKE_REASON
    elif on_cpu and child_left > 360:  # observed: ~23x the reduced data; compile ~1 min
        try:
            A_f, T_f = wl.NORTH_STAR_GRID
            fpm, fmm, M_f, _ = wl.grid_month_inputs(A_f, T_f, dtype)

            def gf(impl="xla"):
                gfn = grid_scalar_fn(
                    wl.GRID_JS, wl.GRID_KS, wl.GRID_SKIP, "rank", impl
                )
                fetch(gfn(fpm, fmm))

            _compiled_leg(f"grid16.rank.xla@{A_f}x{M_f}", gf)  # compile
            with obs.span("bench.row", row="grid16.full.xla"):
                # one rep by design (the full-size leg exists to prove
                # the compile+memory, not to distribute): a single raw
                # sample — the ledger reports point deltas, never a CI
                full_rank_s, _SAMPLES["grid16_rank_full_s"] = \
                    _timed_reps(1, gf)
            obs_metrics.counter("bench.rows_landed").inc()
        except Exception as e:  # record, never lose the JSON line
            full_rank_s = f"failed: {type(e).__name__}: {e}"[:200]
        # the matmul leg doubles the full-size work: re-check the budget and
        # fail independently so a matmul problem can't discard the measured
        # xla number
        child_left = _child_left()
        if isinstance(full_rank_s, float) and child_left > 3 * full_rank_s + 90:
            try:
                _compiled_leg(f"grid16.rank.matmul@{A_f}x{M_f}",
                              lambda: gf("matmul"))  # compile
                with obs.span("bench.row", row="grid16.full.matmul"):
                    full_matmul_s, _SAMPLES["grid16_rank_matmul_full_s"] = \
                        _timed_reps(1, lambda: gf("matmul"))
                obs_metrics.counter("bench.rows_landed").inc()
            except Exception as e:
                full_matmul_s = f"failed: {type(e).__name__}: {e}"[:200]
        else:
            full_matmul_s = (
                "skipped: child budget too small to double the full-size "
                "work after the xla leg" if isinstance(full_rank_s, float)
                else "skipped: xla full-size leg did not produce a wall to "
                     "budget against"
            )
    elif on_cpu:
        full_rank_s = full_matmul_s = (
            f"skipped: child budget exhausted ({int(child_left)}s left < "
            "360s floor for the full-size compile+run)"
        )
    else:
        full_rank_s = full_matmul_s = (
            "not applicable: the main grid above is already north-star size "
            "on this platform"
        )

    # -- mesh leg (ISSUE 10): the full-size grid through the grid-cell x
    # asset sharded engine, when a mesh is visible.  Its workload key
    # CARRIES the layout + device count, so the ledger never pairs a
    # d=1 wall with a d=8 one; the efficiency ratio rides as extra
    # evidence (info in the ledger — CPU host devices share cores).
    full_sharded_s = None
    full_sharded_workload = "see grid16_rank_full_sharded_s for why absent"
    mesh_efficiency = None
    ndev = jax.device_count()
    if on_cpu:
        ref_wall, spanel, smask = full_rank_s, None, None
        if isinstance(full_rank_s, float):
            spanel, smask = fpm, fmm
            A_s, T_s = A_f, T_f
    else:
        ref_wall, spanel, smask = grid_rank_s, pm, mm
        A_s, T_s = A, T
    if SMOKE:
        full_sharded_s = SMOKE_REASON
    elif ndev < 2:
        full_sharded_s = (
            f"skipped: 1 visible device — the sharded leg measures a mesh "
            "(XLA_FLAGS=--xla_force_host_platform_device_count=8 simulates "
            "one on CPU; a TPU slice provides its own)")
    elif spanel is None:
        full_sharded_s = (
            "skipped: no full-size panel in this child (the single-device "
            "full leg did not run; see grid16_rank_full_s)")
    elif _child_left() <= (2 * ref_wall if isinstance(ref_wall, float)
                           else 0) + 120:
        full_sharded_s = (
            "skipped: child budget too small for the sharded full-size "
            "compile+run after the single-device legs")
    else:
        try:
            import jax.numpy as jnp

            from csmom_tpu.mesh.pinning import shards_for
            from csmom_tpu.mesh.rules import grid_asset_mesh
            from csmom_tpu.parallel.collectives import grid_shard_fn

            g_sh = shards_for(len(wl.GRID_JS), ndev)
            a_sh = shards_for(int(spanel.shape[0]), max(1, ndev // g_sh))
            smesh = grid_asset_mesh(g_sh, a_sh)
            sfn = grid_shard_fn(smesh, wl.GRID_SKIP, 10, "rank",
                                max(wl.GRID_KS), "xla")
            Js_a = np.asarray(wl.GRID_JS)
            Ks_a = np.asarray(wl.GRID_KS)
            M_s = spanel.shape[1]

            def sg():
                spreads, live = sfn(spanel, smask, Js_a, Ks_a)
                fetch(jnp.nansum(jnp.where(live, spreads, 0.0)))

            leg = f"mesh.grid16.rank.xla@{A_s}x{M_s}.g{g_sh}a{a_sh}"
            _compiled_leg(leg, sg)  # compile (or serve from the AOT cache)
            with obs.span("bench.row", row="grid16.full.sharded"):
                full_sharded_s, _SAMPLES["grid16_rank_full_sharded_s"] = \
                    _timed_reps(1, sg)
            obs_metrics.counter("bench.rows_landed").inc()
            full_sharded_workload = (
                f"16 cells, {A_s} stocks x {T_s} days, "
                f"grid{g_sh}xassets{a_sh} mesh, d{ndev}")
            if isinstance(ref_wall, float) and full_sharded_s > 0:
                # efficiency charges the devices the mesh actually
                # spans (g*a), not every visible one — an 8-device host
                # running a 4x1 mesh delivered a 4-way split
                mesh_efficiency = round(
                    ref_wall / (full_sharded_s * g_sh * a_sh), 4)
        except Exception as e:  # record, never lose the JSON line
            full_sharded_s = f"failed: {type(e).__name__}: {e}"[:200]

    # simple cost model of the grid's dominant stage (cohort partial sums:
    # nJ x H horizon-shifted masked reductions over the [A, M] panel) so the
    # wall time maps to achieved bandwidth/flops, not vibes
    nJ, H = len(Js), int(Ks.max())
    itemsize = np.dtype(dtype).itemsize
    grid_bytes = nJ * H * 3 * A * M * itemsize     # labels+ret+valid reads/horizon
    grid_flops = nJ * H * 6 * A * M                # cmp+select+2 FMA per side

    # peak HBM bandwidth by device kind, so achieved GB/s reads as a
    # fraction of the roofline rather than a bare number (VERDICT r2 item 2)
    from csmom_tpu.utils.profiling import PEAK_HBM_GBPS

    peak_gbps = None if on_cpu else PEAK_HBM_GBPS.get(
        jax.devices()[0].device_kind
    )

    # the final record EXTENDS the progressively-filled _PROG extra (single
    # source for every measured value — the watchdog's partial dump and the
    # full record can never disagree on a number) with the annotation keys
    # that only make sense once every leg has resolved
    extra = dict(_PROG["extra"])
    extra.update({
        "timing": "per-rep device_get of a scalar (block_until_ready does "
                  "not reliably sync on tunneled backends)",
        "event_batched_per_run_s": (batched_skip_reason
                                    if batched_per_run_s is None
                                    else round(batched_per_run_s, 6)),
        "event_batched_note": (batched_skip_reason
                               if batched_per_run_s is None else
                               "per-run wall of a 32-wide vmapped batch — "
                               "RTT amortized; the throughput number for "
                               "sweeps/bootstrap, vs the dispatch-inclusive "
                               "single-run wall above"),
        "reference_wall_s": 18.4,
        "pack_ingest_note": f"memmapped binary panel ({A}x{T} f32 values + "
                            "mask) read disk->host from the packed cache "
                            "(csmom_tpu.panel.pack); replaces per-run CSV "
                            "parsing at scale",
        "north_star_target_s": NORTH_STAR_TARGET_S,
        "grid_model_gbytes": round(grid_bytes / 1e9, 3),
        "grid_achieved_gbps": round(grid_bytes / grid_rank_s / 1e9, 1),
        "grid_achieved_gflops": round(grid_flops / grid_rank_s / 1e9, 1),
        "device_kind": str(jax.devices()[0].device_kind),
        "chip_peak_hbm_gbps": (
            peak_gbps if peak_gbps is not None else
            ("not applicable: cpu platform has no HBM roofline table entry"
             if on_cpu else
             f"unknown device kind {jax.devices()[0].device_kind!r}: no "
             "peak-bandwidth table entry")
        ),
        "grid_hbm_fraction": (
            round(grid_bytes / grid_rank_s / 1e9 / peak_gbps, 4)
            if peak_gbps is not None else
            "not applicable: no peak-bandwidth entry for this platform"
        ),
        "grid16_rank_full_s": _r4(full_rank_s),
        "grid16_rank_matmul_full_s": _r4(full_matmul_s),
        "grid_full_workload": (
            "16 cells, 3000 stocks x 15120 days"
            if isinstance(full_rank_s, float)
            else "see grid16_rank_full_s for why the full-size leg is absent"
        ),
        "grid16_rank_full_sharded_s": _r4(full_sharded_s),
        "grid_full_sharded_workload": full_sharded_workload,
        "mesh_scaling_efficiency": (
            mesh_efficiency if mesh_efficiency is not None else
            "not measurable: no (reference wall, sharded wall) pair this "
            "run — see grid16_rank_full_sharded_s"
        ),
    })
    # AOT warm-start accounting: with the child's persistence floor at 0,
    # every fresh compile is also a cache write, so cache_misses is an
    # EXACT in-window fresh-compile count — 0 when `csmom warmup` (or a
    # previous child) already compiled this platform's shapes.  Per-leg
    # walls live in compile_legs (recorded at each leg's first call).
    total_cs = compile_stats().delta(_stats0)
    extra["compile_totals"] = {
        **total_cs.as_dict(),
        # with the cache disabled no hit/miss event ever fires — a hard 0
        # here would read as "fully warm" on a machine that spent the whole
        # window compiling, so degrade to a reason string instead
        "in_window_fresh_compiles": (
            total_cs.cache_misses if _cache_dir is not None else
            "not measurable: persistent cache disabled (CSMOM_JIT_CACHE=0) "
            "— hit/miss events never fire; see backend_compiles for a "
            "lower bound on distinct computations built this window"
        ),
        "note": "cache_misses = persistent-cache writes = fresh compiles at "
                "the 0s floor; cache_hits = serialized executables loaded "
                "instead of compiled; traces vs backend_compiles is the "
                "trace-vs-compile split (inner jits trace during an outer "
                "trace without dispatching)",
    }
    if SMOKE:
        extra["smoke"] = ("smoke-mode record: pipeline-shaped, workload "
                          "reduced — NOT a performance capture")
    # telemetry registry snapshot into the record (rows landed, deadline
    # margin, compile counters + listener state folded in) — the "where
    # did the dispatches go" companion to the walls above
    _margin = _child_left()
    obs_metrics.gauge("bench.deadline_margin_s").set(
        None if _margin == float("inf") else round(_margin, 3))
    extra["metrics"] = (
        obs_metrics.snapshot() if obs.armed() else
        "not captured: telemetry disarmed (CSMOM_TELEMETRY=0)"
    )
    line = json.dumps(
        {
            "metric": "intraday_event_backtest_bar_groups_per_sec",
            "value": round(groups_per_sec, 1),
            "unit": "bar_groups/s",
            "vs_baseline": round(groups_per_sec / BASELINE_GROUPS_PER_SEC, 1),
            "extra": extra,
        }
    )
    _chaos("bench.finish")
    # close the child's root span and mirror the final snapshot into the
    # event stream before the summary lands (a supervisor assembling the
    # sidecar reads it from there)
    _root_sp.set(platform=platform)
    _root_sp.__exit__(None, None, None)
    _col = obs.spans.current_collector()
    if _col is not None:
        _col.emit({"kind": "metrics", "t_s": round(time.monotonic(), 6),
                   "data": obs_metrics.snapshot()})
    _finish(line)


def histrank_child_main():
    """Distributed-rank shootout on the 8-virtual-device CPU mesh:
    the O(A) all_gather baseline vs the radix-histogram boundary selection
    (communication independent of A) at a universe size past the
    all_gather design point (A ~ 50k; the north star is 3k).

    On a CPU mesh the collectives are memcpys, so WALL TIME here mostly
    measures local compute — the histogram's O(A*M*E*R/bpr) bucket scans
    vs one O(A log A) sort — while the COMM-BYTES model is what matters on
    real multi-host ICI/DCN.  Both are reported; the JSON consumer decides
    which axis it cares about.
    """
    import jax
    import jax.numpy as jnp
    from csmom_tpu.parallel.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    jax.config.update("jax_platforms", "cpu")

    from csmom_tpu.parallel.collectives import _ranked_labels_local

    n_dev = len(jax.devices())
    A, M, B = 49_152, 120, 10          # A divisible by 8
    rng = np.random.default_rng(0)
    x = rng.normal(size=(A, M)).astype(np.float32)
    valid = rng.random((A, M)) > 0.1
    x = np.where(valid, x, np.nan).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()), ("assets",))

    def build(mode):
        fn = shard_map(
            lambda xl, vl: _ranked_labels_local(xl, vl, B, mode)[0],
            mesh=mesh,
            in_specs=(P("assets", None), P("assets", None)),
            out_specs=P("assets", None),
            check_vma=False,
        )
        return jax.jit(fn)

    def timed(mode, reps=3):
        f = build(mode)
        jax.block_until_ready(f(x, valid))  # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(f(x, valid))
        return (time.perf_counter() - t0) / reps

    wall_gather = timed("rank")
    wall_hist = timed("rank_hist")

    # per-call communication model (bytes entering each device)
    itemsize = 4
    gather_bytes = A * M * itemsize + A * M * 1        # signal f32 + valid bool
    R, E, rounds = 16, B - 1, 32 // 4                  # f32 keys, 4 bits/round
    hist_bytes = rounds * R * M * E * 4 + 6 * M * E * 8  # psum'd hists + tie fixups
    # histogram comm is independent of A, so the BYTES crossover is simply
    # the A where the gather's linear cost passes the histogram's constant;
    # the WALL crossover additionally depends on real interconnect bandwidth
    # vs the histogram's extra local bucket scans, which only a multi-host
    # ICI/DCN measurement can place — until then the bytes model is the
    # honest label (VERDICT r3 weak #6)
    crossover_A = int(hist_bytes / (M * (itemsize + 1)))
    print(json.dumps({
        "metric": "histrank_comparison",
        "value": round(gather_bytes / hist_bytes, 1),
        "unit": "comm_reduction_x",
        "vs_baseline": 0.0,
        "extra": {
            "workload": f"{A} assets x {M} dates, {B} bins, {n_dev}-device CPU mesh",
            "allgather_wall_s": round(wall_gather, 4),
            "rank_hist_wall_s": round(wall_hist, 4),
            "allgather_bytes_per_device": gather_bytes,
            "rank_hist_bytes_per_device": hist_bytes,
            "comm_reduction_x": round(gather_bytes / hist_bytes, 1),
            "bytes_crossover_assets": crossover_A,
            "note": "CPU-mesh walls measure local compute (collectives are "
                    "memcpy); the bytes model is the multi-host story — "
                    "rank_hist communication is independent of A, so its "
                    "comm bytes undercut the gather's above "
                    "bytes_crossover_assets. The WALL crossover (comm "
                    "savings vs the histogram's extra local bucket scans) "
                    "needs a real multi-host ICI measurement; absent one, "
                    "this stays a bytes model, not a speedup claim",
        },
    }))


def warmup_child_main():
    """AOT warm-start pass, CPU-pinned (CSMOM_BENCH_WARMUP=1).

    Compiles every bench-cpu + golden manifest shape into the shared
    'bench' serialized-executable cache and runs the canonical input
    builders, so the next CPU child (this run's fallback or the next
    round's) traces and loads instead of compiling.  Spawned by the
    supervisor in the background while its probe/sleep loop waits for a
    tunnel window; also reachable as `csmom warmup --profiles bench-cpu`.
    Prints one JSON summary line (the supervisor attaches it to the FULL
    record).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    from csmom_tpu import obs
    from csmom_tpu.compile.aot import warmup

    obs.arm_from_env("bench-warmup")
    with obs.span("bench.warmup.child"):
        rep = warmup(profiles=("bench-cpu", "golden"), subdir="bench")
    print(json.dumps({
        "metric": "aot_warmup",
        "value": rep["n_entries"],
        "unit": "manifest_entries",
        "n_cache_hits": rep["n_cache_hits"],
        "n_errors": rep["n_errors"],
        "wall_s": rep["wall_s"],
        "cache_dir": rep["cache_dir"],
    }))


# ----------------------------------------------------------- supervisor ----

def _probe_default_backend(reserve_s: float):
    """True iff the default jax backend initializes in a subprocess within
    the probe timeout (the axon TPU plugin can hang, not just raise).
    ``reserve_s`` is budget that must stay untouched for later stages."""
    if _chaos("bench.probe") == "fail":
        return False, "chaos-injected probe failure (CSMOM_FAULT_PLAN)"
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    timeout = min(PROBE_TIMEOUT_S, _remaining() - reserve_s)
    if timeout < 10:
        return False, "no budget left for a probe"
    try:
        with _tel_span("bench.probe", timeout_s=int(timeout)):
            p = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout,
            )
    except subprocess.TimeoutExpired:
        return False, f"probe timeout after {int(timeout)}s (backend hung at init)"
    if p.returncode == 0:
        return True, (p.stdout.strip().splitlines() or ["?"])[-1]
    return False, (p.stderr or "")[-400:]


def _parse_json_line(stdout: str):
    for line in reversed((stdout or "").strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" in obj and "value" in obj:
            return obj
    return None


def _run_child(force_cpu: bool, reserve_s: float | None = None):
    env = dict(os.environ)
    env["CSMOM_BENCH_CHILD"] = "1"
    if reserve_s is None:
        # default reserves: the CPU fallback must still fit after a failed
        # default-platform child; the CPU child itself reserves nothing
        reserve_s = 0.0 if force_cpu else CPU_RESERVE_S
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env["CSMOM_BENCH_FORCE_CPU"] = "1"
    timeout = _remaining() - reserve_s
    if timeout < 60:
        return None, "no budget left for this attempt"
    env["CSMOM_BENCH_CHILD_BUDGET"] = str(int(timeout))
    try:
        with _tel_span("bench.child.attempt", cpu=force_cpu):
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=timeout,
            )
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        return _parse_json_line(out), f"child timeout after {int(timeout)}s"
    obj = _parse_json_line(p.stdout)
    if obj is not None:
        return obj, None
    return None, f"rc={p.returncode}: {(p.stderr or '')[-400:]}"


def _spawn_warmup_child():
    """Launch the CPU AOT warmup in the background (non-blocking Popen).

    Fired when the probe/sleep loop starts waiting for a tunnel window:
    the wait costs nothing extra, and by the next CPU child every manifest
    shape is a cache load.  Output is collected by ``_reap_warmup_child``;
    failure to launch is recorded, never fatal (warm-start is an
    optimization, not a dependency of the record)."""
    env = dict(os.environ)
    env.pop("CSMOM_BENCH_CHILD", None)
    env["CSMOM_BENCH_WARMUP"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    try:
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
    except OSError as e:
        return f"failed to launch: {type(e).__name__}: {e}"[:200]


def _reap_warmup_child(proc, wait_s: float = 0.0):
    """Status of the background warmup child, as a record-ready value."""
    if proc is None:
        return "not launched: probe/sleep loop never ran (tpu result " \
               "landed early, or the default platform is pinned cpu)"
    if isinstance(proc, str):
        return proc
    try:
        out, _ = proc.communicate(timeout=wait_s)
    except subprocess.TimeoutExpired:
        return ("still running at reporting time (left to finish: the "
                "cache write is atomic per entry, so a partial warmup "
                "still warms every shape it reached)")
    obj = _parse_json_line(out)
    if obj is not None:
        return obj
    return f"exited rc={proc.returncode} without a summary line"


def _run_histrank_child():
    """Run the distributed-rank comparison in its own process (needs the
    8-virtual-device CPU mesh flag set before jax init, which must not leak
    into the main children's timings)."""
    if SMOKE:
        return SMOKE_REASON
    env = dict(os.environ)
    env["CSMOM_BENCH_HISTRANK"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    timeout = _remaining() - 60
    if timeout < 90:
        return f"skipped: no budget left ({int(timeout)}s < 90s floor)"
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return f"failed: histrank child timeout after {int(timeout)}s"
    obj = _parse_json_line(p.stdout)
    if obj is None:
        return f"failed: rc={p.returncode}: {(p.stderr or '')[-300:]}"
    return obj


def _load_committed_json(pattern: str, absent_reason: str):
    """Most recent committed capture matching ``pattern`` (repo root), as a
    compact dict, or the reason there is none.  Cross-process captures
    (histrank walls, multihost equality) are measured by their own
    two-worker scripts and committed — bench only reports them, because
    re-running worker pairs inside the bench budget would starve the
    probe loop."""
    import glob

    paths = sorted(glob.glob(os.path.join(_REPO, pattern)))
    if not paths:
        return absent_reason
    try:
        with open(paths[-1]) as f:
            rec = json.load(f)
        return {"source": os.path.basename(paths[-1]),
                **(rec.get("extra") or {})}
    except (OSError, json.JSONDecodeError) as e:
        return f"unreadable {os.path.basename(paths[-1])}: {e}"[:200]


def _load_histrank_multiproc():
    return _load_committed_json(
        "HISTRANK_MULTIPROC_*.json",
        "not measured: run benchmarks/histrank_multiproc.py to put a "
        "cross-process wall next to the in-process bytes model",
    )


TPU_CHILD_MIN_S = 300   # floor for a useful accelerator child: the child
                        # itself budget-gates its optional legs, so 300s
                        # buys the event headline + the north-star grid
_REPO = os.path.dirname(os.path.abspath(__file__))
LAST_TPU_PATH = os.path.join(_REPO, "BENCH_TPU_LAST.json")

# The round's committed full record. The driver only keeps a 2,000-char
# stdout tail, so everything beyond the headline lives here (in git).
ROUND = os.environ.get("CSMOM_ROUND", "r05")
FULL_RECORD_NAME = f"BENCH_FULL_{ROUND}.json"
HEADLINE_MAX_CHARS = 1600  # hard cap, well under the driver's 2,000 window


def _write_full_record(record: dict) -> str:
    """Persist the complete bench record to the committed per-round file.

    Returns the repo-relative filename (for the headline pointer), or a
    reason string if the write failed — the headline must never be lost to
    a record-file IO error.

    A TOTAL failure (every attempt failed, value 0) never overwrites an
    existing measured round record: an ad-hoc run on a dead tunnel (or
    the driver's own run on a bad day) must not erase the round's
    evidence.  The failure record lands under a ``_failed`` sibling name
    instead, and the headline points there — both files tell the truth."""
    name = FULL_RECORD_NAME
    out_dir = os.environ.get("CSMOM_BENCH_FULL_DIR", _REPO)
    if record.get("value") == 0.0 and (record.get("extra") or {}).get("error"):
        main_path = os.path.join(out_dir, name)
        try:
            with open(main_path) as f:
                existing = json.load(f).get("value")
            if isinstance(existing, (int, float)) and existing > 0:
                name = name.replace(".json", "_failed.json")
        except Exception:
            pass  # no measured record to protect: claim the main name
                  # (never die here — the headline must still print)
    path = os.path.join(out_dir, name)
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        _chaos("bench.land", path=name)  # ENOSPC fault lands in the handler
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        return name
    except OSError as e:
        # never leave a half-written .tmp at the repo root for the driver's
        # end-of-round auto-commit to sweep up
        try:
            os.remove(tmp)
        except OSError:
            pass
        return f"unwritable ({type(e).__name__}: {e})"[:120]


def _headline(record: dict, full_record_ref: str) -> str:
    """One compact JSON line for stdout: the four driver fields plus a
    fixed, size-bounded digest of extra.  Serialized length is guaranteed
    <= HEADLINE_MAX_CHARS by construction + a final degrade step."""
    ex = record.get("extra") or {}

    def _s(v, n=120):  # bound any free-text value
        return v if not isinstance(v, str) else (v if len(v) <= n else v[:n - 1] + "…")

    def _short_provenance(p):
        """The provenance CLASS, complete — never a lossy cut.

        r5's committed headline carried 'session-cached (originally:
        live (r3; block_until_re…' — a provenance string truncated
        mid-parenthesis is not machine-readable provenance at all.  The
        headline keeps only the leading class token ('live' /
        'session-cached'), which is complete and parseable by
        construction; the full composed string stays in the FULL record
        the headline points at (pinned by a round-trip test)."""
        if not isinstance(p, str):
            return p
        head = p.split(" (", 1)[0].strip()
        return head or "unknown"

    probes = ex.get("tpu_probes") or []
    digest = {
        "platform": ex.get("platform"),
        "device_kind": ex.get("device_kind"),
        "north_star_met": ex.get("north_star_met"),
        # the headline metric's workload fingerprint: the perf ledger
        # keys its rows on it, so a round whose FULL record is lost must
        # still land a headline comparable with other rounds' records
        "workload": _s(ex.get("workload")),
        "grid16_rank_s": ex.get("grid16_rank_s"),
        "grid_workload": _s(ex.get("grid_workload")),
        "golden_ok": ex.get("golden_ok"),
        "event_backtest_wall_s": ex.get("event_backtest_wall_s"),
        "in_window_fresh_compiles": (ex.get("compile_totals") or {}).get(
            "in_window_fresh_compiles") if isinstance(
            ex.get("compile_totals"), dict) else None,
        "tpu_provenance": _s(ex.get("tpu_provenance")),
        "tpu_probes_summary": (
            f"{sum(1 for p in probes if p.get('ok'))}/{len(probes)} ok"
            if probes else None
        ),
        "error": _s(ex.get("error")),
        "partial": _s(ex.get("partial")),
        "full_record": full_record_ref,
        "full_record_note": "complete extra (probes, every grid leg, "
                            "histrank, cached TPU record) lives in the "
                            "committed full_record file",
    }
    cached = ex.get("tpu_last_verified")
    if isinstance(cached, dict):
        digest["tpu_last_verified"] = {
            "captured_utc": _s(cached.get("captured_utc"), 60),
            "value": cached.get("value"),
            "unit": _s(cached.get("unit"), 40),
            "provenance": _short_provenance(cached.get("provenance")),
        }
    digest = {k: v for k, v in digest.items() if v is not None}
    head = {
        "metric": _s(record.get("metric"), 80),
        "value": record.get("value"),
        "unit": _s(record.get("unit"), 40),
        "vs_baseline": record.get("vs_baseline"),
    }
    line = json.dumps({**head, "extra": digest})
    if len(line) > HEADLINE_MAX_CHARS:  # degrade, never exceed
        line = json.dumps({
            **head,
            "extra": {"full_record": full_record_ref,
                      "note": "headline digest exceeded the size cap; "
                              "see full_record"},
        })
    return line


def _is_tpu(obj) -> bool:
    return (obj or {}).get("extra", {}).get("platform") == "tpu"


def _save_last_tpu(obj, stamp: str):
    """Persist a live on-chip capture so later runs that hit a full tunnel
    outage can still surface the most recent verified number (with
    explicit provenance) instead of silently reporting CPU.

    A watchdog PARTIAL capture (headline only — the child's deadline hit
    before the grid legs) never replaces an available complete record:
    headline-only today must not mask north-star evidence from yesterday.
    It is still this run's live result; it just doesn't become the cache,
    so the next window is spent upgrading it to a full capture."""
    if (obj.get("extra") or {}).get("partial"):
        prev = _load_last_tpu()
        prev_rec = (prev or {}).get("record") or {}
        if _is_tpu(prev_rec) and not prev_rec.get("extra", {}).get("partial"):
            return
    try:
        with open(LAST_TPU_PATH, "w") as f:
            json.dump({"captured_utc": stamp, "provenance": "live",
                       "record": obj}, f, indent=1)
    except OSError:
        pass  # never lose the JSON line over a cache write


def _load_last_tpu():
    try:
        with open(LAST_TPU_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    # fall back to the committed round-3 session capture so a full-outage
    # run still surfaces the most recent on-chip evidence — labeled with
    # its weaker timing discipline rather than silently dropped
    r3 = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "BENCH_TPU_r03_session.json")
    try:
        with open(r3) as f:
            rec = json.load(f)
        probes = rec.get("extra", {}).get("tpu_probes") or [{}]
        return {
            # read the capture time from the record itself so a replaced
            # file can never be misdated by a stale hardcoded string
            "captured_utc": f"{probes[0].get('utc', 'unknown')} (r3 session)",
            "provenance": "live (r3; block_until_ready-timed — treat walls "
                          "as dispatch-inclusive upper bounds)",
            "record": rec,
        }
    except (OSError, json.JSONDecodeError):
        return None


def main():
    import datetime

    def stamp():
        return datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"
        )

    _tel_start()  # root span + shared event stream for every child
    probes, errors = [], []
    result = None       # CPU fallback (or a default platform that IS cpu)
    tpu_result = None
    default_is_cpu = False  # env pins cpu: probing again can never find a tpu

    # probe 1: early in the budget — if the tunnel is up right now, take it
    ok, info = _probe_default_backend(reserve_s=CPU_RESERVE_S + 60)
    probes.append({"utc": stamp(), "stage": "early", "ok": ok, "info": info})
    default_is_cpu = ok and info.strip() == "cpu"
    if ok:
        # cap this attempt like the loop's: the tunnel can die between the
        # probe and the child's jax init, and an uncapped hang here would
        # eat the budget the probe/sleep loop exists to spend
        obj, err = _run_child(
            force_cpu=False,
            reserve_s=max(CPU_RESERVE_S, _remaining() - 1200.0),
        )
        if obj is not None and _is_tpu(obj):
            tpu_result = obj
        elif obj is not None:
            result = obj  # default platform resolved to cpu: keep it
        else:
            errors.append(f"default child: {err}")

    if tpu_result is None and result is None:
        # CPU fallback secures a JSON line; keep room for the probe loop
        result, err = _run_child(force_cpu=True,
                                 reserve_s=PROBE_TIMEOUT_S + 120)
        if result is None:
            errors.append(f"cpu child: {err}")

    # probe/sleep loop: the tunnel flaps in ~25-minute windows, so a fixed
    # probe count can land entirely inside an outage (round 3 did).  Spend
    # ALL remaining budget alternating probe -> sleep until a window opens
    # or only the reporting reserve is left.  The wait doubles as warm-start
    # time: a background CPU warmup child compiles every manifest shape
    # into the shared cache while this loop sleeps.
    warmup_proc = None
    sleep_s = 30.0
    while (tpu_result is None and not default_is_cpu
           and _remaining() > PROBE_TIMEOUT_S + TPU_CHILD_MIN_S + 60):
        if warmup_proc is None:
            warmup_proc = _spawn_warmup_child()
        okp, infop = _probe_default_backend(
            reserve_s=TPU_CHILD_MIN_S + 60
        )
        probes.append(
            {"utc": stamp(), "stage": "loop", "ok": okp, "info": infop}
        )
        if okp and infop.strip() == "cpu":
            default_is_cpu = True  # env pins cpu; nothing to wait for
            break
        if okp:
            # a window is open: stop the background warmup child first —
            # it compiles north-star-size f64 shapes on every host core,
            # and the TPU child's host-side walls (dispatch, pack ingest)
            # must not be measured under that load.  Per-entry cache
            # writes are atomic, so whatever it warmed stays warmed.
            if warmup_proc is not None and not isinstance(warmup_proc, str):
                if warmup_proc.poll() is None:
                    warmup_proc.terminate()
                    warmup_proc = ("terminated when a tunnel window opened "
                                   "(its partial warm-start is kept: cache "
                                   "writes are atomic per entry)")
            # cap this attempt so a tunnel that dies mid-child costs at
            # most ~20 min of the loop, not the entire remaining budget
            # (the child's own deadline watchdog turns a mid-window death
            # into a partial record rather than a loss)
            obj, err = _run_child(
                force_cpu=False, reserve_s=max(30.0, _remaining() - 1200.0)
            )
            if obj is not None and _is_tpu(obj):
                tpu_result = obj
                break
            if obj is not None and result is None:
                # a measured record (TPU plugin fell back to CPU inside the
                # child) still beats the last-resort stub
                result = obj
            errors.append(f"loop default child: {err or 'non-tpu result'}")
        if _remaining() > PROBE_TIMEOUT_S + TPU_CHILD_MIN_S + 60 + sleep_s:
            time.sleep(sleep_s)
            sleep_s = min(sleep_s * 1.5, 150.0)

    if tpu_result is not None:
        _save_last_tpu(tpu_result, stamp())
        tpu_result.setdefault("extra", {})["tpu_provenance"] = "live"
        result = tpu_result
    elif result is not None:
        # every live TPU attempt failed: attach the most recent verified
        # on-chip record with explicit provenance instead of silently
        # degrading the round's record to CPU-only
        cached = _load_last_tpu()
        if cached is not None and _is_tpu(cached.get("record")):
            rec = cached["record"]
            # records predating the no-bare-nulls policy carry nulls of
            # their own; annotate rather than re-emit them
            rex = rec.get("extra")
            if isinstance(rex, dict):
                rec = dict(rec, extra={
                    k: ("null in the original cached record (predates the "
                        "no-bare-nulls policy)" if v is None else v)
                    for k, v in rex.items()
                })
            result.setdefault("extra", {})["tpu_last_verified"] = {
                # compose: how it was captured then + that it is a cache now
                "provenance": "session-cached (originally: "
                              f"{cached.get('provenance', 'unknown')})",
                "captured_utc": cached.get("captured_utc"),
                "note": "most recent verified on-chip capture (this run's "
                        "probes never found the tunnel up — see tpu_probes); "
                        "NOT measured in this run",
                "value": rec.get("value"),
                "unit": rec.get("unit"),
                "extra": rec.get("extra"),
            }
        else:
            result.setdefault("extra", {})["tpu_last_verified"] = (
                "none available: no live on-chip capture has succeeded on "
                "this machine yet (BENCH_TPU_LAST.json absent)"
            )

    if result is not None:
        result.setdefault("extra", {})["tpu_probes"] = probes
        if errors:
            result["extra"]["attempt_errors"] = errors
        hr = _run_histrank_child()  # budget permitting; reasons otherwise
        result["extra"]["histrank_vs_allgather"] = (
            hr.get("extra", hr) if isinstance(hr, dict) else hr
        )
        # the cross-PROCESS wall (gloo TCP boundary, benchmarks/
        # histrank_multiproc.py) is captured separately and committed; join
        # it to the in-process bytes model rather than re-measuring here
        result["extra"]["histrank_cross_process"] = _load_histrank_multiproc()
        # AOT warm-start provenance: the background warmup child's summary
        # plus the on-disk per-shape report (trace/compile walls, hit/miss
        # per manifest entry) — how "0 in-window compiles" is audited
        result["extra"]["warmup_child"] = _reap_warmup_child(
            warmup_proc, wait_s=max(0.0, min(20.0, _remaining() - 45.0))
        )
        try:
            from csmom_tpu.compile.aot import read_warmup_report

            result["extra"]["aot_warmup_report"] = read_warmup_report("bench")
        except Exception as e:  # never lose the record to report plumbing
            result["extra"]["aot_warmup_report"] = (
                f"unreadable: {type(e).__name__}: {e}"[:200]
            )
        result["extra"]["multihost_equality"] = _load_committed_json(
            "MULTIHOST_CPU_*.json",
            "not captured: run benchmarks/multihost_dryrun.py for the "
            "cross-process sharded==single equality record",
        )
    else:
        # last resort: a parseable record so the driver captures *something*
        result = {
            "metric": "intraday_event_backtest_bar_groups_per_sec",
            "value": 0.0,
            "unit": "bar_groups/s",
            "vs_baseline": 0.0,
            "extra": {"error": "all benchmark attempts failed",
                      "attempts": errors, "tpu_probes": probes},
        }
    # split the output: the TELEMETRY sidecar lands FIRST so the FULL
    # record can point at what actually landed (name or failure reason,
    # never a prediction); then the full record, then the bounded
    # headline line to stdout for the driver
    result.setdefault("extra", {})["telemetry_sidecar"] = _tel_finish(
        os.environ.get("CSMOM_BENCH_FULL_DIR", _REPO)
    )
    ref = _write_full_record(result)
    print(_headline(result, ref))


if __name__ == "__main__":
    if os.environ.get("CSMOM_BENCH_HISTRANK"):
        histrank_child_main()
    elif os.environ.get("CSMOM_BENCH_WARMUP"):
        warmup_child_main()
    elif os.environ.get("CSMOM_BENCH_CHILD"):
        child_main()
    else:
        main()
