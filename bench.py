"""Headline benchmark.

Primary metric: event-backtest throughput on the reference's own golden
workload — the shipped 20-ticker x ~2,728-minute panel that takes the
reference's Python event loop 18.4 s (~148 bar-groups/s, measured; BASELINE
.md) on one CPU core.  Same features, same scores, same fills; ours is the
jit-compiled panel engine.

Also reported (in "extra"): the north-star J x K grid — all 16
Jegadeesh-Titman cells on a 3000-stock x 60-year monthly panel in one
compiled call (target < 10 s on a v5e-8; BASELINE.json).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}
"""

import json
import os
import time

import numpy as np

REFERENCE_DATA = "/root/reference/data"
BASELINE_GROUPS_PER_SEC = 148.3  # measured: 18.4 s / 2,728 datetime groups
DEMO_TICKERS = [
    "AAPL", "MSFT", "AMZN", "GOOGL", "NVDA", "TSLA", "META", "JPM", "BAC", "WMT",
    "PG", "KO", "DIS", "CSCO", "ORCL", "INTC", "AMD", "NFLX", "C", "GS",
]


def _golden_inputs(dtype):
    """Dense minute panels for the event engine, from the shipped caches (or a
    synthesized same-shape workload when the reference data is absent)."""
    import jax.numpy as jnp

    from csmom_tpu.api import intraday_pipeline, synthetic_minute_frame
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    if os.path.isdir(REFERENCE_DATA):
        minute_df = load_intraday(REFERENCE_DATA, DEMO_TICKERS)
        daily_df = load_daily(REFERENCE_DATA, [t for t in DEMO_TICKERS if t != "AAPL"])
    else:  # pragma: no cover
        from csmom_tpu.panel.synthetic import synthetic_daily_panel

        daily = synthetic_daily_panel(20, 7, seed=0)
        daily_df = None
        minute_df = synthetic_minute_frame(
            __import__("pandas").DataFrame(
                {
                    "date": np.repeat(daily.times, 20),
                    "ticker": np.tile(daily.tickers, 7),
                    "open": daily.values.T.ravel(),
                    "close": daily.values.T.ravel(),
                    "volume": 1e6,
                }
            )
        )
    res, fit, compact, dense_score, dense_price, dense_valid = intraday_pipeline(
        minute_df, daily_df, dtype=dtype
    )
    from csmom_tpu.api import daily_risk_maps

    adv, vol = daily_risk_maps(daily_df, compact.tickers)
    return (
        jnp.asarray(dense_price, dtype),
        jnp.asarray(dense_valid),
        jnp.nan_to_num(jnp.asarray(dense_score, dtype)),
        jnp.asarray(adv, dtype),
        jnp.asarray(vol, dtype),
        int(res.n_trades),
    )


def main():
    import jax

    from csmom_tpu.backtest.event import event_backtest
    from csmom_tpu.backtest.grid import jk_grid_backtest
    from csmom_tpu.panel.calendar import month_end_aggregate, month_end_segments
    from csmom_tpu.panel.synthetic import synthetic_daily_panel

    platform = jax.devices()[0].platform
    dtype = np.float32 if platform != "cpu" else np.float64

    price, valid, score, adv, vol, n_trades = _golden_inputs(dtype)
    n_bars = int(np.asarray(valid).any(axis=0).sum())

    run = lambda: jax.block_until_ready(
        event_backtest(price, valid, score, adv, vol).total_pnl
    )
    run()  # compile
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        run()
    dt = (time.perf_counter() - t0) / reps
    groups_per_sec = n_bars / dt

    # north-star grid: 16 cells, 3000 stocks x 60 years
    panel = synthetic_daily_panel(3000, 15120, seed=7, listing_gaps=True)
    seg, ends = month_end_segments(panel.times)
    v, m = panel.device(dtype)
    pm, mm = month_end_aggregate(v, m, seg, len(ends))
    Js = np.array([3, 6, 9, 12])
    Ks = np.array([3, 6, 9, 12])
    g = lambda mode: jax.block_until_ready(
        jk_grid_backtest(pm, mm, Js, Ks, skip=1, mode=mode).mean_spread
    )

    def timed(mode, reps=5):
        g(mode)  # compile + warm the tunnel
        t0 = time.perf_counter()
        for _ in range(reps):
            g(mode)
        return (time.perf_counter() - t0) / reps

    grid_rank_s = timed("rank")
    grid_qcut_s = timed("qcut")

    print(
        json.dumps(
            {
                "metric": "intraday_event_backtest_bar_groups_per_sec",
                "value": round(groups_per_sec, 1),
                "unit": "bar_groups/s",
                "vs_baseline": round(groups_per_sec / BASELINE_GROUPS_PER_SEC, 1),
                "extra": {
                    "platform": platform,
                    # f32 on TPU flips ~2 of 54k |score|>1e-5 threshold
                    # crossings vs the f64 golden run (28,020 trades, matched
                    # exactly by tests/test_event_backtest.py::test_golden_fingerprint)
                    "workload": f"golden 20x{n_bars} minute panel, "
                                f"{n_trades} trades ({dtype.__name__})",
                    "event_backtest_wall_s": round(dt, 6),
                    "reference_wall_s": 18.4,
                    "grid16_3000x60yr_rank_s": round(grid_rank_s, 4),
                    "grid16_3000x60yr_qcut_s": round(grid_qcut_s, 4),
                    "north_star_target_s": 10.0,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
