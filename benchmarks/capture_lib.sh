# Artifact-landing rules shared by tunnel_watch.sh (sourced, no side
# effects) and pinned by tests/test_capture_lib.py.
#
# Contract:
#  - land_artifact RAW ART: extract RAW's last JSON line into ART.
#    Refuses to overwrite an existing ART — unless ART is a PARTIAL
#    (deadline-hit dump) and the new line is FULL, or both are partials
#    and the new one measured STRICTLY more rows/phases: a partial is
#    provisional evidence, never a blocker for its own upgrade, and a
#    richer deadline-hit capture upgrades a thinner one.
#  - promote_capture NAME RAW ART: a finished RAW.tmp with a FULL
#    summary claims RAW (the done-marker the watcher loop checks); a
#    PARTIAL one is kept aside as RAW.partial and landed provisionally,
#    so the loop retries that capture on the next window.
#  - A landed artifact is re-validated as parseable JSON after the write
#    and before the rename: a short write (ENOSPC, dying disk) between
#    the formatter and the mv must never replace a good artifact with a
#    truncated one.  The chaos harness (`csmom rehearse`) pins this via
#    CSMOM_FAULT_LAND_TRUNCATE_BYTES, which simulates exactly that short
#    write; the CSMOM_FAULT_* env names are the shell side of the
#    csmom_tpu.chaos fault-plan contract.
#
# Callers define log() (tunnel_watch.sh logs to its file; tests stub it).

_measured_rows() {  # stdin: one JSON record -> its measured-row count
  # a capture's substance is its measurement list ("rows" for the scaling
  # sweep, "phases" for the phase profile — top-level or nested under
  # "extra", where bench-child and minibench partials carry theirs);
  # unparseable or listless -> 0.  Mirror of chaos.invariants.measured_rows
  # (pinned by tests/test_capture_lib.py): the two sides of the landing
  # contract must size a partial identically or a strictly-richer partial
  # could be refused its upgrade.
  python -c '
import json, sys
try:
    d = json.load(sys.stdin)
except Exception:
    print(0); raise SystemExit
extra = d.get("extra") if isinstance(d.get("extra"), dict) else {}
for k in ("rows", "phases"):
    for holder in (d, extra):
        if isinstance(holder.get(k), list):
            print(len(holder[k])); raise SystemExit
print(0)' 2>/dev/null || echo 0
}

land_artifact() {  # $1 raw log, $2 committed artifact path
  new_line=$(grep '^{' "$1" | tail -1)
  if [ -s "$2" ]; then
    if grep -q '"partial":' "$2"; then
      if ! printf '%s' "$new_line" | grep -q '"partial":'; then
        log "artifact $2 is a partial — upgrading with full capture"
      else
        old_rows=$(_measured_rows < "$2")
        new_rows=$(printf '%s' "$new_line" | _measured_rows)
        if [ "$new_rows" -gt "$old_rows" ] 2>/dev/null; then
          log "artifact $2 is a partial ($old_rows rows) — upgrading with richer partial ($new_rows rows)"
        else
          log "artifact $2 already exists — refusing to overwrite"
          return 0
        fi
      fi
    else
      log "artifact $2 already exists — refusing to overwrite"
      return 0
    fi
  fi
  if printf '%s\n' "$new_line" | python -m json.tool > "$2".tmp 2>/dev/null \
      && [ -s "$2".tmp ]; then
    if [ -n "${CSMOM_FAULT_LAND_TRUNCATE_BYTES:-}" ]; then
      # chaos fault: an ENOSPC/short write hitting between the formatter
      # and the rename (csmom rehearse land-short-write scenario)
      head -c "$CSMOM_FAULT_LAND_TRUNCATE_BYTES" "$2".tmp > "$2".tmp.chaos \
        && mv "$2".tmp.chaos "$2".tmp
      log "chaos: truncated $2.tmp to ${CSMOM_FAULT_LAND_TRUNCATE_BYTES} bytes"
    fi
    if python -c 'import json,sys; json.load(open(sys.argv[1]))' "$2".tmp \
        2>/dev/null; then
      mv "$2".tmp "$2"
    else
      rm -f "$2".tmp
      log "artifact $2 failed post-write JSON validation (short write/ENOSPC?) — not landed, existing artifact untouched"
    fi
  else
    rm -f "$2".tmp
    log "summary extraction FAILED for $2 (artifact not written)"
  fi
}

promote_capture() {  # $1 name for logs, $2 raw out path, $3 artifact path
  if grep '^{' "$2".tmp | tail -1 | grep -q '"partial":'; then
    mv "$2".tmp "$2".partial
    land_artifact "$2".partial "$3"
    log "$1 partial capture kept as .partial — will retry for a full one"
  else
    mv "$2".tmp "$2"
    land_artifact "$2" "$3"
  fi
}
