"""Phase breakdown of the north-star J x K grid (any platform).

Times each stage of ``jk_grid_backtest`` separately — formation signal,
decile ranking, cohort aggregation (each impl), holding/stats tail, and
the full fused call — with the same device_get timing discipline as
``bench.py`` (``block_until_ready`` does not reliably sync on the
tunneled TPU backend), and pairs every wall with a first-principles
bytes/FLOPs model so each phase reads as a fraction of the chip's
roofline rather than a bare number.

The point (VERDICT r3 next-step 3): the 16-cell grid at the north-star
size (3,000 x 720 months) measures ~0.09 s on one v5e chip at ~1.6% of
HBM peak — this tool shows WHICH phase owns the time and at what size
each phase leaves the latency-bound regime.  Run it at several ``--ax``
multipliers to trace the transition.

Usage::

    python benchmarks/grid_phases.py            # north-star size
    python benchmarks/grid_phases.py --ax 32    # 96k assets

Emits one JSON line per phase and a trailing summary line (committed as
``PHASES_TPU_r{N}.json`` when captured on-chip).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root: csmom_tpu package
sys.path.insert(0, _HERE)                   # sibling benchmark modules

# deadline anchor: module-import time ~= process start (tunneled jax setup
# runs inside main, after this — see csmom_tpu.utils.deadline)
_T0 = time.monotonic()

from tpu_scaling import monthly_panel  # noqa: E402  (sibling module)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ax", type=int, default=1,
                    help="asset-count multiplier on the 3,000 north star")
    ap.add_argument("--assets", type=int, default=None,
                    help="explicit asset count (overrides --ax; for quick "
                         "correctness runs on slow hosts)")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--platform", choices=["default", "cpu"], default="default",
                    help="pin the jax platform ('cpu' for hosts whose "
                         "default platform hangs at init; the env-var route "
                         "is defeated by images whose sitecustomize imports "
                         "jax at interpreter start)")
    args = ap.parse_args()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from csmom_tpu.utils.jit_cache import enable_persistent_cache

    # share bench.py's cache dir — a tunnel window must never be spent
    # recompiling shapes a previous capture attempt already paid for
    enable_persistent_cache("bench")
    import jax.numpy as jnp

    from csmom_tpu.backtest.grid import (
        _cohort_partial_sums, _finalize_cohorts, _holding_month_spreads,
        jk_grid_backtest,
    )
    from csmom_tpu.analytics.stats import masked_mean, sharpe
    from csmom_tpu.ops.ranking import decile_assign_panel
    from csmom_tpu.signals.momentum import momentum_dynamic, monthly_returns
    from csmom_tpu.utils.profiling import fetch, measure_rtt

    platform = jax.devices()[0].platform
    kind = str(jax.devices()[0].device_kind)
    if platform != "tpu":
        # same dtype discipline as bench.py: f64 math off-TPU needs x64
        # enabled, otherwise everything silently truncates to f32 while the
        # itemsize-8 traffic model overstates bandwidth 2x
        jax.config.update("jax_enable_x64", True)
    A, M, H, B = args.assets or 3000 * args.ax, 720, 12, 10
    # numpy (not jnp): these are closed over inside an extra jit wrapper,
    # where any jnp op — even on a constant — stages to a tracer and would
    # break the host-side max(Ks) validation in jk_grid_backtest
    Js = np.array([3, 6, 9, 12])
    Ks = np.array([3, 6, 9, 12])
    itemsize = 4 if platform == "tpu" else 8
    dtype = np.float32 if platform == "tpu" else np.float64

    rtt_s = measure_rtt()
    print(json.dumps({"tiny_op_rtt_s": round(rtt_s, 6)}), flush=True)

    pm, mm = monthly_panel(A, M)
    pm = jax.device_put(pm.astype(dtype))
    mm = jax.device_put(mm)

    def timed(fn, *xs, reps=args.reps):
        """Per-rep device_get of an in-jit scalar reduction."""
        f = jax.jit(lambda *a: jnp.asarray(fn(*a), dtype).sum())
        fetch(f(*xs))  # compile
        t0 = time.perf_counter()
        for _ in range(reps):
            fetch(f(*xs))
        return (time.perf_counter() - t0) / reps

    rows = []

    from csmom_tpu.utils.profiling import PEAK_HBM_GBPS

    peak = PEAK_HBM_GBPS.get(kind)

    def summary(partial=None):
        d = {
            "metric": "grid_phase_breakdown",
            "platform": platform,
            "device_kind": kind,
            "A": A, "M": M, "H": H,
            "tiny_op_rtt_s": round(rtt_s, 6),
            "chip_peak_hbm_gbps": peak or "unknown device kind",
            "timing": "per-rep device_get of an in-jit scalar reduction",
            "phases": list(rows),
        }
        if partial:
            d["partial"] = partial
        return d

    # Deadline guard (same as bench.py's child and tpu_scaling.py): an
    # external timeout must never discard the phases already measured.
    from csmom_tpu.utils.deadline import deadline_guard

    finish = deadline_guard(
        "CSMOM_PHASES_BUDGET_S",
        lambda: json.dumps(summary(
            partial="deadline hit: unmeasured phases are absent "
                    "(watchdog dump, not a full breakdown)"
        )) if rows else None,
        t0=_T0,
    )

    def report(phase, wall, gbytes, gflops, note):
        row = {
            "phase": phase,
            "wall_s": round(wall, 5),
            "model_gbytes": round(gbytes, 3),
            "model_gflops": round(gflops, 3),
            "achieved_gbps": round(gbytes / wall, 1),
            "achieved_gflops_s": round(gflops / wall, 1),
            "note": note,
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    # -- phase 1: formation momentum, all four J in one vmap ----------------
    mom_fn = lambda p, v: jax.vmap(
        lambda J: momentum_dynamic(p, v, J, 1)[0]
    )(Js)
    nJ = len(Js)
    report(
        "momentum(vmap J)", timed(mom_fn, pm, mm),
        # log1p + 2 prefix gathers over [A, M] per J, ~4 passes
        nJ * 4 * A * M * itemsize / 1e9, nJ * 3 * A * M / 1e9,
        "telescoped-ratio formation signal for all J",
    )

    # -- phase 2: decile ranking (the batched per-date sort), rank & qcut ---
    mom, momv = jax.jit(
        lambda p, v: jax.vmap(lambda J: momentum_dynamic(p, v, J, 1))(Js)
    )(pm, mm)
    mom = jax.block_until_ready(mom)

    for mode in ("rank", "qcut", "hist"):
        rank_fn = lambda x, v, mode=mode: jax.vmap(
            lambda xj, vj: decile_assign_panel(xj, vj, B, mode=mode)[0]
        )(x, v)
        if mode == "hist":
            # sort-free radix binning: nbits/4 rounds of bucket scans over
            # the [A, M] keys + the (B-1)-boundary compare pass
            rounds = (8 if itemsize == 4 else 16)
            gb = nJ * (rounds + 3) * A * M * itemsize / 1e9
            gf = nJ * rounds * 2 * A * M / 1e9
            note = ("radix-histogram binning (no sort): label-identical to "
                    "rank; CANDIDATE for sort-dominated sizes — measured "
                    "slower on CPU f64 (16 rounds, no fusion win), the "
                    "tpu f32 form (8 rounds, fused scans vs bitonic sort) "
                    "is what this phase row decides")
        else:
            # sort reads+writes [A, M] keys ~log(A) times per J (bitonic on
            # TPU); count one logical pass as the *lower bound* model
            gb = nJ * 3 * A * M * itemsize / 1e9
            gf = nJ * A * np.log2(max(A, 2)) * M / 1e9
            note = ("one batched argsort over (J, M); flops column = "
                    "comparison model")
        report(f"ranking[{mode}]", timed(rank_fn, mom, momv), gb, gf, note)

    labels = jax.jit(
        lambda x, v: jax.vmap(
            lambda xj, vj: decile_assign_panel(xj, vj, B, mode="rank")[0]
        )(x, v)
    )(mom, momv)
    labels = jax.block_until_ready(labels)
    ret, retv = jax.jit(monthly_returns)(pm, mm)
    ret = jax.block_until_ready(ret)

    # -- phase 3: cohort aggregation, each impl -----------------------------
    impls = ["xla", "matmul"] + (["matmul_bf16", "pallas"]
                                 if platform == "tpu" else [])
    for impl in impls:
        coh_fn = lambda l, r, rv, impl=impl: jax.vmap(
            lambda lj: _cohort_partial_sums(lj, r, rv, B, H, impl=impl)[0]
        )(l)
        if impl.startswith("matmul"):
            gb = nJ * (3 * A * M + 2 * M * M) * itemsize / 1e9
            gf = nJ * 2 * 2 * 2 * A * M * M / 1e9  # 2 sides x 2 tables x 2 flop
            note = "2 batched [2,M,A]@[A,M] cross tables + band gather (MXU)"
        else:
            gb = nJ * H * 3 * A * M * itemsize / 1e9
            gf = nJ * H * 6 * A * M / 1e9
            note = "H rolled masked reductions over [A, M] per J (HBM-bound form)"
        report(f"cohort_sums[{impl}]", timed(coh_fn, labels, ret, retv), gb,
               gf, note)

    # -- phase 4: holding/stats tail ----------------------------------------
    sums, counts = jax.jit(
        lambda l, r, rv: jax.vmap(
            lambda lj: _cohort_partial_sums(lj, r, rv, B, H, impl="xla")
        )(l)
    )(labels, ret, retv)
    sums = jax.block_until_ready(sums)

    def tail_fn(s, c):
        R, Rv = jax.vmap(_finalize_cohorts)(s, c)
        spreads, live = _holding_month_spreads(R, Rv, Ks)
        return masked_mean(spreads, live) + sharpe(spreads, live)

    report(
        "holding+stats tail", timed(tail_fn, sums, counts),
        nJ * H * M * 4 * itemsize / 1e9, nJ * H * M * 8 / 1e9,
        "K-overlap gather + masked stats over [nJ, M, H] — asset-free",
    )

    # -- full fused grid ------------------------------------------------------
    full_fn = lambda p, v: jk_grid_backtest(
        p, v, Js, Ks, skip=1, mode="rank", impl="xla", max_hold=H
    ).mean_spread
    report(
        "full grid (fused, rank/xla)", timed(full_fn, pm, mm),
        (nJ * (4 + 3) * A * M + nJ * H * 3 * A * M) * itemsize / 1e9,
        nJ * H * 6 * A * M / 1e9,
        "everything under one jit: XLA fuses phases 1-4",
    )

    finish(json.dumps(summary()))


if __name__ == "__main__":
    main()
