"""Distributed rank across a REAL process boundary (VERDICT r4 #5).

The in-process 8-virtual-device CPU mesh (bench.py's histrank child) times
collectives that are memcpys, so its walls measure local compute and only
the BYTES model speaks to multi-host behaviour.  This benchmark puts an
actual process/serialization boundary under the collective: two OS
processes, each owning half the devices of one global mesh, joined by
``jax.distributed`` with gloo TCP CPU collectives — every ``all_gather``/
``psum`` inside the ranked kernels now crosses process memory through a
socket, the same topology class (if not the same bandwidth) as ICI/DCN.

Honest-labeling note (printed into the artifact): localhost TCP is
~1-5 GB/s with syscall latency in the tens of microseconds — orders of
magnitude below ICI (~400+ GB/s) and still well below DCN.  That *favors*
the comm-avoiding rank_hist relative to the gather, so a rank_hist loss
here would be strong evidence against it at ICI bandwidths, while a win
bounds the regime where comm avoidance pays (slow interconnects) rather
than proving an ICI-wall win.

Run: ``python benchmarks/histrank_multiproc.py [--repeat-runs N]`` (the
launcher spawns the two workers of itself N times, default 3 — the hist
leg measured 13.0 s vs 20.5 s at 49k across two idle runs, so ONE run
cannot be trusted to place a winner).  Prints one JSON summary line in
the committed multi-run schema (``extra.runs`` list + an auto-stub
``conclusion``); the committed ``HISTRANK_MULTIPROC_r05.json`` is this
output with the conclusion field replaced by the author's reading of the
runs.
"""

import json
import os
import subprocess
import sys
import time

PORT = int(os.environ.get("CSMOM_MP_PORT", "12861"))
N_PROC = 2
LOCAL_DEVICES = 4           # per process -> 8-device global mesh, as bench's
M, B = 120, 10
SIZES = (3072, 12288, 49152)
REPS = 3


def worker(process_id: int) -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={LOCAL_DEVICES}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"localhost:{PORT}", num_processes=N_PROC, process_id=process_id,
        cluster_detection_method="deactivate",
    )
    import numpy as np
    import jax.numpy as jnp
    from csmom_tpu.parallel.compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from csmom_tpu.parallel.collectives import _ranked_labels_local

    n_dev = jax.device_count()
    assert n_dev == N_PROC * LOCAL_DEVICES
    mesh = Mesh(np.array(jax.devices()), ("assets",))
    sharding = NamedSharding(mesh, P("assets", None))

    def build(mode):
        fn = shard_map(
            lambda xl, vl: _ranked_labels_local(xl, vl, B, mode)[0],
            mesh=mesh,
            in_specs=(P("assets", None), P("assets", None)),
            out_specs=P("assets", None),
            check_vma=False,
        )
        return jax.jit(fn)

    results = {}
    for A in SIZES:
        # identical full panel on every process (same seed); each process
        # donates only its addressable shards to the global array
        rng = np.random.default_rng(0)
        x = rng.normal(size=(A, M)).astype(np.float32)
        valid = rng.random((A, M)) > 0.1
        x = np.where(valid, x, np.nan).astype(np.float32)
        xg = jax.make_array_from_callback(
            (A, M), sharding, lambda idx: x[idx]
        )
        vg = jax.make_array_from_callback(
            (A, M), sharding, lambda idx: valid[idx]
        )

        walls = {}
        for mode in ("rank", "rank_hist"):
            f = build(mode)
            jax.block_until_ready(f(xg, vg))  # compile + first run
            t0 = time.perf_counter()
            for _ in range(REPS):
                jax.block_until_ready(f(xg, vg))
            walls[mode] = (time.perf_counter() - t0) / REPS
        results[A] = walls
        if process_id == 0:
            print(f"A={A}: gather {walls['rank']*1e3:.1f} ms  "
                  f"hist {walls['rank_hist']*1e3:.1f} ms", file=sys.stderr)

    if process_id == 0:
        itemsize = 4
        out = {
            "metric": "histrank_cross_process",
            "value": round(results[SIZES[-1]]["rank"]
                           / results[SIZES[-1]]["rank_hist"], 3),
            "unit": "allgather_over_hist_wall_ratio_at_largest_A",
            "vs_baseline": 0.0,
            "extra": {
                "topology": f"{N_PROC} OS processes x {LOCAL_DEVICES} CPU "
                            "devices, jax.distributed + gloo TCP collectives "
                            "(localhost socket)",
                "workload": f"M={M} dates, {B} bins, reps={REPS}, f32",
                "walls_s": {
                    str(A): {m: round(w, 4) for m, w in ws.items()}
                    for A, ws in results.items()
                },
                "allgather_bytes_per_device": {
                    str(A): A * M * (itemsize + 1) for A in SIZES
                },
                "note": "localhost TCP (~GB/s, tens-of-us latency) sits far "
                        "BELOW ICI bandwidth, which favors the comm-avoiding "
                        "rank_hist: a hist win here bounds the slow-"
                        "interconnect regime where comm avoidance pays; only "
                        "a real multi-host ICI run places the fast-"
                        "interconnect crossover",
            },
        }
        print(json.dumps(out))


def _one_run() -> dict:
    """Spawn one worker pair and return worker 0's parsed summary record."""
    import threading

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # workers pin cpu via config.update
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(N_PROC)
    ]
    # drain every pipe CONCURRENTLY: the workers share collectives, so one
    # worker blocked on a full 64KB pipe stalls its peer's matching
    # collective and deadlocks the pair; and kill whatever is still alive
    # on any failure so a crashed run can't orphan processes holding the
    # coordinator port
    outs = [None] * N_PROC

    def _drain(i):
        outs[i] = procs[i].stdout.read()

    threads = [threading.Thread(target=_drain, args=(i,)) for i in range(N_PROC)]
    for t in threads:
        t.start()
    try:
        for i, p in enumerate(procs):
            p.wait(timeout=1800)
        for t in threads:
            t.join(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=60)
    for i, p in enumerate(procs):
        if p.returncode != 0:
            print((outs[i] or "")[-3000:], file=sys.stderr)
            raise SystemExit(f"worker {i} failed rc={p.returncode}")
    # the summary JSON is the last {...} line of worker 0's stdout
    for line in reversed((outs[0] or "").strip().splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit("no summary line from worker 0")


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--repeat-runs", type=int, default=3,
                    help="independent launcher runs to aggregate (the hist "
                         "leg is high-variance at 49k; one run cannot "
                         "place a winner)")
    n_runs = ap.parse_args().repeat_runs

    runs, ratios = [], []
    for r in range(n_runs):
        rec = _one_run()
        walls = rec["extra"]["walls_s"]
        runs.append({"label": f"run{r + 1}", "walls_s": walls})
        big = walls[str(SIZES[-1])]
        ratios.append(big["rank"] / big["rank_hist"])
        print(f"run {r + 1}/{n_runs}: 49k ratio {ratios[-1]:.3f}",
              file=sys.stderr)
    itemsize = 4
    print(json.dumps({
        "metric": "histrank_cross_process",
        "value": round(min(ratios), 3),
        "unit": "allgather_over_hist_wall_ratio_at_49k_worst_idle_run",
        "vs_baseline": 0.0,
        "extra": {
            "topology": f"{N_PROC} OS processes x {LOCAL_DEVICES} CPU "
                        "devices, jax.distributed + gloo TCP collectives "
                        "(localhost socket)",
            "workload": f"M={M} dates, {B} bins, reps={REPS} per run, f32",
            "runs": runs,
            "allgather_bytes_per_device": {
                str(A): A * M * (itemsize + 1) for A in SIZES
            },
            "conclusion": "unreviewed auto-capture: interpret runs[] "
                          "(win/loss per size, run-to-run variance) before "
                          "citing a winner",
            "note": "localhost TCP sits far below ICI bandwidth, which "
                    "FAVORS the comm-avoiding rank_hist — a loss here is "
                    "evidence the histogram's extra local compute outweighs "
                    "its comm savings on CPU-class nodes; only a real "
                    "multi-host ICI/TPU run places the fast-interconnect "
                    "answer",
        },
    }))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]))
    else:
        main()
