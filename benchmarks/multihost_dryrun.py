"""Multi-host correctness dry run: sharded engines across a process boundary.

The in-process 8-virtual-device mesh (tests/test_sharding.py) proves the
collectives' math; this proves the DISTRIBUTED RUNTIME path: two OS
processes joined by ``jax.distributed`` (gloo TCP collectives — the same
topology class as a multi-host TPU pod riding DCN), each owning half the
global mesh's devices, running the sharded monthly and banded engines on a
seeded panel.  Process 0 also computes the single-device engines locally
and asserts the distributed results are EQUAL (f64, rtol 1e-12) — the
"distribution must not change a single bit of logic" invariant, now held
across process memory, serialization, and a socket.

Run: ``python benchmarks/multihost_dryrun.py``.  Prints one JSON line; the
r5 capture is committed as ``MULTIHOST_CPU_r05.json``.
"""

import json
import os
import subprocess
import sys
import time

PORT = int(os.environ.get("CSMOM_MH_PORT", "12871"))
N_PROC = 2
LOCAL_DEVICES = 4
A, M = 96, 72   # divisible by the 8-device mesh; months past the JT warmup
SEED = 11


def worker(process_id: int) -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={LOCAL_DEVICES}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    jax.distributed.initialize(
        f"localhost:{PORT}", num_processes=N_PROC, process_id=process_id,
        cluster_detection_method="deactivate",
    )
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from csmom_tpu.backtest import banded_monthly_backtest, monthly_spread_backtest
    from csmom_tpu.parallel.collectives import (
        sharded_banded_backtest,
        sharded_monthly_spread_backtest,
    )

    # identical panel on every process (same seed); masked lanes included
    rng = np.random.default_rng(SEED)
    prices = 50 * np.exp(np.cumsum(rng.normal(0.003, 0.07, size=(A, M)), axis=1))
    prices[: A // 8, : M // 5] = np.nan
    mask = np.isfinite(prices)

    mesh = Mesh(np.array(jax.devices()), ("assets",))
    sharding = NamedSharding(mesh, P("assets", None))
    pv = jax.make_array_from_callback((A, M), sharding, lambda i: prices[i])
    mv = jax.make_array_from_callback((A, M), sharding, lambda i: mask[i])

    t0 = time.perf_counter()
    spread, valid, mean, sh, ts = sharded_monthly_spread_backtest(pv, mv, mesh)
    jax.block_until_ready(spread)
    monthly_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    b_spread, b_valid, b_mean, b_sh, b_tnw = sharded_banded_backtest(
        pv, mv, mesh, lookback=12, skip=1, n_bins=5, band=1
    )
    jax.block_until_ready(b_spread)
    banded_wall = time.perf_counter() - t0

    # sequence-parallel online ridge: the time axis sharded across BOTH
    # processes (exclusive Chan/Gram carries + local Sherman-Morrison —
    # csmom_tpu/parallel/online_ridge.py), gather_outputs=True so the
    # replicated results are process-local readable
    from csmom_tpu.models.online_ridge import online_ridge_scores
    from csmom_tpu.parallel.online_ridge import _compiled as or_compiled

    A_or, R_or, F_or = 4, 64, 3
    rng_or = np.random.default_rng(SEED + 1)
    feats = rng_or.normal(size=(A_or, R_or, F_or))
    y_or = rng_or.normal(scale=1e-2, size=(A_or, R_or))
    w_or = (rng_or.random((A_or, R_or)) > 0.1).astype(np.float64)

    mesh_t = Mesh(np.array(jax.devices()), ("time",))
    Xr = np.ascontiguousarray(np.swapaxes(feats, 0, 1))       # [R, A, F]
    yr = np.ascontiguousarray(np.swapaxes(y_or, 0, 1))
    wr = np.ascontiguousarray(np.swapaxes(w_or, 0, 1))
    sh_x = NamedSharding(mesh_t, P("time", None, None))
    sh_v = NamedSharding(mesh_t, P("time", None))
    Xg = jax.make_array_from_callback(Xr.shape, sh_x, lambda i: Xr[i])
    yg = jax.make_array_from_callback(yr.shape, sh_v, lambda i: yr[i])
    wg = jax.make_array_from_callback(wr.shape, sh_v, lambda i: wr[i])

    or_fn = or_compiled(mesh_t, "time", A_or, F_or, np.dtype(np.float64),
                        0.8, 8, True, gather_outputs=True)
    t0 = time.perf_counter()
    with mesh_t:
        preds_g, seen_g, _, _, _ = or_fn(Xg, yg, wg)
    jax.block_until_ready(preds_g)
    online_wall = time.perf_counter() - t0

    if process_id != 0:
        return

    # out_specs P() replicate the results: pull them to host on process 0
    # and compare against the single-device engines on the same panel
    single = monthly_spread_backtest(prices, mask)
    sb = banded_monthly_backtest(prices, mask, lookback=12, skip=1,
                                 n_bins=5, band=1)

    def _eq(a, b):
        a, b = np.asarray(a), np.asarray(b)
        live = np.isfinite(b)
        return bool(
            np.array_equal(np.isfinite(a), live)
            and np.allclose(a[live], b[live], rtol=1e-12)
        )

    monthly_equal = _eq(spread, single.spread) and bool(
        abs(float(mean) - float(single.mean_spread)) < 1e-12
    )
    banded_equal = _eq(b_spread, sb.spread) and bool(
        abs(float(b_tnw) - float(sb.tstat_nw)) < 1e-11
    )

    # cross-process online-ridge equality: same mask/NaN shaping as the
    # single-device fit's scores (seeded rank-1 chain vs the sequential
    # one differs only in float association at the block seeds)
    or_single = online_ridge_scores(
        jnp.asarray(feats), jnp.asarray(y_or), jnp.asarray(w_or > 0),
        alpha=0.8, burn_in=8,
    )
    got_scores = np.where(
        (np.asarray(wr) > 0) & np.asarray(seen_g),
        np.asarray(preds_g), np.nan,
    ).T
    ref_scores = np.asarray(or_single.scores)
    live_or = np.isfinite(ref_scores)
    online_equal = bool(
        np.array_equal(np.isfinite(got_scores), live_or)
        # atol=0: allclose's default 1e-8 absolute slack would swamp the
        # rtol on ~1e-3-magnitude scores and let a real carry bug pass
        and np.allclose(got_scores[live_or], ref_scores[live_or],
                        rtol=1e-9, atol=0.0)
    )
    print(json.dumps({
        "metric": "multihost_sharded_equals_single",
        "value": float(monthly_equal and banded_equal and online_equal),
        "unit": "bool",
        "vs_baseline": 0.0,
        "extra": {
            "topology": f"{N_PROC} OS processes x {LOCAL_DEVICES} CPU "
                        "devices, jax.distributed + gloo TCP collectives",
            "workload": f"{A} assets x {M} months f64, masked lanes; "
                        "monthly (qcut rank, all_gather + psum), "
                        "banded (band recursion + one psum), J=12 skip=1; "
                        f"online ridge {A_or}x{R_or}x{F_or} time-sharded "
                        "across both processes",
            "monthly_equal": monthly_equal,
            "banded_equal": banded_equal,
            "online_ridge_equal": online_equal,
            "monthly_wall_s": round(monthly_wall, 3),
            "banded_wall_s": round(banded_wall, 3),
            "online_ridge_wall_s": round(online_wall, 3),
            "note": "walls are compile-dominated one-shot runs, recorded "
                    "for provenance only; the payload of this capture is "
                    "the cross-process EQUALITY, which extends the "
                    "in-process mesh equality tests over a real process/"
                    "serialization boundary",
        },
    }))


def main() -> None:
    import threading

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker", str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for i in range(N_PROC)
    ]
    outs = [None] * N_PROC

    def _drain(i):
        outs[i] = procs[i].stdout.read()

    threads = [threading.Thread(target=_drain, args=(i,)) for i in range(N_PROC)]
    for t in threads:
        t.start()
    try:
        for p in procs:
            p.wait(timeout=900)
        for t in threads:
            t.join(timeout=60)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for t in threads:
            t.join(timeout=60)
    for i, p in enumerate(procs):
        if p.returncode != 0:
            print((outs[i] or "")[-3000:], file=sys.stderr)
            raise SystemExit(f"worker {i} failed rc={p.returncode}")
    for line in reversed((outs[0] or "").strip().splitlines()):
        if line.startswith("{"):
            print(line)
            return
    raise SystemExit("no summary line from worker 0")


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        worker(int(sys.argv[2]))
    else:
        main()
