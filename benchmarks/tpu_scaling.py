"""Universe-size scaling of the 16-cell grid on whatever platform is up.

The north-star workload (3,000 stocks x 60 years, 16 J x K cells —
``BASELINE.json``) measures ~0.09 s on one TPU v5e chip, which is
dispatch-bound, not bandwidth-bound.  This benchmark quantifies the
headroom: the same compiled grid at 4x / 16x / 32x the north-star
universe, for each cohort-aggregation kernel (``impl='xla' | 'matmul' |
'pallas'``), plus the decile-ranking kernel alone, emitting one JSON line
per point and a trailing summary line.

Monthly panels are synthesized directly (random-walk prices with
staggered listing starts) instead of going through the daily pipeline:
the grid consumes month-end panels ``pm f[A, M]``, and at A = 96k the
daily intermediate would only add host-side generation time without
touching the compiled path being measured.

Timing discipline: on the image's tunneled 'axon' TPU backend,
``jax.block_until_ready`` has been observed to return in ~60 us without a
device round trip, flat across a 32x spread of problem sizes — so every
timed rep here fetches an in-jit scalar reduction to host
(``jax.device_get``), which provably includes execution, and the tiny-op
RTT baseline is reported alongside.

Run:  ``python benchmarks/tpu_scaling.py``  (honors JAX_PLATFORMS; use
``JAX_PLATFORMS=cpu`` for the fallback).  Valid TPU results are committed
as ``SCALING_TPU_r03.json`` once a tunnel window allows a device_get-timed
run.
"""

import json
import os
import sys
import time

import numpy as np

# runnable as `python benchmarks/tpu_scaling.py` from anywhere: the package
# lives at the repo root, one level up from this script
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# deadline anchor: module-import time ~= process start.  Tunneled jax setup
# (import, device init, RTT probe) can eat 60-120s before main() arms the
# guard; anchoring there would let the external SIGKILL win (see
# csmom_tpu.utils.deadline).
_T0 = time.monotonic()


def monthly_panel(A: int, M: int, seed: int = 7):
    """Month-end price panel with staggered listings: ``(prices, valid)``."""
    rng = np.random.default_rng(seed)
    rets = rng.normal(0.008, 0.06, size=(A, M)).astype(np.float32)
    prices = 100.0 * np.exp(np.cumsum(rets, axis=1, dtype=np.float64))
    start = rng.integers(0, M // 3, size=A)
    valid = np.arange(M)[None, :] >= start[:, None]
    prices = np.where(valid, prices, np.nan).astype(np.float32)
    return prices, valid


def main():
    import jax  # noqa: F401  (cache config must precede first compile)

    from csmom_tpu.utils.jit_cache import enable_persistent_cache

    # share bench.py's cache dir: the grid shapes here are supersets of the
    # bench child's, and a tunnel window must never be spent recompiling
    # what a previous attempt already paid for
    enable_persistent_cache("bench")

    from csmom_tpu.backtest.grid import jk_grid_backtest
    from csmom_tpu.ops.ranking import decile_assign_panel
    from csmom_tpu.signals.momentum import momentum_dynamic

    import jax.numpy as jnp

    from csmom_tpu.utils.profiling import fetch, measure_rtt

    platform = jax.devices()[0].platform
    kind = str(jax.devices()[0].device_kind)

    # Timed reps fetch an in-jit scalar to host (profiling.fetch) —
    # block_until_ready does not reliably sync on the tunneled backend;
    # the tiny-op RTT is the floor such walls cannot go under.
    rtt_s = measure_rtt()
    print(json.dumps({"tiny_op_rtt_s": round(rtt_s, 6)}), flush=True)
    M = 720  # 60 years of months
    Js = np.array([3, 6, 9, 12])
    Ks = np.array([3, 6, 9, 12])
    sizes = [3_000, 12_000, 48_000, 96_000]
    impls = (
        ["xla", "matmul", "matmul_bf16", "pallas"]
        if platform == "tpu"
        else ["xla", "matmul"]
    )
    rows = []

    def summary(partial=None):
        d = {
            "metric": "grid16_scaling",
            "platform": platform,
            "device_kind": kind,
            "grid": "16 cells (J,K in {3,6,9,12}), 60yr monthly, mode=rank",
            "north_star": "A=3000 row",
            "tiny_op_rtt_s": round(rtt_s, 6),
            "timing": "per-rep device_get of an in-jit scalar reduction "
                      "(block_until_ready does not reliably sync on "
                      "tunneled backends)",
            "rows": list(rows),
        }
        if partial:
            d["partial"] = partial
        return d

    # Deadline guard (same failure mode as bench.py's child, r5: a
    # 900s-timeout scaling run was SIGKILLed mid-compile and every point it
    # HAD measured was discarded).  If CSMOM_SCALING_BUDGET_S is set, the
    # summary of whatever points completed is emitted before the external
    # timeout fires; exactly one summary line ever prints.
    from csmom_tpu.utils.deadline import deadline_guard

    finish = deadline_guard(
        "CSMOM_SCALING_BUDGET_S",
        lambda: json.dumps(summary(
            partial="deadline hit: unmeasured sizes/impls are absent "
                    "(watchdog dump, not a full sweep)"
        )) if rows else None,
        t0=_T0,
    )

    for A in sizes:
        pm, mm = monthly_panel(A, M)
        pm_d, mm_d = jax.device_put(pm), jax.device_put(mm)

        # ranking kernel alone: momentum signal -> per-date decile labels.
        # Reduce to a scalar INSIDE the jit so the per-rep host fetch is 4
        # bytes — the fetch forces execution without measuring transfer.
        mom, mom_valid = jax.block_until_ready(
            jax.jit(lambda p, v: momentum_dynamic(p, v, jnp.asarray(12), skip=1))(
                pm_d, mm_d
            )
        )
        rank_fn = jax.jit(
            lambda x, v: decile_assign_panel(x, v, 10, mode="rank")[0].sum()
        )
        fetch(rank_fn(mom, mom_valid))
        reps = 10
        t0 = time.perf_counter()
        for _ in range(reps):
            fetch(rank_fn(mom, mom_valid))
        rank_s = (time.perf_counter() - t0) / reps

        row = {"A": A, "M": M, "decile_rank_s": round(rank_s, 5)}
        for impl in impls:
            g = jax.jit(
                lambda p, v, impl=impl: jk_grid_backtest(
                    p, v, Js, Ks, skip=1, mode="rank", impl=impl
                ).mean_spread.sum()
            )
            try:
                fetch(g(pm_d, mm_d))  # compile
                reps = 5 if A <= 48_000 else 3
                t0 = time.perf_counter()
                for _ in range(reps):
                    fetch(g(pm_d, mm_d))
                row[f"grid16_{impl}_s"] = round((time.perf_counter() - t0) / reps, 5)
            except Exception as e:  # record OOM/compile failures, keep going
                row[f"grid16_{impl}_s"] = f"failed: {type(e).__name__}: {e}"[:160]
        rows.append(row)
        print(json.dumps(row), flush=True)

    finish(json.dumps(summary()))


if __name__ == "__main__":
    main()
