#!/bin/bash
# Watch for a live TPU tunnel window and capture the scaling benchmark.
#
# The image's axon backend flaps (up in ~25-minute windows, otherwise jax
# backend init hangs), so a foreground "run it now" approach misses windows.
# This loop probes with a hard timeout; on the first successful probe it runs
# benchmarks/tpu_scaling.py and saves raw output to benchmarks/scaling_raw.log,
# then exits. All probe attempts are logged with timestamps.
LOG=/root/repo/benchmarks/tunnel_watch.log
OUT=/root/repo/benchmarks/scaling_raw.log
cd /root/repo
while true; do
  ts=$(date -u +%FT%TZ)
  if timeout 120 python -c "import jax; assert jax.devices()[0].platform=='tpu'" 2>>"$LOG"; then
    echo "$ts probe OK — tunnel up, starting scaling capture" >> "$LOG"
    timeout 1500 python benchmarks/tpu_scaling.py > "$OUT" 2>&1
    rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%FT%TZ) scaling capture DONE" >> "$LOG"
      exit 0
    else
      echo "$(date -u +%FT%TZ) scaling capture FAILED/timed out (rc=$rc), will retry" >> "$LOG"
    fi
  else
    echo "$ts probe failed (init hang or no tpu)" >> "$LOG"
  fi
  sleep 150
done
