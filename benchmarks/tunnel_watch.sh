#!/bin/bash
# Watch for a live TPU tunnel window and capture the round's on-chip evidence.
#
# The image's axon backend flaps (up in ~25-minute windows, otherwise jax
# backend init hangs), so a foreground "run it now" approach misses windows.
# This loop probes with a hard timeout; on a successful probe it runs, in
# priority order, whichever captures are still missing:
#   1. bench.py (supervisor persists BENCH_TPU_LAST.json on a live capture)
#   2. benchmarks/tpu_scaling.py      -> benchmarks/scaling_raw.log
#   3. benchmarks/grid_phases.py      -> benchmarks/phases_raw.log
# and exits once all three exist. All probe attempts are logged.
#
# ROUND parameterizes the committed artifact names (SCALING_TPU_${ROUND}.json,
# PHASES_TPU_${ROUND}.json) so a watcher left running past its round can never
# mislabel a later round's captures: pass it as $1 or env ROUND; there is no
# default — the watcher refuses to start without one. It also refuses to
# overwrite an artifact that already exists under the committed name
# (ADVICE r4: a stale watcher must not clobber a landed capture).
ROUND="${1:-${ROUND:-}}"
if [ -z "$ROUND" ]; then
  echo "tunnel_watch.sh: ROUND required (arg or env), e.g. r05" >&2
  exit 2
fi
# Hard lifetime (default 13 h > one round): a watcher that never satisfied
# have_all must still die before it can act in a later round.
WATCH_MAX_S="${WATCH_MAX_S:-46800}"
LOG=/root/repo/benchmarks/tunnel_watch.log
SCALING_OUT=/root/repo/benchmarks/scaling_raw.log
PHASES_OUT=/root/repo/benchmarks/phases_raw.log
BENCH_MARK=/root/repo/BENCH_TPU_LAST.json
SCALING_ART=/root/repo/SCALING_TPU_${ROUND}.json
PHASES_ART=/root/repo/PHASES_TPU_${ROUND}.json
START_TS=$(date +%s)
# resolve BEFORE cd: a relative $0 from another cwd must still source
LIB_DIR=$(cd "$(dirname "$0")" && pwd)
cd /root/repo

log() { echo "$(date -u +%FT%TZ) [$ROUND] $*" >> "$LOG"; }

# land_artifact / promote_capture live in capture_lib.sh (sourced) so the
# partial-vs-full landing rules are testable (tests/test_capture_lib.py).
. "$LIB_DIR"/capture_lib.sh || { echo "capture_lib.sh missing" >&2; exit 2; }

bench_fresh() {
  # BENCH_TPU_LAST.json persists across rounds as bench.py's cache: only a
  # capture NEWER than this watcher counts as this round's evidence
  [ -s "$BENCH_MARK" ] && [ "$(stat -c %Y "$BENCH_MARK")" -ge "$START_TS" ]
}

have_all() {
  bench_fresh && [ -s "$SCALING_OUT" ] && [ -s "$PHASES_OUT" ]
}

while true; do
  if have_all; then
    log "all captures present — watcher done"
    exit 0
  fi
  if [ "$(( $(date +%s) - START_TS ))" -ge "$WATCH_MAX_S" ]; then
    log "lifetime ${WATCH_MAX_S}s reached — watcher exiting (round is over)"
    exit 0
  fi
  if timeout 120 python -c "import jax; assert jax.devices()[0].platform=='tpu'" 2>>"$LOG"; then
    log "probe OK — tunnel up"
    if ! bench_fresh; then
      log "running bench.py (budget 1800s)"
      # CSMOM_ROUND gets a _watcher suffix: the full record this capture
      # writes lands under its OWN committed name and can never clobber
      # the driver's official end-of-round BENCH_FULL_${ROUND}.json
      # 1800s: the supervisor gives the TPU child up to 1200s of this —
      # tunneled compiles alone overran the old 900/450 split (r5: the
      # 03:47 window's child was killed at 477s with nothing printed).
      # The child's own deadline watchdog + persistent compile cache make
      # even a short window land at least a partial on-chip record.
      CSMOM_BENCH_BUDGET=1800 CSMOM_ROUND="${ROUND}_watcher" timeout 1860 \
        python bench.py > /root/repo/benchmarks/bench_tpu_raw.log 2>&1
      log "bench.py rc=$? (fresh BENCH_TPU_LAST.json: $( bench_fresh && echo yes || echo NO ))"
    fi
    if [ ! -s "$SCALING_OUT" ]; then
      log "running tpu_scaling.py"
      CSMOM_SCALING_BUDGET_S=870 timeout 900 \
        python benchmarks/tpu_scaling.py > "$SCALING_OUT".tmp 2>&1
      rc=$?
      if [ "$rc" -eq 0 ]; then
        promote_capture "tpu_scaling" "$SCALING_OUT" "$SCALING_ART"
      fi
      log "tpu_scaling rc=$rc"
    fi
    if [ ! -s "$PHASES_OUT" ]; then
      log "running grid_phases.py (north-star size)"
      CSMOM_PHASES_BUDGET_S=420 timeout 450 python benchmarks/grid_phases.py \
        --reps 5 > "$PHASES_OUT".tmp 2>&1
      rc=$?
      if [ "$rc" -eq 0 ]; then
        promote_capture "grid_phases" "$PHASES_OUT" "$PHASES_ART"
      fi
      log "grid_phases 1x rc=$rc"
    fi
    # 32x is best-effort extra evidence: captured separately so an OOM at
    # 96k assets can never discard or block the north-star phase capture
    PHASES32_OUT=/root/repo/benchmarks/phases32_raw.log
    if [ -s "$PHASES_OUT" ] && [ ! -s "$PHASES32_OUT" ]; then
      log "running grid_phases.py --ax 32 (best-effort)"
      CSMOM_PHASES_BUDGET_S=420 timeout 450 python benchmarks/grid_phases.py \
        --ax 32 --reps 3 > "$PHASES32_OUT".tmp 2>&1
      rc=$?
      if [ "$rc" -eq 0 ]; then mv "$PHASES32_OUT".tmp "$PHASES32_OUT"; fi
      log "grid_phases 32x rc=$rc"
    fi
  else
    log "probe failed (init hang or no tpu)"
  fi
  sleep 150
done
