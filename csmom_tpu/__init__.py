"""csmom_tpu — TPU-native cross-sectional momentum replication & backtesting framework.

A ground-up JAX/XLA re-design of the capabilities of the reference framework
``AkshayJha22/Cross-Sectional-Momentum-Strategy-Replication-Backtesting-Framework``
(a pure-pandas, single-process pipeline; see that repo's ``run_demo.py`` and
``src/``).  Instead of long-format DataFrames iterated row by row, this
framework represents market data as dense **masked panels** — ``f32[A, T]``
arrays (assets x time) resident in accelerator HBM — and expresses all
strategy logic as pure, jit-compiled functions over those panels:

- ``panel``     ingest (CSV dialect repair, calendar alignment), Panel container,
                cache-first fetch layer, synthetic generators
- ``ops``       masked rolling windows, cross-sectional ranking (exact
                pandas-qcut parity + fast rank mode), Pallas TPU kernels
- ``signals``   momentum (J, skip), turnover, intraday minute features
- ``models``    closed-form ridge regression with expanding-window time-series CV
- ``costs``     square-root market impact, spread, fill models
- ``backtest``  vectorized monthly decile engine, J x K grid, double sort,
                walk-forward sweep, event-driven engine
- ``analytics`` sharpe, t-stats, block bootstrap, artifact writers
- ``parallel``  device-mesh sharding (shard_map), distributed rank, collectives
- ``backends``  one API over the 'tpu' (JAX) and 'pandas' engines
- ``native``    C++ runtime components (fast CSV parser via ctypes)
- ``serve``     online workload: micro-batching signal service (bounded
                admission, shape-bucket coalescing, seeded load generator)
- ``cli``       the ``csmom`` entry points (the subcommand table is
                generated into ``csmom --help``'s epilog from the registry)
- ``utils``     structured logging, profiling, error guards

The parameter grid (J x K lookback/holding) is a ``vmap`` axis; the asset axis
shards across a ``jax.sharding.Mesh`` with the cross-sectional rank as the only
global collective (all_gather) and ``psum`` for portfolio reductions.
"""

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy re-export (PEP 562): the eager `from csmom_tpu.panel.panel
    # import Panel` pulled jax + pandas (~2.3 s) into EVERY process that
    # touches the package — including pool worker spawns (the serving
    # tier pays it per worker, per restart, per roll) and jax-free CLI
    # paths.  Resolving Panel on first attribute access keeps the
    # package import near-free; `from csmom_tpu import Panel` still
    # works unchanged.
    if name == "Panel":
        from csmom_tpu.panel.panel import Panel

        return Panel
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
