"""csmom_tpu — TPU-native cross-sectional momentum replication & backtesting framework.

A ground-up JAX/XLA re-design of the capabilities of the reference framework
``AkshayJha22/Cross-Sectional-Momentum-Strategy-Replication-Backtesting-Framework``
(a pure-pandas, single-process pipeline; see that repo's ``run_demo.py`` and
``src/``).  Instead of long-format DataFrames iterated row by row, this
framework represents market data as dense **masked panels** — ``f32[A, T]``
arrays (assets x time) resident in accelerator HBM — and expresses all
strategy logic as pure, jit-compiled functions over those panels:

- ``panel``     ingest (CSV dialect repair, calendar alignment), Panel container
- ``ops``       masked rolling windows, scans, cross-sectional ranking kernels
- ``signals``   momentum (J, skip), turnover, intraday minute features
- ``ranking``   decile assignment (exact pandas-qcut parity + fast rank mode)
- ``models``    closed-form ridge regression with expanding-window time-series CV
- ``costs``     square-root market impact, spread, fill models
- ``backtest``  vectorized monthly decile engine, J x K grid, event-driven engine
- ``analytics`` sharpe, t-stats, decile tables, results schemas
- ``parallel``  device-mesh sharding (shard_map), distributed rank, collectives
- ``strategy``  Strategy protocol; 'tpu' (JAX) and 'pandas' backends behind one API
- ``cli``       run / replicate / grid / sweep commands
- ``utils``     structured logging, profiling, error guards

The parameter grid (J x K lookback/holding) is a ``vmap`` axis; the asset axis
shards across a ``jax.sharding.Mesh`` with the cross-sectional rank as the only
global collective (all_gather) and ``psum`` for portfolio reductions.
"""

__version__ = "0.1.0"

from csmom_tpu.panel.panel import Panel  # noqa: F401
