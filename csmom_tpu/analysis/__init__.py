"""csmom_tpu.analysis — the static-analysis subsystem (ISSUE 11).

One parse per file, N registered rule visitors, scoped in-file pragmas
with stale-pragma detection, and a registry-driven rule set: see
:mod:`csmom_tpu.analysis.core` for the framework and
:mod:`csmom_tpu.analysis.rules` for the builtin rules (clock-discipline,
tracer-hygiene, lock-discipline, donation-safety, enumeration-drift).

Entry points:

- :func:`run_lint` — the sweep (what tier-1 and ``csmom rehearse``
  gate on); returns a :class:`~csmom_tpu.analysis.core.LintReport`;
- ``csmom lint [--json] [--rule <id>] [--paths ...]`` — the CLI
  (:mod:`csmom_tpu.cli.lint`).

Stdlib-only and jax-free: the sweep runs on CPU in about a second, which
is the whole point — a defect caught here never burns a tunnel window.
"""

from __future__ import annotations

from csmom_tpu.analysis.core import (
    Finding,
    LintReport,
    LintRule,
    default_sources,
    run_lint,
)

__all__ = [
    "Finding",
    "LintReport",
    "LintRule",
    "default_sources",
    "run_lint",
]
