"""csmom_tpu.analysis — the static-analysis subsystem (ISSUE 11 + 12).

One parse per file, N registered rule visitors, scoped in-file pragmas
with stale-pragma detection, and a registry-driven rule set: see
:mod:`csmom_tpu.analysis.core` for the framework,
:mod:`csmom_tpu.analysis.rules` for the per-file builtins
(clock-discipline, tracer-hygiene, lock-discipline, donation-safety,
enumeration-drift), :mod:`csmom_tpu.analysis.callgraph` for the
whole-program layer (alias-aware project call graph, per-object lock
identities), and :mod:`csmom_tpu.analysis.project_rules` for the
project-scope rules (lock-order, helper-hygiene, compile-surface).

Entry points:

- :func:`run_lint` — the sweep (what tier-1 and ``csmom rehearse``
  gate on, at project scope); returns a
  :class:`~csmom_tpu.analysis.core.LintReport`;
- ``csmom lint [--project] [--format text|json|github] [--no-cache]
  [--rule <id>] [--paths ...]`` — the CLI (:mod:`csmom_tpu.cli.lint`).

Stdlib-only and jax-free: the sweep runs on CPU in seconds cold and
tens of milliseconds warm (the content-digest incremental cache,
:mod:`csmom_tpu.analysis.cache`), which is the whole point — a defect
caught here never burns a tunnel window.
"""

from __future__ import annotations

from csmom_tpu.analysis.core import (
    Finding,
    LintReport,
    LintRule,
    ProjectRule,
    default_sources,
    run_lint,
)

__all__ = [
    "Finding",
    "LintReport",
    "LintRule",
    "ProjectRule",
    "default_sources",
    "run_lint",
]
