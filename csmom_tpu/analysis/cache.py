"""Incremental sweep cache (ISSUE 12 satellite).

Tier-1's lint gate re-parsed ~150 unchanged files on every run.  This
cache remembers, per file, everything a sweep needs to SKIP the parse:

- the file's **content blake2b** (the key — a byte-identical file gets
  byte-identical findings, which is the framework's reproducibility
  contract restated as a cache invariant);
- the **raw findings** the file-scope rules reported (pre-pragma, so a
  replay routes them through the live pragma machinery and suppression
  semantics stay identical to a fresh run);
- the file's **pragmas** (rule, line, standalone-ness) — enough to
  rebuild suppression and stale-pragma evaluation without tokenizing;
- per-rule **facts** — the cross-file state a rule mines from one file
  (e.g. enumeration-drift's checkpoint call sites), re-absorbed on
  replay so whole-run checks still see every file.

Project-scope results are keyed by the blake2b of the SORTED per-file
digest set: any one file changing invalidates the whole project entry
(a whole-program property has no smaller sound key).  Rules that read
runtime state (the compile-surface registry check) declare
``cacheable = False`` and always run live.

Every key also folds in a signature of the ``analysis/`` package's own
sources plus the active rule-id set, so editing a rule — or
registering a different rule mix — invalidates stale verdicts without
any manual version bump.

Storage is one JSON file under ``.csmom_lint_cache/`` in the scanned
repo root (``--no-cache`` bypasses; the directory is gitignored).
Writes are atomic (tmp + rename) and a damaged/alien cache file is
treated as empty, never an error — the cache may only ever change the
sweep's SPEED.
"""

from __future__ import annotations

import hashlib
import json
import os

__all__ = ["SweepCache", "content_digest"]

_FORMAT = 2

# how many differently-keyed entries coexist per file / for the project
# slot: enough for the sweep mixes one tree realistically runs (the full
# gate, a --rule filter or two), small enough that the cache file stays
# bounded
_SIGS_PER_FILE = 4
_PROJECT_SLOTS = 4


def content_digest(src: bytes | str) -> str:
    if isinstance(src, str):
        src = src.encode("utf-8")
    return hashlib.blake2b(src, digest_size=16).hexdigest()


def _analysis_signature(rule_ids, salts=(), extra_sources=()) -> str:
    """blake2b over the active rule ids, their runtime cache salts
    (``LintRule.cache_salt`` — e.g. the checkpoint vocabulary the
    enumeration-drift verdicts depend on), the analysis package's own
    sources, AND any out-of-package rule sources (plugin rules
    registered through the kind-``lint`` registry path) — a rule edit,
    a different rule mix, or a changed runtime input is a different
    sweep."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr(sorted(rule_ids)).encode("utf-8"))
    h.update(repr(sorted(salts)).encode("utf-8"))
    pkg = os.path.dirname(os.path.abspath(__file__))
    own = [os.path.join(pkg, name) for name in sorted(os.listdir(pkg))
           if name.endswith(".py")]
    for path in own + sorted(extra_sources):
        try:
            with open(path, "rb") as f:
                h.update(f.read())
        except OSError:  # pragma: no cover - unreadable rule source
            pass
    return h.hexdigest()


def _finding_rec(e) -> bool:
    return (isinstance(e, dict) and isinstance(e.get("rule"), str)
            and isinstance(e.get("line"), int)
            and isinstance(e.get("message"), str)
            and isinstance(e.get("chain", []), list)
            and isinstance(e.get("rel", ""), str))


def _pragma_rec(p) -> bool:
    return (isinstance(p, dict) and isinstance(p.get("rule"), str)
            and isinstance(p.get("line"), int))


def _file_entry(e) -> bool:
    return (isinstance(e, dict) and isinstance(e.get("digest"), str)
            and isinstance(e.get("raw"), list)
            and all(_finding_rec(r) for r in e["raw"])
            and isinstance(e.get("pragmas"), list)
            and all(_pragma_rec(p) for p in e["pragmas"])
            and isinstance(e.get("facts"), dict)
            and all(isinstance(k, str) for k in e["facts"]))


def _sane(data) -> bool:
    """True when *data* is structurally a cache this code could have
    written.  The format marker alone is not enough: a truncated or
    hand-edited file (or a future version reusing the marker) must read
    as COLD, never crash a replay — the cache may only ever change the
    sweep's speed."""
    if not (isinstance(data, dict) and data.get("format") == _FORMAT
            and isinstance(data.get("files"), dict)
            and isinstance(data.get("project", {}), dict)):
        return False
    for rel, sigs in data["files"].items():
        if not (isinstance(rel, str) and isinstance(sigs, dict)
                and all(isinstance(s, str) and _file_entry(e)
                        for s, e in sigs.items())):
            return False
    for key, rules in data.get("project", {}).items():
        if not (isinstance(key, str) and isinstance(rules, dict)
                and all(isinstance(rid, str) and isinstance(lst, list)
                        and all(_finding_rec(e) for e in lst)
                        for rid, lst in rules.items())):
            return False
    return True


class SweepCache:
    """One repo's sweep cache: load once, query per file, save once."""

    def __init__(self, repo: str, rule_ids, directory: str | None = None,
                 salts=(), extra_sources=()):
        self.dir = directory or os.path.join(repo, ".csmom_lint_cache")
        self.path = os.path.join(self.dir, "sweep.json")
        self.sig = _analysis_signature(rule_ids, salts, extra_sources)
        self.hits = 0
        self.misses = 0
        self.project_hit = False
        self._dirty = False
        self._data = {"format": _FORMAT, "files": {}, "project": {}}
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            if _sane(data):
                self._data = data
                self._data.setdefault("project", {})
        except (OSError, ValueError):
            pass    # cold, damaged, or alien: start empty

    # ------------------------------------------------------------ per-file

    # entries live per (rel, rule-set signature): a ``--rule`` filtered
    # sweep and the full tier-1 gate coexist in one warm cache instead
    # of evicting each other on every alternation

    def lookup(self, rel: str, digest: str) -> dict | None:
        entry = (self._data["files"].get(rel) or {}).get(self.sig)
        if isinstance(entry, dict) and entry.get("digest") == digest:
            self.hits += 1
            return entry
        self.misses += 1
        return None

    def store(self, rel: str, digest: str, raw: list, pragmas: list,
              facts: dict) -> None:
        sigs = self._data["files"].setdefault(rel, {})
        sigs.pop(self.sig, None)        # re-insert last = newest
        sigs[self.sig] = {"digest": digest, "raw": raw,
                          "pragmas": pragmas, "facts": facts}
        while len(sigs) > _SIGS_PER_FILE:
            sigs.pop(next(iter(sigs)))
        self._dirty = True

    # ------------------------------------------------------------- project

    def project_key(self, digests, rule_ids=()) -> str:
        h = hashlib.blake2b(digest_size=16)
        h.update(self.sig.encode("utf-8"))
        h.update(repr(sorted(rule_ids)).encode("utf-8"))
        for rel, digest in sorted(digests):
            h.update(f"{rel}\0{digest}\n".encode("utf-8"))
        return h.hexdigest()

    def lookup_project(self, key: str) -> dict | None:
        entry = (self._data.get("project") or {}).get(key)
        if isinstance(entry, dict):
            self.project_hit = True
            return entry
        return None

    def store_project(self, key: str, rules: dict) -> None:
        slots = self._data.setdefault("project", {})
        slots.pop(key, None)
        slots[key] = rules
        while len(slots) > _PROJECT_SLOTS:
            slots.pop(next(iter(slots)))
        self._dirty = True

    # ---------------------------------------------------------------- save

    def save(self) -> None:
        if not self._dirty:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._data, f)
            os.replace(tmp, self.path)
        except OSError:  # pragma: no cover - read-only checkout
            pass         # a cache that cannot persist is just cold

    def stats(self) -> dict:
        return {"enabled": True, "hits": self.hits,
                "misses": self.misses, "project_hit": self.project_hit}
