"""Alias-aware project call graph + per-object lock identities (ISSUE 12).

The r16 framework (:mod:`csmom_tpu.analysis.core`) is deliberately
single-file: one parse, N rule visitors, nothing remembered across
files beyond a few rule-owned counters.  That ceiling is exactly where
its three hardest contracts stop being checkable — a blocking call
under a lock hides behind one helper call, lock ACQUISITION ORDER is a
property of the whole program, and "every dispatchable shape has a
warmed manifest entry" spans four subsystems.  This module is the
whole-program layer those project-scope rules share:

- **module naming** — every scanned file gets a dotted module name from
  its repo-relative path (``csmom_tpu/serve/router.py`` →
  ``csmom_tpu.serve.router``; ``__init__.py`` names its package), so a
  cross-module import in one file and a definition in another meet on
  one key;
- **function index** — module functions, class methods, and nested
  defs, each a :class:`FunctionInfo` with a stable qualified name;
- **alias-aware call resolution** — call sites resolve through the
  per-file alias maps (absolute AND relative imports, one re-export
  hop), ``self``-method dispatch with single-base inheritance,
  ``self.attr.method()`` via **self-type inference from ``__init__``
  assignments** (``self._svc = ServeService(...)`` types ``_svc``), and
  local ``x = ClassName(...)`` constructor bindings;
- **lock identities** — every ``self._lock = threading.Lock()`` site is
  a node (``module.Class._lock``), module-level locks likewise;
  ``threading.Condition(self._lock)`` ALIASES the lock it wraps, so
  ``with self._nonempty:`` and ``with self._lock:`` count as the same
  acquisition (they are — that aliasing is why the r16 per-file rule
  could never model it);
- **held-lock regions** — per function, which calls run while which
  locks are held, and which locks are acquired while others are held
  (the raw material of the acquisition-order graph);
- **bounded interprocedural closures** — ``acquired_closure`` (locks a
  call may take, with the call chain as evidence) and
  ``blocking_reach`` (the first chain to a blocking primitive), both
  memoized and depth-bounded at :data:`MAX_CHAIN_DEPTH`.

Honest limits (documented, not hidden): resolution is static and
best-effort — dynamic dispatch through callables stored in dicts,
``**kwargs`` forwarding, and monkeypatching are invisible; inheritance
lookup follows project-resolvable bases only; closures are cut at
``MAX_CHAIN_DEPTH`` hops.  A miss makes a rule QUIETER, never wrong
about what it does report, which is the right failure mode for a gate.

Stdlib-only, jax-free, clock-free — same layering as the rest of
``analysis/``.
"""

from __future__ import annotations

import ast
import dataclasses
import os

__all__ = [
    "CallSite",
    "ClassInfo",
    "FunctionInfo",
    "MAX_CHAIN_DEPTH",
    "ProjectContext",
    "module_name_for",
]

# interprocedural closures stop after this many call hops: deep enough
# for every real chain in the tree (the longest serve-path chain is 4),
# shallow enough that a pathological call web cannot make the sweep
# quadratic
MAX_CHAIN_DEPTH = 6

_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
}

# attribute names that read as indefinite blocking primitives when
# called on ANY receiver (socket family, thread joins, engine dispatch).
# ``Condition.wait`` is deliberately absent: it RELEASES the lock it
# waits on, which is the one blocking call that is correct under a lock.
BLOCKING_ATTRS = frozenset({
    "send", "sendall", "recv", "recv_into", "connect", "accept",
    "dispatch",
})


def module_name_for(rel: str) -> str:
    """Dotted module name for a repo-relative path (posix or native)."""
    rel = rel.replace(os.sep, "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    return rel.replace("/", ".")


@dataclasses.dataclass
class CallSite:
    """One call made by one function: where, to what (as resolved)."""

    line: int
    callee: str | None = None   # qname of a resolved project function
    origin: str | None = None   # dotted origin for external/unresolved
    attr: str | None = None     # raw trailing name (``.sendall`` etc.)
    has_args: bool = False      # any positional/keyword argument present
    held: tuple = ()            # lock ids held at the call site
    anon_held: int = 0          # locally-scoped/anonymous locks held


@dataclasses.dataclass
class ClassInfo:
    """One project class: bases, attribute types, and lock attributes."""

    qname: str
    module: str
    name: str
    node: ast.ClassDef
    rel: str
    bases: tuple = ()           # project-resolved base class qnames
    attr_types: dict = dataclasses.field(default_factory=dict)
    lock_attrs: dict = dataclasses.field(default_factory=dict)
    # condition attr -> the lock attr it wraps (None = its own lock)
    cond_alias: dict = dataclasses.field(default_factory=dict)
    methods: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FunctionInfo:
    """One function/method/nested def and its analyzed body."""

    qname: str
    module: str
    cls: str | None             # owning class qname, None for functions
    name: str
    node: ast.AST
    ctx: object                 # the owning FileContext
    rel: str
    line: int
    calls: list = dataclasses.field(default_factory=list)
    # (outer lock id, inner lock id, line): a DIRECT nested acquisition
    order_pairs: list = dataclasses.field(default_factory=list)
    # (lock id, line): every structured acquisition this body makes
    acquires: list = dataclasses.field(default_factory=list)
    nested: dict = dataclasses.field(default_factory=dict)


class ProjectContext:
    """The whole-program index the project-scope rules share.

    Construction is cheap (it keeps references); the graph is built on
    first access so a project rule that never touches it (the
    compile-surface check) costs nothing.
    """

    def __init__(self, contexts: dict, repo: str):
        self.contexts = contexts        # rel -> FileContext (parse slots)
        self.repo = repo
        self.run = None                 # attached by run_lint
        self._built = False
        self.modules: dict = {}         # dotted module -> FileContext
        self.functions: dict = {}       # qname -> FunctionInfo
        self.classes: dict = {}         # qname -> ClassInfo
        self.module_locks: dict = {}    # lock id -> kind
        self.lock_kinds: dict = {}      # every lock id -> kind
        self._rel_aliases: dict = {}    # rel -> relative-import overlay
        self._closure_memo: dict = {}
        self._blocking_memo: dict = {}
        self._resolve_memo: dict = {}
        self.serve_batch_factories: list = []   # qnames bound as batch_fn

    # ------------------------------------------------------------ report --

    def report(self, rule: str, rel: str, line: int, message: str,
               chain: tuple = ()) -> None:
        """Route a project finding through the owning file's pragma
        machinery (so ``lint: allow[...]`` works for project rules
        exactly like file rules); files outside the scan report raw."""
        slot = self.contexts.get(rel)
        if slot is not None:
            slot.report(rule, line, message, chain=chain)
        else:
            self.run.report(rule, rel, line, message, chain=chain)

    def scanned_rels(self) -> set:
        return {rel.replace(os.sep, "/") for rel in self.contexts}

    # ------------------------------------------------------------- build --

    def build(self) -> "ProjectContext":
        if self._built:
            return self
        self._built = True
        for rel, ctx in self.contexts.items():
            if getattr(ctx, "tree", None) is None:
                continue                # cache-replayed slot, no parse
            mod = module_name_for(rel)
            self.modules[mod] = ctx
            self._rel_aliases[rel] = self._relative_imports(ctx, mod)
        for mod, ctx in self.modules.items():
            self._index_module(mod, ctx)
        for mod, ctx in self.modules.items():
            self._resolve_bases(mod, ctx)
        for info in list(self.functions.values()):
            self._analyze_body(info)
        # registry-registered callables are graph roots: a keyword
        # ``batch_fn=<name>`` anywhere in a module (the builtin
        # registrations are module-level ``REGISTRY.register(...)``
        # calls) marks the factory whose inner functions jit/vmap trace
        for mod, ctx in self.modules.items():
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg == "batch_fn" and isinstance(kw.value,
                                                           ast.Name):
                        q = self.resolve_dotted(
                            self._origin_of(ctx, kw.value)
                            or f"{mod}.{kw.value.id}")
                        if q:
                            self.serve_batch_factories.append(q)
        return self

    @staticmethod
    def _relative_imports(ctx, mod: str) -> dict:
        """Local name -> absolute dotted origin for relative imports
        (``from . import b`` / ``from .helpers import slow_push``) —
        the one import form the per-file alias map cannot resolve,
        because only the project layer knows the file's package."""
        is_pkg = ctx.rel.replace(os.sep, "/").endswith("__init__.py")
        pkg_parts = mod.split(".") if is_pkg else mod.split(".")[:-1]
        out: dict = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.ImportFrom) and node.level > 0):
                continue
            base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
            if node.module:
                base = base + node.module.split(".")
            for a in node.names:
                out[a.asname or a.name] = ".".join(base + [a.name])
        return out

    def _origin_of(self, ctx, node):
        """Alias-map resolution, relative imports included."""
        if isinstance(node, ast.Name):
            overlay = self._rel_aliases.get(ctx.rel, {})
            if node.id in overlay:
                return overlay[node.id]
            return ctx.imports.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._origin_of(ctx, node.value)
            return f"{base}.{node.attr}" if base else None
        return ctx.resolve(node)

    # ------------------------------------------------------------ indexing

    def _index_module(self, mod: str, ctx) -> None:
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mod, None, f"{mod}.{node.name}", node,
                                   ctx)
            elif isinstance(node, ast.ClassDef):
                self._index_class(mod, node, ctx)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                kind = _LOCK_CTORS.get(
                    self._origin_of(ctx, node.value.func) or "")
                if kind:
                    lid = f"{mod}.{node.targets[0].id}"
                    self.module_locks[lid] = kind
                    self.lock_kinds[lid] = kind

    def _index_class(self, mod: str, node: ast.ClassDef, ctx) -> None:
        qname = f"{mod}.{node.name}"
        info = ClassInfo(qname=qname, module=mod, name=node.name,
                         node=node, rel=ctx.rel)
        self.classes[qname] = info
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                m = self._add_function(mod, qname,
                                       f"{qname}.{item.name}", item, ctx)
                info.methods[item.name] = m.qname
        # self-type inference + lock identities: every ``self.X = ...``
        # in ANY method (``__init__`` is just the usual home)
        for item in ast.walk(node):
            if not (isinstance(item, ast.Assign) and len(item.targets) == 1
                    and isinstance(item.targets[0], ast.Attribute)
                    and isinstance(item.targets[0].value, ast.Name)
                    and item.targets[0].value.id == "self"):
                continue
            attr = item.targets[0].attr
            if not isinstance(item.value, ast.Call):
                continue
            origin = self._origin_of(ctx, item.value.func)
            kind = _LOCK_CTORS.get(origin or "")
            if kind == "condition":
                wrapped = None
                if (item.value.args
                        and isinstance(item.value.args[0], ast.Attribute)
                        and isinstance(item.value.args[0].value, ast.Name)
                        and item.value.args[0].value.id == "self"):
                    wrapped = item.value.args[0].attr
                info.cond_alias[attr] = wrapped
                if wrapped is None:
                    # a bare Condition() wraps an RLock (CPython
                    # default) — reentrant; a Condition over an
                    # unresolvable lock expression keeps kind
                    # "condition" (unknown backing: the rule stays
                    # quiet rather than call legal code a deadlock)
                    lid = f"{qname}.{attr}"
                    own_kind = ("rlock" if not item.value.args
                                else "condition")
                    info.lock_attrs[attr] = own_kind
                    self.lock_kinds[lid] = own_kind
            elif kind:
                info.lock_attrs[attr] = kind
                self.lock_kinds[f"{qname}.{attr}"] = kind
            elif origin:
                tcls = self._class_for_origin(origin, ctx)
                if tcls:
                    info.attr_types[attr] = tcls

    def _class_for_origin(self, origin: str, ctx) -> str | None:
        # ``self._svc = ServeService(...)``: ServeService may be local
        # to the module or imported — try the local class first
        mod = module_name_for(ctx.rel)
        if f"{mod}.{origin}" in self.classes or "." not in origin:
            return (f"{mod}.{origin}"
                    if f"{mod}.{origin}" in self.classes else None)
        return origin if origin in self.classes else None

    def _add_function(self, mod, cls, qname, node, ctx) -> FunctionInfo:
        info = FunctionInfo(qname=qname, module=mod, cls=cls,
                            name=node.name, node=node, ctx=ctx,
                            rel=ctx.rel, line=node.lineno)
        self.functions[qname] = info
        for sub in ast.iter_child_nodes(node):
            self._index_nested(info, sub)
        return info

    def _index_nested(self, parent: FunctionInfo, node) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not parent.node:
                q = f"{parent.qname}.{sub.name}"
                if q not in self.functions:
                    child = FunctionInfo(
                        qname=q, module=parent.module, cls=parent.cls,
                        name=sub.name, node=sub, ctx=parent.ctx,
                        rel=parent.rel, line=sub.lineno)
                    self.functions[q] = child
                    parent.nested[sub.name] = q

    def _resolve_bases(self, mod: str, ctx) -> None:
        for node in ctx.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            info = self.classes[f"{mod}.{node.name}"]
            bases = []
            for b in node.bases:
                origin = self._origin_of(ctx, b)
                name = b.id if isinstance(b, ast.Name) else None
                cand = None
                if origin and origin in self.classes:
                    cand = origin
                elif name and f"{mod}.{name}" in self.classes:
                    cand = f"{mod}.{name}"
                elif origin:
                    cand = self._reexport_class(origin)
                if cand:
                    bases.append(cand)
            info.bases = tuple(bases)

    def _reexport_class(self, dotted: str, depth: int = 0) -> str | None:
        """Follow one re-export hop for class names (``from core import
        LintRule`` re-exported through a package ``__init__``)."""
        if depth > 3 or dotted in self.classes:
            return dotted if dotted in self.classes else None
        head, _, tail = dotted.rpartition(".")
        ctx = self.modules.get(head)
        if ctx is None:
            return None
        target = (self._rel_aliases.get(ctx.rel, {}).get(tail)
                  or ctx.imports.get(tail))
        return self._reexport_class(target, depth + 1) if target else None

    # ----------------------------------------------------- call resolution

    def _method_lookup(self, cls_qname: str, name: str,
                       depth: int = 0) -> str | None:
        info = self.classes.get(cls_qname)
        if info is None or depth > 4:
            return None
        if name in info.methods:
            return info.methods[name]
        for b in info.bases:
            hit = self._method_lookup(b, name, depth + 1)
            if hit:
                return hit
        return None

    def resolve_dotted(self, dotted: str, depth: int = 0) -> str | None:
        """Dotted origin -> function qname (one re-export hop, class
        constructor -> ``__init__``, ``Module.Class.method``)."""
        if depth > 4 or not dotted:
            return None
        key = dotted
        if key in self._resolve_memo and depth == 0:
            return self._resolve_memo[key]
        out = None
        if dotted in self.functions:
            out = dotted
        else:
            parts = dotted.split(".")
            for i in range(len(parts) - 1, 0, -1):
                mod = ".".join(parts[:i])
                ctx = self.modules.get(mod)
                if ctx is None:
                    continue
                attrs = parts[i:]
                if len(attrs) == 1:
                    q = f"{mod}.{attrs[0]}"
                    if q in self.functions:
                        out = q
                    elif q in self.classes:
                        out = self._method_lookup(q, "__init__")
                    else:
                        target = (self._rel_aliases.get(ctx.rel, {})
                                  .get(attrs[0])
                                  or ctx.imports.get(attrs[0]))
                        if target and target != dotted:
                            out = self.resolve_dotted(target, depth + 1)
                elif len(attrs) == 2:
                    cls_q = f"{mod}.{attrs[0]}"
                    if cls_q in self.classes:
                        out = self._method_lookup(cls_q, attrs[1])
                break
        if depth == 0:
            self._resolve_memo[key] = out
        return out

    # ------------------------------------------------------- body analysis

    def _lock_identity(self, info: FunctionInfo, expr,
                       local_locks: set) -> tuple:
        """``(lock_id | None, lockish)`` for a with-item/receiver.

        ``lock_id`` is a graph node (per-class attr or module lock);
        ``lockish`` True means "this is a lock even if anonymous" (a
        locally-created lock, a ``state['lock']`` subscript) — held for
        blocking checks, invisible to the order graph."""
        cls = self.classes.get(info.cls) if info.cls else None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls is not None):
            attr = expr.attr
            if attr in cls.cond_alias:
                wrapped = cls.cond_alias[attr]
                return (f"{cls.qname}.{wrapped or attr}", True)
            if attr in cls.lock_attrs:
                return (f"{cls.qname}.{attr}", True)
            if "lock" in attr.lower():
                # a lock attr assigned outside this class body (mixin,
                # late init): still a per-object identity
                lid = f"{cls.qname}.{attr}"
                self.lock_kinds.setdefault(lid, "lock")
                return (lid, True)
            return (None, False)
        if isinstance(expr, ast.Name):
            lid = f"{info.module}.{expr.id}"
            if lid in self.module_locks:
                return (lid, True)
            if expr.id in local_locks:
                return (None, True)
            return (None, "lock" in expr.id.lower())
        if isinstance(expr, ast.Subscript):
            s = expr.slice
            if (isinstance(s, ast.Constant) and isinstance(s.value, str)
                    and "lock" in s.value.lower()):
                return (None, True)
        if isinstance(expr, ast.Attribute) and "lock" in expr.attr.lower():
            return (None, True)
        return (None, False)

    def _analyze_body(self, info: FunctionInfo) -> None:
        ctx = info.ctx
        cls = self.classes.get(info.cls) if info.cls else None

        # local inference: ``x = ClassName(...)`` and local lock ctors
        local_types: dict = {}
        local_locks: set = set()
        for node in self._own_walk(info.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                origin = self._origin_of(ctx, node.value.func)
                name = node.targets[0].id
                if origin in _LOCK_CTORS:
                    local_locks.add(name)
                elif origin:
                    tcls = self._class_for_origin(origin, ctx)
                    if tcls:
                        local_types[name] = tcls
                elif (isinstance(node.value.func, ast.Name)
                        and f"{info.module}.{node.value.func.id}"
                        in self.classes):
                    local_types[name] = (
                        f"{info.module}.{node.value.func.id}")

        def resolve_call(call: ast.Call) -> CallSite:
            f = call.func
            site = CallSite(line=call.lineno,
                            has_args=bool(call.args or call.keywords))
            if isinstance(f, ast.Name):
                site.attr = f.id
                if f.id in info.nested:
                    site.callee = info.nested[f.id]
                    return site
                if f"{info.module}.{f.id}" in self.functions:
                    site.callee = f"{info.module}.{f.id}"
                    return site
                if f"{info.module}.{f.id}" in self.classes:
                    site.callee = self._method_lookup(
                        f"{info.module}.{f.id}", "__init__")
                    site.origin = f"{info.module}.{f.id}"
                    return site
                origin = self._origin_of(ctx, f)
                site.origin = origin
                if origin:
                    site.callee = self.resolve_dotted(origin)
                return site
            if isinstance(f, ast.Attribute):
                site.attr = f.attr
                recv = f.value
                if isinstance(recv, ast.Name) and recv.id == "self" \
                        and cls is not None:
                    site.callee = self._method_lookup(cls.qname, f.attr)
                    return site
                if (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self" and cls is not None):
                    tcls = cls.attr_types.get(recv.attr)
                    if tcls:
                        site.callee = self._method_lookup(tcls, f.attr)
                        return site
                if isinstance(recv, ast.Name) and recv.id in local_types:
                    site.callee = self._method_lookup(
                        local_types[recv.id], f.attr)
                    return site
                origin = self._origin_of(ctx, f)
                site.origin = origin
                if origin:
                    site.callee = self.resolve_dotted(origin)
                return site
            return site

        def scan(node, held: tuple, anon: int) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return              # deferred body: its own FunctionInfo
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_ids, new_anon = [], 0
                for item in node.items:
                    lid, lockish = self._lock_identity(
                        info, item.context_expr, local_locks)
                    if lid is not None:
                        new_ids.append((lid, node.lineno))
                    elif lockish:
                        new_anon += 1
                for i, (lid, line) in enumerate(new_ids):
                    info.acquires.append((lid, line))
                    for outer in held:
                        info.order_pairs.append((outer, lid, line))
                    # ``with a, b:`` acquires left-to-right — the same
                    # ordering constraint as nesting
                    for later, lline in new_ids[i + 1:]:
                        info.order_pairs.append((lid, later, lline))
                # the with-items themselves may contain calls (made
                # BEFORE the new locks are held)
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            site = resolve_call(sub)
                            site.held, site.anon_held = held, anon
                            info.calls.append(site)
                for stmt in node.body:
                    scan(stmt, held + tuple(l for l, _ in new_ids),
                         anon + new_anon)
                return
            if isinstance(node, ast.Call):
                site = resolve_call(node)
                site.held, site.anon_held = held, anon
                info.calls.append(site)
            for child in ast.iter_child_nodes(node):
                scan(child, held, anon)

        for child in ast.iter_child_nodes(info.node):
            scan(child, (), 0)

    @staticmethod
    def _own_walk(fn_node):
        """Walk one function's own body, not descending into nested
        defs/lambdas (those are separate FunctionInfo nodes)."""
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    # --------------------------------------------------------- closures --

    def acquired_closure(self, qname: str) -> dict:
        """lock id -> evidence chain (tuple of qnames ending at the
        acquiring function) for every lock ``qname`` may acquire,
        directly or through ≤ MAX_CHAIN_DEPTH call hops."""
        return self._closure(qname, (), 0)

    def _closure(self, qname: str, path: tuple, depth: int) -> dict:
        if qname in self._closure_memo:
            return self._closure_memo[qname]
        if depth > MAX_CHAIN_DEPTH or qname in path:
            return {}
        info = self.functions.get(qname)
        if info is None:
            return {}
        out: dict = {}
        for lid, _line in info.acquires:
            out.setdefault(lid, (qname,))
        for site in info.calls:
            if site.callee and site.callee in self.functions:
                sub = self._closure(site.callee, path + (qname,),
                                    depth + 1)
                for lid, chain in sub.items():
                    out.setdefault(lid, (qname,) + chain)
        if depth == 0:
            self._closure_memo[qname] = out
        return out

    def blocking_reach(self, qname: str) -> tuple | None:
        """``(chain, leaf description, line-in-first-hop)`` for the
        first blocking primitive reachable from ``qname`` (its own body
        included), or None.  ``chain`` is the qname path; the leaf names
        the primitive (``time.sleep``, ``.sendall``, a timeout-less
        ``join``...)."""
        return self._blocking(qname, (), 0)

    def _blocking(self, qname: str, path: tuple, depth: int):
        if qname in self._blocking_memo:
            return self._blocking_memo[qname]
        if depth > MAX_CHAIN_DEPTH or qname in path:
            return None
        info = self.functions.get(qname)
        if info is None:
            return None
        out = None
        for site in info.calls:
            leaf = self._blocking_leaf(site)
            if leaf:
                out = ((qname,), leaf, site.line)
                break
        if out is None:
            for site in info.calls:
                if site.callee and site.callee in self.functions:
                    sub = self._blocking(site.callee, path + (qname,),
                                         depth + 1)
                    if sub:
                        out = ((qname,) + sub[0], sub[1], site.line)
                        break
        if depth == 0:
            self._blocking_memo[qname] = out
        return out

    @staticmethod
    def _blocking_leaf(site: CallSite) -> str | None:
        if site.origin and (site.origin == "time.sleep"
                            or site.origin.endswith(".sleep")):
            return site.origin
        if site.callee is None and site.attr in BLOCKING_ATTRS:
            return f".{site.attr}"
        if site.callee is None and site.attr == "join" \
                and not site.has_args:
            return ".join (timeout-less)"
        return None
