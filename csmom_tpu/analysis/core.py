"""Single-pass, alias-aware AST lint framework (ISSUE 11 + 12).

The r3-r14 stack grew its disciplines one regex lint at a time: bare
wall-clock bans in ``tests/test_time_discipline.py`` (with a documented
alias hole: ``from time import time as _t; _t()`` passes a
``time\\.time\\(\\)`` regex), an ad-hoc AST walk for enumeration drift in
``tests/test_registry.py``, and review for everything else.  This module
is the shared machinery those checks now run on:

- **one parse per file** — ``ast.parse`` + one ``tokenize`` pass build a
  :class:`FileContext` (tree, parent links, alias map, comment/string
  tokens, pragmas); every registered rule then works off that one
  context, so adding a rule costs a visitor, not another file walk;
- **alias-aware resolution** — :meth:`FileContext.resolve` follows
  ``import time as _t``, ``from time import time as t``, simple
  ``name = time.time`` rebinds, and ``getattr(time, "time")`` dodges
  down to a canonical dotted origin (``"time.time"``), which is what
  closes the regex lint's alias holes;
- **scoped suppressions** — ``# lint: allow[<rule>] <reason>`` pragmas
  replace the count-based ``_ALLOWLIST`` dicts.  A pragma suppresses
  findings of its rule on its own line and the line directly below it
  (so a standalone pragma comment sits above the offending statement).
  A pragma that suppresses nothing is itself a finding
  (``stale-pragma``): an unused suppression is a hole the next
  regression walks through, exactly the failure mode the old stale-
  allowlist test guarded one dict against;
- **rules are registry citizens** — rules register as kind-``lint``
  engines (:mod:`csmom_tpu.registry`); registering one enrolls it in
  the ``csmom lint`` CLI, the tier-1 sweep, ``csmom registry list``,
  and the fixture self-test harness with no other file edited;
- **two scopes** (ISSUE 12) — a rule declares ``scope = "file"`` (the
  default: one file at a time off the shared parse) or
  ``scope = "project"`` (a :class:`ProjectRule`: it runs once over the
  whole scanned set with the alias-aware call graph of
  :mod:`csmom_tpu.analysis.callgraph`).  Project rules join a sweep
  when ``run_lint(project=True)`` / ``csmom lint --project`` asks for
  whole-program scope, or whenever one is named explicitly;
- **an incremental cache** (:mod:`csmom_tpu.analysis.cache`) — per-file
  results keyed by content blake2b, project results by the sorted
  digest set, so the tier-1 gate stops re-parsing ~150 unchanged files
  every run.  Suppression is replayed through the live pragma
  machinery, so a cached sweep and a fresh sweep are byte-identical.

Layering: stdlib-only (ast/tokenize/re), jax-free, clock-free — the
sweep must be runnable on CPU before a tunnel window opens, and its
verdicts must be reproducible from the tree alone.  (The CLI injects a
monotonic ``timer`` for per-rule timings; this module never reads a
clock itself.)
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

__all__ = [
    "FileContext",
    "Finding",
    "LintReport",
    "LintRule",
    "Pragma",
    "ProjectRule",
    "RunContext",
    "default_sources",
    "run_lint",
]

# the pragma grammar: the ``#`` is optional so a docstring line can carry
# its own suppression (comments cannot exist inside string literals)
PRAGMA_RE = re.compile(r"lint:\s*allow\[([A-Za-z0-9_-]+)\]\s*(.*?)\s*$")

STALE_PRAGMA_RULE = "stale-pragma"

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect at one source line (repo-relative path).

    ``chain`` is the project-rule evidence trail (the qualified-name
    call path from the reported site to the defect's leaf); empty for
    single-file findings."""

    rule: str
    path: str
    line: int
    message: str
    chain: tuple = ()

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "chain": list(self.chain)}


@dataclasses.dataclass
class Pragma:
    """One in-file suppression; ``used`` counts the findings it ate."""

    rule: str
    line: int
    reason: str
    used: int = 0
    standalone: bool = False    # a no-code line: also covers line + 1


class LintRule:
    """Base class for registered rules.

    Hooks (all optional overrides):

    - ``start_file(ctx)`` — per-file precomputation off the shared parse
      (rules needing multi-phase context — "which functions are traced"
      — do their whole analysis here; the tree is already parsed);
    - ``visit(node, ctx)`` — called once per AST node on the shared
      walk;
    - ``finish_file(ctx)`` — per-file wrap-up (token-stream checks);
    - ``start_run(run)`` / ``finish_run(run)`` — cross-file state
      (e.g. the checkpoint-vocabulary coverage check);
    - ``file_facts(ctx)`` / ``absorb_facts(rel, facts, run)`` — the
      cache contract for cross-file rules: ``file_facts`` returns the
      JSON-able per-file state the rule mined (cached alongside the
      findings), ``absorb_facts`` folds one file's facts into the run
      (called on BOTH the live and the cache-replay path, so the rule
      has one accumulation code path).

    Report through ``ctx.report(self.id, line, message)`` (pragma-aware)
    or ``run.report(...)`` for findings anchored outside the current
    file.
    """

    id: str = "?"
    description: str = ""
    scope: str = "file"         # "file" | "project"
    # project-scope only: False makes run_lint re-run the rule live on
    # every sweep instead of replaying the project cache (the
    # compile-surface registry check).  A FILE-scope rule whose
    # verdicts depend on runtime state must override cache_salt()
    # instead — per-file entries are keyed by it.
    cacheable: bool = True

    def cache_salt(self) -> str:
        """Extra material for the sweep-cache key: any runtime input
        this rule's verdicts depend on beyond the scanned sources (e.g.
        enumeration-drift's checkpoint vocabulary).  Default: none."""
        return ""

    def start_run(self, run: "RunContext") -> None:  # pragma: no cover
        pass

    def start_file(self, ctx: "FileContext") -> None:
        pass

    def visit(self, node: ast.AST, ctx: "FileContext") -> None:
        pass

    def finish_file(self, ctx: "FileContext") -> None:
        pass

    def file_facts(self, ctx: "FileContext"):
        return None

    def absorb_facts(self, rel: str, facts, run: "RunContext") -> None:
        pass

    def finish_run(self, run: "RunContext") -> None:  # pragma: no cover
        pass


class ProjectRule(LintRule):
    """A whole-program rule: one ``run_project`` pass over the scanned
    set, with the :class:`~csmom_tpu.analysis.callgraph.ProjectContext`
    (call graph, lock identities) shared across every project rule.

    ``needs_graph = False`` lets a rule that only reads the scanned
    file SET (the compile-surface registry cross-check) skip forcing a
    parse of cache-hit files."""

    scope = "project"
    needs_graph = True

    def run_project(self, project, run: "RunContext") -> None:
        raise NotImplementedError


class _Slot:
    """The pragma machinery one scanned file owns — shared by the full
    :class:`FileContext` and the parse-free cache-replay slot."""

    def __init__(self, rel: str, run: "RunContext"):
        self.rel = rel
        self.run = run
        self.pragmas: list = []
        self._pragma_by_line: dict = {}
        self.recording = False
        self.raw_log: list = []

    def _index_pragmas(self) -> None:
        for p in self.pragmas:
            # a pragma covers its own line; a STANDALONE pragma (a
            # comment/prose line carrying no code) also covers the line
            # below it.  A trailing pragma on an offending line must NOT
            # leak onto the next line — a second, unjustified defect
            # there would ship silently.
            self._pragma_by_line.setdefault((p.rule, p.line), []).append(p)
            if p.standalone:
                self._pragma_by_line.setdefault((p.rule, p.line + 1),
                                                []).append(p)

    def pragma_records(self) -> list:
        return [{"rule": p.rule, "line": p.line, "reason": p.reason,
                 "standalone": p.standalone} for p in self.pragmas]

    # -------------------------------------------------------------- report

    def report(self, rule: str, line: int, message: str,
               chain: tuple = ()) -> None:
        if self.recording:
            self.raw_log.append({"rule": rule, "line": line,
                                 "message": message,
                                 "chain": list(chain)})
        if self.run._project_log is not None:
            self.run._project_log.append(
                {"rule": rule, "rel": self.rel, "line": line,
                 "message": message, "chain": list(chain),
                 "bypass": False})
        self._apply(rule, line, message, chain)

    def _apply(self, rule: str, line: int, message: str,
               chain: tuple = ()) -> None:
        f = Finding(rule=rule, path=self.rel, line=line, message=message,
                    chain=tuple(chain))
        for p in self._pragma_by_line.get((rule, line), []):
            p.used += 1
            self.run.suppressed.append(f)
            return
        self.run.findings.append(f)

    def replay(self, raw: list) -> None:
        """Feed cached raw findings back through the LIVE suppression
        machinery (a ``bypass`` record was reported around pragmas on
        purpose — replay preserves that)."""
        for e in raw:
            if e.get("bypass"):
                self.run.findings.append(Finding(
                    rule=e["rule"], path=self.rel, line=e["line"],
                    message=e["message"], chain=tuple(e.get("chain", ()))))
            else:
                self._apply(e["rule"], e["line"], e["message"],
                            tuple(e.get("chain", ())))

    def finish(self, known_rules: set, active_rules: set) -> None:
        """Stale/unknown pragma findings — the framework's own rule.

        Unknown-ness is judged against every REGISTERED rule; staleness
        only against the rules that actually ran (a ``--rule`` filtered
        sweep cannot honestly call another rule's pragma unused)."""
        for p in self.pragmas:
            if p.rule not in known_rules:
                self.run.findings.append(Finding(
                    rule=STALE_PRAGMA_RULE, path=self.rel, line=p.line,
                    message=f"pragma names unknown rule {p.rule!r} "
                            f"(registered: {sorted(known_rules)})"))
            elif p.rule in active_rules and p.used == 0:
                self.run.findings.append(Finding(
                    rule=STALE_PRAGMA_RULE, path=self.rel, line=p.line,
                    message=f"unused suppression: no {p.rule} finding on "
                            "this line or the next — drop the pragma "
                            "(a stale allowance is the hole the next "
                            "regression walks through)"))


class CachedSlot(_Slot):
    """A cache-hit file: pragmas rebuilt from the cache record, no
    parse, no tokens — exists so suppression and stale-pragma checks
    behave identically to a fresh run."""

    tree = None

    def __init__(self, rel: str, pragma_records: list, run: "RunContext"):
        super().__init__(rel, run)
        self.pragmas = [Pragma(rule=p["rule"], line=p["line"],
                               reason=p.get("reason", ""),
                               standalone=bool(p.get("standalone")))
                        for p in pragma_records]
        self._index_pragmas()


class FileContext(_Slot):
    """Everything the rules share about one file: ONE parse, one token
    scan, one alias map — N rule visitors."""

    def __init__(self, path: str, rel: str, src: str, run: "RunContext"):
        super().__init__(rel, run)
        self.path = path
        self.src = src
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=rel)
        self.parents: dict = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.imports = self._build_alias_map(self.tree)
        self.tokens, self._code_lines = self._scan_tokens(src)
        self.pragmas = self._scan_pragmas()
        self._index_pragmas()

    # ------------------------------------------------------------ aliases --

    @staticmethod
    def _build_alias_map(tree: ast.AST) -> dict:
        """Local name -> dotted origin, from imports at ANY scope plus
        simple single-target rebinds (``t = time.time``).  Bindings are
        applied in SOURCE order (``ast.walk`` is breadth-first, which
        would let an early nested-function rebind beat a later
        module-level one), so later bindings win the way a reader
        expects; the map stays deliberately scope-blind beyond that."""
        amap: dict = {}

        def resolve(node):
            if isinstance(node, ast.Name):
                return amap.get(node.id)
            if isinstance(node, ast.Attribute):
                base = resolve(node.value)
                return f"{base}.{node.attr}" if base else None
            return None

        bindings = sorted(
            (node for node in ast.walk(tree)
             if isinstance(node, (ast.Import, ast.ImportFrom, ast.Assign))),
            key=lambda n: (n.lineno, n.col_offset))
        for node in bindings:
            if isinstance(node, ast.Import):
                for a in node.names:
                    amap[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.module:
                    for a in node.names:
                        amap[a.asname or a.name] = f"{node.module}.{a.name}"
            elif (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                origin = resolve(node.value)
                if origin is not None:
                    amap[node.targets[0].id] = origin
                else:
                    # a later rebind to something untracked retires the
                    # alias — keeping it would flag the NEW binding's
                    # calls as the old origin's
                    amap.pop(node.targets[0].id, None)
        return amap

    def resolve(self, node) -> str | None:
        """The dotted origin a name/attribute/getattr-dodge denotes, or
        None for locals the alias map does not track."""
        if isinstance(node, ast.Name):
            return self.imports.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            return f"{base}.{node.attr}" if base else None
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Name) and f.id == "getattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                base = self.resolve(node.args[0])
                return f"{base}.{node.args[1].value}" if base else None
        return None

    def resolve_call(self, call: ast.Call) -> str | None:
        """What callable a Call invokes (alias- and getattr-aware)."""
        return self.resolve(call.func)

    # ------------------------------------------------------------- tokens --

    # token types that do not make a line "code" (a pragma on a line
    # holding only these is standalone and may cover the line below)
    _NONCODE_TOKENS = frozenset({
        tokenize.COMMENT, tokenize.STRING, tokenize.NL, tokenize.NEWLINE,
        tokenize.INDENT, tokenize.DEDENT, tokenize.ENDMARKER,
    })

    @classmethod
    def _scan_tokens(cls, src: str) -> tuple:
        """One tokenize pass: ``(kind, line, text)`` for every comment
        and string token (the prose layer textual rules scan without
        re-reading the file), plus the set of line numbers that carry
        actual code tokens."""
        out = []
        code_lines: set = set()
        try:
            for tok in tokenize.generate_tokens(io.StringIO(src).readline):
                if tok.type == tokenize.COMMENT:
                    out.append(("comment", tok.start[0], tok.string))
                elif tok.type == tokenize.STRING:
                    out.append(("string", tok.start[0], tok.string))
                elif tok.type not in cls._NONCODE_TOKENS:
                    code_lines.update(range(tok.start[0], tok.end[0] + 1))
        except tokenize.TokenError:  # pragma: no cover - ast.parse passed
            pass
        return out, code_lines

    def _scan_pragmas(self) -> list:
        pragmas = []
        for i, line in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(line)
            if m:
                pragmas.append(Pragma(rule=m.group(1), line=i,
                                      reason=m.group(2),
                                      standalone=i not in self._code_lines))
        return pragmas


class RunContext:
    """Cross-file state for one sweep."""

    def __init__(self, repo: str):
        self.repo = repo
        self.findings: list = []
        self.suppressed: list = []
        self.scanned: list = []       # repo-relative paths, scan order
        self._slot = None             # the file currently being swept
        self._project_log = None      # raw project findings (cache feed)

    def report(self, rule: str, rel: str, line: int, message: str,
               chain: tuple = ()) -> None:
        self.findings.append(Finding(rule=rule, path=rel, line=line,
                                     message=message, chain=tuple(chain)))
        # pragma-bypassing reports anchored at the CURRENT file must
        # survive a cache replay too — log them raw, marked bypass
        if (self._slot is not None and self._slot.recording
                and rel == self._slot.rel):
            self._slot.raw_log.append(
                {"rule": rule, "line": line, "message": message,
                 "chain": list(chain), "bypass": True})
        elif self._project_log is not None:
            self._project_log.append(
                {"rule": rule, "rel": rel, "line": line,
                 "message": message, "chain": list(chain), "bypass": True})


@dataclasses.dataclass
class LintReport:
    """One sweep's outcome; ``findings`` are the UNSUPPRESSED defects
    (stale pragmas included — an unused allowance fails the sweep)."""

    findings: list
    suppressed: list
    files: int
    rules: tuple
    project: bool = False
    cache: dict = dataclasses.field(
        default_factory=lambda: {"enabled": False})
    rule_timings_s: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "schema_version": 2,
            "ok": self.ok,
            "files_scanned": self.files,
            "rules": list(self.rules),
            "project": self.project,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "cache": dict(self.cache),
            "rule_timings_s": {k: round(v, 6)
                               for k, v in self.rule_timings_s.items()},
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)


def default_sources(repo: str | None = None) -> list:
    """The sweep's default scope: the package, the bench harness, and
    the benchmark drivers — the same set the regex lints walked."""
    repo = repo or _REPO
    files = [os.path.join(repo, "bench.py")]
    for root in ("csmom_tpu", "benchmarks"):
        for dirpath, dirnames, names in os.walk(os.path.join(repo, root)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files += [os.path.join(dirpath, n) for n in sorted(names)
                      if n.endswith(".py")]
    return sorted(p for p in files if os.path.isfile(p))


def _expand_paths(paths) -> list:
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                out += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
        else:
            out.append(p)
    return out


def _registered_specs():
    from csmom_tpu.registry import lint_rules

    return lint_rules()


def _registered_rules():
    return [spec.rule_cls() for spec in _registered_specs()]


def run_lint(paths=None, rules=None, rule: str | None = None,
             repo: str | None = None, project: bool = False,
             cache: bool | None = None, cache_dir: str | None = None,
             timer=None) -> LintReport:
    """Run the registered rule set (or ``rules`` instances) over
    ``paths`` (default: package + bench.py + benchmarks/).

    ``rule`` filters to one rule id; unknown ids raise with the known
    set named.  ``project=True`` adds the registered project-scope
    rules (whole-program: call graph, lock order, compile-surface
    coverage); a project rule named explicitly (via ``rules`` or
    ``rule``) runs regardless of the flag.  The incremental cache is on
    by default for registered-rule sweeps (``cache=False`` bypasses;
    explicit ``rules`` instances are never cached — their state is not
    part of the key).  ``timer`` (a monotonic-seconds callable) enables
    per-rule timings; this module never reads a clock itself.
    """
    repo = repo or _REPO
    explicit_rules = rules is not None
    if rules is None:
        rules = _registered_rules()
    if rule is not None:
        known = [r.id for r in rules]
        rules = [r for r in rules if r.id == rule]
        if not rules:
            raise KeyError(f"unknown lint rule {rule!r}; registered rules: "
                           f"{known}")
    if not explicit_rules and rule is None and not project:
        rules = [r for r in rules
                 if getattr(r, "scope", "file") == "file"]
    file_rules = [r for r in rules if getattr(r, "scope", "file") == "file"]
    project_rules = [r for r in rules
                     if getattr(r, "scope", "file") == "project"]

    timings: dict = {}

    def timed(rid, fn, *a):
        if timer is None:
            return fn(*a)
        t0 = timer()
        try:
            return fn(*a)
        finally:
            timings[rid] = timings.get(rid, 0.0) + (timer() - t0)

    files = (default_sources(repo) if paths is None
             else _expand_paths(paths))
    run = RunContext(repo)
    active_rules = {r.id for r in rules}
    known_rules = (active_rules | {STALE_PRAGMA_RULE}
                   | {s.name for s in _registered_specs()})
    for r in rules:
        timed(r.id, r.start_run, run)

    sweep_cache = None
    if cache is not False and not explicit_rules:
        from csmom_tpu.analysis.cache import SweepCache

        # per-file entries are keyed by the FILE-scope rule set only
        # (project rules never produce per-file-phase findings), so a
        # plain sweep and a --project sweep share one warm cache
        # instead of thrashing it; the project key folds the project
        # rule ids in separately
        import inspect

        pkg_dir = os.path.dirname(os.path.abspath(__file__))
        plugin_sources = set()
        for r in rules:
            try:
                src_file = inspect.getsourcefile(type(r))
            except TypeError:       # pragma: no cover - builtin class
                src_file = None
            if src_file and os.path.dirname(
                    os.path.abspath(src_file)) != pkg_dir:
                plugin_sources.add(os.path.abspath(src_file))
        sweep_cache = SweepCache(
            repo, sorted(r.id for r in file_rules), cache_dir,
            salts=[f"{r.id}:{r.cache_salt()}" for r in file_rules
                   if r.cache_salt()],
            extra_sources=sorted(plugin_sources))

    # read every file once: the digest is the cache key and the source
    # feeds the parse on a miss
    from csmom_tpu.analysis.cache import content_digest

    entries = []
    for path in files:
        rel = (os.path.relpath(path, repo)
               if os.path.commonpath([os.path.abspath(path), repo]) == repo
               else path)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except (OSError, ValueError) as e:     # ValueError: bad encoding
            run.findings.append(Finding(
                rule="parse-error", path=rel, line=1,
                message=f"unparseable source: {e}"))
            continue
        entries.append((path, rel, src, content_digest(src)))

    # project cache: keyed by the sorted digest set; rules that read
    # runtime state (cacheable=False) always run live
    cached_project = None
    pkey = None
    if sweep_cache is not None and project_rules:
        pkey = sweep_cache.project_key(
            [(rel, d) for _, rel, _, d in entries],
            sorted(pr.id for pr in project_rules))
        cached_project = sweep_cache.lookup_project(pkey)
    live_project = [pr for pr in project_rules
                    if not (pr.cacheable and cached_project is not None
                            and pr.id in cached_project)]
    # a live graph-needing project rule forces a parse even of
    # cache-hit files (the call graph is built from the trees)
    need_trees = any(getattr(pr, "needs_graph", True)
                     for pr in live_project)

    slots: dict = {}
    for path, rel, src, digest in entries:
        # out-of-repo files (tmp fixtures, absolute --paths) are not
        # cached: their keys are absolute paths that would accrete in
        # the repo's cache file forever
        cache_this = sweep_cache is not None and not os.path.isabs(rel)
        hit = sweep_cache.lookup(rel, digest) if cache_this else None
        if hit is not None and not need_trees:
            slot = CachedSlot(rel, hit.get("pragmas", []), run)
        else:
            try:
                slot = FileContext(path, rel, src, run)
            except (SyntaxError, ValueError) as e:
                run.findings.append(Finding(
                    rule="parse-error", path=rel,
                    line=getattr(e, "lineno", 1) or 1,
                    message=f"unparseable source: {e}"))
                continue
        run.scanned.append(rel)
        # every sweep already read the source (the digest needs it) —
        # keep it on the slot so project rules that inspect parse-free
        # CachedSlots (compile-surface's LINT_SURFACE scan) reuse it
        # instead of re-reading the whole tree from disk warm
        slot.src = src
        slots[rel] = slot
        run._slot = slot
        if hit is not None:
            slot.replay(hit.get("raw", []))
            facts = hit.get("facts", {})
            for r in file_rules:
                if r.id in facts:
                    r.absorb_facts(rel, facts[r.id], run)
        else:
            slot.recording = True
            for r in file_rules:
                timed(r.id, r.start_file, slot)
            if timer is None:
                for node in ast.walk(slot.tree):
                    for r in file_rules:
                        r.visit(node, slot)
            else:
                # timing at phase granularity (rule-outer), not per
                # node: two clock reads per (node x rule) measurably
                # slow the path whose whole point is speed
                nodes = list(ast.walk(slot.tree))
                for r in file_rules:
                    t0 = timer()
                    for node in nodes:
                        r.visit(node, slot)
                    timings[r.id] = (timings.get(r.id, 0.0)
                                     + (timer() - t0))
            for r in file_rules:
                timed(r.id, r.finish_file, slot)
            facts = {}
            for r in file_rules:
                fact = r.file_facts(slot)
                if fact is not None:
                    facts[r.id] = fact
                    r.absorb_facts(rel, fact, run)
            slot.recording = False
            if cache_this:
                sweep_cache.store(rel, digest, slot.raw_log,
                                  slot.pragma_records(), facts)
        run._slot = None

    for r in file_rules:
        timed(r.id, r.finish_run, run)

    if project_rules:
        from csmom_tpu.analysis.callgraph import ProjectContext

        pc = ProjectContext(slots, repo)
        pc.run = run
        project_store: dict = {}
        project_ran_live = False
        for pr in project_rules:
            if (cached_project is not None and pr.cacheable
                    and pr.id in cached_project):
                for e in cached_project[pr.id]:
                    slot = slots.get(e.get("rel"))
                    if slot is not None and not e.get("bypass"):
                        slot._apply(e["rule"], e["line"], e["message"],
                                    tuple(e.get("chain", ())))
                    else:
                        run.findings.append(Finding(
                            rule=e["rule"], path=e.get("rel", "?"),
                            line=e["line"], message=e["message"],
                            chain=tuple(e.get("chain", ()))))
                project_store[pr.id] = cached_project[pr.id]
            else:
                run._project_log = []
                timed(pr.id, pr.run_project, pc, run)
                if pr.cacheable:
                    project_store[pr.id] = run._project_log
                    project_ran_live = True
                run._project_log = None
        # store only when a cacheable rule actually ran live: a fully
        # warm sweep must not rewrite sweep.json just to re-save what
        # it read (the dirty flag exists to make warm runs I/O-free)
        if (sweep_cache is not None and pkey is not None
                and project_ran_live):
            cacheable_ids = {pr.id for pr in project_rules if pr.cacheable}
            if cacheable_ids <= set(project_store):
                sweep_cache.store_project(pkey, project_store)

    for slot in slots.values():
        slot.finish(known_rules, active_rules)
    run.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if sweep_cache is not None:
        sweep_cache.save()
    return LintReport(
        findings=run.findings, suppressed=run.suppressed,
        files=len(run.scanned), rules=tuple(r.id for r in rules),
        project=bool(project_rules),
        cache=(sweep_cache.stats() if sweep_cache is not None
               else {"enabled": False}),
        rule_timings_s=timings)
