"""Single-pass, alias-aware AST lint framework (ISSUE 11).

The r3-r14 stack grew its disciplines one regex lint at a time: bare
wall-clock bans in ``tests/test_time_discipline.py`` (with a documented
alias hole: ``from time import time as _t; _t()`` passes a
``time\\.time\\(\\)`` regex), an ad-hoc AST walk for enumeration drift in
``tests/test_registry.py``, and review for everything else.  This module
is the shared machinery those checks now run on:

- **one parse per file** — ``ast.parse`` + one ``tokenize`` pass build a
  :class:`FileContext` (tree, parent links, alias map, comment/string
  tokens, pragmas); every registered rule then works off that one
  context, so adding a rule costs a visitor, not another file walk;
- **alias-aware resolution** — :meth:`FileContext.resolve` follows
  ``import time as _t``, ``from time import time as t``, simple
  ``name = time.time`` rebinds, and ``getattr(time, "time")`` dodges
  down to a canonical dotted origin (``"time.time"``), which is what
  closes the regex lint's alias holes;
- **scoped suppressions** — ``# lint: allow[<rule>] <reason>`` pragmas
  replace the count-based ``_ALLOWLIST`` dicts.  A pragma suppresses
  findings of its rule on its own line and the line directly below it
  (so a standalone pragma comment sits above the offending statement).
  A pragma that suppresses nothing is itself a finding
  (``stale-pragma``): an unused suppression is a hole the next
  regression walks through, exactly the failure mode the old stale-
  allowlist test guarded one dict against;
- **rules are registry citizens** — rules register as kind-``lint``
  engines (:mod:`csmom_tpu.registry`); registering one enrolls it in
  the ``csmom lint`` CLI, the tier-1 sweep, ``csmom registry list``,
  and the fixture self-test harness with no other file edited.

Layering: stdlib-only (ast/tokenize/re), jax-free, clock-free — the
sweep must be runnable on CPU before a tunnel window opens, and its
verdicts must be reproducible from the tree alone.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

__all__ = [
    "FileContext",
    "Finding",
    "LintReport",
    "LintRule",
    "Pragma",
    "RunContext",
    "default_sources",
    "run_lint",
]

# the pragma grammar: the ``#`` is optional so a docstring line can carry
# its own suppression (comments cannot exist inside string literals)
PRAGMA_RE = re.compile(r"lint:\s*allow\[([A-Za-z0-9_-]+)\]\s*(.*?)\s*$")

STALE_PRAGMA_RULE = "stale-pragma"

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect at one source line (repo-relative path)."""

    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message}


@dataclasses.dataclass
class Pragma:
    """One in-file suppression; ``used`` counts the findings it ate."""

    rule: str
    line: int
    reason: str
    used: int = 0


class LintRule:
    """Base class for registered rules.

    Hooks (all optional overrides):

    - ``start_file(ctx)`` — per-file precomputation off the shared parse
      (rules needing multi-phase context — "which functions are traced"
      — do their whole analysis here; the tree is already parsed);
    - ``visit(node, ctx)`` — called once per AST node on the shared
      walk;
    - ``finish_file(ctx)`` — per-file wrap-up (token-stream checks);
    - ``start_run(run)`` / ``finish_run(run)`` — cross-file state
      (e.g. the checkpoint-vocabulary coverage check).

    Report through ``ctx.report(self.id, line, message)`` (pragma-aware)
    or ``run.report(...)`` for findings anchored outside the current
    file.
    """

    id: str = "?"
    description: str = ""

    def start_run(self, run: "RunContext") -> None:  # pragma: no cover
        pass

    def start_file(self, ctx: "FileContext") -> None:
        pass

    def visit(self, node: ast.AST, ctx: "FileContext") -> None:
        pass

    def finish_file(self, ctx: "FileContext") -> None:
        pass

    def finish_run(self, run: "RunContext") -> None:  # pragma: no cover
        pass


class FileContext:
    """Everything the rules share about one file: ONE parse, one token
    scan, one alias map — N rule visitors."""

    def __init__(self, path: str, rel: str, src: str, run: "RunContext"):
        self.path = path
        self.rel = rel
        self.src = src
        self.lines = src.splitlines()
        self.run = run
        self.tree = ast.parse(src, filename=rel)
        self.parents: dict = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.imports = self._build_alias_map(self.tree)
        self.tokens, self._code_lines = self._scan_tokens(src)
        self.pragmas = self._scan_pragmas()
        self._pragma_by_line: dict = {}
        for p in self.pragmas:
            # a pragma covers its own line; a STANDALONE pragma (a
            # comment/prose line carrying no code) also covers the line
            # below it.  A trailing pragma on an offending line must NOT
            # leak onto the next line — a second, unjustified defect
            # there would ship silently.
            self._pragma_by_line.setdefault((p.rule, p.line), []).append(p)
            if p.line not in self._code_lines:
                self._pragma_by_line.setdefault((p.rule, p.line + 1),
                                                []).append(p)

    # ------------------------------------------------------------ aliases --

    @staticmethod
    def _build_alias_map(tree: ast.AST) -> dict:
        """Local name -> dotted origin, from imports at ANY scope plus
        simple single-target rebinds (``t = time.time``).  Bindings are
        applied in SOURCE order (``ast.walk`` is breadth-first, which
        would let an early nested-function rebind beat a later
        module-level one), so later bindings win the way a reader
        expects; the map stays deliberately scope-blind beyond that."""
        amap: dict = {}

        def resolve(node):
            if isinstance(node, ast.Name):
                return amap.get(node.id)
            if isinstance(node, ast.Attribute):
                base = resolve(node.value)
                return f"{base}.{node.attr}" if base else None
            return None

        bindings = sorted(
            (node for node in ast.walk(tree)
             if isinstance(node, (ast.Import, ast.ImportFrom, ast.Assign))),
            key=lambda n: (n.lineno, n.col_offset))
        for node in bindings:
            if isinstance(node, ast.Import):
                for a in node.names:
                    amap[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                if node.module:
                    for a in node.names:
                        amap[a.asname or a.name] = f"{node.module}.{a.name}"
            elif (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                origin = resolve(node.value)
                if origin is not None:
                    amap[node.targets[0].id] = origin
                else:
                    # a later rebind to something untracked retires the
                    # alias — keeping it would flag the NEW binding's
                    # calls as the old origin's
                    amap.pop(node.targets[0].id, None)
        return amap

    def resolve(self, node) -> str | None:
        """The dotted origin a name/attribute/getattr-dodge denotes, or
        None for locals the alias map does not track."""
        if isinstance(node, ast.Name):
            return self.imports.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            return f"{base}.{node.attr}" if base else None
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Name) and f.id == "getattr"
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                base = self.resolve(node.args[0])
                return f"{base}.{node.args[1].value}" if base else None
        return None

    def resolve_call(self, call: ast.Call) -> str | None:
        """What callable a Call invokes (alias- and getattr-aware)."""
        return self.resolve(call.func)

    # ------------------------------------------------------------- tokens --

    # token types that do not make a line "code" (a pragma on a line
    # holding only these is standalone and may cover the line below)
    _NONCODE_TOKENS = frozenset({
        tokenize.COMMENT, tokenize.STRING, tokenize.NL, tokenize.NEWLINE,
        tokenize.INDENT, tokenize.DEDENT, tokenize.ENDMARKER,
    })

    @classmethod
    def _scan_tokens(cls, src: str) -> tuple:
        """One tokenize pass: ``(kind, line, text)`` for every comment
        and string token (the prose layer textual rules scan without
        re-reading the file), plus the set of line numbers that carry
        actual code tokens."""
        out = []
        code_lines: set = set()
        try:
            for tok in tokenize.generate_tokens(io.StringIO(src).readline):
                if tok.type == tokenize.COMMENT:
                    out.append(("comment", tok.start[0], tok.string))
                elif tok.type == tokenize.STRING:
                    out.append(("string", tok.start[0], tok.string))
                elif tok.type not in cls._NONCODE_TOKENS:
                    code_lines.update(range(tok.start[0], tok.end[0] + 1))
        except tokenize.TokenError:  # pragma: no cover - ast.parse passed
            pass
        return out, code_lines

    def _scan_pragmas(self) -> list:
        pragmas = []
        for i, line in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(line)
            if m:
                pragmas.append(Pragma(rule=m.group(1), line=i,
                                      reason=m.group(2)))
        return pragmas

    # -------------------------------------------------------------- report --

    def report(self, rule: str, line: int, message: str) -> None:
        f = Finding(rule=rule, path=self.rel, line=line, message=message)
        for p in self._pragma_by_line.get((rule, line), []):
            p.used += 1
            self.run.suppressed.append(f)
            return
        self.run.findings.append(f)

    def finish(self, known_rules: set, active_rules: set) -> None:
        """Stale/unknown pragma findings — the framework's own rule.

        Unknown-ness is judged against every REGISTERED rule; staleness
        only against the rules that actually ran (a ``--rule`` filtered
        sweep cannot honestly call another rule's pragma unused)."""
        for p in self.pragmas:
            if p.rule not in known_rules:
                self.run.findings.append(Finding(
                    rule=STALE_PRAGMA_RULE, path=self.rel, line=p.line,
                    message=f"pragma names unknown rule {p.rule!r} "
                            f"(registered: {sorted(known_rules)})"))
            elif p.rule in active_rules and p.used == 0:
                self.run.findings.append(Finding(
                    rule=STALE_PRAGMA_RULE, path=self.rel, line=p.line,
                    message=f"unused suppression: no {p.rule} finding on "
                            "this line or the next — drop the pragma "
                            "(a stale allowance is the hole the next "
                            "regression walks through)"))


class RunContext:
    """Cross-file state for one sweep."""

    def __init__(self, repo: str):
        self.repo = repo
        self.findings: list = []
        self.suppressed: list = []
        self.scanned: list = []       # repo-relative paths, scan order

    def report(self, rule: str, rel: str, line: int, message: str) -> None:
        self.findings.append(Finding(rule=rule, path=rel, line=line,
                                     message=message))


@dataclasses.dataclass
class LintReport:
    """One sweep's outcome; ``findings`` are the UNSUPPRESSED defects
    (stale pragmas included — an unused allowance fails the sweep)."""

    findings: list
    suppressed: list
    files: int
    rules: tuple

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "schema_version": 1,
            "ok": self.ok,
            "files_scanned": self.files,
            "rules": list(self.rules),
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)


def default_sources(repo: str | None = None) -> list:
    """The sweep's default scope: the package, the bench harness, and
    the benchmark drivers — the same set the regex lints walked."""
    repo = repo or _REPO
    files = [os.path.join(repo, "bench.py")]
    for root in ("csmom_tpu", "benchmarks"):
        for dirpath, dirnames, names in os.walk(os.path.join(repo, root)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files += [os.path.join(dirpath, n) for n in sorted(names)
                      if n.endswith(".py")]
    return sorted(p for p in files if os.path.isfile(p))


def _expand_paths(paths) -> list:
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                out += [os.path.join(dirpath, n) for n in sorted(names)
                        if n.endswith(".py")]
        else:
            out.append(p)
    return out


def _registered_specs():
    from csmom_tpu.registry import lint_rules

    return lint_rules()


def _registered_rules():
    return [spec.rule_cls() for spec in _registered_specs()]


def run_lint(paths=None, rules=None, rule: str | None = None,
             repo: str | None = None) -> LintReport:
    """Run the registered rule set (or ``rules`` instances) over
    ``paths`` (default: package + bench.py + benchmarks/).

    ``rule`` filters to one rule id; unknown ids raise with the known
    set named.  Every file is parsed exactly once; rule visitors share
    the parse (see the module docstring).
    """
    repo = repo or _REPO
    if rules is None:
        rules = _registered_rules()
    if rule is not None:
        known = [r.id for r in rules]
        rules = [r for r in rules if r.id == rule]
        if not rules:
            raise KeyError(f"unknown lint rule {rule!r}; registered rules: "
                           f"{known}")
    files = (default_sources(repo) if paths is None
             else _expand_paths(paths))
    run = RunContext(repo)
    active_rules = {r.id for r in rules}
    known_rules = (active_rules | {STALE_PRAGMA_RULE}
                   | {s.name for s in _registered_specs()})
    for r in rules:
        r.start_run(run)
    for path in files:
        rel = (os.path.relpath(path, repo)
               if os.path.commonpath([os.path.abspath(path), repo]) == repo
               else path)
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            ctx = FileContext(path, rel, src, run)
        except (OSError, SyntaxError, ValueError) as e:
            run.findings.append(Finding(
                rule="parse-error", path=rel, line=getattr(e, "lineno", 1)
                or 1, message=f"unparseable source: {e}"))
            continue
        run.scanned.append(rel)
        for r in rules:
            r.start_file(ctx)
        for node in ast.walk(ctx.tree):
            for r in rules:
                r.visit(node, ctx)
        for r in rules:
            r.finish_file(ctx)
        ctx.finish(known_rules, active_rules)
    for r in rules:
        r.finish_run(run)
    run.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintReport(findings=run.findings, suppressed=run.suppressed,
                      files=len(run.scanned),
                      rules=tuple(r.id for r in rules))
