"""The whole-program rule set (ISSUE 12) — three project-scope rules on
top of the :mod:`csmom_tpu.analysis.callgraph` layer, registered as
kind-``lint`` engines exactly like the per-file set (one registration
buys the CLI, the tier-1 sweep, ``csmom registry list``, the pragma
contract, and the fixture self-test):

- **lock-order** — held-lock sets propagate interprocedurally over the
  call graph.  Two findings: a CYCLE in the global lock acquisition-
  order graph (lock A held while a chain acquires B, elsewhere B held
  while a chain acquires A — the classic two-thread deadlock, invisible
  to any single file), and a BLOCKING call (sleep / socket send/recv /
  engine dispatch / timeout-less joins) reached under a held lock
  through one or more call hops — the r16 per-file rule only sees the
  leaf function, so "hide it in a helper" passed before this rule.
  Re-acquiring a non-reentrant lock through a call chain is the
  degenerate one-lock cycle and is reported as such.
- **helper-hygiene** — the interprocedural twin of tracer-hygiene +
  donation-safety: a helper that prints, reads a clock, materializes on
  host (``np.asarray``/``float()``), writes a global, or invokes a
  donated-buffer entry is flagged at every jit / shard_map /
  ServeSurface ``batch_fn`` call site that can reach it within
  :data:`~csmom_tpu.analysis.callgraph.MAX_CHAIN_DEPTH` hops.  Taints
  lexically inside the traced function itself are the per-file rule's
  findings and are NOT re-reported here.
- **compile-surface** — the zero-in-window-compiles property as a
  static cross-check instead of a measured ledger row: every
  dispatchable (endpoint, bucket) shape the serving tier admits
  (``registry.serve_endpoints()`` x ``serve/buckets.py`` grid, the
  same arithmetic ``health.expected_entry_names`` uses) must be
  declared warm by some registered manifest feeder's jax-free
  ``manifest_names_fn``.  A dispatchable pair no feeder covers is the
  ONLY way a fresh in-window compile can exist by construction — so it
  is a lint finding, not a tunnel-window surprise.  The rule reads
  live registry state, so it is ``cacheable = False`` (and
  ``needs_graph = False`` — it never touches the call graph, so it
  costs no parse).  Scanning a toy tree (the fixture packages), it
  cross-checks ``LINT_SURFACE`` literal declarations instead of the
  live registry — same arithmetic, statically evaluated.

Stdlib-only, jax-free, clock-free, like everything in ``analysis/``.
"""

from __future__ import annotations

import ast

from csmom_tpu.analysis.callgraph import MAX_CHAIN_DEPTH, ProjectContext
from csmom_tpu.analysis.core import ProjectRule, RunContext

__all__ = [
    "CompileSurface",
    "HelperHygiene",
    "LockOrder",
    "register_project_rules",
]


def _chain_text(chain) -> str:
    return " -> ".join(chain)


# --------------------------------------------------------------------------
# lock-order
# --------------------------------------------------------------------------

class LockOrder(ProjectRule):
    """Global lock acquisition-order cycles and blocking calls hidden
    behind helpers — the two deadlock shapes no per-file rule can see."""

    id = "lock-order"
    description = ("whole-program lock discipline: the global lock "
                   "acquisition-order graph (held-lock sets propagated "
                   "over the call graph) must be acyclic, and no blocking "
                   "call (sleep/socket/dispatch/timeout-less join) may be "
                   "reachable under a held lock through any call chain")

    def run_project(self, project: ProjectContext, run: RunContext) -> None:
        project.build()
        # edge (A, B) -> evidence: (rel, line, description)
        edges: dict = {}

        def add_edge(a, b, rel, line, desc):
            if a != b:
                edges.setdefault((a, b), (rel, line, desc))

        for info in project.functions.values():
            for outer, inner, line in info.order_pairs:
                if outer == inner:
                    # a lexically nested re-acquisition (add_edge drops
                    # self-edges; the chain-based check below only sees
                    # interprocedural ones)
                    # "rlock" is reentrant; "condition" means unknown
                    # backing (an unresolvable Condition arg) — stay
                    # quiet rather than call legal code a deadlock
                    if project.lock_kinds.get(outer, "lock") not in (
                            "rlock", "condition"):
                        project.report(
                            self.id, info.rel, line,
                            f"{outer} is re-acquired inside its own "
                            f"with-block in {info.qname} — a "
                            "non-reentrant lock self-deadlocks here")
                    continue
                add_edge(outer, inner, info.rel, line,
                         f"{info.qname} acquires {inner} while "
                         f"holding {outer}")
            for site in info.calls:
                if not site.held and not site.anon_held:
                    continue
                # blocking work behind >= 1 call hop (the leaf case is
                # the per-file lock-discipline rule's finding).  An
                # ANONYMOUS lock (locally created, e.g. the router's
                # per-request state dict lock) has no order-graph node,
                # but blocking under it serializes its waiters all the
                # same
                if site.callee and site.callee in project.functions:
                    held_desc = (site.held[-1] if site.held
                                 else "a locally-scoped lock")
                    reach = project.blocking_reach(site.callee)
                    if reach is not None:
                        chain, leaf, _ = reach
                        full = (info.qname,) + chain
                        project.report(
                            self.id, info.rel, site.line,
                            f"blocking call ({leaf}) reached while "
                            f"holding {held_desc} via "
                            f"{_chain_text(full)} — every thread "
                            "contending this lock serializes behind the "
                            "hidden wait; move the blocking work outside "
                            "the critical section", chain=full)
                if not site.held:
                    continue
                if site.callee and site.callee in project.functions:
                    for lock, chain in project.acquired_closure(
                            site.callee).items():
                        full = (info.qname,) + chain
                        for held in site.held:
                            if held == lock:
                                kind = project.lock_kinds.get(lock, "lock")
                                if kind not in ("rlock", "condition"):
                                    project.report(
                                        self.id, info.rel, site.line,
                                        f"{lock} is re-acquired through "
                                        f"{_chain_text(full)} while "
                                        "already held — a non-reentrant "
                                        "lock self-deadlocks here",
                                        chain=full)
                            else:
                                add_edge(held, lock, info.rel, site.line,
                                         f"{_chain_text(full)} acquires "
                                         f"{lock} while {info.qname} "
                                         f"holds {held}")

        self._report_cycles(project, edges)

    def _report_cycles(self, project: ProjectContext, edges: dict) -> None:
        graph: dict = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            members = sorted(scc)
            evidence = sorted(
                ((a, b), ev) for (a, b), ev in edges.items()
                if a in scc and b in scc)
            (rel, line, _desc) = evidence[0][1]
            lines = "; ".join(ev[2] for _, ev in evidence[:4])
            project.report(
                self.id, rel, line,
                f"lock acquisition-order cycle between "
                f"{{{', '.join(members)}}}: {lines} — two threads "
                "taking these locks in opposite orders deadlock; pick "
                "ONE global order and restructure the off-order "
                "acquisition")


def _sccs(graph: dict):
    """Tarjan strongly-connected components (iterative)."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    out: list = []
    counter = [0]

    for root in graph:
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                out.append(scc)
    return out


# --------------------------------------------------------------------------
# helper-hygiene
# --------------------------------------------------------------------------

_JIT_SUFFIXES = ("jit", "pjit", "shard_map")
_HOST_MATERIALIZE = {"numpy.asarray", "numpy.array",
                     "numpy.ascontiguousarray"}


class HelperHygiene(ProjectRule):
    """Tracer/donation escapes hidden behind helpers: flagged at every
    traced call site that can reach them (bounded depth)."""

    id = "helper-hygiene"
    description = ("interprocedural tracer-hygiene + donation-safety: a "
                   "helper that prints, reads a clock, materializes on "
                   "host, writes a global, or invokes a donated-buffer "
                   "entry is flagged at every jit/shard_map/ServeSurface "
                   "batch_fn call site that can reach it (bounded depth, "
                   "alias map reused)")

    def run_project(self, project: ProjectContext, run: RunContext) -> None:
        project.build()
        self._taint_memo: dict = {}
        roots = self._traced_roots(project)
        reported: set = set()
        for root in roots:
            self._sweep_root(project, root, reported)

    # ---------------------------------------------------------- roots --

    def _traced_roots(self, project: ProjectContext) -> list:
        roots: set = set()
        for info in project.functions.values():
            node = info.node
            for dec in getattr(node, "decorator_list", ()):
                if self._is_jit_expr(project, info, dec):
                    roots.add(info.qname)
            for site in info.calls:
                origin = site.origin or ""
                is_jit = (origin.endswith(_JIT_SUFFIXES)
                          or (site.callee is None
                              and site.attr in ("jit", "pjit",
                                                "shard_map")))
                if is_jit:
                    # jit(f) / shard_map(f, ...): resolve the first arg
                    for sub in ProjectContext._own_walk(info.node):
                        if (isinstance(sub, ast.Call)
                                and sub.lineno == site.line and sub.args
                                and isinstance(sub.args[0], ast.Name)):
                            q = (info.nested.get(sub.args[0].id)
                                 or project.resolve_dotted(
                                     f"{info.module}.{sub.args[0].id}"))
                            if q:
                                roots.add(q)
        # registry-registered ServeSurface factories: their nested defs
        # are what the serve engine vmaps/jits
        for q in project.serve_batch_factories:
            factory = project.functions.get(q)
            if factory is not None:
                roots.update(factory.nested.values())
                roots.add(q)
        return sorted(roots)

    def _is_jit_expr(self, project, info, dec) -> bool:
        origin = project._origin_of(info.ctx, dec)
        if origin and origin.endswith(_JIT_SUFFIXES):
            return True
        if isinstance(dec, ast.Call):
            o = project._origin_of(info.ctx, dec.func)
            if o and o.endswith(_JIT_SUFFIXES):
                return True
            if o and o.endswith("partial"):
                # only ``@partial(jax.jit, ...)``-shaped partials trace;
                # a partial over anything else is an ordinary decorator
                if not dec.args:
                    return False
                inner = project._origin_of(info.ctx, dec.args[0])
                if inner and inner.endswith(_JIT_SUFFIXES):
                    return True
                name = (dec.args[0].attr
                        if isinstance(dec.args[0], ast.Attribute)
                        else getattr(dec.args[0], "id", None))
                return name in ("jit", "pjit", "shard_map")
            name = (dec.func.attr if isinstance(dec.func, ast.Attribute)
                    else getattr(dec.func, "id", None))
            return name in ("jit", "pjit", "shard_map")
        name = (dec.attr if isinstance(dec, ast.Attribute)
                else getattr(dec, "id", None))
        return name in ("jit", "pjit", "shard_map")

    # ---------------------------------------------------------- taints --

    def _direct_taints(self, project: ProjectContext, qname: str) -> list:
        if qname in self._taint_memo:
            return self._taint_memo[qname]
        info = project.functions.get(qname)
        out: list = []
        if info is None:
            self._taint_memo[qname] = out
            return out
        globals_declared: set = set()
        for sub in ProjectContext._own_walk(info.node):
            if isinstance(sub, ast.Global):
                globals_declared |= set(sub.names)
        for sub in ProjectContext._own_walk(info.node):
            if isinstance(sub, (ast.Assign, ast.AugAssign)):
                tgts = (sub.targets if isinstance(sub, ast.Assign)
                        else [sub.target])
                for t in tgts:
                    if isinstance(t, ast.Name) and t.id in globals_declared:
                        out.append(("global write", sub.lineno, t.id))
        for site in info.calls:
            origin = site.origin or ""
            if site.callee is None and site.attr == "print":
                out.append(("print (host I/O)", site.line, "print"))
            elif origin.startswith("time.") or origin.endswith(
                    ".mono_now_s"):
                out.append(("clock read", site.line, origin))
            elif origin in _HOST_MATERIALIZE:
                out.append(("host materialization", site.line, origin))
            if site.attr and "donated" in site.attr:
                out.append(("donated-buffer entry call", site.line,
                            site.attr))
        self._taint_memo[qname] = out
        return out

    # ----------------------------------------------------------- sweep --

    def _sweep_root(self, project: ProjectContext, root: str,
                    reported: set) -> None:
        info = project.functions.get(root)
        if info is None:
            return
        # BFS over project call edges; depth >= 1 only (depth-0 taints
        # are lexically inside the traced function: the per-file
        # tracer-hygiene rule's findings, not re-reported here)
        seen = {root}
        frontier = [(root, (root,), None)]
        for _depth in range(MAX_CHAIN_DEPTH):
            nxt = []
            for qname, chain, first_site in frontier:
                fi = project.functions.get(qname)
                if fi is None:
                    continue
                for site in fi.calls:
                    callee = site.callee
                    if not callee or callee not in project.functions \
                            or callee in seen:
                        continue
                    seen.add(callee)
                    entry_site = first_site or (fi.rel, site.line)
                    taints = self._direct_taints(project, callee)
                    for kind, tline, detail in taints:
                        key = (root, callee, kind, detail)
                        if key in reported:
                            continue
                        reported.add(key)
                        full = chain + (callee,)
                        rel, line = entry_site
                        project.report(
                            self.id, rel, line,
                            f"traced function {root} reaches "
                            f"{kind} ({detail}) in {callee} "
                            f"(line {tline}) via {_chain_text(full)} — "
                            "a helper does not launder a host sync: "
                            "this runs (or burns a constant) inside the "
                            "traced body at every dispatch",
                            chain=full)
                    nxt.append((callee, chain + (callee,), entry_site))
            frontier = nxt
            if not frontier:
                break


# --------------------------------------------------------------------------
# compile-surface
# --------------------------------------------------------------------------

class CompileSurface(ProjectRule):
    """Every dispatchable (endpoint, bucket) shape has a warmed manifest
    entry — statically, before any window opens."""

    id = "compile-surface"
    description = ("zero in-window compiles as a static fact: registry "
                   "serve endpoints x serve/buckets.py grid must be "
                   "covered by a registered manifest feeder's jax-free "
                   "manifest_names_fn for every bucket profile — a "
                   "dispatchable shape with no warmed entry is the only "
                   "way a fresh in-window compile can exist")
    cacheable = False       # reads live registry state
    needs_graph = False     # never touches the call graph

    def run_project(self, project: ProjectContext, run: RunContext) -> None:
        toy = self._toy_surfaces(project)
        if toy is not None:
            self._check_toy(project, toy)
            return
        rels = project.scanned_rels()
        if ("csmom_tpu/registry/core.py" not in rels
                or "csmom_tpu/serve/buckets.py" not in rels):
            return      # a partial sweep cannot honestly judge coverage
        self._check_live(project)

    # ------------------------------------------------------------ live --

    def _check_live(self, project: ProjectContext) -> None:
        from csmom_tpu.registry import ensure_builtin
        from csmom_tpu.serve import buckets

        reg = ensure_builtin()
        anchor_rel = "csmom_tpu/serve/buckets.py"
        anchor_line = self._profiles_line(project, anchor_rel)
        for profile in sorted(buckets.PROFILES):
            expected = self._expected_names(profile)
            declared = reg.manifest_entry_names(profile)
            feeders = sum(1 for spec in reg.specs()
                          if profile in spec.profiles
                          and spec.manifest_names_fn)
            if feeders == 0:
                project.report(
                    self.id, anchor_rel, anchor_line,
                    f"bucket profile {profile!r} has no registered "
                    "manifest feeder declaring warm coverage "
                    "(manifest_names_fn) — every serve dispatch on this "
                    "profile would compile in-window; register the "
                    "feeder (registry/builtin.py serve.buckets) or drop "
                    "the profile")
                continue
            missing = sorted(expected - declared)
            if missing:
                project.report(
                    self.id, anchor_rel, anchor_line,
                    f"{len(missing)} of {len(expected)} dispatchable "
                    f"(endpoint, bucket) shapes on profile {profile!r} "
                    "have no warmed manifest entry (first missing: "
                    f"{missing[0]}) — a dispatch at that shape is a "
                    "fresh in-window compile by construction; cover it "
                    "in the profile's manifest feeder or shrink the "
                    "bucket grid")

    @staticmethod
    def _expected_names(profile: str) -> set:
        """The dispatchable world, derived from bucket geometry +
        registry endpoints (the same arithmetic as
        ``health.expected_entry_names``, which tests pin against this)."""
        from csmom_tpu.serve.health import expected_entry_names

        return expected_entry_names(profile)

    @staticmethod
    def _slot_tree(project: ProjectContext, rel: str,
                   marker: str | None = None):
        """The slot's AST — rebuilt from the slot's retained source (or
        disk, for out-of-sweep paths) when the slot is parse-free (a
        warm-cache CachedSlot), so this rule's verdicts and anchors are
        identical cold and warm.  ``marker`` gates the parse on a cheap
        substring check first (a warm full-tree sweep must not re-parse
        150 files to learn none declares a toy surface)."""
        import os

        ctx = project.contexts.get(rel)
        tree = getattr(ctx, "tree", None)
        if tree is not None:
            return tree
        src = getattr(ctx, "src", None)
        if src is None:
            path = rel if os.path.isabs(rel) else os.path.join(
                project.repo, rel)
            try:
                with open(path, encoding="utf-8") as f:
                    src = f.read()
            except (OSError, ValueError):
                return None
        if marker is not None and marker not in src:
            return None
        try:
            return ast.parse(src)
        except (SyntaxError, ValueError):
            return None

    def _profiles_line(self, project: ProjectContext, rel: str) -> int:
        """The PROFILES assignment line in serve/buckets.py — the
        finding anchor (and therefore any pragma match), cache-blind."""
        tree = self._slot_tree(project, rel)
        if tree is None:
            return 1
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "PROFILES"
                    for t in node.targets):
                return node.lineno
        return 1

    # ------------------------------------------------------------- toy --

    def _toy_surfaces(self, project: ProjectContext):
        """Merged ``LINT_SURFACE`` literal declarations across the
        scanned files (the fixture form of the registry/bucket/manifest
        world), or None when the scan declares none."""
        merged: dict = {}
        anchor = None
        for rel in sorted(project.contexts):
            tree = self._slot_tree(project, rel, marker="LINT_SURFACE")
            if tree is None:
                continue
            for node in tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == "LINT_SURFACE"):
                    continue
                try:
                    val = ast.literal_eval(node.value)
                except ValueError:
                    continue
                if not isinstance(val, dict):
                    continue
                for k, v in val.items():
                    if k == "warmed":
                        merged.setdefault("warmed", set()).update(v)
                    else:
                        merged[k] = v
                if "endpoints" in val and anchor is None:
                    anchor = (rel, node.lineno)
        if not merged:
            return None
        merged["_anchor"] = anchor or (next(iter(sorted(
            project.contexts))), 1)
        return merged

    def _check_toy(self, project: ProjectContext, toy: dict) -> None:
        rel, line = toy["_anchor"]
        needed = ("endpoints", "months", "asset_buckets", "batch_buckets")
        absent = [k for k in needed if k not in toy]
        if absent:
            project.report(
                self.id, rel, line,
                f"LINT_SURFACE declarations are incomplete: missing "
                f"{absent} — the toy surface must declare the full "
                "(endpoints x buckets) world to be checkable")
            return
        warmed = toy.get("warmed", set())
        M = toy["months"]
        missing = sorted(
            f"serve.{kind}.b{B}@{A}x{M}"
            for kind in toy["endpoints"]
            for B in toy["batch_buckets"] for A in toy["asset_buckets"]
            if f"serve.{kind}.b{B}@{A}x{M}" not in warmed)
        if missing:
            project.report(
                self.id, rel, line,
                f"{len(missing)} dispatchable (endpoint, bucket) "
                "shape(s) have no warmed manifest entry (first missing: "
                f"{missing[0]}) — a fresh in-window compile by "
                "construction")


# --------------------------------------------------------------------------
# registration
# --------------------------------------------------------------------------

PROJECT_RULES = (LockOrder, HelperHygiene, CompileSurface)


def register_project_rules() -> None:
    """Register the project-scope rule set as kind-``lint`` engines
    (import-idempotent, same path as the per-file builtins)."""
    from csmom_tpu.registry import REGISTRY, EngineSpec

    for cls in PROJECT_RULES:
        REGISTRY.register(
            EngineSpec(name=cls.id, kind="lint",
                       description=cls.description, rule_cls=cls),
            replace=True)


register_project_rules()
