"""The builtin lint rule set (ISSUE 11) — registered as kind-``lint``
engines, so one registration buys the ``csmom lint`` CLI, the tier-1
sweep, ``csmom registry list``, and the fixture self-test harness.

Six rules, each mechanizing a discipline an earlier round enforced by
regex or review:

- **clock-discipline** — the r3/r7 time-discipline lints ported to AST
  with the alias holes closed (``from time import time as _t; _t()``,
  ``import time as tt; tt.time()``, ``getattr(time, "time")()``, and
  local rebinds all resolve to the same origin), keeping the per-layer
  tiers: serve timing is ``mono_now_s``-only, the stream data plane
  reads NO clock at all (event time only), the ledger is wall-free, and
  everything else routes legitimate wall needs through
  ``utils.deadline``.  Prose mentions of the wall-clock idiom (comments
  / docstrings) must carry a pragma — the old count-based allowlist's
  two entries became in-file suppressions.
- **tracer-hygiene** — inside any function handed to ``jax.jit`` /
  ``shard_map`` (decorator, direct call, or a registry ``batch_fn``
  factory's inner function), flag host-sync escapes: ``print``, clock
  reads, ``float()`` / ``.item()`` / ``np.asarray`` on traced
  parameters, and mutable-global writes.  A host sync inside a traced
  function is a silent per-dispatch device round trip — the
  tail-latency-by-variability failure mode a TPU window cannot afford
  to discover live.
- **lock-discipline** — ``threading`` locks acquired outside
  ``with`` / try-finally, and blocking calls (socket send/recv,
  ``sleep``, engine dispatch) made while a lock is held.  The r13
  exactly-once terminal transitions serialize on these locks; one
  blocking call under one of them serializes the whole continuous
  batcher.
- **donation-safety** — a buffer passed at a donated position
  (``donate_argnums`` / a ``*donated*`` entry) must not be read later
  in the same scope: donation hands XLA the HBM block, and a
  read-after-donate is garbage on device even though it "works" on CPU
  (where donation is ignored).
- **enumeration-drift** — the r14 registry lint migrated in (no
  module-level ENDPOINTS/…_ENTRIES/WORKLOADS/…_STRATEGIES enumerations
  outside ``csmom_tpu/registry/``) plus checkpoint-name coverage: every
  literal ``checkpoint("x")`` call site must appear in
  ``chaos.plan.KNOWN_POINTS`` and every vocabulary entry must still
  have a call site — the prose inventory in ``chaos/inject.py`` drifted
  twice before the vocabulary became code.
- **dial-discipline** — the r19 persistent-transport contract: the
  one-shot ``proto.request_once`` (connect per call) is for probes and
  one-shot admin/lifecycle ops ONLY; a dial-per-call site on a request
  hot path (router/fabric dispatch) reintroduces exactly the
  connection-per-request tail the pooled channels erased
  (``trace_stage_transport_p99_ms`` 742 → 304 ms, p50 295 → 16 ms,
  in the r19 capture).  Probe/stats/lifecycle functions and the
  supervisor/health admin modules are allowlisted.

Stdlib-only, jax-free (the sweep gates ``csmom rehearse`` on CPU).
Rule messages spell pragma examples with ``{`` placeholders so this
module's own source never parses as a pragma.
"""

from __future__ import annotations

import ast
import os
import re

from csmom_tpu.analysis.core import FileContext, LintRule, RunContext

__all__ = [
    "ClockDiscipline",
    "DialDiscipline",
    "DonationSafety",
    "EnumerationDrift",
    "LockDiscipline",
    "TracerHygiene",
    "banned_enumeration_name",
    "register_builtin_rules",
]


def _posix(rel: str) -> str:
    return rel.replace(os.sep, "/")


# --------------------------------------------------------------------------
# clock-discipline
# --------------------------------------------------------------------------

class ClockDiscipline(LintRule):
    """Per-layer clock tiers, alias-aware (the regex lints' successor)."""

    id = "clock-discipline"
    description = ("wall-clock reads route through utils.deadline; serve "
                   "timing is mono_now_s-only; the stream data plane reads "
                   "no clock at all; the ledger is wall-free (alias-aware: "
                   "closes the from-import/module-alias/getattr holes the "
                   "old regex lint had)")

    # prose layer: the wall-clock CALL idiom quoted in comments/docstrings
    # must justify itself with a pragma (the old _ALLOWLIST sites)
    MENTION_RE = re.compile(
        r"time\.time\(\)|datetime(?:\.datetime)?\.now\(\s*\)")

    # serve/replay timing: every clock read goes through mono_now_s so the
    # clock the queue expires on is the clock the artifact measures on
    MONO_ONLY_FILES = (
        "csmom_tpu/serve/__init__.py",
        "csmom_tpu/serve/buckets.py",
        "csmom_tpu/serve/queue.py",
        "csmom_tpu/serve/batcher.py",
        "csmom_tpu/serve/engine.py",
        "csmom_tpu/serve/service.py",
        "csmom_tpu/serve/loadgen.py",
        "csmom_tpu/serve/proto.py",
        "csmom_tpu/serve/health.py",
        "csmom_tpu/serve/worker.py",
        "csmom_tpu/serve/router.py",
        "csmom_tpu/serve/supervisor.py",
        "csmom_tpu/serve/slo.py",
        "csmom_tpu/serve/cache.py",
        "csmom_tpu/cli/serve.py",
        "csmom_tpu/stream/replay.py",
        "csmom_tpu/cli/replay.py",
        # the request-tracing tier (ISSUE 13): the stage clocks must be
        # the SAME clock the queue expires on and the artifact measures
        # on, or the decomposition could not be subtracted from the p99
        "csmom_tpu/obs/trace.py",
        # the horizontal fabric (ISSUE 14): the routes view, the router
        # supervisor, and the client tier time deadlines/failover on
        # the same clock the replicas and workers expire on — and the
        # transport's receive deadlines (proto.py, already pinned)
        # depend on it end to end
        "csmom_tpu/serve/fabric.py",
        # the fleet observatory (ISSUE 19): series timestamps, demand
        # buckets, and the kill-window capacity account all live on the
        # one monotonic timeline the supervisors stamp lifecycle events
        # on — a wall-clock read anywhere here would shear the
        # cross-process composition the artifact's arithmetic rests on
        "csmom_tpu/obs/fleet.py",
        "csmom_tpu/cli/fleet.py",
        # the elastic fleet controller (ISSUE 20): promotion walls,
        # hysteresis sustain/cooldown windows, and quota refill all
        # measure intervals on the clock the supervisor stamps events
        # on — a wall-clock jump here could promote on thin air or
        # thrash the band
        "csmom_tpu/serve/fleet.py",
    )

    # the stream data plane runs on EVENT TIME: bar stamps and version
    # counters only — a clock read here is a lateness decision smuggled
    # off the event-time axis
    NO_CLOCK_FILES = (
        "csmom_tpu/stream/__init__.py",
        "csmom_tpu/stream/ring.py",
        "csmom_tpu/stream/ingest.py",
        "csmom_tpu/stream/incremental.py",
    )

    # ledger verdicts must be reproducible from committed artifacts alone
    WALL_FREE_FILES = (
        "csmom_tpu/obs/ledger.py",
        "csmom_tpu/obs/regress.py",
        "csmom_tpu/obs/memstats.py",
        "csmom_tpu/cli/ledger.py",
        # renders committed TRACE evidence: verdict-reproducible, so
        # clock-free like the rest of the ledger tier
        "csmom_tpu/cli/trace.py",
    )

    def start_run(self, run: RunContext) -> None:
        for rel in (self.MONO_ONLY_FILES + self.NO_CLOCK_FILES
                    + self.WALL_FREE_FILES):
            path = os.path.join(run.repo, rel)
            # only meaningful against a tree that HAS the layer (a test
            # repo with one doctored module must not spam missing-file
            # findings for every other tier entry)
            if not os.path.isfile(path) and os.path.isdir(
                    os.path.dirname(path)):
                run.report(self.id, rel, 1,
                           "tier contract names a missing module — update "
                           "the tier lists in analysis/rules.py")

    def start_file(self, ctx: FileContext) -> None:
        rel = _posix(ctx.rel)
        self._mono_only = rel in self.MONO_ONLY_FILES
        self._no_clock = rel in self.NO_CLOCK_FILES
        self._contract = (self._mono_only or self._no_clock
                          or rel in self.WALL_FREE_FILES)
        if self._contract:
            # a tier module cannot pragma its way out of its contract —
            # report around the suppression machinery on purpose
            for p in ctx.pragmas:
                if p.rule == self.id:
                    ctx.run.report(
                        self.id, ctx.rel, p.line,
                        "clock tiers are contracts, not defaults: a "
                        "serve/stream/ledger module must not carry a "
                        "clock-discipline pragma")

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if self._no_clock and isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = (node.module if isinstance(node, ast.ImportFrom)
                   else None)
            names = [a.name for a in node.names]
            if mod == "time" or "time" in names:
                ctx.report(self.id, node.lineno,
                           "the streaming data plane is event-time only — "
                           "it must not import the time module")
            if (mod or "").endswith("deadline") and any(
                    a.name == "mono_now_s" for a in node.names):
                ctx.report(self.id, node.lineno,
                           "the streaming data plane reads NO clock, not "
                           "even mono_now_s — lateness and ordering come "
                           "from tick stamps")
        if (self._no_clock and isinstance(node, ast.Name)
                and node.id == "mono_now_s"):
            ctx.report(self.id, node.lineno,
                       "mono_now_s in the event-time-only data plane")
        if not isinstance(node, ast.Call):
            return
        origin = ctx.resolve_call(node)
        if origin is None:
            return
        if origin == "time.time":
            ctx.report(self.id, node.lineno,
                       "bare wall-clock read (resolves to time.time) — "
                       "use utils.deadline.wall_now_s / file_age_s / "
                       "marker_fresh, or mono_now_s for durations")
        elif (origin == "datetime.datetime.now" and not node.args
                and not node.keywords):
            ctx.report(self.id, node.lineno,
                       "argless datetime.now is a wall-clock read — "
                       "pass a timezone for identity stamps "
                       "(datetime.now with timezone.utc) or use the "
                       "utils.deadline helpers")
        elif self._mono_only and origin == "time.monotonic":
            ctx.report(self.id, node.lineno,
                       "inline time.monotonic in a mono_now_s-only "
                       "module — serve/replay timing goes through "
                       "utils.deadline.mono_now_s so one clock rules "
                       "deadlines AND recorded latencies")
        elif self._no_clock and (origin.startswith("time.")
                                 or origin.endswith(".mono_now_s")):
            ctx.report(self.id, node.lineno,
                       f"clock read ({origin}) in the event-time-only "
                       "stream data plane")

    def finish_file(self, ctx: FileContext) -> None:
        for kind, line0, text in ctx.tokens:
            for m in self.MENTION_RE.finditer(text):
                line = line0 + text[: m.start()].count("\n")
                ctx.report(
                    self.id, line,
                    f"prose mention of the wall-clock idiom in a {kind} — "
                    "justify it in place with a pragma "
                    f"(lint: allow{'[' + self.id + ']'} <why>) or drop it")


# --------------------------------------------------------------------------
# tracer-hygiene
# --------------------------------------------------------------------------

_JIT_ORIGINS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}


def _is_jit_origin(origin: str | None, raw_name: str | None) -> bool:
    if origin is not None:
        return origin in _JIT_ORIGINS or origin.endswith("shard_map")
    return raw_name in ("jit", "pjit", "shard_map")


def _callable_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class TracerHygiene(LintRule):
    """Host-sync escapes inside traced (jit/shard_map/registered)
    functions: each one is a hidden device round trip per dispatch."""

    id = "tracer-hygiene"
    description = ("no print/clock/float()/.item()/np.asarray-on-params/"
                   "global-writes inside functions passed to jax.jit, "
                   "shard_map, or registered as a ServeSurface batch_fn")

    _HOST_MATERIALIZE = {"numpy.asarray", "numpy.array",
                         "numpy.ascontiguousarray"}

    def start_file(self, ctx: FileContext) -> None:
        tree = ctx.tree
        defs_by_name: dict = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        # module-level literal constants, so `static_argnames=_STATICS`
        # (the repo's idiom for shared jit wrappings) dereferences
        module_consts: dict = {}
        for node in tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, (ast.Constant, ast.Tuple,
                                                ast.List))):
                module_consts[node.targets[0].id] = node.value

        traced: dict = {}  # def/lambda node -> set of static param names

        def mark(node, static=()):
            if node is None:
                return
            traced.setdefault(node, set()).update(static)

        def static_names(call: ast.Call | None, fn) -> set:
            """Param names a jit call pins static (literal argnums/names
            or a module-level literal constant — the honest subset a
            static pass can know)."""
            out: set = set()
            if call is None:
                return out
            params = _param_names(fn)
            for kw in call.keywords:
                v = kw.value
                if isinstance(v, ast.Name) and v.id in module_consts:
                    v = module_consts[v.id]
                if kw.arg == "static_argnums":
                    idxs = []
                    if isinstance(v, ast.Constant) and isinstance(v.value,
                                                                  int):
                        idxs = [v.value]
                    elif isinstance(v, (ast.Tuple, ast.List)):
                        idxs = [e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, int)]
                    out |= {params[i] for i in idxs if 0 <= i < len(params)}
                elif kw.arg == "static_argnames":
                    if isinstance(v, ast.Constant) and isinstance(v.value,
                                                                  str):
                        out.add(v.value)
                    elif isinstance(v, (ast.Tuple, ast.List)):
                        out |= {e.value for e in v.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)}
            return out

        def unwrap_vmap(node):
            while (isinstance(node, ast.Call)
                   and _callable_name(node.func) in ("vmap", "pmap")
                   and node.args):
                node = node.args[0]
            return node

        def targets_of(node, jit_call=None):
            node = unwrap_vmap(node)
            if isinstance(node, ast.Lambda):
                mark(node, static_names(jit_call, node))
            elif isinstance(node, ast.Name):
                for d in defs_by_name.get(node.id, []):
                    mark(d, static_names(jit_call, d))

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_origin(ctx.resolve(dec),
                                      _callable_name(dec)):
                        mark(node)
                    elif isinstance(dec, ast.Call):
                        origin = ctx.resolve_call(dec)
                        name = _callable_name(dec.func)
                        if _is_jit_origin(origin, name):
                            mark(node, static_names(dec, node))
                        elif ((origin or "").endswith("partial")
                                and dec.args
                                and _is_jit_origin(
                                    ctx.resolve(dec.args[0]),
                                    _callable_name(dec.args[0]))):
                            mark(node, static_names(dec, node))
            elif isinstance(node, ast.Call):
                if _is_jit_origin(ctx.resolve_call(node),
                                  _callable_name(node.func)) and node.args:
                    targets_of(node.args[0], jit_call=node)
                for kw in node.keywords:
                    if kw.arg == "batch_fn" and isinstance(kw.value,
                                                           ast.Name):
                        # a registered ServeSurface factory: its INNER
                        # functions are what jit/vmap ultimately trace
                        for factory in defs_by_name.get(kw.value.id, []):
                            for sub in ast.walk(factory):
                                if sub is not factory and isinstance(
                                        sub, (ast.FunctionDef,
                                              ast.Lambda)):
                                    mark(sub)

        # closure: a def nested inside a traced def is traced too
        changed = True
        while changed:
            changed = False
            for node in list(traced):
                for sub in ast.walk(node):
                    if (sub is not node
                            and isinstance(sub, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef,
                                                 ast.Lambda))
                            and sub not in traced):
                        traced[sub] = set(traced[node])
                        changed = True

        reported: set = set()

        def flag(line, msg):
            if (line, msg) not in reported:
                reported.add((line, msg))
                ctx.report(self.id, line, msg)

        for fn, static in traced.items():
            params = set(_param_names(fn)) - static
            fname = getattr(fn, "name", "<lambda>")
            globals_declared: set = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Global):
                    globals_declared |= set(sub.names)
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.Assign, ast.AugAssign)):
                    tgts = (sub.targets if isinstance(sub, ast.Assign)
                            else [sub.target])
                    for t in tgts:
                        if (isinstance(t, ast.Name)
                                and t.id in globals_declared):
                            flag(sub.lineno,
                                 f"traced function {fname!r} writes "
                                 f"global {t.id!r} — side effects do not "
                                 "re-run on cached executions and force "
                                 "host sync under tracing")
                if not isinstance(sub, ast.Call):
                    continue
                name = _callable_name(sub.func)
                origin = ctx.resolve_call(sub)
                if isinstance(sub.func, ast.Name) and name == "print":
                    flag(sub.lineno,
                         f"print inside traced function {fname!r} — "
                         "host I/O in a jitted/sharded body (use "
                         "jax.debug.print if this must stay)")
                elif origin is not None and (origin.startswith("time.")
                                             or origin.endswith(
                                                 ".mono_now_s")):
                    flag(sub.lineno,
                         f"clock read ({origin}) inside traced function "
                         f"{fname!r} — trace-time constant at best, host "
                         "sync at worst")
                elif origin in self._HOST_MATERIALIZE and sub.args and (
                        isinstance(sub.args[0], ast.Name)
                        and sub.args[0].id in params):
                    flag(sub.lineno,
                         f"{origin} on traced parameter "
                         f"{sub.args[0].id!r} in {fname!r} — host "
                         "materialization blocks the dispatch (use "
                         "jnp.asarray)")
                elif (isinstance(sub.func, ast.Name)
                        and name == "float" and len(sub.args) == 1
                        and isinstance(sub.args[0], ast.Name)
                        and sub.args[0].id in params):
                    flag(sub.lineno,
                         f"float() on traced parameter "
                         f"{sub.args[0].id!r} in {fname!r} — a "
                         "concretization/host sync inside the trace")
                elif (isinstance(sub.func, ast.Attribute)
                        and name == "item" and not sub.args):
                    root = _root_name(sub.func.value)
                    if root is not None and root in params:
                        flag(sub.lineno,
                             f".item() on traced parameter {root!r} in "
                             f"{fname!r} — device->host sync per call")


def _param_names(fn) -> list:
    a = fn.args
    names = [p.arg for p in getattr(a, "posonlyargs", [])]
    names += [p.arg for p in a.args]
    names += [p.arg for p in a.kwonlyargs]
    return names


def _root_name(node) -> str | None:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


# --------------------------------------------------------------------------
# lock-discipline
# --------------------------------------------------------------------------

class LockDiscipline(LintRule):
    """Locks leave scope only through with/try-finally, and never guard
    a blocking call (the r13 exactly-once transitions depend on it)."""

    id = "lock-discipline"
    description = ("threading locks acquired only via with/try-finally, "
                   "and no blocking call (socket send/recv, sleep, engine "
                   "dispatch) while a lock is held")

    BLOCKING = ("sleep", "send", "sendall", "recv", "recv_into",
                "connect", "accept", "dispatch", "score", "request")

    @staticmethod
    def _lock_expr(node) -> bool:
        if isinstance(node, ast.Name):
            return "lock" in node.id.lower()
        if isinstance(node, ast.Attribute):
            return "lock" in node.attr.lower()
        if isinstance(node, ast.Subscript):
            s = node.slice
            return (isinstance(s, ast.Constant) and isinstance(s.value, str)
                    and "lock" in s.value.lower())
        return False

    @staticmethod
    def _recv_text(node) -> str:
        try:
            return ast.unparse(node)
        except Exception:  # pragma: no cover - unparse is total on 3.9+
            return ast.dump(node)

    def _released_in(self, stmts, receiver: str) -> bool:
        for s in stmts:
            for sub in ast.walk(s):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "release"
                        and self._recv_text(sub.func.value) == receiver):
                    return True
        return False

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        # --- bare .acquire() outside with / try-finally -------------------
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
                and self._lock_expr(node.func.value)):
            receiver = self._recv_text(node.func.value)
            if not self._acquire_is_disciplined(node, receiver, ctx):
                ctx.report(self.id, node.lineno,
                           f"{receiver}.acquire() without with/"
                           "try-finally — a raise between acquire and "
                           "release deadlocks every later waiter")
        # --- blocking call while a lock is held ---------------------------
        if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                self._lock_expr(i.context_expr) for i in node.items):
            self._scan_lock_body(node, ctx)

    def _acquire_is_disciplined(self, call, receiver: str,
                                ctx: FileContext) -> bool:
        # disciplined iff some enclosing Try releases this receiver in
        # its finalbody, the very next sibling statement is such a Try,
        # or the acquire is the TEST of an ``if lock.acquire(...):``
        # whose body opens with such a Try — the canonical
        # try-lock-then-finally-release idiom (the r19 read baton)
        stmt = call
        while (stmt in ctx.parents
               and not isinstance(stmt, ast.stmt)):
            stmt = ctx.parents[stmt]
        if (isinstance(stmt, ast.If) and stmt.body
                and any(sub is call for sub in ast.walk(stmt.test))
                and isinstance(stmt.body[0], ast.Try)
                and self._released_in(stmt.body[0].finalbody, receiver)):
            return True
        node = stmt
        while node in ctx.parents:
            parent = ctx.parents[node]
            if isinstance(parent, ast.Try) and self._released_in(
                    parent.finalbody, receiver):
                return True
            for field in ("body", "orelse", "finalbody"):
                body = getattr(parent, field, None)
                if isinstance(body, list) and node in body:
                    i = body.index(node)
                    if (i + 1 < len(body)
                            and isinstance(body[i + 1], ast.Try)
                            and self._released_in(body[i + 1].finalbody,
                                                  receiver)):
                        return True
            node = parent
        return False

    def _scan_lock_body(self, with_node, ctx: FileContext) -> None:
        def scan(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # deferred bodies do not run under the lock
                if isinstance(child, (ast.With, ast.AsyncWith)) and any(
                        self._lock_expr(i.context_expr)
                        for i in child.items):
                    continue  # a nested lock-with gets its own visit
                if isinstance(child, ast.Call):
                    name = _callable_name(child.func)
                    origin = ctx.resolve_call(child)
                    if (name in self.BLOCKING
                            or origin == "time.sleep"):
                        ctx.report(
                            self.id, child.lineno,
                            f"blocking call ({name}) with a lock held — "
                            "every thread contending this lock "
                            "serializes behind the wait; move the "
                            "blocking work outside the critical "
                            "section")
                scan(child)

        for stmt in with_node.body:
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                         ast.Call):
                name = _callable_name(stmt.value.func)
                if name in self.BLOCKING:
                    ctx.report(
                        self.id, stmt.lineno,
                        f"blocking call ({name}) with a lock held — "
                        "move it outside the critical section")
                    continue
            scan(stmt)


# --------------------------------------------------------------------------
# donation-safety
# --------------------------------------------------------------------------

class DonationSafety(LintRule):
    """No read of a buffer after it was passed at a donated position."""

    id = "donation-safety"
    description = ("a buffer passed to a donate_argnums/donated entry is "
                   "surrendered to XLA — reading it afterwards in the "
                   "same scope is garbage on device (CPU ignores "
                   "donation, which is how this escapes testing)")

    @staticmethod
    def _donated_indices(call: ast.Call) -> tuple | None:
        """The donated positional indices a jit call pins, or None when
        the call donates nothing."""
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                idxs = tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
                return idxs or None
        return None

    def start_file(self, ctx: FileContext) -> None:
        scopes = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                               if isinstance(n, (ast.FunctionDef,
                                                 ast.AsyncFunctionDef))]
        for scope in scopes:
            self._scan_scope(scope, ctx)

    @staticmethod
    def _scope_walk(scope):
        """Walk one scope, not descending into nested defs (their
        bindings and execution order are not this scope's)."""
        stack = (list(scope.body) if hasattr(scope, "body")
                 else list(ast.iter_child_nodes(scope)))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # a nested def is its own scope
            stack.extend(ast.iter_child_nodes(node))

    def _scan_scope(self, scope, ctx: FileContext) -> None:
        donated_fns: dict = {}  # local name -> donated indices | None(=all)
        donating_calls: list = []  # (call node, indices | None)

        def is_jit(call):
            return _is_jit_origin(ctx.resolve_call(call),
                                  _callable_name(call.func))

        for node in self._scope_walk(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                call = node.value
                name = node.targets[0].id
                if is_jit(call):
                    idxs = self._donated_indices(call)
                    if idxs:
                        donated_fns[name] = idxs
                elif "donated" in (_callable_name(call.func) or ""):
                    donated_fns[name] = None  # every positional donated

        for node in self._scope_walk(scope):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Name)
                    and node.func.id in donated_fns):
                donating_calls.append((node, donated_fns[node.func.id]))
            elif (isinstance(node.func, ast.Name)
                    and "donated" in node.func.id):
                # a *_donated entry called directly (e.g. one passed in
                # as an argument): every positional buffer is donated
                donating_calls.append((node, None))
            elif isinstance(node.func, ast.Call) and is_jit(node.func):
                idxs = self._donated_indices(node.func)
                if idxs:
                    donating_calls.append((node, idxs))

        for call, idxs in donating_calls:
            indices = (range(len(call.args)) if idxs is None else idxs)
            end = getattr(call, "end_lineno", call.lineno)
            fn_txt = _callable_name(call.func) or "the donated entry"
            for i in indices:
                if i >= len(call.args) or not isinstance(call.args[i],
                                                         ast.Name):
                    continue
                buf = call.args[i].id
                # a rebind on the call's own line (``v = fn(v, m)``) or
                # later retires the name — reads past it are a NEW buffer
                rebound_at = min(
                    (n.lineno for n in self._scope_walk(scope)
                     if isinstance(n, ast.Name) and n.id == buf
                     and isinstance(n.ctx, ast.Store)
                     and n.lineno >= end), default=float("inf"))
                for n in self._scope_walk(scope):
                    if (isinstance(n, ast.Name) and n.id == buf
                            and isinstance(n.ctx, ast.Load)
                            and end < n.lineno < rebound_at
                            and n is not call.args[i]):
                        ctx.report(
                            self.id, n.lineno,
                            f"{buf!r} is read after being donated to "
                            f"{fn_txt} (line {call.lineno}) — the buffer "
                            "was surrendered to XLA; copy it first or "
                            "use the undonated entry")


# --------------------------------------------------------------------------
# dial-discipline
# --------------------------------------------------------------------------

class DialDiscipline(LintRule):
    """No dial-per-call transport on request hot paths (ISSUE 15).

    The pooled multiplexed channel (``proto.ChannelPool``) is the only
    legal transport for score dispatch; ``proto.request_once`` (and its
    back-compat alias ``proto.request``) opens a fresh connection per
    call — exactly the r18 design whose measured bill was an 11× tail
    (``trace_stage_transport_p99_ms`` 44 → 742 ms).  One-shot calls
    stay legal where a fresh connection is the POINT: probes (a probe
    must measure the peer's ability to accept), lifecycle/admin ops
    (stats, drain, stop — they must not ride a channel the request
    path might sever), and the supervisor/health modules that own
    them.  Alias-aware like every rule here: ``from
    csmom_tpu.serve.proto import request_once as r; r(...)`` resolves
    to the same origin."""

    id = "dial-discipline"
    description = ("proto.request_once (dial-per-call) is for probes "
                   "and one-shot admin ops only — request hot paths "
                   "(router/fabric dispatch) must use the pooled "
                   "multiplexed channels, or the connection-per-request "
                   "tail comes back")

    # the one-shot origins (``request`` is the pre-r19 alias)
    ONE_SHOT_ORIGINS = ("csmom_tpu.serve.proto.request_once",
                        "csmom_tpu.serve.proto.request")

    # admin/probe modules that OWN the one-shot pattern: fresh-dial
    # probing and lifecycle ops are their job, not a hot path
    ALLOWED_FILES = (
        "csmom_tpu/serve/supervisor.py",
        "csmom_tpu/serve/health.py",
    )

    # probe/lifecycle functions stay legal anywhere (router_stats on
    # the fabric supervisor, CLI self-probes, rehearse drivers)
    ALLOWED_FN_RE = re.compile(
        r"probe|stats|liveness|readiness|drain|stop|lifecycle|admin",
        re.IGNORECASE)

    def _enclosing_fn(self, node: ast.AST, ctx: FileContext):
        cur = node
        while cur in ctx.parents:
            cur = ctx.parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
        return None

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        if not isinstance(node, ast.Call):
            return
        origin = ctx.resolve_call(node)
        name = _callable_name(node.func)
        if origin is not None:
            # a resolved origin is the truth: a foreign helper that
            # merely SHARES the name request_once is not our transport
            if origin not in self.ONE_SHOT_ORIGINS:
                return
        elif name != "request_once":
            return
        rel = _posix(ctx.rel)
        if rel in self.ALLOWED_FILES:
            return
        fn = self._enclosing_fn(node, ctx)
        if fn is not None and self.ALLOWED_FN_RE.search(fn.name):
            return
        where = f" (in {fn.name!r})" if fn is not None else ""
        ctx.report(
            self.id, node.lineno,
            f"dial-per-call transport{where}: request_once opens a "
            "fresh connection per call — request hot paths dispatch "
            "over proto.ChannelPool (persistent multiplexed channels); "
            "if this is genuinely a probe or one-shot admin op, name "
            "the function for what it is (probe/stats/drain/stop) or "
            "justify in place with a pragma")


# --------------------------------------------------------------------------
# enumeration-drift
# --------------------------------------------------------------------------

_BANNED_ENUMS = ("ENDPOINTS", "ENTRIES", "WORKLOADS", "STRATEGIES")


def banned_enumeration_name(name: str) -> bool:
    """Module-level names that read as an engine/endpoint/workload/entry
    enumeration — the parallel tables the r14 registry deleted."""
    up = name.upper().lstrip("_")
    return any(up == b or up.endswith("_" + b) for b in _BANNED_ENUMS)


class EnumerationDrift(LintRule):
    """The registry stays the only table; the checkpoint vocabulary
    stays bound to its call sites (both directions)."""

    id = "enumeration-drift"
    description = ("no ENDPOINTS/…_ENTRIES/WORKLOADS/…_STRATEGIES "
                   "enumerations outside csmom_tpu/registry/, and every "
                   "checkpoint(\"x\") literal round-trips with "
                   "chaos.plan.KNOWN_POINTS")

    def __init__(self):
        from csmom_tpu.chaos.plan import KNOWN_POINTS

        self._vocab = tuple(KNOWN_POINTS)

    def cache_salt(self) -> str:
        """Verdicts depend on the live checkpoint vocabulary, not just
        the scanned sources — changing KNOWN_POINTS must invalidate
        every cached not-in-vocab verdict, both directions."""
        return repr(self._vocab)

    def start_run(self, run: RunContext) -> None:
        self._points_seen: dict = {}
        self._vocab_site: tuple | None = None

    def start_file(self, ctx: FileContext) -> None:
        self._cur_points: dict = {}
        self._cur_vocab: int | None = None

    def file_facts(self, ctx: FileContext):
        """The cross-file state this file contributes (cache contract):
        its checkpoint call sites and — for chaos/plan.py — the
        KNOWN_POINTS anchor line.  Cached with the findings so a
        cache-replayed file still feeds the whole-run vocabulary
        round-trip."""
        if not self._cur_points and self._cur_vocab is None:
            return None
        return {"points": dict(self._cur_points),
                "vocab_line": self._cur_vocab}

    def absorb_facts(self, rel: str, facts, run: RunContext) -> None:
        for point, line in (facts.get("points") or {}).items():
            self._points_seen.setdefault(point, (rel, line))
        if facts.get("vocab_line") is not None:
            self._vocab_site = (rel, facts["vocab_line"])

    def visit(self, node: ast.AST, ctx: FileContext) -> None:
        rel = _posix(ctx.rel)
        if (isinstance(node, (ast.Assign, ast.AnnAssign))
                and isinstance(ctx.parents.get(node), ast.Module)
                and not rel.startswith("csmom_tpu/registry/")):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Name) and banned_enumeration_name(
                        t.id):
                    ctx.report(
                        self.id, node.lineno,
                        f"module-level enumeration {t.id!r} outside "
                        "csmom_tpu/registry/ — register engines instead "
                        "of growing a parallel table (the four-list "
                        "world ISSUE 9 deleted)")
        if (isinstance(node, ast.Assign) and rel.endswith("chaos/plan.py")
                and any(isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
                        for t in node.targets)):
            self._cur_vocab = node.lineno
        if isinstance(node, ast.Call):
            name = _callable_name(node.func)
            if (name in ("checkpoint", "_chaos") and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                point = node.args[0].value
                self._cur_points.setdefault(point, node.lineno)
                if "*" not in point and point not in self._vocab:
                    ctx.report(
                        self.id, node.lineno,
                        f"checkpoint point {point!r} is not in "
                        "chaos.plan.KNOWN_POINTS — add it there (the "
                        "vocabulary is the checkpoint inventory; an "
                        "undeclared point is invisible to fault plans "
                        "and the rehearse matrix)")

    def finish_run(self, run: RunContext) -> None:
        if self._vocab_site is None:
            return  # partial sweep that never read the vocabulary home
        scanned = {_posix(r) for r in run.scanned}
        if not {"bench.py", "csmom_tpu/chaos/minibench.py"} <= scanned:
            # a partial sweep (e.g. --paths csmom_tpu/chaos) sees the
            # vocabulary but not the call-site homes; only a full sweep
            # can honestly claim an entry is dead
            return
        rel, line = self._vocab_site
        for point in self._vocab:
            if point not in self._points_seen:
                run.report(
                    self.id, rel, line,
                    f"plan point {point!r} is in KNOWN_POINTS but no "
                    "checkpoint call site uses it — dead vocabulary "
                    "drifts exactly like the prose inventory did; drop "
                    "it or restore the call site")


# --------------------------------------------------------------------------
# registration
# --------------------------------------------------------------------------

BUILTIN_RULES = (ClockDiscipline, TracerHygiene, LockDiscipline,
                 DonationSafety, EnumerationDrift, DialDiscipline)


def register_builtin_rules() -> None:
    """Register the builtin rule set as kind-``lint`` engines — one
    registration enrolls a rule in the CLI, the tier-1 sweep, the
    registry listing, and the fixture self-test (import-idempotent)."""
    from csmom_tpu.registry import REGISTRY, EngineSpec

    for cls in BUILTIN_RULES:
        REGISTRY.register(
            EngineSpec(name=cls.id, kind="lint",
                       description=cls.description, rule_cls=cls),
            replace=True)


register_builtin_rules()
