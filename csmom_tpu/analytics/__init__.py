"""Performance analytics: Sharpe, t-stats, bootstrap CIs, result schemas."""

from csmom_tpu.analytics.stats import (
    sharpe,
    rolling_sharpe,
    vol_managed,
    masked_mean,
    masked_std,
    t_stat,
    nw_t_stat,
)
from csmom_tpu.analytics.bootstrap import (
    block_bootstrap,
    block_bootstrap_grid,
    circular_block_indices,
    BootstrapResult,
)
from csmom_tpu.analytics.tearsheet import (
    Tearsheet,
    annual_returns,
    format_tearsheet,
    max_drawdown,
    tearsheet,
)

__all__ = [
    "sharpe",
    "rolling_sharpe",
    "vol_managed",
    "masked_mean",
    "masked_std",
    "t_stat",
    "nw_t_stat",
    "block_bootstrap",
    "block_bootstrap_grid",
    "circular_block_indices",
    "BootstrapResult",
    "Tearsheet",
    "annual_returns",
    "format_tearsheet",
    "max_drawdown",
    "tearsheet",
]
