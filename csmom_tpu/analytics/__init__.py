"""Performance analytics: Sharpe, t-stats, decile tables, result schemas."""

from csmom_tpu.analytics.stats import sharpe, masked_mean, masked_std, t_stat

__all__ = ["sharpe", "masked_mean", "masked_std", "t_stat"]
