"""Circular block-bootstrap confidence intervals (BASELINE config 5).

The reference reports a point estimate only (mean spread + Sharpe,
``run_demo.py:72-73``); the replicated paper quotes t-stats.  This module
adds distributional inference the panel way: resampling is an index-gather,
so the whole bootstrap — S resamples x T months x statistics — is one fused
jit call with a ``vmap`` over the sample axis, not a Python loop over
resamples.

Block (rather than iid) resampling preserves the short-horizon
autocorrelation that monthly spread series carry (the reason the paper
reports Newey–West t-stats); circular wrapping keeps every resample exactly
T months long so shapes stay static.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.analytics.stats import masked_mean, sharpe, t_stat


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BootstrapResult:
    """Bootstrap distribution + percentile CIs for a masked return series."""

    mean_samples: jnp.ndarray    # f[S] resampled mean returns
    sharpe_samples: jnp.ndarray  # f[S] resampled annualized Sharpes
    mean_point: jnp.ndarray      # scalar, on the original series
    sharpe_point: jnp.ndarray    # scalar
    mean_ci: jnp.ndarray         # f[2] percentile interval (lo, hi)
    sharpe_ci: jnp.ndarray       # f[2]


def circular_block_indices(key, n_samples: int, n_times: int, block_len: int):
    """i32[n_samples, n_times] circular-block resample index matrices.

    Each row concatenates ceil(T / L) blocks of L consecutive (mod T) time
    indices with uniformly random start points, truncated to exactly T.
    """
    if block_len < 1:
        raise ValueError(f"block_len must be >= 1, got {block_len}")
    n_blocks = -(-n_times // block_len)
    starts = jax.random.randint(key, (n_samples, n_blocks), 0, n_times)
    offs = jnp.arange(block_len)
    idx = (starts[:, :, None] + offs[None, None, :]) % n_times
    return idx.reshape(n_samples, -1)[:, :n_times].astype(jnp.int32)


@partial(jax.jit, static_argnames=("n_samples", "block_len", "freq"))
def block_bootstrap(
    returns,
    valid,
    key,
    n_samples: int = 1000,
    block_len: int = 6,
    freq: int = 12,
    ci_level: float = 0.95,
) -> BootstrapResult:
    """Bootstrap the mean and annualized Sharpe of a masked return series.

    Args:
      returns: f[T] period returns (NaN allowed at invalid slots).
      valid: bool[T]; invalid months travel with their index, so a resample
        that draws them simply has fewer live observations (masked stats),
        mirroring how the original series treats them.
      key: jax PRNG key.
      n_samples: number of bootstrap resamples (vmapped, one fused call).
      block_len: resample block length in periods.
      freq: periods per year for Sharpe annualization.
      ci_level: central percentile mass for the intervals.
    """
    T = returns.shape[-1]
    idx = circular_block_indices(key, n_samples, T, block_len)
    r = returns[idx]          # [S, T]
    v = valid[idx]
    means = masked_mean(r, v)                         # [S]
    sharpes = sharpe(r, v, freq_per_year=freq)        # [S]

    alpha = (1.0 - ci_level) / 2.0
    q = jnp.array([alpha, 1.0 - alpha])
    return BootstrapResult(
        mean_samples=means,
        sharpe_samples=sharpes,
        mean_point=masked_mean(returns, valid),
        sharpe_point=sharpe(returns, valid, freq_per_year=freq),
        mean_ci=jnp.nanquantile(means, q),
        sharpe_ci=jnp.nanquantile(sharpes, q),
    )


@partial(jax.jit, static_argnames=("n_samples", "block_len", "freq"))
def block_bootstrap_grid(
    spreads,
    spread_valid,
    key,
    n_samples: int = 200,
    block_len: int = 6,
    freq: int = 12,
    ci_level: float = 0.95,
) -> BootstrapResult:
    """Bootstrap every cell of a [..., T] grid of spread series at once.

    One shared set of resample indices is drawn (the grid cells are the
    *same* calendar months under different hyperparameters, so resampling
    must be synchronized across cells for the CIs to be comparable), then
    the statistics broadcast over the leading grid axes: sample arrays come
    back as f[S, ...grid] and CIs as f[2, ...grid].
    """
    T = spreads.shape[-1]
    idx = circular_block_indices(key, n_samples, T, block_len)
    r = spreads[..., idx]   # [...G, S, T]
    v = spread_valid[..., idx]
    means = jnp.moveaxis(masked_mean(r, v), -1, 0)                  # [S, ...G]
    sharpes = jnp.moveaxis(sharpe(r, v, freq_per_year=freq), -1, 0)

    alpha = (1.0 - ci_level) / 2.0
    q = jnp.array([alpha, 1.0 - alpha])
    return BootstrapResult(
        mean_samples=means,
        sharpe_samples=sharpes,
        mean_point=masked_mean(spreads, spread_valid),
        sharpe_point=sharpe(spreads, spread_valid, freq_per_year=freq),
        mean_ci=jnp.nanquantile(means, q, axis=0),
        sharpe_ci=jnp.nanquantile(sharpes, q, axis=0),
    )
