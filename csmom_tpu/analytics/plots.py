"""Artifact writers: the reference's result files, same names, same schema.

The reference emits three artifacts (``run_demo.py:79,183-189``):
``results/monthly_mom_cum.png`` (cumulative spread growth),
``results/intraday_cum_pnl.png`` (cumulative event-backtest PnL) and
``results/trades.csv`` (header ``datetime,ticker,size,price,impact,score``).
Keeping names and schemas identical means a reference user's downstream
tooling keeps working unchanged.

Plot style: line charts — primary hue + a small categorical cycle for
overlays, thin 2px line, recessive grid, neutral ink for text, legend only
when more than one series is drawn (otherwise the title names the series).
"""

from __future__ import annotations

import os

import numpy as np

_LINE = "#3b82b4"   # primary hue
_OVERLAYS = ("#b45a3b", "#5a9e6f", "#8a6db1")  # overlay cycle
_INK = "#333333"
_GRID = "#dddddd"


def ensure_dir(path: str) -> str:
    os.makedirs(path, exist_ok=True)
    return path


def _line_plot(x, y, title: str, ylabel: str, out_path: str, extra=None,
               label=None):
    """One styled line chart; ``extra`` is an optional list of
    ``(label, x, y)`` overlay series drawn in the overlay hue cycle."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(9, 4.5))
    ax.plot(x, y, color=_LINE, linewidth=2, label=label)
    for i, (lab, xo, yo) in enumerate(extra or ()):
        ax.plot(xo, yo, color=_OVERLAYS[i % len(_OVERLAYS)], linewidth=2,
                label=lab)
    ax.set_title(title, color=_INK)
    ax.set_ylabel(ylabel, color=_INK)
    ax.grid(True, color=_GRID, linewidth=0.6)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)
    ax.tick_params(colors=_INK)
    if extra:
        ax.legend(frameon=False, labelcolor=_INK)
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def save_monthly_cum_plot(times, spread, results_dir: str,
                          fname: str = "monthly_mom_cum.png",
                          overlays=None) -> str:
    """Cumulative growth of the monthly spread, ``(1+r).cumprod()``
    (``run_demo.py:75-79``), over valid months only.

    ``overlays`` is an optional ``{label: spread_series}`` dict drawn as
    extra lines (each over its own valid months, in the module's overlay
    hue cycle) — the CLI uses it to put the banded / vol-managed variants
    next to the plain spread in the same reference-schema artifact.
    """
    ensure_dir(results_dir)

    def _cum(s):
        s = np.asarray(s, dtype=float)
        v = np.isfinite(s)
        return np.asarray(times)[v], np.cumprod(1.0 + s[v])

    x, y = _cum(spread)
    extra = [(label, *_cum(s)) for label, s in (overlays or {}).items()]
    return _line_plot(
        x, y,
        "Monthly momentum: cumulative spread growth",
        "growth of $1",
        os.path.join(results_dir, fname),
        extra=extra or None,
        label="spread" if extra else None,
    )


def save_intraday_pnl_plot(times, pnl, results_dir: str,
                           fname: str = "intraday_cum_pnl.png") -> str:
    """Cumulative minute PnL, ``pnl.cumsum()`` (``run_demo.py:186-188``)."""
    ensure_dir(results_dir)
    return _line_plot(
        np.asarray(times), np.cumsum(np.asarray(pnl, dtype=float)),
        "Intraday event backtest: cumulative PnL",
        "PnL ($)",
        os.path.join(results_dir, fname),
    )


def save_horizon_plot(profile, results_dir: str,
                      fname: str = "horizon_profile.png") -> str:
    """Event-time cumulative spread curve (the JT/LeSw hump: persistence
    then reversal).  ``profile`` is a
    :class:`csmom_tpu.backtest.horizon.HorizonProfile` or a
    :class:`~csmom_tpu.backtest.horizon.VolumeHorizonProfile` (one line
    per volume tercile)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ensure_dir(results_dir)
    cum = np.asarray(profile.cum_spread, dtype=float)
    fig, ax = plt.subplots(figsize=(9, 4.5))
    if cum.ndim == 1:
        ax.plot(np.arange(1, len(cum) + 1), cum, color=_LINE, linewidth=2)
    else:
        from csmom_tpu.analytics.tables import tercile_labels

        V = cum.shape[0]
        labels = tercile_labels(V)
        for v in range(V):
            ax.plot(np.arange(1, cum.shape[1] + 1), cum[v], linewidth=2,
                    label=labels[v])
        ax.legend(frameon=False, labelcolor=_INK)
    ax.axhline(0.0, color=_GRID, linewidth=1)
    ax.set_title("Event-time cumulative momentum spread", color=_INK)
    ax.set_xlabel("months since formation", color=_INK)
    ax.set_ylabel("cumulative spread", color=_INK)
    ax.grid(True, color=_GRID, linewidth=0.6)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)
    ax.tick_params(colors=_INK)
    fig.tight_layout()
    out_path = os.path.join(results_dir, fname)
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def save_trades_csv(trades_df, results_dir: str, fname: str = "trades.csv") -> str:
    """Write the trade log with the reference's exact header
    (``results/trades.csv:1``: datetime,ticker,size,price,impact,score)."""
    ensure_dir(results_dir)
    cols = ["datetime", "ticker", "size", "price", "impact", "score"]
    out = os.path.join(results_dir, fname)
    trades_df.loc[:, cols].to_csv(out, index=False)
    return out
