"""Masked summary statistics.

Matches the reference's analytics exactly where they exist: annualized
Sharpe = ``mean * f / (std(ddof=1) * sqrt(f))`` with NaN on empty or
zero-std series (``/root/reference/src/utils.py:8-16``), and adds the
t-statistics the replicated paper reports (Lee–Swaminathan 2000 Tables I-II
quote Newey–West t-stats for monthly spreads) which the reference omits.

All functions are mask-aware reductions over the last axis and jit/vmap
friendly, so a [G, T] grid of spread series reduces in one fused call.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from functools import partial

from csmom_tpu.ops.rolling import rolling_mean, rolling_std


def masked_mean(x, valid, axis=-1):
    n = jnp.sum(valid, axis=axis)
    s = jnp.sum(jnp.where(valid, jnp.nan_to_num(x), 0.0), axis=axis)
    return jnp.where(n > 0, s / jnp.maximum(n, 1), jnp.nan)


def masked_std(x, valid, axis=-1, ddof: int = 1):
    n = jnp.sum(valid, axis=axis)
    xf = jnp.where(valid, jnp.nan_to_num(x), 0.0)
    mean = jnp.where(n > 0, jnp.sum(xf, axis=axis) / jnp.maximum(n, 1), 0.0)
    dev = jnp.where(valid, xf - jnp.expand_dims(mean, axis), 0.0)
    ss = jnp.sum(dev * dev, axis=axis)
    ok = n > ddof
    return jnp.where(ok, jnp.sqrt(ss / jnp.maximum(n - ddof, 1)), jnp.nan)


@partial(jax.jit, static_argnames=("freq_per_year",))
def sharpe(returns, valid, freq_per_year: int = 252):
    """Annualized Sharpe ratio (``utils.py:8-16`` semantics: ddof=1, NaN on
    empty input or zero standard deviation)."""
    mean = masked_mean(returns, valid)
    sd = masked_std(returns, valid, ddof=1)
    ann = mean * freq_per_year
    ann_sd = sd * jnp.sqrt(jnp.asarray(freq_per_year, returns.dtype))
    return jnp.where(ann_sd > 0, ann / ann_sd, jnp.nan)


@jax.jit
def t_stat(returns, valid):
    """Plain t-statistic of the mean (mean / (std/sqrt(n)))."""
    n = jnp.sum(valid, axis=-1)
    mean = masked_mean(returns, valid)
    sd = masked_std(returns, valid, ddof=1)
    se = sd / jnp.sqrt(jnp.maximum(n, 1).astype(returns.dtype))
    return jnp.where((n > 1) & (se > 0), mean / se, jnp.nan)


def nw_t_stat(returns, valid, lags=None, max_lag: int = 24):
    """Newey–West (HAC, Bartlett-kernel) t-statistic of the mean.

    The replicated paper quotes NW t-stats for its monthly spreads
    (Lee–Swaminathan 2000, Tables I–II) because overlapping K-month holding
    makes the series serially correlated *by construction*; the plain
    :func:`t_stat` overstates significance there.  The reference framework
    has no t-stats at all (``/root/reference/src/utils.py:8-16`` is
    Sharpe-only).

    Long-run variance ``lrv = g0 + 2 * sum_{l=1..L} (1 - l/(L+1)) * g_l``
    with autocovariances ``g_l = (1/n) * sum_t u_t u_{t-l}`` of the demeaned
    series; ``t = mean / sqrt(lrv / n)``.

    Conventions (documented so the numbers are reproducible):
      - autocovariances normalized by n, no small-sample correction;
      - invalid slots contribute zero to every autocovariance.  For series
        whose invalid months are a contiguous prefix/suffix (the JT warmup
        and horizon tail — the only invalidity the engines produce) this is
        *identical* to computing on the compacted valid subsequence; interior
        gaps use zero-imputation, a deliberate time-aligned convention;
      - ``lags=None`` uses the Newey–West (1994) rule of thumb
        ``L = floor(4 * (n/100)^(2/9))``, capped at ``max_lag`` and ``n-1``.

    Args:
      returns: f[..., T].
      valid: bool[..., T].
      lags: bandwidth L — scalar or array broadcastable over the leading
        axes (e.g. per-cell holding period K for a J x K grid).  Traced
        values are fine; only ``max_lag`` must be static.
      max_lag: static unroll bound; weights for l > L are exactly zero, so
        any ``max_lag >= max(L)`` gives identical results.

    With L = 0 this reduces to the iid t-stat up to the ddof (n vs n-1)
    variance normalization.
    """
    n = jnp.sum(valid, axis=-1)
    dt = jnp.asarray(returns).dtype
    nf = jnp.maximum(n, 1).astype(dt)
    mean = masked_mean(returns, valid)
    u = jnp.where(valid, jnp.nan_to_num(returns) - jnp.expand_dims(
        jnp.nan_to_num(mean), -1), 0.0)
    if lags is None:
        L = jnp.floor(4.0 * (nf / 100.0) ** (2.0 / 9.0))
    else:
        L = jnp.asarray(lags).astype(dt)
    L = jnp.minimum(jnp.minimum(L, float(max_lag)), nf - 1.0)

    lrv = jnp.sum(u * u, axis=-1) / nf
    for lag in range(1, max_lag + 1):
        if lag >= u.shape[-1]:
            break
        w = jnp.clip(1.0 - lag / (L + 1.0), 0.0, None)
        g = jnp.sum(u[..., lag:] * u[..., :-lag], axis=-1) / nf
        lrv = lrv + 2.0 * w * g
    se = jnp.sqrt(jnp.maximum(lrv, 0.0) / nf)
    return jnp.where((n > 1) & (se > 0), mean / se, jnp.nan)


@jax.jit
def cumulative_growth(returns, valid):
    """Cumulative (1+r) product over valid entries (``run_demo.py:75``)."""
    lr = jnp.where(valid, jnp.log1p(returns), 0.0)
    return jnp.exp(jnp.cumsum(lr, axis=-1))


@partial(jax.jit, static_argnames=("window", "freq_per_year", "min_periods"))
def rolling_sharpe(returns, valid, window: int, freq_per_year: int = 12,
                   min_periods: int | None = None):
    """Trailing-window annualized Sharpe series (the tearsheet's
    stability view: a single full-sample Sharpe hides regime changes the
    rolling series shows).

    Same per-window semantics as :func:`sharpe` (ddof=1; NaN on fewer
    than ``min_periods`` valid observations — default: the full window —
    or zero std), computed for every position of the last axis via the
    shared prefix-sum rolling kernels, so the cost is O(T) regardless of
    the window.

    Returns ``(sharpe f[..., T], out_valid bool[..., T])``.
    """
    mp = window if min_periods is None else min_periods
    mean, mv = rolling_mean(returns, valid, window, min_periods=mp)
    sd, sv = rolling_std(returns, valid, window, min_periods=max(mp, 2),
                         ddof=1)
    f = jnp.asarray(freq_per_year, returns.dtype)
    ann = jnp.nan_to_num(mean) * f
    ann_sd = jnp.nan_to_num(sd) * jnp.sqrt(f)
    ok = mv & sv & (ann_sd > 0)
    return jnp.where(ok, ann / jnp.where(ok, ann_sd, 1.0), jnp.nan), ok


@partial(jax.jit, static_argnames=("window", "freq_per_year"))
def vol_managed(returns, valid, window: int = 6, target_ann_vol: float = 0.12,
                freq_per_year: int = 12, max_leverage: float = 2.0):
    """Volatility-managed overlay (Barroso & Santa-Clara 2015, JFE 116;
    Moreira & Muir 2017): scale the strategy's exposure by
    ``target / sigma_hat`` where ``sigma_hat`` is the trailing
    ``window``-period realized vol ending the period BEFORE — strictly
    prior data, no lookahead.  BSC's result is that momentum's crashes
    live in forecastable high-vol regimes, so the overlay roughly
    preserves the mean while cutting the left tail.  The reference has no
    risk management at all (its analytics are ``utils.py:8-16``).

    Args:
      returns: f[..., T] strategy return series (e.g. the monthly spread).
      valid: bool[..., T].
      window: trailing periods in the vol estimate (BSC use 6 months).
      target_ann_vol: annualized vol target (BSC's momentum target ~12%).
      max_leverage: cap on the scale (BSC cap at 2x; uncapped scales
        explode in quiet regimes).

    Returns:
      ``(managed f[..., T], out_valid bool[..., T], scale f[..., T])`` —
      ``managed[t] = scale[t] * returns[t]``; a slot is valid where the
      raw return is valid AND a full prior window of vol exists.
    """
    sd, sv = rolling_std(returns, valid, window, min_periods=window, ddof=1)
    # the scale applied over period t uses vol measured through t-1
    sd_prev = jnp.roll(sd, 1, axis=-1).at[..., 0].set(jnp.nan)
    sv_prev = jnp.roll(sv, 1, axis=-1).at[..., 0].set(False)
    f = jnp.asarray(freq_per_year, returns.dtype)
    ann_sd = jnp.nan_to_num(sd_prev) * jnp.sqrt(f)
    ok = valid & sv_prev & (ann_sd > 0)
    scale = jnp.clip(
        jnp.asarray(target_ann_vol, returns.dtype)
        / jnp.where(ok, ann_sd, 1.0),
        0.0, max_leverage,
    )
    scale = jnp.where(ok, scale, jnp.nan)
    managed = jnp.where(ok, scale * jnp.nan_to_num(returns), jnp.nan)
    return managed, ok, scale
