"""Masked summary statistics.

Matches the reference's analytics exactly where they exist: annualized
Sharpe = ``mean * f / (std(ddof=1) * sqrt(f))`` with NaN on empty or
zero-std series (``/root/reference/src/utils.py:8-16``), and adds the
t-statistics the replicated paper reports (Lee–Swaminathan 2000 Tables I-II
quote Newey–West t-stats for monthly spreads) which the reference omits.

All functions are mask-aware reductions over the last axis and jit/vmap
friendly, so a [G, T] grid of spread series reduces in one fused call.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from functools import partial


def masked_mean(x, valid, axis=-1):
    n = jnp.sum(valid, axis=axis)
    s = jnp.sum(jnp.where(valid, jnp.nan_to_num(x), 0.0), axis=axis)
    return jnp.where(n > 0, s / jnp.maximum(n, 1), jnp.nan)


def masked_std(x, valid, axis=-1, ddof: int = 1):
    n = jnp.sum(valid, axis=axis)
    xf = jnp.where(valid, jnp.nan_to_num(x), 0.0)
    mean = jnp.where(n > 0, jnp.sum(xf, axis=axis) / jnp.maximum(n, 1), 0.0)
    dev = jnp.where(valid, xf - jnp.expand_dims(mean, axis), 0.0)
    ss = jnp.sum(dev * dev, axis=axis)
    ok = n > ddof
    return jnp.where(ok, jnp.sqrt(ss / jnp.maximum(n - ddof, 1)), jnp.nan)


@partial(jax.jit, static_argnames=("freq_per_year",))
def sharpe(returns, valid, freq_per_year: int = 252):
    """Annualized Sharpe ratio (``utils.py:8-16`` semantics: ddof=1, NaN on
    empty input or zero standard deviation)."""
    mean = masked_mean(returns, valid)
    sd = masked_std(returns, valid, ddof=1)
    ann = mean * freq_per_year
    ann_sd = sd * jnp.sqrt(jnp.asarray(freq_per_year, returns.dtype))
    return jnp.where(ann_sd > 0, ann / ann_sd, jnp.nan)


@jax.jit
def t_stat(returns, valid):
    """Plain t-statistic of the mean (mean / (std/sqrt(n)))."""
    n = jnp.sum(valid, axis=-1)
    mean = masked_mean(returns, valid)
    sd = masked_std(returns, valid, ddof=1)
    se = sd / jnp.sqrt(jnp.maximum(n, 1).astype(returns.dtype))
    return jnp.where((n > 1) & (se > 0), mean / se, jnp.nan)


@jax.jit
def cumulative_growth(returns, valid):
    """Cumulative (1+r) product over valid entries (``run_demo.py:75``)."""
    lr = jnp.where(valid, jnp.log1p(returns), 0.0)
    return jnp.exp(jnp.cumsum(lr, axis=-1))
