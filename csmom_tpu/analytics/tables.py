"""Paper-style result tables.

The reference reports two scalars and a plot (``run_demo.py:72-79``); the
paper it replicates reports full decile tables (Lee & Swaminathan 2000,
Table I: R1..R10 mean returns by (J, K); Table II: momentum spreads within
volume terciles).  These builders render the framework's engine outputs in
that shape, so a replication run can be compared against the published
tables line by line.

All inputs are host-side arrays/results; outputs are small pandas
DataFrames (display objects, not compute).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

__all__ = ["decile_table", "jk_grid_table", "jk_grid_ci_table", "double_sort_table"]


def _masked_rows(x, valid):
    x = np.asarray(x, dtype=float)
    v = np.asarray(valid, dtype=bool) & np.isfinite(x)
    return x, v


def _row_stats(series, valid, freq: int, nw_lags=None):
    """mean / ann. Sharpe / t-stats over the valid months of one series.

    Delegates to :mod:`csmom_tpu.analytics.stats` — the same kernels the
    engines use for their reported scalars — so a table row can never
    disagree with the engine result it renders.  ``t_stat_nw`` is the
    Newey–West statistic (the form the replicated paper's Tables I–II
    quote); ``nw_lags=None`` uses the automatic bandwidth, a K-cell passes
    its holding period."""
    from csmom_tpu.analytics.stats import masked_mean, nw_t_stat, sharpe, t_stat

    return {
        "mean_ret": float(masked_mean(series, valid)),
        "ann_sharpe": float(sharpe(series, valid, freq_per_year=freq)),
        "t_stat_nw": float(nw_t_stat(series, valid, lags=nw_lags)),
        "t_stat": float(t_stat(series, valid)),
        "months": int(valid.sum()),
    }


def decile_table(decile_means, decile_counts, spread, freq: int = 12) -> pd.DataFrame:
    """Per-decile performance table (paper Table I row shape).

    Args:
      decile_means: f[B, M] equal-weighted decile next-month returns
        (``MonthlyReport.decile_means``).
      decile_counts: i[B, M] members per (decile, month).
      spread: f[M] top-minus-bottom series (NaN = invalid month).

    Returns a DataFrame indexed R1 (losers) .. R{B} (winners) plus an
    ``R{B}-R1`` spread row, with mean monthly return, annualized Sharpe,
    t-stat, live month count, and average membership.
    """
    means = np.asarray(decile_means, dtype=float)
    counts = np.asarray(decile_counts)
    B = means.shape[0]
    rows = {}
    for b in range(B):
        x, v = _masked_rows(means[b], counts[b] > 0)
        r = _row_stats(x, v, freq)
        r["avg_members"] = counts[b][counts[b] > 0].mean() if (counts[b] > 0).any() else 0.0
        rows[f"R{b + 1}"] = r
    x, v = _masked_rows(spread, np.isfinite(np.asarray(spread, dtype=float)))
    r = _row_stats(x, v, freq)
    r["avg_members"] = np.nan
    rows[f"R{B}-R1"] = r
    return pd.DataFrame(rows).T


def jk_grid_table(spreads, live, Js, Ks, freq: int = 12):
    """J x K grid summary (paper Table I panel shape).

    Args:
      spreads: f[nJ, nK, M] holding-period spread series
        (``GridResult.spreads``).
      live: bool[nJ, nK, M].

    Returns ``(mean_df, tstat_df, sharpe_df)`` — DataFrames indexed by J
    with K columns.  ``tstat_df`` holds Newey–West t-stats with lag = K
    (overlapping K-month holding makes the spreads serially correlated by
    construction, so the iid t-stat overstates significance exactly where
    the paper's tables need it).
    """
    spreads = np.asarray(spreads, dtype=float)
    live = np.asarray(live, dtype=bool)
    Js = [int(j) for j in np.asarray(Js)]
    Ks = [int(k) for k in np.asarray(Ks)]
    mean = np.full((len(Js), len(Ks)), np.nan)
    tstat = np.full_like(mean, np.nan)
    shp = np.full_like(mean, np.nan)
    for i in range(len(Js)):
        for j in range(len(Ks)):
            r = _row_stats(*_masked_rows(spreads[i, j], live[i, j]), freq,
                           nw_lags=Ks[j])
            mean[i, j], tstat[i, j], shp[i, j] = (
                r["mean_ret"], r["t_stat_nw"], r["ann_sharpe"]
            )
    idx = pd.Index(Js, name="J")
    cols = pd.Index(Ks, name="K")
    return (
        pd.DataFrame(mean, index=idx, columns=cols),
        pd.DataFrame(tstat, index=idx, columns=cols),
        pd.DataFrame(shp, index=idx, columns=cols),
    )


def jk_grid_ci_table(spreads, live, Js, Ks, key=None, n_samples: int = 200,
                     block_len: int = 6, freq: int = 12, ci_level: float = 0.95):
    """Block-bootstrap mean-spread CIs for every grid cell (default grid
    inference alongside the NW t-stats).

    Args:
      spreads: f[nJ, nK, M] (``GridResult.spreads``).
      live: bool[nJ, nK, M].
      key: jax PRNG key (defaults to ``PRNGKey(0)`` for reproducible tables).

    Returns ``(lo_df, hi_df)`` — the central ``ci_level`` percentile
    interval of the bootstrapped mean monthly spread, indexed by J with K
    columns (resamples synchronized across cells, see
    :func:`analytics.bootstrap.block_bootstrap_grid`).
    """
    import jax

    from csmom_tpu.analytics.bootstrap import block_bootstrap_grid

    if key is None:
        key = jax.random.PRNGKey(0)
    spreads = np.nan_to_num(np.asarray(spreads, dtype=float))
    live = np.asarray(live, dtype=bool)
    res = block_bootstrap_grid(
        spreads, live, key, n_samples=n_samples, block_len=block_len,
        freq=freq, ci_level=ci_level,
    )
    ci = np.asarray(res.mean_ci)  # [2, nJ, nK]
    idx = pd.Index([int(j) for j in np.asarray(Js)], name="J")
    cols = pd.Index([int(k) for k in np.asarray(Ks)], name="K")
    return (
        pd.DataFrame(ci[0], index=idx, columns=cols),
        pd.DataFrame(ci[1], index=idx, columns=cols),
    )


def horizon_table(hp, group: int = 6) -> pd.DataFrame:
    """Event-time profile table (Lee–Swaminathan Tables VI–VIII shape:
    momentum by months-since-formation, persistence then reversal).

    Args:
      hp: :class:`csmom_tpu.backtest.horizon.HorizonProfile`.
      group: horizons per printed bucket (6 -> half-year rows); per-month
        rows when 1.

    Returns a DataFrame indexed by horizon bucket with the bucket's mean
    monthly spread, its NW t-stat range, cohort counts, and the cumulative
    event-time spread at the bucket end.
    """
    mean_h = np.asarray(hp.mean_spread, dtype=float)
    t_h = np.asarray(hp.tstat_nw, dtype=float)
    n_h = np.asarray(hp.n_cohorts)
    cum = np.asarray(hp.cum_spread, dtype=float)
    H = len(mean_h)
    rows = {}
    for lo in range(0, H, group):
        hi = min(lo + group, H)
        label = f"m{lo + 1}" if hi == lo + 1 else f"m{lo + 1}-{hi}"
        seg = mean_h[lo:hi]
        ok = np.isfinite(seg)
        t_ok = np.isfinite(t_h[lo:hi]).any()  # t can be NaN where n<=1
        rows[label] = {
            "mean_spread": float(np.mean(seg[ok])) if ok.any() else np.nan,
            "t_nw_min": float(np.nanmin(t_h[lo:hi])) if t_ok else np.nan,
            "t_nw_max": float(np.nanmax(t_h[lo:hi])) if t_ok else np.nan,
            "cohorts": int(n_h[lo:hi].max()),
            "cum_spread": float(cum[hi - 1]),
        }
    return pd.DataFrame(rows).T


def tercile_labels(V: int) -> list[str]:
    """Display names for volume groups, shared by tables and plots so the
    legend and columns can't drift: V1 (low) .. V{V} (high)."""
    if V == 1:
        return ["V1"]
    return (["V1 (low)"] + [f"V{v + 1}" for v in range(1, V - 1)]
            + [f"V{V} (high)"])


def volume_horizon_table(vhp, group: int = 6) -> pd.DataFrame:
    """Momentum life-cycle table (LeSw00 Table VIII shape): event-time mean
    spread per volume tercile, bucketed by horizon, with the high-minus-low
    volume contrast — the late-stage-reversal signature is V_high falling
    below V_low at long horizons.

    Args:
      vhp: :class:`csmom_tpu.backtest.horizon.VolumeHorizonProfile`.
      group: horizons per bucket.
    """
    mean_vh = np.asarray(vhp.mean_spread, dtype=float)   # [V, H]
    diff = np.asarray(vhp.diff_mean, dtype=float)        # [H]
    dt = np.asarray(vhp.diff_tstat_nw, dtype=float)
    V, H = mean_vh.shape
    rows = {}
    for lo in range(0, H, group):
        hi = min(lo + group, H)
        label = f"m{lo + 1}" if hi == lo + 1 else f"m{lo + 1}-{hi}"
        row = {}
        names = tercile_labels(V)
        for v in range(V):
            seg = mean_vh[v, lo:hi]
            ok = np.isfinite(seg)
            row[names[v]] = float(np.mean(seg[ok])) if ok.any() else np.nan
        seg_d = diff[lo:hi]
        ok_d = np.isfinite(seg_d)
        row["Vhigh-Vlow"] = float(np.mean(seg_d[ok_d])) if ok_d.any() else np.nan
        t_seg = dt[lo:hi]
        if np.isfinite(t_seg).any():
            # signed t at max |t|: the reversal signature is this turning
            # significantly NEGATIVE at long horizons, so the sign matters
            row["diff_t_nw"] = float(t_seg[np.nanargmax(np.abs(t_seg))])
        else:
            row["diff_t_nw"] = np.nan
        rows[label] = row
    return pd.DataFrame(rows).T


def double_sort_table(ds, freq: int = 12,
                      half_spread_bps: float | None = None) -> pd.DataFrame:
    """Momentum spread by volume tercile (paper Table II shape).

    Args:
      ds: :class:`csmom_tpu.backtest.double_sort.DoubleSortResult`.
      half_spread_bps: when given, each tercile row also carries its book's
        mean |dw| turnover, the spread net of linear costs at this
        half-spread, and the break-even half-spread (the bps level at
        which that tercile's gross mean is fully consumed) — the same
        cost treatment the replicate/grid paths print.

    Returns a DataFrame indexed V1 (low volume) .. V{n} (high volume) with
    mean spread, Sharpe, t-stat, months, and the high-minus-low volume
    difference row (the paper's "early/late stage" comparison).
    """
    spreads = np.asarray(ds.spreads, dtype=float)
    valid = np.asarray(ds.spread_valid, dtype=bool)
    V = spreads.shape[0]
    rows = {}
    names = tercile_labels(V)
    for v in range(V):
        x, m = _masked_rows(spreads[v], valid[v])
        r = _row_stats(x, m, freq)
        if half_spread_bps is not None:
            turn = np.asarray(ds.book_turnover, dtype=float)[v]
            # average over every month with book ACTIVITY, not just valid
            # months: a full-book unwind lands its |dw| on the first month
            # the book goes invalid, and dropping those months understates
            # turnover — overstating net_mean and be_bps
            active = valid[v] | (np.nan_to_num(turn) > 0)
            mt = float(np.mean(turn[active])) if active.any() else np.nan
            hs = half_spread_bps / 1e4
            r["mean_turnover"] = mt
            r["net_mean"] = r["mean_ret"] - hs * mt
            r["be_bps"] = (r["mean_ret"] / mt * 1e4) if mt > 0 else np.nan
        rows[names[v]] = r
    both = valid[V - 1] & valid[0]
    diff = np.where(both, spreads[V - 1] - spreads[0], np.nan)
    drow = _row_stats(*_masked_rows(diff, both), freq)
    if half_spread_bps is not None:
        # the diff row is a comparison, not a tradable book
        drow["mean_turnover"] = drow["net_mean"] = drow["be_bps"] = np.nan
    rows[f"V{V}-V1"] = drow
    return pd.DataFrame(rows).T
