"""Portfolio tearsheet: the risk/return summary the reference stops short of.

The reference's analytics layer is a single annualized Sharpe plus a
cumulative-return plot (``/root/reference/src/utils.py:8-21``,
``run_demo.py:72-79``); a user taking its strategies seriously immediately
needs the rest of the standard tearsheet — drawdown, Calmar, Sortino, hit
rate, tail risk, higher moments, per-year returns.  This module provides
them in the framework's house style: every statistic is a mask-aware
reduction over the LAST axis, so the same code summarizes one spread
series ``f[T]``, a J x K grid ``f[nJ, nK, T]``, or a bootstrap batch
``f[B, T]`` in one fused jit call with no Python branching on shape.

Masked periods are simply absent: compounding treats them as flat
(log-growth 0), counts use the valid-lane total, and order statistics
sort masked lanes to +inf and index by valid count.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.analytics.stats import (
    cumulative_growth,
    masked_mean,
    masked_std,
    sharpe,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Tearsheet:
    """All fields reduce the time axis; leading axes broadcast through."""

    ann_return: jnp.ndarray      # geometric, (prod(1+r))**(f/n) - 1
    ann_vol: jnp.ndarray         # std(ddof=1) * sqrt(f)
    ann_sharpe: jnp.ndarray      # reference semantics (utils.py:8-16)
    sortino: jnp.ndarray         # mean*f / (downside std * sqrt(f))
    max_drawdown: jnp.ndarray    # positive fraction (0.25 = -25% peak-to-trough)
    calmar: jnp.ndarray          # ann_return / max_drawdown
    hit_rate: jnp.ndarray        # P(r > 0) over valid periods
    skewness: jnp.ndarray        # biased (moment) estimator
    excess_kurtosis: jnp.ndarray # biased, Fisher (normal -> 0)
    var_95: jnp.ndarray          # 5th-percentile period return (a loss, < 0)
    cvar_95: jnp.ndarray         # mean return at or below var_95
    best: jnp.ndarray            # best single-period return
    worst: jnp.ndarray           # worst single-period return
    n_periods: jnp.ndarray       # i32 valid count


def max_drawdown(returns, valid):
    """Largest peak-to-trough loss of the compounded curve, as a positive
    fraction; masked periods compound as flat.  NaN when nothing is valid."""
    growth = cumulative_growth(returns, valid)
    # the running peak starts at the initial capital of 1.0: a curve that
    # declines from inception draws down against 1.0, not its own first point
    peak = jnp.maximum(jax.lax.associative_scan(jnp.maximum, growth, axis=-1), 1.0)
    dd = 1.0 - growth / peak
    mdd = jnp.max(jnp.where(valid, dd, 0.0), axis=-1)
    return jnp.where(jnp.any(valid, axis=-1), mdd, jnp.nan)


def _moment_stats(returns, valid):
    """Biased skewness and excess kurtosis (scipy.stats.skew/kurtosis with
    bias=True), masked."""
    n = jnp.sum(valid, axis=-1)
    mean = masked_mean(returns, valid)
    dev = jnp.where(valid, jnp.nan_to_num(returns) - mean[..., None], 0.0)
    nf = jnp.maximum(n, 1).astype(returns.dtype)
    m2 = jnp.sum(dev**2, axis=-1) / nf
    m3 = jnp.sum(dev**3, axis=-1) / nf
    m4 = jnp.sum(dev**4, axis=-1) / nf
    ok = (n > 2) & (m2 > 0)
    skew = jnp.where(ok, m3 / jnp.where(m2 > 0, m2, 1.0) ** 1.5, jnp.nan)
    kurt = jnp.where(ok, m4 / jnp.where(m2 > 0, m2, 1.0) ** 2 - 3.0, jnp.nan)
    return skew, kurt


def _tail_stats(returns, valid, q: float):
    """Historical VaR (the ceil(q*n)-th worst return) and CVaR (mean of
    returns at or below it).  Lower-tail convention: both are returns, so a
    5% VaR of -0.02 reads 'the worst 5% of periods lose at least 2%'."""
    big = jnp.asarray(jnp.finfo(returns.dtype).max, returns.dtype)
    x = jnp.where(valid, jnp.nan_to_num(returns), big)
    xs = jnp.sort(x, axis=-1)
    n = jnp.sum(valid, axis=-1)
    # snap q*n before the ceil: float representation error (0.05*240 =
    # 12.000000000000002 in f64, exactly 12.0 in f32) would otherwise make
    # the tail count dtype-dependent exactly when q*n is an integer
    k = jnp.maximum(jnp.ceil(q * n - 1e-6).astype(jnp.int32), 1)
    idx = jnp.minimum(k - 1, x.shape[-1] - 1)
    var = jnp.take_along_axis(xs, idx[..., None], axis=-1)[..., 0]
    in_tail = jnp.arange(x.shape[-1]) < k[..., None]
    cvar = jnp.sum(jnp.where(in_tail, xs, 0.0), axis=-1) / k.astype(returns.dtype)
    ok = n > 0
    return jnp.where(ok, var, jnp.nan), jnp.where(ok, cvar, jnp.nan)


@partial(jax.jit, static_argnames=("freq_per_year",))
def tearsheet(returns, valid, freq_per_year: int = 12) -> Tearsheet:
    """Full tearsheet of a masked return series (last axis = time)."""
    dt = returns.dtype
    n = jnp.sum(valid, axis=-1)
    nf = jnp.maximum(n, 1).astype(dt)
    f = jnp.asarray(freq_per_year, dt)

    log_total = jnp.sum(jnp.where(valid, jnp.log1p(returns), 0.0), axis=-1)
    ann_ret = jnp.where(n > 0, jnp.expm1(log_total * f / nf), jnp.nan)
    sd = masked_std(returns, valid, ddof=1)
    ann_vol = sd * jnp.sqrt(f)

    mean = masked_mean(returns, valid)
    down = jnp.where(valid & (returns < 0), jnp.nan_to_num(returns), 0.0)
    dstd = jnp.sqrt(jnp.sum(down**2, axis=-1) / nf)
    sortino = jnp.where(dstd > 0, mean * f / (dstd * jnp.sqrt(f)), jnp.nan)

    mdd = max_drawdown(returns, valid)
    calmar = jnp.where(mdd > 0, ann_ret / mdd, jnp.nan)
    hit = jnp.where(
        n > 0, jnp.sum(valid & (returns > 0), axis=-1) / nf, jnp.nan
    )
    skew, kurt = _moment_stats(returns, valid)
    var95, cvar95 = _tail_stats(returns, valid, 0.05)
    neg_big = jnp.asarray(jnp.finfo(dt).min, dt)
    big = jnp.asarray(jnp.finfo(dt).max, dt)
    best = jnp.where(
        n > 0, jnp.max(jnp.where(valid, jnp.nan_to_num(returns), neg_big), axis=-1),
        jnp.nan,
    )
    worst = jnp.where(
        n > 0, jnp.min(jnp.where(valid, jnp.nan_to_num(returns), big), axis=-1),
        jnp.nan,
    )

    return Tearsheet(
        ann_return=ann_ret,
        ann_vol=ann_vol,
        ann_sharpe=sharpe(returns, valid, freq_per_year=freq_per_year),
        sortino=sortino,
        max_drawdown=mdd,
        calmar=calmar,
        hit_rate=hit,
        skewness=skew,
        excess_kurtosis=kurt,
        var_95=var95,
        cvar_95=cvar95,
        best=best,
        worst=worst,
        n_periods=n.astype(jnp.int32),
    )


def annual_returns(returns, valid, years):
    """Compound per-calendar-year returns.

    Args:
      returns: f[..., T] period returns.
      valid: bool[..., T].
      years: i32[T] calendar-year label per period (need not be contiguous).

    Returns ``(uniq_years i32[Y], ann f[..., Y], any_valid bool[..., Y])``
    with Y = number of distinct labels, sorted ascending; years with no
    valid periods report NaN.  Uses a one-hot matmul over the (small) year
    axis, so it fuses like everything else.
    """
    years = jnp.asarray(years)
    uniq = jnp.unique(years)  # host-side: year labels are concrete
    onehot = (years[None, :] == uniq[:, None]).astype(returns.dtype)  # [Y, T]
    lr = jnp.where(valid, jnp.log1p(returns), 0.0)
    ann = jnp.expm1(jnp.einsum("...t,yt->...y", lr, onehot))
    any_valid = jnp.einsum(
        "...t,yt->...y", valid.astype(returns.dtype), onehot
    ) > 0
    return uniq, jnp.where(any_valid, ann, jnp.nan), any_valid


def format_tearsheet(ts: Tearsheet, label: str = "portfolio") -> str:
    """Plain-text rendering of a scalar tearsheet (CLI surface)."""
    import numpy as np

    def pct(v):
        v = float(np.asarray(v))
        return "n/a" if not np.isfinite(v) else f"{v * 100:+.2f}%"

    def num(v):
        v = float(np.asarray(v))
        return "n/a" if not np.isfinite(v) else f"{v:.2f}"

    rows = [
        ("Ann. return", pct(ts.ann_return)),
        ("Ann. vol", pct(ts.ann_vol)),
        ("Sharpe", num(ts.ann_sharpe)),
        ("Sortino", num(ts.sortino)),
        ("Max drawdown", pct(-np.asarray(ts.max_drawdown))),
        ("Calmar", num(ts.calmar)),
        ("Hit rate", pct(ts.hit_rate)),
        ("Skew", num(ts.skewness)),
        ("Excess kurtosis", num(ts.excess_kurtosis)),
        ("VaR 95 (period)", pct(ts.var_95)),
        ("CVaR 95 (period)", pct(ts.cvar_95)),
        ("Best period", pct(ts.best)),
        ("Worst period", pct(ts.worst)),
        ("Periods", str(int(np.asarray(ts.n_periods)))),
    ]
    w = max(len(k) for k, _ in rows)
    head = f"-- tearsheet: {label} --"
    return "\n".join([head] + [f"{k:<{w}}  {v}" for k, v in rows])
