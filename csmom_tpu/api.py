"""High-level convenience pipeline: CSV caches -> panels -> backtests.

This is the glue the reference keeps inline in ``run_demo.py``; kept thin so
each stage stays independently usable and testable.
"""

from __future__ import annotations

import numpy as np

from csmom_tpu.panel import ingest
from csmom_tpu.panel.calendar import (
    month_end_segments,
    month_end_aggregate,
    segment_sum_panel,
)
from csmom_tpu.panel.panel import Panel


def monthly_price_panel(data_dir: str, tickers, field: str = "adj_close"):
    """Daily CSV caches -> month-end price & volume panels.

    Returns ``(prices Panel[A, M], volume Panel[A, M])`` with month-end
    timestamps, mirroring ``compute_monthly_momentum_from_daily``'s
    aggregation (``features.py:34-39``).
    """
    df = ingest.load_daily(data_dir, tickers)
    price_daily = ingest.long_to_panel(df, field, time_col="date")
    vol_daily = ingest.long_to_panel(
        df, "volume", time_col="date",
        tickers=price_daily.tickers, times=price_daily.times,
    )
    seg, month_ends = month_end_segments(price_daily.times)
    m = len(month_ends)

    pv, pm = price_daily.device()
    prices_m, mask_m = month_end_aggregate(pv, pm, seg, m)
    vv, vm = vol_daily.device()
    vol_m = segment_sum_panel(vv, vm, seg, m)
    # a month is a valid volume observation iff >=1 daily bar existed; a
    # phantom 0 with mask=True would rank pre-listing months into the bottom
    # volume decile of a turnover sort
    vol_obs = np.asarray(segment_sum_panel(vm.astype(vv.dtype), vm, seg, m)) > 0

    prices = Panel(
        values=np.asarray(prices_m),
        mask=np.asarray(mask_m),
        tickers=price_daily.tickers,
        times=month_ends,
        name=f"month_end_{field}",
    )
    volume = Panel(
        values=np.asarray(vol_m),
        mask=vol_obs,
        tickers=price_daily.tickers,
        times=month_ends,
        name="monthly_volume",
    )
    return prices, volume
