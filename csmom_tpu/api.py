"""High-level convenience pipeline: CSV caches -> panels -> backtests.

This is the glue the reference keeps inline in ``run_demo.py``; kept thin so
each stage stays independently usable and testable.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from csmom_tpu.panel import ingest
from csmom_tpu.panel.calendar import (
    month_end_segments,
    month_end_aggregate,
    segment_sum_panel,
)
from csmom_tpu.panel.panel import Panel


def monthly_price_panel(data_dir: str, tickers, field: str = "adj_close",
                        daily_df=None):
    """Daily CSV caches OR a packed panel directory -> month-end panels.

    Returns ``(prices Panel[A, M], volume Panel[A, M])`` with month-end
    timestamps, mirroring ``compute_monthly_momentum_from_daily``'s
    aggregation (``features.py:34-39``).  Pass ``daily_df`` (a canonical
    long frame from :func:`csmom_tpu.panel.ingest.load_daily`) to reuse an
    already-loaded universe instead of re-reading the CSV cache.

    When ``data_dir`` is a packed directory (see
    :func:`csmom_tpu.panel.pack.is_packed`), the dense panels are memmapped
    straight from it: no CSV parsing at all, which is the at-scale path
    (``csmom fetch --pack`` writes it; every monthly-panel CLI subcommand
    — replicate/grid/doublesort/sweep/horizons/residual — then accepts the
    pack as its ``--data-dir``; the intraday pipeline still needs minute
    CSV caches, which packs do not hold).  ``tickers`` selects a subset of
    the pack; pass an empty/None universe to take every packed ticker.
    """
    from csmom_tpu.panel.pack import is_packed

    if daily_df is None and is_packed(data_dir):
        from csmom_tpu.panel.pack import load_packed

        bundle = load_packed(data_dir)
        if isinstance(bundle, Panel):  # single-field pack: no volume leg
            raise ValueError(
                f"packed panel {data_dir} holds only {bundle.name!r}; the "
                f"monthly pipeline needs {field!r} and 'volume' — repack "
                "with both fields (csmom fetch --pack does)"
            )
        for need in (field, "volume"):
            if need not in bundle:
                raise ValueError(
                    f"packed panel {data_dir} lacks field {need!r} "
                    f"(has {', '.join(bundle.fields)}) — repack with it"
                )
        price_daily = bundle[field]
        vol_daily = bundle["volume"]
        if tickers:
            want = set(tickers)
            missing = sorted(want - set(price_daily.tickers))
            if missing:
                raise ValueError(
                    f"packed panel {data_dir} lacks {len(missing)} requested "
                    f"tickers: {','.join(missing[:8])}"
                )
            # sorted, like the CSV path's ingest pivot: the two sources must
            # return identical row order for the same request
            keep = sorted(t for t in price_daily.tickers if t in want)
            price_daily = price_daily.select_assets(keep)
            vol_daily = vol_daily.select_assets(keep)
    else:
        df = (daily_df if daily_df is not None
              else ingest.load_daily(data_dir, tickers))
        price_daily = ingest.long_to_panel(df, field, time_col="date")
        vol_daily = ingest.long_to_panel(
            df, "volume", time_col="date",
            tickers=price_daily.tickers, times=price_daily.times,
        )
    seg, month_ends = month_end_segments(price_daily.times)
    m = len(month_ends)

    pv, pm = price_daily.device()
    prices_m, mask_m = month_end_aggregate(pv, pm, seg, m)
    vv, vm = vol_daily.device()
    vol_m = segment_sum_panel(vv, vm, seg, m)
    # a month is a valid volume observation iff >=1 daily bar existed; a
    # phantom 0 with mask=True would rank pre-listing months into the bottom
    # volume decile of a turnover sort
    vol_obs = np.asarray(segment_sum_panel(vm.astype(vv.dtype), vm, seg, m)) > 0

    prices = Panel(
        values=np.asarray(prices_m),
        mask=np.asarray(mask_m),
        tickers=price_daily.tickers,
        times=month_ends,
        name=f"month_end_{field}",
    )
    volume = Panel(
        values=np.asarray(vol_m),
        mask=vol_obs,
        tickers=price_daily.tickers,
        times=month_ends,
        name="monthly_volume",
    )
    return prices, volume


def synthetic_minute_frame(daily_df, minutes_per_day: int = 390, seed: int = 0):
    """Synthetic 1-minute bars from daily OHLCV, as a canonical long frame.

    Vectorized replacement for the reference's per-minute dict-append loop
    (``data_io.py:251-300``, its third-hottest loop): same construction —
    linear open->close path x (1 + N(0, 5e-4)) noise, sin^2 U-curve volume —
    via one ``synthetic_minute_bars`` call per universe.
    """
    import pandas as pd

    from csmom_tpu.panel.synthetic import synthetic_minute_bars

    if daily_df is None or len(daily_df) == 0:
        return pd.DataFrame(columns=["datetime", "ticker", "price", "volume"])

    tickers = sorted(daily_df["ticker"].unique())
    days = np.sort(daily_df["date"].unique())
    open_p = ingest.long_to_panel(daily_df, "open", "date", tickers, days)
    close_p = ingest.long_to_panel(daily_df, "close", "date", tickers, days)
    vol_p = ingest.long_to_panel(daily_df, "volume", "date", tickers, days)

    ok = np.isfinite(open_p.values) & np.isfinite(close_p.values)
    vols = np.where(np.isfinite(vol_p.values) & (vol_p.values > 0), vol_p.values, 1.0)
    prices, volumes = synthetic_minute_bars(
        np.nan_to_num(open_p.values), np.nan_to_num(close_p.values), vols,
        minutes_per_day=minutes_per_day, seed=seed,
    )

    minute_offsets = (
        np.timedelta64(9 * 60 + 30, "m") + np.arange(minutes_per_day) * np.timedelta64(1, "m")
    )
    stamps = days.astype("datetime64[D]")[None, :, None] + minute_offsets[None, None, :]
    A, D, T = prices.shape
    keep = np.broadcast_to(ok[:, :, None], (A, D, T))
    tick = np.broadcast_to(np.asarray(tickers, dtype=object)[:, None, None], (A, D, T))
    return pd.DataFrame(
        {
            "datetime": np.broadcast_to(stamps, (A, D, T))[keep],
            "ticker": tick[keep],
            "price": prices[keep],
            "volume": volumes[keep].astype(float),
        }
    )


def daily_risk_maps(daily_df, tickers):
    """Per-asset ADV and daily-return vol vectors with reference fallbacks.

    Mirrors the sidecar maps of ``run_demo.py:96-125``: ADV = mean daily
    volume (fallback 100,000 when missing or <= 0); vol = std (ddof=1) of
    daily pct_change of adj_close (fallback 0.02).  An asset absent from the
    daily frame entirely gets both fallbacks — exactly what happens to AAPL
    in the reference's own run, where its daily cache fails to load but its
    intraday cache trades.
    """
    from csmom_tpu.backtest.event import DEFAULT_ADV, DEFAULT_VOL

    adv = np.full(len(tickers), DEFAULT_ADV)
    vol = np.full(len(tickers), DEFAULT_VOL)
    if daily_df is not None and len(daily_df):
        adv_s = daily_df.groupby("ticker")["volume"].mean()
        ret = daily_df.groupby("ticker")["adj_close"].pct_change()
        vol_s = ret.groupby(daily_df["ticker"]).std()
        for i, t in enumerate(tickers):
            a = adv_s.get(t, np.nan)
            if np.isfinite(a) and a > 0:
                adv[i] = float(a)
            v = vol_s.get(t, np.nan)
            if np.isfinite(v) and v > 0:
                vol[i] = float(v)
    return adv, vol


def intraday_pipeline(
    minute_df,
    daily_df,
    window_minutes: int = 30,
    n_splits: int = 3,
    alpha: float | None = None,
    size_shares: int = 50,
    threshold: float = 1e-5,
    cash0: float = 1_000_000.0,
    dtype=np.float64,
    model: str = "ridge",
    l1_ratio: float = 0.5,
    latency_bars: int = 0,
):
    """Minute bars -> features -> model scores -> event backtest.

    The panel-world equivalent of ``intraday_pipeline`` + ``backtest_run``
    (``run_demo.py:81-191``).  ``model`` selects the score model:
    ``'ridge'`` (the reference's, ``models.py:8-22``), ``'online_ridge'``
    (leak-free walk-forward via one Sherman-Morrison scan —
    models/online_ridge.py), ``'elastic_net'``
    / ``'lasso'`` (sparse extensions; ``alpha``/``l1_ratio`` apply), or
    ``'mlp'`` (nonlinear extension; ``alpha`` is its weight decay).
    Note the scales differ: ridge's ``alpha`` is the reference's 1.0, but
    the elastic-net objective is per-row and minute returns are ~1e-4, so
    useful l1 penalties live around 1e-9..1e-7 (larger zeroes every
    coefficient and the strategy goes flat).  ``alpha=None`` therefore
    resolves per model — 1.0 for ridge (``run_demo.py:140``), 1e-8 for
    elastic_net/lasso, 1e-4 (weight decay) for mlp — so API and CLI
    callers get the same sane defaults.
    Returns (EventResult, fit, compact, dense_score, dense_price,
    dense_valid) — ``fit`` is the selected model's fit object (RidgeFit
    for the batch linear family, OnlineRidgeFit for ``'online_ridge'``,
    MLPFit for ``'mlp'``; distinct dataclasses, but all carry
    ``scores`` / ``cv_mse`` / ``n_train``).
    """
    from csmom_tpu.signals.intraday import compact_minutes, minute_features, next_row_return
    from csmom_tpu.models import (
        as_ridge_fit,
        elastic_net_time_series_cv,
        mlp_time_series_cv,
        online_ridge_scores,
        ridge_time_series_cv,
    )
    from csmom_tpu.backtest.event import event_backtest

    if minute_df is None or len(minute_df) == 0:
        # reference behaviour: no live intraday data -> synthesize minutes
        # from daily bars (run_demo.py:82-84 -> data_io.py:251-300)
        minute_df = synthetic_minute_frame(daily_df)
        if len(minute_df) == 0:
            raise ValueError(
                "intraday_pipeline: no intraday rows and no daily bars to "
                "synthesize a fallback from"
            )
    if alpha is None:
        # per-model scales: ridge's 1.0 is the reference's (run_demo.py:140);
        # elastic-net penalties are per-row on ~1e-4 labels; for the MLP,
        # alpha is AdamW weight decay; online_ridge standardizes causally so
        # ridge's unit penalty carries over
        alpha = {"ridge": 1.0, "online_ridge": 1.0, "mlp": 1e-4}.get(model, 1e-8)
    compact = compact_minutes(minute_df)
    price = jnp.asarray(compact.price, dtype)
    volume = jnp.asarray(compact.volume, dtype)
    row_valid = jnp.asarray(compact.row_valid)

    feats, feat_valid = minute_features(price, volume, row_valid, window=window_minutes)
    y, y_valid = next_row_return(price, feat_valid)
    if model == "ridge":
        fit = ridge_time_series_cv(feats, y, y_valid, n_splits=n_splits, alpha=alpha)
    elif model == "online_ridge":
        # leak-free walk-forward: every score strictly out-of-sample
        # (the reference's scaffold scores its own training rows —
        # run_demo.py:139-147; this is the causal counterpart)
        fit = online_ridge_scores(feats, y, y_valid, n_splits=n_splits,
                                  alpha=alpha)
    elif model in ("elastic_net", "lasso"):
        enet = elastic_net_time_series_cv(
            feats, y, y_valid, n_splits=n_splits, alpha=alpha,
            l1_ratio=1.0 if model == "lasso" else l1_ratio,
        )
        if int(enet.n_nonzero) == 0:
            import logging

            logging.getLogger("csmom_tpu.api").warning(
                "%s with alpha=%g zeroed every coefficient — scores are the "
                "intercept only and the strategy will be (nearly) flat; "
                "minute-return labels are ~1e-4, so useful l1 penalties are "
                "~1e-9..1e-7", model, alpha,
            )
        fit = as_ridge_fit(enet)
    elif model == "mlp":
        fit = mlp_time_series_cv(feats, y, y_valid, n_splits=n_splits,
                                 weight_decay=alpha)
    else:
        raise ValueError(
            f"unknown model {model!r} (expected 'ridge', 'online_ridge', "
            f"'elastic_net', 'lasso', or 'mlp')"
        )

    # scatter compacted rows onto the global minute axis; padded/non-model
    # rows are routed to a spill column that is sliced off
    A, R = compact.price.shape
    T = len(compact.times)
    rows = jnp.arange(A)[:, None]
    cols = jnp.where(y_valid, jnp.asarray(compact.time_idx), T)

    def scatter(vals, fillv=np.nan):
        out = jnp.full((A, T + 1), fillv, dtype)
        out = out.at[rows, cols].set(vals.astype(dtype))
        return out[:, :T]

    dense_score = scatter(fit.scores)
    dense_price = scatter(price)
    dense_valid = jnp.zeros((A, T + 1), bool).at[rows, cols].set(y_valid)[:, :T]

    adv, vol = daily_risk_maps(daily_df, compact.tickers)
    result = event_backtest(
        dense_price,
        dense_valid,
        jnp.nan_to_num(dense_score),
        jnp.asarray(adv, dtype),
        jnp.asarray(vol, dtype),
        size_shares=size_shares,
        threshold=threshold,
        cash0=cash0,
        latency_bars=latency_bars,
    )
    return result, fit, compact, dense_score, dense_price, dense_valid
