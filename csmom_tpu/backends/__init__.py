"""Engine backends behind one API.

The north-star constraint (BASELINE.json) is a ``backend='tpu'`` path
*alongside* a pandas engine, both behind the same interface, so the CLI,
results schema and analytics are backend-agnostic.  ``run_monthly`` is that
interface; ``pandas_engine`` is the reference-semantics CPU engine.
"""

from csmom_tpu.backends.dispatch import run_monthly, MonthlyReport
from csmom_tpu.backends.pandas_engine import monthly_spread_backtest_pandas

__all__ = ["run_monthly", "MonthlyReport", "monthly_spread_backtest_pandas"]
