"""One monthly-backtest API over two engines: ``backend='tpu' | 'pandas'``.

The north-star constraint: the accelerated path lands *behind* the existing
interface so callers (CLI, analytics, plots) never branch on engine.  Both
engines consume a :class:`~csmom_tpu.panel.panel.Panel` and return the same
:class:`MonthlyReport` host-side schema; the golden-parity test pins them to
each other.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from csmom_tpu.panel.panel import Panel


@dataclasses.dataclass(frozen=True)
class MonthlyReport:
    """Backend-agnostic monthly backtest report (host types only).

    The results schema mirrors what the reference prints/plots
    (``run_demo.py:72-79``): the spread series, its mean / annualized Sharpe,
    plus the decile detail the paper tabulates.
    """

    times: np.ndarray          # [M] month-end timestamps
    spread: np.ndarray         # f[M], NaN = invalid month
    decile_means: np.ndarray   # f[n_bins, M]
    decile_counts: np.ndarray  # i[n_bins, M]
    labels: np.ndarray         # i[A, M], -1 invalid
    mean_spread: float
    ann_sharpe: float
    tstat: float
    tstat_nw: float
    backend: str

    def spread_series(self):
        """The spread as a pandas Series (reference's ``spread`` variable,
        ``run_demo.py:60-67``)."""
        import pandas as pd

        return pd.Series(self.spread, index=self.times, name="spread").dropna()


def run_monthly(
    panel: Panel,
    lookback: int = 12,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    freq: int = 12,
    backend: str = "tpu",
    strategy=None,
    sector_ids=None,
    n_sectors: int = 0,
    **panels,
) -> MonthlyReport:
    """Run the monthly decile backtest on the requested engine.

    Args:
      panel: month-end price Panel [A, M].
      backend: ``'tpu'`` (jit-compiled panel engine, the default) or
        ``'pandas'`` (reference-semantics CPU engine).
      mode: ranking mode, TPU engine only ('qcut' parity / 'rank' fast).
      strategy: optional :class:`csmom_tpu.strategy.Strategy` plugin; when
        None the reference's momentum signal (``lookback``/``skip``) runs.
        Extra ``**panels`` (e.g. ``volumes=``) are forwarded to its
        ``signal``.  Either engine ranks the plugged-in scores through the
        same tail, so callers never branch on signal choice.
      sector_ids: optional i32[A] sector id per asset (negative =
        unclassified, excluded from ranking) with ``n_sectors`` the id
        count (required, >= 1) — switches the TPU engine to
        sector-neutral ranking (BASELINE config 3), with or without a
        ``strategy`` (any plugged-in signal ranks within sectors).  Not
        supported on the pandas backend.
    """
    if sector_ids is not None and (n_sectors is None or int(n_sectors) < 1):
        raise ValueError(
            "sector_ids requires n_sectors >= 1 (the sector id count)"
        )
    if strategy is None and panels:
        raise TypeError(
            f"unexpected keyword arguments {sorted(panels)} — extra panels are "
            "only forwarded to a strategy plugin (did you misspell a parameter, "
            "or forget strategy=?)"
        )
    if strategy is not None and panels:
        from csmom_tpu.strategy import consumed_panels

        allowed = consumed_panels(strategy)
        unknown = sorted(set(panels) - allowed)
        if unknown:
            raise TypeError(
                f"panel kwarg(s) {unknown} match no signal parameter of "
                f"{type(strategy).__name__} (accepts: {sorted(allowed) or None}) "
                "— misspelled? A strategy's **panels catch-all exists to ignore "
                "panels other strategies need, not to swallow typos."
            )
    if sector_ids is not None and backend != "tpu":
        raise NotImplementedError(
            "sector-neutral ranking runs on the TPU engine only "
            "(backend='tpu'; works with or without strategy=)"
        )
    if backend == "tpu":
        from csmom_tpu.backtest import monthly_spread_backtest

        v, m = panel.device()
        if strategy is not None:
            from csmom_tpu.strategy import strategy_backtest

            sector_kw = {}
            if sector_ids is not None:
                sector_kw = dict(
                    sector_ids=np.asarray(sector_ids, np.int32),
                    n_sectors=int(n_sectors),
                )
            res = strategy_backtest(
                v, m, strategy, n_bins=n_bins, mode=mode, freq=freq,
                **sector_kw, **panels,
            )
        elif sector_ids is not None:
            from csmom_tpu.backtest import sector_neutral_backtest

            res = sector_neutral_backtest(
                v, m, np.asarray(sector_ids, np.int32), int(n_sectors),
                lookback=lookback, skip=skip, n_bins=n_bins, mode=mode,
                freq=freq,
            )
        else:
            res = monthly_spread_backtest(
                v, m, lookback=lookback, skip=skip, n_bins=n_bins, mode=mode, freq=freq
            )
        spread = np.where(np.asarray(res.spread_valid), np.asarray(res.spread), np.nan)
        return MonthlyReport(
            times=panel.times,
            spread=spread,
            decile_means=np.asarray(res.decile_means),
            decile_counts=np.asarray(res.decile_counts),
            labels=np.asarray(res.labels),
            mean_spread=float(res.mean_spread),
            ann_sharpe=float(res.ann_sharpe),
            tstat=float(res.tstat),
            tstat_nw=float(res.tstat_nw),
            backend="tpu",
        )
    if backend == "pandas":
        if strategy is not None:
            from csmom_tpu.strategy import strategy_backtest_pandas

            res = strategy_backtest_pandas(
                panel.to_dataframe(), strategy, n_bins=n_bins, freq=freq, **panels
            )
        else:
            from csmom_tpu.backends.pandas_engine import monthly_spread_backtest_pandas

            res = monthly_spread_backtest_pandas(
                panel.to_dataframe(), lookback=lookback, skip=skip, n_bins=n_bins,
                freq=freq,
            )
        return MonthlyReport(
            times=panel.times,
            spread=res.spread.to_numpy(),
            decile_means=res.decile_means.to_numpy(),
            decile_counts=res.decile_counts.to_numpy(),
            labels=res.labels.to_numpy(),
            mean_spread=res.mean_spread,
            ann_sharpe=res.ann_sharpe,
            tstat=res.tstat,
            tstat_nw=res.tstat_nw,
            backend="pandas",
        )
    raise ValueError(f"unknown backend {backend!r} (expected 'tpu' or 'pandas')")
