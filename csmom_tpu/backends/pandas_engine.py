"""Pandas engine: reference-semantics monthly backtest on the CPU.

Implements the same monthly momentum replication as
:func:`csmom_tpu.backtest.monthly_spread_backtest`, but in pandas over the
masked panel's wide-DataFrame view — the engine a reference user runs where
no accelerator exists, and the oracle the TPU engine is tested against.

Semantics follow the reference pipeline exactly (independently re-derived,
not copied): per-ticker ``pct_change`` monthly returns over *surviving*
months (``/root/reference/src/features.py:44`` — pandas bridges masked gaps
by operating on present rows only), momentum as the compounded J-month
return ending ``skip`` months before formation with NaN warmup propagation
(``features.py:47-52``: the leading ``pct_change`` NaN poisons every window
containing it, so the first signal lands at month J+skip+1 — SURVEY
§2.1.2), per-date ``qcut(duplicates='drop')`` deciles with the ordinal-rank
fallback (``run_demo.py:18-29``), and the equal-weighted top-minus-bottom
next-month spread (``run_demo.py:46-73``).

One deliberate, documented deviation mirrors the TPU engine: ``next_ret``
is the *calendar* next month's return (valid only when both consecutive
month-ends exist), not the next-surviving-row return — the reference's
post-filter ``pct_change().shift(-1)`` silently spans multi-month gaps
(SURVEY §2.1.5); on gap-free panels the two are identical.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd


@dataclasses.dataclass(frozen=True)
class PandasMonthlyResult:
    """Mirror of :class:`csmom_tpu.backtest.MonthlyResult` in host types."""

    spread: pd.Series           # indexed by month-end timestamp (NaN = invalid)
    decile_means: pd.DataFrame  # [n_bins x M]
    decile_counts: pd.DataFrame
    labels: pd.DataFrame        # [A x M], -1 invalid
    mean_spread: float
    ann_sharpe: float
    tstat: float
    tstat_nw: float


def _nw_tstat_1d(sv: np.ndarray, lags: int | None = None) -> float:
    """Newey–West (Bartlett) t-stat of the mean of a dense 1-d series.

    Independent numpy implementation of the convention documented in
    :func:`csmom_tpu.analytics.stats.nw_t_stat` (gammas normalized by n, no
    small-sample correction, automatic bandwidth floor(4*(n/100)^(2/9)) when
    ``lags`` is None) — serving as the host-side oracle the backend-parity
    tests compare the kernel against.
    """
    sv = np.asarray(sv, dtype=float)
    n = len(sv)
    if n < 2:
        return float("nan")
    u = sv - sv.mean()
    L = int(np.floor(4.0 * (n / 100.0) ** (2.0 / 9.0))) if lags is None else int(lags)
    L = min(L, n - 1)
    lrv = float(u @ u) / n
    for lag in range(1, L + 1):
        w = 1.0 - lag / (L + 1.0)
        lrv += 2.0 * w * float(u[lag:] @ u[:-lag]) / n
    if lrv <= 0:
        return float("nan")
    return float(sv.mean() / np.sqrt(lrv / n))


def _qcut_labels_1d(vals: pd.Series, n_bins: int) -> pd.Series:
    """Reference decile assignment on one cross-section
    (``run_demo.py:18-29``): qcut with duplicates dropped, rank fallback."""
    out = pd.Series(-1, index=vals.index, dtype=int)
    sv = vals.dropna()
    if sv.empty:
        return out
    try:
        labels = pd.qcut(sv, q=n_bins, labels=False, duplicates="drop")
    except ValueError:
        ranks = sv.rank(method="first", pct=True)
        labels = np.minimum(np.floor(ranks * n_bins), n_bins - 1)
    labels = pd.Series(labels, index=sv.index)
    good = labels.notna()
    out.loc[labels.index[good]] = labels[good].astype(int)
    return out


def _momentum_frame(prices: pd.DataFrame, lookback: int, skip: int) -> pd.DataFrame:
    """Compounded J-month momentum ended ``skip`` months back, per row.

    ``prices`` is wide [A x M].  Computed per ticker over surviving columns
    via log1p prefix sums with a NaN-poisoning guard, which is arithmetically
    identical to ``shift(skip).rolling(J, min_periods=1).apply(prod-1)`` on
    gapless monthly returns (the leading pct_change NaN makes every partial
    window NaN, so min_periods=1 never bites at the head — SURVEY §2.1.2).
    """
    mom = pd.DataFrame(np.nan, index=prices.index, columns=prices.columns)
    for ticker, row in prices.iterrows():
        s = row.dropna()
        if len(s) < 2:
            continue
        ret = s.pct_change()
        log_g = np.log1p(ret.fillna(0.0))
        csum = log_g.cumsum()
        nan_c = ret.isna().astype(int).cumsum()
        m = np.exp(csum.shift(skip) - csum.shift(skip + lookback)) - 1.0
        # windows containing any NaN return (i.e. the first row) are invalid
        poisoned = (nan_c.shift(skip) - nan_c.shift(skip + lookback)) != 0
        m[poisoned | m.isna()] = np.nan
        mom.loc[ticker, s.index] = m.values
    return mom


def monthly_spread_backtest_pandas(
    prices: pd.DataFrame,
    lookback: int = 12,
    skip: int = 1,
    n_bins: int = 10,
    freq: int = 12,
) -> PandasMonthlyResult:
    """Monthly decile backtest with reference semantics, pure pandas.

    Args:
      prices: wide [A x M] month-end price frame (NaN = no observation),
        e.g. ``Panel.to_dataframe()``.
    """
    mom = _momentum_frame(prices, lookback, skip)
    return spread_from_scores_pandas(prices, mom, n_bins=n_bins, freq=freq)


def spread_from_scores_pandas(
    prices: pd.DataFrame,
    scores: pd.DataFrame,
    n_bins: int = 10,
    freq: int = 12,
) -> PandasMonthlyResult:
    """Ranking/portfolio tail shared by every strategy on this engine:
    per-date qcut deciles of ``scores`` -> equal-weighted next-month decile
    means -> top-minus-bottom spread (``run_demo.py:46-73`` semantics).

    ``scores`` is wide [A x M], NaN = not rankable that date (the Strategy
    plugin boundary's contract; see :mod:`csmom_tpu.strategy`).
    """
    ret = prices.pct_change(axis=1)
    # calendar-aligned validity: both consecutive month-ends present
    both = prices.notna() & prices.shift(1, axis=1).notna()
    ret = ret.where(both)

    labels = scores.apply(lambda col: _qcut_labels_1d(col, n_bins), axis=0)

    next_ret = ret.shift(-1, axis=1)
    bins = range(n_bins)
    sums, counts = [], []
    for b in bins:
        member = (labels == b) & next_ret.notna()
        sums.append(next_ret.where(member).sum(axis=0))
        counts.append(member.sum(axis=0))
    decile_means = pd.DataFrame(
        [s / c.where(c > 0) for s, c in zip(sums, counts)], index=list(bins)
    )
    decile_counts = pd.DataFrame(counts, index=list(bins))

    spread = decile_means.loc[n_bins - 1] - decile_means.loc[0]
    live = (decile_counts.loc[n_bins - 1] > 0) & (decile_counts.loc[0] > 0)
    spread = spread.where(live)

    sv = spread.dropna()
    mean_spread = float(sv.mean()) if len(sv) else float("nan")
    sd = float(sv.std(ddof=1)) if len(sv) > 1 else float("nan")
    ann_sharpe = (
        mean_spread * freq / (sd * np.sqrt(freq))
        if np.isfinite(sd) and sd > 0
        else float("nan")
    )
    tstat = (
        mean_spread / (sd / np.sqrt(len(sv)))
        if np.isfinite(sd) and sd > 0 and len(sv)
        else float("nan")
    )
    return PandasMonthlyResult(
        spread=spread,
        decile_means=decile_means,
        decile_counts=decile_counts,
        labels=labels.astype(int),
        mean_spread=mean_spread,
        ann_sharpe=ann_sharpe,
        tstat=tstat,
        tstat_nw=_nw_tstat_1d(sv.to_numpy()),
    )
