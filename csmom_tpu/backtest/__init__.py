"""Backtest engines: vectorized monthly decile engine, J x K grid, event engine."""

from csmom_tpu.backtest.monthly import monthly_spread_backtest, MonthlyResult

__all__ = ["monthly_spread_backtest", "MonthlyResult"]
