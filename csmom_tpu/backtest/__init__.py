"""Backtest engines: vectorized monthly decile engine, J x K grid, event engine."""

from csmom_tpu.backtest.monthly import (
    monthly_spread_backtest,
    net_of_costs,
    net_of_costs_arrays,
    sector_neutral_backtest,
    MonthlyResult,
)
from csmom_tpu.backtest.banded import (
    BandedResult,
    banded_books,
    banded_monthly_backtest,
)
from csmom_tpu.backtest.grid import (grid_break_even_bps, grid_net_of_costs,
                                     jk_grid_backtest, GridResult)
from csmom_tpu.backtest.horizon import (
    horizon_profile,
    HorizonProfile,
    volume_horizon_profile,
    VolumeHorizonProfile,
)
from csmom_tpu.backtest.double_sort import volume_double_sort, DoubleSortResult
from csmom_tpu.backtest.event import (
    CostAttribution,
    EventResult,
    cost_attribution,
    event_backtest,
    hysteresis_event_backtest,
    threshold_sweep,
    trades_dataframe,
)
from csmom_tpu.backtest.walkforward import (
    walk_forward_select,
    walk_forward_grid_backtest,
    WalkForwardResult,
)

__all__ = [
    "BandedResult",
    "banded_books",
    "banded_monthly_backtest",
    "monthly_spread_backtest",
    "net_of_costs",
    "net_of_costs_arrays",
    "sector_neutral_backtest",
    "MonthlyResult",
    "jk_grid_backtest",
    "grid_break_even_bps",
    "grid_net_of_costs",
    "GridResult",
    "horizon_profile",
    "HorizonProfile",
    "volume_horizon_profile",
    "VolumeHorizonProfile",
    "volume_double_sort",
    "DoubleSortResult",
    "walk_forward_select",
    "walk_forward_grid_backtest",
    "WalkForwardResult",
    "CostAttribution",
    "EventResult",
    "cost_attribution",
    "event_backtest",
    "hysteresis_event_backtest",
    "threshold_sweep",
    "trades_dataframe",
]
