"""Hysteresis-banded monthly rebalancing: trade less, keep the signal.

The reference (and our plain engine, :mod:`csmom_tpu.backtest.monthly`)
re-forms the long-short book from scratch every month: hold decile
``n_bins-1`` minus decile ``0`` of *this month's* sort
(``/root/reference/run_demo.py:46-65``).  That pays full two-leg turnover
whenever names shuffle across the decile edge — names that sit at rank
8.9/9.1 flap in and out, and the cost framework (``costs/impact.py``,
BASELINE config 3) charges every flap.

The banded engine is the standard practitioner fix, absent from the
reference: a no-trade hysteresis band.  A name ENTERS the long book only
in the extreme decile (``label == n_bins-1``) but STAYS while it remains
within ``band`` deciles of the top (``label >= n_bins-1-band``); the short
leg is symmetric (enter at 0, stay while ``label <= band``).  Invalid
months (no signal — delisting, gap) force an exit, and ``band=0`` reduces
*exactly* to the plain engine's top-minus-bottom portfolio (the invariant
test).  The band trades a little signal freshness for a lot of turnover —
the knob that moves the break-even cost level.

TPU shape: the membership recursion ``x' = enter | (stay & x)`` is a
boolean affine map, and those compose associatively — so the book is a
``lax.associative_scan`` (parallel prefix, O(log M) depth), not a
sequential ``lax.scan``; see :func:`banded_books`.  The asset axis stays
shardable (books are per-asset; only the member counts need a ``psum``
in the sharded variant).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from csmom_tpu.analytics.stats import masked_mean, nw_t_stat, sharpe, t_stat
from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.signals.momentum import (
    formation_listed_mask,
    momentum,
    monthly_returns,
)

__all__ = ["BandedResult", "banded_from_labels", "banded_monthly_backtest",
           "banded_books", "book_partials", "finalize_book_spread",
           "validate_band"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BandedResult:
    """Outputs of one banded monthly backtest (time-indexed arrays)."""

    spread: jnp.ndarray        # f[M] long-book minus short-book next-month return
    spread_valid: jnp.ndarray  # bool[M]
    weights: jnp.ndarray       # f[A, M] book weights at formation (+1/nL, -1/nS)
    n_long: jnp.ndarray        # i32[M] long-book members
    n_short: jnp.ndarray       # i32[M] short-book members
    turnover: jnp.ndarray      # f[M] L1 weight change vs previous month
    mean_spread: jnp.ndarray   # scalar
    ann_sharpe: jnp.ndarray    # scalar
    tstat: jnp.ndarray         # scalar iid t
    tstat_nw: jnp.ndarray      # scalar Newey–West t


def banded_books(labels, n_bins: int, band: int):
    """Long/short membership books under the hysteresis rule.

    The recursion per month is ``x' = enter | (stay & x)`` — a boolean
    affine map, and those compose associatively::

        (later ∘ earlier): a = a2 | (b2 & a1),  b = b2 & b1

    so the "sequential" trigger is really a parallel prefix: one
    ``lax.associative_scan`` over (enter, stay) pairs, O(log M) depth
    instead of an O(M) ``lax.scan`` — the same transformation the event
    engine applies to its running state, now for the monthly book.  With
    the initial state False, the book IS the scanned ``a`` component.

    Args:
      labels: i32[A, M] decile ids (-1 invalid), as produced by
        :func:`csmom_tpu.ops.ranking.decile_assign_panel`.
      band: stay-zone width in deciles.  0 = plain extreme-decile book.

    Returns:
      ``(long bool[A, M], short bool[A, M])``.
    """
    labv = labels >= 0
    top = n_bins - 1

    def compose(earlier, later):
        a1, b1 = earlier
        a2, b2 = later
        return a2 | (b2 & a1), b2 & b1

    def book(enter, stay):
        a, _ = lax.associative_scan(compose, (enter, stay), axis=1)
        return a

    long_b = book(labv & (labels == top), labv & (labels >= top - band))
    short_b = book(labv & (labels == 0), labv & (labels <= band))
    return long_b, short_b


@partial(jax.jit, static_argnames=("lookback", "skip", "n_bins", "mode",
                                   "band", "freq"))
def banded_monthly_backtest(
    prices,
    mask,
    lookback: int = 12,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    band: int = 1,
    freq: int = 12,
) -> BandedResult:
    """Monthly momentum with a no-trade hysteresis band.

    Same formation pipeline as :func:`monthly_spread_backtest` (signal,
    per-date decile sort — identical labels), then the book recursion of
    :func:`banded_books` instead of a fresh extreme-decile book.  The
    spread is the equal-weighted mean next-month return of the long book
    minus the short book (members with a missing next-month return drop
    from the mean, exactly as in the plain engine); ``turnover`` is the L1
    change of the membership weights, ready for
    ``cost[t] = half_spread * turnover[t]`` netting.

    ``band`` must satisfy ``2*band < n_bins - 1`` so the two stay-zones
    cannot overlap (a name can never qualify for both books).
    """
    ret, ret_valid = monthly_returns(prices, mask)
    mom, mom_valid = momentum(prices, mask, lookback=lookback, skip=skip)
    # same delisting rule as the plain engine (band=0 must stay identical)
    mom_valid = mom_valid & formation_listed_mask(mask, skip)
    mom = jnp.where(mom_valid, mom, jnp.nan)
    labels, _ = decile_assign_panel(mom, mom_valid, n_bins=n_bins, mode=mode)
    return banded_from_labels(labels, ret, ret_valid, n_bins=n_bins,
                              band=band, freq=freq)


def validate_band(band: int, n_bins: int) -> None:
    """The ONE band-rule validator (engines raise it; the CLI catches it):
    stay-zones must not overlap, so a name can never qualify for both
    books."""
    if band < 0 or 2 * band >= n_bins - 1:
        raise ValueError(
            f"band={band} with n_bins={n_bins}: need 0 <= 2*band < n_bins-1 "
            "so the long and short stay-zones cannot overlap"
        )


def book_partials(long_b, short_b, ret, ret_valid):
    """Shard-local per-month partials of the book aggregation.

    The ONE definition of how books turn into portfolio sums — the
    single-device engine finalizes these directly; the sharded engine
    (:func:`csmom_tpu.parallel.collectives.sharded_banded_backtest`)
    ``psum``s the stack over the asset mesh axis first, which is the only
    difference between the two.  Returns f[4, M]: long return sum, short
    return sum, long valid-member count, short valid-member count, where
    "valid" means the member has a next-month return (the plain engine's
    drop-from-the-mean convention).
    """
    next_ret = jnp.roll(ret, -1, axis=1)
    next_valid = jnp.roll(ret_valid, -1, axis=1).at[:, -1].set(False)
    lv = long_b & next_valid
    sv = short_b & next_valid
    r0 = jnp.where(next_valid, jnp.nan_to_num(next_ret), 0.0)
    return jnp.stack([
        jnp.sum(jnp.where(lv, r0, 0.0), axis=0),
        jnp.sum(jnp.where(sv, r0, 0.0), axis=0),
        lv.sum(axis=0).astype(r0.dtype),
        sv.sum(axis=0).astype(r0.dtype),
    ])


def finalize_book_spread(partials):
    """(possibly psum'd) book partials -> ``(spread, valid, nl, ns)``."""
    lsum, ssum, nl, ns = partials
    lmean = lsum / jnp.maximum(nl, 1.0)
    smean = ssum / jnp.maximum(ns, 1.0)
    valid = (nl > 0) & (ns > 0)
    return jnp.where(valid, lmean - smean, jnp.nan), valid, nl, ns


@partial(jax.jit, static_argnames=("n_bins", "band", "freq"))
def banded_from_labels(
    labels,
    ret,
    ret_valid,
    n_bins: int = 10,
    band: int = 1,
    freq: int = 12,
) -> BandedResult:
    """Banded backtest from precomputed decile labels + monthly returns.

    The labels-level entry point: callers that already ranked (the CLI
    holds ``rep.labels`` from the plain run; a research loop may sweep
    ``band`` over one ranking) skip re-running formation — the band
    recursion and portfolio tail are all that compile here.
    """
    validate_band(band, n_bins)

    long_b, short_b = banded_books(labels, n_bins, band)
    n_long = long_b.sum(axis=0, dtype=jnp.int32)
    n_short = short_b.sum(axis=0, dtype=jnp.int32)

    partials = book_partials(long_b, short_b, ret, ret_valid)
    spread, spread_valid, nl, ns = finalize_book_spread(partials)

    # weight conventions mirror long_short_weights/turnover_cost EXACTLY
    # (denominators and live-gating use next-VALID member counts, while
    # every book member carries a weight) so band=0 reproduces the plain
    # cost path's charge to the last month — the invariant that keeps one
    # cost semantics across engines
    dt = ret.dtype
    w = (
        long_b.astype(dt) / jnp.maximum(nl, 1).astype(dt)
        - short_b.astype(dt) / jnp.maximum(ns, 1).astype(dt)
    )
    w = jnp.where(spread_valid[None, :], w, 0.0)
    prev = jnp.roll(w, 1, axis=1).at[:, 0].set(0.0)
    turnover = jnp.sum(jnp.abs(w - prev), axis=0)

    return BandedResult(
        spread=spread,
        spread_valid=spread_valid,
        weights=w,
        n_long=n_long,
        n_short=n_short,
        turnover=turnover,
        mean_spread=masked_mean(spread, spread_valid),
        ann_sharpe=sharpe(spread, spread_valid, freq_per_year=freq),
        tstat=t_stat(spread, spread_valid),
        tstat_nw=nw_t_stat(spread, spread_valid),
    )
