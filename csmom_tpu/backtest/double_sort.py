"""Momentum x volume double sort (Lee–Swaminathan 2000, Table II).

The replicated paper's headline result beyond plain momentum: sort stocks
independently into J-month momentum deciles (R1..R10) and average-turnover
terciles (V1..V3); the R10-R1 spread is markedly larger among high-turnover
stocks (1.46 %/mo in V3 vs 0.54 %/mo in V1 for J=K=6 — BASELINE.md).  The
reference computes the turnover inputs but never performs this sort
(SURVEY §2 row 6); this module completes the capability.

Construction: independent two-way sort at each formation date; intersection
cells (momentum extreme x volume tercile) are equal-weighted over the next
month.  One jit call produces spreads for every volume tercile at once.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.signals.momentum import (
    formation_listed_mask,
    momentum_dynamic,
    monthly_returns,
)
from csmom_tpu.signals.turnover import volume_tercile_labels
from csmom_tpu.analytics.stats import sharpe, masked_mean, t_stat, nw_t_stat
from csmom_tpu.costs.impact import long_short_weights, turnover_cost


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DoubleSortResult:
    spreads: jnp.ndarray       # f[V, M] R-top minus R-bottom within tercile v
    spread_valid: jnp.ndarray  # bool[V, M]
    mean_spread: jnp.ndarray   # f[V]
    ann_sharpe: jnp.ndarray    # f[V]
    tstat: jnp.ndarray         # f[V] plain iid t-stat
    tstat_nw: jnp.ndarray      # f[V] Newey–West t-stat (paper Table II form)
    cell_counts: jnp.ndarray   # i32[V, 2, M] members in (bottom, top) cells
    book_turnover: jnp.ndarray  # f[V, M] sum |dw| of the tercile's long-short
                                # book (equal-weight legs; dead months hold
                                # no book) — price at any half-spread later


@partial(jax.jit, static_argnames=("n_bins", "n_vol_bins", "mode", "freq"))
def volume_double_sort(
    prices,
    mask,
    turnover,
    turnover_valid,
    lookback=6,
    skip: int = 1,
    n_bins: int = 10,
    n_vol_bins: int = 3,
    mode: str = "qcut",
    freq: int = 12,
) -> DoubleSortResult:
    """Momentum spread within each volume tercile.

    Args:
      prices: f[A, M] month-end prices.
      mask: bool[A, M].
      turnover: f[A, M] volume signal (e.g. ``turn_avg``).
      turnover_valid: bool[A, M].
      lookback: J (traced ok).
      n_vol_bins: volume groups (3 = LeSw terciles).
    """
    ret, ret_valid = monthly_returns(prices, mask)
    mom, mom_valid = momentum_dynamic(prices, mask, lookback, skip)
    mom_valid = mom_valid & formation_listed_mask(mask, skip)
    mom = jnp.where(mom_valid, mom, jnp.nan)
    mom_labels, _ = decile_assign_panel(mom, mom_valid, n_bins=n_bins, mode=mode)
    # independent sort: momentum decile edges use every mom-valid asset
    # (turnover-less names still shape the breakpoints); the volume tercile
    # sort is restricted to assets with both signals live, and intersection
    # cells below require membership in both sorts
    both = mom_valid & turnover_valid
    vol_labels, _ = volume_tercile_labels(
        jnp.where(both, turnover, jnp.nan), both, n_vol_bins=n_vol_bins, mode=mode
    )

    next_ret = jnp.roll(ret, -1, axis=1)
    next_valid = jnp.roll(ret_valid, -1, axis=1).at[:, -1].set(False)
    live = next_valid & (mom_labels >= 0) & (vol_labels >= 0)

    rf = jnp.where(live, jnp.nan_to_num(next_ret), 0.0)

    def per_tercile(v):
        in_v = live & (vol_labels == v)

        def cell(mom_bin):
            mem = in_v & (mom_labels == mom_bin)
            cnt = jnp.sum(mem, axis=0)
            s = jnp.sum(jnp.where(mem, rf, 0.0), axis=0)
            return s / jnp.maximum(cnt, 1), cnt

        top_r, top_n = cell(n_bins - 1)
        bot_r, bot_n = cell(0)
        valid = (top_n > 0) & (bot_n > 0)
        spread = jnp.where(valid, top_r - bot_r, jnp.nan)

        # the tercile's long-short book and its |dw| turnover, through the
        # SAME weight/cost kernels every other cost path uses
        # (costs/impact.py long_short_weights + turnover_cost) — the
        # double-sort's net numbers can never diverge in convention
        t_labels = jnp.where(in_v, mom_labels, -1)
        counts_bm = (
            jnp.zeros((n_bins,) + top_n.shape, top_n.dtype)
            .at[0].set(bot_n)
            .at[n_bins - 1].set(top_n)
        )
        w = long_short_weights(t_labels, counts_bm, n_bins)
        turn = turnover_cost(w, half_spread=1.0)  # unit spread -> raw |dw|
        return (spread, valid,
                jnp.stack([bot_n, top_n]).astype(jnp.int32), turn)

    spreads, valids, counts, turns = jax.vmap(per_tercile)(
        jnp.arange(n_vol_bins)
    )
    return DoubleSortResult(
        spreads=spreads,
        spread_valid=valids,
        mean_spread=masked_mean(spreads, valids),
        ann_sharpe=sharpe(spreads, valids, freq_per_year=freq),
        tstat=t_stat(spreads, valids),
        tstat_nw=nw_t_stat(spreads, valids),
        cell_counts=counts,
        book_turnover=turns,
    )
