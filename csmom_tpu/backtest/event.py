"""Event-driven intraday backtest as a fully vectorized panel program.

Reference: ``SimpleEventBacktester`` (``/root/reference/src/backtester.py``)
— a Python loop over datetime groups with per-row ``iterrows`` order
generation, immediate market fills, an integer position book, and
mark-to-market that scans the whole DataFrame for a fallback price
(``backtester.py:46-58``, worst-case O(bars x N) — the reference's hottest
loop at 18.4 s for 2,728 bars x 20 tickers, SURVEY §3.4).

Panel form: with one fixed per-asset order size, every quantity is a prefix
sum over the ``[A, T]`` minute grid —

- order side     = thresholded score (strict inequalities, backtester.py:29-32)
- fill price     = ``price * (1 + side*(spread/2 + impact_a))`` where the
                   square-root impact is constant per asset (fixed size/ADV/vol)
- position book  = ``cumsum`` of signed trades along time
- cash ledger    = ``cash0 - cumsum`` of signed fill notional
- mark-to-market = forward-filled last observed price (associative-scan max
                   over observed row indices) — semantically identical to the
                   reference's "last price <= dt" DataFrame scan, minus the
                   O(N^2)
- PnL            = first difference of portfolio value over bar timestamps

No ``lax.scan`` is needed; everything is a cumulative op XLA fuses into a
handful of passes, embarrassingly parallel along assets.  The trade log of
the golden fingerprint (28,020 trades, SURVEY §2 row 17) is reconstructed
host-side from the trade mask.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from csmom_tpu.costs.impact import square_root_impact

DEFAULT_ADV = 100_000.0  # fallback ADV shares (run_demo.py:100, backtester.py:35)
DEFAULT_VOL = 0.02       # fallback daily vol (run_demo.py:125, backtester.py:36)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EventResult:
    pnl: jnp.ndarray          # f[T] per-bar portfolio value change (0 where no bar)
    bar_mask: jnp.ndarray     # bool[T] minutes with >=1 event row
    portfolio_value: jnp.ndarray  # f[T]
    cash: jnp.ndarray         # f[T] cash path
    positions: jnp.ndarray    # i32[A, T] share positions
    trade_side: jnp.ndarray   # i8[A, T] signed trade UNITS: +1/-1/0 in the
                              # threshold engine; the hysteresis engine's
                              # flips store ±2 (one 2-unit fill), so every
                              # consumer (TCA, the trade log) sees true size
    exec_price: jnp.ndarray   # f[A, T] fill price where traded
    impact: jnp.ndarray       # f[A] per-asset impact fraction
    total_pnl: jnp.ndarray    # f[] sum of pnl
    n_trades: jnp.ndarray     # i32
    n_buys: jnp.ndarray       # i32
    n_sells: jnp.ndarray      # i32
    net_notional: jnp.ndarray # f[] sum of signed fill notional


def counter_uniform(key, shape, a_offset, t_offset, dtype):
    """Uniform draws that are a pure function of (key, global panel cell).

    ``u[i, j] = uniform(fold_in(fold_in(key, a_offset + i), t_offset + j))``
    depends only on the key and the cell's global (asset, bar) coordinates
    — never on how the ``[A, T]`` panel is partitioned *or padded*, so
    limit fills come out identical single-device, asset-sharded, and
    time-sharded (the replicated-key draw they replace changed with the
    local block shape — VERDICT r2 missing #4).  Two nested folds rather
    than a linearized ``a * T + t`` counter: a stride would bake the
    (possibly padded) panel length into every draw, silently changing
    fills whenever ``pad_time`` rounds T up to the shard count.
    """
    A_l, T_l = shape
    gi = a_offset + jnp.arange(A_l, dtype=jnp.int32)
    gj = t_offset + jnp.arange(T_l, dtype=jnp.int32)
    row_keys = jax.vmap(lambda a: jax.random.fold_in(key, a))(gi)
    return jax.vmap(
        lambda rk: jax.vmap(
            lambda t: jax.random.uniform(jax.random.fold_in(rk, t), (), dtype)
        )(gj)
    )(row_keys)


def limit_fill_probability(adv, size_shares, aggressiveness, dtype):
    """Reference ``simulate_limit_fill`` probability
    ``(0.2 + 0.7*agg) * (1 - 0.5*min(1, size/ADV))``
    (``execution_models.py:14-22``), per asset."""
    return (0.2 + 0.7 * aggressiveness) * (
        1.0 - 0.5 * jnp.minimum(
            1.0, float(size_shares) / jnp.maximum(1.0, adv.astype(dtype))
        )
    )


def limit_fill_price(exec_base, aggressiveness, spread):
    """Reference ``simulate_limit_fill`` executed price — side-independent
    improvement ``price * (1 - 0.5*agg*spread)`` (``execution_models.py:20``).
    Shared by the single-device and time-sharded engines so the semantics
    cannot drift apart."""
    return exec_base * (1.0 - 0.5 * aggressiveness * spread)


def threshold_sides(valid, score, threshold):
    """Order sides from thresholded scores: +1/-1 when |score| > threshold
    strictly, at valid event rows only (backtester.py:29-32)."""
    return jnp.where(
        valid & (score > threshold), 1,
        jnp.where(valid & (score < -threshold), -1, 0),
    ).astype(jnp.int32)


def market_fill_prices(exec_base, side, traded, impact, spread):
    """Market-order fill prices: ``price * (1 + side*(spread/2 + impact))``
    where traded, 0 elsewhere (execution_models.py:9-12)."""
    return jnp.where(
        traded, exec_base * (1.0 + side * (spread / 2.0 + impact[:, None])), 0.0
    )



def _settlement_fill_idx(valid, latency_bars: int):
    """The engine's latency fill rule: first valid row at or after
    decision + latency, per asset (reverse running min over the event
    mask).  Shared by :func:`event_backtest` and :func:`cost_attribution`
    so the TCA can never attribute against a different settlement bar
    than the engine filled at.  Returns i32[A, T]; T marks "no such row"
    (the engine treats those as unfillable)."""
    T = valid.shape[1]
    t_idx = jnp.arange(T, dtype=jnp.int32)
    nxt = jax.lax.associative_scan(
        jnp.minimum, jnp.where(valid, t_idx[None, :], T), axis=1, reverse=True
    )
    target = jnp.clip(t_idx + latency_bars, 0, T - 1)
    return nxt[:, target]



def _apply_latency(price, valid, units, latency_bars: int):
    """Shared delayed-fill plumbing for both intraday engines.

    ``units i32[A, T]`` are the signed trade units decided per cell (the
    threshold engine's side, the hysteresis engine's delta).  Returns
    ``(kept_units, fill_idx, exec_base)``: decisions whose settlement row
    (first valid row >= decision + latency) does not exist are dropped,
    and ``exec_base`` is the settlement-bar price gathered back onto the
    decision cells.  ``latency_bars == 0`` is the identity (same-bar)."""
    A, T = price.shape
    t_idx = jnp.arange(T, dtype=jnp.int32)
    if latency_bars <= 0:
        return units, jnp.broadcast_to(t_idx[None, :], (A, T)), jnp.nan_to_num(price)
    fill_idx = _settlement_fill_idx(valid, latency_bars)
    fillable = ((units != 0)
                & (t_idx[None, :] + latency_bars <= T - 1)
                & (fill_idx < T))
    units = jnp.where(fillable, units, 0)
    fill_idx = jnp.clip(fill_idx, 0, T - 1)
    exec_base = jnp.take_along_axis(jnp.nan_to_num(price), fill_idx, axis=1)
    return units, fill_idx, exec_base


def _scatter_settle(shares, fill, fill_idx, latency_bars: int, dtype):
    """Scatter decided shares/notional onto their settlement rows (identity
    at latency 0).  Shared by both engines for the same no-drift reason as
    :func:`_settlement_fill_idx`."""
    if latency_bars <= 0:
        return shares, fill * shares.astype(dtype)
    A, T = shares.shape
    rows = jnp.arange(A)[:, None]
    shares_settle = jnp.zeros((A, T), jnp.int32).at[rows, fill_idx].add(shares)
    notional_settle = (
        jnp.zeros((A, T), dtype).at[rows, fill_idx].add(fill * shares.astype(dtype))
    )
    return shares_settle, notional_settle


_EVENT_STATICS = ("size_shares", "latency_bars", "order_type", "axis_name")


def _event_backtest_impl(
    price,
    valid,
    score,
    adv,
    vol,
    size_shares: int = 50,
    threshold: float = 1e-5,
    cash0: float = 1_000_000.0,
    spread: float = 0.001,
    latency_bars: int = 0,
    order_type: str = "market",
    aggressiveness: float = 0.5,
    fill_key=None,
    axis_name: str | None = None,
) -> EventResult:
    """Run the event backtest over a dense minute panel.

    Args:
      price: f[A, T] minute prices at event rows (NaN elsewhere).
      valid: bool[A, T] event rows (the feature frame's surviving rows —
        only these can trade or refresh the mark, matching the reference
        which backtests exactly the feature DataFrame, run_demo.py:163-170).
      score: f[A, T] model scores at event rows.
      adv: f[A] average daily volume (fallbacks pre-applied).
      vol: f[A] daily return volatility (fallbacks pre-applied).
      size_shares: fixed order size (run_demo.py:180 uses 50).
      threshold: trade when |score| > threshold, strictly.
      latency_bars: order-to-fill delay in bars.  0 = same-bar fill, the
        reference's (only) behaviour — it stores ``latency_ms`` but never
        reads it (``backtester.py:8,14``, SURVEY §2.1.7).  With L > 0 an
        order decided at row t executes at the asset's first event row
        >= t+L, at *that* row's price (decision score, delayed execution);
        orders with no remaining event row are dropped unfilled.  The trade
        log keeps decision timestamps; positions/cash move at fill time.
      order_type: 'market' (parity path) or 'limit' — the reference ships
        ``simulate_limit_fill`` as dead code (``execution_models.py:14-22``,
        zero call sites); here it is a live mode with its exact semantics:
        fill probability ``(0.2 + 0.7*agg) * (1 - 0.5*min(1, size/ADV))``
        per order, executed price ``price * (1 - 0.5*agg*spread)``, unfilled
        orders dropped.  Requires ``fill_key`` (explicit PRNG, unlike the
        reference's unseeded global numpy RNG).
      aggressiveness: limit-order aggressiveness in [0, 1].
      axis_name: when called inside ``shard_map`` with the asset axis
        sharded, the mesh axis to ``psum`` the cross-asset reductions over
        (order flow, marks, trade counts); None = single-device.  See
        :func:`csmom_tpu.parallel.sharded_event_backtest`.
    """
    A, T = price.shape
    dtype = price.dtype
    allsum = (lambda x: jax.lax.psum(x, axis_name)) if axis_name else (lambda x: x)

    side = threshold_sides(valid, score, threshold)
    traded = side != 0

    if order_type == "limit":
        if fill_key is None:
            raise ValueError("order_type='limit' requires fill_key")
        p_fill = limit_fill_probability(adv, size_shares, aggressiveness, dtype)
        # counter-based draws: u[a, t] is keyed by the *global* cell, so a
        # sharded call (asset axis split inside shard_map) reproduces the
        # single-device fills exactly
        a_offset = jax.lax.axis_index(axis_name) * A if axis_name else 0
        u = counter_uniform(fill_key, (A, T), a_offset, 0, dtype)
        side = jnp.where(u < p_fill[:, None], side, 0)
        traded = side != 0
    elif order_type != "market":
        raise ValueError(f"unknown order_type {order_type!r}")

    impact = square_root_impact(
        jnp.asarray(float(size_shares), dtype), adv.astype(dtype), vol.astype(dtype)
    )

    side, fill_idx, exec_base = _apply_latency(price, valid, side, latency_bars)
    traded = side != 0

    if order_type == "limit":
        fill = jnp.where(traded, limit_fill_price(exec_base, aggressiveness, spread), 0.0)
    else:
        fill = market_fill_prices(exec_base, side, traded, impact, spread)

    shares = side * size_shares                       # i32[A, T] at decision rows
    shares_settle, notional_settle = _scatter_settle(
        shares, fill, fill_idx, latency_bars, dtype
    )

    return _settle_mark_and_wrap(
        price, valid, shares_settle, notional_settle, side, fill, traded,
        impact, cash0, allsum,
    )


# One body, two jit wrappings: ``event_backtest`` (the public engine — every
# caller that reuses its panels, including the vmapped threshold sweep and
# the sharded wrappers) and ``event_backtest_donated``, which donates the
# [A, T] price/valid/score panels so XLA reuses their memory for the
# engine's prefix-sum intermediates.  Donation cannot be toggled per-call on
# one jit; callers of the donated form give up their input buffers
# (``.is_deleted()`` afterwards) in exchange for the smaller peak footprint.
event_backtest = partial(
    jax.jit, static_argnames=_EVENT_STATICS
)(_event_backtest_impl)
event_backtest_donated = jax.jit(
    _event_backtest_impl, static_argnames=_EVENT_STATICS, donate_argnums=(0, 1, 2)
)


def _settle_mark_and_wrap(price, valid, shares_settle, notional_settle,
                          side, fill, traded, impact, cash0, allsum):
    """Shared tail of every event engine: settled shares/notional ->
    positions, cash, forward-filled marks, portfolio value, per-bar PnL,
    trade counts — one definition of the accounting, used by the plain
    threshold engine and the hysteresis engine so the two cannot drift."""
    A, T = price.shape
    dtype = price.dtype
    t_idx = jnp.arange(T, dtype=jnp.int32)

    positions = jnp.cumsum(shares_settle, axis=1)
    flow = allsum(jnp.sum(notional_settle, axis=0))   # signed notional per bar
    cash = cash0 - jnp.cumsum(flow)

    # forward-filled mark price: last observed row price at or before t
    obs = jnp.where(valid, t_idx[None, :], -1)
    last_obs = jax.lax.associative_scan(jnp.maximum, obs, axis=1)
    mark = jnp.take_along_axis(
        jnp.nan_to_num(price), jnp.clip(last_obs, 0, T - 1), axis=1
    )
    mark = jnp.where(last_obs >= 0, mark, 0.0)  # pre-history marks at 0 (backtester.py:57)

    pv = cash + allsum(jnp.sum(positions.astype(dtype) * mark, axis=0))

    # per-bar PnL over bar timestamps only; first bar = 0 (backtester.py:59-62)
    bar_mask = allsum(jnp.sum(valid, axis=0)) > 0
    # pv of the previous bar: gather pv at the last bar index < t
    obs_bar = jnp.where(bar_mask, t_idx, -1)
    last_bar = jax.lax.associative_scan(jnp.maximum, obs_bar)
    prev_bar = jnp.where(bar_mask, jnp.roll(last_bar, 1).at[0].set(-1), -1)
    pv_prev = jnp.where(prev_bar >= 0, pv[jnp.clip(prev_bar, 0, T - 1)], pv)
    pnl = jnp.where(bar_mask & (prev_bar >= 0), pv - pv_prev, 0.0)

    n_trades = allsum(jnp.sum(traded))
    return EventResult(
        pnl=pnl,
        bar_mask=bar_mask,
        portfolio_value=pv,
        cash=cash,
        positions=positions,
        trade_side=side.astype(jnp.int8),
        exec_price=fill,
        impact=impact,
        total_pnl=jnp.sum(pnl),
        n_trades=n_trades.astype(jnp.int32),
        n_buys=allsum(jnp.sum(side > 0)).astype(jnp.int32),
        n_sells=allsum(jnp.sum(side < 0)).astype(jnp.int32),
        net_notional=jnp.sum(flow),
    )


def hysteresis_event_backtest(
    price,
    valid,
    score,
    adv,
    vol,
    threshold_hi: float = 1e-4,
    threshold_lo: float = 1e-5,
    size_shares: int = 50,
    cash0: float = 1_000_000.0,
    spread: float = 0.001,
    latency_bars: int = 0,
    donate_panels: bool = False,
) -> EventResult:
    """Event backtest with a Schmitt-trigger position state per asset.

    The plain engine fires an order at EVERY bar whose |score| clears one
    threshold (``backtester.py:29-32``) — at minute frequency that is a
    new 50-share order nearly every bar (28,020 trades on the golden
    workload) and the position book grows without bound.  The hysteresis
    engine instead targets a bounded state with two thresholds, the
    classic two-threshold trigger:

    - enter long  (+1 unit) when ``score >  threshold_hi``;
    - enter short (-1 unit) when ``score < -threshold_hi``;
    - go flat when ``|score| < threshold_lo``;
    - otherwise (``threshold_lo <= |score| <= threshold_hi``) HOLD the
      previous state — the no-trade band that absorbs score flutter.

    Trades happen only on state changes (enter/exit/flip; a flip trades
    2x ``size_shares``), filled at the reference's market-fill formula.
    Positions are therefore bounded at one unit per asset — this is a
    different product from the reference's accumulate-every-signal book,
    not a parametrization of it (``threshold_hi == threshold_lo`` gives a
    1-unit-target engine, still not the accumulating one; documented, not
    hidden).

    TPU shape: the state machine is resolved WITHOUT a scan — the state
    at t is decided by the most recent event among {enter-long,
    enter-short, exit} at or before t, and "most recent event index" is
    an associative running max per event type; three cummaxes and a
    comparison replace the sequential trigger.  ``threshold_lo <=
    threshold_hi`` is validated HOST-side on the Python floats; the
    compiled body keeps both thresholds traced, so repeated calls with
    different float thresholds share one compile.  A ``vmap`` over
    thresholds would hit the host-side ``float()`` — vmap
    ``_hysteresis_body`` directly for that (and validate the grid
    yourself), the same pattern as :func:`threshold_sweep`.

    With ``latency_bars > 0`` each state-change trade settles at the next
    valid row >= decision + latency (the threshold engine's rule, via the
    shared :func:`_settlement_fill_idx`); unfillable tail decisions are
    dropped, and because the deltas telescope, the position path still
    sums to the decided target wherever settlement completes.  The
    time-sharded variant (:mod:`csmom_tpu.parallel.event_time`) remains
    latency-0 only.
    """
    if float(threshold_lo) > float(threshold_hi):
        raise ValueError(
            f"threshold_lo={threshold_lo} > threshold_hi={threshold_hi}: "
            "the exit threshold must not exceed the entry threshold"
        )
    # donate_panels: same contract as event_backtest_donated — the caller's
    # price/valid/score buffers are deleted on return
    body = _hysteresis_body_donated if donate_panels else _hysteresis_body
    return body(price, valid, score, adv, vol, threshold_hi,
                threshold_lo, size_shares, cash0, spread,
                latency_bars)


def _hysteresis_body_impl(price, valid, score, adv, vol, threshold_hi,
                          threshold_lo, size_shares, cash0, spread,
                          latency_bars: int = 0) -> EventResult:
    A, T = price.shape
    dtype = price.dtype
    t_idx = jnp.arange(T, dtype=jnp.int32)

    e_long = valid & (score > threshold_hi)
    e_short = valid & (score < -threshold_hi)
    e_exit = valid & (jnp.abs(score) < threshold_lo)

    def last_idx(ev):
        return jax.lax.associative_scan(
            jnp.maximum, jnp.where(ev, t_idx[None, :], -1), axis=1
        )
    iL, iS, iX = last_idx(e_long), last_idx(e_short), last_idx(e_exit)
    target = jnp.where(
        (iL > iS) & (iL > iX), 1, jnp.where((iS > iL) & (iS > iX), -1, 0)
    ).astype(jnp.int32)

    prev_target = jnp.pad(target, ((0, 0), (1, 0)))[:, :T]
    delta = target - prev_target                    # i32[A, T], in {-2..2}

    # shared settlement rule: fills land at the next valid row >=
    # decision + latency; unfillable tail decisions are dropped (the
    # deltas telescope, so kept positions still sum to the decided target)
    delta, fill_idx, exec_base = _apply_latency(price, valid, delta, latency_bars)

    sgn = jnp.sign(delta).astype(jnp.int32)         # fill-price direction
    traded = sgn != 0

    impact = square_root_impact(
        jnp.asarray(float(size_shares), dtype), adv.astype(dtype),
        vol.astype(dtype),
    )
    fill = market_fill_prices(exec_base, sgn, traded, impact, spread)
    shares = delta * size_shares
    shares_settle, notional_settle = _scatter_settle(
        shares, fill, fill_idx, latency_bars, dtype
    )
    # the stored side is the SIGNED UNIT COUNT (delta: flips are ±2) so
    # cost_attribution and trades_dataframe see the true trade size; the
    # fill PRICE above uses only the direction (the market-fill formula's
    # side is ±1 — execution_models.py:9-12)
    return _settle_mark_and_wrap(
        price, valid, shares_settle, notional_settle, delta, fill, traded,
        impact, cash0, lambda x: x,
    )


_HYST_STATICS = ("size_shares", "latency_bars")
_hysteresis_body = jax.jit(_hysteresis_body_impl, static_argnames=_HYST_STATICS)
_hysteresis_body_donated = jax.jit(
    _hysteresis_body_impl, static_argnames=_HYST_STATICS, donate_argnums=(0, 1, 2)
)


def trades_dataframe(result: EventResult, tickers, times, score, size_shares: int = 50):
    """Reconstruct the reference's trade log (``results/trades.csv`` schema:
    datetime,ticker,size,price,impact,score — sorted by datetime then ticker,
    which is the backtester's row order, backtester.py:9).  Host-side.

    Latency runs: rows are DECISION bars (datetime/score are the order's),
    while ``price`` is the delayed fill — an order blotter, not a print
    tape; the settlement bar is recoverable via
    :func:`_settlement_fill_idx` on the run's ``valid`` mask."""
    import pandas as pd

    side = np.asarray(result.trade_side)
    fill = np.asarray(result.exec_price)
    imp = np.asarray(result.impact)
    score = np.asarray(score)
    a_idx, t_idx = np.nonzero(side)
    order = np.lexsort((np.asarray(tickers, dtype=object)[a_idx], t_idx))
    a_idx, t_idx = a_idx[order], t_idx[order]
    return pd.DataFrame(
        {
            "datetime": np.asarray(times)[t_idx],
            "ticker": np.asarray(tickers, dtype=object)[a_idx],
            "size": side[a_idx, t_idx].astype(int) * size_shares,
            "price": fill[a_idx, t_idx],
            "impact": imp[a_idx],
            "score": score[a_idx, t_idx],
        }
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CostAttribution:
    """Execution-cost decomposition of an event backtest (all scalars).

    ``total_cost`` is exact in any order mode (signed slippage of every
    fill against the DECISION-bar mid — the implementation-shortfall
    benchmark); the spread/impact split is the market-fill formula's
    decomposition (``execution_models.py:9-12``:
    ``exec = mid * (1 + side*(spread/2 + impact))``), so ``residual`` is
    ~0 for market orders and absorbs the difference for limit fills
    (which can earn, not pay, the half-spread).

    With ``latency_bars > 0`` the shortfall additionally carries
    ``delay_cost`` — the market's signed move from the decision-bar mid
    to the settlement-bar mid, the part of the shortfall that is drift
    during the delay rather than execution: ``total = delay + spread +
    impact + residual`` in every mode (``delay_cost == 0`` at latency 0).
    """

    gross_pnl: jnp.ndarray      # f[] PnL had every fill been at decision mid
    net_pnl: jnp.ndarray        # f[] realized PnL (== EventResult.total_pnl)
    total_cost: jnp.ndarray     # f[] gross - net (implementation shortfall)
    delay_cost: jnp.ndarray     # f[] decision->settlement mid drift leg
    spread_cost: jnp.ndarray    # f[] half-spread leg of the fill formula
    impact_cost: jnp.ndarray    # f[] sqrt-impact leg
    residual: jnp.ndarray       # f[] total - delay - spread - impact
    gross_notional: jnp.ndarray # f[] sum of |size| * decision mid over fills
    cost_bps: jnp.ndarray       # f[] total_cost / gross_notional * 1e4


def cost_attribution(result: EventResult, price, size_shares: int = 50,
                     spread: float = 0.001,
                     latency_bars: int = 0, valid=None) -> CostAttribution:
    """Decompose an :class:`EventResult` into gross PnL and cost legs.

    Args:
      result: the backtest output.
      price: f[A, T] the same mid-price panel the backtest ran on.
      size_shares / spread: the constants the backtest ran with.
      latency_bars: must echo the backtest's value.  With a delay, the
        shortfall against the decision-bar mid is decomposed into the
        drift leg (decision mid -> settlement mid, ``delay_cost``) and
        the execution legs measured against the SETTLEMENT-bar mid —
        the standard implementation-shortfall treatment; ``valid`` is
        required to recompute the engine's settlement bars.
      valid: bool[A, T] the backtest's event mask (latency runs only —
        settlement bars are the next valid rows, ``event_backtest``'s
        own fill rule).

    The reference's analytics never separate costs from alpha even though
    its trade log stores the impact leg per fill
    (``run_demo.py:188-189``); this is the standard TCA summary built
    from the same panel outputs.
    """
    side = result.trade_side.astype(price.dtype)   # signed units (flips ±2)
    units = jnp.abs(side)
    traded = result.trade_side != 0
    mid = jnp.where(traded, jnp.nan_to_num(price), 0.0)
    fill = jnp.where(traded, jnp.nan_to_num(result.exec_price), 0.0)
    sz = jnp.asarray(size_shares, price.dtype)

    if latency_bars > 0:  # same gate as the engine: <=0 means same-bar fills
        if valid is None:
            raise ValueError(
                "cost_attribution with latency_bars > 0 needs the "
                "backtest's `valid` mask to recompute settlement bars"
            )
        # the engine's own settlement rule, via the shared helper
        T = price.shape[1]
        fill_idx = jnp.clip(_settlement_fill_idx(valid, latency_bars), 0, T - 1)
        settle_mid = jnp.take_along_axis(jnp.nan_to_num(price), fill_idx, axis=1)
        settle_mid = jnp.where(traded, settle_mid, 0.0)
    else:
        settle_mid = mid

    # exact: signed slippage against the DECISION-bar mid, per UNIT — a
    # hysteresis flip (2 units at one fill price) costs twice
    total_cost = jnp.sum((fill - mid) * side) * sz
    # drift during the delay: decision mid -> settlement mid (0 at lat=0)
    delay_cost = jnp.sum((settle_mid - mid) * side) * sz
    # formula split (market fills) against the mid the fill was priced
    # off: settle_mid * (spread/2 + impact_a) per share
    spread_cost = jnp.sum(settle_mid * units) * (spread / 2.0) * sz
    impact_cost = jnp.sum(settle_mid * result.impact[:, None] * units) * sz

    gross_notional = jnp.sum(mid * units) * sz
    net = result.total_pnl
    return CostAttribution(
        gross_pnl=net + total_cost,
        net_pnl=net,
        total_cost=total_cost,
        delay_cost=delay_cost,
        spread_cost=spread_cost,
        impact_cost=impact_cost,
        residual=total_cost - delay_cost - spread_cost - impact_cost,
        gross_notional=gross_notional,
        cost_bps=jnp.where(
            gross_notional > 0, total_cost / gross_notional * 1e4, jnp.nan
        ),
    )


def threshold_sweep(price, valid, score, adv, vol, thresholds, **kwargs):
    """Event backtest at every score threshold in one vmapped call.

    The reference hardcodes ``threshold=1e-5`` (``run_demo.py:180``) with
    no way to ask the obvious next question — how sensitive are PnL and
    trade count to it.  ``threshold`` is a traced argument of
    :func:`event_backtest`, so the whole sensitivity curve is one
    ``vmap``: every other input is closed over, XLA batches the prefix
    sums, and no per-threshold recompilation happens.

    Args:
      thresholds: f[N] thresholds (ascending recommended for readability).
      **kwargs: forwarded to :func:`event_backtest` (sizes, costs, latency
        — anything but ``threshold``).

    Returns ``(total_pnl f[N], n_trades i32[N], cost_bps f[N])`` —
    ``cost_bps`` is :func:`cost_attribution`'s total slippage over gross
    mid notional per threshold (NaN where nothing traded).  Latency runs
    attribute through the implementation-shortfall path (drift +
    execution legs; the engine's ``valid`` mask is in scope here).
    """
    thresholds = jnp.asarray(thresholds)
    size_shares = kwargs.get("size_shares", 50)
    spread = kwargs.get("spread", 0.001)
    latency_bars = kwargs.get("latency_bars", 0)
    kwargs = {k: v for k, v in kwargs.items() if k != "threshold"}

    def one(th):
        r = event_backtest(price, valid, score, adv, vol, threshold=th,
                           **kwargs)
        tca = cost_attribution(r, price, size_shares=size_shares,
                               spread=spread, latency_bars=latency_bars,
                               valid=valid)
        return r.total_pnl, r.n_trades, tca.cost_bps

    return jax.vmap(one)(thresholds)
