"""Jegadeesh–Titman J x K strategy grid as a single compiled call.

The reference computes one (J=12, K=1) cell (``run_demo.py:31-79``); the
paper it replicates (Lee–Swaminathan 2000, following Jegadeesh–Titman 1993)
reports a full grid of formation periods J and *overlapping* K-month holding
periods: the portfolio held in month m averages the K cohorts formed at
months m-1 .. m-K, each equal-weighted within its top/bottom decile
(the "1/K overlapping portfolios" construction of JT §I).

TPU-first design: nothing here is a loop over grid cells.

- formation signals for all J values: one ``vmap`` over a traced J vector
  (``momentum_dynamic`` — index arithmetic only, so J need not be static);
- decile labels for all J: ``vmap`` of the ranking kernel;
- cohort forward returns ``R[j, s, h]`` (cohort formed at s under J_j,
  its spread h months later): a static unroll over h = 1..Kmax of
  masked membership means — O(nJ * A * M * Kmax) fused elementwise work;
- the K axis: a cumulative mean over h, gathered at each K — so every
  (J, K) cell shares the same cohort tensor.

One jit call returns the full [nJ, nK] grid of spread series and summary
stats.  The asset axis stays the leading axis end-to-end, so the same code
shards over devices with the ranking collective as the only global op.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.signals.momentum import (
    formation_listed_mask,
    momentum_dynamic,
    monthly_returns,
)
from csmom_tpu.analytics.stats import sharpe, masked_mean, t_stat, nw_t_stat


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GridResult:
    """Full J x K grid outputs; axes [nJ, nK, ...] / time axis = holding month.

    The build parameters ride along (``Js/Ks/skip`` as arrays, ``n_bins`` /
    ``mode`` as static metadata) so downstream transforms that must
    recompute formation books — :func:`grid_net_of_costs` — read them from
    the result instead of trusting the caller to re-specify them
    consistently.  Results whose axes are *not* a (formation, holding) grid
    (e.g. the residual sweep's est_window axis) leave them ``None``, which
    makes parameter-dependent transforms fail loudly instead of netting a
    differently-binned book.
    """

    spreads: jnp.ndarray       # f[nJ, nK, M] portfolio spread return in month m
    spread_valid: jnp.ndarray  # bool[nJ, nK, M] (all K cohorts live)
    mean_spread: jnp.ndarray   # f[nJ, nK]
    ann_sharpe: jnp.ndarray    # f[nJ, nK]
    tstat: jnp.ndarray         # f[nJ, nK] plain iid t-stat (oracle-matched)
    tstat_nw: jnp.ndarray      # f[nJ, nK] Newey–West t-stat, lag = K (the
                               # reported inference: K-overlap spreads are
                               # serially correlated by construction)
    Js: jnp.ndarray | None = None    # i32[nJ] formation lookbacks built with
    Ks: jnp.ndarray | None = None    # i32[nK] holding periods built with
    skip: jnp.ndarray | None = None  # i32[] formation-to-holding skip months
    n_bins: int | None = dataclasses.field(
        default=None, metadata=dict(static=True)
    )
    mode: str | None = dataclasses.field(
        default=None, metadata=dict(static=True)
    )


def _cohort_partial_sums(labels, ret, ret_valid, n_bins: int, max_hold: int,
                         impl: str = "xla"):
    """Shard-local sums/counts for each cohort x horizon.

    Returns ``(sums f[2, M, H], counts f[2, M, H])`` over the (local) asset
    axis, side 0 = bottom decile, side 1 = top.  A distributed run psums
    these over the asset mesh axis before ``_finalize_cohorts``.

    ``impl='pallas'`` streams tiles through the fused VMEM kernel
    (:func:`csmom_tpu.ops.pallas_kernels.cohort_partial_sums_pallas`) —
    O(A*M) HBM traffic independent of H, vs the H rolled panel copies the
    XLA form materializes between fusion boundaries.  Interpreter mode off
    TPU keeps tests portable.

    ``impl='matmul'`` recasts the whole aggregation as two batched
    [2, M, A] @ [A, M] matmuls (membership^T @ returns and membership^T @
    validity, both sides in the stacked leading axis — the full formation
    x measurement-month cross table) followed by a diagonal-band gather of
    columns s+1..s+H.  2*A*M^2 FLOPs per matmul
    instead of H masked panel passes; on TPU this is MXU work, and the
    band gather reads 2*M*H elements.  Summation order differs from the
    elementwise forms, so float results agree to tolerance, not bitwise.

    ``impl='matmul_bf16'`` is the same cross table with bf16 operands and
    f32 accumulation — the TPU MXU's native fast path.  Counts stay exact
    (0/1 operands are representable; accumulation is f32); return sums
    carry bf16's ~3-decimal-digit input rounding, so this is the opt-in
    throughput mode, not parity mode.
    """
    if impl in ("matmul", "matmul_bf16"):
        A, M = ret.shape
        rf = jnp.where(ret_valid, jnp.nan_to_num(ret), 0.0)
        count_dtype = jnp.promote_types(rf.dtype, jnp.float32)
        mem = jnp.stack([labels == 0, labels == (n_bins - 1)])  # [2, A, M]
        if impl == "matmul_bf16":
            # MXU-native operands, f32 accumulation: membership and validity
            # are 0/1 (exact in bf16), so the COUNT cross table is exact to
            # 2^24; only the return sums carry bf16's ~3-decimal-digit input
            # rounding.  Opt-in reduced precision — the bf16 MXU path is the
            # chip's fast path for exactly this shape of work.
            mem = mem.astype(jnp.bfloat16)
            full_sums = jnp.einsum(
                "kas,am->ksm", mem, rf.astype(jnp.bfloat16),
                preferred_element_type=count_dtype,
            )
            full_cnts = jnp.einsum(
                "kas,am->ksm", mem, ret_valid.astype(jnp.bfloat16),
                preferred_element_type=count_dtype,
            )
        else:
            mem = mem.astype(rf.dtype)
            vf = ret_valid.astype(count_dtype)
            full_sums = jnp.einsum("kas,am->ksm", mem, rf)      # [2, M, M]
            full_cnts = jnp.einsum("kas,am->ksm", mem.astype(count_dtype), vf)
        col = jnp.arange(M)[:, None] + jnp.arange(1, max_hold + 1)[None, :]
        in_range = col < M                                       # [M, H]
        colc = jnp.clip(col, 0, M - 1)[None]
        sums = jnp.take_along_axis(full_sums, colc, axis=2)      # [2, M, H]
        counts = jnp.take_along_axis(full_cnts, colc, axis=2)
        keep = in_range[None]
        return jnp.where(keep, sums, 0.0), jnp.where(keep, counts, 0.0)
    if impl == "pallas":
        import jax as _jax

        from csmom_tpu.ops.pallas_kernels import cohort_partial_sums_pallas

        return cohort_partial_sums_pallas(
            ret, ret_valid, labels, n_bins=n_bins, max_hold=max_hold,
            interpret=_jax.default_backend() != "tpu",
        )
    if impl != "xla":
        raise ValueError(
            f"unknown impl {impl!r}: use 'xla', 'matmul', 'matmul_bf16' or "
            f"'pallas'"
        )
    A, M = ret.shape
    top = labels == (n_bins - 1)
    bot = labels == 0
    rf = jnp.where(ret_valid, jnp.nan_to_num(ret), 0.0)

    def at_horizon(h):
        # member return h months after formation: ret[:, s+h]
        r_h = jnp.roll(rf, -h, axis=1)
        v_h = jnp.roll(ret_valid, -h, axis=1)
        # months rolled past the end are dead
        alive = jnp.arange(M) < (M - h)
        v_h = v_h & alive[None, :]

        def side(m):
            mem = m & v_h
            return jnp.sum(jnp.where(mem, r_h, 0.0), axis=0), jnp.sum(mem, axis=0)

        bs, bn = side(bot)
        ts, tn = side(top)
        # counts must stay exact integers through the psum: bf16 panels would
        # round counts > 256, so promote to at least f32 (exact to 2^24)
        count_dtype = jnp.promote_types(rf.dtype, jnp.float32)
        return jnp.stack([bs, ts]), jnp.stack([bn, tn]).astype(count_dtype)

    cols = [at_horizon(h) for h in range(1, max_hold + 1)]
    sums = jnp.stack([c[0] for c in cols], axis=-1)    # [2, M, H]
    counts = jnp.stack([c[1] for c in cols], axis=-1)  # [2, M, H]
    return sums, counts


def _finalize_cohorts(sums, counts):
    """(possibly psum'd) partials -> (R f[M, H], R_valid bool[M, H])."""
    means = sums / jnp.maximum(counts, 1.0)
    ok = counts > 0
    R = means[1] - means[0]
    R_valid = ok[1] & ok[0]
    return R, R_valid


def _cohort_spreads(labels, ret, ret_valid, n_bins: int, max_hold: int,
                    impl: str = "xla"):
    """Forward spread of each formation cohort at horizons 1..max_hold.

    ``R[s, h-1]`` is the equal-weighted top-minus-bottom return of the
    cohort formed at s, h months after formation; valid iff both extreme
    deciles have >=1 member with a live return that month.
    """
    return _finalize_cohorts(
        *_cohort_partial_sums(labels, ret, ret_valid, n_bins, max_hold, impl=impl)
    )


def _holding_month_spreads(R, R_valid, Ks):
    """Cohort tensor -> per-(J, K) overlap-averaged spreads by holding month.

    Re-indexes cohorts by holding month (``D[j, m, h] = R[j, m-(h+1), h]``),
    prefix-sums over the horizon axis, and gathers each K — the JT 1/K
    overlap.  A month is live only when all K cohorts exist.  Shared by the
    single-device and sharded engines (their outputs must stay bit-equal).

    Args:
      R: f[nJ, M, H]; R_valid: bool[nJ, M, H]; Ks: i32[nK].

    Returns (spreads f[nJ, nK, M] NaN-filled, live bool[nJ, nK, M]).
    """
    nJ, M, H = R.shape
    src = jnp.arange(M)[:, None] - (jnp.arange(H)[None, :] + 1)
    in_range = src >= 0
    src_c = jnp.clip(src, 0, M - 1)
    D = R[:, src_c, jnp.arange(H)[None, :]]
    D_valid = R_valid[:, src_c, jnp.arange(H)[None, :]] & in_range[None, :, :]

    Dz = jnp.where(D_valid, D, 0.0)
    csum = jnp.cumsum(Dz, axis=2)
    cvalid = jnp.cumsum(D_valid.astype(jnp.int32), axis=2)

    k_idx = jnp.clip(Ks - 1, 0, H - 1)
    spreads = csum[:, :, k_idx] / jnp.maximum(Ks[None, None, :], 1)
    live = cvalid[:, :, k_idx] == Ks[None, None, :]
    spreads = jnp.transpose(spreads, (0, 2, 1))      # [nJ, nK, M]
    live = jnp.transpose(live, (0, 2, 1))
    return jnp.where(live, spreads, jnp.nan), live


def validate_grid_args(Ks, max_hold):
    """Shared host-side guard: the static horizon bound must cover max(Ks)."""
    import numpy as np

    if isinstance(Ks, jax.core.Tracer):
        if max_hold is None:
            raise ValueError(
                "grid backtest called with traced Ks and no max_hold: the "
                "static cohort-horizon bound cannot be inferred from a tracer, "
                "and a too-small default would silently invalidate K > "
                "max_hold columns — pass max_hold explicitly (>= max(Ks))"
            )
        return max_hold
    if max_hold is None:
        return int(np.max(Ks))
    if int(np.max(Ks)) > max_hold:
        raise ValueError(
            f"max(Ks)={int(np.max(Ks))} exceeds max_hold={max_hold}; raise "
            "max_hold (the static cohort-horizon bound) to cover every K"
        )
    return max_hold


def jk_grid_backtest(
    prices,
    mask,
    Js,
    Ks,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    max_hold: int | None = None,
    freq: int = 12,
    impl: str = "xla",
    donate_panels: bool = False,
) -> GridResult:
    """Run the full J x K momentum grid in one compiled call.

    Args:
      prices: f[A, M] month-end price panel.
      mask: bool[A, M].
      Js: i32[nJ] formation lookbacks (traced — any values).
      Ks: i32[nK] holding periods; max(Ks) must be <= max_hold.
      skip: months skipped between formation window and holding (static-free).
      n_bins: quantile bins.
      mode: ranking mode ('qcut' parity / 'rank' fast).
      max_hold: static horizon bound (defaults to max(Ks) when Ks is concrete).
      impl: cohort-aggregation kernel — 'xla' (rolled-panel reference form),
        'matmul' (MXU cross-table form, fastest at scale), 'matmul_bf16'
        (same with bf16 operands / f32 accumulation — opt-in reduced
        precision for the MXU fast path), or 'pallas' (fused VMEM kernel,
        TPU).
      donate_panels: donate the ``prices``/``mask`` device buffers to the
        call (``donate_argnums``) — at the north star the panel pair is
        the largest allocation on chip.  XLA realizes donation as
        input-output aliasing, so how much memory it actually reclaims is
        backend-dependent (the grid's outputs are [nJ, nK, M]-shaped, so
        current XLA may decline the alias with a "donated buffers were
        not usable" warning); what the flag GUARANTEES is the contract:
        the caller must treat its arrays as consumed after the call, and
        a loop re-feeding the same panels (bench's timed reps) must keep
        the default.
    """
    max_hold = validate_grid_args(Ks, max_hold)
    fn = _jk_grid_backtest_donated if donate_panels else _jk_grid_backtest
    return fn(
        prices, mask, Js, Ks, skip=skip, n_bins=n_bins, mode=mode,
        max_hold=max_hold, freq=freq, impl=impl,
    )


_GRID_STATICS = ("n_bins", "mode", "max_hold", "freq", "impl")


def _jk_grid_backtest_impl(
    prices, mask, Js, Ks, skip, n_bins, mode, max_hold, freq, impl="xla"
) -> GridResult:
    Js = jnp.asarray(Js)
    Ks = jnp.asarray(Ks)
    ret, ret_valid = monthly_returns(prices, mask)

    listed = formation_listed_mask(mask, skip)

    def per_J(J):
        mom, mom_valid = momentum_dynamic(prices, mask, J, skip)
        mom_valid = mom_valid & listed
        mom = jnp.where(mom_valid, mom, jnp.nan)
        labels, _ = decile_assign_panel(mom, mom_valid, n_bins=n_bins, mode=mode)
        return _cohort_spreads(labels, ret, ret_valid, n_bins, max_hold, impl=impl)

    R, R_valid = jax.vmap(per_J)(Js)  # [nJ, M, H], [nJ, M, H]
    spreads, spread_valid = _holding_month_spreads(R, R_valid, Ks)

    return GridResult(
        spreads=spreads,
        spread_valid=spread_valid,
        mean_spread=masked_mean(spreads, spread_valid),
        ann_sharpe=sharpe(spreads, spread_valid, freq_per_year=freq),
        tstat=t_stat(spreads, spread_valid),
        tstat_nw=nw_t_stat(spreads, spread_valid, lags=Ks[None, :],
                           max_lag=max_hold),
        Js=Js,
        Ks=Ks,
        skip=jnp.asarray(skip),
        n_bins=n_bins,
        mode=mode,
    )


# two jit wrappings of ONE body: the hot path must offer buffer donation
# (the AOT warm-start pipeline's dispatch-hygiene leg) without breaking the
# many callers that reuse their panels across calls — donation cannot be
# toggled per-call on a single jit, so the public wrapper picks the variant
_jk_grid_backtest = jax.jit(_jk_grid_backtest_impl, static_argnames=_GRID_STATICS)
_jk_grid_backtest_donated = jax.jit(
    _jk_grid_backtest_impl, static_argnames=_GRID_STATICS, donate_argnums=(0, 1)
)


def grid_net_of_costs(prices, mask, grid: GridResult,
                      half_spread: float = 0.0005, freq: int = 12,
                      donate_panels: bool = False):
    """Cost-netted J x K grid: exact overlapping-portfolio turnover.

    The month-m (J, K) portfolio is the 1/K average of the K most recent
    formation cohorts' equal-weight long-short books (the same alignment
    as :func:`_holding_month_spreads`: cohorts formed at m-K .. m-1).  Its
    weights are therefore a K-window rolling mean of the per-formation
    cohort weights, the month-over-month L1 weight change is the traded
    turnover, and ``half_spread`` per unit turnover nets the spread —
    BASELINE config 3 extended from the single monthly engine
    (:func:`csmom_tpu.backtest.monthly.net_of_costs`) to every grid cell.
    A K-month book naturally replaces ~1/K of itself each month, so the
    cost per month falls roughly as 1/K — the classic reason the paper's
    longer holding periods survive costs better.

    Formation labels are recomputed with the grid's own kernels
    (``momentum_dynamic`` + ``decile_assign_panel``) from the parameters
    the :class:`GridResult` itself carries (``Js/Ks/skip/n_bins/mode``),
    so no grid parameter can be re-specified inconsistently.  The one
    input still owed by the caller is the PANEL: ``prices``/``mask`` must
    be the arrays the grid was built from (the result does not retain
    them — at north-star scale that would double its footprint), or the
    recomputed books will not be the books behind ``grid.spreads``.
    Raises on a result that carries no parameters (e.g. the residual
    sweep, whose nK axis is not a holding axis).  Weights are the
    formation-date books (a later missing return is a data hole, not a
    trade).

    Host-side only: ``Ks`` and ``skip`` become static rolling windows, so
    the carried values are read back concretely — call this on a
    materialized result, not under an outer ``jit`` trace.

    Returns a :class:`GridResult` of the netted spreads (same validity
    and parameters).
    """
    import numpy as np

    if grid.Js is None or grid.Ks is None or grid.skip is None \
            or grid.n_bins is None or grid.mode is None:
        raise ValueError(
            "grid_net_of_costs needs the GridResult's build parameters "
            "(Js/Ks/skip/n_bins/mode), but this result carries none — it "
            "was not produced by jk_grid_backtest (the residual sweep's "
            "est_window axis, for one, is not a holding axis, so spread "
            "netting is undefined for it)"
        )
    if isinstance(grid.Ks, jax.core.Tracer) or isinstance(grid.skip, jax.core.Tracer):
        raise ValueError(
            "grid_net_of_costs is host-side: the carried Ks/skip define "
            "static rolling windows, so it cannot run under an outer jit "
            "trace — materialize the GridResult first, then net costs"
        )
    Ks_c = tuple(int(k) for k in np.asarray(grid.Ks))
    # donate_panels: the netting pass re-ranks the full panel, so its
    # prices/mask buffers are as donation-worthy as the grid's.  jnp.asarray
    # of a HOST array commits a fresh device buffer (safe to donate); only a
    # caller handing over live DEVICE panels gives up its copies.
    fn = _grid_net_core_donated if donate_panels else _grid_net_core
    return fn(
        jnp.asarray(prices), jnp.asarray(mask), jnp.asarray(grid.Js),
        grid.spreads, grid.spread_valid, half_spread,
        Ks_c=Ks_c, skip=int(np.asarray(grid.skip)), n_bins=grid.n_bins,
        mode=grid.mode, freq=freq,
    )


def grid_break_even_bps(prices, mask, grid: GridResult,
                        unit: GridResult | None = None):
    """Per-cell break-even transaction cost, in bps of half-spread.

    Turnover cost is LINEAR in the half-spread (cost_m = hs * L1 weight
    change), so one unit-cost netting run prices every cost level: the
    break-even half-spread of cell (J, K) is the gross mean spread per
    unit of mean turnover,

        be_bps[J, K] = mean(gross_m) / mean(turnover_m) * 1e4,

    the cost level at which the cell's mean monthly spread nets to zero.
    The classic JT/LeSw finding falls out: longer K replaces ~1/K of the
    book per month, so break-evens rise with K even as gross spreads fall.

    Same host-side contract as :func:`grid_net_of_costs` (parameters ride
    on the result; ``prices``/``mask`` must be the panel the grid was
    built from).  Pass ``unit`` — a ``grid_net_of_costs(..., half_spread
    =1.0)`` result — to reuse an existing netting run instead of
    recomputing the books (the CLI does; see :func:`grid_net_from_unit`).
    Returns ``(be_bps f[nJ, nK], mean_turnover f[nJ, nK])`` — cells with
    zero mean turnover report +/-inf by sign of the spread.
    """
    if unit is None:
        unit = grid_net_of_costs(prices, mask, grid, half_spread=1.0)
    # mean cost at hs=1 == mean turnover per month (masked to live months;
    # both spread tensors are already NaN outside spread_valid)
    mean_turn = masked_mean(grid.spreads - unit.spreads, grid.spread_valid)
    be = grid.mean_spread / mean_turn * 1e4
    return be, mean_turn


def grid_net_from_unit(grid: GridResult, unit: GridResult,
                       half_spread: float, freq: int = 12) -> GridResult:
    """Re-price a netted grid at any cost level from ONE unit-cost run.

    The cost series is linear in the half-spread, so with ``unit`` =
    ``grid_net_of_costs(..., half_spread=1.0)`` the per-month unit cost is
    ``grid.spreads - unit.spreads`` and any level is an elementwise
    re-price — no book recomputation.  Statistics (Sharpe, iid and
    Newey–West t) are re-assembled from the re-priced series, matching
    ``grid_net_of_costs(..., half_spread)`` exactly.
    """
    import numpy as np

    cost_unit = grid.spreads - unit.spreads
    net = jnp.where(grid.spread_valid, grid.spreads - half_spread * cost_unit,
                    jnp.nan)
    Ks_c = tuple(int(k) for k in np.asarray(grid.Ks))
    return GridResult(
        spreads=net,
        spread_valid=grid.spread_valid,
        mean_spread=masked_mean(net, grid.spread_valid),
        ann_sharpe=sharpe(net, grid.spread_valid, freq_per_year=freq),
        tstat=t_stat(net, grid.spread_valid),
        tstat_nw=nw_t_stat(net, grid.spread_valid,
                           lags=jnp.asarray(Ks_c)[None, :],
                           max_lag=max(Ks_c)),
        Js=grid.Js,
        Ks=grid.Ks,
        skip=grid.skip,
        n_bins=grid.n_bins,
        mode=grid.mode,
    )


_NET_STATICS = ("Ks_c", "skip", "n_bins", "mode", "freq")


def _grid_net_core_impl(prices, mask, Js, spreads, spread_valid, half_spread,
                        Ks_c: tuple, skip: int, n_bins: int, mode: str,
                        freq: int):
    from csmom_tpu.costs.impact import long_short_weights, turnover_cost
    from csmom_tpu.ops.rolling import _windowed_prefix_diff

    A, M = prices.shape
    moms, mvalids = jax.vmap(
        lambda J: momentum_dynamic(prices, mask, J, skip)
    )(Js)
    labels, _ = jax.vmap(
        lambda s, v: decile_assign_panel(s, v, n_bins=n_bins, mode=mode)
    )(moms, mvalids)                                   # i32[nJ, A, M]
    # long_short_weights reads only the two extreme bins' counts; build
    # exactly those rows instead of a full [nJ, B, A, M] one-hot
    bot_n = jnp.sum(labels == 0, axis=1).astype(jnp.int32)   # i32[nJ, M]
    top_n = jnp.sum(labels == n_bins - 1, axis=1).astype(jnp.int32)
    counts = jnp.zeros(
        (labels.shape[0], n_bins, M), jnp.int32
    ).at[:, 0].set(bot_n).at[:, n_bins - 1].set(top_n)
    w_f = jax.vmap(
        lambda l, c: long_short_weights(l, c, n_bins)
    )(labels, counts)                                  # f[nJ, A, M]

    # the per-K helper calls share one cumsum: the whole body is under one
    # jit, so XLA CSE dedupes _windowed_prefix_diff's identical prefix sum
    costs = []
    for K in Ks_c:
        # book at holding month m = mean of cohorts formed at m-K .. m-1
        S = _windowed_prefix_diff(w_f, K)
        w_pf = jnp.pad(S, ((0, 0), (0, 0), (1, 0)))[..., :M] / K
        costs.append(turnover_cost(w_pf, half_spread))  # f[nJ, M]
    cost = jnp.stack(costs, axis=1)                    # f[nJ, nK, M]

    net = jnp.where(spread_valid, spreads - cost, jnp.nan)
    Ks_arr = jnp.asarray(Ks_c)
    return GridResult(
        spreads=net,
        spread_valid=spread_valid,
        mean_spread=masked_mean(net, spread_valid),
        ann_sharpe=sharpe(net, spread_valid, freq_per_year=freq),
        tstat=t_stat(net, spread_valid),
        # same HAC bandwidth as the gross grid (lag = K), so gross-vs-net
        # significance is an apples-to-apples comparison
        tstat_nw=nw_t_stat(net, spread_valid, lags=Ks_arr[None, :],
                           max_lag=max(Ks_c)),
        Js=Js,
        Ks=Ks_arr,
        skip=jnp.asarray(skip),
        n_bins=n_bins,
        mode=mode,
    )


_grid_net_core = jax.jit(_grid_net_core_impl, static_argnames=_NET_STATICS)
_grid_net_core_donated = jax.jit(
    _grid_net_core_impl, static_argnames=_NET_STATICS, donate_argnums=(0, 1)
)
