"""Event-time horizon profile: momentum profit by months since formation.

Lee–Swaminathan (2000) track portfolio performance for up to five years
after formation (their Tables VI–VIII: momentum persists through year 1–2,
then *reverses*, with the reversal concentrated in high-volume winners —
``/root/reference/LeSw00.pdf``).  The reference framework computes only the
K=1 holding return (``run_demo.py:31-79``) and has no event-time view at
all; this module supplies it.

TPU-first: no new engine is needed.  The grid engine's cohort tensor
``R[s, h]`` (spread of the cohort formed at month s, measured h+1 months
after formation — ``backtest.grid._cohort_spreads``) already contains every
(formation, horizon) observation; the profile is a masked reduction over
the formation axis at each horizon, one jit call for all horizons, with
Newey–West inference per horizon (adjacent cohorts hold overlapping
positions, so the event-time series is serially correlated by
construction).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.analytics.stats import masked_mean, nw_t_stat, t_stat
from csmom_tpu.backtest.grid import _cohort_spreads  # shared cohort kernel
from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.signals.momentum import (
    formation_listed_mask,
    momentum_dynamic,
    monthly_returns,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HorizonProfile:
    """Per-horizon event-time statistics; every array is [H] (h = 1..H
    months after formation)."""

    mean_spread: jnp.ndarray   # f[H] mean top-minus-bottom return at horizon h
    tstat_nw: jnp.ndarray      # f[H] Newey–West t (rule-of-thumb bandwidth)
    tstat: jnp.ndarray         # f[H] iid t, for reference
    n_cohorts: jnp.ndarray     # i32[H] live cohorts entering each mean
    cum_spread: jnp.ndarray    # f[H] cumulative sum of mean_spread — the
                               # JT event-time curve whose hump-then-decline
                               # is the persistence/reversal picture


@partial(jax.jit, static_argnames=("n_bins", "mode", "max_h"))
def horizon_profile(
    prices,
    mask,
    lookback: int = 6,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    max_h: int = 36,
) -> HorizonProfile:
    """Event-time momentum profile over horizons 1..max_h.

    Args:
      prices: f[A, M] month-end price panel.
      mask: bool[A, M].
      lookback: formation months J (traced; any value).
      skip: months skipped between formation and measurement.
      n_bins: quantile bins (top-minus-bottom spread).
      mode: ranking mode ('qcut' parity / 'rank' fast / see ops.ranking).
      max_h: static horizon bound (the paper's five-year view is max_h=60).
    """
    ret, ret_valid = monthly_returns(prices, mask)
    mom, mom_valid = momentum_dynamic(prices, mask, lookback, skip)
    # same delisting rule as every ranking engine: pad semantics carry a
    # delisted asset's signal, the listed mask drops it from new cohorts
    mom_valid = mom_valid & formation_listed_mask(mask, skip)
    mom = jnp.where(mom_valid, mom, jnp.nan)
    labels, _ = decile_assign_panel(mom, mom_valid, n_bins=n_bins, mode=mode)
    R, R_valid = _cohort_spreads(labels, ret, ret_valid, n_bins, max_h)  # [M, H]

    Rs, Vs = R.T, R_valid.T                      # [H, M]: horizon-major
    mean_h = masked_mean(Rs, Vs)
    cum = jnp.cumsum(jnp.nan_to_num(mean_h))
    # max_lag bounds the NW bandwidth UNROLL, not the bandwidth itself: the
    # event-time series runs over formation months, so the rule-of-thumb
    # bandwidth must not be truncated by the unrelated horizon count max_h
    return HorizonProfile(
        mean_spread=mean_h,
        tstat_nw=nw_t_stat(Rs, Vs, lags=None, max_lag=24),
        tstat=t_stat(Rs, Vs),
        n_cohorts=jnp.sum(Vs, axis=-1).astype(jnp.int32),
        cum_spread=cum,
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class VolumeHorizonProfile:
    """Per-(volume tercile, horizon) event-time statistics; arrays are
    [V, H] (tercile-major; V1 = low volume)."""

    mean_spread: jnp.ndarray   # f[V, H]
    tstat_nw: jnp.ndarray      # f[V, H]
    n_cohorts: jnp.ndarray     # i32[V, H]
    cum_spread: jnp.ndarray    # f[V, H]
    diff_mean: jnp.ndarray     # f[H] V_high - V_low mean spread by horizon
    diff_tstat_nw: jnp.ndarray # f[H] NW t of that difference series


@partial(jax.jit, static_argnames=("n_bins", "n_vol_bins", "mode", "max_h"))
def volume_horizon_profile(
    prices,
    mask,
    turnover,
    turnover_valid,
    lookback: int = 6,
    skip: int = 1,
    n_bins: int = 10,
    n_vol_bins: int = 3,
    mode: str = "qcut",
    max_h: int = 36,
) -> VolumeHorizonProfile:
    """Event-time profile conditioned on trading volume — the paper's
    "momentum life cycle" (LeSw00 Table VIII): high-volume winners carry
    late-stage momentum that reverses sooner and harder than low-volume
    momentum.  Independent double sort at formation (same construction as
    :func:`csmom_tpu.backtest.double_sort.volume_double_sort`), then the
    MXU cross-table form per (tercile, side): membership^T @ returns with
    a diagonal-band gather, one jit call for all (V, H) cells.
    """
    from csmom_tpu.signals.turnover import volume_tercile_labels

    ret, ret_valid = monthly_returns(prices, mask)
    mom, mom_valid = momentum_dynamic(prices, mask, lookback, skip)
    mom_valid = mom_valid & formation_listed_mask(mask, skip)
    mom = jnp.where(mom_valid, mom, jnp.nan)
    mom_labels, _ = decile_assign_panel(mom, mom_valid, n_bins=n_bins, mode=mode)
    both = mom_valid & turnover_valid
    vol_labels, _ = volume_tercile_labels(
        jnp.where(both, turnover, jnp.nan), both, n_vol_bins=n_vol_bins, mode=mode
    )

    # restrict the momentum labels to one tercile at a time (-1 = outside),
    # then the grid engine's MXU cross-table kernel does the rest — one
    # shared implementation of the band-gather/masking invariants
    def per_tercile(v):
        labels_v = jnp.where(vol_labels == v, mom_labels, -1)
        return _cohort_spreads(labels_v, ret, ret_valid, n_bins, max_h,
                               impl="matmul")

    R, R_valid = jax.vmap(per_tercile)(jnp.arange(n_vol_bins))  # [V, M, H]

    Rs = jnp.swapaxes(R, 1, 2)                                # [V, H, M]
    Vs = jnp.swapaxes(R_valid, 1, 2)
    mean_vh = masked_mean(Rs, Vs)
    both_v = Vs[-1] & Vs[0]                                   # [H, M]
    diff = jnp.where(both_v, Rs[-1] - Rs[0], jnp.nan)
    return VolumeHorizonProfile(
        mean_spread=mean_vh,
        tstat_nw=nw_t_stat(Rs, Vs, lags=None, max_lag=24),
        n_cohorts=jnp.sum(Vs, axis=-1).astype(jnp.int32),
        cum_spread=jnp.cumsum(jnp.nan_to_num(mean_vh), axis=-1),
        diff_mean=masked_mean(diff, both_v),
        diff_tstat_nw=nw_t_stat(diff, both_v, lags=None, max_lag=24),
    )
