"""Event-time horizon profile: momentum profit by months since formation.

Lee–Swaminathan (2000) track portfolio performance for up to five years
after formation (their Tables VI–VIII: momentum persists through year 1–2,
then *reverses*, with the reversal concentrated in high-volume winners —
``/root/reference/LeSw00.pdf``).  The reference framework computes only the
K=1 holding return (``run_demo.py:31-79``) and has no event-time view at
all; this module supplies it.

TPU-first: no new engine is needed.  The grid engine's cohort tensor
``R[s, h]`` (spread of the cohort formed at month s, measured h+1 months
after formation — ``backtest.grid._cohort_spreads``) already contains every
(formation, horizon) observation; the profile is a masked reduction over
the formation axis at each horizon, one jit call for all horizons, with
Newey–West inference per horizon (adjacent cohorts hold overlapping
positions, so the event-time series is serially correlated by
construction).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.analytics.stats import masked_mean, nw_t_stat, t_stat
from csmom_tpu.backtest.grid import _cohort_spreads
from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.signals.momentum import momentum_dynamic, monthly_returns


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HorizonProfile:
    """Per-horizon event-time statistics; every array is [H] (h = 1..H
    months after formation)."""

    mean_spread: jnp.ndarray   # f[H] mean top-minus-bottom return at horizon h
    tstat_nw: jnp.ndarray      # f[H] Newey–West t (rule-of-thumb bandwidth)
    tstat: jnp.ndarray         # f[H] iid t, for reference
    n_cohorts: jnp.ndarray     # i32[H] live cohorts entering each mean
    cum_spread: jnp.ndarray    # f[H] cumulative sum of mean_spread — the
                               # JT event-time curve whose hump-then-decline
                               # is the persistence/reversal picture


@partial(jax.jit, static_argnames=("n_bins", "mode", "max_h"))
def horizon_profile(
    prices,
    mask,
    lookback: int = 6,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    max_h: int = 36,
) -> HorizonProfile:
    """Event-time momentum profile over horizons 1..max_h.

    Args:
      prices: f[A, M] month-end price panel.
      mask: bool[A, M].
      lookback: formation months J (traced; any value).
      skip: months skipped between formation and measurement.
      n_bins: quantile bins (top-minus-bottom spread).
      mode: ranking mode ('qcut' parity / 'rank' fast / see ops.ranking).
      max_h: static horizon bound (the paper's five-year view is max_h=60).
    """
    ret, ret_valid = monthly_returns(prices, mask)
    mom, mom_valid = momentum_dynamic(prices, mask, lookback, skip)
    labels, _ = decile_assign_panel(mom, mom_valid, n_bins=n_bins, mode=mode)
    R, R_valid = _cohort_spreads(labels, ret, ret_valid, n_bins, max_h)  # [M, H]

    Rs, Vs = R.T, R_valid.T                      # [H, M]: horizon-major
    mean_h = masked_mean(Rs, Vs)
    cum = jnp.cumsum(jnp.nan_to_num(mean_h))
    # max_lag bounds the NW bandwidth UNROLL, not the bandwidth itself: the
    # event-time series runs over formation months, so the rule-of-thumb
    # bandwidth must not be truncated by the unrelated horizon count max_h
    return HorizonProfile(
        mean_spread=mean_h,
        tstat_nw=nw_t_stat(Rs, Vs, lags=None, max_lag=24),
        tstat=t_stat(Rs, Vs),
        n_cohorts=jnp.sum(Vs, axis=-1).astype(jnp.int32),
        cum_spread=cum,
    )
