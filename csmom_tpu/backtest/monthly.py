"""Vectorized monthly cross-sectional decile backtest.

Replaces the reference's ``monthly_replication`` driver
(``/root/reference/run_demo.py:31-79``): momentum signal -> per-date decile
sort -> equal-weighted decile means of next-month returns -> top-minus-bottom
spread -> Sharpe.  The reference's groupby/unstack pipeline becomes a handful
of masked one-hot matmuls over the ``[A, M]`` panel — the whole backtest is
one jit-compiled call with no Python in the loop, which is what makes the
J x K grid a trivial ``vmap`` and the asset axis shardable.

Semantics parity notes (each verified by the golden test against the
BASELINE measured numbers):

- Deciles are assigned over all mom-valid assets at each date *including*
  assets whose next-month return is missing; those assets drop out only from
  the decile means (reference order: decile transform at ``run_demo.py:46``
  precedes ``dropna(['next_ret','decile'])`` at ``:49``).
- ``next_ret[a, t] = ret[a, t+1]`` with both months valid — identical to the
  reference's post-filter ``pct_change().shift(-1)`` on contiguous
  histories (SURVEY §2.1.5 documents the gappy-history caveat).
- The spread is ``decile_mean[9] - decile_mean[0]``; a date where either
  extreme decile is empty (qcut collapsed bins) yields an invalid spread,
  mirroring NaN rows dropped at ``run_demo.py:67``.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.ops.ranking import decile_assign_panel, sector_decile_assign_panel
from csmom_tpu.signals.momentum import (
    formation_listed_mask,
    momentum,
    monthly_returns,
)
from csmom_tpu.analytics.stats import sharpe, masked_mean, t_stat, nw_t_stat
from csmom_tpu.costs.impact import long_short_weights, turnover_cost


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MonthlyResult:
    """Outputs of one monthly decile backtest (all arrays time-indexed)."""

    spread: jnp.ndarray        # f[M] top-minus-bottom next-month return
    spread_valid: jnp.ndarray  # bool[M]
    decile_means: jnp.ndarray  # f[n_bins, M] equal-weighted decile returns
    decile_counts: jnp.ndarray # i32[n_bins, M]
    labels: jnp.ndarray        # i32[A, M] decile id at formation, -1 invalid
    mean_spread: jnp.ndarray   # scalar
    ann_sharpe: jnp.ndarray    # scalar
    tstat: jnp.ndarray         # scalar plain iid t-stat (oracle-matched)
    tstat_nw: jnp.ndarray      # scalar Newey–West t-stat (auto bandwidth) —
                               # the inference the replicated paper quotes


def decile_partial_sums(next_ret, next_valid, labels, n_bins: int,
                        impl: str = "xla"):
    """Per-(decile, date) sums and counts over the (local) asset axis.

    One-hot membership matmul instead of groupby.  Returns
    ``(sums f[B, M], counts i32[B, M])`` — the shard-local partials that a
    distributed run ``psum``s over the asset mesh axis before ``decile_means``
    divides (the only reduction the portfolio step needs).

    ``impl='pallas'`` uses the fused VMEM-tiled kernel
    (:mod:`csmom_tpu.ops.pallas_kernels`; ~13x the XLA path at 3000x720 on
    a v5e chip) — numerically equal up to f32 reduction order.  It runs in
    interpreter mode automatically off-TPU so tests stay portable.
    """
    if impl == "pallas":
        import jax as _jax

        from csmom_tpu.ops.pallas_kernels import decile_partial_sums_pallas

        lab = jnp.where(next_valid, labels, -1)
        r = jnp.where(lab >= 0, jnp.nan_to_num(next_ret), 0.0)
        sums, counts = decile_partial_sums_pallas(
            r, lab, n_bins=n_bins,
            interpret=_jax.default_backend() != "tpu",
        )
        return sums, counts.astype(jnp.int32)
    bins = jnp.arange(n_bins, dtype=labels.dtype)
    member = (labels[None, :, :] == bins[:, None, None]) & next_valid[None, :, :]
    r = jnp.where(next_valid, jnp.nan_to_num(next_ret), 0.0)
    sums = jnp.sum(member * r[None, :, :], axis=1)
    counts = jnp.sum(member, axis=1)
    return sums, counts.astype(jnp.int32)


def decile_means(sums, counts):
    """Finalize per-decile equal-weighted means from (possibly psum'd) partials."""
    return jnp.where(counts > 0, sums / jnp.maximum(counts, 1), jnp.nan)


def decile_portfolio_returns(next_ret, next_valid, labels, n_bins: int,
                             impl: str = "xla"):
    """Equal-weighted mean next-period return per (decile, date):
    ``(means f[B, M], counts i32[B, M])``."""
    sums, counts = decile_partial_sums(next_ret, next_valid, labels, n_bins, impl=impl)
    return decile_means(sums, counts), counts


def _assemble_result(ret, ret_valid, labels, n_bins: int, freq: int,
                     impl: str = "xla") -> MonthlyResult:
    """Shared tail of the monthly engines: align next-month returns to the
    formation date, pool decile means, and wrap the spread stats.  Formation
    validity is carried entirely by ``labels`` (>= 0 == ranked that date), so
    the plain and sector-neutral engines stay bit-identical here."""
    next_ret = jnp.roll(ret, -1, axis=1)
    next_valid = jnp.roll(ret_valid, -1, axis=1).at[:, -1].set(False)
    next_valid = next_valid & (labels >= 0)

    means, counts = decile_portfolio_returns(next_ret, next_valid, labels, n_bins,
                                             impl=impl)
    spread = means[n_bins - 1] - means[0]
    spread_valid = (counts[n_bins - 1] > 0) & (counts[0] > 0)
    spread = jnp.where(spread_valid, spread, jnp.nan)

    return MonthlyResult(
        spread=spread,
        spread_valid=spread_valid,
        decile_means=means,
        decile_counts=counts,
        labels=labels,
        mean_spread=masked_mean(spread, spread_valid),
        ann_sharpe=sharpe(spread, spread_valid, freq_per_year=freq),
        tstat=t_stat(spread, spread_valid),
        tstat_nw=nw_t_stat(spread, spread_valid),
    )


@partial(jax.jit, static_argnames=("lookback", "skip", "n_bins", "mode", "freq", "impl"))
def monthly_spread_backtest(
    prices,
    mask,
    lookback: int = 12,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    freq: int = 12,
    impl: str = "xla",
) -> MonthlyResult:
    """Full monthly momentum replication on a month-end price panel.

    Args:
      prices: f[A, M] month-end (adjusted) prices, NaN at masked slots.
      mask: bool[A, M] observation mask.
      lookback: J months compounded into the formation signal.
      skip: skip months between window end and formation.
      n_bins: cross-sectional quantile bins (10 = deciles).
      mode: 'qcut' for pandas parity, 'rank' for the fast path at scale.
      freq: periods per year for annualization (12 for monthly).
    """
    ret, ret_valid = monthly_returns(prices, mask)
    mom, mom_valid = momentum(prices, mask, lookback=lookback, skip=skip)
    # run_demo forms the signal from raw shifted prices: an asset drops out
    # of ranking once delisted at the window-end month (pad semantics still
    # carry it through interior gaps)
    mom_valid = mom_valid & formation_listed_mask(mask, skip)
    mom = jnp.where(mom_valid, mom, jnp.nan)
    labels, _ = decile_assign_panel(mom, mom_valid, n_bins=n_bins, mode=mode)
    return _assemble_result(ret, ret_valid, labels, n_bins, freq, impl=impl)


@partial(jax.jit, static_argnames=("n_sectors", "lookback", "skip", "n_bins", "mode", "freq"))
def sector_neutral_backtest(
    prices,
    mask,
    sector_ids,
    n_sectors: int,
    lookback: int = 12,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    freq: int = 12,
) -> MonthlyResult:
    """Monthly decile backtest with sector-neutral ranking (BASELINE config 3).

    Identical to :func:`monthly_spread_backtest` except the formation-date
    bins come from :func:`~csmom_tpu.ops.ranking.sector_decile_assign_panel`:
    each asset is ranked only within its sector, and the pooled top/bottom
    bins across sectors form the long-short legs, so the spread carries no
    net sector tilt.  The reference has no sector machinery at all (its
    universe is 20 hand-picked large caps, ``run_demo.py:15-16``); this is
    the BASELINE.json config-3 extension expressed the panel way.

    ``sector_ids`` is i32[A] in ``[0, n_sectors)``; negative ids mark
    unclassified assets, which are excluded from ranking (like masked
    lanes).
    """
    ret, ret_valid = monthly_returns(prices, mask)
    mom, mom_valid = momentum(prices, mask, lookback=lookback, skip=skip)
    mom_valid = mom_valid & formation_listed_mask(mask, skip)
    mom = jnp.where(mom_valid, mom, jnp.nan)
    labels, _ = sector_decile_assign_panel(
        mom, mom_valid, sector_ids, n_sectors, n_bins=n_bins, mode=mode
    )
    return _assemble_result(ret, ret_valid, labels, n_bins, freq)


@partial(jax.jit, static_argnames=("n_bins", "freq"))
def net_of_costs_arrays(
    labels,
    decile_counts,
    spread,
    spread_valid,
    half_spread: float = 0.0005,
    n_bins: int = 10,
    freq: int = 12,
):
    """Array-level core of :func:`net_of_costs` — takes exactly the four
    panel outputs the cost adjustment needs, so callers holding a host-side
    report (e.g. the CLI's ``MonthlyReport``) don't have to fabricate an
    engine-internal :class:`MonthlyResult`."""
    w = long_short_weights(labels, decile_counts, n_bins)
    cost = turnover_cost(w, half_spread)
    net = jnp.where(spread_valid, spread - cost, jnp.nan)
    return (
        net,
        masked_mean(net, spread_valid),
        sharpe(net, spread_valid, freq_per_year=freq),
    )


def net_of_costs(
    result: MonthlyResult,
    half_spread: float = 0.0005,
    n_bins: int = 10,
    freq: int = 12,
):
    """Spread series net of linear transaction costs (BASELINE config 3).

    Charges ``half_spread`` per unit of weight turnover on the equal-weight
    long-short portfolio implied by the decile labels.  Returns
    ``(net_spread f[M], net_mean, net_sharpe)``; validity is unchanged (costs
    only shift live months).
    """
    return net_of_costs_arrays(
        result.labels, result.decile_counts, result.spread,
        result.spread_valid, half_spread=half_spread, n_bins=n_bins,
        freq=freq,
    )
