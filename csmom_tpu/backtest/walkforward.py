"""Walk-forward (J, K) hyperparameter sweep (BASELINE config 5).

Out-of-sample strategy selection: at every month m, pick the grid cell
with the best annualized Sharpe over all *prior* months (expanding window),
and realize that cell's month-m spread.  The reference has no model
selection at all (one hardcoded J=12/K=1 cell, ``run_demo.py:32``); this is
the standard antidote to grid-level lookahead when reporting a single
tradable series from a J x K sweep.

TPU-first: no re-running of backtests per split.  The grid engine already
returns every cell's full spread series in one call; expanding-window
statistics for *all* months are prefix sums (``cumsum`` over time of x,
x^2 and the live mask), so the entire sweep — selection at every month for
every cell — is O(G * M) fused elementwise work on top of one grid call.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.analytics.stats import masked_mean, sharpe, t_stat, nw_t_stat
from csmom_tpu.backtest.grid import jk_grid_backtest, validate_grid_args


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class WalkForwardResult:
    """Out-of-sample selection path and its realized spread series."""

    choice: jnp.ndarray        # i32[M] flat grid-cell index chosen at month m (-1 = none eligible)
    insample_sharpe: jnp.ndarray  # f[G, M] expanding-window Sharpe used for selection
    oos_spread: jnp.ndarray    # f[M] realized spread of the chosen cell
    oos_valid: jnp.ndarray     # bool[M]
    mean_spread: jnp.ndarray   # scalar (masked over oos_valid)
    ann_sharpe: jnp.ndarray    # scalar
    tstat: jnp.ndarray         # scalar plain iid t-stat
    tstat_nw: jnp.ndarray      # scalar Newey–West t-stat (auto bandwidth)


def _expanding_sharpe(x, live, freq: int):
    """f[G, M] annualized Sharpe of each series over months [0, m) (strictly
    prior — the month-m value is not in its own selection window).

    NaN where fewer than 2 live prior months or zero variance, matching
    ``analytics.stats.sharpe`` semantics on the same window.
    """
    xf = jnp.where(live, jnp.nan_to_num(x), 0.0)
    n = jnp.cumsum(live, axis=-1).astype(xf.dtype)
    s = jnp.cumsum(xf, axis=-1)
    ss = jnp.cumsum(xf * xf, axis=-1)
    # shift right: stats at m cover months 0..m-1
    pad = lambda a: jnp.concatenate([jnp.zeros_like(a[..., :1]), a[..., :-1]], axis=-1)
    n, s, ss = pad(n), pad(s), pad(ss)
    mean = s / jnp.maximum(n, 1.0)
    var = (ss - n * mean * mean) / jnp.maximum(n - 1.0, 1.0)
    ok = (n >= 2) & (var > 0)
    sh = jnp.where(ok, mean / jnp.sqrt(jnp.where(ok, var, 1.0)) * jnp.sqrt(float(freq)), jnp.nan)
    return sh, n


@partial(jax.jit, static_argnames=("min_months", "freq"))
def walk_forward_select(
    spreads,
    spread_valid,
    min_months: int = 24,
    freq: int = 12,
) -> WalkForwardResult:
    """Select among pre-computed spread series, strictly out-of-sample.

    Args:
      spreads: f[..., M] grid of spread series (leading axes flattened into
        one cell axis G).
      spread_valid: bool[..., M].
      min_months: minimum live prior months before a cell is eligible; until
        any cell qualifies the OOS series is invalid (warmup).
      freq: periods per year for annualization.
    """
    M = spreads.shape[-1]
    x = spreads.reshape(-1, M)
    live = spread_valid.reshape(-1, M)

    sh, n_prior = _expanding_sharpe(x, live, freq)
    eligible = (n_prior >= min_months) & jnp.isfinite(sh)
    score = jnp.where(eligible, sh, -jnp.inf)
    any_eligible = jnp.any(eligible, axis=0)
    choice = jnp.where(any_eligible, jnp.argmax(score, axis=0), -1).astype(jnp.int32)

    cols = jnp.arange(M)
    chosen = jnp.clip(choice, 0, x.shape[0] - 1)
    oos_valid = any_eligible & live[chosen, cols]
    oos = jnp.where(oos_valid, x[chosen, cols], jnp.nan)

    return WalkForwardResult(
        choice=choice,
        insample_sharpe=sh,
        oos_spread=oos,
        oos_valid=oos_valid,
        mean_spread=masked_mean(oos, oos_valid),
        ann_sharpe=sharpe(oos, oos_valid, freq_per_year=freq),
        tstat=t_stat(oos, oos_valid),
        tstat_nw=nw_t_stat(oos, oos_valid),
    )


def walk_forward_grid_backtest(
    prices,
    mask,
    Js,
    Ks,
    skip: int = 1,
    n_bins: int = 10,
    mode: str = "qcut",
    max_hold: int | None = None,
    min_months: int = 24,
    freq: int = 12,
    impl: str = "xla",
):
    """End-to-end walk-forward sweep: one grid call + one selection pass.

    Returns ``(WalkForwardResult, GridResult)``; the chosen flat index maps
    to (J, K) as ``choice // len(Ks), choice % len(Ks)``.
    """
    max_hold = validate_grid_args(Ks, max_hold)
    grid = jk_grid_backtest(
        prices, mask, Js, Ks, skip=skip, n_bins=n_bins, mode=mode,
        max_hold=max_hold, freq=freq, impl=impl,
    )
    wf = walk_forward_select(
        grid.spreads, grid.spread_valid, min_months=min_months, freq=freq
    )
    return wf, grid
