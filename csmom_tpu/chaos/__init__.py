"""Deterministic fault injection + capture-pipeline rehearsal.

Two consecutive TPU tunnel windows were lost to builder-controllable
failures (r4: a SIGKILL at the external timeout discarded a fully measured
headline; r5: the supervisor's own attempt cap killed the bench child
mid-compile with every measurement unprinted — ``benchmarks/
CAPTURES_r05.md``).  The fix set (``utils/deadline.py``, ``utils/
jit_cache.py``, ``compile/aot.py``, ``benchmarks/capture_lib.sh``) is only
trustworthy if it can be *proven* offline: this package injects those
failures deterministically and rehearses the full supervisor → warmup →
bench → deadline → land pipeline under each one, on a CPU-only machine,
before a scarce tunnel window opens.

Layout:

- :mod:`~csmom_tpu.chaos.plan` — seeded, serializable fault plans
  (``CSMOM_FAULT_PLAN`` env var pointing at a TOML file, or inline TOML).
- :mod:`~csmom_tpu.chaos.inject` — the ``checkpoint("name")`` hooks
  threaded through bench.py, compile/aot.py, and utils/deadline.py.
  No-ops unless a plan is armed: the unarmed fast path is one dict lookup
  in ``os.environ``, no imports, no allocation.
- :mod:`~csmom_tpu.chaos.invariants` — schema validation for every landed
  artifact (headline lines, full records, driver captures, multichip
  summaries, partials and their monotone-upgrade rule).
- :mod:`~csmom_tpu.chaos.minibench` — a jax-free miniature capture child
  (measured rows + deadline guard + trailing JSON) for sub-second
  rehearsal of the capture *path* without the bench *workload*.

The operator entry point is ``csmom rehearse`` (:mod:`csmom_tpu.cli.
rehearse`): the built-in fault matrix, a pass/fail table, and a nonzero
exit on any invariant violation so watcher scripts can gate on it.

The reference has no analogue (single process, no measurement harness);
this is the evidence-discipline layer of the TPU rebuild, and its shape —
chaos testing for a distributed measurement/serving pipeline — transfers
directly to training/inference stacks.
"""

from csmom_tpu.chaos.inject import checkpoint  # noqa: F401
from csmom_tpu.chaos.plan import Fault, FaultPlan  # noqa: F401
