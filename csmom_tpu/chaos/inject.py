"""Checkpoint runtime: where armed fault plans actually fire.

Instrumentation points call ``checkpoint("bench.compile", leg=name)``.
Unarmed (no ``CSMOM_FAULT_PLAN`` and no ``CSMOM_TELEMETRY`` in the
environment) the call is two ``os.environ`` membership tests — no
imports, no allocation — so the hot measurement path pays nothing.
Armed, the active plan is parsed once per process and each visit is
counted per checkpoint name; faults whose (point pattern, role, hit
window) match execute their action.

Every checkpoint site doubles as a telemetry event: when run telemetry
is armed (:mod:`csmom_tpu.obs`), the visit is recorded as a durationless
point in the run's event stream BEFORE any fault fires — so a fault that
kills the process still leaves "we reached bench.row" in the timeline,
which is exactly the post-mortem breadcrumb the r4/r5 forensics lacked.

Self-executing actions (kill / exit / sleep / trip_deadline / clock_skew /
corrupt_file / truncate_file / stdout_noise) happen inside the call;
``raise_oserror`` propagates an ``OSError`` into the caller's existing
error handling (that handling surviving the error IS the invariant); and
``fail`` returns the string ``"fail"`` for control-flow points whose
failure mode is a *result*, not an exception (e.g. a tunnel probe).

The checkpoint inventory is CODE, not prose: ``chaos.plan.KNOWN_POINTS``
holds every point name, and the enumeration-drift rule in ``csmom lint``
cross-checks it against the literal ``checkpoint("...")`` call sites in
both directions on every sweep.  (A prose table used to live here; by
ISSUE 11 it had silently lost ``mini.start`` and ``serve.cache`` — the
drift the vocabulary now makes impossible.)

The ``serve.*`` points run in the signal service's own threads.  In the
SINGLE-process service, process-fatal actions (kill/exit) take the whole
service down, so the rehearsed in-process worker-crash fault is the
``fail`` action at ``serve.dispatch`` (the batch terminates ``rejected``
and the queue stays drainable).  In the POOL, each worker is its own
process that inherits the fault plan from the supervisor, so a ``kill``
at ``serve.dispatch`` is a REAL worker-process death mid-batch — pair it
with ``global_once`` so exactly one worker in the fleet dies; the
router's hedged retries and the supervisor's backoff restart are what
the scenario then measures.
"""

from __future__ import annotations

import glob
import os
import random
import sys
import threading
import time

from csmom_tpu.chaos.plan import PLAN_ENV, current_role, load_active_plan

__all__ = ["checkpoint", "reset"]

# csmom_tpu.obs.spans.ENV_STREAM, spelled out so the unarmed fast path
# never imports the obs package just to read one constant
_OBS_ENV = "CSMOM_TELEMETRY"


def _obs_point(point: str, ctx: dict) -> None:
    """Mirror a checkpoint visit into the armed telemetry stream.

    No-op (after the lazy import) in processes that inherited the env
    var but never armed a collector; never raises — observability must
    not become a new fault injector."""
    try:
        from csmom_tpu.obs import spans as _spans

        if _spans._COLLECTOR is not None:
            _spans.point(f"chaos.{point}", **ctx)
    except Exception:
        pass

_STATE_LOCK = threading.Lock()
_PLAN = None
_PLAN_LOADED = False
_HITS: dict = {}


def reset() -> None:
    """Forget the cached plan and hit counters (tests re-arm per case)."""
    global _PLAN, _PLAN_LOADED
    with _STATE_LOCK:
        _PLAN = None
        _PLAN_LOADED = False
        _HITS.clear()


def _plan():
    global _PLAN, _PLAN_LOADED
    if not _PLAN_LOADED:
        with _STATE_LOCK:
            if not _PLAN_LOADED:
                _PLAN = load_active_plan()
                _PLAN_LOADED = True
    return _PLAN


def checkpoint(point: str, **ctx) -> str | None:
    """Visit an instrumentation point; fire any matching armed faults.

    Returns the last fired action name (``"fail"`` is the one callers
    branch on), or None when nothing fired.  Unarmed cost: two environ
    lookups (fault plan + telemetry).
    """
    if os.environ.get(_OBS_ENV, "0") not in ("", "0"):
        # telemetry first, fault second: a kill/exit fault must not erase
        # the evidence that its checkpoint was reached
        _obs_point(point, ctx)
    if PLAN_ENV not in os.environ:
        return None
    plan = _plan()
    if plan is None or not plan.faults:
        return None
    with _STATE_LOCK:
        hit = _HITS.get(point, 0)
        _HITS[point] = hit + 1
    role = current_role()
    fired = None
    for i, fault in enumerate(plan.faults):
        if fault.matches(point, hit, role):
            if fault.global_once and not _claim_global(plan, i):
                continue  # another process in the tree already fired this
            _execute(fault, plan.seed + i, point, ctx)
            fired = fault.action
    return fired


def _claim_global(plan, fault_index: int) -> bool:
    """Atomically claim a tree-wide single firing of fault ``fault_index``.

    The claim is an ``O_CREAT | O_EXCL`` marker file in
    ``CSMOM_FAULT_STATE``, which the whole process tree shares by env
    inheritance (``csmom rehearse`` sets it per scenario sandbox).
    Exactly one process wins; a SIGKILLed winner leaves the marker
    behind, which is the point — its successors must not re-fire.

    Without ``CSMOM_FAULT_STATE`` a FRESH tempdir is created and exported
    into this process's environment so its descendants share it.  A
    run-keyed dir, not a plan-keyed one: a stale marker from yesterday's
    manually-armed run must not silently disarm today's fault (a
    rehearsal that never experienced its fault certifies nothing).  The
    cost: siblings spawned by an ancestor that never claimed first do not
    share a dir — trees that need cross-sibling global_once must set
    ``CSMOM_FAULT_STATE`` explicitly.
    """
    import tempfile

    state = os.environ.get("CSMOM_FAULT_STATE", "")
    if not state:
        state = tempfile.mkdtemp(prefix="csmom_chaos_")
        os.environ["CSMOM_FAULT_STATE"] = state
        _log(f"no CSMOM_FAULT_STATE set; using fresh claim dir {state}")
    try:
        os.makedirs(state, exist_ok=True)
        fd = os.open(
            os.path.join(state, f"fired_{fault_index}"),
            os.O_CREAT | os.O_EXCL | os.O_WRONLY,
        )
        os.write(fd, str(os.getpid()).encode())
        os.close(fd)
        return True
    except FileExistsError:
        return False
    except OSError as e:
        _log(f"global_once claim failed ({e}); firing anyway")
        return True


def _log(msg: str) -> None:
    # stderr, never stdout: the trailing-JSON stdout contract is exactly
    # what several faults exist to attack
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def _execute(fault, seed: int, point: str, ctx: dict) -> None:
    act = fault.action
    _log(f"fire {act} at {point} (role={current_role()}, ctx={ctx or '{}'})")
    if act == "kill":
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)  # pragma: no cover - SIGKILL is not instantaneous
    elif act == "exit":
        os._exit(fault.code)
    elif act == "sleep":
        time.sleep(fault.seconds)
    elif act == "trip_deadline":
        from csmom_tpu.utils.deadline import trip_active_guard

        if not trip_active_guard():
            _log("trip_deadline: no guard armed in this process")
    elif act == "clock_skew":
        _skew_wall_clock(fault.seconds)
    elif act == "corrupt_file":
        _damage_files(fault, seed, truncate=False)
    elif act == "truncate_file":
        _damage_files(fault, seed, truncate=True)
    elif act == "raise_oserror":
        raise OSError(
            fault.errno_,
            f"chaos raise_oserror at {point} (injected, errno={fault.errno_})",
        )
    elif act == "stdout_noise":
        _start_stdout_noise(fault, seed)
    elif act in ("fail", "tick_late", "tick_dup", "tick_drop",
                 "version_skew", "cache_poison", "conn_reset",
                 "net_delay", "partition"):
        pass  # the return value is the fault; the caller interprets it
    else:  # pragma: no cover - plan.validate() bars unknown actions
        raise ValueError(f"unknown fault action {act!r}")


def _skew_wall_clock(seconds: float) -> None:
    """Monkeypatch ``time.time`` to jump by ``seconds`` — an NTP step.

    Monotonic clocks are untouched (exactly as on a real NTP step), so a
    deadline anchored per the ``utils.deadline`` contract keeps its true
    fuse; anything anchored on the wall clock visibly breaks under this
    fault.  Patching is process-local and deliberately not undone: a real
    clock step does not revert either.
    """
    real_time = time.time

    def skewed():
        # lint: allow[clock-discipline] this wrapper IS the skew under test
        return real_time() + seconds

    time.time = skewed


def _damage_files(fault, seed: int, *, truncate: bool) -> None:
    pattern = os.path.expandvars(fault.path)
    paths = sorted(p for p in glob.glob(pattern) if os.path.isfile(p))
    if not paths:
        _log(f"no files match {pattern!r}; nothing to damage")
        return
    rng = random.Random(seed)
    for p in paths:
        try:
            if truncate:
                with open(p, "r+b") as f:
                    f.truncate(max(0, fault.bytes))
                _log(f"truncated {p} to {fault.bytes} bytes")
            else:
                with open(p, "r+b") as f:
                    data = bytearray(f.read())
                    if not data:
                        continue
                    n = max(1, len(data) // 64)
                    for _ in range(n):
                        data[rng.randrange(len(data))] ^= 0xFF
                    f.seek(0)
                    f.write(data)
                _log(f"flipped {n} bytes in {p}")
        except OSError as e:  # damaging must never crash the rehearsal
            _log(f"could not damage {p}: {e}")


def _start_stdout_noise(fault, seed: int) -> None:
    """A daemon thread racing buffered junk against the trailing JSON.

    The payload never starts with ``{`` so a *correctly* quarantined
    summary line stays the only parseable JSON on stdout; if the summary
    emit is not a single atomic write, the interleave corrupts it and the
    invariant checker catches the damage.
    """
    rng = random.Random(seed)
    stop_at = time.monotonic() + max(0.5, fault.seconds or 1.0)

    def spam():
        while time.monotonic() < stop_at:
            print(f"{fault.text} {rng.random():.17f} " * 8, end="", flush=rng.random() < 0.5)
            time.sleep(0.001)

    t = threading.Thread(target=spam, daemon=True)
    t.start()
