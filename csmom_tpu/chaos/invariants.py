"""Artifact invariants: what every landed capture must look like.

The driver, the watcher loop, and the humans reading a round's evidence
all parse the same small family of JSON artifacts.  This module is the
single written-down contract for them, used three ways:

- ``csmom rehearse`` validates every artifact a faulted pipeline lands;
- the test tier validates every committed ``BENCH_*.json`` /
  ``MULTICHIP_*.json`` at the repo root, so historical records can never
  silently drift from the parser contract;
- capture scripts may call :func:`validate` before landing.

Validators return a list of violation strings (empty = valid) instead of
raising: a rehearsal reports ALL breakage of a landed artifact, not the
first.

Artifact kinds (detected from keys, see :func:`detect_kind`):

``record``
    A bench-style summary: ``metric``/``value``/``unit``/``vs_baseline``
    (+ optional ``extra`` dict).  Full bench records, MULTIHOST/HISTRANK
    captures, and the stdout headline all have this shape.
``driver_capture``
    The round driver's wrapper: ``rc``/``tail`` (+ ``cmd``/``n``/
    ``parsed``).  ``parsed`` may be null only for a nonzero ``rc`` — a
    successful run whose tail did not parse is exactly the r4 failure.
``multichip``
    ``n_devices``/``rc``/``ok``/``skipped``/``tail``.
``phases``
    A phase profile: ``metric`` + ``phases`` list.
``tpu_cache``
    ``BENCH_TPU_LAST.json``: ``captured_utc``/``provenance``/``record``.
``telemetry``
    A run-telemetry sidecar (``TELEMETRY_*.json``, :mod:`csmom_tpu.obs.
    timeline`): ``run_id``/``schema_version``/``wall_s``/``phases``,
    where the phase durations PARTITION the wall (their sum must land
    within 5% of ``wall_s`` — the whole point of the artifact is that
    the time is accounted for, not vibes).
``serve``
    A signal-service load-generation record (``SERVE_*.json``,
    :mod:`csmom_tpu.serve.loadgen`): headline + ``requests`` accounting
    + ``latency_ms`` percentiles + ``batches``.  Closed-world schema AND
    closed books: ``served + rejected + expired == admitted`` and
    ``expired_dispatched == 0`` are schema rules — an artifact whose
    request ledger does not balance (a silently dropped request, an
    expired request that was dispatched anyway) is invalid evidence,
    full stop.  Schema v2 (ISSUE 8) extends the contract: per-SLO-class
    books that each close and sum to the global book, a result-cache
    book whose ``stale_hits`` must be 0 and whose ``hit_rate``
    reconciles with its own counters, and an offered-load record
    (``offered_rps`` + ``offered_limited``) so an offered-load-limited
    headline can never be misread as a saturation ceiling.
``serve_pool``
    A multi-process pool load record (``SERVE_POOL_*.json``, the
    router/worker/supervisor tier): the serve closed-book rule enforced
    ACROSS the process boundary — the router's
    ``served + rejected + expired == admitted`` must balance no matter
    which worker crashed mid-batch — plus hedging consistency (a hedge
    pair that both answered counts exactly one terminal state and one
    ``duplicates_suppressed``; suppressed/wins can never exceed hedges)
    and an ``availability`` that reconciles with ``rejected_infra``.
``serve_fabric``
    A THREE-TIER horizontal-fabric load record (``SERVE_FABRIC_*.json``,
    ISSUE 14: loadgen client → supervised router replicas → workers over
    unix/tcp): the closed-book rule binds at the CLIENT tier — the
    outermost ledger, the one a SIGKILLed router replica cannot take
    with it — plus ``transport.routers >= 2`` (replication is the
    kind's point), a pool-level cache book whose ``pool_hit_rate``
    reconciles with the client's cache-hit count and whose
    fleet-aggregated ``stale_hits`` is structurally 0 across
    rebalances, hedge arithmetic, and per-tier fleet evidence.
``replay``
    An event-time replay record (``REPLAY_*.json``,
    :mod:`csmom_tpu.stream.replay`): TWO closed books as schema rules —
    the tick ledger (``applied + merged_late + quarantined + deduped ==
    offered`` and ``offered == generated + duplicated - dropped_gap``:
    every tick the feed emitted is in exactly one bucket) and the serve
    book (same balanced-requests rule as kind ``serve``) — plus
    ingest-vs-serve panel-version reconciliation: every served
    response's ``panel_version`` must be one the ingestor issued
    (``serve_max <= ingest_final``), and skew refusals must reconcile
    with the serve book's ``rejected_version_skew`` counter.

``trace``
    A request-path trace record (``TRACE_*.json``,
    :mod:`csmom_tpu.obs.trace`): CLOSED trace books (every opened trace
    ends complete or reasoned-partial; the ledger must balance), orphan
    halves closed with reasons, per-stage walls that telescope to each
    request wall within epsilon (the ``reconcile`` block), and
    reconciliation against the driven serve run's request book
    (``complete == served``, ``partial == rejected + expired``).

Partial rules: a partial artifact carries ``extra.partial`` (non-empty
string saying *what* is missing); a partial with a measurement list
(``rows``/``phases``) is sized by it, and upgrades must be monotone —
full beats partial, a partial only replaces a partial with strictly more
measured rows (:func:`upgrade_ok`, the same rule
``benchmarks/capture_lib.sh`` enforces shell-side).
"""

from __future__ import annotations

import json
import os
import re

__all__ = [
    "committable_sidecar",
    "detect_kind",
    "measured_rows",
    "trailing_json",
    "upgrade_ok",
    "validate",
    "validate_file",
    "validate_headline_text",
    "validate_tree",
]

# the round driver's stdout capture window; a headline longer than this is
# truncated and its JSON lost (the r4 failure)
DRIVER_TAIL_CHARS = 2000

# telemetry sidecar schema versions this checker (and the ledger/timeline
# readers) understand; a sidecar stamped with anything else is from a
# different era of the code and must fail loudly, not half-parse
KNOWN_TELEMETRY_SCHEMA_VERSIONS = (1,)

# serve artifact schema versions this checker (and the ledger) understand
# — the same closed-world rule as telemetry.  v2 (ISSUE 8, adaptive
# dispatch) adds per-SLO-class books, the result-cache book, and the
# offered-load record; v3 (ISSUE 9, engine registry) adds per-ENDPOINT
# books whose name set must be registered engines — the artifact's
# endpoint world is validated against the registry, not a literal.
# v4 (ISSUE 13, request tracing) adds per-class SLO error-budget burn
# accounting (violations + budget_burn) and bounded per-request latency
# samples in extra.samples (the CI backing for serve p99 gate rows).
# v1/v2/v3 artifacts (SERVE_r10/r13/r14, SERVE_MESH_r15) stay valid.
KNOWN_SERVE_SCHEMA_VERSIONS = (1, 2, 3, 4)

# trace artifact schema versions (TRACE_*.json, the request-path
# decomposition family — obs.trace): closed trace books + telescoping
# stage reconciliation, enforced by schema like every other kind
KNOWN_TRACE_SCHEMA_VERSIONS = (1,)

# serve-pool artifact schema versions (SERVE_POOL_*.json, the
# multi-process tier) — closed-world like the rest
KNOWN_SERVE_POOL_SCHEMA_VERSIONS = (1,)

# serve-fabric artifact schema versions (SERVE_FABRIC_*.json, the
# THREE-TIER horizontal fabric — ISSUE 14: loadgen client → supervised
# router replicas → workers, over unix or tcp).  The client tier's books
# are the outermost ledger (a SIGKILLed replica cannot take them along),
# so the closed-book rule binds THERE, and the pool-level cache book
# carries the structural stale_hits == 0 rule across rebalances.
KNOWN_SERVE_FABRIC_SCHEMA_VERSIONS = (1,)

# replay artifact schema versions (REPLAY_*.json, the event-time
# streaming harness) — closed-world like the rest
KNOWN_REPLAY_SCHEMA_VERSIONS = (1,)

# fleet artifact schema versions (FLEET_*.json, the continuous
# cross-process metrics observatory — obs/fleet.py): closed stream
# books (every process's series ends with a REASON — fin or severed,
# never silence), monotone-by-construction counter series, a demand
# book that reconciles with the driven serve run's request ledger BY
# SCHEMA, and the kill-window capacity account
KNOWN_FLEET_SCHEMA_VERSIONS = (1,)

# lint report schema versions (`csmom lint --format json`) — v1 was the
# r16 per-file report; v2 (ISSUE 12) adds the project flag, per-finding
# call chains, cache stats, and per-rule timings.  Closed-world both
# ways: unknown versions fail, and a v2 report carrying keys outside the
# declared set fails (the CI archiver must never half-parse a report
# from a different era of the code).
KNOWN_LINT_SCHEMA_VERSIONS = (1, 2)
_LINT_V2_KEYS = frozenset({
    "schema_version", "ok", "files_scanned", "rules", "project",
    "findings", "suppressed", "cache", "rule_timings_s",
})
_LINT_FINDING_KEYS = frozenset({"rule", "path", "line", "message",
                                "chain"})

# only ROUND sidecars are committed evidence: TELEMETRY_r<NN>.json,
# SERVE_r<NN>.json, SERVE_POOL_r<NN>.json, and SERVE_MESH_r<NN>.json
# (the multi-device serving family, ISSUE 10).  Rehearse/smoke/scratch
# files (TELEMETRY_rehearse_*, SERVE_smoke*, SERVE_POOL_rehearse_*,
# pid-suffixed operator reruns) are regenerated per run and gitignored —
# one slipped into the tree once, which is why this is a named rule with
# a tier-1 test behind it instead of a .gitignore comment.
_REGENERATED_PREFIXES = ("TELEMETRY_", "SERVE_", "REPLAY_", "TRACE_",
                         "FLEET_")
_COMMITTED_SIDECAR_RE = re.compile(
    r"^(?:TELEMETRY|SERVE|SERVE_POOL|SERVE_MESH|SERVE_FABRIC|REPLAY"
    r"|TRACE|FLEET)_r\d+\.json$")

_NUM = (int, float)


def committable_sidecar(basename: str) -> bool:
    """True iff this TELEMETRY/SERVE file name may be committed (round
    artifacts only); other name families are not this rule's business."""
    if not basename.startswith(_REGENERATED_PREFIXES):
        return True
    return bool(_COMMITTED_SIDECAR_RE.match(basename))


def trailing_json(text: str):
    """The last parseable JSON-object line of ``text``, or None — the same
    extraction rule as bench's supervisor and capture_lib.sh."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def detect_kind(obj: dict) -> str | None:
    if not isinstance(obj, dict):
        return None
    # fleet before trace/fabric (it embeds a requests block and series
    # books of its own), trace/replay before pool, pool before serve,
    # serve before record: each carries the previous kind's key
    # signature plus its own
    if obj.get("kind") == "fleet" or {"series", "demand",
                                      "capacity"} <= set(obj):
        return "fleet"
    if obj.get("kind") == "trace" or {"books", "stages",
                                      "reconcile"} <= set(obj):
        return "trace"
    if obj.get("kind") == "replay" or {"ticks", "panel",
                                       "reconcile"} <= set(obj):
        return "replay"
    if obj.get("kind") == "serve_fabric" or {"requests", "availability",
                                             "routers",
                                             "transport"} <= set(obj):
        # fabric before pool: a fabric artifact carries the pool's
        # requests/availability/hedge signature PLUS its router tier
        return "serve_fabric"
    if obj.get("kind") == "serve_pool" or {"requests", "availability",
                                           "hedge"} <= set(obj):
        return "serve_pool"
    if obj.get("kind") == "serve" or {"requests", "latency_ms",
                                      "batches"} <= set(obj):
        return "serve"
    if obj.get("kind") == "telemetry" or {"run_id", "wall_s",
                                          "phases"} <= set(obj):
        return "telemetry"
    if {"files_scanned", "rules", "findings"} <= set(obj):
        return "lint"
    if {"captured_utc", "record"} <= set(obj):
        return "tpu_cache"
    if {"n_devices", "ok"} <= set(obj):
        return "multichip"
    if "phases" in obj and "metric" in obj:
        return "phases"
    if {"metric", "value"} <= set(obj):
        return "record"
    if {"rc", "tail"} <= set(obj):
        return "driver_capture"
    return None


def measured_rows(obj: dict) -> int:
    """A capture's substance: the length of its measurement list (mirrors
    ``_measured_rows`` in capture_lib.sh; listless records count 0)."""
    if not isinstance(obj, dict):
        return 0
    for k in ("rows", "phases"):
        v = obj.get(k)
        if isinstance(v, list):
            return len(v)
        extra = obj.get("extra")
        if isinstance(extra, dict) and isinstance(extra.get(k), list):
            return len(extra[k])
    return 0


def is_partial(obj: dict) -> bool:
    if not isinstance(obj, dict):
        return False
    extra = obj.get("extra")
    return bool(obj.get("partial")
                or (isinstance(extra, dict) and extra.get("partial")))


def _require(obj, key, types, kind, out, type_name=None):
    if key not in obj:
        out.append(f"{kind}: missing required key {key!r}")
        return None
    v = obj[key]
    if not isinstance(v, types) or isinstance(v, bool) and bool not in (
            types if isinstance(types, tuple) else (types,)):
        out.append(
            f"{kind}: {key!r} must be {type_name or types}, got "
            f"{type(v).__name__}"
        )
        return None
    return v


def _validate_record(obj: dict, kind: str = "record") -> list:
    out: list = []
    _require(obj, "metric", str, kind, out)
    _require(obj, "value", _NUM, kind, out, "a number")
    _require(obj, "unit", str, kind, out)
    _require(obj, "vs_baseline", _NUM, kind, out, "a number")
    extra = obj.get("extra")
    if extra is not None and not isinstance(extra, dict):
        out.append(f"{kind}: extra must be a dict when present")
        extra = None
    if isinstance(extra, dict):
        p = extra.get("partial")
        if p is not None and (not isinstance(p, str) or not p.strip()):
            out.append(
                f"{kind}: extra.partial must be a non-empty string saying "
                "what is missing"
            )
        for k in ("rows", "phases"):
            if k in extra and not isinstance(extra[k], list):
                out.append(f"{kind}: extra.{k} must be a list")
        samples = extra.get("samples")
        if samples is not None:
            # the perf-ledger contract: raw per-rep walls, keyed by the
            # matching aggregate field, every sample a number — a string
            # smuggled into a sample list would poison the bootstrap
            if not isinstance(samples, dict):
                out.append(f"{kind}: extra.samples must be a dict of "
                           "leg -> list of raw per-rep numbers")
            else:
                for leg, vals in samples.items():
                    if (not isinstance(vals, list)
                            or not all(isinstance(v, _NUM)
                                       and not isinstance(v, bool)
                                       for v in vals)):
                        out.append(f"{kind}: extra.samples[{leg!r}] must "
                                   "be a list of numbers")
    for k in ("rows", "phases"):
        if k in obj and not isinstance(obj[k], list):
            out.append(f"{kind}: {k} must be a list")
    p = obj.get("partial")
    if p is not None and (not isinstance(p, str) or not p.strip()):
        out.append(f"{kind}: partial must be a non-empty string")
    return out


def _validate_driver_capture(obj: dict) -> list:
    out: list = []
    rc = _require(obj, "rc", int, "driver_capture", out)
    _require(obj, "tail", str, "driver_capture", out)
    parsed = obj.get("parsed")
    if parsed is None:
        if rc == 0:
            out.append(
                "driver_capture: rc == 0 but parsed is null — the tail's "
                "trailing JSON was lost (the r4 failure mode)"
            )
    elif not isinstance(parsed, dict):
        out.append("driver_capture: parsed must be an object or null")
    else:
        out += [f"parsed.{v}" for v in _validate_record(parsed)]
        tail_obj = trailing_json(obj.get("tail", ""))
        if tail_obj is not None and tail_obj.get("value") != parsed.get("value"):
            out.append(
                "driver_capture: parsed.value disagrees with the tail's "
                "trailing JSON line"
            )
    return out


def _validate_multichip(obj: dict) -> list:
    out: list = []
    _require(obj, "n_devices", int, "multichip", out)
    _require(obj, "rc", int, "multichip", out)
    _require(obj, "tail", str, "multichip", out)
    for k in ("ok", "skipped"):
        if k in obj and not isinstance(obj[k], bool):
            out.append(f"multichip: {k!r} must be a bool")
        elif k not in obj:
            out.append(f"multichip: missing required key {k!r}")
    if obj.get("ok") and obj.get("rc") != 0:
        out.append("multichip: ok is true but rc != 0")
    return out


def _validate_phases(obj: dict) -> list:
    out: list = []
    _require(obj, "metric", str, "phases", out)
    phases = _require(obj, "phases", list, "phases", out)
    if phases is not None:
        for i, ph in enumerate(phases):
            if not isinstance(ph, dict):
                out.append(f"phases: phases[{i}] must be an object")
    return out


def _validate_tpu_cache(obj: dict) -> list:
    out: list = []
    _require(obj, "captured_utc", str, "tpu_cache", out)
    _require(obj, "provenance", str, "tpu_cache", out)
    rec = _require(obj, "record", dict, "tpu_cache", out)
    if rec is not None:
        out += [f"record.{v}" for v in _validate_record(rec)]
    return out


def _validate_telemetry(obj: dict) -> list:
    out: list = []
    _require(obj, "run_id", str, "telemetry", out)
    ver = _require(obj, "schema_version", int, "telemetry", out)
    if ver is not None and ver not in KNOWN_TELEMETRY_SCHEMA_VERSIONS:
        out.append(
            f"telemetry: unknown schema_version {ver} (this checker "
            f"understands {list(KNOWN_TELEMETRY_SCHEMA_VERSIONS)}) — the "
            "sidecar is from a different era of the code; do not "
            "half-parse it"
        )
    wall = _require(obj, "wall_s", _NUM, "telemetry", out, "a number")
    phases = _require(obj, "phases", list, "telemetry", out)
    if phases is not None:
        names = []
        total = 0.0
        for i, ph in enumerate(phases):
            if not isinstance(ph, dict):
                out.append(f"telemetry: phases[{i}] must be an object")
                continue
            if not isinstance(ph.get("name"), str):
                out.append(f"telemetry: phases[{i}].name must be a string")
            else:
                names.append(ph["name"])
            if not isinstance(ph.get("dur_s"), _NUM):
                out.append(f"telemetry: phases[{i}].dur_s must be a number")
            else:
                total += ph["dur_s"]
        if len(names) != len(set(names)):
            out.append("telemetry: duplicate phase names")
        # the artifact's core claim: the phases ACCOUNT for the wall.
        # Tolerance 5% (rounding, torn tail events); floored so a
        # sub-second smoke run is not failed over microseconds.
        if isinstance(wall, _NUM) and not out:
            tol = max(0.05 * wall, 0.02)
            if abs(total - wall) > tol:
                out.append(
                    f"telemetry: phase durations sum to {total:.4f}s but "
                    f"wall_s is {wall:.4f}s (off by more than 5% — the "
                    "timeline lost track of where the time went)"
                )
    if "spans" in obj and not isinstance(obj["spans"], list):
        out.append("telemetry: spans must be a list")
    # device-memory axis (obs.memstats through the metrics snapshot):
    # per-shape byte fields must be ints and carry the comparable peak —
    # the ledger's memory gate diffs exactly these numbers, so a
    # mistyped field here would corrupt a cross-run verdict silently
    metrics = obj.get("metrics")
    mem = metrics.get("memory") if isinstance(metrics, dict) else None
    if mem is not None:
        if not isinstance(mem, dict):
            out.append("telemetry: metrics.memory must be a dict of "
                       "shape -> byte stats")
        else:
            for shape, stats in mem.items():
                if isinstance(stats, str):
                    continue  # a capture-failure reason is a valid value
                if not isinstance(stats, dict):
                    out.append(f"telemetry: metrics.memory[{shape!r}] must "
                               "be a byte-stats dict or a reason string")
                    continue
                pk = stats.get("peak_bytes")
                if not isinstance(pk, int) or isinstance(pk, bool):
                    out.append(f"telemetry: metrics.memory[{shape!r}] "
                               "missing int peak_bytes (the ledger's "
                               "comparable scalar)")
                if not isinstance(stats.get("platform"), str):
                    out.append(f"telemetry: metrics.memory[{shape!r}] "
                               "missing str platform — compiled bytes "
                               "are per-backend and must say whose they "
                               "are")
                for k, v in stats.items():
                    if k.endswith("_in_bytes") and (
                            not isinstance(v, int) or isinstance(v, bool)):
                        out.append(f"telemetry: metrics.memory[{shape!r}]."
                                   f"{k} must be an int byte count")
    return out


def _validate_serve(obj: dict) -> list:
    """The serve artifact contract: balanced request books, ordered
    percentiles, consistent batch histogram, a known schema era."""
    out: list = []
    _require(obj, "run_id", str, "serve", out)
    ver = _require(obj, "schema_version", int, "serve", out)
    if ver is not None and ver not in KNOWN_SERVE_SCHEMA_VERSIONS:
        out.append(
            f"serve: unknown schema_version {ver} (this checker "
            f"understands {list(KNOWN_SERVE_SCHEMA_VERSIONS)}) — the "
            "artifact is from a different era of the code; do not "
            "half-parse it"
        )
    _require(obj, "wall_s", _NUM, "serve", out, "a number")
    # the headline is record-shaped (metric/value/unit/vs_baseline), so
    # the record rules apply verbatim
    out += _validate_record(obj, kind="serve")

    req = _require(obj, "requests", dict, "serve", out)
    served = 0
    if req is not None:
        req = _validate_serve_requests(req, "serve", out)
        if req is not None:
            served = req["served"]

    lat = _require(obj, "latency_ms", dict, "serve", out)
    if lat is not None:
        for leg in ("queue", "service", "total"):
            side = lat.get(leg)
            if not isinstance(side, dict):
                out.append(f"serve: latency_ms.{leg} must be a dict of "
                           "p50/p95/p99")
                continue
            vals = []
            for q in ("p50", "p95", "p99"):
                v = side.get(q)
                if v is None:
                    # legal only when nothing was observed on that leg
                    if leg != "queue" and served:
                        out.append(f"serve: latency_ms.{leg}.{q} is null "
                                   "but requests were served — the "
                                   "latency was measured, record it")
                    continue
                if not isinstance(v, _NUM) or isinstance(v, bool):
                    out.append(f"serve: latency_ms.{leg}.{q} must be a "
                               "number (milliseconds) or null")
                else:
                    vals.append(v)
            if vals != sorted(vals):
                out.append(f"serve: latency_ms.{leg} percentiles must be "
                           "non-decreasing (p50 <= p95 <= p99)")

    batches = _require(obj, "batches", dict, "serve", out)
    if batches is not None:
        count = batches.get("count")
        hist = batches.get("size_hist")
        if not isinstance(count, int) or isinstance(count, bool):
            out.append("serve: batches.count must be an int")
        elif not isinstance(hist, dict):
            out.append("serve: batches.size_hist must be a dict of "
                       "batch-size -> count")
        else:
            bad = [k for k, v in hist.items()
                   if not (isinstance(v, int) and not isinstance(v, bool))
                   or not str(k).isdigit()]
            if bad:
                out.append(f"serve: batches.size_hist has non-int-keyed or "
                           f"non-int-valued entries: {bad}")
            elif sum(hist.values()) != count:
                out.append(
                    f"serve: batches.size_hist sums to "
                    f"{sum(hist.values())} but batches.count is {count} — "
                    "a dispatched batch is missing from the histogram"
                )
    comp = obj.get("compile")
    if comp is not None and not isinstance(comp, dict):
        out.append("serve: compile must be a dict when present")
    elif isinstance(comp, dict):
        fc = comp.get("in_window_fresh_compiles")
        if fc is not None and not isinstance(fc, (int, str)):
            out.append("serve: compile.in_window_fresh_compiles must be "
                       "an int count or a reason string")
    if isinstance(ver, int) and ver >= 2:
        out += _validate_serve_v2(obj, req)
    if isinstance(ver, int) and ver >= 3:
        out += _validate_serve_v3(obj, req)
    if isinstance(ver, int) and ver >= 4:
        out += _validate_serve_v4(obj)
    return out


def _validate_serve_v4(obj: dict) -> list:
    """The ISSUE 13 additions: per-class SLO error-budget burn
    accounting (``violations``/``budget_burn`` in every class book) and
    bounded per-request latency samples in ``extra.samples`` — the CI
    backing behind the serve p99 gate rows.  Both are schema rules so
    neither can silently vanish from committed evidence."""
    out: list = []
    classes = obj.get("classes")
    if isinstance(classes, dict):
        for name, book in classes.items():
            if not isinstance(book, dict):
                continue  # already reported by the v2 rules
            v = book.get("violations")
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"serve: classes[{name!r}].violations must be "
                           "a non-negative int (v4 burn accounting)")
            elif isinstance(book.get("served"), int) and v > book["served"]:
                out.append(f"serve: classes[{name!r}].violations {v} > "
                           f"served {book['served']}")
            burn = book.get("budget_burn")
            if burn is not None and (not isinstance(burn, _NUM)
                                     or isinstance(burn, bool)
                                     or burn < 0):
                out.append(f"serve: classes[{name!r}].budget_burn must "
                           "be a non-negative number or null")
            if (burn is None and isinstance(book.get("served"), int)
                    and book["served"] > 0
                    and book.get("budget_ms") is not None):
                out.append(f"serve: classes[{name!r}] served requests "
                           "against a budget but budget_burn is null — "
                           "the burn was computable, record it")
    samples = (obj.get("extra") or {}).get("samples")
    if not isinstance(samples, dict) or "serve_total_ms" not in samples:
        out.append("serve: v4 artifacts must carry extra.samples with a "
                   "serve_total_ms list (the bootstrap-CI backing for "
                   "the p99 gate rows)")
    req = obj.get("requests")
    if (isinstance(samples, dict)
            and isinstance(samples.get("serve_total_ms"), list)
            and isinstance(req, dict)
            and isinstance(req.get("served"), int)):
        n = len(samples["serve_total_ms"])
        if req["served"] and not n:
            out.append("serve: requests were served but "
                       "extra.samples.serve_total_ms is empty — the "
                       "latencies were measured, persist them")
    return out


def _registered_serve_endpoints() -> tuple:
    """The live endpoint registry (the v3 ground truth).  Imported
    lazily: this module stays cheap for validators that never see a v3
    serve artifact, and the registry's core is jax-free by design."""
    from csmom_tpu.registry import serve_endpoints

    return serve_endpoints()


def _validate_serve_v3(obj: dict, req: dict | None) -> list:
    """The ISSUE 9 additions: per-ENDPOINT books that close and sum to
    the global book, with the endpoint NAME SET validated against the
    VALIDATING PROCESS's live engine registry.  Committed round
    evidence uses builtin endpoints, which every process registers; an
    artifact produced by a runtime-registered plugin endpoint validates
    only in processes that also register that plugin — the same
    process-level discipline the serving tier itself applies (a worker
    without the plugin cannot serve it either)."""
    out: list = []
    registered = _registered_serve_endpoints()
    eps = _require(obj, "endpoints", dict, "serve", out)
    if isinstance(eps, dict):
        if not eps:
            out.append("serve: endpoints must name at least one endpoint "
                       "(the per-endpoint book is v3's contract)")
        served_sum = 0
        broken = False
        for name, book in eps.items():
            if name not in registered:
                out.append(
                    f"serve: endpoints[{name!r}] is not a registered "
                    f"engine (registry: {list(registered)}) — the "
                    "artifact's endpoint set must come from the "
                    "registry, not a literal")
                broken = True
                continue
            if not isinstance(book, dict):
                out.append(f"serve: endpoints[{name!r}] must be a dict")
                broken = True
                continue
            for k in ("submitted", "served", "rejected", "expired"):
                v = book.get(k)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    out.append(f"serve: endpoints[{name!r}].{k} must be a "
                               "non-negative int")
                    broken = True
                    break
            else:
                total = (book["served"] + book["rejected"]
                         + book["expired"])
                if total != book["submitted"]:
                    out.append(
                        f"serve: endpoint {name!r} book broken — served "
                        f"{book['served']} + rejected {book['rejected']} "
                        f"+ expired {book['expired']} = {total} != "
                        f"submitted {book['submitted']}")
                served_sum += book["served"]
                _validate_latency_side(book.get("latency_ms"),
                                       f"endpoints.{name}", "serve", out)
        if not broken and req is not None and served_sum != req["served"]:
            out.append(
                f"serve: endpoint books do not sum to the global book — "
                f"sum(served) = {served_sum} != requests.served "
                f"{req['served']} (a request escaped its endpoint "
                "ledger)")
    kinds = (obj.get("offered") or {}).get("kinds")
    if isinstance(kinds, list):
        rogue = [k for k in kinds if k not in registered]
        if rogue:
            out.append(
                f"serve: offered.kinds contains unregistered endpoints "
                f"{rogue} (registry: {list(registered)})")
    return out


def _validate_serve_v2(obj: dict, req: dict | None) -> list:
    """The ISSUE 8 additions: closed PER-CLASS books that sum to the
    global book, a cache book with zero stale hits and a reconciling
    hit rate, and an offered-load record carrying ``offered_rps`` so an
    offered-load-limited headline can never be misread as a saturation
    ceiling."""
    out: list = []
    classes = _require(obj, "classes", dict, "serve", out)
    if isinstance(classes, dict):
        if not classes:
            out.append("serve: classes must name at least one SLO class")
        sums = dict.fromkeys(("admitted", "served", "rejected",
                              "expired"), 0)
        broken = False
        for name, book in classes.items():
            if not isinstance(book, dict):
                out.append(f"serve: classes[{name!r}] must be a dict")
                broken = True
                continue
            for k in ("admitted", "served", "rejected", "expired",
                      "rejected_quota"):
                v = book.get(k)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    out.append(f"serve: classes[{name!r}].{k} must be a "
                               "non-negative int (the per-class book is "
                               "the contract)")
                    broken = True
                    break
            else:
                total = book["served"] + book["rejected"] + book["expired"]
                if total != book["admitted"]:
                    out.append(
                        f"serve: class {name!r} book broken — served "
                        f"{book['served']} + rejected {book['rejected']} + "
                        f"expired {book['expired']} = {total} != admitted "
                        f"{book['admitted']}")
                for k in sums:
                    sums[k] += book[k]
        if not broken and req is not None:
            for k, csum in sums.items():
                if csum != req[k]:
                    out.append(
                        f"serve: class books do not sum to the global "
                        f"book — sum({k}) = {csum} != requests.{k} "
                        f"{req[k]} (a request escaped its class ledger)")
    cache = _require(obj, "cache", dict, "serve", out)
    if isinstance(cache, dict) and cache.get("enabled", True):
        ok = True
        for k in ("hits", "misses", "stale_blocked", "stale_hits",
                  "lookups", "inserts", "evictions"):
            v = cache.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"serve: cache.{k} must be a non-negative int")
                ok = False
        if ok:
            if cache["stale_hits"] != 0:
                out.append(
                    f"serve: cache.stale_hits = {cache['stale_hits']} — a "
                    "result computed from a panel version the floor has "
                    "passed was SERVED; stale cache hits are invalid "
                    "evidence, full stop")
            want = (cache["hits"] + cache["misses"]
                    + cache["stale_blocked"])
            if cache["lookups"] != want:
                out.append(
                    f"serve: cache.lookups {cache['lookups']} != hits + "
                    f"misses + stale_blocked = {want}")
            hr = cache.get("hit_rate")
            if not isinstance(hr, _NUM) or isinstance(hr, bool):
                out.append("serve: cache.hit_rate must be a number")
            elif not 0.0 <= hr <= 1.0:
                out.append(f"serve: cache.hit_rate {hr} outside [0, 1]")
            elif cache["lookups"] and abs(
                    hr - cache["hits"] / cache["lookups"]) > 1e-3:
                out.append(
                    f"serve: cache.hit_rate {hr} does not reconcile with "
                    f"hits/lookups = "
                    f"{cache['hits'] / cache['lookups']:.4f}")
    offered = _require(obj, "offered", dict, "serve", out)
    if isinstance(offered, dict):
        orps = offered.get("offered_rps")
        if not isinstance(orps, _NUM) or isinstance(orps, bool) \
                or orps < 0:
            out.append("serve: offered.offered_rps must be a non-negative "
                       "number (the achieved-vs-offered distinction is "
                       "the r11 footnote made mechanical)")
        if not isinstance(offered.get("schedule_kind"), str):
            out.append("serve: offered.schedule_kind must be a string "
                       "(bursty/diurnal/adversarial/custom)")
    if not isinstance(obj.get("offered_limited"), bool):
        out.append("serve: offered_limited must be a bool (did the run "
                   "measure the load or the ceiling?)")
    return out


def _validate_latency_side(side, leg: str, kind: str, out: list) -> None:
    """Shared percentile rules: numbers-or-null, non-decreasing."""
    if not isinstance(side, dict):
        out.append(f"{kind}: latency_ms.{leg} must be a dict of "
                   "p50/p95/p99")
        return
    vals = []
    for q in ("p50", "p95", "p99"):
        v = side.get(q)
        if v is None:
            continue
        if not isinstance(v, _NUM) or isinstance(v, bool):
            out.append(f"{kind}: latency_ms.{leg}.{q} must be a number "
                       "(milliseconds) or null")
        else:
            vals.append(v)
    if vals != sorted(vals):
        out.append(f"{kind}: latency_ms.{leg} percentiles must be "
                   "non-decreasing (p50 <= p95 <= p99)")


def _validate_serve_pool(obj: dict) -> list:
    """The pool artifact contract: the closed request book ACROSS the
    process boundary, exactly-once hedging arithmetic, and an
    availability figure that reconciles with its own counters."""
    out: list = []
    _require(obj, "run_id", str, "serve_pool", out)
    ver = _require(obj, "schema_version", int, "serve_pool", out)
    if ver is not None and ver not in KNOWN_SERVE_POOL_SCHEMA_VERSIONS:
        out.append(
            f"serve_pool: unknown schema_version {ver} (this checker "
            f"understands {list(KNOWN_SERVE_POOL_SCHEMA_VERSIONS)}) — the "
            "artifact is from a different era of the code; do not "
            "half-parse it"
        )
    _require(obj, "wall_s", _NUM, "serve_pool", out, "a number")
    out += _validate_record(obj, kind="serve_pool")

    req = _require(obj, "requests", dict, "serve_pool", out)
    if req is not None:
        for k in ("admitted", "served", "rejected", "expired",
                  "rejected_infra", "hedged", "hedge_wins",
                  "duplicates_suppressed"):
            v = req.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"serve_pool: requests.{k} must be a "
                           "non-negative int (the accounting is the "
                           "contract)")
                req = None
                break
    if req is not None:
        total = req["served"] + req["rejected"] + req["expired"]
        if total != req["admitted"]:
            out.append(
                f"serve_pool: request accounting broken across the "
                f"process boundary — served {req['served']} + rejected "
                f"{req['rejected']} + expired {req['expired']} = {total} "
                f"!= admitted {req['admitted']} (a request was dropped "
                "or double-counted between router and workers)"
            )
        if req["rejected_infra"] > req["rejected"]:
            out.append("serve_pool: rejected_infra exceeds rejected")
        if req["hedge_wins"] > req["hedged"]:
            out.append(
                f"serve_pool: hedge_wins {req['hedge_wins']} > hedged "
                f"{req['hedged']}")
        if req["duplicates_suppressed"] > req["hedged"]:
            out.append(
                f"serve_pool: duplicates_suppressed "
                f"{req['duplicates_suppressed']} > hedged {req['hedged']}"
                " — a duplicate terminal without a hedge means "
                "exactly-once broke"
            )

    avail = _require(obj, "availability", _NUM, "serve_pool", out,
                     "a number")
    if isinstance(avail, _NUM) and not isinstance(avail, bool):
        if not 0.0 <= avail <= 1.0:
            out.append(f"serve_pool: availability {avail} outside [0, 1]")
        elif req is not None and req["admitted"]:
            want = 1.0 - req["rejected_infra"] / req["admitted"]
            if abs(avail - want) > 1e-4:
                out.append(
                    f"serve_pool: availability {avail} does not reconcile "
                    f"with 1 - rejected_infra/admitted = {want:.6f} — the "
                    "headline must be computable from the books"
                )

    hedge = _require(obj, "hedge", dict, "serve_pool", out)
    if hedge is not None and req is not None and req["admitted"]:
        rate = hedge.get("rate")
        if not isinstance(rate, _NUM) or isinstance(rate, bool):
            out.append("serve_pool: hedge.rate must be a number")
        elif abs(rate - req["hedged"] / req["admitted"]) > 1e-3:
            out.append(
                f"serve_pool: hedge.rate {rate} does not reconcile with "
                f"hedged/admitted = {req['hedged'] / req['admitted']:.4f}"
            )

    lat = _require(obj, "latency_ms", dict, "serve_pool", out)
    if lat is not None:
        _validate_latency_side(lat.get("total"), "total", "serve_pool", out)

    pool = _require(obj, "pool", dict, "serve_pool", out)
    if pool is not None:
        for k in ("n_workers", "kills", "restarts"):
            v = pool.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"serve_pool: pool.{k} must be a non-negative "
                           "int")
        if "events" in pool and not isinstance(pool["events"], list):
            out.append("serve_pool: pool.events must be a list")

    workers = _require(obj, "workers", list, "serve_pool", out)
    if workers is not None:
        for i, w in enumerate(workers):
            if not isinstance(w, dict) or not isinstance(
                    w.get("worker_id"), str):
                out.append(f"serve_pool: workers[{i}] must be a dict with "
                           "a worker_id")
    comp = obj.get("compile")
    if comp is not None and not isinstance(comp, dict):
        out.append("serve_pool: compile must be a dict when present")
    elif isinstance(comp, dict):
        fc = comp.get("in_window_fresh_compiles")
        if fc is not None and not isinstance(fc, (int, str)):
            out.append("serve_pool: compile.in_window_fresh_compiles must "
                       "be an int count or a reason string")
    return out


def _validate_serve_fabric(obj: dict) -> list:
    """The three-tier fabric contract (ISSUE 14): closed CLIENT-tier
    books (the outermost ledger — the one a SIGKILLed router replica
    cannot take with it), availability reconciling with its own infra
    counter, a pool-level cache book whose hit rate reconciles with the
    client's cache-hit count and whose fleet-aggregated ``stale_hits``
    is structurally zero across rebalances, hedge arithmetic, and at
    least TWO router replicas (replication is the kind's point)."""
    out: list = []
    _require(obj, "run_id", str, "serve_fabric", out)
    ver = _require(obj, "schema_version", int, "serve_fabric", out)
    if ver is not None and ver not in KNOWN_SERVE_FABRIC_SCHEMA_VERSIONS:
        out.append(
            f"serve_fabric: unknown schema_version {ver} (this checker "
            f"understands {list(KNOWN_SERVE_FABRIC_SCHEMA_VERSIONS)}) — "
            "the artifact is from a different era of the code; do not "
            "half-parse it")
    _require(obj, "wall_s", _NUM, "serve_fabric", out, "a number")
    out += _validate_record(obj, kind="serve_fabric")

    trans = _require(obj, "transport", dict, "serve_fabric", out)
    if isinstance(trans, dict):
        if trans.get("scheme") not in ("unix", "tcp"):
            out.append(f"serve_fabric: transport.scheme "
                       f"{trans.get('scheme')!r} must be 'unix' or 'tcp'")
        nr = trans.get("routers")
        if not isinstance(nr, int) or isinstance(nr, bool) or nr < 2:
            out.append(f"serve_fabric: transport.routers {nr!r} — the "
                       "fabric requires >= 2 router replicas (one "
                       "router is the r11 pool, not a fabric)")
        nw = trans.get("workers")
        if not isinstance(nw, int) or isinstance(nw, bool) or nw < 1:
            out.append(f"serve_fabric: transport.workers must be a "
                       f"positive int, got {nw!r}")

    req = _require(obj, "requests", dict, "serve_fabric", out)
    if isinstance(req, dict):
        counters = ("admitted", "served", "rejected", "expired",
                    "rejected_infra", "served_cache_hits",
                    "served_hedged", "router_conn_failures", "failovers")
        ok = True
        for k in counters:
            v = req.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"serve_fabric: requests.{k} must be a "
                           "non-negative int (the client-tier ledger is "
                           "the contract)")
                ok = False
        if not ok:
            # malformed counters: the availability/cache/hedge reconcile
            # blocks below divide by these values — a violation must stay
            # a violation, not become a TypeError out of validate()
            req = None
        else:
            total = req["served"] + req["rejected"] + req["expired"]
            if total != req["admitted"]:
                out.append(
                    f"serve_fabric: client books broken — served "
                    f"{req['served']} + rejected {req['rejected']} + "
                    f"expired {req['expired']} = {total} != admitted "
                    f"{req['admitted']} (a request died with a replica)")
            if req["rejected_infra"] > req["rejected"]:
                out.append("serve_fabric: rejected_infra exceeds rejected")
            if req["served_cache_hits"] > req["served"]:
                out.append("serve_fabric: served_cache_hits exceeds served")
            if req["served_hedged"] > req["served"]:
                out.append("serve_fabric: served_hedged exceeds served")

    avail = _require(obj, "availability", _NUM, "serve_fabric", out,
                     "a number")
    if isinstance(avail, _NUM) and not isinstance(avail, bool):
        if not 0.0 <= avail <= 1.0:
            out.append(f"serve_fabric: availability {avail} outside [0, 1]")
        elif isinstance(req, dict) and req.get("admitted"):
            want = round(1.0 - req.get("rejected_infra", 0)
                         / req["admitted"], 6)
            if abs(avail - want) > 1e-6:
                out.append(
                    f"serve_fabric: availability {avail} does not "
                    f"reconcile with 1 - rejected_infra/admitted = {want}")

    cache = _require(obj, "cache", dict, "serve_fabric", out)
    if isinstance(cache, dict):
        hr = cache.get("pool_hit_rate")
        if not isinstance(hr, _NUM) or isinstance(hr, bool) \
                or not 0.0 <= hr <= 1.0:
            out.append(f"serve_fabric: cache.pool_hit_rate {hr!r} must "
                       "be a number in [0, 1]")
        elif isinstance(req, dict) and req.get("served"):
            want = round(req.get("served_cache_hits", 0)
                         / req["served"], 4)
            if abs(hr - want) > 1e-4:
                out.append(
                    f"serve_fabric: cache.pool_hit_rate {hr} does not "
                    f"reconcile with served_cache_hits/served = {want}")
        wagg = cache.get("workers")
        if not isinstance(wagg, dict):
            out.append("serve_fabric: cache.workers (the fleet-aggregated "
                       "worker cache book) must be a dict")
        else:
            sh = wagg.get("stale_hits")
            if not isinstance(sh, int) or isinstance(sh, bool):
                out.append("serve_fabric: cache.workers.stale_hits must "
                           "be an int")
            elif sh != 0:
                out.append(
                    f"serve_fabric: cache.workers.stale_hits = {sh} — a "
                    "STALE entry was returned somewhere in the fleet; "
                    "the version floor must make this structurally "
                    "impossible, rebalances included")

    hedge = _require(obj, "hedge", dict, "serve_fabric", out)
    if isinstance(hedge, dict):
        rate = hedge.get("rate")
        if not isinstance(rate, _NUM) or isinstance(rate, bool):
            out.append("serve_fabric: hedge.rate must be a number")
        elif isinstance(req, dict) and req.get("admitted"):
            want = round(req.get("served_hedged", 0)
                         / max(1, req["admitted"]), 4)
            if abs(rate - want) > 1e-4:
                out.append(
                    f"serve_fabric: hedge.rate {rate} does not reconcile "
                    f"with served_hedged/admitted = {want}")
        rt = hedge.get("router_tier")
        if isinstance(rt, dict):
            if isinstance(rt.get("wins"), int) and \
                    isinstance(rt.get("hedged"), int) and \
                    rt["wins"] > rt["hedged"]:
                out.append(
                    f"serve_fabric: router_tier hedge_wins {rt['wins']} "
                    f"> hedged {rt['hedged']} — a hedge cannot win more "
                    "than it fired")

    lat = _require(obj, "latency_ms", dict, "serve_fabric", out)
    if isinstance(lat, dict):
        _validate_latency_side(lat.get("total"), "total", "serve_fabric",
                               out)

    for tier, id_key in (("routers", "router_id"), ("workers", "worker_id")):
        block = _require(obj, tier, dict, "serve_fabric", out)
        if not isinstance(block, dict):
            continue
        rows = block.get("replicas" if tier == "routers" else "stats")
        if not isinstance(rows, list):
            out.append(f"serve_fabric: {tier} must carry its per-process "
                       "stats list")
        else:
            for i, r in enumerate(rows):
                if not isinstance(r, dict) or id_key not in r:
                    out.append(f"serve_fabric: {tier} row {i} must be a "
                               f"dict with a {id_key}")
        for k in ("kills", "restarts"):
            v = block.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"serve_fabric: {tier}.{k} must be a "
                           "non-negative int")

    comp = obj.get("compile")
    if comp is not None and not isinstance(comp, dict):
        out.append("serve_fabric: compile must be a dict when present")
    elif isinstance(comp, dict):
        fc = comp.get("in_window_fresh_compiles")
        if fc is not None and not isinstance(fc, (int, str)):
            out.append("serve_fabric: compile.in_window_fresh_compiles "
                       "must be an int count or a reason string")
    return out


def _validate_serve_requests(req: dict, kind: str, out: list) -> dict | None:
    """The single-process balanced-request-book rule, shared by the
    ``serve`` kind and the replay artifact's embedded serve book.  The
    POOL book is deliberately not this rule: its cross-process ledger
    carries hedging counters instead of ``expired_dispatched`` (the
    queue-local claim lives inside each worker), so ``serve_pool``
    keeps its own validator."""
    for k in ("admitted", "served", "rejected", "expired",
              "expired_dispatched"):
        v = req.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            out.append(f"{kind}: requests.{k} must be a non-negative int "
                       "(the accounting is the contract)")
            return None
    total = req["served"] + req["rejected"] + req["expired"]
    if total != req["admitted"]:
        out.append(
            f"{kind}: request accounting broken — served {req['served']} "
            f"+ rejected {req['rejected']} + expired {req['expired']} = "
            f"{total} != admitted {req['admitted']} (a request was "
            "dropped or double-counted)")
    if req["expired_dispatched"] != 0:
        out.append(
            f"{kind}: expired_dispatched = {req['expired_dispatched']} — "
            "a request that expired while queued must be cancelled, "
            "never dispatched")
    return req


def _validate_replay(obj: dict) -> list:
    """The replay artifact contract: closed tick books, closed serve
    books, and ingest-vs-serve panel-version reconciliation."""
    out: list = []
    _require(obj, "run_id", str, "replay", out)
    ver = _require(obj, "schema_version", int, "replay", out)
    if ver is not None and ver not in KNOWN_REPLAY_SCHEMA_VERSIONS:
        out.append(
            f"replay: unknown schema_version {ver} (this checker "
            f"understands {list(KNOWN_REPLAY_SCHEMA_VERSIONS)}) — the "
            "artifact is from a different era of the code; do not "
            "half-parse it")
    _require(obj, "wall_s", _NUM, "replay", out, "a number")
    out += _validate_record(obj, kind="replay")

    ticks = _require(obj, "ticks", dict, "replay", out)
    if ticks is not None:
        keys = ("generated", "offered", "applied", "merged_late",
                "quarantined", "deduped", "dropped_gap", "duplicated")
        for k in keys:
            v = ticks.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"replay: ticks.{k} must be a non-negative int "
                           "(the tick ledger is the contract)")
                ticks = None
                break
    if ticks is not None:
        landed = (ticks["applied"] + ticks["merged_late"]
                  + ticks["quarantined"] + ticks["deduped"])
        if landed != ticks["offered"]:
            out.append(
                f"replay: tick accounting broken — applied "
                f"{ticks['applied']} + merged_late {ticks['merged_late']} "
                f"+ quarantined {ticks['quarantined']} + deduped "
                f"{ticks['deduped']} = {landed} != offered "
                f"{ticks['offered']} (a tick vanished between the feed "
                "and the ledger)")
        want_offered = (ticks["generated"] + ticks["duplicated"]
                        - ticks["dropped_gap"])
        if ticks["offered"] != want_offered:
            out.append(
                f"replay: feed accounting broken — offered "
                f"{ticks['offered']} != generated {ticks['generated']} + "
                f"duplicated {ticks['duplicated']} - dropped_gap "
                f"{ticks['dropped_gap']} = {want_offered}")

    panel = _require(obj, "panel", dict, "replay", out)
    if panel is not None:
        for k in ("version_final", "bars_appended", "gap_bars",
                  "stale_bars", "unfilled_cells"):
            v = panel.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"replay: panel.{k} must be a non-negative int")

    serve = _require(obj, "serve", dict, "replay", out)
    req = None
    if serve is not None:
        sreq = serve.get("requests")
        if not isinstance(sreq, dict):
            out.append("replay: serve.requests must be a dict (the serve "
                       "book rides inside the replay artifact)")
        else:
            req = _validate_serve_requests(sreq, "replay serve", out)
        _validate_latency_side((serve.get("latency_ms") or {}).get("total"),
                               "total", "replay", out)

    versions = _require(obj, "versions", dict, "replay", out)
    if versions is not None and panel is not None:
        vf = versions.get("ingest_final")
        if not isinstance(vf, int) or isinstance(vf, bool):
            out.append("replay: versions.ingest_final must be an int")
        elif isinstance(panel.get("version_final"), int) \
                and vf != panel["version_final"]:
            out.append(
                f"replay: versions.ingest_final {vf} != "
                f"panel.version_final {panel['version_final']} — the "
                "ingest side must agree with itself")
        smax = versions.get("serve_max")
        smin = versions.get("serve_min")
        for name, v in (("serve_min", smin), ("serve_max", smax)):
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 0):
                out.append(f"replay: versions.{name} must be a "
                           "non-negative int or null")
        if (isinstance(smax, int) and isinstance(vf, int)
                and smax > vf):
            out.append(
                f"replay: version reconciliation broken — serve answered "
                f"from panel version {smax} but ingest only ever issued "
                f"up to {vf} (a response was computed from a version "
                "that never existed)")
        if (isinstance(smin, int) and isinstance(smax, int)
                and smin > smax):
            out.append("replay: versions.serve_min > serve_max")
        for name in ("skew_events", "skew_attempts", "skew_refusals"):
            v = versions.get(name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"replay: versions.{name} must be a "
                           "non-negative int")
        sk = versions.get("skew_refusals")
        ska = versions.get("skew_attempts")
        if isinstance(sk, int) and isinstance(ska, int) and sk > ska:
            out.append(
                f"replay: skew_refusals {sk} > skew_attempts {ska} — "
                "more refusals than stale requests were ever submitted")
        if (isinstance(sk, int) and req is not None
                and sk != req.get("rejected_version_skew", 0)):
            out.append(
                f"replay: versions.skew_refusals {sk} does not reconcile "
                f"with serve.requests.rejected_version_skew "
                f"{req.get('rejected_version_skew', 0)}")

    rec = _require(obj, "reconcile", dict, "replay", out)
    if rec is not None:
        for k in ("count", "drift_events", "rebuilds"):
            v = rec.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"replay: reconcile.{k} must be a non-negative "
                           "int")
        # r14's window-slide counter: optional (pre-r14 artifacts lack
        # it) but typed like its sibling counters when present
        v = rec.get("reanchors")
        if v is not None and (not isinstance(v, int) or isinstance(v, bool)
                              or v < 0):
            out.append("replay: reconcile.reanchors must be a "
                       "non-negative int when present")
        if (isinstance(rec.get("count"), int)
                and isinstance(rec.get("drift_events"), int)
                and rec["drift_events"] > rec["count"]):
            out.append("replay: reconcile.drift_events exceeds "
                       "reconcile.count")

    stale = _require(obj, "staleness_ms", dict, "replay", out)
    if stale is not None:
        vals = []
        for q in ("p50", "p95", "p99"):
            v = stale.get(q)
            if v is None:
                continue
            if not isinstance(v, _NUM) or isinstance(v, bool):
                out.append(f"replay: staleness_ms.{q} must be a number "
                           "(milliseconds) or null")
            else:
                vals.append(v)
        if vals != sorted(vals):
            out.append("replay: staleness_ms percentiles must be "
                       "non-decreasing")

    comp = obj.get("compile")
    if comp is not None and not isinstance(comp, dict):
        out.append("replay: compile must be a dict when present")
    elif isinstance(comp, dict):
        fc = comp.get("in_window_fresh_compiles")
        if fc is not None and not isinstance(fc, (int, str)):
            out.append("replay: compile.in_window_fresh_compiles must be "
                       "an int count or a reason string")
    return out


def _validate_trace(obj: dict) -> list:
    """The trace artifact contract (``TRACE_*.json``, obs.trace): CLOSED
    trace books (every opened trace ends complete or reasoned-partial),
    telescoping stage reconciliation under epsilon, per-class burn
    arithmetic, and reconciliation against the driven serve run's
    request book (``complete == served``, ``partial == rejected +
    expired``) — the decomposition is only evidence if it covers every
    request the serve books admitted."""
    out: list = []
    _require(obj, "run_id", str, "trace", out)
    ver = _require(obj, "schema_version", int, "trace", out)
    if ver is not None and ver not in KNOWN_TRACE_SCHEMA_VERSIONS:
        out.append(
            f"trace: unknown schema_version {ver} (this checker "
            f"understands {list(KNOWN_TRACE_SCHEMA_VERSIONS)}) — the "
            "artifact is from a different era of the code; do not "
            "half-parse it")
        return out
    out += _validate_record(obj, kind="trace")

    books = _require(obj, "books", dict, "trace", out)
    if books is not None:
        for k in ("opened", "complete", "partial"):
            v = books.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"trace: books.{k} must be a non-negative int "
                           "(the closed trace books are the contract)")
                books = None
                break
    if books is not None:
        if books["complete"] + books["partial"] != books["opened"]:
            out.append(
                f"trace: books broken — complete {books['complete']} + "
                f"partial {books['partial']} = "
                f"{books['complete'] + books['partial']} != opened "
                f"{books['opened']} (a request's trace never closed)")
        reasons = books.get("partial_reasons")
        if not isinstance(reasons, dict):
            out.append("trace: books.partial_reasons must be a dict of "
                       "reason -> count")
        elif books["partial"] and sum(reasons.values()) != books["partial"]:
            out.append(
                f"trace: partial_reasons sum to {sum(reasons.values())} "
                f"but partial is {books['partial']} — a partial trace "
                "closed without a reason")

    orphans = _require(obj, "orphans", dict, "trace", out)
    if isinstance(orphans, dict):
        oc = orphans.get("count")
        if not isinstance(oc, int) or isinstance(oc, bool) or oc < 0:
            out.append("trace: orphans.count must be a non-negative int")
        reasons = orphans.get("reasons")
        if not isinstance(reasons, dict):
            out.append("trace: orphans.reasons must be a dict of "
                       "reason -> count")
        elif isinstance(oc, int) and sum(reasons.values()) != oc:
            out.append(
                f"trace: orphan reasons sum to {sum(reasons.values())} "
                f"but count is {oc} — an orphan half was closed without "
                "its reason")

    stages = _require(obj, "stages", dict, "trace", out)
    if isinstance(stages, dict):
        if not stages and books and books.get("complete"):
            out.append("trace: complete traces exist but the stage "
                       "decomposition is empty")
        for name, s in stages.items():
            if not isinstance(s, dict):
                out.append(f"trace: stages[{name!r}] must be a dict")
                continue
            c = s.get("count")
            if not isinstance(c, int) or isinstance(c, bool) or c < 0:
                out.append(f"trace: stages[{name!r}].count must be a "
                           "non-negative int")
            _validate_latency_side(
                {q: s.get(q) for q in ("p50", "p95", "p99")},
                f"stages.{name}", "trace", out)

    rec = _require(obj, "reconcile", dict, "trace", out)
    if isinstance(rec, dict):
        for k in ("checked", "violations"):
            v = rec.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"trace: reconcile.{k} must be a non-negative "
                           "int")
        eps = rec.get("epsilon_ms")
        res = rec.get("max_abs_residual_ms")
        for name, v in (("epsilon_ms", eps), ("max_abs_residual_ms", res)):
            if not isinstance(v, _NUM) or isinstance(v, bool) or v < 0:
                out.append(f"trace: reconcile.{name} must be a "
                           "non-negative number")
        if rec.get("violations"):
            out.append(
                f"trace: {rec['violations']} trace(s) whose stage walls "
                "do not sum to the request wall within epsilon — the "
                "decomposition lost track of where the time went; "
                "invalid evidence, full stop")
        if (isinstance(eps, _NUM) and isinstance(res, _NUM)
                and not isinstance(eps, bool) and res > eps):
            out.append(
                f"trace: reconcile.max_abs_residual_ms {res} exceeds "
                f"epsilon_ms {eps} but violations claims none — the "
                "reconcile block disagrees with itself")

    slowest = _require(obj, "slowest", list, "trace", out)
    if isinstance(slowest, list) and isinstance(rec, dict):
        eps = rec.get("epsilon_ms")
        for i, e in enumerate(slowest):
            if not isinstance(e, dict) or not isinstance(
                    e.get("stages"), dict):
                out.append(f"trace: slowest[{i}] must be a dict with a "
                           "stages breakdown")
                continue
            wall = e.get("wall_ms")
            if not isinstance(wall, _NUM) or isinstance(wall, bool):
                out.append(f"trace: slowest[{i}].wall_ms must be a number")
                continue
            ssum = sum(v for v in e["stages"].values()
                       if isinstance(v, _NUM) and not isinstance(v, bool))
            if isinstance(eps, _NUM) and abs(ssum - wall) > eps:
                out.append(
                    f"trace: slowest[{i}] stage walls sum to {ssum:.3f} "
                    f"ms but wall_ms is {wall:.3f} (off by more than "
                    f"epsilon {eps} ms) — the critical path does not "
                    "reconcile")

    classes = _require(obj, "classes", dict, "trace", out)
    if isinstance(classes, dict):
        for name, book in classes.items():
            if not isinstance(book, dict):
                out.append(f"trace: classes[{name!r}] must be a dict")
                continue
            for k in ("count", "served", "violations"):
                v = book.get(k)
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    out.append(f"trace: classes[{name!r}].{k} must be a "
                               "non-negative int")
                    break
            else:
                if book["violations"] > book["served"]:
                    out.append(f"trace: classes[{name!r}].violations "
                               f"{book['violations']} > served "
                               f"{book['served']}")
                burn = book.get("budget_burn")
                if burn is not None and (not isinstance(burn, _NUM)
                                         or isinstance(burn, bool)
                                         or burn < 0):
                    out.append(f"trace: classes[{name!r}].budget_burn "
                               "must be a non-negative number or null")

    req = _require(obj, "requests", dict, "trace", out)
    if isinstance(req, dict):
        ok = True
        for k in ("admitted", "served", "rejected", "expired"):
            v = req.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"trace: requests.{k} must be a non-negative "
                           "int (the serve book this trace run must "
                           "reconcile against)")
                ok = False
        if ok and books is not None:
            if books["complete"] != req["served"]:
                out.append(
                    f"trace: books.complete {books['complete']} != "
                    f"requests.served {req['served']} — a served request "
                    "has no complete trace (or a trace claims a serve "
                    "that never happened)")
            if books["partial"] != req["rejected"] + req["expired"]:
                out.append(
                    f"trace: books.partial {books['partial']} != "
                    f"rejected {req['rejected']} + expired "
                    f"{req['expired']} — the partial ledger does not "
                    "cover every non-served request")

    comp = obj.get("compile")
    if comp is not None and not isinstance(comp, dict):
        out.append("trace: compile must be a dict when present")
    elif isinstance(comp, dict):
        fc = comp.get("in_window_fresh_compiles")
        if fc is not None and not isinstance(fc, (int, str)):
            out.append("trace: compile.in_window_fresh_compiles must be "
                       "an int count or a reason string")
    return out


def _validate_lint(obj: dict) -> list:
    """The lint report contract (`csmom lint --format json`): known
    schema version, the closed v2 key world, coherent findings shape,
    and ``ok`` actually meaning zero findings."""
    out: list = []
    ver = _require(obj, "schema_version", int, "lint", out)
    if ver is not None and ver not in KNOWN_LINT_SCHEMA_VERSIONS:
        out.append(
            f"lint: unknown schema_version {ver} (this checker "
            f"understands {list(KNOWN_LINT_SCHEMA_VERSIONS)}) — the "
            "report is from a different era of the code; do not "
            "half-parse it")
        return out
    _require(obj, "ok", bool, "lint", out)
    _require(obj, "files_scanned", int, "lint", out)
    _require(obj, "rules", list, "lint", out)
    findings = _require(obj, "findings", list, "lint", out)
    _require(obj, "suppressed", list, "lint", out)
    if ver == 2:
        unknown = sorted(set(obj) - _LINT_V2_KEYS)
        if unknown:
            out.append(f"lint: unknown v2 keys {unknown} — the report "
                       "key world is closed; bump the schema version "
                       "for new fields")
        _require(obj, "project", bool, "lint", out)
        cache = _require(obj, "cache", dict, "lint", out)
        if cache is not None and not isinstance(cache.get("enabled"),
                                                bool):
            out.append("lint: cache.enabled must be a bool")
        _require(obj, "rule_timings_s", dict, "lint", out)
    if findings is not None:
        for i, f in enumerate(findings):
            if not isinstance(f, dict):
                out.append(f"lint: findings[{i}] must be an object")
                continue
            missing = {"rule", "path", "line", "message"} - set(f)
            if missing:
                out.append(f"lint: findings[{i}] missing {sorted(missing)}")
            if ver == 2 and not set(f) <= _LINT_FINDING_KEYS:
                out.append(f"lint: findings[{i}] carries unknown keys "
                           f"{sorted(set(f) - _LINT_FINDING_KEYS)}")
        if isinstance(obj.get("ok"), bool) and obj["ok"] != (
                len(findings) == 0):
            out.append("lint: ok flag disagrees with the findings list "
                       "(ok means ZERO unsuppressed findings)")
    return out


def _validate_fleet(obj: dict) -> list:
    """The fleet observatory contract (FLEET_*.json, obs/fleet.py):

    - CLOSED stream books: every process that ever streamed ends with a
      non-empty close reason (fin on clean drain, ``stream severed`` on
      SIGKILL) — a series that just stops without a reason is the r4
      silent-truncation failure wearing a new coat.
    - No orphan series: every ``points`` entry's proc has a process
      book (data from a process the aggregator never opened is forged
      or corrupted).
    - Counter series are MONOTONE: the aggregator reconstructs counters
      as ``cum += max(0, delta)``, so a decreasing counter series can
      only mean the artifact was edited after landing.
    - Demand reconciles three ways: per-second buckets sum to the class
      totals, ``admitted <= offered`` per class, and the run totals
      match the embedded serve request book — BY SCHEMA, not by eye.
    - Capacity account arithmetic: fractions in [0, 1], available never
      exceeds nominal, and every kill window's ready stamp is at or
      after its kill stamp."""
    out: list = []
    _require(obj, "run_id", str, "fleet", out)
    ver = _require(obj, "schema_version", int, "fleet", out)
    if ver is not None and ver not in KNOWN_FLEET_SCHEMA_VERSIONS:
        out.append(
            f"fleet: unknown schema_version {ver} (this checker "
            f"understands {list(KNOWN_FLEET_SCHEMA_VERSIONS)}) — the "
            "artifact is from a different era of the code; do not "
            "half-parse it")
    _require(obj, "cadence_s", _NUM, "fleet", out, "a number")
    _require(obj, "window_s", _NUM, "fleet", out, "a number")
    out += _validate_record(obj, kind="fleet")

    series = _require(obj, "series", dict, "fleet", out)
    procs: dict = {}
    if isinstance(series, dict):
        books = series.get("books")
        if not isinstance(books, dict):
            out.append("fleet: series.books (the stream ledger) must be "
                       "a dict")
            books = {}
        for k in ("procs_opened", "procs_closed", "frames",
                  "frames_malformed", "seq_gaps",
                  "frames_dropped_by_emitters", "series_count",
                  "series_dropped"):
            v = books.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(f"fleet: series.books.{k} must be a "
                           "non-negative int")
        procs = series.get("processes")
        if not isinstance(procs, dict):
            out.append("fleet: series.processes must be a dict of "
                       "per-process stream books")
            procs = {}
        for name, book in procs.items():
            if not isinstance(book, dict):
                out.append(f"fleet: process book {name!r} must be a dict")
                continue
            if not book.get("closed") or not book.get("close_reason"):
                out.append(
                    f"fleet: process {name!r} stream is not reason-"
                    "closed — every series must end with fin or a "
                    "severed-stream reason, never silence (a SIGKILLed "
                    "emitter reads as a reason-closed gap, not "
                    "truncation)")
        if isinstance(books.get("procs_opened"), int) and \
                isinstance(books.get("procs_closed"), int) and \
                books["procs_opened"] != books["procs_closed"]:
            out.append(
                f"fleet: series books not closed — procs_opened "
                f"{books['procs_opened']} != procs_closed "
                f"{books['procs_closed']}")
        points = series.get("points")
        if not isinstance(points, dict):
            out.append("fleet: series.points must be a dict of series")
            points = {}
        for key, s in points.items():
            if not isinstance(s, dict):
                out.append(f"fleet: series point {key!r} must be a dict")
                continue
            if s.get("proc") not in procs:
                out.append(
                    f"fleet: orphan series {key!r} — proc "
                    f"{s.get('proc')!r} has no process book (data from "
                    "a stream the aggregator never opened)")
            ts, vs = s.get("t_s"), s.get("v")
            if not isinstance(ts, list) or not isinstance(vs, list) \
                    or len(ts) != len(vs):
                out.append(f"fleet: series {key!r} t_s/v must be "
                           "parallel lists")
                continue
            if s.get("kind") == "counter":
                for i in range(1, len(vs)):
                    if vs[i] < vs[i - 1]:
                        out.append(
                            f"fleet: counter series {key!r} decreases "
                            f"at index {i} ({vs[i - 1]} -> {vs[i]}) — "
                            "counters are monotone by construction "
                            "(cum += max(0, delta)); a decrease means "
                            "the artifact was edited after landing")
                        break

    req = obj.get("requests")
    if req is not None and not isinstance(req, dict):
        out.append("fleet: requests (the driven serve run's book) must "
                   "be a dict when present")
        req = None
    demand = _require(obj, "demand", dict, "fleet", out)
    if isinstance(demand, dict):
        classes = demand.get("classes")
        per_s = demand.get("per_second")
        if not isinstance(classes, dict):
            out.append("fleet: demand.classes must be a dict")
            classes = {}
        if not isinstance(per_s, list):
            out.append("fleet: demand.per_second must be a list")
            per_s = []
        bucket_sums: dict = {}
        for row in per_s:
            if not isinstance(row, dict):
                out.append("fleet: demand.per_second rows must be dicts")
                continue
            for cls, ev in row.items():
                if cls == "t_s" or not isinstance(ev, dict):
                    continue
                b = bucket_sums.setdefault(cls, {})
                for e, n in ev.items():
                    b[e] = b.get(e, 0) + (n if isinstance(n, int) else 0)
        for cls, tot in classes.items():
            if not isinstance(tot, dict):
                out.append(f"fleet: demand.classes[{cls!r}] must be a "
                           "dict")
                continue
            if bucket_sums.get(cls, {}) != tot:
                out.append(
                    f"fleet: demand per-second buckets for {cls!r} sum "
                    f"to {bucket_sums.get(cls, {})} but the class total "
                    f"says {tot} — the time series and the totals are "
                    "the same events; they cannot disagree")
            if tot.get("admitted", 0) > tot.get("offered", 0):
                out.append(f"fleet: demand class {cls!r} admitted "
                           f"{tot.get('admitted')} > offered "
                           f"{tot.get('offered')}")
        if isinstance(req, dict):
            for event, book_key in (("admitted", "admitted"),
                                    ("served", "served")):
                d_tot = sum(tot.get(event, 0)
                            for tot in classes.values()
                            if isinstance(tot, dict))
                want = req.get(book_key)
                if isinstance(want, int) and d_tot != want:
                    out.append(
                        f"fleet: unreconciled demand — {event} totals "
                        f"across classes = {d_tot} but the embedded "
                        f"serve book says requests.{book_key} = {want} "
                        "(demand telemetry and the request ledger "
                        "count the same run)")

    cap = _require(obj, "capacity", dict, "fleet", out)
    if isinstance(cap, dict):
        nom, avail = cap.get("nominal_worker_s"), cap.get(
            "available_worker_s")
        if isinstance(nom, _NUM) and isinstance(avail, _NUM) and \
                not isinstance(nom, bool) and not isinstance(avail, bool):
            if avail > nom + 1e-6:
                out.append(
                    f"fleet: capacity.available_worker_s {avail} > "
                    f"nominal_worker_s {nom} — a fleet cannot serve "
                    "more worker-seconds than it has slots")
        for k in ("kill_window_loss_frac", "steady_state_loss_frac"):
            v = cap.get(k)
            if not isinstance(v, _NUM) or isinstance(v, bool) \
                    or not 0.0 <= v <= 1.0:
                out.append(f"fleet: capacity.{k} {v!r} must be a number "
                           "in [0, 1]")
        kws = cap.get("kill_windows")
        if not isinstance(kws, list):
            out.append("fleet: capacity.kill_windows must be a list")
            kws = []
        for i, kw in enumerate(kws):
            if not isinstance(kw, dict):
                out.append(f"fleet: kill_windows[{i}] must be a dict")
                continue
            tk, tr = kw.get("t_kill_s"), kw.get("t_ready_s")
            if isinstance(tk, _NUM) and isinstance(tr, _NUM) and tr < tk:
                out.append(
                    f"fleet: kill_windows[{i}] t_ready_s {tr} < "
                    f"t_kill_s {tk} — a victim cannot be ready before "
                    "it was killed")
            lf = kw.get("loss_frac")
            if lf is not None and (not isinstance(lf, _NUM)
                                   or isinstance(lf, bool)
                                   or not 0.0 <= lf <= 1.0):
                out.append(f"fleet: kill_windows[{i}].loss_frac {lf!r} "
                           "must be a number in [0, 1]")
    lc = obj.get("lifecycle")
    if lc is not None and not isinstance(lc, dict):
        out.append("fleet: lifecycle must be a dict when present")
    elif isinstance(lc, dict):
        rw = lc.get("ready_walls_s")
        if not isinstance(rw, list) or any(
                not isinstance(w, _NUM) or isinstance(w, bool) or w < 0
                for w in rw):
            out.append("fleet: lifecycle.ready_walls_s must be a list "
                       "of non-negative numbers")
    out += _validate_fleet_elastic(obj)
    return out


def _validate_fleet_elastic(obj: dict) -> list:
    """The ``fleet.elastic`` block (ISSUE 20): spares held out of the
    serving books BY SCHEMA, promotions exactly-once, every autoscaler
    decision reasoned."""
    el = obj.get("elastic")
    if el is None:
        return []
    if not isinstance(el, dict):
        return ["fleet: elastic must be a dict when present"]
    out = []
    spare_ids = el.get("spare_ids")
    if not isinstance(spare_ids, list) or any(
            not isinstance(s, str) for s in spare_ids):
        out.append("fleet: elastic.spare_ids must be a list of strings")
        spare_ids = []
    # spares never enter the serving books: lifecycle samples and kill
    # windows may not carry a spare's id (the victim's SLOT keeps its
    # own id through a promotion)
    spares = set(spare_ids)
    lc = obj.get("lifecycle") or {}
    for e in (lc.get("events") or []):
        if isinstance(e, dict) and e.get("worker_id") in spares:
            out.append(
                f"fleet: spare {e['worker_id']!r} appears in "
                "lifecycle.events — a parked spare must be held out of "
                "the serving lifecycle book by schema")
    cap = obj.get("capacity") or {}
    for kw in (cap.get("kill_windows") or []):
        if isinstance(kw, dict) and kw.get("worker_id") in spares:
            out.append(
                f"fleet: spare {kw['worker_id']!r} opened a kill window "
                "— a parked spare was never serving, so its death digs "
                "no capacity hole")
    promos = el.get("promotions")
    if not isinstance(promos, list):
        out.append("fleet: elastic.promotions must be a list")
        promos = []
    seen_spares, seen_slots = set(), set()
    for i, p in enumerate(promos):
        if not isinstance(p, dict):
            out.append(f"fleet: elastic.promotions[{i}] must be a dict")
            continue
        tk, tr = p.get("t_kill_s"), p.get("t_ready_s")
        if isinstance(tk, _NUM) and isinstance(tr, _NUM) and tr < tk:
            out.append(
                f"fleet: elastic.promotions[{i}] t_ready_s {tr} < "
                f"t_kill_s {tk} — a promotion cannot complete before "
                "the kill it answers")
        sid = p.get("spare")
        if sid in seen_spares:
            out.append(
                f"fleet: spare {sid!r} promoted twice — promotion must "
                "be exactly-once per spare (one process cannot fill two "
                "slots)")
        seen_spares.add(sid)
        slot = (p.get("victim"), p.get("generation"))
        if slot in seen_slots:
            out.append(
                f"fleet: slot generation {slot!r} filled by two "
                "promotions — promotion must be exactly-once per "
                "(victim, generation)")
        seen_slots.add(slot)
        if sid is not None and sid not in spares:
            out.append(f"fleet: promotion spare {sid!r} is not a "
                       "declared spare id")
    sp = el.get("spares")
    if not isinstance(sp, dict):
        out.append("fleet: elastic.spares must be a dict of counters")
    elif isinstance(sp.get("promoted"), int) \
            and sp["promoted"] != len(promos):
        out.append(
            f"fleet: elastic.spares.promoted {sp['promoted']} != "
            f"{len(promos)} promotion records — the counter and the "
            "record list count the same events")
    decisions = el.get("decisions")
    if not isinstance(decisions, list):
        out.append("fleet: elastic.decisions must be a list")
        decisions = []
    for i, d in enumerate(decisions):
        if not isinstance(d, dict):
            out.append(f"fleet: elastic.decisions[{i}] must be a dict")
            continue
        if not str(d.get("reason") or "").strip():
            out.append(
                f"fleet: elastic.decisions[{i}] "
                f"({d.get('action')!r}) has no reason — every "
                "autoscaler decision must be a reasoned event")
        if d.get("action") not in ("scale_up", "scale_down", "hold",
                                   "tune_quota"):
            out.append(f"fleet: elastic.decisions[{i}].action "
                       f"{d.get('action')!r} unknown")
    quota = el.get("quota")
    if isinstance(quota, dict):
        floor, ceil = quota.get("floor_rps"), quota.get("ceiling_rps")
        for q in (quota.get("applied") or []):
            r = q.get("quota_rps") if isinstance(q, dict) else None
            if isinstance(r, _NUM) and isinstance(floor, _NUM) \
                    and isinstance(ceil, _NUM) \
                    and not (floor - 1e-9 <= r <= ceil + 1e-9):
                out.append(
                    f"fleet: applied quota {r} rps outside the declared "
                    f"floor/ceiling [{floor}, {ceil}] — auto-tuning must "
                    "respect its declared bounds")
    return out


_VALIDATORS = {
    "record": _validate_record,
    "lint": _validate_lint,
    "trace": _validate_trace,
    "replay": _validate_replay,
    "serve": _validate_serve,
    "serve_pool": _validate_serve_pool,
    "serve_fabric": _validate_serve_fabric,
    "fleet": _validate_fleet,
    "telemetry": _validate_telemetry,
    "driver_capture": _validate_driver_capture,
    "multichip": _validate_multichip,
    "phases": _validate_phases,
    "tpu_cache": _validate_tpu_cache,
}


def validate(obj, kind: str | None = None) -> list:
    """All contract violations of one artifact object (empty = valid)."""
    if not isinstance(obj, dict):
        return [f"artifact must be a JSON object, got {type(obj).__name__}"]
    kind = kind or detect_kind(obj)
    if kind is None:
        return ["unrecognized artifact shape: none of the known key "
                "signatures (record / driver_capture / multichip / phases "
                "/ tpu_cache / telemetry / serve / serve_pool / "
                "serve_fabric / fleet / replay / trace / lint) match"]
    if kind not in _VALIDATORS:
        return [f"unknown artifact kind {kind!r}"]
    return _VALIDATORS[kind](obj)


def validate_headline_text(stdout_text: str) -> list:
    """The stdout contract of a capture process: a trailing JSON line that
    parses, validates as a record, and fits the driver's tail window."""
    out: list = []
    obj = trailing_json(stdout_text)
    if obj is None:
        return ["no parseable trailing JSON line on stdout (the r5 "
                "failure mode: measurements existed but no line landed)"]
    line = next(
        ln for ln in reversed(stdout_text.strip().splitlines())
        if ln.strip().startswith("{")
        and _parses(ln.strip())
    )
    if len(line.strip()) > DRIVER_TAIL_CHARS:
        out.append(
            f"headline line is {len(line.strip())} chars — longer than the "
            f"driver's {DRIVER_TAIL_CHARS}-char tail window (the r4 "
            "failure mode)"
        )
    out += validate(obj, "record")
    return out


def _parses(line: str) -> bool:
    try:
        return isinstance(json.loads(line), dict)
    except json.JSONDecodeError:
        return False


def upgrade_ok(old, new) -> list:
    """Monotone-upgrade rule for re-landing an artifact name (the
    capture_lib.sh contract): full beats partial; a partial only replaces
    a partial with STRICTLY more measured rows; nothing replaces a full.
    Returns violations of ``new`` landing over ``old``."""
    if old is None:
        return []
    if not is_partial(old):
        return ["landing over a FULL artifact: a full capture is never "
                "overwritten"]
    if is_partial(new) and measured_rows(new) <= measured_rows(old):
        return [
            f"partial-over-partial downgrade: new has {measured_rows(new)} "
            f"measured rows, existing partial has {measured_rows(old)}"
        ]
    return []


def validate_file(path: str) -> list:
    """Violations of one artifact file (unreadable/unparseable included)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except OSError as e:
        return [f"unreadable: {e}"]
    except json.JSONDecodeError as e:
        return [f"not valid JSON: {e}"]
    return validate(obj)


def validate_tree(root: str, patterns=("BENCH_*.json", "MULTICHIP_*.json",
                                       "MULTIHOST_*.json", "HISTRANK_*.json",
                                       "PHASES_*.json", "TELEMETRY_*.json",
                                       "SERVE_*.json",
                                       "REPLAY_*.json",
                                       "TRACE_*.json",
                                       "FLEET_*.json")) -> dict:
    """``{relative_path: violations}`` for every committed artifact under
    ``root`` matching ``patterns`` (non-recursive: round artifacts land at
    the repo root by contract).  Paths with no violations are included
    with an empty list, so callers can report coverage, not just failures.
    """
    import glob as _glob

    out = {}
    for pat in patterns:
        for path in sorted(_glob.glob(os.path.join(root, pat))):
            out[os.path.basename(path)] = validate_file(path)
    return out
