"""Miniature capture child: the capture *path* without the bench *workload*.

``python -m csmom_tpu.chaos.minibench`` plays the role of a measurement
process (a bench child / scaling sweep) in milliseconds: it arms the same
:func:`~csmom_tpu.utils.deadline.deadline_guard`, "measures" N rows with
a ``mini.row`` checkpoint between them, mirrors every measured row into a
progress sidecar file (the ground truth rehearsal compares artifacts
against — a row in the sidecar but not in the landed artifact IS a lost
measurement), and ends with one trailing JSON line through the guard's
quarantined emit path.

This is what makes the tier-1 rehearsal subset fast: the deadline /
trailing-JSON / landing invariants are properties of the capture plumbing,
not of the workload being measured, so they rehearse in <1 s per fault
with no jax import, while the slow matrix drives the real bench.py
supervisor end to end.

Env contract (mirrors bench's child contract):

- ``CSMOM_MINIBENCH_BUDGET``  wall budget (s) for the deadline guard
- ``CSMOM_MINIBENCH_ROWS``    rows to measure (default 5)
- ``CSMOM_MINIBENCH_ROW_S``   simulated wall per row (default 0.01)
- ``CSMOM_MINIBENCH_SIDECAR`` path for the progress sidecar (JSON lines)
- ``CSMOM_FAULT_PLAN``        the armed fault plan, as everywhere
"""

from __future__ import annotations

import json
import os
import sys
import time

_T0 = time.monotonic()


def main() -> int:
    from csmom_tpu.chaos.inject import checkpoint
    from csmom_tpu.obs import arm_from_env
    from csmom_tpu.utils.deadline import deadline_guard

    # join an armed telemetry stream (CSMOM_TELEMETRY): every checkpoint
    # below then doubles as a timeline point, mirroring bench's contract
    arm_from_env("minibench")

    n_rows = int(os.environ.get("CSMOM_MINIBENCH_ROWS", "5"))
    row_s = float(os.environ.get("CSMOM_MINIBENCH_ROW_S", "0.01"))
    sidecar = os.environ.get("CSMOM_MINIBENCH_SIDECAR", "")

    rows: list = []

    def record_row(row: dict) -> None:
        rows.append(row)
        if sidecar:  # ground truth: appended the instant a row is measured
            with open(sidecar, "a") as f:
                f.write(json.dumps(row) + "\n")
                f.flush()
                os.fsync(f.fileno())

    def partial_line():
        if not rows:
            return None  # nothing measured: no artifact-worthy line
        return json.dumps({
            "metric": "minibench_rows_per_sec",
            "value": round(rows[-1]["value"], 4),
            "unit": "rows/s",
            "vs_baseline": 1.0,
            "extra": {
                "rows": rows,
                "partial": "minibench deadline hit before every row "
                           "completed; unmeasured rows are absent",
            },
        })

    finish = deadline_guard(
        "CSMOM_MINIBENCH_BUDGET", partial_line, t0=_T0,
        min_delay_s=float(os.environ.get("CSMOM_MINIBENCH_MIN_DELAY", "30")),
    )

    checkpoint("mini.start")
    for i in range(n_rows):
        checkpoint("mini.row", row=i)
        t0 = time.perf_counter()
        # the "measurement": a deterministic spin standing in for a timed leg
        acc = 0.0
        k = 0
        while time.perf_counter() - t0 < row_s:
            acc += (k % 97) * 1e-9
            k += 1
        record_row({"row": i, "value": 1.0 / max(row_s, 1e-9),
                    "wall_s": round(time.perf_counter() - t0, 6)})
        print(f"row {i} done wall={rows[-1]['wall_s']}s", flush=(i % 2 == 0))

    checkpoint("mini.finish")
    finish(json.dumps({
        "metric": "minibench_rows_per_sec",
        "value": round(rows[-1]["value"], 4),
        "unit": "rows/s",
        "vs_baseline": 1.0,
        "extra": {"rows": rows, "n_rows": len(rows)},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
