"""Fault plans: seeded, serializable descriptions of what to break where.

A plan is a list of :class:`Fault`\\ s, each bound to a named
instrumentation point (:func:`csmom_tpu.chaos.inject.checkpoint` call
sites).  Plans serialize to TOML and arm through the ``CSMOM_FAULT_PLAN``
environment variable — either a path to a ``.toml`` file or the TOML text
itself (anything containing a newline or ``[[fault]]`` is treated as
inline).  The env-var transport is deliberate: the capture pipeline is a
process *tree* (supervisor → probe subprocesses → bench children →
warmup child), and environment inheritance arms every process in it with
one assignment, no plumbing.

Determinism: ``seed`` drives every randomized choice a fault makes
(corruption byte offsets, noise payloads) through ``random.Random`` — the
same plan byte-for-byte reproduces the same damage.  Hit counting is
per-process (each process in the tree counts its own checkpoint visits),
which is what makes "kill the FIRST bench child at its first compile, let
the fallback child live" expressible: the fallback is a new process whose
counters start at zero, so a fault with ``max_fires = 1`` consumed by the
first child never fires again *in that process* — cross-process scoping
uses ``role`` instead (supervisor / child / warmup / any, derived from
the ``CSMOM_BENCH_*`` env contract the pipeline already carries).

TOML shape::

    name = "kill-child-mid-compile"
    seed = 7

    [[fault]]
    point = "bench.compile"     # checkpoint name (fnmatch pattern ok)
    action = "kill"             # see Fault.ACTIONS
    role = "child"              # supervisor | child | warmup | any
    after = 0                   # skip this many matching hits first
    max_fires = 1               # fire at most this many times (0 = every)
    # action-specific keys: seconds, path, bytes, code, errno, text
"""

from __future__ import annotations

import dataclasses
import os
from fnmatch import fnmatch

__all__ = ["Fault", "FaultPlan", "load_active_plan", "PLAN_ENV"]

PLAN_ENV = "CSMOM_FAULT_PLAN"

# The plan-point vocabulary: every literal ``checkpoint("...")`` call
# site in the package/bench harness, i.e. every point a fault plan can
# target.  The enumeration-drift lint rule (csmom_tpu/analysis/rules.py)
# cross-checks BOTH directions on every sweep: a call site whose point
# is missing here fails `csmom lint`, and an entry here whose call site
# vanished is dead vocabulary and fails it too — this tuple replaced the
# prose inventory in chaos/inject.py, which had drifted twice (no
# mini.start, no serve.cache) by the time the vocabulary became code.
KNOWN_POINTS = (
    "bench.probe", "bench.compile", "bench.row", "bench.finish",
    "bench.land",
    "warmup.entry", "aot.compile",
    "mini.start", "mini.row", "mini.finish",
    "serve.admit", "serve.coalesce", "serve.dispatch", "serve.cache",
    "serve.transport",
    "pool.route", "pool.hedge", "pool.spawn",
    "stream.tick", "stream.ingest", "stream.serve",
)

_ROLES = ("any", "supervisor", "child", "warmup")


def _toml_module():
    try:
        import tomllib  # 3.11+ stdlib
    except ModuleNotFoundError:  # pragma: no cover - 3.10 image
        import tomli as tomllib
    return tomllib


def _toml_value(v) -> str:
    """One scalar as TOML source (bools are lowercase; strings escape via
    the JSON rules, which TOML basic strings share)."""
    import json

    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return json.dumps(v)
    return repr(v)


def current_role() -> str:
    """Which pipeline process this is, from the env contract bench already
    sets on its children (``CSMOM_BENCH_CHILD`` / ``CSMOM_BENCH_WARMUP``).
    A process that is neither is the supervisor (or a standalone CLI run,
    which rehearses as one)."""
    if os.environ.get("CSMOM_BENCH_WARMUP"):
        return "warmup"
    if os.environ.get("CSMOM_BENCH_CHILD"):
        return "child"
    return "supervisor"


@dataclasses.dataclass(frozen=True)
class Fault:
    """One fault: fire ``action`` at the ``after+1``-th .. hit of ``point``.

    ``point`` is matched with :func:`fnmatch.fnmatch`, so
    ``point = "bench.*"`` hits every bench checkpoint.  ``max_fires = 0``
    means "every matching hit".
    """

    point: str
    action: str
    role: str = "any"
    after: int = 0
    max_fires: int = 1
    global_once: bool = False  # fire once across the whole PROCESS TREE
                               # (file-marker claim in CSMOM_FAULT_STATE):
                               # "kill the first bench child, spare the
                               # fallback" — per-process counters cannot
                               # express that, a new process starts at 0
    # action parameters (unused ones stay at their defaults)
    seconds: float = 0.0     # sleep
    path: str = ""           # corrupt_file / truncate_file glob (env-expanded)
    bytes: int = 64          # truncate_file: size to keep
    code: int = 1            # exit: status
    errno_: int = 28         # raise_oserror: errno (default ENOSPC)
    text: str = "chaos"      # stdout_noise payload / fail reason

    ACTIONS = (
        "kill",           # SIGKILL this process, right now (external cap)
        "exit",           # os._exit(code) — a crash that skips cleanup
        "sleep",          # hang for `seconds` (tunnel stall)
        "trip_deadline",  # fire the armed deadline guard immediately
        # lint: allow[clock-discipline] documents what the skew fault perturbs
        "clock_skew",     # jump time.time() by `seconds`; monotonic clocks
                          # must shield every deadline from this
        "corrupt_file",   # seeded byte-flips over files matching `path`
        "truncate_file",  # cut files matching `path` to `bytes` bytes
        "raise_oserror",  # raise OSError(errno_) at the checkpoint (ENOSPC)
        "stdout_noise",   # concurrent writer racing the trailing JSON line
        "fail",           # return "fail" for the caller to interpret
        # stream-replay tick faults (ISSUE 7) — like "fail", these are
        # RESULT faults the caller interprets: the replay feed holds the
        # tick back (late/out-of-order arrival), re-offers it
        # (duplicate), or discards it (gap); "version_skew" makes a
        # serve probe answer from a stale panel snapshot, which the
        # service's version gate must refuse
        "tick_late",
        "tick_dup",
        "tick_drop",
        "version_skew",
        # serve result-cache fault (ISSUE 8) — caller-interpreted at the
        # serve.cache checkpoint: the cache plants an entry under the
        # looked-up key stamped BELOW the version floor; the get path's
        # floor check must refuse it (stale_blocked), never serve it
        "cache_poison",
        # network faults (ISSUE 14) — caller-interpreted at the
        # serve.transport checkpoint (serve/proto.py): "conn_reset"
        # raises a connection reset into the dispatcher's failover
        # handling, "net_delay" stalls the transport by
        # CSMOM_CHAOS_NET_DELAY_S (an induced straggler for the hedging
        # policy to route around), and "partition" cuts the firing
        # process off from the peer address for CSMOM_CHAOS_PARTITION_S.
        # On the r19 persistent channels a partition SEVERS every live
        # channel to the peer — in-flight requests reason-close into
        # failover, not just new dials refused — until it heals
        "conn_reset",
        "net_delay",
        "partition",
    )

    def validate(self) -> None:
        if self.action not in self.ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r} (expected one of "
                f"{', '.join(self.ACTIONS)})"
            )
        if self.role not in _ROLES:
            raise ValueError(
                f"unknown fault role {self.role!r} (expected one of "
                f"{', '.join(_ROLES)})"
            )
        if self.after < 0 or self.max_fires < 0:
            raise ValueError("after/max_fires must be >= 0")

    def matches(self, point: str, hit_index: int, role: str) -> bool:
        """Does this fault fire for the ``hit_index``-th (0-based) matching
        visit of ``point`` in a process with ``role``?"""
        if self.role not in ("any", role):
            return False
        if not fnmatch(point, self.point):
            return False
        if hit_index < self.after:
            return False
        if self.max_fires and hit_index >= self.after + self.max_fires:
            return False
        return True

    def to_toml(self) -> str:
        lines = ["[[fault]]",
                 f"point = {_toml_value(self.point)}",
                 f"action = {_toml_value(self.action)}"]
        defaults = Fault(point="", action="kill")
        for f in dataclasses.fields(self):
            if f.name in ("point", "action"):
                continue
            v = getattr(self, f.name)
            if v != getattr(defaults, f.name):
                key = "errno" if f.name == "errno_" else f.name
                lines.append(f"{key} = {_toml_value(v)}")
        return "\n".join(lines)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A named, seeded set of faults (the unit ``csmom rehearse`` runs)."""

    name: str
    faults: tuple
    seed: int = 0

    def validate(self) -> None:
        if not self.name:
            raise ValueError("fault plan needs a name")
        for f in self.faults:
            f.validate()

    def to_toml(self) -> str:
        head = f'name = "{self.name}"\nseed = {self.seed}\n'
        return head + "\n" + "\n\n".join(f.to_toml() for f in self.faults) + "\n"

    @classmethod
    def from_toml(cls, text: str) -> "FaultPlan":
        raw = _toml_module().loads(text)
        known = {f.name for f in dataclasses.fields(Fault)} | {"errno"}
        faults = []
        for i, entry in enumerate(raw.get("fault", [])):
            bad = set(entry) - known
            if bad:
                raise ValueError(
                    f"fault #{i}: unknown keys {sorted(bad)} (a typo'd "
                    "fault key must not silently become a no-op)"
                )
            if "errno" in entry:
                entry = dict(entry, errno_=entry.pop("errno"))
            faults.append(Fault(**entry))
        plan = cls(
            name=str(raw.get("name", "")),
            seed=int(raw.get("seed", 0)),
            faults=tuple(faults),
        )
        plan.validate()
        return plan

    @classmethod
    def from_env_value(cls, value: str) -> "FaultPlan":
        """Resolve the ``CSMOM_FAULT_PLAN`` value: a path unless it looks
        like inline TOML (contains a newline or a ``[[fault]]`` table)."""
        if "\n" in value or "[[fault]]" in value:
            return cls.from_toml(value)
        with open(value) as f:
            return cls.from_toml(f.read())


def load_active_plan() -> "FaultPlan | None":
    """The armed plan, or None.  Raises loudly on an unparseable plan — a
    rehearsal that silently ran fault-free would certify nothing."""
    value = os.environ.get(PLAN_ENV, "")
    if not value:
        return None
    return FaultPlan.from_env_value(value)
