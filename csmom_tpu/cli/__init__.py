"""Command-line interface: run / replicate / grid / sweep."""
