"""csmom fleet — render a run's FLEET_<run>.json observatory capture.

The serve/fabric artifacts say what the run *ended* with; this command
answers what the fleet *looked like while it ran*.  Given a committed
fleet artifact (:mod:`csmom_tpu.obs.fleet`), it prints:

- the **kill-window capacity account**: nominal vs available
  worker-seconds, each kill window's width / loss fraction / offered
  demand trapped inside it, and the steady-state loss (≈ 0 is a
  measured result, not an assumption);
- **lifecycle walls**: every (re)spawn's spawn→ready wall with the
  worker-reported bind/warm decomposition — the denominator of the
  kill window;
- the **demand book**: per-class offered/admitted/served totals (which
  reconcile with the serve request ledger by schema) and the peak
  per-second offered rate;
- **occupancy**: queue-depth and in-flight quantiles per worker;
- the **stream books**: every process's series span and CLOSE REASON —
  fin on clean drain, a severed-stream reason for a SIGKILL victim;
  silence is not an option the schema permits.

Evidence-only and clock-free (the clock-discipline lint pins this module
mono-only): rendering a committed artifact must be reproducible from its
bytes alone.  Registered via ``register(sub)`` like trace/timeline — the
cli/main.py split.
"""

from __future__ import annotations

import glob
import json
import os
import sys

from csmom_tpu.chaos import invariants as inv

__all__ = ["cmd_fleet", "register"]


def _locate(run: str, root: str | None) -> str | None:
    if os.path.isfile(run):
        return run
    from csmom_tpu.obs.timeline import sidecar_search_roots

    for r in sidecar_search_roots(root):
        for pat in (f"FLEET_{run}.json", f"FLEET_*{run}*.json"):
            hits = sorted(glob.glob(os.path.join(r, pat)))
            if hits:
                return hits[0]
    return None


def _fmt(v, w=8, p=3) -> str:
    return f"{v:>{w}.{p}f}" if isinstance(v, (int, float)) else f"{'—':>{w}}"


def _print_capacity(obj: dict) -> None:
    for label, cap in (("worker", obj.get("capacity")),
                       ("router", obj.get("router_capacity"))):
        if not isinstance(cap, dict):
            continue
        print(f"\n{label}-tier capacity account "
              f"({cap.get('n_slots')} slot(s), "
              f"{cap.get('window_s')} s window):")
        print(f"  worker-seconds: nominal {cap.get('nominal_worker_s')} "
              f"available {cap.get('available_worker_s')}")
        print(f"  loss fraction: kill-window "
              f"{cap.get('kill_window_loss_frac')}  steady-state "
              f"{cap.get('steady_state_loss_frac')}")
        kws = cap.get("kill_windows") or []
        if not kws:
            print("  kill windows: none")
            continue
        print(f"  {'victim':<10} {'t_kill_s':>9} {'t_ready_s':>9} "
              f"{'width_s':>8} {'loss':>7} {'offered_in_window':>18}")
        for kw in kws:
            tr = (f"{_fmt(kw.get('t_ready_s'), 9)}"
                  if not kw.get("open_ended")
                  else f"{'(never)':>9}")
            print(f"  {str(kw.get('worker_id')):<10} "
                  f"{_fmt(kw.get('t_kill_s'), 9)} {tr} "
                  f"{_fmt(kw.get('width_s'), 8)} "
                  f"{_fmt(kw.get('loss_frac'), 7, 4)} "
                  f"{kw.get('demand_offered_in_window', '—'):>18}")


def _print_lifecycle(obj: dict) -> None:
    events = (obj.get("lifecycle") or {}).get("events") or []
    if not events:
        return
    print("\nlifecycle walls (one row per (re)spawn reaching ready):")
    print(f"  {'worker':<10} {'gen':>4} {'spawn→ready':>12} "
          f"{'main→bind':>10} {'warm':>8}")
    for e in events:
        walls = e.get("walls") or {}
        print(f"  {str(e.get('worker_id')):<10} "
              f"{str(e.get('generation', '—')):>4} "
              f"{_fmt(e.get('wall_s'), 12)} "
              f"{_fmt(walls.get('main_to_bind_s'), 10)} "
              f"{_fmt(walls.get('warm_s'), 8)}")


def _print_demand(obj: dict) -> None:
    demand = obj.get("demand") or {}
    classes = demand.get("classes") or {}
    if not classes:
        print("\ndemand book: (window never opened)")
        return
    window_s = obj.get("window_s") or 0
    print("\ndemand book (client-tier arrivals, reconciles with the "
          "serve request ledger by schema):")
    print(f"  {'class':<12} {'offered':>8} {'admitted':>9} {'served':>8} "
          f"{'rps':>8}")
    for cls, tot in sorted(classes.items()):
        rps = (round(tot.get("offered", 0) / window_s, 2)
               if window_s else None)
        print(f"  {cls:<12} {tot.get('offered', 0):>8} "
              f"{tot.get('admitted', 0):>9} {tot.get('served', 0):>8} "
              f"{_fmt(rps, 8, 2)}")
    per_s = demand.get("per_second") or []
    peak, peak_t = 0, None
    for row in per_s:
        n = sum(ev.get("offered", 0) for k, ev in row.items()
                if k != "t_s" and isinstance(ev, dict))
        if n > peak:
            peak, peak_t = n, row.get("t_s")
    if peak_t is not None:
        print(f"  peak offered: {peak} req/s at t={peak_t} s "
              f"({len(per_s)} one-second buckets)")


def _print_occupancy(obj: dict) -> None:
    occ = obj.get("occupancy") or {}
    if not occ:
        return
    print("\noccupancy (per-process series quantiles over the capture):")
    print(f"  {'process':<14} {'depth p50':>10} {'p95':>7} {'max':>7} "
          f"{'inflight p50':>13} {'p95':>7} {'max':>7}")
    for proc, q in sorted(occ.items()):
        d = q.get("queue_depth") or {}
        f = q.get("in_flight") or {}
        print(f"  {proc:<14} {_fmt(d.get('p50'), 10, 1)} "
              f"{_fmt(d.get('p95'), 7, 1)} {_fmt(d.get('max'), 7, 1)} "
              f"{_fmt(f.get('p50'), 13, 1)} {_fmt(f.get('p95'), 7, 1)} "
              f"{_fmt(f.get('max'), 7, 1)}")


def _print_streams(obj: dict) -> None:
    series = obj.get("series") or {}
    books = series.get("books") or {}
    print(f"\nstream books: {books.get('procs_opened')} process stream(s) "
          f"opened, {books.get('procs_closed')} closed; "
          f"{books.get('frames')} frames ({books.get('frames_malformed')} "
          f"malformed), {books.get('seq_gaps')} seq gap(s), "
          f"{books.get('frames_dropped_by_emitters')} dropped by "
          f"emitters; {books.get('series_count')} series")
    procs = series.get("processes") or {}
    for name, book in sorted(procs.items()):
        span = (f"t {book.get('t_first_s')}–{book.get('t_last_s')} s, "
                f"{book.get('samples')} frame(s), pid {book.get('pid')}")
        print(f"  {name:<14} {span:<44} closed: "
              f"{book.get('close_reason')}")


def cmd_fleet(args) -> int:
    """Render a run's FLEET_<run>.json: kill-window capacity account,
    lifecycle walls, demand book, occupancy, reason-closed stream books."""
    path = _locate(args.run, args.root)
    if path is None:
        print(f"error: no FLEET artifact matches {args.run!r} (looked for "
              "a file path, then FLEET_<run>.json in "
              f"{args.root or '. and the repo root'}).  Capture one with "
              "`csmom loadgen --fabric --fleet` (or --pool --fleet).",
              file=sys.stderr)
        return 2
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: unreadable fleet artifact {path}: {e}",
              file=sys.stderr)
        return 2
    violations = inv.validate(obj, "fleet")
    if args.json:
        json.dump(obj, sys.stdout, indent=1)
        print()
    else:
        print(f"[{os.path.relpath(path)}]")
        extra = obj.get("extra") or {}
        print(f"run {obj.get('run_id')}  platform "
              f"{extra.get('platform')}  cadence {obj.get('cadence_s')} s"
              f"  window {obj.get('window_s')} s  fresh compiles in "
              f"window "
              f"{(obj.get('compile') or {}).get('in_window_fresh_compiles')!r}")
        if extra.get("workload"):
            print(f"workload: {extra['workload']}")
        try:
            _print_capacity(obj)
            _print_lifecycle(obj)
            _print_demand(obj)
            _print_occupancy(obj)
            _print_streams(obj)
        except Exception as e:  # a damaged artifact must still get its
            print(f"(render failed: {type(e).__name__}: {e} — "  # diagnosis
                  "schema report below)")
    if violations:
        print("\nschema violations (the artifact is damaged or "
              "stale-format):", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


def register(sub) -> None:
    """Attach the ``fleet`` subparser (called from cli.main)."""
    sp = sub.add_parser(
        "fleet",
        help="render a run's FLEET_<run>.json observatory capture "
             "(kill-window capacity account, lifecycle walls, demand "
             "book, occupancy, reason-closed stream books)",
    )
    sp.add_argument("run",
                    help="fleet artifact path or run id (resolved as "
                         "FLEET_<run>.json in . and the repo root)")
    sp.add_argument("--root", help="artifact directory (default: cwd, "
                                   "then the repo checkout)")
    sp.add_argument("--json", action="store_true",
                    help="dump the artifact object instead of rendering")
    sp.set_defaults(fn=cmd_fleet)
