"""csmom lint — run the static-analysis sweep (ISSUE 11 + 12).

Runs every registered kind-``lint`` rule over the package, ``bench.py``,
and ``benchmarks/`` in a single parse-per-file pass; ``--project`` adds
the whole-program rules (lock-order, helper-hygiene, compile-surface)
on the alias-aware project call graph.  Exit 0 means the tree is clean
(zero unsuppressed findings; a stale pragma counts as a finding); exit
1 names every defect as ``path:line: [rule] message``.

``--format`` selects the output:

- ``text`` (default) — human-readable findings + a per-rule timing
  footer;
- ``json`` — the machine-readable findings report (schema_version 2:
  project flag, per-finding call chains, cache stats, rule timings) —
  what tier-1 parses and what CI archives.  ``--json`` stays as an
  alias;
- ``github`` — ``::error file=...,line=...`` workflow annotations so CI
  surfaces findings inline on the PR diff.

The incremental cache (``.csmom_lint_cache/``, content-digest keyed)
makes an unchanged-tree re-sweep nearly free; ``--no-cache`` bypasses
it.  The sweep wall time lands on the ``lint.sweep_s`` gauge
(:mod:`csmom_tpu.obs.metrics`) when telemetry is armed.

``csmom rehearse`` refuses to start when this sweep (project scope
included) fails: a dirty tree must not reach a tunnel window.

Registered via ``register(sub)`` like serve/replay/ledger (the
cli/main.py split: new subcommands do not grow the monolith).
"""

from __future__ import annotations

import sys

__all__ = ["cmd_lint", "register"]


def _print_github(report) -> None:
    for f in report.findings:
        # one line per finding; newlines would break the annotation
        msg = f.message.replace("\n", " ")
        print(f"::error file={f.path},line={f.line},"
              f"title=lint:{f.rule}::{msg}")
    print(f"{len(report.findings)} finding(s) over {report.files} "
          f"file(s)")


def cmd_lint(args) -> int:
    """Run the registered static-analysis rules over the tree."""
    from csmom_tpu.analysis import run_lint
    from csmom_tpu.obs import metrics
    from csmom_tpu.registry import lint_rules
    from csmom_tpu.utils.deadline import mono_now_s

    if getattr(args, "rules_list", False):
        specs = lint_rules()
        for spec in specs:
            scope = getattr(spec.rule_cls, "scope", "file")
            print(f"{spec.name}" + ("  [project]"
                                    if scope == "project" else ""))
            print(f"    {spec.description}")
        print(f"\n{len(specs)} rules registered (kind 'lint') — register "
              "one more with register_engine(name=..., kind='lint', "
              "rule_cls=...) and it joins this sweep, tier-1, and the "
              "fixture self-test with no other file edited")
        return 0
    # an explicit --format always wins; --json is only a default-filler
    # alias (``--format github --json`` must not silently emit JSON)
    fmt = (getattr(args, "format", None)
           or ("json" if getattr(args, "json", False) else "text"))
    t0 = mono_now_s()
    try:
        report = run_lint(paths=args.paths or None, rule=args.rule,
                          project=getattr(args, "project", False),
                          cache=not getattr(args, "no_cache", False),
                          timer=mono_now_s)
    except KeyError as e:
        print(str(e).strip('"'), file=sys.stderr)
        return 2
    sweep_s = mono_now_s() - t0
    metrics.gauge("lint.sweep_s").set(round(sweep_s, 6))
    if fmt == "json":
        print(report.to_json())
        return 0 if report.ok else 1
    if fmt == "github":
        _print_github(report)
        return 0 if report.ok else 1
    for f in report.findings:
        print(f)
    cache = report.cache
    cache_txt = (
        f"cache {cache['hits']} hit/{cache['misses']} miss"
        + ("+project" if cache.get("project_hit") else "")
        if cache.get("enabled") else "cache off")
    print(f"{len(report.findings)} finding(s) over {report.files} "
          f"file(s); {len(report.suppressed)} suppressed by pragma "
          f"({len(report.rules)} rules"
          + (", project scope" if report.project else "")
          + f"; {cache_txt}; {sweep_s:.2f}s)")
    if report.rule_timings_s:
        slowest = sorted(report.rule_timings_s.items(),
                         key=lambda kv: -kv[1])
        print("per-rule: " + ", ".join(
            f"{rid} {s * 1000:.0f}ms" for rid, s in slowest))
    if not report.ok:
        print("fix the findings or, for a justified exception, add "
              "`lint: allow" + "[<rule>] <reason>` on (or directly "
              "above) the offending line — unused pragmas fail the "
              "sweep too", file=sys.stderr)
    return 0 if report.ok else 1


def register(sub) -> None:
    """Attach the ``lint`` subparser (called from cli.main)."""
    sp = sub.add_parser(
        "lint",
        help="run the static-analysis sweep: registered AST rules for "
             "clock/tracer/lock/donation/enumeration discipline, plus "
             "whole-program lock-order/helper-hygiene/compile-surface "
             "with --project (tier-1 runs it; rehearse gates on it)",
    )
    sp.add_argument("--format", choices=("text", "json", "github"),
                    help="output format: human text (default), the "
                         "schema_version-2 JSON report, or GitHub "
                         "workflow annotations (::error file=...)")
    sp.add_argument("--json", action="store_true",
                    help="alias for --format json (kept for r16 "
                         "compatibility)")
    sp.add_argument("--project", action="store_true",
                    help="add the whole-program rules (lock-order, "
                         "helper-hygiene, compile-surface) on the "
                         "project call graph")
    sp.add_argument("--no-cache", action="store_true",
                    help="bypass the incremental sweep cache "
                         "(.csmom_lint_cache/)")
    sp.add_argument("--rule", metavar="ID",
                    help="run only this rule id (see --rules)")
    sp.add_argument("--paths", nargs="+", metavar="PATH",
                    help="files or directories to scan (default: the "
                         "package, bench.py, and benchmarks/)")
    sp.add_argument("--rules", dest="rules_list", action="store_true",
                    help="list the registered rules and exit")
    sp.set_defaults(fn=cmd_lint)
