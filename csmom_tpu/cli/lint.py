"""csmom lint — run the static-analysis sweep (ISSUE 11).

Runs every registered kind-``lint`` rule (clock-discipline,
tracer-hygiene, lock-discipline, donation-safety, enumeration-drift —
plus any runtime registration) over the package, ``bench.py``, and
``benchmarks/`` in a single parse-per-file pass.  Exit 0 means the tree
is clean (zero unsuppressed findings; a stale pragma counts as a
finding); exit 1 names every defect as ``path:line: [rule] message``.

``--json`` emits the machine-readable findings report (schema_version
1) — what tier-1 parses and what CI archives.  ``--rule`` runs one rule;
``--paths`` narrows the scan; ``--rules`` lists the registered rule set
with descriptions (the registry is the only rule table).

``csmom rehearse`` refuses to start when this sweep fails: a dirty tree
must not reach a tunnel window.

Registered via ``register(sub)`` like serve/replay/ledger (the
cli/main.py split: new subcommands do not grow the monolith).
"""

from __future__ import annotations

import sys

__all__ = ["cmd_lint", "register"]


def cmd_lint(args) -> int:
    """Run the registered static-analysis rules over the tree."""
    from csmom_tpu.analysis import run_lint
    from csmom_tpu.registry import lint_rules

    if getattr(args, "rules_list", False):
        specs = lint_rules()
        for spec in specs:
            print(f"{spec.name}")
            print(f"    {spec.description}")
        print(f"\n{len(specs)} rules registered (kind 'lint') — register "
              "one more with register_engine(name=..., kind='lint', "
              "rule_cls=...) and it joins this sweep, tier-1, and the "
              "fixture self-test with no other file edited")
        return 0
    try:
        report = run_lint(paths=args.paths or None, rule=args.rule)
    except KeyError as e:
        print(str(e).strip('"'), file=sys.stderr)
        return 2
    if args.json:
        print(report.to_json())
        return 0 if report.ok else 1
    for f in report.findings:
        print(f)
    print(f"{len(report.findings)} finding(s) over {report.files} "
          f"file(s); {len(report.suppressed)} suppressed by pragma "
          f"({len(report.rules)} rules)")
    if not report.ok:
        print("fix the findings or, for a justified exception, add "
              "`lint: allow" + "[<rule>] <reason>` on (or directly "
              "above) the offending line — unused pragmas fail the "
              "sweep too", file=sys.stderr)
    return 0 if report.ok else 1


def register(sub) -> None:
    """Attach the ``lint`` subparser (called from cli.main)."""
    sp = sub.add_parser(
        "lint",
        help="run the static-analysis sweep: registered AST rules for "
             "clock/tracer/lock/donation/enumeration discipline "
             "(tier-1 runs it; rehearse gates on it)",
    )
    sp.add_argument("--json", action="store_true",
                    help="emit the machine-readable findings report "
                         "(schema_version 1) instead of text")
    sp.add_argument("--rule", metavar="ID",
                    help="run only this rule id (see --rules)")
    sp.add_argument("--paths", nargs="+", metavar="PATH",
                    help="files or directories to scan (default: the "
                         "package, bench.py, and benchmarks/)")
    sp.add_argument("--rules", dest="rules_list", action="store_true",
                    help="list the registered rules and exit")
    sp.set_defaults(fn=cmd_lint)
