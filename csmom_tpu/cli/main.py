"""``csmom`` CLI — research, capture, and serving entry points.

The subcommand table is GENERATED from the live registry into the
``--help`` epilog (see :func:`_registry_epilog`): a hand-written list
here drifted once (it named 6 of what were by then 16 subcommands), so
no prose enumeration of subcommands is maintained anywhere anymore.

The reference has no CLI at all — its driver hardcodes every parameter
(``/root/reference/run_demo.py:193-207``).  Each subcommand here covers one
stage of that driver with the constants exposed as flags, defaults equal to
the reference's values (see ``csmom_tpu.config``), and the same artifacts
written to ``--out`` (monthly_mom_cum.png / intraday_cum_pnl.png /
trades.csv — identical names and schemas).

``--config file.toml`` loads a :class:`~csmom_tpu.config.RunConfig`; flags
given on the command line override the file.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

from csmom_tpu.config import RunConfig, load_config
from csmom_tpu.utils.logging import get_logger

log = get_logger("cli")


def _parse_tickers(s: str) -> tuple:
    """One comma-list parser for every --tickers flag (fetch included)."""
    return tuple(t.strip().upper() for t in s.split(",") if t.strip())


def _load_cfg(args) -> RunConfig:
    cfg = load_config(args.config) if args.config else RunConfig()
    if getattr(args, "backend", None):
        cfg = dataclasses.replace(cfg, backend=args.backend)
    if getattr(args, "out", None):
        cfg = dataclasses.replace(cfg, results_dir=args.out)
    if getattr(args, "data_dir", None):
        cfg = dataclasses.replace(
            cfg, universe=dataclasses.replace(cfg.universe, data_dir=args.data_dir)
        )
    if getattr(args, "tickers", None) and args.command != "fetch":
        cfg = dataclasses.replace(
            cfg,
            universe=dataclasses.replace(cfg.universe,
                                         tickers=_parse_tickers(args.tickers)),
            explicit_universe=True,
        )
    mom = cfg.momentum
    explicit = set(cfg.explicit_momentum)  # config-file keys (load_config)
    for field in ("lookback", "skip", "n_bins", "mode"):
        v = getattr(args, field, None)
        if v is not None:
            mom = dataclasses.replace(mom, **{field: v})
            explicit.add(field)
    return dataclasses.replace(cfg, momentum=mom,
                               explicit_momentum=tuple(sorted(explicit)))


def _price_panel(cfg: RunConfig):
    from csmom_tpu.api import monthly_price_panel
    from csmom_tpu.panel.pack import is_packed

    tickers = list(cfg.universe.tickers)
    if not cfg.explicit_universe and is_packed(cfg.universe.data_dir):
        # a packed --data-dir with no user-chosen universe means "run the
        # whole pack" — the built-in 20-name demo list is a CSV-era default
        tickers = None
    return monthly_price_panel(cfg.universe.data_dir, tickers)


def _load_sector_map(path: str, tickers):
    """``ticker,sector`` CSV -> (ids i32[A], n_sectors) aligned to the panel.

    Sector names factorize in sorted order; panel tickers absent from the
    file get id -1 (excluded from sector-neutral ranking, like masked
    lanes) with a warning naming them.
    """
    import numpy as np
    import pandas as pd

    df = pd.read_csv(path)
    df.columns = [c.strip().lower() for c in df.columns]
    if not {"ticker", "sector"} <= set(df.columns):
        raise SystemExit(
            f"--sector-map {path}: need columns ticker,sector "
            f"(got {list(df.columns)})"
        )
    mapping = dict(zip(df["ticker"].astype(str).str.strip().str.upper(),
                       df["sector"].astype(str).str.strip()))
    names = sorted(set(mapping.values()))
    code = {s: i for i, s in enumerate(names)}
    ids = np.full(len(tickers), -1, np.int32)
    missing = []
    for i, t in enumerate(tickers):
        s = mapping.get(str(t).upper())
        if s is None:
            missing.append(str(t))
        else:
            ids[i] = code[s]
    if missing:
        log.warning("sector map has no entry for %s — excluded from ranking",
                    ",".join(missing))
    if (ids >= 0).sum() == 0:
        raise SystemExit(
            f"--sector-map {path}: no entry matches any panel ticker — "
            "check the ticker naming convention"
        )
    return ids, len(names)


def _parse_strategy(args, cfg):
    """``--strategy name [--strategy-arg k=v ...]`` -> Strategy | None.

    Momentum params flow through only when explicitly set: a ``lookback``/
    ``skip`` the user gave (CLI flag or config file) overrides a strategy
    field of the same name — so ``--lookback 6 --strategy momentum`` really
    runs J=6 — but built-in defaults leave each strategy's own defaults
    alone.  The resolved instance is printed so the parametrization is
    always visible.
    """
    name = getattr(args, "strategy", None)
    if not name:
        return None
    import ast
    import dataclasses

    from csmom_tpu.strategy import available_strategies, make_strategy

    params = {}
    for kv in getattr(args, "strategy_arg", None) or []:
        k, _, v = kv.partition("=")
        try:
            params[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            params[k] = v
    cls = available_strategies().get(name)
    if cls is not None:
        field_names = {f.name for f in dataclasses.fields(cls)}
        # only user-set momentum keys flow through (cfg.explicit_momentum:
        # config-file keys + CLI flags, recorded by load_config/_load_cfg) —
        # built-in MomentumConfig defaults must not override a strategy's
        # own defaults (ADVICE r1 #1)
        for fld in set(cfg.explicit_momentum) & {"lookback", "skip"}:
            if fld in field_names and fld not in params:
                params[fld] = getattr(cfg.momentum, fld)
    strat = make_strategy(name, **params)
    print(f"strategy: {strat}")
    return strat


def cmd_replicate(args) -> int:
    """Monthly momentum replication (the reference's ``monthly_replication``,
    ``run_demo.py:31-79``) on either backend; ``--strategy`` swaps the
    ranked signal without touching the engine."""
    cfg = _load_cfg(args)
    prices, volume = _price_panel(cfg)

    from csmom_tpu.backends import run_monthly

    strategy = _parse_strategy(args, cfg)
    panels = {}
    if strategy is not None:
        from csmom_tpu.strategy import consumed_panels

        # offer the volume panels, but only forward what this strategy's
        # signal actually reads (the engine rejects unmatched panel kwargs)
        offered = {"volumes": volume.values, "volumes_mask": volume.mask}
        allowed = consumed_panels(strategy)
        panels = {k: v for k, v in offered.items() if k in allowed}
    sector_kw = {}
    if getattr(args, "sector_map", None):
        if cfg.backend != "tpu":
            print("--sector-map needs the TPU engine (drop "
                  "--backend pandas); any --strategy plugin works",
                  file=sys.stderr)
            return 2
        ids, n_sectors = _load_sector_map(args.sector_map, prices.tickers)
        sector_kw = {"sector_ids": ids, "n_sectors": n_sectors}
        print(f"sector-neutral ranking: {n_sectors} sectors"
              + (f" (signal: {args.strategy})" if strategy is not None else ""))
    # --band/--band-sweep: validate BEFORE the plain run so misuse really
    # does fail fast; validity rule lives once in banded.validate_band.
    # The band applies to WHATEVER labels the plain run produces — built-in
    # momentum, any --strategy plugin, sector-neutral ranks, either backend
    # (banded_from_labels needs only labels + monthly returns).
    band_sweep = band_select = None
    want_band = getattr(args, "band", None) is not None
    if (want_band or getattr(args, "band_sweep", None)
            or getattr(args, "band_select", None)):
        from csmom_tpu.backtest.banded import validate_band

        def _parse_widths(spec, flag):
            try:
                widths = [int(s) for s in spec.split(",") if s.strip()]
            except ValueError:
                print(f"{flag} {spec!r}: widths must be plain integers, "
                      f"e.g. {flag} 0,1,2", file=sys.stderr)
                return None
            if not widths:
                print(f"{flag}: empty width list", file=sys.stderr)
                return None
            return widths

        if getattr(args, "band_sweep", None):
            band_sweep = _parse_widths(args.band_sweep, "--band-sweep")
            if band_sweep is None:
                return 2
        if getattr(args, "band_select", None):
            band_select = _parse_widths(args.band_select, "--band-select")
            if band_select is None:
                return 2
            if len(band_select) < 2:
                print("--band-select: need at least two widths to select "
                      "among", file=sys.stderr)
                return 2
        # validate each flag's widths under its OWN name, so the error
        # points at the flag whose value is actually invalid
        for flag, widths in (
            ("--band", [args.band] if want_band else []),
            ("--band-sweep", band_sweep or []),
            ("--band-select", band_select or []),
        ):
            try:
                for b in widths:
                    validate_band(b, cfg.momentum.n_bins)
            except ValueError as e:
                print(f"{flag}: invalid widths — {e} (stay-zones must not "
                      "overlap)", file=sys.stderr)
                return 2
    if getattr(args, "vol_target", None) is not None and args.vol_target <= 0:
        # validate BEFORE the plain run, like --band
        print(f"--vol-target {args.vol_target:g}: the annualized vol "
              "target must be positive (percent, e.g. 12)", file=sys.stderr)
        return 2
    rep = run_monthly(
        prices,
        lookback=cfg.momentum.lookback,
        skip=cfg.momentum.skip,
        n_bins=cfg.momentum.n_bins,
        mode=cfg.momentum.mode,
        backend=cfg.backend,
        strategy=strategy,
        **sector_kw,
        **panels,
    )
    # name the universe the numbers were computed on: this ingest reads the
    # dialect-B caches the reference's own loader drops (SURVEY §2.1.1), so
    # on the reference data a fresh run is 20 tickers (mean ~0.001935) while
    # BASELINE.md's measured 0.003674 is the reference's effective
    # 19-ticker panel — a universe difference, not drift
    from csmom_tpu.panel.pack import is_packed

    src = ("packed panel" if is_packed(cfg.universe.data_dir)
           else "all readable caches included — the reference's own loader "
                "drops dialect-B files")
    print(f"Universe: {prices.n_assets} tickers x {prices.n_times} dates "
          f"({prices.tickers[0]}..{prices.tickers[-1]}; {src})")
    print(f"Mean monthly spread: {rep.mean_spread:.6f}")
    print(f"Annualized Sharpe:   {rep.ann_sharpe:.4f}")
    print(f"t-stat (NW):         {rep.tstat_nw:.3f}")
    print(f"t-stat (iid):        {rep.tstat:.3f}")
    plot_overlays = {}  # extra cum-growth lines (banded / vol-managed)

    if getattr(args, "tc_bps", None) is not None:
        import jax.numpy as jnp
        import numpy as np

        from csmom_tpu.analytics.stats import masked_mean, nw_t_stat, sharpe
        from csmom_tpu.backtest.monthly import net_of_costs_arrays

        # ONE unit-cost netting prices every level (the cost model is
        # linear in the half-spread) — same pattern as cmd_grid: the unit
        # run feeds the requested net level AND the break-even
        valid = np.isfinite(rep.spread)
        spread0 = jnp.nan_to_num(jnp.asarray(rep.spread))
        net1, _, _ = net_of_costs_arrays(
            rep.labels, rep.decile_counts, spread0, jnp.asarray(valid),
            half_spread=1.0, n_bins=cfg.momentum.n_bins,
        )
        cost1 = spread0 - net1                 # per-month unit turnover cost
        hs = args.tc_bps / 1e4
        net = spread0 - hs * cost1
        vj = jnp.asarray(valid)
        net_mean = masked_mean(net, vj)
        net_sharpe = sharpe(net, vj, freq_per_year=12)
        net_t = nw_t_stat(net, vj)
        print(f"net of {args.tc_bps:g} bps half-spread turnover costs: "
              f"mean {float(net_mean):+.6f}, Sharpe {float(net_sharpe):.4f}, "
              f"NW t {float(net_t):+.3f}")
        cost1 = np.asarray(cost1)
        mean_turn = float(cost1[valid].mean()) if valid.any() else float("nan")
        if mean_turn > 0:
            be = float(rep.mean_spread) / mean_turn * 1e4
            print(f"break-even half-spread: {be:+.1f} bps "
                  f"(mean monthly turnover {mean_turn:.3f})")

    if want_band or band_sweep is not None or band_select is not None:
        # shared setup for the banded surfaces: formation already ran, so
        # reuse rep.labels — WHATEVER produced them (built-in momentum, a
        # --strategy plugin, sector-neutral ranks, either backend); only
        # the band recursion + portfolio tail compile below, and the
        # device transfer happens once
        import jax.numpy as jnp
        import numpy as np

        from csmom_tpu.backtest.banded import banded_from_labels
        from csmom_tpu.signals.momentum import monthly_returns

        v, m = prices.device()
        mret, mret_valid = monthly_returns(v, m)
        lab = jnp.asarray(rep.labels)

    if want_band:
        bres = banded_from_labels(
            lab, mret, mret_valid,
            n_bins=cfg.momentum.n_bins, band=args.band,
        )
        plot_overlays[f"band {args.band}"] = np.asarray(bres.spread)
        bt = np.asarray(bres.turnover)
        bv = np.asarray(bres.spread_valid)
        pvalid = np.isfinite(np.asarray(rep.spread))
        if getattr(args, "tc_bps", None) is not None:
            # cost1 from the --tc-bps block IS the plain unit-turnover
            # series; don't recompute it
            plain_turn = mean_turn if mean_turn > 0 else None
        else:
            from csmom_tpu.costs.impact import long_short_weights, turnover_cost

            w_plain = long_short_weights(
                lab, jnp.asarray(rep.decile_counts),
                cfg.momentum.n_bins,
            )
            pt = np.asarray(turnover_cost(w_plain, half_spread=1.0))
            plain_turn = float(pt[pvalid].mean()) if pvalid.any() else None
        print(f"\nhysteresis band {args.band} (enter at extreme decile, "
              f"stay within {args.band}):")
        print(f"  gross mean {float(bres.mean_spread):+.6f}, Sharpe "
              f"{float(bres.ann_sharpe):.4f}, NW t {float(bres.tstat_nw):+.3f}")
        if getattr(args, "bootstrap", None):
            import jax as _jax

            from csmom_tpu.analytics import block_bootstrap

            bbs = block_bootstrap(
                np.asarray(bres.spread), bv, _jax.random.PRNGKey(0),
                n_samples=args.bootstrap,
                block_len=getattr(args, "block_len", None) or 6,
            )
            blo, bhi = np.asarray(bbs.mean_ci)
            print(f"  95% CI mean: [{blo:.6f}, {bhi:.6f}] "
                  f"({args.bootstrap} block-bootstrap resamples)")
        b_turn = float(bt[bv].mean()) if bv.any() else float("nan")
        msg = f"  mean monthly turnover {b_turn:.3f}"
        if plain_turn is not None and plain_turn > 0:
            msg += (f" vs plain {plain_turn:.3f} "
                    f"({(1 - b_turn / plain_turn) * 100:.0f}% less trading)")
        print(msg)
        if getattr(args, "tc_bps", None) is not None:
            hs = args.tc_bps / 1e4
            bnet = np.where(bv, np.asarray(bres.spread) - hs * bt, np.nan)
            bmean = float(np.nanmean(bnet)) if bv.any() else float("nan")
            print(f"  net of {args.tc_bps:g} bps: mean {bmean:+.6f}")
            if b_turn > 0:
                print(f"  break-even half-spread: "
                      f"{float(bres.mean_spread) / b_turn * 1e4:+.1f} bps")

    if band_sweep is not None:
        hs_bps = getattr(args, "tc_bps", None)
        hdr = f"{'band':>4}  {'gross/mo':>9}  {'turnover':>8}  {'b/e bps':>8}"
        if hs_bps is not None:
            hdr += f"  {'net@' + format(hs_bps, 'g') + 'bps':>12}"
        print("\nhysteresis band sweep (formation ranked once):")
        print(hdr)
        for b in band_sweep:
            r = banded_from_labels(lab, mret, mret_valid,
                                   n_bins=cfg.momentum.n_bins, band=b)
            rv = np.asarray(r.spread_valid)
            turn = np.asarray(r.turnover)
            mt = float(turn[rv].mean()) if rv.any() else float("nan")
            be = (float(r.mean_spread) / mt * 1e4 if mt > 0
                  else float("nan"))
            row = (f"{b:>4}  {float(r.mean_spread):>+9.6f}  {mt:>8.3f}  "
                   f"{be:>+8.1f}")
            if hs_bps is not None:
                net = np.where(rv, np.asarray(r.spread)
                               - hs_bps / 1e4 * turn, np.nan)
                nm = float(np.nanmean(net)) if rv.any() else float("nan")
                row += f"  {nm:>+12.6f}"
            print(row)

    if band_select is not None:
        from csmom_tpu.backtest import walk_forward_select

        hs = (getattr(args, "tc_bps", None) or 0.0) / 1e4
        series, valids = [], []
        for b in band_select:
            r = banded_from_labels(lab, mret, mret_valid,
                                   n_bins=cfg.momentum.n_bins, band=b)
            rv = np.asarray(r.spread_valid)
            net = np.asarray(r.spread) - hs * np.asarray(r.turnover)
            series.append(np.where(rv, net, 0.0))
            valids.append(rv)
        wf = walk_forward_select(np.stack(series), np.stack(valids),
                                 min_months=24)
        basis = (f"net of {args.tc_bps:g} bps" if hs else "gross")
        ov = np.asarray(wf.oos_valid)
        choice = np.asarray(wf.choice)
        print(f"\nwalk-forward band selection over {band_select} "
              f"({basis}; expanding Sharpe, 24-month warmup):")
        print(f"  OOS months {int(ov.sum())}, mean "
              f"{float(wf.mean_spread):+.6f}, Sharpe "
              f"{float(wf.ann_sharpe):.4f}, NW t {float(wf.tstat_nw):+.3f}")
        picks = ", ".join(
            f"band {b} x{int(((choice == i) & ov).sum())}"
            for i, b in enumerate(band_select)
            if ((choice == i) & ov).any()
        )
        print(f"  selections: {picks or 'none'}")

    if getattr(args, "vol_target", None) is not None:
        import numpy as np

        from csmom_tpu.analytics import vol_managed
        from csmom_tpu.analytics.stats import nw_t_stat, sharpe

        tgt = args.vol_target / 100.0
        _VM_WINDOW, _VM_CAP = 6, 2.0
        sp_arr = np.asarray(rep.spread, dtype=float)
        sv = np.isfinite(sp_arr)
        managed, mok, scale = vol_managed(
            np.nan_to_num(sp_arr), sv, window=_VM_WINDOW,
            target_ann_vol=tgt, max_leverage=_VM_CAP,
        )
        mok_np = np.asarray(mok)
        if not mok_np.any():
            print(f"vol target {args.vol_target:g}%: no months with a full "
                  "6-month prior vol window — series too short",
                  file=sys.stderr)
        else:
            m = np.asarray(managed)
            mmean = float(np.nanmean(m[mok_np]))
            msharpe = float(sharpe(np.nan_to_num(m), mok, freq_per_year=12))
            mt = float(nw_t_stat(np.nan_to_num(m), mok))
            raw_vol = float(np.std(sp_arr[sv], ddof=1) * np.sqrt(12))
            man_vol = float(np.std(m[mok_np], ddof=1) * np.sqrt(12))
            sc = np.asarray(scale)[mok_np]
            print(f"\nvol-managed overlay (BSC 2015, target "
                  f"{args.vol_target:g}% ann, {_VM_WINDOW}m trailing, "
                  f"{_VM_CAP:g}x cap):")
            print(f"  mean {mmean:+.6f}, Sharpe {msharpe:.4f}, NW t {mt:+.3f}"
                  f"  ({int(mok_np.sum())} of {int(sv.sum())} live months)")
            print(f"  realized ann vol: raw {raw_vol * 100:.1f}% -> managed "
                  f"{man_vol * 100:.1f}%; scale range "
                  f"[{sc.min():.2f}, {sc.max():.2f}]")
            plot_overlays[f"vol-managed {args.vol_target:g}%"] = np.where(
                mok_np, m, np.nan
            )

    if getattr(args, "tables", False):
        from csmom_tpu.analytics.tables import decile_table

        print("\nPer-decile performance (R1 = losers):")
        print(decile_table(rep.decile_means, rep.decile_counts,
                           rep.spread).round(4).to_string())

    if getattr(args, "tearsheet", False):
        import numpy as np
        import pandas as pd

        from csmom_tpu.analytics import annual_returns, format_tearsheet, tearsheet

        spread = np.asarray(rep.spread)
        valid = np.isfinite(spread)
        print()
        print(format_tearsheet(
            tearsheet(np.nan_to_num(spread), valid, freq_per_year=12),
            label=f"monthly spread ({cfg.backend})",
        ))
        years = pd.DatetimeIndex(rep.times).year.values.astype(np.int32)
        uniq, ann, any_valid = annual_returns(
            np.nan_to_num(spread), valid, years
        )
        live = np.asarray(any_valid)
        print("\nPer-year compounded spread:")
        for yy, aa in zip(np.asarray(uniq)[live], np.asarray(ann)[live]):
            print(f"  {int(yy)}  {aa * 100:+.2f}%")

        from csmom_tpu.analytics import rolling_sharpe

        W = 36
        rs, rs_ok = rolling_sharpe(np.nan_to_num(spread), valid, W,
                                   freq_per_year=12)
        rs, rs_ok = np.asarray(rs), np.asarray(rs_ok)
        if rs_ok.any():  # stability view: one full-sample Sharpe hides regimes
            print(f"Rolling {W}m Sharpe: last {rs[rs_ok][-1]:+.2f}, "
                  f"min {np.nanmin(rs[rs_ok]):+.2f}, "
                  f"max {np.nanmax(rs[rs_ok]):+.2f} "
                  f"({int(rs_ok.sum())} windows)")

    if getattr(args, "bootstrap", None):
        import jax
        import numpy as np

        from csmom_tpu.analytics import block_bootstrap

        bs = block_bootstrap(
            rep.spread, np.isfinite(rep.spread), jax.random.PRNGKey(0),
            n_samples=args.bootstrap, block_len=args.block_len or 6,
        )
        mlo, mhi = np.asarray(bs.mean_ci)
        slo, shi = np.asarray(bs.sharpe_ci)
        print(f"95% CI mean:         [{mlo:.6f}, {mhi:.6f}]  "
              f"({args.bootstrap} block-bootstrap resamples)")
        print(f"95% CI Sharpe:       [{slo:.4f}, {shi:.4f}]")

    from csmom_tpu.analytics.plots import save_monthly_cum_plot

    out = save_monthly_cum_plot(
        prices.times, rep.spread, cfg.results_dir,
        overlays=plot_overlays or None,
    )
    log.info("wrote %s", out)
    return 0


def cmd_grid(args) -> int:
    """Full J x K grid in one compiled call; prints the mean/Sharpe tables."""
    import numpy as np

    cfg = _load_cfg(args)
    Js = [int(j) for j in args.js.split(",")] if args.js else list(cfg.grid.Js)
    Ks = [int(k) for k in args.ks.split(",")] if args.ks else list(cfg.grid.Ks)
    # fail fast on flag problems BEFORE the compiled backtest runs: a
    # silently-dropped sweep after minutes of compute is the worst outcome
    tc_levels = None
    if getattr(args, "tc_sweep", None):
        if getattr(args, "tc_bps", None) is None:
            print("--tc-sweep needs --tc-bps (it re-prices the unit-cost "
                  "run that --tc-bps triggers); add e.g. --tc-bps 5",
                  file=sys.stderr)
            return 2
        try:
            tc_levels = [float(s) for s in args.tc_sweep.split(",") if s.strip()]
        except ValueError:
            print(f"--tc-sweep {args.tc_sweep!r}: levels must be plain "
                  "numbers in bps, e.g. --tc-sweep 0,5,25", file=sys.stderr)
            return 2
    prices, _ = _price_panel(cfg)

    v, m = prices.device()
    n_shards = getattr(args, "shards", None) or 0
    mode = getattr(args, "mode", None) or cfg.momentum.mode
    if n_shards > 1 and mode == "hist":
        # sharded 'hist' would all_gather and then re-run the full-panel
        # histogram kernel redundantly on every shard — strictly worse than
        # the gather+sort baseline at exactly the sizes hist targets.  The
        # labels are identical to rank by construction, so substitute it.
        print("--mode hist under --shards: labels are identical to rank; "
              "using the distributed rank path (rank_hist is the "
              "comm-efficient large-A form)", file=sys.stderr)
        mode = "rank"
    if n_shards > 1 or mode == "rank_hist":
        # distributed grid over an asset-sharded mesh; the only mode that
        # REQUIRES it is rank_hist (the O(A)-free radix-histogram rank has
        # no single-device form — its point is the collective pattern)
        import jax

        from csmom_tpu.parallel import auto_mesh, sharded_jk_grid_backtest
        from csmom_tpu.parallel.mesh import pad_assets

        n_shards = max(n_shards, 2)
        n_dev = len(jax.devices())
        if n_shards > n_dev:
            print(
                f"--shards {n_shards} exceeds the {n_dev} visible device(s); "
                "on CPU set XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{n_shards} before launch", file=sys.stderr,
            )
            return 2
        mesh = auto_mesh(n_shards)
        pv, mv, _ = pad_assets(np.asarray(v), np.asarray(m), n_shards)
        res = sharded_jk_grid_backtest(
            pv, mv, np.asarray(Js), np.asarray(Ks), mesh,
            skip=cfg.momentum.skip, n_bins=cfg.momentum.n_bins, mode=mode,
            impl=getattr(args, "impl", None) or "xla",
        )
    else:
        from csmom_tpu.backtest import jk_grid_backtest

        res = jk_grid_backtest(
            v, m, np.asarray(Js), np.asarray(Ks),
            skip=cfg.momentum.skip, n_bins=cfg.momentum.n_bins, mode=mode,
            impl=getattr(args, "impl", None) or "xla",
        )

    from csmom_tpu.analytics.tables import jk_grid_table

    if getattr(args, "tc_bps", None) is not None and mode == "rank_hist":
        print("--tc-bps" + ("/--tc-sweep" if tc_levels else "") + ": cost "
              "netting recomputes labels single-device and has no rank_hist "
              "form; rerun with --mode rank", file=sys.stderr)
    elif getattr(args, "tc_bps", None) is not None:
        import pandas as pd

        from csmom_tpu.backtest.grid import grid_net_of_costs, grid_net_from_unit

        # ONE book computation prices every cost level (linear model): the
        # unit-cost run feeds both the requested net level and break-evens
        unit = grid_net_of_costs(np.asarray(v), np.asarray(m), res,
                                 half_spread=1.0)
        net = grid_net_from_unit(res, unit, half_spread=args.tc_bps / 1e4)

        def _net_table(field):
            return pd.DataFrame(np.asarray(field),
                                index=pd.Index(Js, name="J"),
                                columns=pd.Index(Ks, name="K"))

        print(f"\nNET of {args.tc_bps:g} bps half-spread turnover costs "
              "(exact overlapping-book turnover):")
        for name, field in (("mean monthly spread", net.mean_spread),
                            ("Newey-West t-stat (lag=K)", net.tstat_nw),
                            ("annualized Sharpe", net.ann_sharpe)):
            print(f"\n{name}, net:")
            print(_net_table(field).round(4).to_string())

        from csmom_tpu.backtest.grid import grid_break_even_bps

        be, mean_turn = grid_break_even_bps(np.asarray(v), np.asarray(m),
                                            res, unit=unit)
        print("\nbreak-even half-spread (bps) — cost level where the cell's "
              "mean spread nets to zero:")
        print(_net_table(be).round(1).to_string())
        print("\nmean monthly turnover (L1 weight change):")
        print(_net_table(mean_turn).round(3).to_string())

        if tc_levels:
            print("\ncost sweep — net mean monthly spread by half-spread "
                  "level (all re-priced from the single unit-cost run):")
            rows = {}
            for bps in tc_levels:
                n_l = grid_net_from_unit(res, unit, half_spread=bps / 1e4)
                rows[f"{bps:g}bps"] = np.asarray(n_l.mean_spread).ravel()
            idx = pd.MultiIndex.from_product([Js, Ks], names=["J", "K"])
            print(pd.DataFrame(rows, index=idx).round(4).to_string())

    mean_df, tstat_df, sharpe_df = jk_grid_table(res.spreads, res.spread_valid, Js, Ks)
    for name, df in (("mean monthly spread", mean_df),
                     ("Newey-West t-stat (lag=K)", tstat_df),
                     ("annualized Sharpe", sharpe_df)):
        print(f"\n{name}:")
        print(df.round(4).to_string())

    if getattr(args, "tearsheet", False):
        import pandas as pd

        _print_cell_tearsheets(
            res.spreads, res.spread_valid,
            pd.Index(Js, name="J"), pd.Index(Ks, name="K"),
        )

    n_boot = args.bootstrap if getattr(args, "bootstrap", None) is not None else 200
    if n_boot > 0:  # default inference: per-cell block-bootstrap mean CIs
        from csmom_tpu.analytics.tables import jk_grid_ci_table

        lo_df, hi_df = jk_grid_ci_table(
            res.spreads, res.spread_valid, Js, Ks,
            n_samples=n_boot, block_len=getattr(args, "block_len", None) or 6,
        )
        for name, df in (("95% CI mean spread, lower", lo_df),
                         ("95% CI mean spread, upper", hi_df)):
            print(f"\n{name} ({n_boot} block-bootstrap resamples):")
            print(df.round(4).to_string())
    return 0


def _build_turnover(args, cfg, prices, volume):
    """Shared turnover-panel construction for the volume-conditioned
    commands (doublesort, horizons --by-volume): shares outstanding when
    fetched, trailing-average-volume proxy otherwise.

    Returns ``(turn, turn_valid, turn_lb)``.
    """
    import numpy as np

    from csmom_tpu.panel.fetch import get_shares_info
    from csmom_tpu.signals.turnover import (
        shares_outstanding_vector,
        turnover_features,
    )

    fetch = getattr(args, "fetch_shares", False)
    shares_info = get_shares_info(list(prices.tickers)) if fetch else {}
    pv = np.asarray(prices.values)
    # each asset's last *finite* price (not the final column, which is NaN
    # for names that stopped trading) keeps the market_cap/price fallback
    # usable for every asset
    finite = np.isfinite(pv)
    last_idx = pv.shape[1] - 1 - np.argmax(finite[:, ::-1], axis=1)
    last_price = np.where(
        finite.any(axis=1), pv[np.arange(pv.shape[0]), last_idx], np.nan
    )
    shares = np.asarray(shares_outstanding_vector(prices.tickers, shares_info,
                                                  last_price))
    known = np.isfinite(shares)
    if not known.any():
        # offline runs have no shares metadata (get_shares_info is a network
        # fetch); trailing share volume is the standard proxy — within a
        # cross-section it sorts identically to turnover whenever float
        # counts are comparable
        print("note: no shares-outstanding metadata (run with --fetch-shares "
              "for true turnover); sorting on trailing average volume instead")
        shares = np.ones(len(prices.tickers))
    elif not known.all():
        missing = [t for t, k in zip(prices.tickers, known) if not k]
        print(f"note: no shares metadata for {len(missing)} ticker(s) "
              f"({', '.join(missing[:5])}{'...' if len(missing) > 5 else ''}) — "
              "they are excluded from the volume terciles")
    turn_lb = (getattr(args, "turnover_lookback", None)
               or cfg.momentum.turnover_lookback)
    turn, turn_valid = turnover_features(
        np.asarray(volume.values), np.asarray(volume.mask), shares,
        lookback=turn_lb,
    )["turn_avg"]
    return turn, turn_valid, turn_lb


def cmd_doublesort(args) -> int:
    """Momentum spread within volume terciles (Lee-Swaminathan Table II;
    the turnover leg the reference computes but never ranks on,
    ``features.py:60-107`` / SURVEY item 6)."""
    import numpy as np

    cfg = _load_cfg(args)
    prices, volume = _price_panel(cfg)

    from csmom_tpu.analytics.tables import double_sort_table
    from csmom_tpu.backtest import volume_double_sort

    turn, turn_valid, turn_lb = _build_turnover(args, cfg, prices, volume)
    pv = np.asarray(prices.values)
    res = volume_double_sort(
        pv, np.asarray(prices.mask),
        np.asarray(turn), np.asarray(turn_valid),
        lookback=cfg.momentum.lookback, skip=cfg.momentum.skip,
        n_bins=cfg.momentum.n_bins, mode=cfg.momentum.mode,
    )
    print("Momentum spread by volume tercile "
          f"(J={cfg.momentum.lookback}, skip={cfg.momentum.skip}, "
          f"turnover avg over {turn_lb} months):")
    hs_bps = getattr(args, "tc_bps", None)
    print(double_sort_table(res, half_spread_bps=hs_bps).round(4).to_string())
    if hs_bps is not None:
        print(f"(net_mean at {hs_bps:g} bps half-spread; be_bps = the cost "
              "level that consumes each tercile's gross mean)")
    return 0


def cmd_sweep(args) -> int:
    """Walk-forward (J, K) selection: out-of-sample series from the grid.

    ``--tc-bps`` makes the whole exercise net-of-costs: the expanding
    window selects cells on NET past performance and the OOS series is
    net too — the honest form of the sweep (a gross selector happily
    picks high-turnover cells whose edge a realistic spread erases).
    """
    import numpy as np

    cfg = _load_cfg(args)
    Js = [int(j) for j in args.js.split(",")] if args.js else list(cfg.grid.Js)
    Ks = [int(k) for k in args.ks.split(",")] if args.ks else list(cfg.grid.Ks)
    prices, _ = _price_panel(cfg)

    from csmom_tpu.backtest import jk_grid_backtest, walk_forward_select

    grid = jk_grid_backtest(
        np.asarray(prices.values), np.asarray(prices.mask),
        np.asarray(Js), np.asarray(Ks),
        skip=cfg.momentum.skip, n_bins=cfg.momentum.n_bins,
        mode=cfg.momentum.mode,
    )
    label = "gross"
    if getattr(args, "tc_bps", None) is not None:
        from csmom_tpu.backtest.grid import grid_net_of_costs

        grid = grid_net_of_costs(
            np.asarray(prices.values), np.asarray(prices.mask), grid,
            half_spread=args.tc_bps / 1e4,
        )
        label = f"net of {args.tc_bps:g} bps"
    wf = walk_forward_select(
        grid.spreads, grid.spread_valid,
        min_months=args.min_months or cfg.grid.walk_forward_min_months,
    )
    top, _n_live = _most_picked(wf.choice, Js, Ks, "J", "K")
    print(f"Selection basis:   {label}")
    print(f"OOS months:        {int(np.asarray(wf.oos_valid).sum())}")
    print(f"OOS mean spread:   {float(wf.mean_spread):.6f}")
    print(f"OOS ann. Sharpe:   {float(wf.ann_sharpe):.4f}")
    if top:
        print("Most-selected cells:", ", ".join(f"J={j}/K={k} x{n}" for (j, k), n in top))
    return 0


def cmd_intraday(args) -> int:
    """Intraday pipeline + event backtest (``run_demo.py:81-191``): features,
    score-model CV (--model ridge|online_ridge|elastic_net|lasso|mlp),
    per-minute fills;
    writes trades.csv + intraday_cum_pnl.png."""
    import numpy as np

    cfg = _load_cfg(args)
    from csmom_tpu.api import intraday_pipeline
    from csmom_tpu.panel.ingest import load_daily, load_intraday
    from csmom_tpu.panel.pack import is_packed

    if is_packed(cfg.universe.data_dir):
        print("error: --data-dir is a packed panel, which holds daily "
              "panels only; the intraday pipeline needs the minute CSV "
              "caches — point --data-dir at the CSV cache directory",
              file=sys.stderr)
        return 2
    tickers = list(cfg.universe.tickers)
    minute_df = load_intraday(cfg.universe.data_dir, tickers)
    daily_tickers = tickers
    if getattr(args, "parity", False):
        # reproduce the reference's EFFECTIVE daily universe: its loader
        # loses dialect-B caches (SURVEY §2.1.1), so those tickers fall
        # back to default ADV/vol in its risk maps — match that exactly,
        # or fills diverge on the affected names (observed: AAPL)
        from csmom_tpu.panel.ingest import reference_readable_daily

        daily_tickers = reference_readable_daily(cfg.universe.data_dir, tickers)
        lost = sorted(set(tickers) - set(daily_tickers))
        print(f"parity mode: daily risk-map universe drops {len(lost)} "
              f"caches the reference's loader cannot read (dialect-B "
              f"headers or fetch-cache marker lines): "
              f"{','.join(lost) or 'none'}")
    daily_df = load_daily(cfg.universe.data_dir, daily_tickers)
    lat = getattr(args, "latency_bars", None) or 0
    if lat < 0:
        print("--latency-bars must be >= 0", file=sys.stderr)
        return 2
    model = getattr(args, "model", None) or "ridge"
    if getattr(args, "alpha", None) is not None:
        alpha = args.alpha
    elif model in ("ridge", "online_ridge"):
        # same penalty scale (online_ridge standardizes causally, so
        # ridge's unit alpha carries over) — the leaky-vs-causal
        # comparison must not silently run at two different penalties
        alpha = cfg.intraday.alpha
    else:
        # non-ridge scales differ (l1 penalties live on the per-row
        # objective scale of ~1e-4 minute returns; the MLP's alpha is
        # weight decay) — let the API resolve its per-model defaults
        alpha = None
    extra = {}
    if getattr(args, "l1_ratio", None) is not None:
        extra["l1_ratio"] = args.l1_ratio
    res, fit, compact, dense_score, dense_price, dense_valid = intraday_pipeline(
        minute_df, daily_df,
        window_minutes=cfg.intraday.window_minutes,
        n_splits=cfg.intraday.n_splits,
        alpha=alpha,
        size_shares=cfg.intraday.size_shares,
        threshold=cfg.intraday.threshold,
        cash0=cfg.intraday.cash0,
        model=model,
        latency_bars=lat,
        **extra,
    )
    if model == "online_ridge":
        import jax as _jax

        if not _jax.config.jax_enable_x64:
            print("note: causal scores sit near the entry threshold, so the "
                  "f32 default flips marginal crossings vs f64 (trade count "
                  "~28.5k vs ~37.6k on the reference data); the sign of the "
                  "OOS result is precision-stable (examples/causal_scoring.py)")
    print(f"CV MSEs:     {[f'{m:.3g}' for m in np.asarray(fit.cv_mse)]}")
    print(f"Trades:      {int(res.n_trades)} "
          f"({int(res.n_buys)} buys / {int(res.n_sells)} sells)")
    print(f"Total PnL:   ${float(res.total_pnl):,.2f}")

    from csmom_tpu.backtest.event import cost_attribution

    bar = np.asarray(res.bar_mask)
    tca = cost_attribution(res, dense_price,
                           size_shares=cfg.intraday.size_shares,
                           latency_bars=lat, valid=dense_valid)
    delay_leg = (f"delay drift ${float(tca.delay_cost):,.2f}, "
                 if lat else "")
    print(f"Costs:       ${float(tca.total_cost):,.2f} "
          f"({float(tca.cost_bps):.2f} bps of ${float(tca.gross_notional):,.0f}"
          f" traded; {delay_leg}spread ${float(tca.spread_cost):,.2f}, "
          f"impact ${float(tca.impact_cost):,.2f}) — "
          f"gross PnL ${float(tca.gross_pnl):,.2f}")

    if (getattr(args, "threshold_hi", None) is not None
            and getattr(args, "threshold_lo", None) is None):
        print("--threshold-hi sets the hysteresis ENTRY threshold and does "
              "nothing alone: add --threshold-lo (the exit threshold) to "
              "run the Schmitt-trigger engine", file=sys.stderr)
        return 2
    if (getattr(args, "threshold_sweep", None)
            or getattr(args, "threshold_lo", None) is not None):
        from csmom_tpu.api import daily_risk_maps

        adv, vol = daily_risk_maps(daily_df, compact.tickers)

    if getattr(args, "threshold_sweep", None):
        from csmom_tpu.backtest.event import threshold_sweep

        ths = [float(t) for t in args.threshold_sweep.split(",")]
        pnl, ntr, bps = threshold_sweep(
            dense_price, dense_valid, np.nan_to_num(np.asarray(dense_score)),
            np.asarray(adv), np.asarray(vol),
            np.asarray(ths), size_shares=cfg.intraday.size_shares,
            cash0=cfg.intraday.cash0, latency_bars=lat,
        )
        print("\nthreshold sensitivity (one vmapped call):")
        print(f"{'threshold':>12} {'trades':>8} {'PnL':>16} {'cost bps':>9}")
        for t, p, n, b in zip(ths, np.asarray(pnl), np.asarray(ntr),
                              np.asarray(bps)):
            print(f"{t:>12g} {int(n):>8d} {float(p):>16,.2f} {float(b):>9.2f}")

    if getattr(args, "threshold_lo", None) is not None:
        from csmom_tpu.backtest import hysteresis_event_backtest

        hi = (args.threshold_hi if getattr(args, "threshold_hi", None)
              is not None else cfg.intraday.threshold)
        if args.threshold_lo > hi:
            print(f"--threshold-lo {args.threshold_lo:g} must not exceed "
                  f"the entry threshold {hi:g} (--threshold-hi)",
                  file=sys.stderr)
            return 2
        hres = hysteresis_event_backtest(
            dense_price, dense_valid, np.nan_to_num(np.asarray(dense_score)),
            np.asarray(adv), np.asarray(vol),
            threshold_hi=hi, threshold_lo=args.threshold_lo,
            size_shares=cfg.intraday.size_shares, cash0=cfg.intraday.cash0,
            latency_bars=lat,
        )
        print(f"\nhysteresis trigger (enter |score|>{hi:g}, exit "
              f"|score|<{args.threshold_lo:g}, bounded 1-unit book):")
        print(f"  trades {int(hres.n_trades)} (plain engine: "
              f"{int(res.n_trades)}), total PnL ${float(hres.total_pnl):,.2f}")
        from csmom_tpu.analytics.plots import save_trades_csv as _stc
        from csmom_tpu.backtest.event import trades_dataframe as _tdf

        h_trades = _tdf(hres, compact.tickers, compact.times,
                        np.nan_to_num(np.asarray(dense_score)),
                        size_shares=cfg.intraday.size_shares)
        h_csv = _stc(h_trades, cfg.results_dir, fname="trades_hysteresis.csv")
        print(f"  trade log: {h_csv} (flips are single ±2-unit rows)")

    if getattr(args, "tearsheet", False):
        import pandas as pd

        from csmom_tpu.analytics import format_tearsheet, tearsheet

        # minute PnL -> calendar-day returns on starting capital: the
        # standard daily tearsheet for an intraday strategy
        days = pd.DatetimeIndex(np.asarray(compact.times)[bar]).normalize()
        daily = pd.Series(np.asarray(res.pnl)[bar], index=days).groupby(level=0).sum()
        rets = (daily / cfg.intraday.cash0).to_numpy()
        print()
        print(format_tearsheet(
            tearsheet(rets, np.isfinite(rets), freq_per_year=252),
            label=f"daily PnL / ${cfg.intraday.cash0:,.0f} start",
        ))

    from csmom_tpu.analytics.plots import save_intraday_pnl_plot, save_trades_csv
    from csmom_tpu.backtest.event import trades_dataframe

    trades = trades_dataframe(
        res, compact.tickers, compact.times, np.asarray(dense_score),
        size_shares=cfg.intraday.size_shares,
    )
    out_csv = save_trades_csv(trades, cfg.results_dir)
    out_png = save_intraday_pnl_plot(
        np.asarray(compact.times)[bar], np.asarray(res.pnl)[bar], cfg.results_dir
    )
    log.info("wrote %s and %s", out_csv, out_png)
    return 0


def cmd_run(args) -> int:
    """Full demo: replicate + intraday, like the reference's ``main()``."""
    rc = cmd_replicate(args)
    if rc:
        return rc
    return cmd_intraday(args)


def cmd_horizons(args) -> int:
    """Event-time momentum profile by months since formation.

    The paper's long-horizon persistence-then-reversal view (LeSw00
    Tables VI-VIII); the reference computes only the 1-month holding
    return."""
    import numpy as np

    cfg = _load_cfg(args)
    prices, volume = _price_panel(cfg)

    v, m = prices.device()
    max_h = getattr(args, "max_h", None) or 36
    group = getattr(args, "group", None) or 6

    if getattr(args, "by_volume", False):
        from csmom_tpu.analytics.tables import volume_horizon_table
        from csmom_tpu.backtest import volume_horizon_profile

        turn, turn_valid, turn_lb = _build_turnover(args, cfg, prices, volume)
        vhp = volume_horizon_profile(
            v, m, np.asarray(turn), np.asarray(turn_valid),
            lookback=cfg.momentum.lookback, skip=cfg.momentum.skip,
            n_bins=cfg.momentum.n_bins, mode=cfg.momentum.mode, max_h=max_h,
        )
        print(f"J={cfg.momentum.lookback} momentum life cycle by volume "
              f"tercile (turnover avg {turn_lb}m), horizons 1..{max_h}:")
        print(volume_horizon_table(vhp, group=group).round(4).to_string())
        if getattr(args, "out", None):
            from csmom_tpu.analytics.plots import save_horizon_plot

            log.info("wrote %s", save_horizon_plot(
                vhp, cfg.results_dir, fname="horizon_profile_by_volume.png"
            ))
        return 0

    from csmom_tpu.analytics.tables import horizon_table
    from csmom_tpu.backtest import horizon_profile

    hp = horizon_profile(
        v, m, lookback=cfg.momentum.lookback, skip=cfg.momentum.skip,
        n_bins=cfg.momentum.n_bins, mode=cfg.momentum.mode, max_h=max_h,
    )
    print(f"J={cfg.momentum.lookback} event-time profile, horizons 1..{max_h}:")
    print(horizon_table(hp, group=group).round(4).to_string())
    if getattr(args, "out", None):
        from csmom_tpu.analytics.plots import save_horizon_plot

        log.info("wrote %s", save_horizon_plot(hp, cfg.results_dir))
    return 0


def cmd_fetch(args) -> int:
    """Populate or refresh the CSV cache for a universe.

    Cache-first like the reference's fetch layer (``data_io.py:131-228``):
    tickers with a readable cache are left alone unless --force-refresh;
    missing ones go to the network (requires yfinance, absent in offline
    images — the error names the fix).  Writes versioned caches that
    always roundtrip (the reference's dialect-B files silently dropped a
    ticker on re-read, SURVEY §2.1.1)."""
    cfg = _load_cfg(args)

    from csmom_tpu.panel.fetch import fetch_daily, fetch_intraday

    tickers = (
        list(_parse_tickers(args.tickers))
        if getattr(args, "tickers", None) else list(cfg.universe.tickers)
    )
    data_dir = cfg.universe.data_dir
    kind = getattr(args, "kind", None) or "both"
    force = bool(getattr(args, "force_refresh", False))
    rc = 0
    daily_df = None
    if kind in ("daily", "both"):
        df = daily_df = fetch_daily(
            tickers,
            start=getattr(args, "start", None) or cfg.universe.start,
            end=getattr(args, "end", None) or cfg.universe.end,
            data_dir=data_dir, force_refresh=force,
        )
        got = df.groupby("ticker").size() if len(df) else {}
        print(f"daily: {len(got)}/{len(tickers)} tickers cached in {data_dir}")
        if len(got) < len(tickers):  # partial failure is failure: a scripted
            rc = 1                   # fetch && replicate must stop, not run
                                     # on a silently smaller universe
    if kind in ("intraday", "both"):
        df = fetch_intraday(
            tickers,
            period=getattr(args, "period", None) or "7d",
            interval=getattr(args, "interval", None) or "1m",
            data_dir=data_dir, force_refresh=force,
        )
        got = df.groupby("ticker").size() if len(df) else {}
        print(f"intraday: {len(got)}/{len(tickers)} tickers cached in {data_dir}")
        if len(got) < len(tickers):
            rc = 1
    pack_to = getattr(args, "pack", None)
    if pack_to:
        # cache -> dense [A, T] pack: the at-scale binary path the grid and
        # bench feed from (memmapped load; CSV parse happens exactly once).
        # A partial fetch must NOT pack: a pack quietly missing tickers is
        # exactly the §2.1.1 universe-shrink failure the format exists to
        # prevent.
        if rc != 0:
            print("not packing: fetch was incomplete (see above) — fix the "
                  "universe or drop the failing tickers, then re-run",
                  file=sys.stderr)
            return rc
        import json as _json

        from csmom_tpu.panel.pack import pack_csv_cache

        try:
            # reuse the frame fetch_daily already parsed (double-parsing the
            # CSVs is the cost the pack exists to eliminate); intraday-only
            # invocations still read the daily caches themselves
            import numpy as _np

            out = pack_csv_cache(
                data_dir, tickers, pack_to, df=daily_df,
                dtype=_np.float32 if getattr(args, "pack_f32", False) else None,
            )
        except ValueError as e:
            print(f"pack failed: {e}", file=sys.stderr)
            return 1
        meta = _json.load(open(os.path.join(out, "meta.json")))
        n_packed = len(meta["tickers"])
        print(f"packed {n_packed} tickers -> {out}")
        if n_packed < len(tickers):
            print(f"pack is INCOMPLETE: {len(tickers) - n_packed} of "
                  f"{len(tickers)} requested tickers had no readable daily "
                  "cache", file=sys.stderr)
            return 1
    return rc


def cmd_packinfo(args) -> int:
    """Describe a packed panel directory: fields, universe, calendar,
    coverage, on-disk size."""
    import numpy as np

    from csmom_tpu.panel.pack import is_packed, load_packed

    path = args.pack_dir
    if not is_packed(path):
        print(f"{path}: not a packed panel (no meta.json)", file=sys.stderr)
        return 2
    b = load_packed(path)  # memmap: coverage scan pages through lazily
    panels = b.panels if hasattr(b, "panels") else {b.name: b}
    first = next(iter(panels.values()))
    a, t = first.shape
    size_mb = sum(
        os.path.getsize(os.path.join(path, f)) for f in os.listdir(path)
    ) / 1e6
    t0 = np.datetime_as_string(first.times[0], unit="D")
    t1 = np.datetime_as_string(first.times[-1], unit="D")
    print(f"packed panel: {path} ({size_mb:.1f} MB on disk)")
    print(f"universe: {a} tickers ({first.tickers[0]}..{first.tickers[-1]})")
    print(f"calendar: {t} dates, {t0} .. {t1}")
    for name, p in sorted(panels.items()):
        cov = float(np.asarray(p.mask).mean())
        print(f"field {name}: dtype {np.asarray(p.values).dtype}, "
              f"coverage {cov:.1%}")
    return 0


def cmd_bench(args) -> int:
    """Run the headline benchmark (same as ``python bench.py``)."""
    import subprocess

    # resolve bench.py from the repo checkout this package lives in, not
    # the caller's cwd (the CLI is routinely invoked from /tmp)
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    bench = os.path.join(repo, "bench.py")
    if not os.path.isfile(bench):
        print(f"bench.py not found at {bench}: the benchmark is a repo-"
              "checkout script, not an installed module — run it from the "
              "source tree", file=sys.stderr)
        return 2
    return subprocess.call([sys.executable, bench])


def cmd_warmup(args) -> int:
    """AOT-compile the hot-path shape manifest into the persistent cache.

    The warm-start half of the bench pipeline: enumerates every hot jitted
    entry point at its canonical bench/CLI shapes (csmom_tpu.compile
    .manifest), runs ``jit(...).lower(shapes).compile()`` for each with the
    serialized-executable cache enabled, and writes a per-shape report
    (trace wall, compile wall, hit/miss) next to the cache.  Run it any
    time BEFORE a measurement window — a later ``bench.py`` (or CLI)
    process at the same shapes loads executables from disk instead of
    compiling, so the window is spent measuring, not compiling.
    """
    profiles = [p.strip() for p in (args.profiles or "").split(",") if p.strip()]
    if not profiles:
        # platform-appropriate default: the CPU fallback's shapes plus the
        # CLI-facing golden kernels; on an accelerator, its bench shapes
        import jax

        on_cpu = jax.devices()[0].platform == "cpu"
        profiles = ["bench-cpu", "golden"] if on_cpu else ["bench-tpu", "golden"]

    from csmom_tpu.compile.manifest import PROFILES, build_manifest

    unknown = [p for p in profiles if p not in PROFILES]
    if unknown:
        print(f"unknown profile(s) {unknown}: choose from {list(PROFILES)}",
              file=sys.stderr)
        return 2

    if args.list:
        # enumerate + validate without compiling (manifest drift surfaces
        # here as a TypeError naming the stale entry)
        for profile in profiles:
            for e in build_manifest(profile):
                e.validate()
                print(f"{profile:10s} {e.name:44s} {e.shape_summary()}")
        return 0

    from csmom_tpu.compile.aot import warmup

    # honor an armed telemetry stream (CSMOM_TELEMETRY): the per-entry
    # warmup/aot spans then land on the run's timeline and a sidecar is
    # written.  Unlike bench/rehearse (default-ON runs), a standalone
    # warmup arms ONLY via the env contract — arm_policy with no default
    from csmom_tpu import obs

    tel_col = obs.arm_policy("warmup-cli")
    with obs.span("warmup.cli", root=True, profiles=",".join(profiles)):
        report = warmup(
            profiles=tuple(profiles),
            subdir=args.cache_subdir,
            include_golden_event=not args.no_golden_event,
        )
    if tel_col is not None:
        from csmom_tpu.obs import metrics as obs_metrics
        from csmom_tpu.obs import timeline as obs_tl

        # warmup only ever runs env-armed, so its run id is the
        # operator's: never overwrite an existing sidecar of that name
        # (it could be a round's committed evidence)
        sidecar = obs_tl.finish_and_write(
            os.environ.get("CSMOM_TELEMETRY_DIR") or os.getcwd(),
            fallback_metrics=obs_metrics.snapshot(), overwrite=False)
        print(f"telemetry: {sidecar}")
    for r in report["entries"]:
        status = ("HIT" if r.get("cache_hit")
                  else ("ERROR " + r["error"] if "error" in r else "compiled"))
        print(f"{r.get('name', '?'):44s} trace {r.get('trace_s', 0.0):7.2f}s "
              f"compile {r.get('compile_s', 0.0):7.2f}s  {status}")
    print(f"\n{report['n_entries']} entries, {report['n_cache_hits']} served "
          f"from cache, {report['n_errors']} errors in {report['wall_s']}s "
          f"(platform {report['platform']})")
    print(f"cache: {report['cache_dir']}")
    print(f"inputs: {report['input_builders']}")
    print(f"golden event: {report['golden_event']}")
    if report["n_errors"] and args.strict:
        return 1
    return 0


def _most_picked(choice, row_labels, col_labels, row_name, col_name, top_n=3):
    """Decode a walk-forward flat cell index path into the top-N
    most-selected (row, col) cells: ``[((row, col), count), ...]``.
    Shared by the sweep and residual subcommands so the -1-sentinel /
    flat-index semantics live in one place."""
    from collections import Counter

    import numpy as np

    choice = np.asarray(choice)
    live = choice >= 0
    picked = [
        (row_labels[c // len(col_labels)], col_labels[c % len(col_labels)])
        for c in choice[live]
    ]
    return Counter(picked).most_common(top_n), int(live.sum())


def _print_cell_tearsheets(spreads, spread_valid, index, columns):
    """Shared per-cell risk tables for grid-shaped results (grid/residual):
    one batched tearsheet call, one table per field."""
    import numpy as np
    import pandas as pd

    from csmom_tpu.analytics import tearsheet

    ts = tearsheet(np.nan_to_num(np.asarray(spreads)),
                   np.asarray(spread_valid), freq_per_year=12)
    for name, field in (("max drawdown", ts.max_drawdown),
                        ("Calmar", ts.calmar),
                        ("hit rate", ts.hit_rate)):
        df = pd.DataFrame(np.asarray(field), index=index, columns=columns)
        print(f"\n{name}:")
        print(df.round(4).to_string())


def cmd_residual(args) -> int:
    """Residual-momentum (lookback x est_window) hyperparameter grid in one
    compiled call; prints mean / NW-t / Sharpe tables per cell."""
    import numpy as np
    import pandas as pd

    cfg = _load_cfg(args)
    Js = ([int(j) for j in args.js.split(",")] if getattr(args, "js", None)
          else [3, 6, 12])
    Ws = ([int(w) for w in args.est_windows.split(",")]
          if getattr(args, "est_windows", None) else [12, 24, 36])
    bad = [(j, w) for j in Js for w in Ws if w < max(j, 3)]
    if bad:
        print("structurally invalid cells (est_window < max(lookback, 3)) "
              "will be all-NaN: "
              + ", ".join(f"J={j}/W={w}" for j, w in bad), file=sys.stderr)
    prices, _ = _price_panel(cfg)
    v, m = prices.device()

    from csmom_tpu.signals.residual import residual_sweep_backtest

    res = residual_sweep_backtest(
        v, m, np.asarray(Js), np.asarray(Ws), skip=cfg.momentum.skip,
        n_bins=cfg.momentum.n_bins, mode=cfg.momentum.mode,
    )

    def table(field):
        return pd.DataFrame(np.asarray(field), index=pd.Index(Js, name="J"),
                            columns=pd.Index(Ws, name="est_window"))

    for name, field in (("mean monthly spread", res.mean_spread),
                        ("Newey-West t-stat", res.tstat_nw),
                        ("annualized Sharpe", res.ann_sharpe)):
        print(f"\n{name}:")
        print(table(field).round(4).to_string())

    if getattr(args, "tearsheet", False):
        _print_cell_tearsheets(
            res.spreads, res.spread_valid,
            pd.Index(Js, name="J"), pd.Index(Ws, name="est_window"),
        )

    if getattr(args, "sweep", False):
        from csmom_tpu.backtest.walkforward import walk_forward_select

        wf = walk_forward_select(
            res.spreads, res.spread_valid,
            min_months=getattr(args, "min_months", None)
            or cfg.grid.walk_forward_min_months,
        )
        print(f"\nwalk-forward (expanding in-sample Sharpe selection): "
              f"OOS mean {float(wf.mean_spread):+.6f}, "
              f"Sharpe {float(wf.ann_sharpe):.4f}, "
              f"NW t {float(wf.tstat_nw):+.3f}")
        top, n_live = _most_picked(wf.choice, Js, Ws, "J", "est_window")
        if top:
            (j, w), n = top[0]
            print(f"most-picked cell: J={j}, est_window={w} "
                  f"({n}/{n_live} months)")
    return 0


def cmd_strategies(args) -> int:
    """List registered strategy plugins (name, parameters, description)."""
    import dataclasses

    from csmom_tpu.strategy import available_strategies

    for name, cls in sorted(available_strategies().items()):
        # user plugins may lack docstrings or plain defaults — never let
        # one undocumented registration break the whole listing
        def _param(f):
            if f.default is not dataclasses.MISSING:
                return f"{f.name}={f.default!r}"
            if f.default_factory is not dataclasses.MISSING:
                try:
                    return f"{f.name}={f.default_factory()!r}"
                except Exception:
                    return f.name  # a raising factory must not kill the listing
            return f.name

        params = ", ".join(_param(f) for f in dataclasses.fields(cls))
        lines = (cls.__doc__ or "").strip().splitlines()
        print(f"{name}({params})")
        if lines:
            print(f"    {lines[0]}")
    print("\nuse: csmom replicate --strategy NAME "
          "[--strategy-arg key=value ...]")
    return 0


def _add_common(p, tickers: bool = True):
    p.add_argument("--config", help="TOML RunConfig file")
    p.add_argument("--data-dir", help="CSV cache directory, or a packed "
                                      "panel directory (csmom fetch --pack)")
    if tickers:
        p.add_argument("--tickers",
                       help="comma-separated symbols (default: config "
                            "universe; with a packed --data-dir, default = "
                            "every packed ticker)")
    p.add_argument("--out", help="results directory")
    p.add_argument("--backend", choices=["tpu", "pandas"])
    p.add_argument("--platform", choices=["cpu", "tpu", "default"],
                   help="pin the jax platform before first device use "
                        "('default' keeps the environment's selection; use "
                        "'cpu' when the TPU tunnel is unavailable — the env "
                        "may pin an experimental platform that hangs at init)")
    p.add_argument("--lookback", type=int, help="formation months J")
    p.add_argument("--skip", type=int, help="skip months")
    p.add_argument("--n-bins", dest="n_bins", type=int)
    p.add_argument("--mode", choices=["qcut", "rank", "hist", "rank_hist"],
                   help="decile assignment: qcut (pandas parity), rank "
                        "(fast ordinal, one batched sort), hist (sort-free "
                        "radix-histogram form of rank — same labels; the "
                        "candidate for >=50k-asset universes), rank_hist "
                        "(distributed radix-histogram rank — grid command "
                        "only, implies a sharded mesh)")


def _add_turnover_flags(sp):
    """Volume-sort flags shared by every turnover-conditioned subcommand
    (doublesort, horizons --by-volume) — one definition so help text and
    defaults cannot drift."""
    sp.add_argument("--fetch-shares", dest="fetch_shares",
                    action="store_true",
                    help="fetch shares outstanding for true turnover "
                         "(network); default uses a volume proxy")
    sp.add_argument("--turnover-lookback", dest="turnover_lookback",
                    type=int,
                    help="months averaged into the volume sort (default: "
                         "config's 3; use J for the paper's "
                         "formation-period turnover)")


def build_parser() -> argparse.ArgumentParser:
    from csmom_tpu import __version__

    p = argparse.ArgumentParser(prog="csmom", description=__doc__)
    p.add_argument("--version", action="version", version=f"csmom_tpu {__version__}")
    sub = p.add_subparsers(dest="command")

    for name, fn, extra in (
        ("run", cmd_run,
         ("bootstrap", "strategy", "tables", "tearsheet", "monthly_extras")),
        ("replicate", cmd_replicate,
         ("bootstrap", "strategy", "tables", "tearsheet", "monthly_extras")),
        ("grid", cmd_grid, ("js", "ks", "bootstrap", "tearsheet", "tc")),
        ("doublesort", cmd_doublesort, ("doublesort",)),
        ("sweep", cmd_sweep, ("js", "ks", "min_months", "tc_bps")),
        ("intraday", cmd_intraday, ("model", "tearsheet")),
        ("horizons", cmd_horizons, ("horizons",)),
        ("fetch", cmd_fetch, ("fetch",)),
        ("residual", cmd_residual,
         ("js", "est_windows", "tearsheet", "wf", "min_months")),
        ("strategies", cmd_strategies, ()),
        ("pack-info", cmd_packinfo, ()),
        ("bench", cmd_bench, ()),
        ("warmup", cmd_warmup, ()),
    ):  # rehearse lives in cli/rehearse.py (the main.py split: new
        # subcommands register themselves instead of growing this module)
        sp = sub.add_parser(name, help=(fn.__doc__ or "").splitlines()[0])
        if name == "pack-info":
            sp.add_argument("pack_dir", help="packed panel directory")
            sp.set_defaults(fn=fn)
            continue
        if name == "warmup":
            sp.add_argument("--profiles",
                            help="comma-separated warmup profiles "
                                 "(bench-cpu, bench-tpu, golden, smoke, "
                                 "serve, serve-smoke; default: platform-"
                                 "appropriate bench + golden)")
            sp.add_argument("--platform", choices=["cpu", "tpu", "default"],
                            help="pin the jax platform before compiling "
                                 "(shapes are cached per backend: warm CPU "
                                 "shapes any time, TPU shapes during a "
                                 "tunnel window)")
            sp.add_argument("--cache-subdir", dest="cache_subdir",
                            default="bench",
                            help="persistent-cache namespace (default "
                                 "'bench' — the directory bench children "
                                 "and the capture scripts share)")
            sp.add_argument("--list", action="store_true",
                            help="print the manifest (validated against the "
                                 "live signatures) without compiling")
            sp.add_argument("--no-golden-event", dest="no_golden_event",
                            action="store_true",
                            help="skip resolving the event engine at the "
                                 "actual golden workload shapes (skips the "
                                 "intraday pipeline build)")
            sp.add_argument("--strict", action="store_true",
                            help="exit 1 when any manifest entry fails to "
                                 "compile")
            sp.set_defaults(fn=fn)
            continue
        _add_common(sp, tickers=(name != "fetch"))  # fetch has its own
        if "js" in extra:
            sp.add_argument("--js", help="comma-separated J values")
        if "ks" in extra:
            sp.add_argument("--ks", help="comma-separated K values")
        if "est_windows" in extra:
            sp.add_argument("--est-windows", dest="est_windows",
                            help="comma-separated OLS estimation windows "
                                 "(months; default 12,24,36)")
        if "wf" in extra:
            sp.add_argument("--sweep", action="store_true",
                            help="also walk-forward the grid (out-of-sample "
                                 "expanding-window cell selection)")
        if name == "grid":
            sp.add_argument("--shards", type=int, metavar="N",
                            help="run the grid asset-sharded over an N-device "
                                 "mesh (required form for --mode rank_hist)")
            sp.add_argument("--impl",
                            choices=["xla", "pallas", "matmul", "matmul_bf16"],
                            help="cohort-aggregation kernel (default xla; "
                                 "matmul = MXU cross-table form, ~5x on big "
                                 "panels; matmul_bf16 = bf16 operands/f32 "
                                 "accumulation; pallas = fused VMEM kernel, "
                                 "TPU)")
        if "min_months" in extra:
            sp.add_argument("--min-months", dest="min_months", type=int)
        if "bootstrap" in extra:
            sp.add_argument("--bootstrap", type=int, metavar="N",
                            help="print block-bootstrap 95%% CIs from N resamples")
            sp.add_argument("--block-len", dest="block_len", type=int)
        if "tables" in extra:
            sp.add_argument("--tables", action="store_true",
                            help="print the paper-style per-decile table")
        if "tearsheet" in extra:
            sp.add_argument("--tearsheet", action="store_true",
                            help="print the full risk tearsheet (drawdown, "
                                 "Calmar, Sortino, tails; per-cell tables "
                                 "for grid)")
        if ("monthly_extras" in extra or "tc" in extra
                or "tc_bps" in extra or "doublesort" in extra):
            if "tc_bps" in extra:  # the sweep: costs change the SELECTION
                tc_help = ("select cells and report OOS performance NET of "
                           "linear transaction costs at this half-spread "
                           "(bps per unit weight turnover)")
            elif "doublesort" in extra:
                tc_help = ("also report each tercile's book turnover, the "
                           "spread net of linear costs at this half-spread, "
                           "and its break-even bps")
            else:
                tc_help = ("also report the spread net of linear "
                           "transaction costs at this half-spread (bps per "
                           "unit weight turnover)")
            sp.add_argument("--tc-bps", dest="tc_bps", type=float,
                            help=tc_help)
        if "tc" in extra:
            sp.add_argument("--tc-sweep", dest="tc_sweep", metavar="BPS,...",
                            help="with --tc-bps: also print net mean spreads "
                                 "at these half-spread levels, re-priced "
                                 "from the single unit-cost run (the cost "
                                 "model is linear in the half-spread)")
        if "monthly_extras" in extra:
            sp.add_argument("--sector-map", dest="sector_map",
                            help="ticker,sector CSV: rank within sectors "
                                 "(sector-neutral momentum; TPU engine)")
            sp.add_argument("--band", type=int, metavar="B",
                            help="also run the hysteresis-banded book: "
                                 "enter at the extreme decile, stay within "
                                 "B deciles of it (cuts turnover; with "
                                 "--tc-bps also reports the banded net and "
                                 "break-even)")
            sp.add_argument("--vol-target", dest="vol_target", type=float,
                            metavar="PCT",
                            help="also report the volatility-managed "
                                 "overlay (Barroso-Santa-Clara 2015): "
                                 "scale exposure to this annualized vol "
                                 "target (percent, e.g. 12) using the "
                                 "trailing 6-month realized vol")
            sp.add_argument("--band-sweep", dest="band_sweep",
                            metavar="B,B,...",
                            help="with --band surfaces: compare several "
                                 "hysteresis band widths in one table "
                                 "(gross mean / turnover / break-even; "
                                 "net at --tc-bps when given) — formation "
                                 "runs once, only the book tail re-runs "
                                 "per band")
            sp.add_argument("--band-select", dest="band_select",
                            metavar="B,B,...",
                            help="walk-forward band selection: at every "
                                 "month pick the width with the best "
                                 "expanding-window Sharpe over PRIOR "
                                 "months (net of --tc-bps when given) and "
                                 "realize its month — the out-of-sample "
                                 "answer to 'which band?'")
        if "doublesort" in extra:
            _add_turnover_flags(sp)
        if "horizons" in extra:
            sp.add_argument("--max-h", dest="max_h", type=int,
                            help="longest horizon in months (default 36; "
                                 "the paper's five-year view is 60)")
            sp.add_argument("--group", type=int,
                            help="horizons per table row (default 6)")
            sp.add_argument("--by-volume", dest="by_volume",
                            action="store_true",
                            help="condition the profile on volume terciles "
                                 "(the paper's momentum life cycle, Table "
                                 "VIII: high-volume momentum reverses "
                                 "sooner)")
            _add_turnover_flags(sp)
        if "fetch" in extra:
            sp.add_argument("--tickers", help="comma-separated symbols "
                                              "(default: config universe)")
            sp.add_argument("--kind", choices=["daily", "intraday", "both"],
                            help="which bars to fetch (default both)")
            sp.add_argument("--start", help="daily range start (YYYY-MM-DD)")
            sp.add_argument("--end", help="daily range end")
            sp.add_argument("--period", help="intraday lookback (default 7d)")
            sp.add_argument("--interval", help="intraday bar size (default 1m)")
            sp.add_argument("--force-refresh", dest="force_refresh",
                            action="store_true",
                            help="re-download even when a cache file exists")
            sp.add_argument("--pack", metavar="DIR",
                            help="after fetch, convert the daily CSV cache "
                                 "to a packed binary panel directory "
                                 "(dense [A,T] .npy + manifest; loads "
                                 "memmapped via panel.load_packed)")
            sp.add_argument("--pack-f32", dest="pack_f32",
                            action="store_true",
                            help="store packed values as float32 (half the "
                                 "disk; the TPU compute dtype anyway)")
        if "model" in extra:
            sp.add_argument("--model",
                            choices=["ridge", "online_ridge", "elastic_net", "lasso", "mlp"],
                            help="score model (default: ridge, the reference's)")
            sp.add_argument("--alpha", type=float,
                            help="regularization strength (mlp: weight decay)")
            sp.add_argument("--l1-ratio", dest="l1_ratio", type=float,
                            help="elastic-net l1 ratio (default 0.5)")
            sp.add_argument("--threshold-sweep", dest="threshold_sweep",
                            help="comma-separated score thresholds: print "
                                 "PnL/trades/cost sensitivity (one vmapped "
                                 "call)")
            sp.add_argument("--threshold-hi", dest="threshold_hi",
                            type=float, metavar="S",
                            help="hysteresis entry threshold (default: the "
                                 "config threshold); used with "
                                 "--threshold-lo")
            sp.add_argument("--threshold-lo", dest="threshold_lo",
                            type=float, metavar="S",
                            help="ALSO run the Schmitt-trigger event "
                                 "engine: enter a bounded 1-unit position "
                                 "when |score| > entry, exit when |score| "
                                 "< this, hold in between (cuts intraday "
                                 "churn; reports trades/PnL vs the plain "
                                 "engine)")
            sp.add_argument("--latency-bars", dest="latency_bars",
                            type=int, metavar="N",
                            help="order-to-fill delay in bars (fills at the "
                                 "next valid row >= decision+N; the cost "
                                 "print adds the delay-drift leg of the "
                                 "implementation shortfall)")
            sp.add_argument("--parity", action="store_true",
                            help="reproduce the reference's EFFECTIVE daily "
                                 "risk-map universe (drop dialect-B caches "
                                 "its loader loses — SURVEY §2.1.1) so the "
                                 "trade log matches results/trades.csv "
                                 "row-for-row")
        if "strategy" in extra:
            sp.add_argument("--strategy",
                            help="registered strategy plugin to rank instead of "
                                 "the built-in momentum path")
            sp.add_argument("--strategy-arg", dest="strategy_arg",
                            action="append", metavar="K=V",
                            help="strategy parameter, repeatable")
        sp.set_defaults(fn=fn)

    from csmom_tpu.cli.fleet import register as register_fleet
    from csmom_tpu.cli.ledger import register as register_ledger
    from csmom_tpu.cli.lint import register as register_lint
    from csmom_tpu.cli.registry import register as register_registry
    from csmom_tpu.cli.rehearse import register as register_rehearse
    from csmom_tpu.cli.replay import register as register_replay
    from csmom_tpu.cli.serve import register as register_serve
    from csmom_tpu.cli.timeline import register as register_timeline
    from csmom_tpu.cli.trace import register as register_trace

    register_rehearse(sub)
    register_timeline(sub)
    register_trace(sub)
    register_fleet(sub)
    register_ledger(sub)
    register_serve(sub)
    register_replay(sub)
    register_registry(sub)
    register_lint(sub)
    # the epilog is built AFTER every registration hook has run, from the
    # registry itself — a subcommand cannot exist without appearing here
    p.epilog = _registry_epilog(sub)
    p.formatter_class = argparse.RawDescriptionHelpFormatter
    return p


def _registry_epilog(sub) -> str:
    """The ``--help`` subcommand table, generated from the live subparser
    registry (names + their registered help lines).  This replaced a
    hand-maintained docstring list that had drifted to a third of the
    real registry — generation is the only form that cannot drift."""
    helps = {a.dest: a.help or "" for a in
             getattr(sub, "_choices_actions", [])}
    names = sorted(sub.choices)
    lines = [f"subcommands ({len(names)}):"]
    for n in names:
        first = helps.get(n, "").split("\n")[0]
        lines.append(f"  {n:<12} {first}".rstrip())
    return "\n".join(lines)


# commands that never touch a device (pure pandas/numpy, or — bench and
# rehearse — supervisors that do their own subprocess probing): no init
# probe for these.  ledger pins cpu itself before its bootstrap math, so
# the probe would only add a failure mode to an offline evidence reader.
_DEVICE_FREE_COMMANDS = {"fetch", "strategies", "bench", "pack-info",
                         "rehearse", "timeline", "ledger", "lint",
                         "fleet"}


def _apply_platform(args) -> int:
    """Pin the jax platform before any device use; fail fast on dead tunnels.

    The env-var route is not enough in images that pin ``JAX_PLATFORMS``
    and import jax at interpreter start (sitecustomize);
    ``jax.config.update`` post-import is the override that works.

    When no ``--platform`` is given and the environment pins a non-cpu
    platform, backend init can HANG (observed: a tunneled TPU plugin
    blocking ``jax.devices()`` for >900 s when the tunnel is down), so the
    default platform is probed in a subprocess with a hard timeout
    (``CSMOM_PLATFORM_PROBE_S``, default 20 s) before any in-process device
    use; on timeout the CLI prints the workaround and exits 3 instead of
    hanging.  A successful probe is cached for
    ``CSMOM_PLATFORM_PROBE_TTL_S`` (default 120 s) in a timestamped marker
    file, so consecutive invocations skip re-probing inside one tunnel
    window.  ``CSMOM_PLATFORM_PROBE_S=0`` disables the probe (the "I
    know, wait for it" escape hatch — an explicit ``--platform tpu``
    is NOT that: it selects the local tpu plugin, a different backend
    than a tunneled platform like this image's 'axon').
    """
    choice = getattr(args, "platform", None)
    if choice in (None, "default"):
        envp = os.environ.get("JAX_PLATFORMS", "")
        if "jax" in sys.modules:
            import jax

            if (jax.config.jax_platforms or "") == "cpu":
                # an embedder (the test suite, a notebook) already pinned
                # the in-process backend to cpu via config.update — that
                # override beats the env var, so there is nothing to probe
                return 0
        if (envp and envp != "cpu"
                and getattr(args, "command", None) not in _DEVICE_FREE_COMMANDS):
            import subprocess
            import tempfile

            # Default raised from 6 s (ADVICE r4): cold TPU runtime init can
            # legitimately take >6 s, and a false exit 3 on a healthy tunnel
            # is worse than a slower first failure.
            probe_s = float(os.environ.get("CSMOM_PLATFORM_PROBE_S", "20"))
            if probe_s <= 0:
                return 0  # probe disabled: proceed on the env's platform
            # A recent successful probe is cached (timestamped marker file,
            # keyed by the platform string) so back-to-back CLI invocations
            # pay the subprocess init once, not per command.  TTL is short:
            # this image's tunnel flaps in ~25-min windows, so a stale "ok"
            # must expire well inside one.  Freshness goes through the
            # deadline module's skew-resistant marker_fresh (the chaos
            # clock_skew fault monkeypatches time.time, which used to make
            # this cache read "fresh" for an hour or "expired" instantly).
            from csmom_tpu.utils.deadline import marker_fresh

            ttl_s = float(os.environ.get("CSMOM_PLATFORM_PROBE_TTL_S", "120"))
            mark = os.path.join(
                tempfile.gettempdir(),
                f"csmom_probe_ok_{''.join(c if c.isalnum() else '_' for c in envp)}",
            )
            if marker_fresh(mark, ttl_s):
                return 0  # fresh success cached: skip the probe
            try:
                subprocess.run(
                    [sys.executable, "-c",
                     "import jax; jax.devices()"],
                    capture_output=True, timeout=probe_s, check=True,
                )
                try:
                    with open(mark, "w"):
                        pass
                except OSError:
                    pass  # cache write failure only costs the next probe
            except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
                print(
                    f"error: the environment pins JAX_PLATFORMS={envp!r} and "
                    f"that backend did not initialize within {probe_s:.0f}s "
                    "(remote tunnel down?).\n"
                    "  - re-run with `--platform cpu` (every subcommand "
                    "supports it), or\n"
                    "  - set CSMOM_PLATFORM_PROBE_S=0 to skip this probe "
                    "and wait the backend out, or raise it for a longer "
                    "probe (note: `--platform tpu` selects a LOCAL tpu "
                    "plugin, which is a different backend than a tunneled "
                    "one like 'axon')",
                    file=sys.stderr,
                )
                return 3
        return 0
    import jax

    jax.config.update("jax_platforms", choice)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not getattr(args, "command", None):
        build_parser().print_help()
        return 0
    if getattr(args, "mode", None) == "rank_hist" and args.command != "grid":
        print("--mode rank_hist is distributed-only: use "
              "`csmom grid --shards N --mode rank_hist`", file=sys.stderr)
        return 2
    rc = _apply_platform(args)
    if rc:
        return rc
    # Persistent compile cache: consecutive CLI invocations re-jit identical
    # shapes (a replicate's kernels, a grid's cells); on the tunneled TPU
    # backend each costs ~30s+, so the cache is decisive there.  On CPU the
    # compiles are seconds AND XLA's AOT loader logs a spurious
    # machine-feature-mismatch ERROR for every cached entry (tuning
    # pseudo-features like prefer-no-scatter are recorded at serialize time
    # but absent from the host CPUID list) — stderr spam a demo user would
    # read as breakage.  So: cache by default off-CPU; on CPU only when the
    # user points CSMOM_JIT_CACHE somewhere explicitly.  Device-free
    # subcommands stay jax-free: the helper imports jax, and these commands
    # never compile anything.
    if getattr(args, "command", None) not in _DEVICE_FREE_COMMANDS:
        explicit_cache = os.environ.get("CSMOM_JIT_CACHE", "") not in ("", "0")
        resolved_cpu = (
            getattr(args, "platform", None) == "cpu"
            or os.environ.get("JAX_PLATFORMS", "") == "cpu"
        )
        if not resolved_cpu:
            # ask the backend itself (covers jax defaulting to CPU on an
            # accelerator-less box with a clean env).  This command is
            # device-using, so the backend init happens momentarily anyway,
            # and _apply_platform's probe has already vetted it.
            import jax

            resolved_cpu = (
                (jax.config.jax_platforms or "") == "cpu"
                or jax.default_backend() == "cpu"
            )
        if explicit_cache or not resolved_cpu:
            from csmom_tpu.utils.jit_cache import enable_persistent_cache

            enable_persistent_cache("cli")
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
