"""``csmom`` CLI entry point.

The reference has no CLI at all — its driver hardcodes every parameter
(``/root/reference/run_demo.py:193-207``).  This module grows the
run/replicate/grid/sweep subcommands as the framework lands; for now it
reports the package version and available subcommands.
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="csmom", description=__doc__)
    from csmom_tpu import __version__

    p.add_argument("--version", action="version", version=f"csmom_tpu {__version__}")
    p.add_subparsers(dest="command")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not getattr(args, "command", None):
        build_parser().print_help()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
