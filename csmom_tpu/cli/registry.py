"""csmom registry — inspect the engine registry (ISSUE 9).

``csmom registry list`` prints every registered engine with its kind
and the surfaces registration bought it: which warmup profiles carry
its manifest entries, whether it is a live serve endpoint / loadgen
workload leg, whether a donated-buffer variant exists, and the state of
its sharded hook (``stub`` until ROADMAP item 1 fills partition rules
in).  ``--kind`` filters; ``--endpoints`` prints just the serving
tier's endpoint names (the set ``serve/buckets.py::ENDPOINTS`` used to
hard-code — scripts that consumed that literal read it here now).

Registered via ``register(sub)`` like serve/replay/ledger (the
cli/main.py split: new subcommands do not grow the monolith).
"""

from __future__ import annotations

import sys

__all__ = ["cmd_registry", "register"]


def _surfaces(spec) -> str:
    """One engine's surface summary, compact enough for a table row."""
    if spec.kind == "lint":
        # a registered rule's surfaces: the CLI sweep, tier-1, and the
        # known-bad/clean fixture self-test (ISSUE 11)
        return "csmom-lint tier-1 self-test"
    out = []
    if spec.profiles:
        out.append(f"manifest({','.join(spec.profiles)})")
    if spec.kind == "serve":
        out.append("serve")
        if spec.workload:
            out.append("loadgen")
        out.append("donated")  # auto-derived for every serve engine
    elif spec.donated_fn is not None:
        out.append("donated")
    if spec.entry_fn is not None:
        out.append("entry")
    if spec.kind != "strategy":
        out.append("sharded" if spec.sharded_fn is not None
                   else "sharded:stub")
    return " ".join(out) or "-"


def cmd_registry(args) -> int:
    """List registered engines and the surfaces registration bought them."""
    from csmom_tpu.registry import engine_specs, serve_endpoints

    if args.action != "list":
        print(f"unknown registry action {args.action!r} (try: list)",
              file=sys.stderr)
        return 2
    if args.endpoints:
        for name in serve_endpoints():
            print(name)
        return 0
    kinds = ((args.kind,) if args.kind
             else ("serve", "compile", "strategy", "lint"))
    n = 0
    for kind in kinds:
        specs = engine_specs(kind)
        if kind == "strategy" and not specs:
            # strategies register on zoo import; force it so the listing
            # is complete without the caller knowing that detail
            from csmom_tpu.registry import strategies

            strategies()
            specs = engine_specs(kind)
        if kind == "lint" and not specs:
            # lint rules register on analysis.rules import, same deal
            from csmom_tpu.registry import lint_rules

            lint_rules()
            specs = engine_specs(kind)
        if not specs:
            continue
        print(f"{kind} ({len(specs)}):")
        for spec in specs:
            n += 1
            print(f"  {spec.name:<22} {_surfaces(spec)}")
            if spec.description and not args.terse:
                print(f"  {'':<22} {spec.description}")
        print()
    print(f"{n} engines registered — one registration buys: shape-"
          "manifest entries (csmom warmup), a donated-buffer variant, "
          "a serve endpoint on the bucket grid, a loadgen workload leg "
          "with ledger rows, and a sharded variant; a kind-'lint' "
          "registration buys the csmom lint sweep, the tier-1 gate, "
          "and the fixture self-test")
    return 0


def register(sub) -> None:
    """Attach the ``registry`` subparser (from cli.main)."""
    sp = sub.add_parser(
        "registry",
        help="inspect the engine registry: every registered engine and "
             "the production surfaces registration bought it",
    )
    sp.add_argument("action", nargs="?", default="list",
                    help="what to do (list: print the registry table)")
    sp.add_argument("--kind", choices=["serve", "compile", "strategy",
                                       "lint"],
                    help="only this kind of engine")
    sp.add_argument("--endpoints", action="store_true",
                    help="print only the serve endpoint names (one per "
                         "line; the old ENDPOINTS literal, read from "
                         "the registry)")
    sp.add_argument("--terse", action="store_true",
                    help="omit descriptions (names + surfaces only)")
    sp.set_defaults(fn=cmd_registry)
