"""csmom rehearse — prove the capture pipeline survives its fault matrix.

Runs the supervisor → warmup → bench → deadline → land pipeline inside a
sandbox tmpdir under every fault in the built-in matrix (plus ``--plan``
for custom ones) and prints a per-fault pass/fail table.  Exit status is
nonzero on ANY invariant violation, so watcher scripts can gate a tunnel
window on a green rehearsal.  Everything runs on a CPU-only machine: the
point is to rehearse BEFORE a window opens, not during one.

Two pipeline tiers, because the invariants are properties of the capture
*plumbing*, not the workload:

- ``mini`` / ``shell`` scenarios drive :mod:`csmom_tpu.chaos.minibench`
  and ``benchmarks/capture_lib.sh`` — sub-second per fault, no jax.
  ``csmom rehearse --fast`` runs only these (the tier-1 subset).
- ``bench`` scenarios drive the real ``bench.py`` supervisor or child in
  smoke mode (``CSMOM_BENCH_SMOKE=1``: full pipeline shape, reduced
  workload) — the r5 failure mode reproduced and shown fixed against the
  actual code that will hold a window's measurements.

This module is also the first move of the cli/main.py split (VERDICT:
1,701 lines and growing): new subcommands land as their own module with a
``register(sub)`` hook instead of growing the monolith.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.chaos.plan import PLAN_ENV, Fault, FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CAPTURE_LIB = os.path.join(_REPO, "benchmarks", "capture_lib.sh")


# ------------------------------------------------------------ scenarios ----

class Scenario:
    """One rehearsal: a fault plan, a pipeline to drive, and the checks
    the landed evidence must pass."""

    def __init__(self, name, pipeline, plan, check, fast=False, notes="",
                 env=None, rows=6, budget_s=None):
        self.name = name
        self.pipeline = pipeline  # mini | shell | bench-child | bench
        self.plan = plan
        self.check = check        # fn(result dict) -> list of violations
        self.fast = fast
        self.notes = notes
        self.env = env or {}
        self.rows = rows
        self.budget_s = budget_s


def _rows_of(obj) -> int:
    return inv.measured_rows(obj or {})


def _plan_summary(plan) -> str:
    """One-line digest of a scenario's armed fault plan for ``--list``:
    ``seed N: point:action[@after][xfires][!]`` per fault (``!`` marks
    global-once), or the runner-driven note when no plan arms."""
    if plan is None:
        return "none (runner-driven faults / env contract)"
    parts = []
    for f in plan.faults:
        p = f"{f.point}:{f.action}"
        if f.after:
            p += f"@{f.after}"
        if f.max_fires != 1:
            p += f"x{f.max_fires or 'inf'}"
        if f.global_once:
            p += "!"
        parts.append(p)
    return f"seed {plan.seed}: " + ", ".join(parts)


def _check_partial_no_lost_rows(r):
    """A deadline-hit run must land a partial carrying EVERY measured row."""
    out = list(r["headline_violations"])
    obj = r["trailing"]
    if obj is None:
        return out + ["no trailing JSON line — measurements lost"]
    if not inv.is_partial(obj):
        out.append("expected an explicitly-partial record")
    if r["sidecar_rows"] and _rows_of(obj) != r["sidecar_rows"]:
        out.append(
            f"lost measured rows: sidecar has {r['sidecar_rows']}, landed "
            f"artifact has {_rows_of(obj)}"
        )
    if r.get("artifact") is not None:
        out += [f"artifact: {v}" for v in inv.validate(r["artifact"])]
        if _rows_of(r["artifact"]) != r["sidecar_rows"]:
            out.append("landed artifact dropped measured rows")
    elif r["sidecar_rows"]:
        out.append("partial line printed but no artifact landed")
    if r["rc"] != 0:
        out.append(f"deadline dump must exit 0, got rc={r['rc']}")
    return out


def _check_full_all_rows(r):
    """An unfaulted-outcome run: full record, all rows, schema-valid."""
    out = list(r["headline_violations"])
    obj = r["trailing"]
    if obj is None:
        return out + ["no trailing JSON line"]
    if inv.is_partial(obj):
        out.append("expected a FULL record, got a partial")
    if r["sidecar_rows"] and _rows_of(obj) != r["sidecar_rows"]:
        out.append(
            f"row count mismatch: sidecar {r['sidecar_rows']} vs landed "
            f"{_rows_of(obj)}"
        )
    if r.get("artifact") is not None:
        out += [f"artifact: {v}" for v in inv.validate(r["artifact"])]
    if r["rc"] != 0:
        out.append(f"rc={r['rc']}")
    return out


def _check_killed_nothing_fabricated(r):
    """A SIGKILLed process prints nothing; the landing layer must not
    fabricate an artifact from the corpse (and must keep any prior one)."""
    out = []
    if r["rc"] >= 0:
        out.append(f"expected SIGKILL (negative rc), got rc={r['rc']}")
    if r["trailing"] is not None:
        out.append("a SIGKILLed process somehow printed a summary line")
    if r.get("artifact") is not None:
        out.append("landing fabricated an artifact from a dead process")
    return out


def _mini_scenarios():
    sleep_long = 600.0
    return [
        Scenario(
            "expire-deadline-between-rows", "mini",
            FaultPlan("expire-deadline-between-rows", seed=1, faults=(
                Fault(point="mini.row", action="trip_deadline", after=3),
            )),
            _check_partial_no_lost_rows, fast=True,
            notes="deadline expires between measured rows -> partial dump "
                  "carries every measured row (r4/r5 fix, fast form)",
        ),
        Scenario(
            "hang-mid-row", "mini",
            FaultPlan("hang-mid-row", seed=2, faults=(
                Fault(point="mini.row", action="sleep", after=2,
                      seconds=sleep_long),
            )),
            _check_partial_no_lost_rows,
            notes="tunnel-style hang mid-row -> watchdog beats the stall "
                  "and dumps the measured rows",
            env={"CSMOM_MINIBENCH_BUDGET": "2",
                 "CSMOM_MINIBENCH_MIN_DELAY": "1"},
            budget_s=None,
        ),
        Scenario(
            "stdout-interleave", "mini",
            FaultPlan("stdout-interleave", seed=3, faults=(
                Fault(point="mini.finish", action="stdout_noise",
                      seconds=1.0),
            )),
            _check_full_all_rows, fast=True,
            notes="concurrent stdout writer racing the trailing JSON -> "
                  "the quarantined single-write emit keeps it parseable",
        ),
        Scenario(
            "clock-skew", "mini",
            FaultPlan("clock-skew", seed=4, faults=(
                Fault(point="mini.start", action="clock_skew",
                      seconds=3600.0),
            )),
            _check_full_all_rows,
            notes="wall clock jumps +1h mid-capture -> monotonic-anchored "
                  "deadline keeps its true fuse, run completes in full",
            env={"CSMOM_MINIBENCH_BUDGET": "30"},
        ),
        Scenario(
            "sigkill-mid-row", "mini",
            FaultPlan("sigkill-mid-row", seed=5, faults=(
                Fault(point="mini.row", action="kill", after=2),
            )),
            _check_killed_nothing_fabricated,
            notes="SIGKILL between rows: unpreventable loss, but the "
                  "landing layer must not fabricate or clobber artifacts",
        ),
    ]


def _check_short_write(r):
    out = []
    if r.get("artifact") is not None:
        out.append("a truncated (ENOSPC) write LANDED as the artifact")
    if not r.get("prior_intact", True):
        out.append("the faulted landing damaged the pre-existing artifact")
    if r.get("retry_artifact") is None:
        out.append("the fault-free retry failed to land the artifact")
    else:
        out += [f"retry artifact: {v}"
                for v in inv.validate(r["retry_artifact"])]
    return out


def _shell_scenarios():
    return [
        Scenario(
            "land-short-write", "shell", None, _check_short_write, fast=True,
            notes="ENOSPC/short write between formatter and rename -> "
                  "post-write JSON validation refuses to land garbage; "
                  "the fault-free retry lands cleanly",
        ),
    ]


def _check_serve_worker_crash(r):
    """ISSUE 5: a worker crash mid-batch must terminate its batch as
    rejected-with-reason (never a silent drop), leave the remaining
    queue drainable, and keep the accounting equation closed — the
    validator enforces served + rejected + expired == admitted."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve")
    req = art.get("requests") or {}
    if not req.get("rejected_worker_crash"):
        out.append("the injected crash terminated no requests as "
                   "rejected — the fault did not fire or the loss was "
                   "hidden")
    if not req.get("served"):
        out.append("no request served after the crash — the queue did "
                   "not stay drainable")
    if (art.get("batches") or {}).get("count", 0) < 2:
        out.append("fewer than 2 batches dispatched — nothing ran after "
                   "the crashed batch")
    return out


def _check_serve_deadline_storm(r):
    """Overload + tight deadlines: requests must expire WHILE QUEUED and
    never be dispatched (expired_dispatched == 0 is a validator rule),
    with the books still balanced on the drained queue."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve")
    req = art.get("requests") or {}
    if not req.get("expired"):
        out.append("the deadline storm expired no requests — the storm "
                   "did not overload the queue (tune the plan)")
    return out


def _check_serve_burst_storm(r):
    """ISSUE 8: a bulk-heavy burst storm against the SLO classes — the
    bulk quota must actually enforce (rejected_quota > 0 in bulk's own
    book), every interactive request must be SERVED (none rejected or
    expired behind the flood), interactive must never queue behind bulk
    (its p99 bounded by bulk's — the rank-order claim), and the
    per-class books must close (schema rules of serve v2).

    The starvation evidence is deliberately scheduling-invariant: an
    absolute wall-clock p99 bound flakes when the REHEARSAL machine is
    contended (the whole run slows uniformly), but quota rejections and
    the interactive-never-behind-bulk ordering hold at any machine
    speed.  The absolute per-class budget claim lives in the committed
    SERVE_r13.json (a dedicated capture, not a shared-tier test) and in
    tests/test_serve_slo.py's paced starvation test."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve")
    classes = art.get("classes") or {}
    bulk = classes.get("bulk") or {}
    inter = classes.get("interactive") or {}
    if not bulk.get("rejected_quota"):
        out.append("bulk.rejected_quota == 0 — the burst never hit the "
                   "quota; the storm rehearsed nothing (tune the "
                   "schedule or the quota)")
    if not inter.get("served"):
        out.append("no interactive request served under the bulk storm")
    elif inter.get("served") != inter.get("admitted"):
        out.append(
            f"interactive served {inter.get('served')} of "
            f"{inter.get('admitted')} admitted — the bulk storm cost "
            "interactive requests (rejected/expired), which is exactly "
            "the starvation the SLO classes exist to prevent")
    ip99 = (inter.get("latency_ms") or {}).get("p99")
    bp99 = (bulk.get("latency_ms") or {}).get("p99")
    if (isinstance(ip99, (int, float)) and isinstance(bp99, (int, float))
            and inter.get("within_budget") is not True
            and ip99 > bp99 + 100.0):
        out.append(
            f"interactive p99 {ip99} ms exceeds bulk's served p99 "
            f"{bp99} ms (and its own budget) — interactive queued "
            "BEHIND bulk, rank-ordered collection did not hold")
    return out


def _check_serve_cache_poison(r):
    """ISSUE 8: the chaos ``cache_poison`` action plants entries under
    live keys stamped below the version floor — the get path must refuse
    every one (``stale_blocked`` > 0, ``stale_hits`` == 0 BY SCHEMA),
    genuine repeats must still hit, and the books must close."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve")
    cache = art.get("cache") or {}
    if not cache.get("hits"):
        out.append("cache.hits == 0 — the reuse stream produced no "
                   "genuine hits; the scenario rehearsed nothing")
    if not cache.get("stale_blocked"):
        out.append("cache.stale_blocked == 0 — the poison fault never "
                   "fired (or its entry was silently served)")
    # stale_hits != 0 is already a schema violation; restate it pointedly
    if cache.get("stale_hits"):
        out.append(f"cache.stale_hits = {cache['stale_hits']} — a "
                   "POISONED result reached a caller")
    return out


def _burst_policy():
    """The burst-storm SLO policy: default shape, but a bulk quota small
    enough that the rehearse burst provably exceeds it even when a
    contended machine stretches the run (token refill is time-based, so
    a slower run earns MORE tokens — the margin must survive that)."""
    from csmom_tpu.serve.slo import SLOClass, SLOPolicy

    return SLOPolicy((
        SLOClass("interactive", rank=0, deadline_s=0.5),
        SLOClass("standard", rank=1, deadline_s=1.0, queue_share=0.75),
        SLOClass("bulk", rank=2, deadline_s=3.0,
                 quota_rps=15.0, quota_burst=5.0, queue_share=0.5),
    ))


def _serve_scenarios():
    return [
        Scenario(
            "serve-worker-kill-mid-batch", "serve",
            FaultPlan("serve-worker-kill", seed=20, faults=(
                Fault(point="serve.dispatch", action="fail", after=1,
                      max_fires=1),
            )),
            _check_serve_worker_crash, fast=True,
            notes="worker crash mid-batch (chaos fail at serve.dispatch):"
                  " the batch terminates rejected-with-reason, the queue "
                  "drains on, served+rejected+expired == admitted",
            env={"load": {"schedule": "0.5x80", "seed": 11,
                          "deadline_s": 2.0}},
        ),
        Scenario(
            "serve-deadline-storm", "serve",
            FaultPlan("serve-deadline-storm", seed=21, faults=(
                Fault(point="serve.dispatch", action="sleep",
                      seconds=0.12, after=0, max_fires=3),
            )),
            _check_serve_deadline_storm, fast=True,
            notes="slow dispatches pile the queue past tight deadlines: "
                  "requests expire WHILE QUEUED (never dispatched), "
                  "backpressure rejects at the bound, books balance",
            env={"load": {"schedule": "0.4x150", "seed": 12,
                          "deadline_s": 0.08},
                 "serve": {"capacity": 24}},
        ),
        Scenario(
            "serve-burst-storm", "serve", None,
            _check_serve_burst_storm, fast=True,
            notes="bulk-heavy burst storm against the SLO classes: the "
                  "bulk token bucket rejects over-quota admissions, "
                  "every interactive request is served and never queues "
                  "behind bulk, and the per-class books close BY SCHEMA "
                  "(serve v2)",
            env={"load": {"schedule": "0.2x30,0.15x280,0.2x30,0.15x300",
                          "seed": 22,
                          "class_mix": (("interactive", 0.4),
                                        ("bulk", 0.6)),
                          # generous explicit deadlines: a contended
                          # rehearse machine must not expire requests
                          # the scheduling property would have served
                          "deadline_s": 10.0,
                          "schedule_kind": "bursty"},
                 "serve": {"policy": _burst_policy(), "capacity": 256}},
        ),
        Scenario(
            "serve-cache-poison", "serve",
            FaultPlan("serve-cache-poison", seed=23, faults=(
                Fault(point="serve.cache", action="cache_poison",
                      after=3, max_fires=4),
            )),
            _check_serve_cache_poison, fast=True,
            notes="chaos plants stale-version entries under live cache "
                  "keys: the get-path version floor refuses every one "
                  "(stale_blocked > 0, stale_hits == 0 by schema) while "
                  "genuine repeats keep hitting and books stay closed",
            env={"load": {"schedule": "0.5x120", "seed": 24,
                          "reuse_fraction": 0.6, "version_bumps": 1,
                          "deadline_s": 2.0}},
        ),
    ]


def _check_pool_worker_kill(r):
    """ISSUE 6: a worker-PROCESS death mid-batch (chaos ``kill`` at
    serve.dispatch, fired inside one worker of the fleet) must lose no
    request: the router's books stay closed across the process boundary,
    conn-failed dispatches fail over, the pool keeps serving, and
    availability stays >= 99%."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_pool")
    req = art.get("requests") or {}
    pool = art.get("pool") or {}
    if not pool.get("kills"):
        out.append("no worker death observed — the injected process kill "
                   "did not fire (or the supervisor missed it)")
    if not req.get("worker_conn_failures"):
        out.append("no connection failure recorded — the kill missed "
                   "every in-flight dispatch (nothing was rescued)")
    if not req.get("served"):
        out.append("nothing served — the pool did not keep serving past "
                   "the dead worker")
    if (art.get("availability") or 0.0) < 0.99:
        out.append(f"availability {art.get('availability')} < 0.99 after "
                   "a single worker kill — hedged retries did not route "
                   "around the corpse")
    return out


def _check_trace_stitch_worker_kill(r):
    """ISSUE 13: cross-process trace stitching under a mid-batch worker
    SIGKILL.  The landed TRACE artifact must be schema-valid (closed
    trace books, stage sums reconciling within epsilon, orphan reasons
    summing to the orphan count), the killed worker's unstitchable
    dispatches must appear as reason-closed ORPHAN halves, and the
    surviving complete traces must carry BOTH halves of the stitch
    (router-side transport + worker-side queue_wait/dispatch stages)."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_pool")
    tart = r.get("trace_artifact") or {}
    out += [f"trace: {v}" for v in inv.validate(tart, "trace")]
    out += list(r.get("trace_book_violations") or [])
    orphans = tart.get("orphans") or {}
    if not orphans.get("count"):
        out.append("no orphan half closed — the SIGKILLed worker's "
                   "in-flight dispatch left no reason-closed orphan "
                   "(the kill missed, or the orphan leaked)")
    elif not any("connection" in reason or "closed" in reason
                 for reason in (orphans.get("reasons") or {})):
        out.append(f"orphan reasons {list(orphans.get('reasons') or {})} "
                   "never name the connection failure — the reason was "
                   "lost in the close")
    stages = tart.get("stages") or {}
    for want in ("transport", "queue_wait", "dispatch"):
        if want not in stages:
            out.append(f"no {want!r} stage in the stitched decomposition "
                       "— the worker half (or the router half) was "
                       "never stitched in")
    books = tart.get("books") or {}
    if not books.get("complete"):
        out.append("no complete trace — failover served nothing the "
                   "trace layer could stitch")
    return out


def _check_fleet_capture_worker_kill(r):
    """ISSUE 19: continuous fleet capture across a mid-batch worker
    SIGKILL.  The landed FLEET artifact must be schema-valid (every
    process stream reason-closed, counter series monotone, demand
    reconciling with the request books), the victim's stream must read
    as a SEVERED series gap — never silent truncation — its
    replacement's spawn→ready wall must land as a lifecycle sample
    beyond the initial fleet's, and the kill-window capacity account
    must show a loss the steady state does not."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_pool")
    fart = r.get("fleet_artifact") or {}
    out += [f"fleet: {v}" for v in inv.validate(fart, "fleet")]
    series = fart.get("series") or {}
    procs = series.get("processes") or {}
    severed = [p for p, b in procs.items()
               if "severed" in str(b.get("close_reason", ""))]
    if not severed:
        out.append("no severed stream book — the SIGKILLed worker's "
                   "emitter died without its gap being reason-closed "
                   "(silent truncation, the one outcome the observatory "
                   "exists to forbid)")
    n_workers = ((art.get("pool") or {}).get("n_workers")
                 or (fart.get("capacity") or {}).get("n_slots") or 0)
    walls = (fart.get("lifecycle") or {}).get("ready_walls_s") or []
    if len(walls) <= n_workers:
        out.append(f"{len(walls)} ready-wall sample(s) for "
                   f"{n_workers} worker slot(s) — the replacement's "
                   "(re)spawn→ready wall never landed in the lifecycle "
                   "book")
    cap = fart.get("capacity") or {}
    if not cap.get("kill_windows"):
        out.append("no kill window in the capacity account — the "
                   "injected process kill left no trace in the "
                   "availability timeline")
    if not (cap.get("kill_window_loss_frac") or 0) > 0:
        out.append("kill-window capacity loss fraction is 0 — a dead "
                   "worker slot cost nothing, which no capacity account "
                   "should claim")
    demand = (fart.get("demand") or {}).get("classes") or {}
    if not demand:
        out.append("empty demand book — the client-tier hooks never "
                   "fired while the load ran")
    return out


def _check_spare_promote_on_kill(r):
    """ISSUE 20: a hot spare is parked OUT of the ring when chaos
    SIGKILLs a worker mid-batch.  The elastic tier must promote the
    spare into the victim's slot (one promotion, ready wall far below a
    re-warm), the spare-credited capacity account must show ~no
    kill-window capacity loss (the reserve covered the hole), the
    spare's ids must never leak into the serving books, and the FLEET
    artifact — elastic block included — must close by schema."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_pool")
    fart = r.get("fleet_artifact") or {}
    out += [f"fleet: {v}" for v in inv.validate(fart, "fleet")]
    el = fart.get("elastic") or {}
    if not el:
        out.append("no elastic block in the FLEET artifact — the spare "
                   "tier ran unbooked")
    promos = el.get("promotions") or []
    if len(promos) != 1:
        out.append(f"{len(promos)} promotion(s) booked for 1 kill with "
                   "1 spare — the spare was not promoted exactly once")
    for p in promos:
        if (p.get("wall_s") or 0) > 1.5:
            out.append(f"promotion ready wall {p['wall_s']:.3f}s > 1.5s "
                       "— promoting a pre-warmed spare took as long as "
                       "a re-warm, which defeats the reserve")
    if el.get("promotions_missed"):
        out.append(f"{el['promotions_missed']} promotion(s) MISSED — a "
                   "death found no ready spare despite one configured")
    cap = fart.get("capacity") or {}
    loss = cap.get("kill_window_loss_frac")
    if loss is None or loss > 0.10:
        out.append(f"kill-window capacity loss {loss!r} > 0.10 — the "
                   "spare reserve did not cover the kill window (the "
                   "account found a capacity hole the spare exists to "
                   "fill)")
    series = fart.get("series") or {}
    procs = series.get("processes") or {}
    if not any("severed" in str(b.get("close_reason", ""))
               for b in procs.values()):
        out.append("no severed stream book — the victim's emitter died "
                   "without its gap being reason-closed")
    spare_ids = set(el.get("spare_ids") or [])
    booked = {e.get("worker_id")
              for e in ((fart.get("lifecycle") or {}).get("events") or [])}
    for w in (cap.get("kill_windows") or []):
        booked.add(w.get("worker_id"))
    if spare_ids & booked:
        out.append(f"spare id(s) {sorted(spare_ids & booked)} leaked "
                   "into the serving lifecycle/kill-window books — "
                   "spares must stay out of the ring until promoted")
    return out


def _check_pool_rolling_restart(r):
    """ISSUE 6: a rolling restart under load replaces every worker with
    zero in-window fresh compiles (warm-before-ready via the AOT cache)
    and zero availability loss — the predecessor drains only after its
    replacement demonstrated ready."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_pool")
    roll = r.get("roll") or {}
    n_workers = (art.get("pool") or {}).get("n_workers", 0)
    if roll.get("aborted"):
        out.append(f"roll aborted: {roll['aborted']}")
    if len(roll.get("rolled") or []) != n_workers:
        out.append(f"rolled {len(roll.get('rolled') or [])} of "
                   f"{n_workers} workers — the roll did not complete")
    fresh = (art.get("compile") or {}).get("in_window_fresh_compiles")
    if fresh != 0:
        out.append(f"in_window_fresh_compiles = {fresh!r} across the "
                   "rolled fleet — a replacement compiled instead of "
                   "loading the AOT cache (warm-before-ready broke)")
    if art.get("availability") != 1.0:
        out.append(f"availability {art.get('availability')} != 1.0 — the "
                   "rolling restart dropped requests")
    if not (art.get("requests") or {}).get("served"):
        out.append("nothing served during the roll")
    return out


def _check_pool_version_skew(r):
    """ISSUE 6: AOT-cache version skew between supervisor and worker —
    the worker must REFUSE ready with a pointed message (naming the skew
    and the warmup remedy) instead of compiling in the window, and the
    supervisor must park the slot rather than restart-loop a condition a
    restart cannot fix."""
    s = r.get("skew") or {}
    out = []
    if s.get("started"):
        out.append("the pool started with a version-skewed worker — the "
                   "ready gate did not hold")
    if s.get("state") != "failed":
        out.append(f"skewed slot ended {s.get('state')!r}, expected "
                   "'failed' (parked)")
    reason = s.get("reason") or ""
    if "skew" not in reason:
        out.append(f"refusal reason does not name the version skew: "
                   f"{reason[:120]!r}")
    if "csmom warmup" not in reason:
        out.append("refusal reason lost the `csmom warmup` pointer")
    if s.get("restarts"):
        out.append(f"supervisor scheduled {s['restarts']} restart(s) for "
                   "a skew refusal — a redeploy problem must not be "
                   "hot-spun")
    return out


def _check_mesh_pinned_worker_kill(r):
    """ISSUE 10: SIGKILL a device-pinned worker mid-batch — the r11
    pool-kill scenario on the mesh path.  The replacement must re-pin
    its predecessor's EXACT device slice (slices are slot-derived, and
    the spawn events prove the derivation was honored), re-warm from
    the serialized AOT cache (fresh compiles stay 0 across the fleet),
    and the pool's cross-process books must still close."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_pool")
    pool = art.get("pool") or {}
    if not pool.get("kills"):
        out.append("no worker death observed — the injected process kill "
                   "did not fire")
    if not pool.get("restarts"):
        out.append("no restart recorded — the dead pinned worker was "
                   "never replaced")
    events = pool.get("events") or []
    spawns: dict = {}
    for e in events:
        if e.get("event") == "spawn":
            spawns.setdefault(e.get("worker_id"), []).append(
                e.get("device_slice"))
    if any(s is None for slices in spawns.values() for s in slices):
        out.append("a spawn event carries no device_slice — pinning was "
                   "not plumbed to the worker")
    respawned = {w: slices for w, slices in spawns.items()
                 if len(slices) >= 2}
    if not respawned:
        out.append("no worker spawned twice — the replacement's re-pin "
                   "was never exercised")
    for w, slices in respawned.items():
        if len(set(slices)) != 1:
            out.append(f"{w} re-pinned a DIFFERENT slice across spawns "
                       f"({slices}) — the slot->slice derivation broke")
    fresh = (art.get("compile") or {}).get("in_window_fresh_compiles")
    if isinstance(fresh, int) and fresh != 0:
        out.append(f"in_window_fresh_compiles = {fresh} — a replacement "
                   "compiled instead of loading the AOT cache")
    if not (art.get("requests") or {}).get("served"):
        out.append("nothing served — the pool did not keep serving past "
                   "the dead pinned worker")
    return out


def _serve_pool_scenarios():
    # chaos hit counters are PER-PROCESS: every worker's own readiness
    # self-probe dispatches once per REGISTERED endpoint before any load
    # arrives, so the kill's `after` must skip exactly that many hits or
    # the worker kills itself during its probe (and the load sees no
    # failure to rescue).  Derived from the registry, like the probe.
    from csmom_tpu.registry import serve_endpoints

    probe_dispatches = len(serve_endpoints())
    return [
        Scenario(
            "pool-worker-kill-mid-batch", "serve-pool",
            FaultPlan("pool-worker-kill", seed=30, faults=(
                Fault(point="serve.dispatch", action="kill",
                      after=probe_dispatches,
                      max_fires=1, global_once=True),
            )),
            _check_pool_worker_kill, fast=True,
            notes="one worker PROCESS dies mid-batch (chaos kill at "
                  "serve.dispatch, global-once across the fleet): router "
                  "books stay closed, failover rescues in-flight "
                  "requests, availability >= 99%",
            env={"mode": "kill",
                 "pool": {"n_workers": 2},
                 "load": {"schedule": "0.6x70", "seed": 13,
                          "deadline_s": 3.0}},
        ),
        Scenario(
            "mesh-pinned-worker-kill", "serve-pool",
            FaultPlan("mesh-pinned-worker-kill", seed=31, faults=(
                Fault(point="serve.dispatch", action="kill",
                      after=probe_dispatches,
                      max_fires=1, global_once=True),
            )),
            _check_mesh_pinned_worker_kill, fast=True,
            notes="ISSUE 10: SIGKILL a DEVICE-PINNED worker mid-batch: "
                  "the replacement re-pins its slot's exact device slice "
                  "(spawn events prove it), re-warms from the AOT cache "
                  "(0 fresh compiles), and the pool books close",
            env={"mode": "kill", "wait_respawn": True,
                 "pool": {"n_workers": 2, "devices_per_worker": 2},
                 "load": {"schedule": "0.8x70", "seed": 15,
                          "deadline_s": 3.0}},
        ),
        Scenario(
            "trace-stitch-worker-kill", "serve-pool",
            FaultPlan("trace-stitch-worker-kill", seed=32, faults=(
                Fault(point="serve.dispatch", action="kill",
                      after=probe_dispatches,
                      max_fires=1, global_once=True),
            )),
            _check_trace_stitch_worker_kill, fast=True,
            notes="ISSUE 13: the pool kill with request tracing ARMED — "
                  "complete traces carry both stitched halves (router "
                  "transport + worker stages), the dead worker's "
                  "dispatches close as reason-carrying orphan halves, "
                  "trace books balance and stage sums reconcile (trace "
                  "schema)",
            env={"mode": "kill", "trace": True, "wait_respawn": True,
                 "pool": {"n_workers": 2},
                 "load": {"schedule": "0.8x70", "seed": 16,
                          "deadline_s": 3.0}},
        ),
        Scenario(
            "fleet-capture-worker-kill", "serve-pool",
            FaultPlan("fleet-capture-worker-kill", seed=33, faults=(
                Fault(point="serve.dispatch", action="kill",
                      after=probe_dispatches,
                      max_fires=1, global_once=True),
            )),
            _check_fleet_capture_worker_kill, fast=True,
            notes="ISSUE 19: the pool kill with the fleet observatory "
                  "ARMED — the victim's metric stream closes as a "
                  "severed series gap (never silent truncation), its "
                  "replacement's spawn→ready wall lands as a lifecycle "
                  "sample, the kill-window capacity account books a "
                  "loss the steady state does not, and the demand book "
                  "reconciles with the request ledger (fleet schema)",
            env={"mode": "kill", "fleet": True, "wait_respawn": True,
                 "pool": {"n_workers": 2},
                 "load": {"schedule": "0.8x70", "seed": 16,
                          "deadline_s": 3.0}},
        ),
        Scenario(
            "spare-promote-on-kill", "serve-pool",
            FaultPlan("spare-promote-on-kill", seed=34, faults=(
                Fault(point="serve.dispatch", action="kill",
                      after=probe_dispatches,
                      max_fires=1, global_once=True),
            )),
            _check_spare_promote_on_kill, fast=True,
            notes="ISSUE 20: the pool kill with a HOT SPARE parked out "
                  "of the ring — the elastic tier promotes the spare "
                  "into the victim's slot (one promotion, wall far "
                  "below a re-warm), the spare-credited capacity "
                  "account shows no kill-window capacity hole, the "
                  "spare ids never leak into the serving books, and "
                  "the elastic block closes by schema",
            env={"mode": "kill", "fleet": True, "spares": 1,
                 "wait_respawn": True,
                 "pool": {"n_workers": 2},
                 "load": {"schedule": "0.8x70", "seed": 16,
                          "deadline_s": 3.0}},
        ),
        Scenario(
            "pool-rolling-restart-under-load", "serve-pool", None,
            _check_pool_rolling_restart, fast=True,
            notes="rolling restart under open-loop load: every "
                  "replacement warm-before-ready (0 in-window compiles), "
                  "predecessors drain only after, availability 100%",
            env={"mode": "roll",
                 "pool": {"n_workers": 2},
                 "load": {"schedule": "1.2x40", "seed": 14,
                          "deadline_s": 3.0}},
        ),
        Scenario(
            "pool-aot-cache-version-skew", "serve-pool", None,
            _check_pool_version_skew, fast=True,
            notes="supervisor expects a different AOT cache version: the "
                  "worker refuses ready with a pointed message (skew + "
                  "warmup remedy) and the slot parks — no restart loop, "
                  "no silent in-window compile",
            env={"mode": "skew", "pool": {"n_workers": 1}},
        ),
    ]


def _check_fabric_partition(r):
    """ISSUE 14: a router replica is PARTITIONED from a worker host
    mid-burst (chaos ``partition`` at serve.transport, fired inside one
    replica, global-once across the tier).  Every dial to that peer
    fails instantly until the partition heals; the replica's failover/
    hedging must route around it, the CLIENT books must stay closed,
    and availability must reconcile at 1.0 — an admitted request never
    dies with a partitioned wire."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_fabric")
    req = art.get("requests") or {}
    conn_fails = 0
    for rep in (art.get("routers") or {}).get("replicas") or []:
        a = rep.get("accounting")
        if isinstance(a, dict):
            conn_fails += a.get("worker_conn_failures", 0) or 0
    if not conn_fails:
        out.append("no router→worker connection failure recorded — the "
                   "partition never fired (or its refusals were hidden)")
    if not req.get("served"):
        out.append("nothing served — the fabric did not keep serving "
                   "through the partition")
    if (art.get("availability") or 0.0) < 1.0:
        out.append(f"availability {art.get('availability')} < 1.0 — an "
                   "admitted request was lost to a healed partition "
                   "(failover/hedging did not route around it)")
    return out


def _check_fabric_straggler(r):
    """ISSUE 14: induced stragglers (chaos ``net_delay`` at
    serve.transport stalls a bounded number of router→worker dials).
    The hedging policy is what the scenario measures: hedges MUST fire
    (the straggler was detected) and the hedge rate MUST stay bounded
    (Tail at Scale's paid-insurance property — a hedge storm would
    double fleet load exactly when it straggles), with the client books
    closed and availability 1.0."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_fabric")
    hedge = art.get("hedge") or {}
    rt = hedge.get("router_tier") or {}
    if not rt.get("hedged"):
        out.append("no hedge fired — the induced straggler was never "
                   "detected (or the delay missed every dial)")
    rate = hedge.get("rate")
    if isinstance(rate, (int, float)) and rate > 0.5:
        out.append(f"hedge rate {rate} > 0.5 — hedging went from paid "
                   "insurance to a load doubler under the straggler")
    if (art.get("availability") or 0.0) < 1.0:
        out.append(f"availability {art.get('availability')} < 1.0 — a "
                   "stalled wire cost an admitted request")
    if not (art.get("requests") or {}).get("served"):
        out.append("nothing served under the induced straggler")
    return out


def _check_fabric_router_kill(r):
    """ISSUE 14: the rehearsed r18 double kill — one ROUTER replica and
    one WORKER SIGKILLed mid-burst.  The client tier must fail its
    in-flight requests over to the surviving replica (failovers > 0),
    both supervisors must respawn their slots, the CLIENT books must
    close (the outermost ledger survives both corpses), and
    availability must reconcile at 1.0."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "serve_fabric")
    req = art.get("requests") or {}
    routers = art.get("routers") or {}
    workers = art.get("workers") or {}
    if not routers.get("kills"):
        out.append("no router replica death observed — the SIGKILL "
                   "missed the router tier")
    if not workers.get("kills"):
        out.append("no worker death observed — the SIGKILL missed the "
                   "worker tier")
    if not (req.get("failovers") or req.get("router_conn_failures")):
        out.append("no client-side failover recorded — the router kill "
                   "hit no in-flight request (nothing was rescued)")
    if not routers.get("restarts"):
        out.append("the dead router replica was never replaced")
    if not workers.get("restarts"):
        out.append("the dead worker was never replaced")
    if (art.get("availability") or 0.0) < 1.0:
        out.append(f"availability {art.get('availability')} < 1.0 — an "
                   "admitted request died with a corpse; the fabric's "
                   "whole point is that none can")
    if not req.get("served"):
        out.append("nothing served through the double kill")
    return out


def _serve_fabric_scenarios():
    return [
        Scenario(
            "fabric-partition-mid-burst", "serve-fabric",
            FaultPlan("fabric-partition", seed=33, faults=(
                Fault(point="serve.transport", action="partition",
                      after=6, max_fires=1, global_once=True),
            )),
            _check_fabric_partition,
            notes="ISSUE 14: one router replica loses a worker HOST "
                  "mid-burst (chaos partition at serve.transport, "
                  "global-once): dials to the peer fail instantly until "
                  "the partition heals, failover/hedging route around "
                  "it, client books close, availability 1.0",
            env={"transport": "tcp",
                 "pool": {"n_workers": 2},
                 "chaos_env": {"CSMOM_CHAOS_PARTITION_S": "0.6"},
                 "load": {"schedule": "1.0x60", "seed": 17,
                          "deadline_s": 3.0}},
        ),
        Scenario(
            "fabric-induced-straggler", "serve-fabric",
            FaultPlan("fabric-straggler", seed=34, faults=(
                Fault(point="serve.transport", action="net_delay",
                      after=4, max_fires=5),
            )),
            _check_fabric_straggler,
            notes="ISSUE 14: induced stragglers (net_delay stalls a "
                  "bounded number of router→worker dials): hedges fire "
                  "(Tail at Scale) but the hedge rate stays bounded "
                  "<= 0.5, books close, availability 1.0",
            # the induced delay (0.9 s) must OUTLAST the hedge trigger
            # (0.25 x the 1.5 s budget ≈ 0.38 s): a delay the primary
            # absorbs before the hedge timer fires rehearses nothing
            env={"pool": {"n_workers": 2},
                 "hedge_fraction": 0.25,
                 "chaos_env": {"CSMOM_CHAOS_NET_DELAY_S": "0.9"},
                 "load": {"schedule": "0.8x50", "seed": 18,
                          "deadline_s": 1.5}},
        ),
        Scenario(
            "fabric-router-kill-mid-burst", "serve-fabric", None,
            _check_fabric_router_kill,
            notes="ISSUE 14: the rehearsed r18 double kill — one router "
                  "replica AND one worker SIGKILLed mid-burst: client "
                  "failover rescues in-flight requests, both tiers "
                  "respawn, the outermost books close, availability 1.0",
            env={"pool": {"n_workers": 2},
                 "kill": {"router_after": 0.25, "worker_after": 0.45},
                 "load": {"schedule": "1.4x45", "seed": 19,
                          "deadline_s": 3.0}},
        ),
    ]


def _check_replay_tick_storm(r):
    """ISSUE 7: under a storm of late / out-of-order / duplicate / gap
    ticks, the replay must keep BOTH closed books (tick ledger + serve
    book — schema rules of kind ``replay``), materialize the gap as a
    stale bar instead of carrying the last price, and the incremental
    signals must still reconcile bit-for-bit against the full-panel
    recompute (drift_events == 0; late merges show up as rebuilds)."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "replay")
    t = art.get("ticks") or {}
    for k in ("merged_late", "quarantined", "deduped", "dropped_gap"):
        if not t.get(k):
            out.append(f"ticks.{k} == 0 — the injected fault did not "
                       "fire or its outcome was hidden")
    if not (art.get("panel") or {}).get("gap_bars"):
        out.append("no gap bar materialized — the dropped bar was "
                   "papered over instead of marked stale")
    rec = art.get("reconcile") or {}
    if not rec.get("count"):
        out.append("no reconciliation ran — the equivalence check never "
                   "exercised")
    if rec.get("drift_events"):
        out.append(f"reconcile.drift_events = {rec['drift_events']} — "
                   "the incremental signals drifted from the full "
                   "recompute under the tick storm")
    if not rec.get("rebuilds"):
        out.append("no rebuild after late merges — merged-in-place "
                   "history must invalidate running sums")
    if not ((art.get("serve") or {}).get("requests") or {}).get("served"):
        out.append("nothing served — the live panel never answered "
                   "under the storm")
    return out


def _check_replay_version_skew(r):
    """ISSUE 7: a serve probe answering from a stale panel snapshot must
    be REFUSED and counted (the streaming analogue of the r11 AOT
    version-skew gate), with the books still balanced and later probes
    served from fresh snapshots."""
    art = r.get("artifact") or {}
    out = inv.validate(art, "replay")
    v = art.get("versions") or {}
    if not v.get("skew_events"):
        out.append("the version-skew fault never fired — nothing was "
                   "rehearsed")
    if not v.get("skew_refusals"):
        out.append("a stale-snapshot request was NOT refused — the "
                   "panel-version gate did not hold")
    if not ((art.get("serve") or {}).get("requests") or {}).get("served"):
        out.append("nothing served — the gate refused more than the "
                   "skewed probe")
    return out


def _replay_scenarios():
    return [
        Scenario(
            "replay-tick-storm", "replay",
            FaultPlan("replay-tick-storm", seed=40, faults=(
                Fault(point="stream.tick", action="tick_late", after=90,
                      max_fires=6),
                Fault(point="stream.tick", action="tick_late", after=140,
                      max_fires=5),
                Fault(point="stream.tick", action="tick_dup", after=110,
                      max_fires=4),
                # a whole-bar gap: drop every tick of one bar (8 assets)
                Fault(point="stream.tick", action="tick_drop",
                      after=22 * 8, max_fires=8),
            )),
            _check_replay_tick_storm, fast=True,
            notes="late/out-of-order/duplicate/gap tick storm: closed "
                  "tick books, gap marked stale (never price-carried), "
                  "incremental == full recompute bit-for-bit "
                  "(rebuild-on-merge, zero drift)",
        ),
        Scenario(
            "replay-ingest-serve-skew", "replay",
            FaultPlan("replay-ingest-serve-skew", seed=41, faults=(
                Fault(point="stream.serve", action="version_skew",
                      after=1, max_fires=1),
            )),
            _check_replay_version_skew, fast=True,
            notes="serve probe answers from a stale snapshot: the "
                  "panel-version gate refuses it (counted), books stay "
                  "closed, fresh probes keep serving — the r11 AOT-skew "
                  "gate's streaming twin",
        ),
    ]


def _check_bench_partial(r):
    """r5 reproduced and shown fixed: the child lost its window mid-run but
    the already-measured headline landed in an explicitly-partial line."""
    out = list(r["headline_violations"])
    obj = r["trailing"]
    if obj is None:
        return out + ["no trailing JSON line — the r5 empty-record failure"]
    extra = obj.get("extra") or {}
    if not str(extra.get("partial", "")).startswith("child deadline hit"):
        out.append("expected the child deadline watchdog's partial marker")
    if not isinstance(obj.get("value"), (int, float)) or obj["value"] <= 0:
        out.append("the measured headline value was lost")
    if extra.get("platform") != "cpu":
        out.append("partial record lost its platform attribution")
    if r["rc"] != 0:
        out.append(f"watchdog dump must exit 0, got rc={r['rc']}")
    return out


def _check_bench_supervisor_landed(r):
    """Supervisor-level faults: whatever broke, ONE schema-valid headline
    lands on stdout and points at (or explains) the full record."""
    out = list(r["headline_violations"])
    obj = r["trailing"]
    if obj is None:
        return out + ["supervisor printed no parseable headline"]
    extra = obj.get("extra") or {}
    if "full_record" not in extra:
        out.append("headline does not reference the full record")
    full = r.get("full_record")
    if full is not None:
        out += [f"full record: {v}" for v in inv.validate(full)]
    return out


def _check_bench_fallback_measured(r):
    out = _check_bench_supervisor_landed(r)
    obj = r["trailing"] or {}
    if not isinstance(obj.get("value"), (int, float)) or obj.get("value", 0) <= 0:
        out.append("no measured value — the fallback child did not secure "
                   "the record")
    return out


def _check_kill_fallback(r):
    out = _check_bench_fallback_measured(r)
    full = r.get("full_record") or {}
    errs = (full.get("extra") or {}).get("attempt_errors") or []
    if not any("child" in str(e) for e in errs):
        out.append("the SIGKILLed first child left no trace in "
                   "attempt_errors — a lost attempt must be recorded, "
                   "not hidden")
    return out


def _check_warmup_healed(r):
    out = []
    rep = r.get("trailing")
    if rep is None:
        return ["warmup printed no summary line"]
    if rep.get("n_errors", 1) != 0:
        out.append(
            f"warmup reported {rep.get('n_errors')} errors over a corrupt "
            "cache — self-heal (evict + recompile) did not hold"
        )
    if rep.get("value", 0) <= 0:
        out.append("warmup compiled no manifest entries")
    return out


def _bench_scenarios():
    return [
        Scenario(
            "r5-hang-mid-compile-window", "bench-child",
            FaultPlan("r5-hang", seed=10, faults=(
                # the r5 wound: the window dies right after the headline,
                # mid "compile the next leg"
                Fault(point="bench.row", action="sleep", seconds=600.0,
                      role="child"),
            )),
            _check_bench_partial,
            notes="THE r5 reproduction: child loses the window after the "
                  "headline; the deadline guard lands a partial with the "
                  "measured headline instead of an empty record",
            env={"CSMOM_BENCH_CHILD_BUDGET": "150"},
        ),
        Scenario(
            "expire-deadline-mid-row", "bench-child",
            FaultPlan("expire-deadline-mid-row", seed=11, faults=(
                Fault(point="bench.row", action="trip_deadline",
                      role="child"),
            )),
            _check_bench_partial,
            notes="deadline expiry between measured rows on the real "
                  "child — instant form of the r5 rehearsal",
            env={"CSMOM_BENCH_CHILD_BUDGET": "600"},
        ),
        Scenario(
            "kill-child-mid-compile", "bench",
            FaultPlan("kill-child-mid-compile", seed=12, faults=(
                Fault(point="bench.compile", action="kill", role="child",
                      global_once=True),
            )),
            _check_kill_fallback,
            notes="supervisor's cap SIGKILLs the first child mid-compile; "
                  "the fallback child still secures a measured record",
        ),
        Scenario(
            "probe-outage", "bench",
            FaultPlan("probe-outage", seed=13, faults=(
                Fault(point="bench.probe", action="fail",
                      role="supervisor", max_fires=0),
            )),
            _check_bench_fallback_measured,
            notes="every tunnel probe fails; the CPU fallback secures the "
                  "record and the probes are recorded, not hidden",
            budget_s=480,  # small enough that the probe/sleep loop yields
                           # to the reporting reserve right after fallback
        ),
        Scenario(
            "enospc-on-land", "bench",
            FaultPlan("enospc-on-land", seed=14, faults=(
                Fault(point="bench.land", action="raise_oserror",
                      role="supervisor", errno_=28),
            )),
            _check_bench_supervisor_landed,
            notes="full-record write hits ENOSPC; the headline still "
                  "prints, carrying the write failure as a reason",
        ),
        Scenario(
            "corrupt-aot-cache", "warmup",
            FaultPlan("corrupt-aot-cache", seed=15, faults=(
                Fault(point="warmup.entry", action="corrupt_file",
                      path="$CSMOM_JIT_CACHE/*", max_fires=1),
            )),
            _check_warmup_healed,
            notes="serialized-executable cache corrupted on disk; warmup "
                  "evicts + recompiles (self-heal) instead of crashing",
        ),
        Scenario(
            "clock-skew-mid-child", "bench-child",
            FaultPlan("clock-skew-mid-child", seed=16, faults=(
                Fault(point="bench.compile", action="clock_skew",
                      seconds=3600.0, role="child"),
            )),
            _check_bench_child_full,
            notes="NTP-step wall-clock jump inside the child; the "
                  "monotonic deadline holds and the full record lands",
            env={"CSMOM_BENCH_CHILD_BUDGET": "600"},
        ),
    ]


def _check_bench_child_full(r):
    out = list(r["headline_violations"])
    obj = r["trailing"]
    if obj is None:
        return out + ["no trailing JSON line"]
    if inv.is_partial(obj):
        out.append("clock skew shortened the monotonic deadline — the "
                   "run was cut into a partial")
    if r["rc"] != 0:
        out.append(f"rc={r['rc']}")
    return out


def builtin_matrix(fast: bool = False):
    mats = (_mini_scenarios() + _shell_scenarios() + _serve_scenarios()
            + _serve_pool_scenarios() + _serve_fabric_scenarios()
            + _replay_scenarios())
    if not fast:
        mats += _bench_scenarios()
    else:
        mats = [s for s in mats if s.fast]
    return mats


# -------------------------------------------------------------- runners ----

def _land_with_capture_lib(raw_path: str, art_path: str, env=None) -> None:
    script = (
        "log() { echo \"[capture_lib] $*\" >&2; }; "
        f"source '{_CAPTURE_LIB}'; "
        f"land_artifact '{raw_path}' '{art_path}'"
    )
    subprocess.run(["bash", "-c", script], check=False,
                   env={**os.environ, **(env or {})},
                   capture_output=True, text=True)


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _base_env(scenario, box: str) -> dict:
    env = dict(os.environ)
    env.pop("CSMOM_FAULT_STATE", None)
    # a rehearsed process must not append to the REHEARSAL's own telemetry
    # stream (its run is the scenario's, not ours); bench-supervisor
    # scenarios re-arm themselves with a fresh stream in their sandbox
    env.pop("CSMOM_TELEMETRY", None)
    env.pop("CSMOM_TELEMETRY_RUN", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "CSMOM_FAULT_STATE": os.path.join(box, "chaos-state"),
        "PYTHONPATH": _REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    if scenario.plan is not None:
        plan_path = os.path.join(box, "plan.toml")
        with open(plan_path, "w") as f:
            f.write(scenario.plan.to_toml())
        env["CSMOM_FAULT_PLAN"] = plan_path
    env.update(scenario.env)
    return env


def _run_mini(scenario, box: str) -> dict:
    sidecar = os.path.join(box, "sidecar.jsonl")
    env = _base_env(scenario, box)
    env.setdefault("CSMOM_MINIBENCH_BUDGET", "60")
    env.update({
        "CSMOM_MINIBENCH_ROWS": str(scenario.rows),
        "CSMOM_MINIBENCH_SIDECAR": sidecar,
    })
    p = subprocess.run(
        [sys.executable, "-m", "csmom_tpu.chaos.minibench"],
        capture_output=True, text=True, timeout=120, env=env, cwd=box,
    )
    raw = os.path.join(box, "raw.out")
    with open(raw, "w") as f:
        f.write(p.stdout)
    art = os.path.join(box, "ARTIFACT.json")
    _land_with_capture_lib(raw, art)
    sidecar_rows = 0
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            sidecar_rows = sum(1 for ln in f if ln.strip())
    trailing = inv.trailing_json(p.stdout)
    return {
        "rc": p.returncode,
        "stdout": p.stdout,
        "stderr": p.stderr,
        "trailing": trailing,
        "headline_violations": (
            inv.validate_headline_text(p.stdout) if trailing else []
        ),
        "sidecar_rows": sidecar_rows,
        "artifact": _read_json(art),
    }


def _run_shell(scenario, box: str) -> dict:
    # a known-good raw capture, landed twice: once under the short-write
    # fault (must refuse), once clean (must land)
    full = {"metric": "m", "value": 3.0, "unit": "u", "vs_baseline": 1.0,
            "extra": {"rows": [{"r": 0}, {"r": 1}]}}
    prior = {"metric": "m", "value": 2.0, "unit": "u", "vs_baseline": 1.0,
             "extra": {"partial": "one row measured", "rows": [{"r": 0}]}}
    raw = os.path.join(box, "raw.out")
    with open(raw, "w") as f:
        f.write("progress line\n" + json.dumps(full) + "\n")
    art = os.path.join(box, "ARTIFACT.json")
    prior_path = os.path.join(box, "PRIOR.json")
    with open(prior_path, "w") as f:
        json.dump(prior, f)
    # faulted landing over an empty slot must not land garbage
    _land_with_capture_lib(raw, art,
                           env={"CSMOM_FAULT_LAND_TRUNCATE_BYTES": "20"})
    landed_faulted = _read_json(art)
    # faulted landing over an existing partial must leave it intact
    _land_with_capture_lib(raw, prior_path,
                           env={"CSMOM_FAULT_LAND_TRUNCATE_BYTES": "20"})
    prior_after = _read_json(prior_path)
    # clean retry lands
    _land_with_capture_lib(raw, art)
    return {
        "rc": 0,
        "stdout": "",
        "stderr": "",
        "trailing": full,
        "headline_violations": [],
        "sidecar_rows": 0,
        "artifact": landed_faulted,
        "prior_intact": prior_after == prior,
        "retry_artifact": _read_json(art),
    }


def _run_bench_child(scenario, box: str) -> dict:
    env = _base_env(scenario, box)
    env.update({
        "CSMOM_BENCH_CHILD": "1",
        "CSMOM_BENCH_FORCE_CPU": "1",
        "CSMOM_BENCH_SMOKE": "1",
    })
    env.setdefault("CSMOM_BENCH_CHILD_BUDGET", "300")
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        capture_output=True, text=True, env=env, cwd=box,
        timeout=float(env["CSMOM_BENCH_CHILD_BUDGET"]) + 60,
    )
    trailing = inv.trailing_json(p.stdout)
    return {
        "rc": p.returncode,
        "stdout": p.stdout,
        "stderr": p.stderr,
        "trailing": trailing,
        # the SUPERVISOR parses a child's line (no driver tail window —
        # it builds the bounded headline itself), so a direct child run
        # validates record schema only, not the 2,000-char cap
        "headline_violations": (
            inv.validate(trailing, "record") if trailing else []
        ),
        "sidecar_rows": 0,
    }


def _run_bench_supervisor(scenario, box: str) -> dict:
    env = _base_env(scenario, box)
    env.update({
        "CSMOM_BENCH_SMOKE": "1",
        "CSMOM_BENCH_FULL_DIR": box,
        "CSMOM_ROUND": "rehearse",
        "CSMOM_BENCH_BUDGET": str(scenario.budget_s or 600),
    })
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        capture_output=True, text=True, env=env, cwd=box,
        timeout=(scenario.budget_s or 600) + 120,
    )
    trailing = inv.trailing_json(p.stdout)
    return {
        "rc": p.returncode,
        "stdout": p.stdout,
        "stderr": p.stderr,
        "trailing": trailing,
        "headline_violations": (
            inv.validate_headline_text(p.stdout) if trailing else []
        ),
        "sidecar_rows": 0,
        "full_record": _read_json(
            os.path.join(box, "BENCH_FULL_rehearse.json")
        ),
    }


def _run_warmup(scenario, box: str) -> dict:
    env = _base_env(scenario, box)
    cache = os.path.join(box, "jit-cache")
    env["CSMOM_JIT_CACHE"] = cache
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import json;"
        "from csmom_tpu.compile.aot import warmup;"
        "rep = warmup(profiles=('smoke',), subdir='rehearse',"
        "             include_golden_event=False, write_report=False);"
        "print(json.dumps({'metric': 'aot_warmup', 'value': rep['n_entries'],"
        "                  'unit': 'entries', 'vs_baseline': 1.0,"
        "                  'n_errors': rep['n_errors'],"
        "                  'n_cache_hits': rep['n_cache_hits']}))"
    )
    # pass 1: populate the cache, fault-free
    clean = {k: v for k, v in env.items() if k != "CSMOM_FAULT_PLAN"}
    p0 = subprocess.run([sys.executable, "-c", code], capture_output=True,
                        text=True, env=clean, cwd=box, timeout=600)
    # pass 2: the armed fault corrupts every cached executable before the
    # first entry compiles; self-heal must evict + recompile
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, cwd=box, timeout=600)
    trailing = inv.trailing_json(p.stdout)
    out = {
        "rc": p.returncode,
        "stdout": p.stdout,
        "stderr": p.stderr,
        "trailing": trailing,
        "headline_violations": [],
        "sidecar_rows": 0,
    }
    if p0.returncode != 0:
        out["headline_violations"] = [
            f"fault-free warmup pass failed rc={p0.returncode}: "
            f"{p0.stderr[-300:]}"
        ]
    return out


def _run_serve(scenario, box: str) -> dict:
    """Drive the signal service IN-PROCESS (stub engine, smoke buckets).

    The serve subsystem is thread-based by design and the rehearsed
    faults are result faults (``fail``) and delays (``sleep``), not
    process faults — so the scenario runs inside the rehearsal process:
    no subprocess, no jax, which is what keeps the fast tier's wall
    inside its 30 s budget with the two serve scenarios aboard.
    ``scenario.env`` here carries runner kwargs (``serve`` -> ServeConfig
    overrides, ``load`` -> LoadConfig overrides), not OS env vars.
    """
    from csmom_tpu.chaos import inject
    from csmom_tpu.serve.loadgen import (
        LoadConfig,
        run_loadgen,
        write_artifact,
    )
    from csmom_tpu.serve.service import ServeConfig, SignalService

    saved = {k: os.environ.get(k) for k in (PLAN_ENV, "CSMOM_FAULT_STATE")}
    try:
        if scenario.plan is not None:
            plan_path = os.path.join(box, "plan.toml")
            with open(plan_path, "w") as f:
                f.write(scenario.plan.to_toml())
            os.environ[PLAN_ENV] = plan_path
        os.environ["CSMOM_FAULT_STATE"] = os.path.join(box, "chaos-state")
        inject.reset()  # re-read the scenario's plan, fresh hit counters
        svc = SignalService(ServeConfig(
            profile="serve-smoke", engine="stub",
            **scenario.env.get("serve", {}))).start()
        load = LoadConfig(run_id=f"rehearse_{scenario.name}",
                          **scenario.env.get("load", {}))
        art = run_loadgen(svc, load)
        write_artifact(box, art)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        inject.reset()  # the next scenario must not inherit this plan
    return {
        "rc": 0,
        "stdout": "",
        "stderr": "",
        "trailing": art,
        "headline_violations": [],
        "sidecar_rows": 0,
        "artifact": art,
    }


def _run_serve_pool(scenario, box: str) -> dict:
    """Drive the MULTI-PROCESS pool: stub-engine worker subprocesses
    behind the real supervisor + router (serve-smoke buckets, no jax in
    any process — the fast tier stays jax-free).

    The fault plan arms via the environment so the worker PROCESSES
    inherit it (the ``kill`` at ``serve.dispatch`` is a real process
    death); ``scenario.env`` carries runner kwargs: ``mode``
    (kill | roll | skew), ``pool`` -> PoolConfig overrides, ``load`` ->
    LoadConfig overrides.
    """
    from csmom_tpu.chaos import inject
    from csmom_tpu.obs import fleet as obs_fleet
    from csmom_tpu.obs import trace as obs_trace
    from csmom_tpu.serve.loadgen import (
        LoadConfig,
        run_pool_loadgen,
        write_artifact,
    )
    from csmom_tpu.serve.router import Router, RouterConfig
    from csmom_tpu.serve.supervisor import PoolConfig, PoolSupervisor
    from csmom_tpu.utils.deadline import mono_now_s

    mode = scenario.env.get("mode", "load")
    saved = {k: os.environ.get(k) for k in (PLAN_ENV, "CSMOM_FAULT_STATE")}
    sup = None
    trace_book = (obs_trace.arm_tracing(seed=scenario.plan.seed
                                        if scenario.plan else 0)
                  if scenario.env.get("trace") else None)
    # fleet capture arms BEFORE the supervisor exists so the worker
    # processes inherit the env contract at spawn (ISSUE 19)
    fleet_agg = (obs_fleet.arm(f"rehearse_{scenario.name}",
                               scratch_dir=box)
                 if scenario.env.get("fleet") else None)
    result: dict = {"rc": 0, "stdout": "", "stderr": "",
                    "trailing": None, "headline_violations": [],
                    "sidecar_rows": 0}
    try:
        if scenario.plan is not None:
            plan_path = os.path.join(box, "plan.toml")
            with open(plan_path, "w") as f:
                f.write(scenario.plan.to_toml())
            os.environ[PLAN_ENV] = plan_path
        else:
            os.environ.pop(PLAN_ENV, None)
        os.environ["CSMOM_FAULT_STATE"] = os.path.join(box, "chaos-state")
        inject.reset()
        cfg = PoolConfig(
            profile="serve-smoke", engine="stub",
            backoff_base_s=0.05, backoff_cap_s=0.5, ready_timeout_s=30.0,
            **({"expect_cache_version": "skewed-deadbeef"}
               if mode == "skew" else {}),
            **scenario.env.get("pool", {}))
        sup = PoolSupervisor(cfg, box)
        if mode == "skew":
            try:
                sup.start()
                started = True
            except RuntimeError:
                started = False
            h = sup.handles[0]
            result["skew"] = {
                "started": started,
                "state": h.state,
                "reason": h.reason or "",
                "restarts": h.restarts,
            }
            return result
        sup.start()
        spares = int(scenario.env.get("spares", 0) or 0)
        if spares:
            # the elastic tier (ISSUE 20): hot spares parked out of the
            # ring; in pool mode a promotion propagates the instant the
            # handle swaps (the router reads ready_workers live)
            from csmom_tpu.serve.fleet import FleetConfig, FleetController

            FleetController(
                sup, FleetConfig(spares=spares,
                                 min_workers=cfg.n_workers,
                                 max_workers=cfg.n_workers + 2),
                aggregator=fleet_agg).start()
        load_over = dict(scenario.env.get("load", {}))
        deadline = load_over.pop("deadline_s", 3.0)
        router = Router(sup.ready_workers, RouterConfig(
            profile="serve-smoke", default_deadline_s=deadline))
        load = LoadConfig(run_id=f"rehearse_{scenario.name}",
                          deadline_s=deadline, **load_over)
        if fleet_agg is not None:
            # the pool path runs no self-probes through the router, so
            # the demand window opens at the measured load's doorstep
            obs_fleet.open_demand_window()
        t_load0 = mono_now_s()
        if mode == "roll":
            roll_box: dict = {}

            def _roll():
                time.sleep(0.2)  # let the load stream establish first
                roll_box["roll"] = sup.rolling_restart()

            # books close only after load AND roll settle (the
            # `concurrent` contract), so the artifact's fleet stats see
            # the post-roll generation, not a mid-roll race
            art = run_pool_loadgen(router, sup, load, concurrent=_roll)
            result["roll"] = roll_box.get("roll")
        else:
            conc = None
            if scenario.env.get("wait_respawn"):
                # the artifact must be built from a fleet where the
                # killed worker's replacement already respawned (its
                # spawn event is the re-pin evidence the check reads) —
                # run_pool_loadgen's `concurrent` contract settles it
                def conc():
                    give_up = time.monotonic() + 15.0
                    while time.monotonic() < give_up:
                        if any(h.generation >= 1 and h.state == "ready"
                               for h in sup.handles):
                            return
                        time.sleep(0.05)

            art = run_pool_loadgen(router, sup, load, concurrent=conc)
        if art is not None:
            write_artifact(box, art, prefix="SERVE_POOL")
            if trace_book is not None:
                # land the stitched trace evidence next to the pool
                # artifact, the same reconciliation the committed
                # TRACE_rNN.json family carries
                result["trace_book_violations"] = \
                    trace_book.invariant_violations()
                tart = obs_trace.build_artifact(
                    trace_book, load.run_id,
                    requests={k: art["requests"][k]
                              for k in ("admitted", "served", "rejected",
                                        "expired")},
                    fresh_compiles=(art.get("compile") or {}).get(
                        "in_window_fresh_compiles"),
                    platform=(art.get("extra") or {}).get("platform"),
                    workload=(art.get("extra") or {}).get("workload"),
                )
                write_artifact(box, tart, prefix="TRACE")
                result["trace_artifact"] = tart
            if fleet_agg is not None:
                # drain-stop the pool NOW so every surviving worker's
                # emitter fins before the books freeze — the SIGKILLed
                # generation's severed close reason is already booked,
                # and Channel.request is a synchronous round-trip so
                # stop() returning implies the fins are ingested
                sup.stop()
                obs_fleet.disarm_emitter("loadgen finished")
                fleet_agg.close_all("run-end")
                fart = obs_fleet.build_artifact(
                    fleet_agg, load.run_id,
                    requests={k: art["requests"][k]
                              for k in ("admitted", "served", "rejected",
                                        "expired")},
                    worker_events=obs_fleet.absolute_events(
                        sup.summary()["events"], sup.t0_mono_s),
                    n_workers=cfg.n_workers,
                    window=(t_load0, t_load0 + art["wall_s"]),
                    fresh_compiles=(art.get("compile") or {}).get(
                        "in_window_fresh_compiles"),
                    platform=(art.get("extra") or {}).get("platform"),
                    workload=(art.get("extra") or {}).get("workload"),
                    elastic=(sup.fleet.summary()
                             if getattr(sup, "fleet", None) is not None
                             else None),
                )
                write_artifact(box, fart, prefix="FLEET")
                result["fleet_artifact"] = fart
        result["trailing"] = art
        result["artifact"] = art
        return result
    finally:
        if trace_book is not None:
            obs_trace.disarm_tracing()
        if sup is not None:
            sup.stop()
        if fleet_agg is not None:
            # idempotent after the success path's own disarm: fin the
            # local emitter, close any still-open books, retract the env
            # contract so the NEXT scenario's spawns stay disarmed
            obs_fleet.disarm("rehearse-end")
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        inject.reset()  # the next scenario must not inherit this plan


def _run_serve_fabric(scenario, box: str) -> dict:
    """Drive the THREE-TIER fabric: stub-engine worker processes, real
    supervised router-replica processes, and the FabricClient in this
    process (serve-smoke buckets, no jax anywhere).

    Network fault plans arm in the ROUTER TIER ONLY (via the router
    supervisor's ``extra_env``): the replicas are the processes that
    dial workers at ``serve.transport``, and the rehearse process's own
    client dials must not fire the fault.  ``scenario.env`` carries
    runner kwargs: ``transport`` (unix | tcp), ``routers``, ``pool`` ->
    worker PoolConfig overrides, ``hedge_fraction``, ``chaos_env`` ->
    extra router-tier environment (fault duration knobs), ``kill`` ->
    {router_after, worker_after} mid-burst SIGKILLs, ``load`` ->
    LoadConfig overrides.
    """
    from csmom_tpu.serve.fabric import (
        build_fabric,
        kill_mid_burst,
        stop_fabric,
    )
    from csmom_tpu.serve.loadgen import (
        LoadConfig,
        run_fabric_loadgen,
        write_artifact,
    )
    from csmom_tpu.serve.supervisor import PoolConfig

    result: dict = {"rc": 0, "stdout": "", "stderr": "",
                    "trailing": None, "headline_violations": [],
                    "sidecar_rows": 0}
    wsup = rsup = publisher = None
    try:
        transport = scenario.env.get("transport", "unix")
        smoke = dict(profile="serve-smoke", engine="stub",
                     transport=transport, backoff_base_s=0.05,
                     backoff_cap_s=0.5, ready_timeout_s=30.0)
        wcfg = PoolConfig(**{**smoke, **scenario.env.get("pool", {})})
        rcfg = PoolConfig(n_workers=scenario.env.get("routers", 2),
                          **smoke)
        load_over = dict(scenario.env.get("load", {}))
        deadline = load_over.pop("deadline_s", 3.0)

        def arm_router_tier(rsup):
            # fault plans arm in the ROUTER TIER ONLY: the replicas are
            # the processes that dial workers at serve.transport
            if scenario.plan is not None:
                plan_path = os.path.join(box, "plan.toml")
                with open(plan_path, "w") as f:
                    f.write(scenario.plan.to_toml())
                rsup.extra_env[PLAN_ENV] = plan_path
                rsup.extra_env["CSMOM_FAULT_STATE"] = os.path.join(
                    box, "chaos-state")
            rsup.extra_env.update(scenario.env.get("chaos_env", {}))

        wsup, publisher, rsup, client = build_fabric(
            wcfg, rcfg, box,
            deadline_ms=deadline * 1e3,
            hedge_fraction=scenario.env.get("hedge_fraction", 0.35),
            client_deadline_s=deadline,
            configure_router=arm_router_tier)
        load = LoadConfig(run_id=f"rehearse_{scenario.name}",
                          deadline_s=deadline, **load_over)

        kill = scenario.env.get("kill") or {}
        conc = None
        if kill:
            def conc():
                # books are built only from a SETTLED fleet: both
                # victims' replacements must demonstrate ready first
                if not kill_mid_burst(
                        [(kill.get("router_after"), rsup, "router"),
                         (kill.get("worker_after"), wsup, "worker")],
                        settle_timeout_s=30.0):
                    raise RuntimeError(
                        "a killed tier never re-demonstrated ready — "
                        "the scenario's books would come from an "
                        "unsettled fleet")

        art = run_fabric_loadgen(client, rsup, wsup, load,
                                 concurrent=conc)
        write_artifact(box, art, prefix="SERVE_FABRIC")
        result["artifact"] = art
        result["trailing"] = art
        return result
    finally:
        stop_fabric(publisher, rsup, wsup)


def _run_replay(scenario, box: str) -> dict:
    """Drive the event-time replay IN-PROCESS (stub engine, smoke
    buckets, no jax — the fast tier stays jax-free).  The fault plan
    arms via the env contract so the ``stream.*`` checkpoints fire with
    fresh per-scenario hit counters; ``scenario.env`` may carry a
    ``replay`` dict of ReplayConfig overrides."""
    from csmom_tpu.chaos import inject
    from csmom_tpu.stream.replay import (
        ReplayConfig,
        run_replay,
        write_artifact,
    )

    saved = {k: os.environ.get(k) for k in (PLAN_ENV, "CSMOM_FAULT_STATE")}
    try:
        if scenario.plan is not None:
            plan_path = os.path.join(box, "plan.toml")
            with open(plan_path, "w") as f:
                f.write(scenario.plan.to_toml())
            os.environ[PLAN_ENV] = plan_path
        else:
            os.environ.pop(PLAN_ENV, None)
        os.environ["CSMOM_FAULT_STATE"] = os.path.join(box, "chaos-state")
        inject.reset()
        cfg = ReplayConfig(run_id=f"rehearse_{scenario.name}",
                           engine="stub", profile="serve-smoke",
                           **scenario.env.get("replay", {}))
        art = run_replay(cfg)
        write_artifact(box, art, prefix="REPLAY")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        inject.reset()  # the next scenario must not inherit this plan
    return {
        "rc": 0,
        "stdout": "",
        "stderr": "",
        "trailing": art,
        "headline_violations": [],
        "sidecar_rows": 0,
        "artifact": art,
    }


_RUNNERS = {
    "mini": _run_mini,
    "shell": _run_shell,
    "bench-child": _run_bench_child,
    "bench": _run_bench_supervisor,
    "warmup": _run_warmup,
    "serve": _run_serve,
    "serve-pool": _run_serve_pool,
    "serve-fabric": _run_serve_fabric,
    "replay": _run_replay,
}


# ------------------------------------------------------------------ cmd ----

def _run_scenario(scenario, sandbox_root: str) -> tuple:
    box = os.path.join(sandbox_root, scenario.name)
    os.makedirs(box, exist_ok=True)
    t0 = time.monotonic()
    try:
        result = _RUNNERS[scenario.pipeline](scenario, box)
        violations = scenario.check(result)
    except subprocess.TimeoutExpired as e:
        violations = [f"scenario runner timed out after {e.timeout:.0f}s"]
        result = {}
    wall = time.monotonic() - t0
    return result, violations, wall


# the generic invariant of a custom plan on each pipeline: the outcome may
# be full OR partial, but a schema-valid line must land with zero lost rows
def _check_custom_generic(r):
    out = list(r["headline_violations"])
    obj = r["trailing"]
    if obj is None:
        return out + ["no parseable trailing JSON line — the fault lost "
                      "the measurements"]
    if r["sidecar_rows"] and _rows_of(obj) != r["sidecar_rows"]:
        out.append(
            f"lost measured rows: sidecar has {r['sidecar_rows']}, landed "
            f"line has {_rows_of(obj)}"
        )
    return out


def _check_serve_generic(r):
    # whatever the custom fault did, the landed SERVE artifact must be
    # schema-valid — which INCLUDES balanced request books and zero
    # expired-but-dispatched requests (the serve kind's core invariants)
    return inv.validate(r.get("artifact") or {}, "serve")


def _check_serve_pool_generic(r):
    # same rule one tier up: the pool artifact's schema IS the closed
    # cross-process book plus the hedging arithmetic
    return inv.validate(r.get("artifact") or {}, "serve_pool")


def _check_serve_fabric_generic(r):
    # and one tier further out: the fabric artifact's schema IS the
    # closed CLIENT-tier book plus replication, cache, and hedge rules
    return inv.validate(r.get("artifact") or {}, "serve_fabric")


def _check_replay_generic(r):
    # whatever the custom fault did, the landed REPLAY artifact must be
    # schema-valid — which INCLUDES the closed tick ledger, the closed
    # serve book, and the version reconciliation (the replay kind's
    # core invariants)
    return inv.validate(r.get("artifact") or {}, "replay")


_CUSTOM_CHECKS = {
    "mini": _check_custom_generic,
    "bench-child": _check_custom_generic,
    "bench": _check_bench_supervisor_landed,
    "warmup": _check_warmup_healed,
    "serve": _check_serve_generic,
    "serve-pool": _check_serve_pool_generic,
    "serve-fabric": _check_serve_fabric_generic,
    "replay": _check_replay_generic,
}


def _lint_gate() -> list:
    """The static-analysis gate (ISSUE 11 + 12): the unsuppressed
    findings of a full `csmom lint --project` sweep — per-file rules AND
    the whole-program set (lock-order cycles, helper-hidden blocking
    calls, compile-surface coverage).  ``cmd_rehearse`` refuses to start
    on a non-empty result — a deadlock or an unwarmed dispatchable shape
    a CPU AST pass can catch must never reach (let alone burn) a tunnel
    window.  The incremental cache makes the repeat gate nearly free."""
    from csmom_tpu.analysis import run_lint

    return run_lint(project=True).findings


def cmd_rehearse(args) -> int:
    """Rehearse the capture pipeline under deterministic fault injection."""
    if not getattr(args, "list", False):
        findings = _lint_gate()
        if findings:
            print(f"refusing to rehearse: `csmom lint` reports "
                  f"{len(findings)} finding(s) — a dirty tree must not "
                  "reach a tunnel window", file=sys.stderr)
            for f in findings[:20]:
                print(f"  {f}", file=sys.stderr)
            if len(findings) > 20:
                print(f"  ... and {len(findings) - 20} more "
                      "(run `csmom lint`)", file=sys.stderr)
            return 1
    if getattr(args, "plan", None):
        if args.pipeline not in _CUSTOM_CHECKS:
            print(
                f"--pipeline {args.pipeline} does not take a custom plan "
                "(its faults are CSMOM_FAULT_* env-var driven, not "
                "checkpoint-based); use one of "
                f"{', '.join(sorted(_CUSTOM_CHECKS))}",
                file=sys.stderr,
            )
            return 2
        plan = FaultPlan.from_env_value(args.plan)
        custom_plan = True
        matrix = [Scenario(
            plan.name or "custom-plan", args.pipeline, plan,
            _CUSTOM_CHECKS[args.pipeline],
            notes="custom plan (generic invariants: a schema-valid line "
                  "lands, full or explicitly partial, zero lost rows)",
        )]
    else:
        custom_plan = False
        matrix = builtin_matrix(fast=args.fast)
    if getattr(args, "only", None):
        matrix = [s for s in matrix if args.only in s.name]
        if not matrix:
            print(f"no scenario matches --only {args.only!r}",
                  file=sys.stderr)
            return 2
    if getattr(args, "list", False):
        # the scenario matrix, runnable nothing: name, pipeline, tier,
        # the armed plan's fault summary, and the intent line — enough
        # to pick an --only target without reading the source
        for s in matrix:
            tier = "fast" if s.fast else "full"
            print(f"{s.name:32s} {s.pipeline:12s} [{tier}] {s.notes}")
            print(f"{'':32s} {'':12s}        plan: {_plan_summary(s.plan)}")
        return 0

    sandbox_root = args.sandbox or tempfile.mkdtemp(prefix="csmom-rehearse-")
    os.makedirs(sandbox_root, exist_ok=True)
    print(f"rehearsing {len(matrix)} fault scenario(s) in {sandbox_root} "
          f"({'fast tier' if args.fast else 'full matrix'})\n")

    # run telemetry (csmom_tpu.obs): the rehearsal is itself a run — each
    # scenario is a measured row, and the sidecar answers "which scenario
    # ate the wall" the same way bench's answers "which leg did"
    from csmom_tpu import obs
    from csmom_tpu.obs import metrics as obs_metrics
    from csmom_tpu.obs import timeline as obs_tl

    # distinct run ids per flavor so a custom-plan rehearsal can never
    # land over the built-in matrix's sidecar name; the arming decision
    # itself is the shared obs.spans.arm_policy (operator env honored,
    # sandbox stream as the default-ON fallback)
    run_id = ("rehearse_custom" if custom_plan
              else "rehearse_fast" if args.fast else "rehearse")
    # operator-armed (env contract) runs carry a FOREIGN run id, so their
    # sidecar must not overwrite an existing file of that name (e.g. a
    # committed round sidecar); our own default names overwrite freely
    operator_armed = os.environ.get(obs.spans.ENV_STREAM,
                                    "") not in ("", "0")
    col = obs.arm_policy(
        "rehearse",
        default_path=os.path.join(sandbox_root, "telemetry_events.jsonl"),
        run_id=run_id,
    )
    telemetry_on = col is not None
    if telemetry_on:
        run_id = col.run_id

    # register both counters up front so a green run snapshots an
    # explicit failures=0 — "no failures" must be distinguishable from
    # "failure counting not wired" (the counters-read-0 ambiguity this
    # layer exists to remove)
    obs_metrics.counter("rehearse.scenarios")
    obs_metrics.counter("rehearse.failures")
    failures = 0
    rows = []
    with obs.span("rehearse.run", root=True, scenarios=len(matrix)):
        for scenario in matrix:
            with obs.span("rehearse.row", phase="row",
                          scenario=scenario.name,
                          pipeline=scenario.pipeline) as sp:
                result, violations, wall = _run_scenario(
                    scenario, sandbox_root)
                sp.set(ok=not violations)  # before the span record emits
            ok = not violations
            obs_metrics.counter("rehearse.scenarios").inc()
            if not ok:
                obs_metrics.counter("rehearse.failures").inc()
            failures += 0 if ok else 1
            rows.append((scenario, ok, wall, violations))
            status = "PASS" if ok else "FAIL"
            print(f"  [{status}] {scenario.name:32s} ({scenario.pipeline}, "
                  f"{wall:5.1f}s)")
            for v in violations:
                print(f"         - {v}")
            if not ok and args.verbose and result.get("stderr"):
                print("         stderr tail:",
                      result["stderr"][-400:].replace("\n", "\n           "))

    if telemetry_on:
        # scratch sidecars land in the run-scoped scratch directory, not
        # the cwd: a rehearse run launched from the repo root must never
        # strew TELEMETRY_rehearse*.json next to committed round
        # evidence (three once sat there).  `csmom timeline` searches
        # the scratch dir, so the render pointer below still resolves.
        out_dir = obs_tl.scratch_dir()
        sidecar = obs_tl.finish_and_write(
            out_dir,
            fallback_metrics=obs_metrics.snapshot(),
            overwrite=not operator_armed,
        )
        loc = (os.path.join(out_dir, sidecar)
               if sidecar.endswith(".json") else sidecar)
        print(f"\ntelemetry: {loc} (render with `csmom timeline "
              f"{run_id}`)")

    print(f"\n{len(matrix) - failures}/{len(matrix)} scenarios green")
    if failures:
        print("rehearsal FAILED: the capture pipeline would lose evidence "
              "under at least one rehearsed fault — fix before a window",
              file=sys.stderr)
    if not args.keep and not args.sandbox and not failures:
        shutil.rmtree(sandbox_root, ignore_errors=True)
    elif failures:
        print(f"sandbox kept for inspection: {sandbox_root}")
    return 1 if failures else 0


def register(sub) -> None:
    """Attach the ``rehearse`` subparser (called from cli.main)."""
    sp = sub.add_parser(
        "rehearse",
        help="rehearse the capture pipeline under deterministic fault "
             "injection (run before every tunnel window)",
    )
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 subset: capture-path faults only (<30 s, "
                         "no jax) — what the watcher gates on")
    sp.add_argument("--plan", metavar="TOML",
                    help="run a custom fault plan (path or inline TOML) "
                         "instead of the built-in matrix")
    sp.add_argument("--pipeline", default="mini",
                    choices=sorted(_RUNNERS),
                    help="pipeline a custom --plan drives (default mini)")
    sp.add_argument("--only", metavar="SUBSTR",
                    help="run only matrix scenarios whose name contains "
                         "SUBSTR")
    sp.add_argument("--list", action="store_true",
                    help="print the scenario matrix without running it")
    sp.add_argument("--sandbox", metavar="DIR",
                    help="run in DIR instead of a fresh tmpdir (kept)")
    sp.add_argument("--keep", action="store_true",
                    help="keep the sandbox even when green")
    sp.add_argument("--verbose", action="store_true",
                    help="print stderr tails of failing scenarios")
    sp.set_defaults(fn=cmd_rehearse)
