"""csmom replay — drive a trading day's tick log through the live loop.

Runs the event-time replay harness (:mod:`csmom_tpu.stream.replay`):
synthetic seeded tick log -> watermark ingest -> incremental signal
updates -> serve-under-load from versioned snapshots -> periodic
full-panel reconciliation, and lands a schema-valid ``REPLAY_<run>.json``
(kind ``replay`` in :mod:`csmom_tpu.chaos.invariants`).

Fault injection: ``--chaos builtin`` arms the canonical replay fault
plan (late + out-of-order + duplicate + gap ticks, one ingest-serve
version-skew event); ``--chaos PATH_OR_TOML`` arms a custom plan; a
pre-armed ``CSMOM_FAULT_PLAN`` is honored as-is.  Either way the run
must keep BOTH closed books — tick accounting and serve accounting —
and the version reconciliation, or this command exits nonzero: a replay
whose ledger doesn't balance is not evidence.

Exit is also nonzero when a jax-engine replay reports in-window fresh
compiles: the serve buckets and the ``stream`` reconcile entries are a
closed shape world, and compiling inside the window means the warmup
contract broke (run ``csmom warmup --profiles serve stream`` first;
``--smoke`` warms its own tiny shapes inline).

Registered via ``register(sub)`` like rehearse/serve/ledger (the
cli/main.py split: new subcommands do not grow the monolith).
"""

from __future__ import annotations

import json
import os
import sys

__all__ = ["cmd_replay", "register"]


def _arm_chaos(args, cfg) -> dict | None:
    """Arm the requested fault plan via the env contract; returns the
    saved env state to restore, or None when nothing was armed."""
    from csmom_tpu.chaos import inject
    from csmom_tpu.chaos.plan import PLAN_ENV

    if not args.chaos:
        return None
    saved = {k: os.environ.get(k) for k in (PLAN_ENV, "CSMOM_FAULT_STATE")}
    if args.chaos == "builtin":
        from csmom_tpu.stream.replay import builtin_fault_plan

        plan = builtin_fault_plan(cfg)
        os.environ[PLAN_ENV] = plan.to_toml()
    else:
        os.environ[PLAN_ENV] = args.chaos
    inject.reset()  # re-read the plan with fresh hit counters
    return saved


def _restore_chaos(saved: dict | None) -> None:
    from csmom_tpu.chaos import inject

    if saved is None:
        return
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    inject.reset()


def cmd_replay(args) -> int:
    from csmom_tpu.chaos import invariants as inv
    from csmom_tpu.stream.replay import (
        ReplayConfig,
        run_replay,
        write_artifact,
    )

    smoke = bool(args.smoke)
    engine = "stub" if args.stub else args.engine
    # full-mode preset first, explicit flags override it (merged BEFORE
    # unpacking: two ** expansions sharing a key is a TypeError)
    kw = {} if smoke else {"n_assets": 32, "bars": 96,
                           "serve_every_bars": 6,
                           "reconcile_every_bars": 16}
    if args.assets is not None:
        kw["n_assets"] = args.assets
    if args.bars is not None:
        kw["bars"] = args.bars
    if args.capacity is not None:
        kw["capacity"] = args.capacity
    cfg = ReplayConfig(
        run_id=args.run_id,
        seed=args.seed,
        engine=engine,
        profile="serve-smoke" if smoke else "serve",
        **kw,
    )
    saved = _arm_chaos(args, cfg)
    try:
        art = run_replay(cfg)
    finally:
        _restore_chaos(saved)

    out_dir = args.out_dir or os.getcwd()
    path = write_artifact(out_dir, art, prefix="REPLAY")
    print(f"landed {path}")

    violations = inv.validate(art, "replay")
    t = art["ticks"]
    v = art["versions"]
    print(
        f"ticks: offered {t['offered']} = applied {t['applied']} + "
        f"merged_late {t['merged_late']} + quarantined "
        f"{t['quarantined']} + deduped {t['deduped']} "
        f"(gap bars {art['panel']['gap_bars']}, dup {t['duplicated']}, "
        f"dropped {t['dropped_gap']})"
    )
    print(
        f"versions: ingest v{v['ingest_final']}, served "
        f"[{v['serve_min']}, {v['serve_max']}]; skew: {v['skew_events']} "
        f"event(s), {v['skew_refusals']}/{v['skew_attempts']} stale "
        "request(s) refused"
    )
    print(f"reconcile: {art['reconcile']}")
    fresh = art["compile"]["in_window_fresh_compiles"]
    print(f"throughput: {art['value']} {art['unit']}; in-window fresh "
          f"compiles: {fresh}")
    if isinstance(fresh, int) and fresh > 0:
        violations.append(
            f"{fresh} in-window fresh compile(s): the replay window "
            "dispatched an unwarmed shape — run `csmom warmup --profiles "
            "serve stream` before replaying")
    if violations:
        print("\nREPLAY artifact violates its own invariants:",
              file=sys.stderr)
        for viol in violations:
            print(f"  - {viol}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({"metric": art["metric"], "value": art["value"],
                          "unit": art["unit"],
                          "vs_baseline": art["vs_baseline"]}))
    return 0


def register(sub) -> None:
    """Attach the ``replay`` subparser (called from cli.main)."""
    sp = sub.add_parser(
        "replay",
        help="replay a trading day's tick log through ingest -> "
             "incremental signals -> serve, deterministically and "
             "chaos-injectably; lands REPLAY_<run>.json",
    )
    sp.add_argument("--run-id", dest="run_id", default="smoke",
                    help="artifact run id (rNN names are committable "
                         "round evidence; everything else is scratch)")
    sp.add_argument("--seed", type=int, default=12,
                    help="tick-log + fault seed (default 12)")
    sp.add_argument("--engine", default="jax", choices=["jax", "stub"],
                    help="serve/reconcile backend (default jax)")
    sp.add_argument("--stub", action="store_true",
                    help="shortcut for --engine stub (jax-free)")
    sp.add_argument("--smoke", action="store_true",
                    help="smoke preset: tiny panel, smoke serve buckets, "
                         "sub-second — the tier-1 shape")
    sp.add_argument("--assets", type=int,
                    help="universe size (default: 32 full / 8 smoke)")
    sp.add_argument("--capacity", type=int,
                    help="ring capacity in bars (default: 3/4 of the "
                         "log, floored at the serve window — the ring "
                         "WRAPS by default so the window-slide "
                         "reconcile path is always exercised; pass "
                         "capacity == bars for a non-evicting ring)")
    sp.add_argument("--bars", type=int,
                    help="bars in the day (default: 96 full / 32 smoke)")
    sp.add_argument("--chaos", metavar="PLAN",
                    help="'builtin' for the canonical replay fault plan "
                         "(late/ooo/dup/gap ticks + one version skew), "
                         "or a fault-plan path / inline TOML")
    sp.add_argument("--out-dir", dest="out_dir",
                    help="artifact directory (default: cwd)")
    sp.add_argument("--json", action="store_true",
                    help="also print a record-shaped headline line")
    sp.set_defaults(fn=cmd_replay)
