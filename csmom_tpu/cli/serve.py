"""csmom serve / csmom loadgen — the online workload's entry points.

``csmom serve`` starts the in-process micro-batching signal service
(:mod:`csmom_tpu.serve`): warms every bucket shape, prints the readiness
report (bucket grid, warmup stats), runs a per-endpoint self-probe so
"ready" is a demonstrated claim, then serves until ``--duration``
elapses (0 = until Ctrl-C) and prints the request accounting on the way
out.  With ``--workers N`` it instead runs the MULTI-PROCESS tier: a
supervisor spawns N worker processes (each its own ``SignalService``
behind a unix socket), a router hedges requests across them, and the
self-probe goes through the router — the pool serves through worker
crashes, with rolling restarts available to redeploy without downtime
(see ``csmom_tpu/serve/{router,worker,supervisor,health}.py``).

Readiness is honest about cold caches: with the jax engine, ``csmom
serve`` first checks the on-disk AOT warmup evidence for the selected
bucket profile and exits nonzero pointing at ``csmom warmup --profiles
serve`` when it is missing or stale — warming is a deploy step, not
something to silently pay inside a ready probe (``--allow-cold-cache``
is the explicit opt-out).

``csmom loadgen`` drives an in-process service with the seeded open-loop
generator (:mod:`csmom_tpu.serve.loadgen`) and lands a schema-valid
``SERVE_<run>.json``: throughput, p50/p95/p99 queue+service latency,
batch-size distribution, request accounting, in-window compile count.
``csmom loadgen --pool`` drives the multi-process tier instead and lands
``SERVE_POOL_<run>.json`` (router accounting, availability, hedge rate,
per-worker fresh-compile counts — kind ``serve_pool``).
``--smoke`` is the tier-1 preset: smoke buckets, a sub-second schedule,
the whole admission→coalesce→dispatch pipeline on CPU.  Exit is nonzero
when the artifact fails its own invariants (kind ``serve`` in
:mod:`csmom_tpu.chaos.invariants`) — a loadgen whose books don't balance
must fail loudly, not land evidence.

Registered via ``register(sub)`` like rehearse/timeline/ledger (the
cli/main.py split: new subcommands do not grow the monolith).
"""

from __future__ import annotations

import os
import sys

__all__ = ["cmd_loadgen", "cmd_serve", "register"]


def _engine_name(args, engine_default: str = "jax") -> str:
    mesh = getattr(args, "mesh", False)
    if args.stub:
        if mesh:
            print("warning: --mesh has no effect with --stub (the numpy "
                  "stub has no devices to shard over)", file=sys.stderr)
        return "stub"
    if not mesh and getattr(args, "devices_per_worker", 0) > 0:
        # pinning is read by the jax-mesh engine only: without --mesh
        # the slices are derived and exported but nothing meshes them
        print("warning: --devices-per-worker without --mesh is a no-op "
              "(only the jax-mesh engine builds its mesh from the "
              "pinned slice); add --mesh", file=sys.stderr)
    return "jax-mesh" if mesh else engine_default


def _mk_service(args, engine_default: str = "jax"):
    from csmom_tpu.serve.service import ServeConfig, SignalService

    profile = args.profile or ("serve-smoke" if getattr(args, "smoke", False)
                               else "serve")
    cfg = ServeConfig(
        profile=profile,
        engine=_engine_name(args, engine_default),
        capacity=args.capacity,
        max_wait_s=args.max_wait_ms / 1e3,
        # unset --deadline-ms = the SLO class budgets; 0 = no default
        # deadline; an explicit value wins for every class (r10 mode)
        default_deadline_s=("class" if args.deadline_ms is None
                            else None if args.deadline_ms == 0
                            else args.deadline_ms / 1e3),
    )
    return SignalService(cfg)


def _check_cache_honesty(args, profile: str) -> int:
    """The cold-cache gate: with the jax engine, refuse to 'be ready' by
    compiling — exit 3 with the warmup pointer instead.  Returns 0 when
    serving may proceed."""
    if args.stub or getattr(args, "allow_cold_cache", False):
        return 0
    from csmom_tpu.serve.health import cache_readiness

    mesh_devices = None
    if getattr(args, "mesh", False):
        # the mesh engine's warm evidence is the serve-mesh profile's,
        # keyed by the device count each ENGINE actually meshes: the
        # per-worker slice when the pool pins devices, else every
        # visible device (jax is already this command's backend)
        dpw = getattr(args, "devices_per_worker", 0)
        if getattr(args, "workers", 0) > 0 and dpw > 0:
            mesh_devices = dpw
        else:
            import jax

            mesh_devices = len(jax.devices())
    ready, reason = cache_readiness(profile, mesh_devices=mesh_devices)
    if not ready:
        print(f"NOT READY (cold AOT cache): {reason}", file=sys.stderr)
        print("readiness is a demonstrated claim — compiling inside the "
              "ready probe would fake it; warm first, or pass "
              "--allow-cold-cache to accept the compile pause",
              file=sys.stderr)
        return 3
    print(f"AOT cache check: {reason}")
    return 0


def _mk_pool(args, run_dir: str):
    """Build supervisor + router for pool mode (shared by serve/loadgen)."""
    from csmom_tpu.serve.router import Router, RouterConfig
    from csmom_tpu.serve.supervisor import PoolConfig, PoolSupervisor

    profile = args.profile or ("serve-smoke" if getattr(args, "smoke", False)
                               else "serve")
    engine = _engine_name(args)
    # the pool wire carries per-request deadlines from the router, so
    # the worker-side default keeps plain float semantics (r10 mode)
    pool_deadline_ms = 500.0 if args.deadline_ms is None else args.deadline_ms
    cfg = PoolConfig(
        # --pool without --workers means "a pool": two workers is the
        # smallest fleet hedging can route around
        n_workers=args.workers if args.workers > 0 else 2,
        profile=profile,
        engine=engine,
        capacity=args.capacity,
        max_wait_ms=args.max_wait_ms,
        deadline_ms=pool_deadline_ms,
        devices_per_worker=getattr(args, "devices_per_worker", 0),
        require_warm_cache=(engine.startswith("jax")
                            and not getattr(args, "allow_cold_cache", False)
                            and not getattr(args, "smoke", False)),
    )
    sup = PoolSupervisor(cfg, run_dir).start()
    router = Router(sup.ready_workers, RouterConfig(
        profile=profile,
        default_deadline_s=(None if pool_deadline_ms == 0
                            else pool_deadline_ms / 1e3),
        hedge_fraction=args.hedge_fraction,
    ))
    return sup, router


def _print_pool_ready(sup, router) -> None:
    print(f"serving pool ready: {len(sup.ready_workers())}/"
          f"{sup.config.n_workers} workers (engine {sup.config.engine}, "
          f"profile {sup.config.profile})")
    print(f"  cache version: {sup.expect_cache_version}")
    for h in sup.handles:
        rep = h.ready_report or {}
        # the lifecycle walls are recorded even with fleet capture
        # disarmed (ISSUE 19): spawn→ready, with the worker-reported
        # main→bind and warm legs — the denominator of a kill window
        walls = rep.get("walls") or {}
        wall = (f" ready_wall {h.t_ready_s - h.t_spawned_s:.2f}s"
                f" (bind {walls.get('main_to_bind_s', '—')}s, warm "
                f"{walls.get('warm_s', '—')}s)"
                if h.t_ready_s is not None and h.t_spawned_s is not None
                else "")
        print(f"  {h.worker_id} g{h.generation} [{h.state}] pid "
              f"{h.proc.pid if h.proc else '-'} fresh_compiles "
              f"{rep.get('fresh_compiles')!r}{wall}")
    print(f"  hedging: fraction {router.config.hedge_fraction}, floor "
          f"{router.config.hedge_floor_s * 1e3:g} ms, max attempts "
          f"{router.config.max_attempts}")


def _pool_self_probe(submitter, spec=None) -> list:
    """One probe request per endpoint THROUGH ``submitter`` (the pool's
    router, or a fabric client for the three-tier path) — the tier's
    demonstrated-ready claim.  Returns the failed probes (empty = ok).
    ``spec`` defaults to the submitter's own bucket spec (the router
    carries one; a fabric client does not)."""
    import numpy as np

    from csmom_tpu.registry import serve_endpoints

    spec = spec if spec is not None else submitter.spec
    A = spec.asset_buckets[0]
    rng = np.random.default_rng(0)
    probes = []
    for kind in serve_endpoints():
        v = 100.0 * np.exp(np.cumsum(
            rng.normal(0, 0.03, (A, spec.months)), axis=1))
        probes.append(submitter.submit(kind, v.astype(np.float32),
                                       np.ones((A, spec.months), bool),
                                       deadline_s=10.0))
    for p in probes:
        p.wait(15.0)
    return [p for p in probes if p.state != "served"]


def _cmd_serve_pool(args) -> int:
    """The multi-process tier behind ``csmom serve --workers N``."""
    import tempfile
    import time

    from csmom_tpu.utils.deadline import mono_now_s

    profile = args.profile or "serve"
    if not args.stub:
        rc = _check_cache_honesty(args, profile)
        if rc:
            return rc
    run_dir = tempfile.mkdtemp(prefix="csmom-pool-")
    try:
        sup, router = _mk_pool(args, run_dir)
    except RuntimeError as e:
        print(f"pool failed to start: {e}", file=sys.stderr)
        return 1
    # from here every exit path must stop the fleet: worker processes
    # are independent OS processes that would outlive a crashed CLI
    try:
        _print_pool_ready(sup, router)
        failed = _pool_self_probe(router)
        print(f"  self-probe: "
              f"{'all endpoints served' if not failed else 'FAILED'}")
        if failed:
            for p in failed:
                print(f"    {p.kind}: state={p.state} error={p.error}",
                      file=sys.stderr)
            return 1
        try:
            if args.duration > 0:
                end = mono_now_s() + args.duration
                while mono_now_s() < end:
                    time.sleep(min(0.2, max(0.0, end - mono_now_s())))
            else:
                print("pool serving until interrupted (Ctrl-C) ...")
                while True:
                    time.sleep(0.5)
        except KeyboardInterrupt:
            print("\ninterrupted — draining the fleet")
        acct = router.accounting()
        viols = router.invariant_violations()
    finally:
        sup.stop()
    print(f"pool accounting: {acct}")
    print(f"availability: {router.availability()}")
    print(f"fleet: kills {sup.summary()['kills']}, restarts "
          f"{sup.summary()['restarts']}, rolls "
          f"{sup.summary()['rolls_completed']}")
    for v in viols:
        print(f"INVARIANT VIOLATION: {v}", file=sys.stderr)
    return 1 if viols else 0


def _print_ready(svc) -> None:
    from csmom_tpu.registry import serve_endpoints

    spec = svc.spec
    print(f"signal service ready: engine {svc.engine.name}, bucket "
          f"profile {spec.name}")
    print(f"  endpoints: {', '.join(serve_endpoints())}")
    print(f"  buckets: B({','.join(map(str, spec.batch_buckets))}) x "
          f"A({','.join(map(str, spec.asset_buckets))}) x {spec.months} "
          f"months ({spec.dtype})")
    print(f"  admission: capacity {svc.config.capacity}, coalesce window "
          f"{svc.config.max_wait_s * 1e3:g} ms, default deadline "
          f"{svc.config.default_deadline_s}")
    print(f"  warmup: {svc.warm_report}")


def cmd_serve(args) -> int:
    """Run the signal service: in-process (default) or the multi-process
    pool (``--workers N``)."""
    import numpy as np

    from csmom_tpu.registry import serve_endpoints

    if args.workers > 0:
        return _cmd_serve_pool(args)
    if not args.stub:
        rc = _check_cache_honesty(args, args.profile or "serve")
        if rc:
            return rc
    svc = _mk_service(args)
    svc.start()
    _print_ready(svc)

    # a demonstrated "ready": one probe request per endpoint through the
    # full admission -> coalesce -> dispatch path
    spec = svc.spec
    A = spec.asset_buckets[0]
    rng = np.random.default_rng(0)
    probes = []
    for kind in serve_endpoints():
        v = 100.0 * np.exp(np.cumsum(
            rng.normal(0, 0.03, (A, spec.months)), axis=1))
        probes.append(svc.submit(kind, v.astype(np.float32),
                                 np.ones((A, spec.months), bool),
                                 deadline_s=5.0))
    ok = all(p.wait(10.0) and p.state == "served" for p in probes)
    print(f"  self-probe: {'all endpoints served' if ok else 'FAILED'}")
    if not ok:
        svc.stop()
        for p in probes:
            if p.state != "served":
                print(f"    {p.kind}: state={p.state} error={p.error}",
                      file=sys.stderr)
        return 1

    import time

    from csmom_tpu.utils.deadline import mono_now_s

    try:
        if args.duration > 0:
            end = mono_now_s() + args.duration
            while mono_now_s() < end:
                time.sleep(min(0.2, max(0.0, end - mono_now_s())))
        else:
            print("serving until interrupted (Ctrl-C) ...")
            while True:
                time.sleep(0.5)
    except KeyboardInterrupt:
        print("\ninterrupted — draining")
    svc.stop(drain=True)
    print(f"accounting: {svc.accounting()}")
    print(f"batches: {svc.batch_stats()}")
    print(f"in-window fresh compiles: {svc.fresh_compiles()}")
    viols = svc.invariant_violations()
    for v in viols:
        print(f"INVARIANT VIOLATION: {v}", file=sys.stderr)
    return 1 if viols else 0


def _arm_trace(args):
    """Arm the request-trace book when --trace was asked for (obs.trace;
    the disarmed path costs nothing, so this is the ONLY place the flag
    is consulted)."""
    if not getattr(args, "trace", False):
        return None
    from csmom_tpu.obs import trace as obs_trace

    return obs_trace.arm_tracing(seed=args.seed)


def _land_trace(args, book, run_id: str, art: dict, out_dir: str) -> int:
    """Build, validate, and land TRACE_<run>.json from an armed book +
    the serve artifact it must reconcile with.  Returns nonzero when the
    trace books are broken — unbalanced tracing is invalid evidence."""
    from csmom_tpu.chaos import invariants as inv
    from csmom_tpu.obs import trace as obs_trace
    from csmom_tpu.serve.loadgen import write_artifact

    viols = book.invariant_violations()
    trace_art = obs_trace.build_artifact(
        book, run_id,
        requests={k: art["requests"][k]
                  for k in ("admitted", "served", "rejected", "expired")},
        fresh_compiles=art["compile"]["in_window_fresh_compiles"],
        platform=art["extra"].get("platform"),
        workload=art["extra"].get("workload"),
    )
    path = write_artifact(out_dir, trace_art, prefix="TRACE")
    books = trace_art["books"]
    print(f"\ntrace books: opened {books['opened']} = complete "
          f"{books['complete']} + partial {books['partial']}; orphan "
          f"halves {trace_art['orphans']['count']}; max stage-sum "
          f"residual {trace_art['reconcile']['max_abs_residual_ms']} ms")
    print(f"trace artifact: {path} (render with `csmom trace {run_id}`)")
    obs_trace.disarm_tracing()
    schema = inv.validate_file(path)
    if viols or schema:
        print("TRACE INVALID:", file=sys.stderr)
        for v in viols + schema:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


def _arm_fleet(args, run_id: str):
    """Arm the fleet observatory when --fleet was asked for (obs.fleet).

    MUST run before the supervisors spawn: arming exports the
    CSMOM_FLEET env contract, and worker/router processes join the
    aggregator only if they inherit it.  The disarmed path costs one
    env read at each child's main, so this is the ONLY place the flag
    is consulted."""
    if not getattr(args, "fleet", False):
        return None
    from csmom_tpu.obs import fleet as obs_fleet

    transport = ("tcp" if getattr(args, "transport", "unix") == "tcp"
                 else "unix")
    agg = obs_fleet.arm(run_id, transport=transport)
    print(f"fleet observatory armed: aggregator at {agg.address} "
          f"(cadence {agg.cadence_s}s)")
    return agg


def _elastic_config(args, n_workers: int):
    """The FleetConfig the --spares/--autoscale/--prefork flags ask for
    (None when the elastic tier is not armed).  The configured fleet
    size is the autoscaler's declared floor — a drain can never shrink
    the fleet below what the operator asked to run."""
    spares = getattr(args, "spares", 0) or 0
    autoscale = bool(getattr(args, "autoscale", False))
    prefork = bool(getattr(args, "prefork", False))
    if not (spares or autoscale or prefork):
        return None
    from csmom_tpu.serve.fleet import FleetConfig

    return FleetConfig(spares=spares, autoscale=autoscale,
                       prefork=prefork, min_workers=n_workers,
                       max_workers=n_workers + 2)


def _arm_elastic(args, wsup, publisher=None):
    """Pool-mode elastic arming: attach a FleetController to a running
    supervisor (fabric mode threads the config through build_fabric
    instead).  Returns the controller or None."""
    cfg = _elastic_config(args, wsup.config.n_workers)
    if cfg is None:
        return None
    from csmom_tpu.obs import fleet as obs_fleet
    from csmom_tpu.serve.fleet import FleetController

    ctl = FleetController(wsup, cfg, publisher=publisher,
                          aggregator=obs_fleet.current_aggregator())
    ctl.start()
    print(f"elastic fleet armed: {cfg.spares} hot spare(s)"
          + (", prefork warm path" if cfg.prefork else "")
          + (", autoscaler" if cfg.autoscale else ""))
    return ctl


def _land_fleet(run_id: str, art: dict, out_dir: str, wsup, rsup,
                window: tuple) -> int:
    """Build, validate, and land FLEET_<run>.json from the armed
    aggregator + the serve artifact its demand book must reconcile
    with.  Called AFTER the fabric/pool stopped, so every surviving
    emitter's fin frame is already in the books (a SIGKILL victim's
    stream was severed-closed when its connection died).  Returns
    nonzero when the fleet books are broken — an unclosed or
    unreconciled observatory is invalid evidence."""
    from csmom_tpu.chaos import invariants as inv
    from csmom_tpu.obs import fleet as obs_fleet
    from csmom_tpu.serve.loadgen import write_artifact

    agg = obs_fleet.current_aggregator()
    if agg is None:
        return 0
    # fin-close the loadgen process's own emitter, then reason-close
    # any straggler book before the snapshot freezes
    obs_fleet.disarm_emitter("loadgen finished")
    agg.close_all("run-end")
    worker_events = obs_fleet.absolute_events(
        wsup.summary()["events"], wsup.t0_mono_s)
    router_events = (obs_fleet.absolute_events(
        rsup.summary()["events"], rsup.t0_mono_s)
        if rsup is not None else None)
    fleet_art = obs_fleet.build_artifact(
        agg, run_id,
        requests={k: art["requests"][k]
                  for k in ("admitted", "served", "rejected", "expired")},
        worker_events=worker_events,
        router_events=router_events,
        # the autoscaler may have grown the fleet past the configured
        # size: nominal capacity counts the slots that actually existed
        n_workers=max(wsup.config.n_workers, len(wsup.handles)),
        n_routers=(rsup.config.n_workers if rsup is not None else None),
        window=window,
        channels=(art.get("extra") or {}).get("client_channels"),
        fresh_compiles=art["compile"]["in_window_fresh_compiles"],
        platform=art["extra"].get("platform"),
        workload=art["extra"].get("workload"),
        elastic=(wsup.fleet.summary()
                 if getattr(wsup, "fleet", None) is not None else None),
    )
    path = write_artifact(out_dir, fleet_art, prefix="FLEET")
    books = fleet_art["series"]["books"]
    cap = fleet_art["capacity"]
    print(f"\nfleet books: {books['procs_opened']} stream(s) opened = "
          f"{books['procs_closed']} reason-closed; {books['frames']} "
          f"frames, {books['seq_gaps']} seq gap(s), "
          f"{books['frames_dropped_by_emitters']} dropped")
    print(f"fleet capacity: kill-window loss "
          f"{cap['kill_window_loss_frac']} over "
          f"{len(cap['kill_windows'])} window(s), steady-state "
          f"{cap['steady_state_loss_frac']}; ready walls "
          f"{fleet_art['lifecycle']['ready_walls_s']} s")
    el = fleet_art.get("elastic")
    if el:
        sp = el["spares"]
        print(f"elastic: {sp['promoted']} promotion(s) "
              f"{[p['wall_s'] for p in el['promotions']]} s wall, "
              f"{sp['spawned']} spare(s) spawned "
              f"({sp['died_parked']} died parked, {sp['backfills']} "
              f"backfill(s)), {len(el['decisions'])} reasoned "
              "autoscaler decision(s)")
    print(f"fleet artifact: {path} (render with `csmom fleet {run_id}`)")
    obs_fleet.disarm("run-end")
    schema = inv.validate_file(path)
    if schema:
        print("FLEET INVALID:", file=sys.stderr)
        for v in schema:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


def _cmd_loadgen_pool(args, schedule: str, run_id: str,
                      schedule_kind: str = "custom",
                      preset: dict | None = None) -> int:
    """Pool-mode loadgen: drive the router, land SERVE_POOL_<run>.json."""
    import tempfile

    from csmom_tpu.chaos import invariants as inv
    from csmom_tpu.serve.loadgen import (
        LoadConfig,
        run_pool_loadgen,
        write_artifact,
    )

    run_dir = tempfile.mkdtemp(prefix="csmom-pool-")
    # fleet arming must precede the spawns: workers join the aggregator
    # through the env contract they inherit at fork
    fleet_agg = _arm_fleet(args, run_id)
    try:
        sup, router = _mk_pool(args, run_dir)
    except RuntimeError as e:
        print(f"pool failed to start: {e}", file=sys.stderr)
        if fleet_agg is not None:
            from csmom_tpu.obs import fleet as obs_fleet
            obs_fleet.disarm("pool failed to start")
        return 1
    try:
        _print_pool_ready(sup, router)
        # pool mode has no routes publisher: a promotion propagates the
        # instant the handle swaps (the router reads ready_workers live)
        _arm_elastic(args, sup)
        if fleet_agg is not None:
            # the pool path runs no self-probes through the router, so
            # the demand window opens at the measured load's doorstep
            # and reconciles with the router's request book by schema
            from csmom_tpu.obs import fleet as obs_fleet
            obs_fleet.open_demand_window()
        # a named schedule's preset applies where the pool loadgen
        # implements it (the class mix); cache reuse / version bumps are
        # single-process shapes today (the pool has no shared cache yet
        # — ROADMAP item 3's remaining depth) and are dropped LOUDLY so
        # the artifact's schedule_kind never overclaims
        preset = dict(preset or {})
        class_mix = preset.pop("class_mix", None)
        preset.pop("use_class_deadlines", None)  # pool deadlines are
        # per-request floats through the router, not class budgets
        if preset:
            print(f"note: named-schedule preset keys {sorted(preset)} "
                  "apply to the single-process loadgen only; this pool "
                  "run uses the schedule + class mix")
        load = LoadConfig(
            schedule=schedule,
            schedule_kind=schedule_kind,
            seed=args.seed,
            class_mix=class_mix,
            deadline_s=(None if args.deadline_ms == 0
                        else 0.5 if args.deadline_ms is None
                        else args.deadline_ms / 1e3),
            run_id=run_id,
        )
        trace_book = _arm_trace(args)
        concurrent = None
        kill_after = getattr(args, "kill_worker_after", 0.0) or 0.0
        if kill_after > 0:
            # the mid-run worker SIGKILL (the trace round's rehearsed
            # fault, on demand): kill one worker, then wait for its
            # replacement to demonstrate ready so the artifact is built
            # from a settled fleet — run_pool_loadgen's `concurrent`
            # contract
            import time as _time

            from csmom_tpu.utils.deadline import mono_now_s

            def concurrent():
                _time.sleep(kill_after)
                victim = sup.handles[0].worker_id
                print(f"  [chaos] SIGKILL worker {victim} "
                      f"({kill_after:g}s into the run)")
                sup.kill_worker(victim)
                give_up = mono_now_s() + 60.0
                while mono_now_s() < give_up:
                    if any(h.generation >= 1 and h.state == "ready"
                           for h in sup.handles):
                        return
                    _time.sleep(0.05)

        print(f"offering (pool): schedule {schedule} (seed {load.seed}, "
              f"deadline {load.deadline_s}s"
              + (", trace armed" if trace_book is not None else "")
              + (f", worker kill @{kill_after:g}s" if kill_after else "")
              + ") ...")
        from csmom_tpu.utils.deadline import mono_now_s as _mono

        t_load0 = _mono()
        art = run_pool_loadgen(router, sup, load, concurrent=concurrent)
    finally:
        # a Ctrl-C or a loadgen failure must not leak N live worker
        # processes — they are independent of this CLI's lifetime
        sup.stop()
    out_dir = args.out or os.getcwd()
    path = write_artifact(out_dir, art, prefix="SERVE_POOL")

    req = art["requests"]
    lat = art["latency_ms"]["total"]
    print(f"\nthroughput: {art['value']} req/s achieved vs "
          f"{art['offered']['offered_rps']} req/s offered over "
          f"{art['wall_s']}s wall"
          + (" (offered-load-limited)" if art["offered_limited"] else ""))
    print(f"requests: admitted {req['admitted']} -> served {req['served']}, "
          f"rejected {req['rejected']} (infra {req['rejected_infra']}), "
          f"expired {req['expired']}")
    print(f"availability: {art['availability']}  hedge rate: "
          f"{art['hedge']['rate']} ({req['hedged']} hedged, "
          f"{req['hedge_wins']} wins, {req['duplicates_suppressed']} "
          "suppressed)")
    print(f"latency total ms: p50 {lat['p50']}  p95 {lat['p95']}  "
          f"p99 {lat['p99']}")
    print(f"fleet: kills {art['pool']['kills']}, restarts "
          f"{art['pool']['restarts']}, rolls "
          f"{art['pool']['rolls_completed']}")
    print(f"in-window fresh compiles: "
          f"{art['compile']['in_window_fresh_compiles']!r}")
    print(f"artifact: {path}")

    rc = 0
    if trace_book is not None:
        rc = _land_trace(args, trace_book, run_id, art, out_dir)
    if fleet_agg is not None:
        rc = max(rc, _land_fleet(run_id, art, out_dir, sup, None,
                                 (t_load0, t_load0 + art["wall_s"])))
    viols = inv.validate_file(path)
    if viols:
        print("ARTIFACT INVALID:", file=sys.stderr)
        for v in viols:
            print(f"  - {v}", file=sys.stderr)
        return 1
    fresh = art["compile"]["in_window_fresh_compiles"]
    if isinstance(fresh, int) and fresh > 0 and not args.allow_fresh_compiles:
        print(f"error: {fresh} fresh compile(s) inside the serving window "
              "across the fleet — a worker compiled instead of loading "
              "the AOT cache; rerun with --allow-fresh-compiles to land "
              "anyway", file=sys.stderr)
        return 1
    return rc


def _mk_fabric(args, run_dir: str):
    """Build the THREE-TIER fabric: worker supervisor + routes publisher
    + router-replica supervisor + fabric client (ISSUE 14)."""
    from csmom_tpu.serve.fabric import build_fabric
    from csmom_tpu.serve.supervisor import PoolConfig

    profile = args.profile or ("serve-smoke" if getattr(args, "smoke", False)
                               else "serve")
    engine = _engine_name(args)
    pool_deadline_ms = 500.0 if args.deadline_ms is None else args.deadline_ms
    wcfg = PoolConfig(
        n_workers=args.workers if args.workers > 0 else 2,
        profile=profile,
        engine=engine,
        transport=args.transport,
        capacity=args.capacity,
        max_wait_ms=args.max_wait_ms,
        deadline_ms=pool_deadline_ms,
        devices_per_worker=getattr(args, "devices_per_worker", 0),
        require_warm_cache=(engine.startswith("jax")
                            and not getattr(args, "allow_cold_cache", False)
                            and not getattr(args, "smoke", False)),
    )
    rcfg = PoolConfig(
        n_workers=max(2, args.routers),   # replication is the point
        profile=profile,
        engine="stub",                    # replicas hold no compiled world
        transport=args.transport,
    )
    return build_fabric(
        wcfg, rcfg, run_dir,
        deadline_ms=pool_deadline_ms,
        hedge_fraction=args.hedge_fraction,
        trace=getattr(args, "trace", False),
        client_deadline_s=(None if pool_deadline_ms == 0
                           else pool_deadline_ms / 1e3),
        fleet_config=_elastic_config(args, wcfg.n_workers))


def _cmd_loadgen_fabric(args, schedule: str, run_id: str,
                        schedule_kind: str = "custom",
                        preset: dict | None = None) -> int:
    """Fabric-mode loadgen: drive the three-tier fabric, SIGKILL one
    router and one worker mid-burst when asked, land
    SERVE_FABRIC_<run>.json."""
    import tempfile

    from csmom_tpu.chaos import invariants as inv
    from csmom_tpu.serve.fabric import kill_mid_burst, stop_fabric
    from csmom_tpu.serve.loadgen import (
        LoadConfig,
        run_fabric_loadgen,
        write_artifact,
    )

    run_dir = tempfile.mkdtemp(prefix="csmom-fabric-")
    # fleet arming must precede the spawns: router replicas and workers
    # join the aggregator through the env contract they inherit at fork
    fleet_agg = _arm_fleet(args, run_id)
    try:
        wsup, publisher, rsup, client = _mk_fabric(args, run_dir)
    except RuntimeError as e:
        print(f"fabric failed to start: {e}", file=sys.stderr)
        if fleet_agg is not None:
            from csmom_tpu.obs import fleet as obs_fleet
            obs_fleet.disarm("fabric failed to start")
        return 1
    trace_book = None
    try:
        print(f"fabric ready: {len(rsup.ready_workers())} router "
              f"replicas over {args.transport}, "
              f"{len(wsup.ready_workers())}/{wsup.config.n_workers} "
              f"workers (engine {wsup.config.engine}, profile "
              f"{wsup.config.profile})")
        for h in rsup.handles:
            print(f"  {h.worker_id} g{h.generation} [{h.state}] "
                  f"{h.socket_path}")
        for h in wsup.handles:
            print(f"  {h.worker_id} g{h.generation} [{h.state}] "
                  f"{h.socket_path}")
        if getattr(wsup, "fleet", None) is not None:
            fcfg = wsup.fleet.config
            print(f"  elastic: {len(wsup.fleet.spares)} hot spare(s) "
                  "parked out of the ring"
                  + (", prefork warm path" if fcfg.prefork else "")
                  + (", autoscaler armed" if fcfg.autoscale else ""))
        # a demonstrated three-tier ready: one probe per endpoint
        # through client -> replica -> worker.  Probes go through a
        # THROWAWAY client and tracing arms only AFTER they pass: the
        # measured client's books ARE the artifact's request ledger,
        # and probe traffic would contaminate the committed evidence
        # (admitted/hit-rate denominators, trace stage samples)
        from csmom_tpu.serve.buckets import bucket_spec
        from csmom_tpu.serve.fabric import FabricClient

        probe_client = FabricClient(rsup.ready_workers, client.config)
        failed = _pool_self_probe(probe_client,
                                  spec=bucket_spec(wsup.config.profile))
        print(f"  self-probe: "
              f"{'all endpoints served' if not failed else 'FAILED'}")
        if failed:
            for p in failed:
                print(f"    {p.kind}: state={p.state} error={p.error}",
                      file=sys.stderr)
            if fleet_agg is not None:
                from csmom_tpu.obs import fleet as obs_fleet
                obs_fleet.disarm("self-probe failed")
            return 1
        # the throwaway probe client's channels must not linger into
        # the measured window (its dials are not the run's evidence)
        probe_client.close()
        if fleet_agg is not None:
            # demand opens AFTER the probes' terminal events, so the
            # book counts exactly the measured client's arrivals and
            # reconciles with its request ledger by schema
            from csmom_tpu.obs import fleet as obs_fleet
            obs_fleet.open_demand_window()
        trace_book = _arm_trace(args)

        preset = dict(preset or {})
        class_mix = preset.pop("class_mix", None)
        preset_reuse = preset.pop("reuse_fraction", 0.0)
        bumps = preset.pop("version_bumps", 0)
        preset.pop("use_class_deadlines", None)
        if preset:
            print(f"note: named-schedule preset keys {sorted(preset)} "
                  "apply to the single-process loadgen only")
        # explicit --reuse-fraction wins; else the named schedule's
        # preset — the pool-level cache story NEEDS repeats to route
        reuse = (args.reuse_fraction if args.reuse_fraction is not None
                 else preset_reuse)
        load = LoadConfig(
            schedule=schedule,
            schedule_kind=schedule_kind,
            seed=args.seed,
            class_mix=class_mix,
            reuse_fraction=reuse,
            version_bumps=bumps,
            deadline_s=(None if args.deadline_ms == 0
                        else 0.5 if args.deadline_ms is None
                        else args.deadline_ms / 1e3),
            run_id=run_id,
        )

        kill_router_after = args.kill_router_after or 0.0
        kill_worker_after = getattr(args, "kill_worker_after", 0.0) or 0.0
        concurrent = None
        if kill_router_after > 0 or kill_worker_after > 0:
            def concurrent():
                # the rehearsed r18 double kill: one ROUTER replica and
                # one WORKER die mid-burst; the client fails over, the
                # routes view rebalances the ring, and both supervisors
                # respawn — the artifact is built only after both tiers
                # settled (run_fabric_loadgen's `concurrent` contract)
                if not kill_mid_burst(
                        [(kill_router_after, rsup, "router"),
                         (kill_worker_after, wsup, "worker")],
                        announce=lambda tier, victim, at_s: print(
                            f"  [chaos] SIGKILL {tier} {victim} "
                            f"({at_s:g}s into the run)")):
                    raise RuntimeError(
                        "a killed tier never re-demonstrated ready — "
                        "refusing to build books from an unsettled "
                        "fleet (crash loop? check the supervisor logs "
                        f"under {run_dir})")

        print(f"offering (fabric): schedule {schedule} (seed {load.seed}, "
              f"deadline {load.deadline_s}s, reuse {load.reuse_fraction}"
              + (", trace armed" if trace_book is not None else "")
              + (f", router kill @{kill_router_after:g}s"
                 if kill_router_after else "")
              + (f", worker kill @{kill_worker_after:g}s"
                 if kill_worker_after else "")
              + ") ...")
        from csmom_tpu.utils.deadline import mono_now_s as _mono

        t_load0 = _mono()
        art = run_fabric_loadgen(client, rsup, wsup, load,
                                 concurrent=concurrent)
    finally:
        # every exit path must stop BOTH process tiers and the publisher
        stop_fabric(publisher, rsup, wsup)
        client.close()  # the measured client's persistent channels
    out_dir = args.out or os.getcwd()
    path = write_artifact(out_dir, art, prefix="SERVE_FABRIC")

    req = art["requests"]
    lat = art["latency_ms"]["total"]
    cache = art["cache"]
    print(f"\nthroughput: {art['value']} req/s achieved vs "
          f"{art['offered']['offered_rps']} req/s offered over "
          f"{art['wall_s']}s wall"
          + (" (offered-load-limited)" if art["offered_limited"] else ""))
    print(f"requests: admitted {req['admitted']} -> served {req['served']}, "
          f"rejected {req['rejected']} (infra {req['rejected_infra']}), "
          f"expired {req['expired']}; failovers {req['failovers']}, "
          f"router conn failures {req['router_conn_failures']}")
    print(f"availability: {art['availability']}")
    print(f"pool cache: hit rate {cache['pool_hit_rate']} "
          f"({cache['served_cache_hits']}/{cache['served']} served) vs "
          f"r15 per-worker baseline {cache['per_worker_baseline']}; "
          f"worker books: stale_hits {cache['workers']['stale_hits']}")
    print(f"hedge: served hedged {art['hedge']['served_hedged']} "
          f"(rate {art['hedge']['rate']}), router tier hedged "
          f"{art['hedge']['router_tier']['hedged']}")
    print(f"latency total ms: p50 {lat['p50']}  p95 {lat['p95']}  "
          f"p99 {lat['p99']}")
    print(f"routers: kills {art['routers']['kills']}, restarts "
          f"{art['routers']['restarts']}; workers: kills "
          f"{art['workers']['kills']}, restarts {art['workers']['restarts']}")
    print(f"in-window fresh compiles: "
          f"{art['compile']['in_window_fresh_compiles']!r}")
    print(f"artifact: {path}")

    rc = 0
    if trace_book is not None:
        rc = _land_trace(args, trace_book, run_id, art, out_dir)
    if fleet_agg is not None:
        rc = max(rc, _land_fleet(run_id, art, out_dir, wsup, rsup,
                                 (t_load0, t_load0 + art["wall_s"])))
    viols = inv.validate_file(path)
    if viols:
        print("ARTIFACT INVALID:", file=sys.stderr)
        for v in viols:
            print(f"  - {v}", file=sys.stderr)
        return 1
    fresh = art["compile"]["in_window_fresh_compiles"]
    if isinstance(fresh, int) and fresh > 0 and not args.allow_fresh_compiles:
        print(f"error: {fresh} fresh compile(s) inside the serving window "
              "across the fleet — a worker compiled instead of loading "
              "the AOT cache; rerun with --allow-fresh-compiles to land "
              "anyway", file=sys.stderr)
        return 1
    return rc


def cmd_loadgen(args) -> int:
    """Open-loop load generation against an in-process service (or the
    pool with ``--pool``); lands SERVE_<run>.json / SERVE_POOL_<run>.json."""
    from csmom_tpu.chaos import invariants as inv
    from csmom_tpu.serve.loadgen import (
        LoadConfig,
        parse_schedule,
        resolve_schedule,
        run_loadgen,
        write_artifact,
    )

    if args.smoke:
        raw = args.schedule or "0.8x60"
        run_id = args.run_id or "smoke"
    else:
        raw = args.schedule or "2x40"
        run_id = args.run_id or f"loadgen-{os.getpid()}"
    schedule, schedule_kind, preset = resolve_schedule(raw)
    try:
        parse_schedule(schedule)
    except ValueError as e:
        print(f"--schedule: {e}", file=sys.stderr)
        return 2
    if getattr(args, "fabric", False):
        return _cmd_loadgen_fabric(args, schedule, run_id, schedule_kind,
                                   preset)
    if args.pool:
        return _cmd_loadgen_pool(args, schedule, run_id, schedule_kind,
                                 preset)
    svc = _mk_service(args)
    svc.start()
    _print_ready(svc)
    # key the mesh branches off the RESOLVED engine, not the flag:
    # --stub --mesh degrades to the stub with a warning, and a stub run
    # must never print mesh claims or land in the SERVE_MESH family
    mesh_engine = svc.engine.name == "jax-mesh"
    if mesh_engine:
        mesh = svc.warm_report.get("mesh") or {}
        print(f"  mesh: {mesh.get('devices')} devices, placements "
              + ", ".join(f"{k}:{v['axis']}"
                          for k, v in (mesh.get("endpoints") or {}).items()))
    load = LoadConfig(
        schedule=schedule,
        schedule_kind=schedule_kind,
        seed=args.seed,
        deadline_s=(None if args.deadline_ms == 0
                    else 0.5 if args.deadline_ms is None
                    else args.deadline_ms / 1e3),
        run_id=run_id,
        **preset,
    )
    trace_book = _arm_trace(args)
    print(f"offering: schedule {schedule_kind} = {schedule} (seed "
          f"{load.seed}, deadline "
          f"{'class budgets' if load.use_class_deadlines else load.deadline_s}"
          ") ...")
    art = run_loadgen(svc, load)
    out_dir = args.out or os.getcwd()
    # mesh runs land under their own prefix: SERVE_MESH_rNN.json is the
    # multi-device evidence family (committable like SERVE_rNN.json),
    # and the name says which serving story the numbers belong to
    path = write_artifact(out_dir, art,
                          prefix="SERVE_MESH" if mesh_engine else "SERVE")

    req = art["requests"]
    lat = art["latency_ms"]["total"]
    print(f"\nthroughput: {art['value']} req/s achieved vs "
          f"{art['offered']['offered_rps']} req/s offered over "
          f"{art['wall_s']}s wall"
          + (" (offered-load-limited)" if art["offered_limited"] else ""))
    print(f"requests: admitted {req['admitted']} -> served {req['served']} "
          f"(cache hits {req['served_cache_hits']}, coalesced "
          f"{req['served_coalesced']}), rejected {req['rejected']} "
          f"(queue-full {req['rejected_queue_full']}, quota "
          f"{req['rejected_quota']}, crash "
          f"{req['rejected_worker_crash']}), expired {req['expired']}")
    for name, book in art["classes"].items():
        wb = book["within_budget"]
        print(f"  class {name}: {book['served']}/{book['admitted']} served, "
              f"quota-rejected {book['rejected_quota']}, p99 "
              f"{book['latency_ms']['p99']} ms vs budget "
              f"{book['budget_ms']} ms "
              f"[{'ok' if wb else 'unused' if wb is None else 'BUSTED'}]")
    cache = art["cache"]
    if cache.get("enabled"):
        print(f"cache: hit rate {cache['hit_rate']} ({cache['hits']} hits / "
              f"{cache['lookups']} lookups), stale hits "
              f"{cache['stale_hits']}, stale blocked "
              f"{cache['stale_blocked']}, evictions {cache['evictions']}")
    print(f"latency total ms: p50 {lat['p50']}  p95 {lat['p95']}  "
          f"p99 {lat['p99']}")
    print(f"batches: {art['batches']}")
    print(f"in-window fresh compiles: "
          f"{art['compile']['in_window_fresh_compiles']}")
    print(f"artifact: {path}")

    rc = 0
    if trace_book is not None:
        rc = _land_trace(args, trace_book, run_id, art, out_dir)
    viols = inv.validate_file(path)
    if viols:
        print("ARTIFACT INVALID:", file=sys.stderr)
        for v in viols:
            print(f"  - {v}", file=sys.stderr)
        return 1
    fresh = art["compile"]["in_window_fresh_compiles"]
    if isinstance(fresh, int) and fresh > 0 and not args.allow_fresh_compiles:
        print(f"error: {fresh} fresh compile(s) inside the serving window "
              "— a dispatch missed the warmed bucket grid (padding or "
              "warmup bug); rerun with --allow-fresh-compiles to land "
              "anyway", file=sys.stderr)
        return 1
    return rc


def _common_flags(sp) -> None:
    sp.add_argument("--platform", choices=["cpu", "tpu", "default"],
                    help="pin the jax platform before the engine warms "
                         "(every subcommand supports this; use 'cpu' "
                         "when the TPU tunnel is unavailable)")
    sp.add_argument("--profile", choices=["serve", "serve-smoke"],
                    help="bucket grid (default: serve; --smoke implies "
                         "serve-smoke)")
    sp.add_argument("--stub", action="store_true",
                    help="numpy stub engine (no jax): plumbing/chaos runs")
    sp.add_argument("--mesh", action="store_true",
                    help="the jax-mesh engine: sharded dispatch over the "
                         "device mesh (batch-axis across micro-batch "
                         "rows, asset-axis for per-asset-independent "
                         "signals — csmom_tpu/mesh partition rules); "
                         "bitwise-equal outputs, SERVE_MESH_* artifacts; "
                         "on CPU simulate devices with XLA_FLAGS="
                         "--xla_force_host_platform_device_count=8")
    sp.add_argument("--devices-per-worker", dest="devices_per_worker",
                    type=int, default=0,
                    help="pool mode: pin each worker to a fixed "
                         "contiguous slice of this many devices (slot k "
                         "owns devices [k*N, k*N+N); replacements re-pin "
                         "the same slice; 0 = no pinning)")
    sp.add_argument("--capacity", type=int, default=64,
                    help="admission-queue bound (backpressure beyond it; "
                         "default 64)")
    sp.add_argument("--max-wait-ms", dest="max_wait_ms", type=float,
                    default=10.0,
                    help="micro-batch coalescing window (default 10 ms)")
    sp.add_argument("--deadline-ms", dest="deadline_ms", type=float,
                    default=None,
                    help="default per-request deadline (unset = each "
                         "request inherits its SLO class budget — "
                         "interactive 500 ms / standard 1 s / bulk 3 s; "
                         "an explicit value applies to every class; "
                         "0 = none; a request expiring while queued is "
                         "cancelled, never dispatched)")
    sp.add_argument("--workers", type=int, default=0,
                    help="run the MULTI-PROCESS pool with N supervised "
                         "worker processes behind a hedging router "
                         "(0 = the in-process single service; default 0)")
    sp.add_argument("--hedge-fraction", dest="hedge_fraction", type=float,
                    default=0.35,
                    help="pool mode: hedge a request after this fraction "
                         "of its remaining deadline (default 0.35)")
    sp.add_argument("--allow-cold-cache", dest="allow_cold_cache",
                    action="store_true",
                    help="serve even when the AOT cache is cold/stale "
                         "for the bucket profile (default: exit 3 with a "
                         "`csmom warmup --profiles serve` pointer)")


def register(sub) -> None:
    """Attach the ``serve`` and ``loadgen`` subparsers (from cli.main)."""
    sp = sub.add_parser(
        "serve",
        help="run the in-process micro-batching signal service (warm "
             "bucket shapes, self-probe every endpoint, serve)",
    )
    _common_flags(sp)
    sp.add_argument("--duration", type=float, default=5.0,
                    help="seconds to serve after the self-probe "
                         "(0 = until Ctrl-C; default 5)")
    sp.set_defaults(fn=cmd_serve)

    lg = sub.add_parser(
        "loadgen",
        help="seeded open-loop load generator against an in-process "
             "service; lands a SERVE_<run>.json latency/throughput "
             "artifact",
    )
    _common_flags(lg)
    lg.add_argument("--smoke", action="store_true",
                    help="tier-1 preset: smoke buckets, sub-second "
                         "schedule, SERVE_smoke.json (gitignored)")
    lg.add_argument("--pool", action="store_true",
                    help="drive the multi-process pool (--workers N) "
                         "instead of the in-process service; lands "
                         "SERVE_POOL_<run>.json (kind serve_pool)")
    lg.add_argument("--fabric", action="store_true",
                    help="drive the THREE-TIER horizontal fabric: "
                         "supervised router-replica processes "
                         "(--routers N) over unix/tcp in front of the "
                         "worker pool, consistent-hash cache routing, "
                         "client-side failover; lands "
                         "SERVE_FABRIC_<run>.json (kind serve_fabric)")
    lg.add_argument("--routers", type=int, default=2,
                    help="fabric mode: router replica count (min 2 — "
                         "replication is the point; default 2)")
    lg.add_argument("--transport", choices=["unix", "tcp"],
                    default="unix",
                    help="fabric mode: wire transport for every hop "
                         "(unix = one host, tcp = loopback ports today, "
                         "cross-container by swapping the host; "
                         "default unix)")
    lg.add_argument("--reuse-fraction", dest="reuse_fraction",
                    type=float, default=None, metavar="F",
                    help="fabric mode: probability a request reuses a "
                         "recent panel (repeats are what the "
                         "consistent-hash cache routing compounds; "
                         "default: the named schedule's preset, else 0)")
    lg.add_argument("--kill-router-after", dest="kill_router_after",
                    type=float, default=0.0, metavar="SEC",
                    help="fabric mode: SIGKILL one router replica SEC "
                         "seconds into the run (the client fails over "
                         "to a surviving replica; the artifact is built "
                         "only after the replacement is ready; "
                         "0 = no kill)")
    lg.add_argument("--schedule", metavar="DURxRPS|NAME",
                    help="arrival schedule: explicit segments (2x25,3x60) "
                         "or a named traffic shape — bursty (quiet + hard "
                         "bursts, bulk-heavy mix, panel reuse + mid-run "
                         "panel_version bump), diurnal (compressed-day "
                         "ramp), adversarial (bucket-boundary-hugging "
                         "universe sizes).  Named schedules preset the "
                         "class mix / reuse / version bumps that make "
                         "them meaningful (default: 2x40; smoke: 0.8x60)")
    lg.add_argument("--seed", type=int, default=0,
                    help="load stream seed (arrivals, mixes, panels; "
                         "same seed = same request stream)")
    lg.add_argument("--run-id", dest="run_id",
                    help="artifact run id: SERVE_<run-id>.json (round "
                         "evidence must be rNN; anything else is "
                         "scratch and gitignored)")
    lg.add_argument("--out", help="artifact directory (default: cwd)")
    lg.add_argument("--trace", action="store_true",
                    help="arm per-request tracing (obs.trace) and land "
                         "TRACE_<run-id>.json next to the serve artifact: "
                         "telescoping per-stage walls, closed trace "
                         "books, orphan halves reason-closed; render "
                         "with `csmom trace <run-id>`")
    lg.add_argument("--kill-worker-after", dest="kill_worker_after",
                    type=float, default=0.0, metavar="SEC",
                    help="pool mode: SIGKILL one worker SEC seconds into "
                         "the run (the rehearsed mid-batch death, on "
                         "demand — the router fails over, the trace "
                         "book closes the orphan halves with reason, "
                         "and the artifact is built only after the "
                         "replacement is ready; 0 = no kill)")
    lg.add_argument("--fleet", action="store_true",
                    help="arm the fleet observatory (obs.fleet): every "
                         "process streams metrics snapshot deltas to a "
                         "per-run aggregator on a fixed cadence; lands "
                         "FLEET_<run-id>.json (continuous time series, "
                         "demand book, kill-window capacity account) "
                         "next to the serve artifact; render with "
                         "`csmom fleet <run-id>`")
    lg.add_argument("--spares", type=int, default=0, metavar="N",
                    help="elastic fleet (serve.fleet): park N hot spare "
                         "workers — pre-spawned, demonstrated-ready, "
                         "held OUT of the hash ring — and promote one "
                         "into a dead victim's slot in O(routes-publish) "
                         "instead of paying the re-warm window; the pool "
                         "backfills off the hot path (0 = off)")
    lg.add_argument("--autoscale", action="store_true",
                    help="elastic fleet: arm the demand-driven control "
                         "loop — hysteresis-banded scale up/down within "
                         "declared floors/ceilings off the fleet "
                         "observatory's per-class demand series, plus "
                         "SLO-class quota auto-tune; every decision "
                         "lands reasoned in the fleet.elastic block "
                         "(requires --fleet for the demand input)")
    lg.add_argument("--prefork", action="store_true",
                    help="elastic fleet: spawn spares through a "
                         "forkserver-style prefork parent with the "
                         "serve stack pre-imported and the AOT cache "
                         "prewarmed into the page cache (fast warm "
                         "path)")
    lg.add_argument("--allow-fresh-compiles", dest="allow_fresh_compiles",
                    action="store_true",
                    help="land the artifact even when the serving window "
                         "compiled fresh shapes (default: exit 1 — the "
                         "zero-compile property is the contract)")
    lg.set_defaults(fn=cmd_loadgen)
