"""csmom timeline — render a run's telemetry sidecar as a text flame summary.

``csmom timeline <run>`` takes a path to a ``TELEMETRY_*.json`` sidecar,
a raw JSONL event stream (assembled on the fly), or a bare run id (the
sidecar is located by glob in the current directory, then the repo
root).  Output is the phase table (where the wall went:
warmup/probe/compile/row/land/other), the top spans by total wall, and
the run's final metrics snapshot — the "read the timeline instead of
reconstructing it" half of the telemetry contract
(:mod:`csmom_tpu.obs`).

Device-free and jax-free, like ``rehearse``: rendering evidence must
never depend on a backend being up.  Second module of the cli/main.py
split — subcommands register themselves via ``register(sub)``.
"""

from __future__ import annotations

import glob
import json
import os
import sys

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.obs import timeline as tl


def _locate(run: str) -> str | None:
    """Resolve a run argument to a sidecar/event-stream path.  Search
    order is ``obs.timeline.sidecar_search_roots`` — the one list shared
    with ``csmom trace``: CSMOM_TELEMETRY_DIR override, then cwd and
    repo root (committed round sidecars), each with its
    ``.csmom_scratch`` scratch directory (regenerated rehearse/smoke
    sidecars land there — see ``obs.timeline.scratch_dir``)."""
    if os.path.isfile(run):
        return run
    hits: list = []
    for root in tl.sidecar_search_roots():
        hits += sorted(glob.glob(os.path.join(root, f"TELEMETRY_*{run}*.json")))
        hits += sorted(glob.glob(os.path.join(root, f"TELEMETRY_{run}")))
    return hits[0] if hits else None


def cmd_timeline(args) -> int:
    """Render a run's TELEMETRY sidecar (or raw event stream) as a text
    flame summary."""
    path = _locate(args.run)
    if path is None:
        print(
            f"error: no TELEMETRY sidecar matches {args.run!r} (looked for "
            "a file path, then TELEMETRY_*<run>*.json in . and the repo "
            "root).  Runs emit one when telemetry is armed "
            "(CSMOM_TELEMETRY; bench and rehearse arm it by default).",
            file=sys.stderr,
        )
        return 2
    if path.endswith((".jsonl", ".events")):
        events = tl.read_events(path)
        # a reused (append-mode) stream can carry several runs; render
        # the most recent one rather than a blended timeline that
        # corresponds to none of them
        runs = [e.get("run") for e in events if e.get("run")]
        latest = runs[-1] if runs else None
        if len(set(runs)) > 1:
            print(
                f"note: stream carries {len(set(runs))} runs; rendering "
                f"the most recent ({latest!r})", file=sys.stderr,
            )
        obj = tl.assemble(events, run_id=latest)
    else:
        try:
            obj = tl.load_sidecar(path)
        except (OSError, json.JSONDecodeError) as e:
            print(f"error: unreadable sidecar {path}: {e}", file=sys.stderr)
            return 2
    violations = inv.validate(obj, "telemetry")
    if args.json:
        json.dump(obj, sys.stdout, indent=1)
        print()
    else:
        print(f"[{os.path.relpath(path)}]")
        try:
            print(tl.render(obj, top=args.top))
        except Exception as e:  # a damaged sidecar must still get its
            print(f"(render failed: {type(e).__name__}: {e} — "  # diagnosis
                  "schema report below)")
    if violations:
        print("\nschema violations (the sidecar is damaged or stale-format):",
              file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


def register(sub) -> None:
    """Attach the ``timeline`` subparser (called from cli.main)."""
    sp = sub.add_parser(
        "timeline",
        help="render a run's TELEMETRY_*.json sidecar (phases, top spans, "
             "metrics) as a text flame summary",
    )
    sp.add_argument("run",
                    help="sidecar path, raw .jsonl event stream, or run id "
                         "(globbed as TELEMETRY_*<run>*.json)")
    sp.add_argument("--top", type=int, default=12,
                    help="span aggregates to show (default 12)")
    sp.add_argument("--json", action="store_true",
                    help="dump the assembled sidecar object instead of "
                         "rendering")
    sp.set_defaults(fn=cmd_timeline)
