"""csmom trace — render a run's TRACE_<run>.json request-path decomposition.

"p99 was 13.6 ms" is one opaque number; this command answers *where* it
went.  Given a committed trace artifact (:mod:`csmom_tpu.obs.trace`), it
prints:

- the **per-stage decomposition table**: p50/p95/p99 per stage (admit,
  queue_wait, coalesce, pad, dispatch, serialize — plus route/transport
  for pool-stitched runs), so a tail regression names its layer;
- the **critical path** of the slowest-k complete requests: each one's
  full stage breakdown, largest stage first — the "this request burned
  its budget in queue-wait, not the engine" view;
- **padding-waste goodput per bucket**: used vs padded lanes and the
  fire-reason mix for every (endpoint, bucket) the run dispatched;
- the **closed trace books**: complete/partial with reasons, orphan
  halves (a SIGKILLed worker's unstitchable dispatches) with reasons,
  and the per-class SLO error-budget burn rates.

Evidence-only and clock-free (the clock-discipline lint pins this module
into the ledger's wall-free tier): rendering a committed artifact must be
reproducible from its bytes alone.  Registered via ``register(sub)``
like rehearse/timeline/ledger — the cli/main.py split.
"""

from __future__ import annotations

import glob
import json
import os
import sys

from csmom_tpu.chaos import invariants as inv

__all__ = ["cmd_trace", "register"]


def _locate(run: str, root: str | None) -> str | None:
    if os.path.isfile(run):
        return run
    # one shared search order with `csmom timeline` (an explicit --root
    # wins; otherwise CSMOM_TELEMETRY_DIR, then cwd / repo root and
    # their scratch dirs) — see obs.timeline.sidecar_search_roots
    from csmom_tpu.obs.timeline import sidecar_search_roots

    for r in sidecar_search_roots(root):
        for pat in (f"TRACE_{run}.json", f"TRACE_*{run}*.json"):
            hits = sorted(glob.glob(os.path.join(r, pat)))
            if hits:
                return hits[0]
    return None


def _fmt_ms(v) -> str:
    return f"{v:>9.3f}" if isinstance(v, (int, float)) else f"{'—':>9}"


def _print_stages(obj: dict) -> None:
    stages = obj.get("stages") or {}
    if not stages:
        print("\n(no complete traces: no stage decomposition)")
        return
    # request-path order first, anything else after
    from csmom_tpu.obs.trace import STAGES

    order = [s for s in STAGES if s in stages]
    order += [s for s in sorted(stages) if s not in order]
    print("\nper-stage decomposition (ms, complete traces):")
    print(f"  {'stage':<12} {'count':>6} {'p50':>9} {'p95':>9} "
          f"{'p99':>9} {'max':>9} {'total_s':>9}")
    for name in order:
        s = stages[name]
        print(f"  {name:<12} {s.get('count', 0):>6} "
              f"{_fmt_ms(s.get('p50'))} {_fmt_ms(s.get('p95'))} "
              f"{_fmt_ms(s.get('p99'))} {_fmt_ms(s.get('max_ms'))} "
              f"{s.get('total_s', 0.0):>9.3f}")


def _print_slowest(obj: dict, top: int) -> None:
    slowest = obj.get("slowest") or []
    if not slowest:
        return
    print(f"\ncritical path of the slowest {min(top, len(slowest))} "
          "complete request(s):")
    for e in slowest[:top]:
        attrs = e.get("attrs") or {}
        bits = [f"{e.get('endpoint')}/{e.get('class')}"]
        if attrs.get("fire_reason"):
            bits.append(f"fire={attrs['fire_reason']}")
        if attrs.get("bucket"):
            bits.append(f"bucket={attrs['bucket']}")
        if attrs.get("mesh_shards"):
            bits.append(f"shards={attrs['mesh_shards']}"
                        f"/{attrs.get('mesh_devices')}d")
        if attrs.get("worker"):
            bits.append(f"worker={attrs['worker']}")
        print(f"  {e.get('trace_id')}  wall {e.get('wall_ms')} ms  "
              f"[{', '.join(bits)}]")
        ranked = sorted((e.get("stages") or {}).items(),
                        key=lambda kv: -(kv[1] or 0.0))
        wall = e.get("wall_ms") or 0.0
        for stage, ms in ranked:
            share = f" {ms / wall:>6.1%}" if wall else ""
            print(f"      {stage:<12} {_fmt_ms(ms)} ms{share}")


def _print_padding(obj: dict) -> None:
    padding = obj.get("padding") or {}
    if not padding:
        return
    print("\npadding-waste goodput per bucket:")
    print(f"  {'bucket':<28} {'batches':>7} {'used':>8} {'padded':>8} "
          f"{'pad_frac':>8}  fire reasons")
    for key, b in sorted(padding.items()):
        fr = ",".join(f"{k}:{v}" for k, v in
                      sorted((b.get("fire_reasons") or {}).items()))
        print(f"  {key:<28} {b.get('batches', 0):>7} "
              f"{b.get('used_lanes', 0):>8} {b.get('pad_lanes', 0):>8} "
              f"{b.get('pad_fraction', 0.0):>8.4f}  {fr}")


def _print_books(obj: dict) -> None:
    books = obj.get("books") or {}
    print(f"\ntrace books: opened {books.get('opened')} = complete "
          f"{books.get('complete')} + partial {books.get('partial')}")
    for reason, n in sorted((books.get("partial_reasons") or {}).items()):
        print(f"  partial x{n}: {reason}")
    orphans = obj.get("orphans") or {}
    if orphans.get("count"):
        print(f"orphan halves: {orphans['count']} (dispatches whose "
              "worker died before replying — closed with reason):")
        for reason, n in sorted((orphans.get("reasons") or {}).items()):
            print(f"  x{n}: {reason}")
    else:
        print("orphan halves: 0")
    rec = obj.get("reconcile") or {}
    print(f"reconcile: {rec.get('checked')} trace(s), max residual "
          f"{rec.get('max_abs_residual_ms')} ms (epsilon "
          f"{rec.get('epsilon_ms')} ms), violations "
          f"{rec.get('violations')}")
    classes = obj.get("classes") or {}
    if classes:
        print("per-class SLO error-budget burn "
              f"(target {next(iter(classes.values())).get('slo_target')}):")
        for name, book in sorted(classes.items()):
            burn = book.get("budget_burn")
            verdict = ("—" if burn is None
                       else "within budget" if burn <= 1.0 else "BURNING")
            print(f"  {name:<12} served {book.get('served'):>5}  "
                  f"violations {book.get('violations'):>4}  p99 "
                  f"{_fmt_ms((book.get('latency_ms') or {}).get('p99'))} "
                  f"ms vs budget {_fmt_ms(book.get('budget_ms'))} ms  "
                  f"burn {burn if burn is not None else '—'} "
                  f"[{verdict}]")


def cmd_trace(args) -> int:
    """Render a run's TRACE_<run>.json: per-stage p50/p99 decomposition,
    slowest-k critical paths, padding goodput per bucket, closed books."""
    path = _locate(args.run, args.root)
    if path is None:
        print(f"error: no TRACE artifact matches {args.run!r} (looked for "
              "a file path, then TRACE_<run>.json in "
              f"{args.root or '. and the repo root'}).  Capture one with "
              "`csmom loadgen --trace` (add --pool for the stitched "
              "multi-process decomposition).", file=sys.stderr)
        return 2
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: unreadable trace artifact {path}: {e}",
              file=sys.stderr)
        return 2
    violations = inv.validate(obj, "trace")
    if args.json:
        json.dump(obj, sys.stdout, indent=1)
        print()
    else:
        print(f"[{os.path.relpath(path)}]")
        print(f"run {obj.get('run_id')}  platform "
              f"{(obj.get('extra') or {}).get('platform')}  "
              f"fresh compiles in window "
              f"{(obj.get('compile') or {}).get('in_window_fresh_compiles')!r}")
        wl = (obj.get("extra") or {}).get("workload")
        if wl:
            print(f"workload: {wl}")
        try:
            _print_books(obj)
            _print_stages(obj)
            _print_slowest(obj, args.top)
            _print_padding(obj)
        except Exception as e:  # a damaged artifact must still get its
            print(f"(render failed: {type(e).__name__}: {e} — "  # diagnosis
                  "schema report below)")
    if violations:
        print("\nschema violations (the artifact is damaged or "
              "stale-format):", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


def register(sub) -> None:
    """Attach the ``trace`` subparser (called from cli.main)."""
    sp = sub.add_parser(
        "trace",
        help="render a run's TRACE_<run>.json request-path decomposition "
             "(per-stage p99s, slowest-request critical paths, padding "
             "goodput, closed trace books)",
    )
    sp.add_argument("run",
                    help="trace artifact path or run id (resolved as "
                         "TRACE_<run>.json in . and the repo root)")
    sp.add_argument("--root", help="artifact directory (default: cwd, "
                                   "then the repo checkout)")
    sp.add_argument("--top", type=int, default=8,
                    help="slowest traces to break down (default 8)")
    sp.add_argument("--json", action="store_true",
                    help="dump the artifact object instead of rendering")
    sp.set_defaults(fn=cmd_trace)
