"""AOT warm-start pipeline: shape manifest + ahead-of-time compilation.

The tunneled 'axon' TPU backend flaps in ~25-minute windows and a fresh
jit compile costs ~30 s per hot shape, so a window spent compiling is a
window lost to measurement.  This package makes the hot path mechanically
warm:

- :mod:`csmom_tpu.compile.workloads` — the canonical bench/CLI input
  builders (golden 20-ticker event panel, 512x3780 CPU grid, 3000x15120
  north-star grid), shared by ``bench.py`` and the warmup so both sides
  compile byte-identical programs;
- :mod:`csmom_tpu.compile.entries` — the shared jitted entry wrappers
  (one callable per hot computation, used by bench AND warmup: identical
  HLO in, identical serialized-executable cache key out);
- :mod:`csmom_tpu.compile.manifest` — the shape manifest: every hot
  jitted entry point with its canonical argument shapes, bound against
  the functions' real signatures so the manifest cannot silently drift
  from the code;
- :mod:`csmom_tpu.compile.aot` — ``lower().compile()`` per manifest
  entry with the persistent serialized-executable cache enabled
  (``utils.jit_cache``), per-shape trace/compile walls, and cache
  hit/miss accounting.  Exposed as the ``csmom warmup`` CLI subcommand
  and invoked by ``bench.py``'s supervisor during its probe/sleep loop.
"""

from csmom_tpu.compile.manifest import ManifestEntry, build_manifest  # noqa: F401
from csmom_tpu.compile.aot import aot_compile, warmup  # noqa: F401
