"""AOT lowering/compilation of the shape manifest, with cache accounting.

``aot_compile`` runs one manifest entry through
``jit(...).lower(shapes).compile()`` with the persistent
serialized-executable cache enabled, so the compiled artifact lands
on disk keyed by (HLO, backend) — any later process that traces the same
computation at the same shapes loads it instead of compiling
(``utils.jit_cache``).  ``warmup`` does that for a whole profile and
writes a per-shape report (trace wall, compile wall, hit/miss) next to
the cache, which ``bench.py`` attaches to the round's FULL record.

The point: on the flapping tunneled TPU backend a fresh compile is
~30 s/shape and tunnel windows are ~25 min — compilation must happen
BEFORE a window opens (CPU shapes any time; TPU shapes during an earlier
window, after which they persist).  ``csmom warmup`` is the operator
knob; bench's supervisor also fires a CPU warmup from its probe/sleep
loop so even a cold machine's fallback record is compile-free.
"""

from __future__ import annotations

import json
import os
import time

from csmom_tpu.utils.logging import get_logger

log = get_logger("compile.aot")

REPORT_NAME = "warmup_report.json"


def _evict_cache_entries() -> int:
    """Remove every serialized-executable file from the live cache dir.

    The cache keys are opaque (HLO hash + backend), so a corrupt entry
    cannot be mapped back to the computation that tripped over it — and a
    cache that has already served one poisoned entry is not worth
    trusting for the rest of a scarce window.  Eviction costs only
    recompiles; keeping a poisoned entry costs the window.  Returns the
    number of files removed.
    """
    import glob

    import jax

    d = jax.config.jax_compilation_cache_dir
    if not d or not os.path.isdir(d):
        return 0
    n = 0
    for p in glob.glob(os.path.join(d, "*")):
        if os.path.basename(p) == REPORT_NAME or not os.path.isfile(p):
            continue
        try:
            os.remove(p)
            n += 1
        except OSError:
            pass  # a file we cannot remove we also cannot make worse
    return n


# error-text shapes a poisoned serialized executable surfaces as (jax
# soft-fails zlib header damage with a warning, but truncation/bit-flips
# can raise from the decompressor or the XLA deserializer instead)
_CORRUPTION_MARKERS = (
    "deserial", "decompress", "corrupt", "truncat", "incorrect header",
    "invalid compressed data", "compilation cache",
)


def _looks_like_cache_corruption(e: Exception) -> bool:
    msg = f"{type(e).__name__}: {e}".lower()
    return any(m in msg for m in _CORRUPTION_MARKERS)


def _compile_with_self_heal(lowered, name: str):
    """``lowered.compile()`` that survives a corrupt cache entry.

    jax soft-fails on some damage (a zlib header error logs a warning and
    recompiles) but a truncated or bit-flipped serialized executable can
    surface as a raising deserialization error instead — and before this
    guard, that single poisoned file crashed the warmup/bench child and
    cost the window (the chaos ``corrupt-aot-cache`` fault pins this
    path).  On a corruption-shaped exception: log, evict the cache, retry
    once cold.  Any other exception (OOM, unsupported op, a backend that
    died) propagates untouched — evicting the cache for those would
    destroy every already-warmed shape over an error eviction cannot fix.
    A second failure after eviction is a real compile problem and
    propagates too.
    """
    try:
        return lowered.compile(), False
    except Exception as e:
        if not _looks_like_cache_corruption(e):
            raise
        evicted = _evict_cache_entries()
        log.warning(
            "compile of %s raised %s: %s — evicted %d cache entries, "
            "recompiling cold (corrupt serialized-executable self-heal)",
            name, type(e).__name__, str(e)[:200], evicted,
        )
        return lowered.compile(), True


def aot_compile(entry) -> dict:
    """Lower + compile one :class:`ManifestEntry`; return its record.

    The record carries the trace-vs-compile wall split, whether the
    backend compile was served from the serialized-executable cache
    (``cache_hit``), and the shape's device-memory analysis (``memory``:
    argument/output/temp/peak bytes via ``compiled.memory_analysis()``,
    registered with :mod:`csmom_tpu.obs.memstats`) — the per-shape
    evidence the bench record and the perf ledger embed.  The compiled
    executable object itself is then discarded: the persistent product
    is the on-disk cache entry, not the in-process handle.

    A corrupt cache entry is detected, logged, evicted, and recompiled
    (``self_healed`` in the record) instead of raising — a poisoned cache
    must cost recompiles, never a window.
    """
    from csmom_tpu.chaos.inject import checkpoint
    from csmom_tpu.obs import memstats, span
    from csmom_tpu.utils.profiling import compile_stats

    entry.validate()
    before = compile_stats()
    with span("aot.compile", entry=entry.name) as sp:
        t0 = time.perf_counter()
        lowered = entry.fn.lower(*entry.args, **dict(entry.kwargs))
        trace_s = time.perf_counter() - t0
        checkpoint("aot.compile", entry=entry.name)
        t1 = time.perf_counter()
        compiled, healed = _compile_with_self_heal(lowered, entry.name)
        compile_s = time.perf_counter() - t1
        sp.set(trace_s=round(trace_s, 4), compile_s=round(compile_s, 4))
    d = compile_stats().delta(before)
    # the AOT pass is the one place a Compiled handle exists for every
    # hot shape, so the device-memory axis is read here (HBM peak /
    # argument / temp / output bytes) and registered with obs.memstats —
    # metrics snapshots and the TELEMETRY sidecar fold it in from there
    import jax as _jax

    memory = memstats.capture(entry.name, compiled,
                              platform=_jax.default_backend())
    rec = {
        "name": entry.name,
        "shapes": entry.shape_summary(),
        "trace_s": round(trace_s, 4),
        "compile_s": round(compile_s, 4),
        "memory": memory,
        "cache_hits": d.cache_hits,
        "cache_writes": d.cache_misses,  # jax's "miss" event fires on WRITE
        # hit iff at least one serialized executable was READ and none had
        # to be compiled+written — a compile below the persistence floor
        # records neither, which warmup() rules out by zeroing the floor
        "cache_hit": bool(d.cache_hits and d.cache_misses == 0),
    }
    if healed:
        rec["self_healed"] = ("corrupt cache entry evicted and recompiled "
                              "cold")
        rec["cache_hit"] = False
    return rec


def warmup(profiles=("bench-cpu", "golden"), *, subdir: str = "bench",
           include_golden_event: bool = True, write_report: bool = True) -> dict:
    """AOT-compile every manifest entry of the given profiles.

    Enables the persistent compile cache under ``subdir`` (the SAME
    "bench" directory bench children and the capture scripts share — the
    whole point is that their compiles become loads), builds each
    profile's manifest, compiles each entry, and (for bench profiles,
    when ``include_golden_event``) resolves + compiles the event engine
    at the actual golden workload shapes, which warms the full intraday
    pipeline as a side effect.

    Returns the report dict (also written to ``<cache_dir>/warmup_report
    .json`` unless disabled): per-entry walls + hit/miss, totals, and the
    cache directory.  Never raises on a single entry — a failed entry is
    recorded with its error so one bad shape cannot void the rest of the
    warm-start.
    """
    import datetime

    import jax

    from csmom_tpu.compile.manifest import build_manifest, golden_event_entries
    from csmom_tpu.compile.workloads import bench_platform
    from csmom_tpu.utils.jit_cache import enable_persistent_cache
    from csmom_tpu.utils.profiling import compile_stats, measure_rtt

    # min_compile_s=0: warmup's contract is EVERY manifest shape on disk,
    # including the ones XLA compiles in milliseconds — a later process
    # asserts hit-count == manifest size against exactly this guarantee
    cache_dir = enable_persistent_cache(subdir, min_compile_s=0.0)
    platform, on_cpu, dtype = bench_platform(jax)
    t_start = time.perf_counter()
    base = compile_stats()

    entries = []
    for profile in profiles:
        entries += [(profile, e) for e in build_manifest(profile)]

    from csmom_tpu.chaos.inject import checkpoint
    from csmom_tpu.obs import span

    rows = []
    for profile, entry in entries:
        checkpoint("warmup.entry", entry=entry.name)
        with span("warmup.entry", entry=entry.name, profile=profile):
            try:
                rec = aot_compile(entry)
            except Exception as e:  # record, keep warming the rest
                rec = {"name": entry.name,
                       "error": f"{type(e).__name__}: {e}"[:200]}
        rec["profile"] = profile
        rows.append(rec)
        log.info("warmup %-40s trace %.2fs compile %.2fs %s",
                 rec.get("name"), rec.get("trace_s", 0.0),
                 rec.get("compile_s", 0.0),
                 "HIT" if rec.get("cache_hit") else
                 ("ERROR" if "error" in rec else "miss"))

    # the bench child's wall is not only its entry-point compiles: building
    # the grid inputs (pack synthesis on a cold machine, memmap ingest,
    # month-end aggregation) compiles auxiliary kernels and eager ops of
    # its own.  Run the SAME builders here so all of that is warm too —
    # the pack lands in /tmp, the aux compiles land in the cache.
    inputs_note = "skipped: no bench profile requested"
    if any(p.startswith("bench") for p in profiles):
        from csmom_tpu.compile.workloads import (
            NORTH_STAR_GRID,
            REDUCED_GRID,
            grid_month_inputs,
        )

        sizes = ([REDUCED_GRID, NORTH_STAR_GRID]
                 if "bench-cpu" in profiles else [NORTH_STAR_GRID])
        t0_in = time.perf_counter()
        try:
            for A, T in sizes:
                grid_month_inputs(A, T, dtype)
            inputs_note = (f"grid month panels built for {sizes} in "
                           f"{time.perf_counter() - t0_in:.1f}s "
                           "(pack + aux kernels warmed)")
        except Exception as e:
            inputs_note = f"failed: {type(e).__name__}: {e}"[:200]

    golden_note = "skipped: include_golden_event=False"
    if include_golden_event and any(p.startswith("bench") for p in profiles):
        # resolve the event engine at the REAL golden shapes; building the
        # inputs executes the intraday pipeline, warming its kernels too.
        # Off-CPU, also the 32-wide vmapped batch (bench's RTT-amortizing
        # TPU leg; on CPU bench skips it, so compiling it would be waste)
        try:
            for entry in golden_event_entries(dtype,
                                              batch=None if on_cpu else 32):
                rec = aot_compile(entry)
                rec["profile"] = "golden-event"
                rows.append(rec)
            measure_rtt(dtype)  # bench's first compile is the RTT tiny op
            golden_note = "resolved from the golden input build"
        except Exception as e:
            golden_note = f"failed: {type(e).__name__}: {e}"[:200]

    total = compile_stats().delta(base)
    from csmom_tpu.obs import memstats

    peaks = {r["name"]: memstats.peak_bytes(r.get("memory")) for r in rows}
    measured = {k: v for k, v in peaks.items() if v is not None}
    report = {
        "metric": "aot_warmup",
        "utc": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "platform": platform,
        "profiles": list(profiles),
        "cache_dir": cache_dir or "disabled (CSMOM_JIT_CACHE=0)",
        "n_entries": len(rows),
        "n_cache_hits": sum(1 for r in rows if r.get("cache_hit")),
        "n_errors": sum(1 for r in rows if "error" in r),
        "input_builders": inputs_note,
        "golden_event": golden_note,
        "wall_s": round(time.perf_counter() - t_start, 2),
        "totals": total.as_dict(),
        # manifest-level memory digest (per-shape detail rides in each
        # entry's "memory" dict): which shape claims the most device
        # memory, so a report reader sees the binding shape first
        "memory": (
            {
                "n_shapes_measured": len(measured),
                "max_peak_bytes": max(measured.values()),
                "max_peak_entry": max(measured, key=measured.get),
            }
            if measured else
            "not measured: no entry produced a memory analysis"
        ),
        "entries": rows,
    }
    if write_report and cache_dir:
        path = os.path.join(cache_dir, REPORT_NAME)
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(report, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
    return report


def read_warmup_report(subdir: str = "bench") -> dict | str:
    """The most recent warmup report for ``subdir``'s cache dir, or a
    reason string.  Used by bench to attach warm-start provenance to the
    FULL record without re-running the warmup."""
    from csmom_tpu.utils.jit_cache import cache_dir

    d = cache_dir(subdir)
    if d is None:
        return "not available: persistent cache disabled (CSMOM_JIT_CACHE=0)"
    path = os.path.join(d, REPORT_NAME)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return (f"not available: no warmup report at {path} — run "
                "`csmom warmup` (or let bench's supervisor fire one)")
