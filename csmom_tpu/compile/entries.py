"""Shared jitted entry wrappers for the grid/event hot path.

bench.py and the AOT warmup must compile BYTE-IDENTICAL programs or the
serialized-executable cache cannot connect them.  The jit-of-a-lambda
wrappers bench used to build inline (grid -> in-jit scalar reduction, so
each timed rep is one dispatch + one 4-byte fetch) therefore live here,
``lru_cache``d so every caller in one process shares one callable and
every caller across processes lowers the same HLO module.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np


@lru_cache(maxsize=64)
def grid_scalar_fn(Js: tuple, Ks: tuple, skip: int, mode: str, impl: str):
    """The grid hot entry: full J x K backtest -> in-jit scalar, one
    dispatch per call.  ``Js``/``Ks`` are baked in as compile-time
    constants (tuples, hashable), matching bench's closed-over arrays."""
    import jax

    from csmom_tpu.backtest.grid import jk_grid_backtest

    Js_a = np.asarray(Js)
    Ks_a = np.asarray(Ks)
    return jax.jit(
        lambda p, v: jk_grid_backtest(
            p, v, Js_a, Ks_a, skip=skip, mode=mode, impl=impl
        ).mean_spread.sum()
    )


@lru_cache(maxsize=8)
def batched_event_fn(batch: int):
    """The TPU RTT-amortizing leg: a ``batch``-wide vmapped event backtest
    summed to one scalar (bench's throughput number for sweeps)."""
    import jax

    from csmom_tpu.backtest.event import event_backtest

    def fn(price, valid, bscore, adv, vol):
        return jax.vmap(
            lambda sc: event_backtest(price, valid, sc, adv, vol).total_pnl
        )(bscore).sum()

    return jax.jit(fn)


@lru_cache(maxsize=8)
def histrank_labels_fn(n_bins: int):
    """Single-device histogram-rank labels (the sort-free binning kernel;
    with ``axis_name=None`` the collectives degenerate to identities)."""
    import jax

    from csmom_tpu.parallel.histrank import histogram_rank_labels

    return jax.jit(
        lambda x, v: histogram_rank_labels(x, v, n_bins, axis_name=None)
    )
