"""Shape manifest: every hot jitted entry point + its canonical shapes.

The manifest is the warm-start pipeline's contract: the list of
``(jitted function, argument shapes)`` pairs that ``csmom warmup`` AOT
compiles so a later process — a bench child inside a tunnel window, a
CLI invocation — finds every hot shape already serialized in the
persistent executable cache (``utils.jit_cache``).

Two properties keep it honest:

- **no drift**: every entry is BOUND against its function's real
  signature (``inspect.signature(...).bind``) at validation time, so a
  renamed, removed, or re-ordered parameter breaks manifest construction
  loudly instead of letting warmup compile a stale call;
- **no duplicate shape definitions**: panel sizes come from
  :mod:`csmom_tpu.compile.workloads` (the same constants bench builds its
  inputs from) and month counts are derived from the same calendar
  generator the packs use — there is no hand-maintained shape table to
  fall out of sync.

Entries cover the hot jitted computations across the engine layers:
``backtest/grid.py`` (``_jk_grid_backtest`` plain + donated, and
``_grid_net_core``), ``backtest/monthly.py``'s three jitted kernels,
``backtest/event.py``'s panel engines (threshold + hysteresis, plain +
donated), ``parallel/histrank.py``'s histogram rank, and
``parallel/online_ridge.py``'s time-sharded scan.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Mapping

import numpy as np

from csmom_tpu.compile import workloads as wl


@dataclasses.dataclass(frozen=True)
class ManifestEntry:
    """One hot jitted entry point at one canonical argument signature.

    ``args``/``kwargs`` hold ``jax.ShapeDtypeStruct`` leaves for arrays
    (``fn.lower`` accepts abstract values) and plain Python scalars for
    traced scalars / static arguments.
    """

    name: str
    fn: Callable
    args: tuple
    kwargs: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        """Bind the abstract arguments against the function's signature.

        Raises ``TypeError`` when the manifest and the code have drifted
        (renamed/removed parameter, wrong arity) — the failure mode this
        method exists to surface at warmup/test time instead of silently
        compiling a stale call.
        """
        inspect.signature(self.fn).bind(*self.args, **dict(self.kwargs))

    def shape_summary(self) -> str:
        """Human/record-readable digest of the array arguments."""
        def one(v):
            shape = getattr(v, "shape", None)
            dtype = getattr(v, "dtype", None)
            if shape is None or dtype is None:
                return repr(v)
            return f"{np.dtype(dtype).name}[{','.join(map(str, shape))}]"

        parts = [one(a) for a in self.args]
        parts += [f"{k}={one(v)}" for k, v in self.kwargs.items()]
        return ", ".join(parts)


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def _grid_entries(A: int, M: int, dtype, *, modes_impls, tag: str,
                  donated: bool = False) -> list[ManifestEntry]:
    """Grid scalar entries (the bench hot path) at one panel size, plus —
    when ``donated`` — the donated full-result grid entry point."""
    from csmom_tpu.backtest.grid import _jk_grid_backtest_donated
    from csmom_tpu.compile.entries import grid_scalar_fn

    p = _sds((A, M), dtype)
    m = _sds((A, M), bool)
    out = [
        ManifestEntry(
            name=f"grid.jk16.{mode}.{impl}@{tag}",
            fn=grid_scalar_fn(wl.GRID_JS, wl.GRID_KS, wl.GRID_SKIP, mode, impl),
            args=(p, m),
        )
        for mode, impl in modes_impls
    ]
    if donated:
        idx = np.dtype(np.int64 if np.dtype(dtype) == np.float64 else np.int32)
        out.append(ManifestEntry(
            name=f"grid.jk16.rank.xla.donated@{tag}",
            fn=_jk_grid_backtest_donated,
            args=(p, m, _sds((len(wl.GRID_JS),), idx),
                  _sds((len(wl.GRID_KS),), idx), wl.GRID_SKIP),
            kwargs=dict(n_bins=10, mode="rank", max_hold=max(wl.GRID_KS),
                        freq=12, impl="xla"),
        ))
    return out


def _monthly_entries(A: int, M: int, dtype, tag: str) -> list[ManifestEntry]:
    """The three jitted monthly kernels at the golden monthly panel size."""
    from csmom_tpu.backtest.monthly import (
        monthly_spread_backtest,
        net_of_costs_arrays,
        sector_neutral_backtest,
    )

    p = _sds((A, M), dtype)
    m = _sds((A, M), bool)
    i32 = np.int32
    return [
        ManifestEntry(
            name=f"monthly.spread@{tag}",
            fn=monthly_spread_backtest,
            args=(p, m),
            kwargs=dict(lookback=12, skip=1, n_bins=10, mode="qcut"),
        ),
        ManifestEntry(
            name=f"monthly.sector_neutral@{tag}",
            fn=sector_neutral_backtest,
            args=(p, m, _sds((A,), i32)),
            kwargs=dict(n_sectors=5, lookback=12, skip=1, n_bins=10,
                        mode="qcut"),
        ),
        ManifestEntry(
            name=f"monthly.net_of_costs@{tag}",
            fn=net_of_costs_arrays,
            args=(_sds((A, M), i32), _sds((10, M), i32), _sds((M,), dtype),
                  _sds((M,), bool), 0.0005),
            kwargs=dict(n_bins=10),
        ),
    ]


def _grid_net_entry(A: int, M: int, dtype, tag: str) -> ManifestEntry:
    """``_grid_net_core`` (the CLI --tc-bps netting pass) at the grid size."""
    from csmom_tpu.backtest.grid import _grid_net_core

    nJ, nK = len(wl.GRID_JS), len(wl.GRID_KS)
    idx = np.dtype(np.int64 if np.dtype(dtype) == np.float64 else np.int32)
    return ManifestEntry(
        name=f"grid.net_core@{tag}",
        fn=_grid_net_core,
        args=(_sds((A, M), dtype), _sds((A, M), bool), _sds((nJ,), idx),
              _sds((nJ, nK, M), dtype), _sds((nJ, nK, M), bool), 1.0),
        kwargs=dict(Ks_c=wl.GRID_KS, skip=wl.GRID_SKIP, n_bins=10,
                    mode="rank", freq=12),
    )


def _event_entries(A: int, T: int, dtype, tag: str) -> list[ManifestEntry]:
    """The event panel engines (threshold plain + donated, hysteresis) at
    one minute-panel size."""
    from csmom_tpu.backtest.event import (
        _hysteresis_body,
        event_backtest,
        event_backtest_donated,
    )

    p = _sds((A, T), dtype)
    v = _sds((A, T), bool)
    s = _sds((A, T), dtype)
    a = _sds((A,), dtype)
    vo = _sds((A,), dtype)
    return [
        ManifestEntry(name=f"event.threshold@{tag}", fn=event_backtest,
                      args=(p, v, s, a, vo)),
        ManifestEntry(name=f"event.threshold.donated@{tag}",
                      fn=event_backtest_donated, args=(p, v, s, a, vo)),
        ManifestEntry(
            name=f"event.hysteresis@{tag}", fn=_hysteresis_body,
            args=(p, v, s, a, vo, 1e-4, 1e-5, 50, 1_000_000.0, 0.001),
        ),
    ]


def _histrank_entry(A: int, M: int, dtype, tag: str) -> ManifestEntry:
    from csmom_tpu.compile.entries import histrank_labels_fn

    return ManifestEntry(
        name=f"parallel.histrank@{tag}",
        fn=histrank_labels_fn(10),
        args=(_sds((A, M), dtype), _sds((A, M), bool)),
    )


def _online_ridge_entry(R: int, A: int, F: int, dtype, tag: str) -> ManifestEntry:
    """The time-sharded online-ridge scan on a 1-device mesh (the warmup
    process may not have the test tier's 8 virtual devices; the scan's
    compiled structure is shard-count-generic)."""
    import jax
    from jax.sharding import Mesh

    from csmom_tpu.parallel.online_ridge import _compiled

    mesh = Mesh(np.array(jax.devices()[:1]), ("time",))
    fn = _compiled(mesh, "time", A, F, np.dtype(dtype), 1.0, 8, True)
    return ManifestEntry(
        name=f"parallel.online_ridge@{tag}",
        fn=fn,
        args=(_sds((R, A, F), dtype), _sds((R, A), dtype), _sds((R, A), dtype)),
    )


# month counts for the grid panel sizes are derived from the pack calendar
# (cached here per process; workloads.months_in_days is the single source)
_MONTH_CACHE: dict[int, int] = {}


def _months(T: int) -> int:
    if T not in _MONTH_CACHE:
        _MONTH_CACHE[T] = wl.months_in_days(T)
    return _MONTH_CACHE[T]


def _serve_entries(profile: str, dtype=None) -> list[ManifestEntry]:
    """The serve bucket grid: every (endpoint, batch, assets) shape the
    signal service may dispatch (:mod:`csmom_tpu.serve.buckets`).

    The entries wrap the SAME ``lru_cache``-shared jitted callables the
    live service dispatches (``serve.engine.serve_entry_fn`` at the
    ``ServeConfig`` defaults), so ``csmom warmup --profiles serve``
    AOT-persists byte-identical HLO and a restarted service loads every
    bucket executable from disk instead of compiling at startup."""
    from csmom_tpu.serve.buckets import ENDPOINTS, bucket_spec
    from csmom_tpu.serve.engine import serve_entry_fn
    from csmom_tpu.serve.service import ServeConfig

    spec = bucket_spec(profile)
    dt = np.dtype(dtype or spec.dtype)
    cfg = ServeConfig()  # the single source of the service's signal params
    out = []
    for kind in ENDPOINTS:
        fn = serve_entry_fn(kind, cfg.lookback, cfg.skip, cfg.n_bins,
                            cfg.mode)
        for B, A, M in spec.shapes():
            out.append(ManifestEntry(
                name=f"serve.{kind}.b{B}@{A}x{M}",
                fn=fn,
                args=(_sds((B, A, M), dt), _sds((B, A, M), bool)),
            ))
    return out


def _stream_entries(profile: str, dtype=None) -> list[ManifestEntry]:
    """The event-time replay's on-device reconciliation entries: the
    REAL jitted ``signals`` engines (momentum + turnover) at the
    canonical replay panel shapes (:mod:`csmom_tpu.stream.replay` —
    serve asset buckets x the replay bar count), so a jax-engine
    replay's periodic full-panel reconciliation dispatches only warmed
    shapes and the whole window stays zero-compile."""
    from csmom_tpu.serve.buckets import bucket_spec
    from csmom_tpu.signals.momentum import momentum
    from csmom_tpu.signals.turnover import turnover_features
    from csmom_tpu.stream.replay import (
        REPLAY_BARS,
        REPLAY_SMOKE_BARS,
        ReplayConfig,
    )

    smoke = profile == "stream-smoke"
    spec = bucket_spec("serve-smoke" if smoke else "serve")
    bars = REPLAY_SMOKE_BARS if smoke else REPLAY_BARS
    cfg = ReplayConfig()  # the single source of the replay signal params
    dt = np.dtype(dtype or cfg.dtype)
    out = []
    for A in spec.asset_buckets:
        p = _sds((A, bars), dt)
        m = _sds((A, bars), bool)
        out.append(ManifestEntry(
            name=f"stream.momentum@{A}x{bars}",
            fn=momentum, args=(p, m),
            kwargs=dict(lookback=cfg.lookback, skip=cfg.skip),
        ))
        out.append(ManifestEntry(
            name=f"stream.turn_avg@{A}x{bars}",
            fn=turnover_features,
            args=(p, m, _sds((A,), dt)),
            kwargs=dict(lookback=cfg.turn_lookback),
        ))
    return out


PROFILES = ("bench-cpu", "bench-tpu", "golden", "smoke", "serve",
            "serve-smoke", "stream", "stream-smoke")


def build_manifest(profile: str, dtype=None) -> list[ManifestEntry]:
    """Manifest entries for one warmup profile.

    Profiles:

    - ``"bench-cpu"``: every shape a CPU bench child compiles
      unconditionally or budget-permitting — the golden event panel, the
      reduced 512-stock grid (rank/qcut/matmul + donated), the full
      north-star-size grid legs (rank xla/matmul), and the netting core.
      f64 (bench enables x64 on CPU).
    - ``"bench-tpu"``: the accelerator child's shapes — golden event
      (+32-wide batched), the north-star grid in every impl, netting
      core.  f32.
    - ``"golden"``: the CLI-facing reference-scale kernels — monthly
      spread / sector-neutral / net-of-costs at the 20-ticker monthly
      panel, histrank, online ridge.
    - ``"smoke"``: tiny shapes of every entry kind — the test tier's
      profile (fast to compile, exercises every manifest code path).
    - ``"serve"`` / ``"serve-smoke"``: the signal service's bucket grids
      (``csmom_tpu.serve.buckets``) — every (endpoint, batch, assets)
      shape a micro-batch dispatch may take, at the service's own jitted
      entries.  f32 (the serve compute dtype).
    - ``"stream"`` / ``"stream-smoke"``: the event-time replay's
      on-device reconciliation entries — the jitted ``signals`` engines
      at the canonical replay panel shapes.  f32.

    ``dtype`` overrides the profile's default float dtype.
    """
    if profile == "bench-cpu":
        dt = np.dtype(dtype or np.float64)
        A_r, T_r = wl.REDUCED_GRID
        A_f, T_f = wl.NORTH_STAR_GRID
        M_r, M_f = _months(T_r), _months(T_f)
        entries = _grid_entries(
            A_r, M_r, dt, tag=f"{A_r}x{M_r}", donated=True,
            modes_impls=[("rank", "xla"), ("qcut", "xla"), ("rank", "matmul")],
        )
        entries += _grid_entries(
            A_f, M_f, dt, tag=f"{A_f}x{M_f}",
            modes_impls=[("rank", "xla"), ("rank", "matmul")],
        )
        entries.append(_grid_net_entry(A_r, M_r, dt, tag=f"{A_r}x{M_r}"))
        return entries
    if profile == "bench-tpu":
        dt = np.dtype(dtype or np.float32)
        A_f, T_f = wl.NORTH_STAR_GRID
        M_f = _months(T_f)
        entries = _grid_entries(
            A_f, M_f, dt, tag=f"{A_f}x{M_f}", donated=True,
            modes_impls=[("rank", "xla"), ("qcut", "xla"), ("rank", "matmul"),
                         ("rank", "matmul_bf16"), ("rank", "pallas")],
        )
        entries.append(_grid_net_entry(A_f, M_f, dt, tag=f"{A_f}x{M_f}"))
        return entries
    if profile == "golden":
        dt = np.dtype(dtype or np.float64)
        A, M = 20, 60  # the 20-ticker demo universe, ~5y of months
        entries = _monthly_entries(A, M, dt, tag=f"{A}x{M}")
        entries.append(_histrank_entry(4096, 120, np.float32, tag="4096x120"))
        entries.append(_online_ridge_entry(64, 8, 4, dt, tag="64x8x4"))
        return entries
    if profile == "smoke":
        dt = np.dtype(dtype or np.float64)
        entries = _grid_entries(
            16, 48, dt, tag="16x48", donated=True,
            modes_impls=[("rank", "xla")],
        )
        entries += _monthly_entries(8, 24, dt, tag="8x24")
        entries.append(_grid_net_entry(16, 48, dt, tag="16x48"))
        entries += _event_entries(4, 32, dt, tag="4x32")
        entries.append(_histrank_entry(32, 6, np.float32, tag="32x6"))
        entries.append(_online_ridge_entry(12, 3, 2, dt, tag="12x3x2"))
        return entries
    if profile in ("serve", "serve-smoke"):
        # the online workload's closed shape world: warm it before
        # starting a service and the request path never compiles
        return _serve_entries(profile, dtype)
    if profile in ("stream", "stream-smoke"):
        # the replay reconciliation's closed shape world (ISSUE 7): warm
        # it (with the matching serve profile) before a jax-engine
        # replay and the whole window stays zero-compile
        return _stream_entries(profile, dtype)
    raise ValueError(f"unknown warmup profile {profile!r}: use one of {PROFILES}")


def golden_event_entries(dtype, batch: int | None = None) -> list[ManifestEntry]:
    """Event-engine entries at the ACTUAL golden workload shapes.

    The golden minute-panel length depends on the data (reference mount
    present or the synthetic fallback), so these shapes are resolved by
    building the golden inputs through the same
    :func:`csmom_tpu.compile.workloads.golden_event_inputs` path bench
    uses — which also warms every upstream pipeline kernel as a side
    effect.  Separated from :func:`build_manifest` because resolving them
    runs the pipeline (seconds), which tests and shape listings should
    not pay.

    ``batch``: when given, also include the ``batch``-wide vmapped event
    entry (bench's TPU RTT-amortizing leg, skipped on CPU).
    """
    from csmom_tpu.compile.entries import batched_event_fn

    price, valid, score, adv, vol, _ = wl.golden_event_inputs(np.dtype(dtype))
    A, T = price.shape
    dt = np.dtype(dtype)
    entries = _event_entries(A, T, dt, tag=f"golden{A}x{T}")
    if batch:
        p = _sds((A, T), dt)
        v = _sds((A, T), bool)
        entries.append(ManifestEntry(
            name=f"event.batched{batch}@golden{A}x{T}",
            fn=batched_event_fn(batch),
            args=(p, v, _sds((batch, A, T), dt), _sds((A,), dt),
                  _sds((A,), dt)),
        ))
    return entries
