"""Shape manifest: every hot jitted entry point + its canonical shapes.

The manifest is the warm-start pipeline's contract: the list of
``(jitted function, argument shapes)`` pairs that ``csmom warmup`` AOT
compiles so a later process — a bench child inside a tunnel window, a
CLI invocation — finds every hot shape already serialized in the
persistent executable cache (``utils.jit_cache``).

Three properties keep it honest:

- **no drift**: every entry is BOUND against its function's real
  signature (``inspect.signature(...).bind``) at validation time, so a
  renamed, removed, or re-ordered parameter breaks manifest construction
  loudly instead of letting warmup compile a stale call;
- **no duplicate shape definitions**: panel sizes come from
  :mod:`csmom_tpu.compile.workloads` (the same constants bench builds its
  inputs from) and month counts are derived from the same calendar
  generator the packs use — there is no hand-maintained shape table to
  fall out of sync;
- **no per-module profile tables** (ISSUE 9): which engines feed which
  warmup profile, at which shapes, is declared on the engine's
  registration (:mod:`csmom_tpu.registry`).  :func:`build_manifest` is a
  registry QUERY — the per-profile ``if/elif`` dispatch this module used
  to own is gone, so a newly registered engine (including one registered
  at runtime) AOT-warms and memory-profiles with no edit here.

This module keeps the manifest DATA MODEL (:class:`ManifestEntry`) and
the shape-binding helpers the registered engines build their entries
from (``grid_entries``/``monthly_entries``/... — given a panel size,
produce bound entries); the enumeration of who uses them lives in
:mod:`csmom_tpu.registry.builtin`.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Any, Callable, Mapping

import numpy as np

from csmom_tpu.compile import workloads as wl

__all__ = [
    "ManifestEntry",
    "build_manifest",
    "event_entries",
    "golden_event_entries",
    "grid_entries",
    "grid_net_entry",
    "histrank_entry",
    "monthly_entries",
    "months_of",
    "online_ridge_entry",
    "sds",
]


@dataclasses.dataclass(frozen=True)
class ManifestEntry:
    """One hot jitted entry point at one canonical argument signature.

    ``args``/``kwargs`` hold ``jax.ShapeDtypeStruct`` leaves for arrays
    (``fn.lower`` accepts abstract values) and plain Python scalars for
    traced scalars / static arguments.
    """

    name: str
    fn: Callable
    args: tuple
    kwargs: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        """Bind the abstract arguments against the function's signature.

        Raises ``TypeError`` when the manifest and the code have drifted
        (renamed/removed parameter, wrong arity) — the failure mode this
        method exists to surface at warmup/test time instead of silently
        compiling a stale call.
        """
        inspect.signature(self.fn).bind(*self.args, **dict(self.kwargs))

    def shape_summary(self) -> str:
        """Human/record-readable digest of the array arguments."""
        def one(v):
            shape = getattr(v, "shape", None)
            dtype = getattr(v, "dtype", None)
            if shape is None or dtype is None:
                return repr(v)
            return f"{np.dtype(dtype).name}[{','.join(map(str, shape))}]"

        parts = [one(a) for a in self.args]
        parts += [f"{k}={one(v)}" for k, v in self.kwargs.items()]
        return ", ".join(parts)


def sds(shape, dtype):
    """A ``jax.ShapeDtypeStruct`` leaf (the manifest's abstract array)."""
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


# ---------------------------------------------------------------------------
# shape-binding helpers: given ONE panel size, produce bound entries.
# The registry's builtin specs call these with their declared shapes.
# ---------------------------------------------------------------------------

def grid_entries(A: int, M: int, dtype, *, modes_impls, tag: str,
                 donated: bool = False) -> list[ManifestEntry]:
    """Grid scalar entries (the bench hot path) at one panel size, plus —
    when ``donated`` — the donated full-result grid entry point."""
    from csmom_tpu.backtest.grid import _jk_grid_backtest_donated
    from csmom_tpu.compile.entries import grid_scalar_fn

    p = sds((A, M), dtype)
    m = sds((A, M), bool)
    out = [
        ManifestEntry(
            name=f"grid.jk16.{mode}.{impl}@{tag}",
            fn=grid_scalar_fn(wl.GRID_JS, wl.GRID_KS, wl.GRID_SKIP, mode, impl),
            args=(p, m),
        )
        for mode, impl in modes_impls
    ]
    if donated:
        idx = np.dtype(np.int64 if np.dtype(dtype) == np.float64 else np.int32)
        out.append(ManifestEntry(
            name=f"grid.jk16.rank.xla.donated@{tag}",
            fn=_jk_grid_backtest_donated,
            args=(p, m, sds((len(wl.GRID_JS),), idx),
                  sds((len(wl.GRID_KS),), idx), wl.GRID_SKIP),
            kwargs=dict(n_bins=10, mode="rank", max_hold=max(wl.GRID_KS),
                        freq=12, impl="xla"),
        ))
    return out


def monthly_entries(A: int, M: int, dtype, tag: str) -> list[ManifestEntry]:
    """The three jitted monthly kernels at the golden monthly panel size."""
    from csmom_tpu.backtest.monthly import (
        monthly_spread_backtest,
        net_of_costs_arrays,
        sector_neutral_backtest,
    )

    p = sds((A, M), dtype)
    m = sds((A, M), bool)
    i32 = np.int32
    return [
        ManifestEntry(
            name=f"monthly.spread@{tag}",
            fn=monthly_spread_backtest,
            args=(p, m),
            kwargs=dict(lookback=12, skip=1, n_bins=10, mode="qcut"),
        ),
        ManifestEntry(
            name=f"monthly.sector_neutral@{tag}",
            fn=sector_neutral_backtest,
            args=(p, m, sds((A,), i32)),
            kwargs=dict(n_sectors=5, lookback=12, skip=1, n_bins=10,
                        mode="qcut"),
        ),
        ManifestEntry(
            name=f"monthly.net_of_costs@{tag}",
            fn=net_of_costs_arrays,
            args=(sds((A, M), i32), sds((10, M), i32), sds((M,), dtype),
                  sds((M,), bool), 0.0005),
            kwargs=dict(n_bins=10),
        ),
    ]


def grid_net_entry(A: int, M: int, dtype, tag: str) -> ManifestEntry:
    """``_grid_net_core`` (the CLI --tc-bps netting pass) at the grid size."""
    from csmom_tpu.backtest.grid import _grid_net_core

    nJ, nK = len(wl.GRID_JS), len(wl.GRID_KS)
    idx = np.dtype(np.int64 if np.dtype(dtype) == np.float64 else np.int32)
    return ManifestEntry(
        name=f"grid.net_core@{tag}",
        fn=_grid_net_core,
        args=(sds((A, M), dtype), sds((A, M), bool), sds((nJ,), idx),
              sds((nJ, nK, M), dtype), sds((nJ, nK, M), bool), 1.0),
        kwargs=dict(Ks_c=wl.GRID_KS, skip=wl.GRID_SKIP, n_bins=10,
                    mode="rank", freq=12),
    )


def event_entries(A: int, T: int, dtype, tag: str) -> list[ManifestEntry]:
    """The event panel engines (threshold plain + donated, hysteresis) at
    one minute-panel size."""
    from csmom_tpu.backtest.event import (
        _hysteresis_body,
        event_backtest,
        event_backtest_donated,
    )

    p = sds((A, T), dtype)
    v = sds((A, T), bool)
    s = sds((A, T), dtype)
    a = sds((A,), dtype)
    vo = sds((A,), dtype)
    return [
        ManifestEntry(name=f"event.threshold@{tag}", fn=event_backtest,
                      args=(p, v, s, a, vo)),
        ManifestEntry(name=f"event.threshold.donated@{tag}",
                      fn=event_backtest_donated, args=(p, v, s, a, vo)),
        ManifestEntry(
            name=f"event.hysteresis@{tag}", fn=_hysteresis_body,
            args=(p, v, s, a, vo, 1e-4, 1e-5, 50, 1_000_000.0, 0.001),
        ),
    ]


def histrank_entry(A: int, M: int, dtype, tag: str) -> ManifestEntry:
    from csmom_tpu.compile.entries import histrank_labels_fn

    return ManifestEntry(
        name=f"parallel.histrank@{tag}",
        fn=histrank_labels_fn(10),
        args=(sds((A, M), dtype), sds((A, M), bool)),
    )


def online_ridge_entry(R: int, A: int, F: int, dtype,
                       tag: str) -> ManifestEntry:
    """The time-sharded online-ridge scan on a 1-device mesh (the warmup
    process may not have the test tier's 8 virtual devices; the scan's
    compiled structure is shard-count-generic)."""
    import jax
    from jax.sharding import Mesh

    from csmom_tpu.parallel.online_ridge import _compiled

    mesh = Mesh(np.array(jax.devices()[:1]), ("time",))
    fn = _compiled(mesh, "time", A, F, np.dtype(dtype), 1.0, 8, True)
    return ManifestEntry(
        name=f"parallel.online_ridge@{tag}",
        fn=fn,
        args=(sds((R, A, F), dtype), sds((R, A), dtype), sds((R, A), dtype)),
    )


# month counts for the grid panel sizes are derived from the pack calendar
# (cached here per process; workloads.months_in_days is the single source)
_MONTH_CACHE: dict[int, int] = {}


def months_of(T: int) -> int:
    if T not in _MONTH_CACHE:
        _MONTH_CACHE[T] = wl.months_in_days(T)
    return _MONTH_CACHE[T]


def build_manifest(profile: str, dtype=None) -> list[ManifestEntry]:
    """Manifest entries for one warmup profile — a registry query.

    The profile's contents are whatever the registered engines declared
    (:mod:`csmom_tpu.registry.builtin` for the builtins): the bench grid
    shapes, the golden/smoke kernels, the serve bucket grid generated
    from the live endpoint registry, the stream reconcile entries.
    ``dtype`` overrides the profile's default float dtype.
    """
    from csmom_tpu.registry import manifest_entries

    return manifest_entries(profile, dtype)


def __getattr__(name: str):
    if name == "PROFILES":
        # derived from the registry, not a literal: the set of profiles
        # is exactly what registered engines declared
        from csmom_tpu.registry import manifest_profiles

        return manifest_profiles()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def golden_event_entries(dtype, batch: int | None = None) -> list[ManifestEntry]:
    """Event-engine entries at the ACTUAL golden workload shapes.

    The golden minute-panel length depends on the data (reference mount
    present or the synthetic fallback), so these shapes are resolved by
    building the golden inputs through the same
    :func:`csmom_tpu.compile.workloads.golden_event_inputs` path bench
    uses — which also warms every upstream pipeline kernel as a side
    effect.  Separated from :func:`build_manifest` because resolving them
    runs the pipeline (seconds), which tests and shape listings should
    not pay.

    ``batch``: when given, also include the ``batch``-wide vmapped event
    entry (bench's TPU RTT-amortizing leg, skipped on CPU).
    """
    from csmom_tpu.compile.entries import batched_event_fn

    price, valid, score, adv, vol, _ = wl.golden_event_inputs(np.dtype(dtype))
    A, T = price.shape
    dt = np.dtype(dtype)
    entries = event_entries(A, T, dt, tag=f"golden{A}x{T}")
    if batch:
        p = sds((A, T), dt)
        v = sds((A, T), bool)
        entries.append(ManifestEntry(
            name=f"event.batched{batch}@golden{A}x{T}",
            fn=batched_event_fn(batch),
            args=(p, v, sds((batch, A, T), dt), sds((A,), dt),
                  sds((A,), dt)),
        ))
    return entries
