"""Canonical hot-path workloads shared by ``bench.py`` and the AOT warmup.

Single source of the argument SHAPES the warm-start pipeline promises to
have compiled before a tunnel window opens.  bench and ``csmom warmup``
build their inputs through these same functions, so the
serialized-executable cache one of them writes is hit by the other by
construction — the shapes cannot drift apart because there is only one
definition of each workload:

- the **golden event workload**: the reference's own 20-ticker x ~2,728
  minute panel (or the synthesized same-shape fallback when the
  reference mount is absent) — bench's headline metric;
- the **reduced CPU grid**: 512 stocks x 3,780 days, the CPU fallback's
  16-cell J x K grid;
- the **north-star grid**: 3,000 stocks x 15,120 days (720 months), the
  on-chip record workload.

Everything here is host-side input building (CSV/pack ingest, synthetic
generation, month-end aggregation); the jitted entry points these feed
live in :mod:`csmom_tpu.compile.entries` and the engine modules.
"""

from __future__ import annotations

import os
import time

import numpy as np

REFERENCE_DATA = "/root/reference/data"
DEMO_TICKERS = [
    "AAPL", "MSFT", "AMZN", "GOOGL", "NVDA", "TSLA", "META", "JPM", "BAC", "WMT",
    "PG", "KO", "DIS", "CSCO", "ORCL", "INTC", "AMD", "NFLX", "C", "GS",
]

# grid parameter canon (BASELINE.json): 16 cells, J/K in {3, 6, 9, 12}
GRID_JS = (3, 6, 9, 12)
GRID_KS = (3, 6, 9, 12)
GRID_SKIP = 1

# panel sizes (assets, days): the CPU fallback's reduced grid and the
# north-star on-chip workload
REDUCED_GRID = (512, 3780)
NORTH_STAR_GRID = (3000, 15120)


def bench_platform(jax_mod=None):
    """``(platform, on_cpu, dtype)`` under bench's platform policy: f64 on
    CPU (x64 enabled, oracle-tight), f32 on accelerators.  Shared so a
    warmup process resolves the exact dtypes a bench child will compile."""
    import jax

    jax = jax_mod or jax
    platform = jax.devices()[0].platform
    on_cpu = platform == "cpu"
    if on_cpu:
        jax.config.update("jax_enable_x64", True)
    return platform, on_cpu, (np.float64 if on_cpu else np.float32)


def golden_event_inputs(dtype):
    """Dense minute panels for the event engine, from the shipped caches (or
    a synthesized same-shape workload when the reference data is absent).

    Returns ``(price, valid, score, adv, vol, n_trades)`` — the exact
    argument set (and shapes) of bench's headline ``event_backtest`` call.
    Building these runs the full intraday pipeline, which warms every
    upstream kernel (features, model CV, the event engine itself) through
    the persistent cache as a side effect — deliberate: a warmup that
    skipped the pipeline would leave those compiles to the bench window.
    """
    import jax.numpy as jnp

    from csmom_tpu.api import daily_risk_maps, intraday_pipeline, synthetic_minute_frame
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    if os.path.isdir(REFERENCE_DATA):
        minute_df = load_intraday(REFERENCE_DATA, DEMO_TICKERS)
        daily_df = load_daily(REFERENCE_DATA, [t for t in DEMO_TICKERS if t != "AAPL"])
    else:  # pragma: no cover
        from csmom_tpu.panel.synthetic import synthetic_daily_panel

        daily = synthetic_daily_panel(20, 7, seed=0)
        daily_df = None
        minute_df = synthetic_minute_frame(
            __import__("pandas").DataFrame(
                {
                    "date": np.repeat(daily.times, 20),
                    "ticker": np.tile(daily.tickers, 7),
                    "open": daily.values.T.ravel(),
                    "close": daily.values.T.ravel(),
                    "volume": 1e6,
                }
            )
        )
    res, fit, compact, dense_score, dense_price, dense_valid = intraday_pipeline(
        minute_df, daily_df, dtype=dtype
    )
    adv, vol = daily_risk_maps(daily_df, compact.tickers)
    return (
        jnp.asarray(dense_price, dtype),
        jnp.asarray(dense_valid),
        jnp.nan_to_num(jnp.asarray(dense_score, dtype)),
        jnp.asarray(adv, dtype),
        jnp.asarray(vol, dtype),
        int(res.n_trades),
    )


def ensure_pack(A: int, T: int) -> str:
    """Create-if-missing the synthetic daily pack, atomically; returns its dir.

    Keyed by SYNTH_VERSION so a generator edit can never serve stale
    panels; built in a pid-suffixed temp dir and os.rename'd into place so
    concurrent runs cannot read a half-written pack (rename is atomic; the
    loser just removes its own temp copy).
    """
    import shutil
    import tempfile

    from csmom_tpu.panel.pack import save_packed
    from csmom_tpu.panel.synthetic import SYNTH_VERSION, synthetic_daily_panel

    d = os.path.join(
        tempfile.gettempdir(),
        f"csmom_pack_s{SYNTH_VERSION}_{A}x{T}_seed7",
    )
    if not os.path.exists(os.path.join(d, "meta.json")):
        tmp = f"{d}.build{os.getpid()}"
        save_packed(
            synthetic_daily_panel(A, T, seed=7, listing_gaps=True), tmp
        )
        try:
            os.rename(tmp, d)
        except OSError:  # lost the race: someone else's pack is in place
            shutil.rmtree(tmp, ignore_errors=True)
    return d


def grid_month_inputs(A: int, T: int, dtype):
    """Month-end grid panels from the packed binary cache.

    Returns ``(pm, mm, M, pack_ingest_s)`` — device month-end price/mask
    panels, the month count, and the measured disk -> host wall of the
    memmapped pack read (the number that replaces a CSV parse at scale).
    The pack build (if cold) happens OUTSIDE the timed region.
    """
    import jax.numpy as jnp

    from csmom_tpu.panel.calendar import month_end_aggregate, month_end_segments
    from csmom_tpu.panel.pack import load_packed

    pack_dir = ensure_pack(A, T)
    t0 = time.perf_counter()
    panel = load_packed(pack_dir)  # memmap: pages fault in on first touch
    # copy=True forces the full read inside the timed window — with a
    # matching dtype, ascontiguousarray on a memmap is a zero-copy view and
    # the pages would otherwise fault in later, under someone else's timer
    host_values = np.array(panel.values, dtype=dtype, copy=True)
    host_mask = np.array(panel.mask, copy=True)
    pack_ingest_s = time.perf_counter() - t0
    seg, ends = month_end_segments(panel.times)
    v, m = jnp.asarray(host_values), jnp.asarray(host_mask)
    pm, mm = month_end_aggregate(v, m, seg, len(ends))
    return pm, mm, len(ends), pack_ingest_s


def months_in_days(T: int) -> int:
    """Month count of the synthetic pack calendar for ``T`` business days —
    the grid panels' time axis, derived from the SAME calendar generator the
    pack uses (no hardcoded month constants to drift)."""
    from csmom_tpu.panel.calendar import month_end_segments
    from csmom_tpu.panel.synthetic import synthetic_daily_panel

    times = synthetic_daily_panel(1, T, seed=7).times
    _, ends = month_end_segments(times)
    return len(ends)
