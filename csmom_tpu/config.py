"""Typed run configuration + TOML loader.

The reference has no config system at all — every parameter is a hardcoded
constant (universe at ``run_demo.py:15-16``, dates ``:196``, J/skip ``:32``,
cash/size/threshold ``:170,180``, impact constants
``execution_models.py:4,9``).  Here the same knobs are one frozen dataclass
tree; the defaults reproduce the reference's constants exactly, so a
default-constructed ``RunConfig()`` *is* parity mode.

Loadable from TOML (stdlib ``tomllib``): top-level tables mirror the
dataclass names, unknown keys are rejected loudly (a typo'd knob must not
silently fall back to a default).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

# the reference demo's hardcoded 20-name universe (run_demo.py:15-16)
DEFAULT_TICKERS = (
    "AAPL", "MSFT", "AMZN", "GOOGL", "NVDA", "TSLA", "META", "JPM", "BAC", "WMT",
    "PG", "KO", "DIS", "CSCO", "ORCL", "INTC", "AMD", "NFLX", "C", "GS",
)


@dataclasses.dataclass(frozen=True)
class UniverseConfig:
    """What to trade and when (run_demo.py:15-16,196)."""

    tickers: Sequence[str] = DEFAULT_TICKERS
    start: str = "2018-01-01"
    end: str = "2024-12-31"
    data_dir: str = "data"


@dataclasses.dataclass(frozen=True)
class MomentumConfig:
    """Formation/holding parameters (run_demo.py:32; features.py:5)."""

    lookback: int = 12
    skip: int = 1
    n_bins: int = 10
    mode: str = "qcut"          # 'qcut' parity | 'rank' fast
    holding: int = 1            # K (reference holds 1 month)
    turnover_lookback: int = 3  # turn_avg window (features.py:60 lookback=3)


@dataclasses.dataclass(frozen=True)
class GridConfig:
    """J x K sweep axes (Lee-Swaminathan / Jegadeesh-Titman grid)."""

    Js: Sequence[int] = (3, 6, 9, 12)
    Ks: Sequence[int] = (3, 6, 9, 12)
    walk_forward_min_months: int = 24


@dataclasses.dataclass(frozen=True)
class CostConfig:
    """Execution model constants (execution_models.py:4-12)."""

    impact_k: float = 0.1
    impact_expo: float = 0.5
    spread: float = 0.001       # full spread, 10 bp
    half_spread_monthly: float = 0.0005  # linear cost on monthly turnover


@dataclasses.dataclass(frozen=True)
class IntradayConfig:
    """Minute pipeline + event backtest knobs (run_demo.py:86,140,170,180)."""

    window_minutes: int = 30
    n_splits: int = 3
    alpha: float = 1.0
    train_frac: float = 0.7
    size_shares: int = 50
    threshold: float = 1e-5
    cash0: float = 1_000_000.0


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """Complete run description; the default is reference parity mode."""

    universe: UniverseConfig = UniverseConfig()
    momentum: MomentumConfig = MomentumConfig()
    grid: GridConfig = GridConfig()
    costs: CostConfig = CostConfig()
    intraday: IntradayConfig = IntradayConfig()
    results_dir: str = "results"   # run_demo.py:12
    backend: str = "tpu"
    # momentum keys the user explicitly set (config-file keys recorded by
    # load_config; CLI flags appended by the CLI layer).  Lets consumers —
    # e.g. strategy parametrization — distinguish "user chose lookback=12"
    # from "built-in default is 12", without re-parsing the file.
    explicit_momentum: Sequence[str] = ()
    # True when the user chose the universe (config-file [universe].tickers
    # or a --tickers flag) rather than inheriting the built-in demo list;
    # lets pack-aware consumers default to "every packed ticker" without
    # overriding an explicit choice
    explicit_universe: bool = False


_SECTIONS = {
    "universe": UniverseConfig,
    "momentum": MomentumConfig,
    "grid": GridConfig,
    "costs": CostConfig,
    "intraday": IntradayConfig,
}


def _build(cls, table: dict, where: str):
    names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(table) - names
    if unknown:
        raise ValueError(f"unknown key(s) {sorted(unknown)} in [{where}]")
    return cls(**{k: tuple(v) if isinstance(v, list) else v for k, v in table.items()})


def load_config(path: str) -> RunConfig:
    """Load a RunConfig from a TOML file; absent sections keep defaults."""
    try:
        import tomllib  # 3.11+ stdlib
    except ImportError:  # 3.10: the API-identical backport this image ships
        import tomli as tomllib

    with open(path, "rb") as f:
        raw = tomllib.load(f)

    top_names = {f.name for f in dataclasses.fields(RunConfig)}
    unknown = set(raw) - top_names
    if unknown:
        raise ValueError(f"unknown top-level key(s) {sorted(unknown)}")

    kwargs = {}
    for key, val in raw.items():
        if key in _SECTIONS:
            kwargs[key] = _build(_SECTIONS[key], val, key)
        else:
            kwargs[key] = val
    kwargs["explicit_momentum"] = tuple(sorted(raw.get("momentum", {})))
    kwargs["explicit_universe"] = "tickers" in raw.get("universe", {})
    return RunConfig(**kwargs)
