"""Execution cost models: market impact, spread, fills."""

from csmom_tpu.costs.impact import (
    square_root_impact,
    market_fill,
    limit_fill,
    long_short_weights,
    turnover_cost,
)

__all__ = [
    "square_root_impact",
    "market_fill",
    "limit_fill",
    "long_short_weights",
    "turnover_cost",
]
