"""Execution cost models, vectorized.

Reference: ``/root/reference/src/execution_models.py``:

- ``square_root_impact`` (``:4-7``): ``k * sigma * (|size|/ADV)^expo`` with
  k=0.1, expo=0.5, and 0 when ADV <= 0.
- ``simulate_market_fill`` (``:9-12``): fill at
  ``price * (1 + side * (spread/2 + impact))``, default spread 10bp.
- ``simulate_limit_fill`` (``:14-22``): probabilistic fill from
  aggressiveness & participation (dead code in the reference — zero call
  sites — but part of the API surface, so provided here with an explicit
  PRNG key instead of global ``np.random``).

All functions are scalar-or-array polymorphic pure jax: the event engine
calls them on whole ``[A]`` cross-sections (or ``[A, T]`` panels) at once
rather than per order inside a Python loop (``backtester.py:34-38``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def square_root_impact(size_shares, adv_shares, volatility, k=0.1, expo=0.5):
    """Square-root market impact as a return fraction; 0 where ADV <= 0."""
    adv_ok = adv_shares > 0
    part = jnp.abs(size_shares) / jnp.where(adv_ok, adv_shares, 1.0)
    return jnp.where(adv_ok, k * volatility * part**expo, 0.0)


def market_fill(price, size_shares, adv_shares, volatility, side, spread=0.001):
    """Immediate market-order fill with half-spread + impact.

    Returns (executed_price, impact).  ``side`` is +1 buy / -1 sell; both
    costs move the fill against the trader.
    """
    impact = square_root_impact(size_shares, adv_shares, volatility)
    executed = price * (1.0 + side * (spread / 2.0 + impact))
    return executed, impact


def limit_fill(key, price, size_shares, adv_shares, volatility, aggressiveness=0.5):
    """Probabilistic limit-order fill (reference ``:14-22`` semantics, explicit
    PRNG): fill prob ``(0.2 + 0.7*agg) * (1 - 0.5*min(1, |size|/max(1, adv)))``;
    executed price improves by ``0.5*agg*10bp``; expected slippage =
    unfilled-impact share ``impact * (1-agg)``.

    Returns (filled bool, executed_price, expected_slippage).
    """
    p_fill = 0.2 + 0.7 * aggressiveness
    size_frac = jnp.minimum(1.0, jnp.abs(size_shares) / jnp.maximum(1.0, adv_shares))
    p_full = p_fill * (1.0 - 0.5 * size_frac)
    u = jax.random.uniform(key, jnp.shape(p_full))
    filled = u < p_full
    executed = price * (1.0 - 0.5 * aggressiveness * 0.001)
    slip = square_root_impact(size_shares, adv_shares, volatility) * (1.0 - aggressiveness)
    return filled, executed, slip


def long_short_weights(labels, counts, n_bins: int):
    """Equal-weight long-short portfolio weights from decile labels.

    ``w[a, t] = +1/n_top`` for top-decile members, ``-1/n_bot`` for bottom,
    0 otherwise; both legs zero when either extreme decile is empty.

    Args:
      labels: i32[A, M] decile ids (-1 invalid).
      counts: i32[B, M] members per decile (``MonthlyResult.decile_counts``).
    """
    top_n = counts[n_bins - 1]
    bot_n = counts[0]
    live = (top_n > 0) & (bot_n > 0)
    w_top = jnp.where((labels == n_bins - 1) & live[None, :], 1.0 / jnp.maximum(top_n, 1), 0.0)
    w_bot = jnp.where((labels == 0) & live[None, :], 1.0 / jnp.maximum(bot_n, 1), 0.0)
    return w_top - w_bot


def turnover_cost(weights, half_spread=0.0005):
    """Linear transaction cost of rebalancing a weight panel.

    ``cost[t] = half_spread * sum_a |w[a, t] - w[a, t-1]|`` — the standard
    weight-turnover cost charge (BASELINE config 3: 'decile long-short with
    txn costs').  A month that replaces both full legs pays ~4*half_spread.

    Args:
      weights: f[A, M] portfolio weights (asset axis leading).
    """
    prev = jnp.roll(weights, 1, axis=-1).at[..., 0].set(0.0)
    return jnp.sum(jnp.abs(weights - prev), axis=-2) * half_spread
