"""csmom_tpu.mesh — sharding as a first-class subsystem (ROADMAP item 1).

Before this package, distribution lived at call sites: ``parallel/``
had the collectives and the mesh builders, but every consumer that
wanted a sharded engine had to hand-build a mesh, pick axis placements,
and wire its own shard/gather calls — which is why, four serving rounds
in, the serving tier and the north-star grid were still single-device
while ``registry/core.py`` carried a declared-but-stubbed ``sharded()``
hook on every engine.

This package is the missing middle layer:

- :mod:`csmom_tpu.mesh.rules` — the partition-rule table (the
  SNIPPETS [1]/[3] pattern): ``match_partition_rules`` maps named
  leaves to :class:`~jax.sharding.PartitionSpec` by regex, and the
  named tables encode the repo's axis placements once (batch-axis for
  serve micro-batches, asset-axis for per-asset-independent panels,
  grid-cell x asset for the J x K backtest) against the
  ``(grid, assets)`` / ``(batch,)`` meshes built by
  :mod:`csmom_tpu.parallel.mesh`.
- :mod:`csmom_tpu.mesh.shard` — shard/gather helpers and the
  ``shard_map``-via-``compat`` wrapper.  A one-device mesh is the
  degenerate path: collectives become identities and the wrapped
  program is the single-device program, so parity is by construction,
  not by tolerance.
- :mod:`csmom_tpu.mesh.variants` — fills every registry engine's
  sharded surface (surface (e)): serve endpoints get batch-axis
  sharding across micro-batch rows and asset-axis sharding for the
  per-asset-independent signals; the grid backtest gets grid-cell x
  asset sharding; the stream reconcile signals shard the asset axis.
  :func:`csmom_tpu.registry.core.EngineSpec.sharded` resolves here
  when no explicit ``sharded_fn`` was registered.
- :mod:`csmom_tpu.mesh.pinning` — stdlib-only device-slice bookkeeping
  for the worker pool (``--devices-per-worker``): slot -> slice
  mapping, the env contract workers inherit, and the shard-count
  arithmetic the jax layers share.  Import-safe from the jax-free
  supervisor/rehearse paths.

jax imports stay inside functions (pinning is stdlib-only; rules/
shard/variants pay jax only when a mesh is actually built), so the
registry and the fast rehearse tier can keep querying engine surfaces
without initializing a backend.
"""

from csmom_tpu.mesh.pinning import (
    DEVICE_SLICE_ENV,
    parse_device_slice,
    shards_for,
    slice_for_slot,
)

__all__ = [
    "DEVICE_SLICE_ENV",
    "parse_device_slice",
    "shards_for",
    "slice_for_slot",
]
