"""Device-slice pinning arithmetic — stdlib-only, shared across processes.

The pool's pinning contract (ISSUE 10): a worker slot owns a FIXED
contiguous slice of the process's device list, ``slot * per : slot *
per + per``.  The slice is a function of the slot alone, so a
replacement worker spawned into the same slot re-pins the same devices
by construction — the supervisor does not track slices, it derives
them, and the rehearsal only has to check the derivation was honored
(the spawn events and ready reports both carry the slice string).

The slice crosses the process boundary as an env var
(:data:`DEVICE_SLICE_ENV`, value ``"<start>:<count>"``) because the
worker must know its slice BEFORE it builds an engine, and because env
inheritance is the same channel the fault plans already ride.

Everything here is integer arithmetic on strings — no jax, no numpy —
so the jax-free supervisor, the stub-engine rehearse tier, and
``serve/health.py`` can all import it for free.
"""

from __future__ import annotations

__all__ = [
    "DEVICE_SLICE_ENV",
    "parse_device_slice",
    "shards_for",
    "slice_for_slot",
]

# worker processes read their pinned slice from here ("<start>:<count>");
# set by the supervisor at spawn, re-set identically at every respawn of
# the same slot
DEVICE_SLICE_ENV = "CSMOM_MESH_DEVICE_SLICE"


def slice_for_slot(slot: int, devices_per_worker: int) -> str:
    """The canonical slice string for one worker slot."""
    if slot < 0 or devices_per_worker <= 0:
        raise ValueError(
            f"need slot >= 0 and devices_per_worker > 0, got "
            f"slot={slot}, devices_per_worker={devices_per_worker}")
    return f"{slot * devices_per_worker}:{devices_per_worker}"


def parse_device_slice(value: str) -> tuple:
    """``"<start>:<count>"`` -> ``(start, count)``; raises on garbage so
    a mis-plumbed env var fails at worker startup, not mid-dispatch."""
    try:
        start_s, _, count_s = value.partition(":")
        start, count = int(start_s), int(count_s)
    except (AttributeError, ValueError):
        raise ValueError(
            f"bad device slice {value!r}: expected '<start>:<count>', "
            "e.g. '4:2'") from None
    if start < 0 or count <= 0:
        raise ValueError(
            f"bad device slice {value!r}: start must be >= 0 and count "
            "> 0")
    return start, count


def shards_for(n: int, max_shards: int) -> int:
    """Largest shard count <= ``max_shards`` that divides ``n`` evenly.

    The mesh layer never pads a serve bucket axis (padding would change
    the dispatched shape set the warmup contract closed over), so an
    axis of length ``n`` on ``d`` devices shards ``shards_for(n, d)``
    ways — 1 when nothing divides, which IS the single-device
    degenerate path.
    """
    if n <= 0 or max_shards <= 0:
        return 1
    for d in range(min(n, max_shards), 0, -1):
        if n % d == 0:
            return d
    return 1
