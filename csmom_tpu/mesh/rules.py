"""The partition-rule table: regex -> PartitionSpec, resolved on a named mesh.

The SNIPPETS [1]/[3] pattern, specialized to this repo's axes: instead
of every call site hand-placing arrays on a mesh, a RULE TABLE maps
leaf names to :class:`~jax.sharding.PartitionSpec`\\ s and
:func:`match_partition_rules` resolves a whole named tree at once.
Scalars and singletons are never partitioned; a leaf no rule matches is
a loud error — an array silently replicated by omission is exactly the
drift this table exists to prevent.

Axis placements (one written-down table, from the
:mod:`csmom_tpu.parallel.mesh` layout principle: the asset axis is the
only one with collectives, so it rides ICI; batch rows and grid cells
are embarrassingly parallel):

==================  =====================  ============================
table               mesh                   what shards
==================  =====================  ============================
serve batch rules   ``("batch",)``         micro-batch rows of
                                           ``values/mask f[B, A, M]``
                                           (rows are independent: the
                                           split is bitwise-neutral)
serve asset rules   ``("assets",)``        the asset axis of per-asset-
                                           independent endpoints
                                           (momentum/turnover): large
                                           universes split with zero
                                           communication
grid rules          ``("grid", "assets")``  J cells across ``grid``
                                           (no communication), assets
                                           across ``assets`` (one
                                           all_gather for the rank +
                                           psums, the collectives
                                           engine's pattern)
panel asset rules   ``("assets",)``        ``[A, ...]`` panels + per-
                                           asset vectors (stream
                                           reconcile, histrank, event)
==================  =====================  ============================

Which PLACEMENT a serve endpoint gets is itself a rule
(:func:`serve_axis_for`): per-asset-independent signals declare the
asset axis, everything that reduces across the cross-section (the
backtest summary, z-scored combos) stays batch-sharded — an asset
split there would change reduction order and break the bitwise-parity
contract :mod:`tests.test_mesh` pins.
"""

from __future__ import annotations

import math
import re

__all__ = [
    "grid_asset_mesh",
    "match_partition_rules",
    "named_mesh",
    "panel_asset_rules",
    "serve_axis_for",
    "serve_rules",
    "grid_rules",
]

# serve-endpoint placement table: regex on the REGISTERED endpoint name
# -> mesh axis.  Asset-axis entries must be per-asset independent
# (bitwise-safe under an asset split); anything unmatched — including a
# runtime-registered plugin the table has never heard of — falls back
# to the always-safe batch axis.
_SERVE_AXIS_RULES = (
    (r"^(momentum|turnover)$", "assets"),
    (r".", "batch"),
)


def serve_axis_for(endpoint: str) -> str:
    """Which mesh axis a serve endpoint's sharded entry splits."""
    for rule, axis in _SERVE_AXIS_RULES:
        if re.search(rule, endpoint):
            return axis
    return "batch"


def _P():
    from jax.sharding import PartitionSpec

    return PartitionSpec


def serve_rules(axis: str):
    """The serve-panel rule table for one placement: ``values``/``mask``
    are ``f[B, A, M]`` micro-batches; outputs are ``f[B, A]``
    (per-asset) or ``f[B, k]`` (summary)."""
    P = _P()
    if axis == "batch":
        return (
            (r"(^|/)(values|mask)$", P("batch", None, None)),
            (r"(^|/)out_per_asset$", P("batch", None)),
            (r"(^|/)out_summary$", P("batch", None)),
        )
    if axis == "assets":
        return (
            (r"(^|/)(values|mask)$", P(None, "assets", None)),
            (r"(^|/)out_per_asset$", P(None, "assets")),
        )
    raise ValueError(f"unknown serve placement {axis!r}: use 'batch' or "
                     "'assets'")


def grid_rules():
    """The J x K grid table: panels replicated per asset shard, J cells
    across ``grid``, per-cell planes gathered grid-major."""
    P = _P()
    return (
        (r"(^|/)(prices|mask)$", P("assets", None)),
        (r"(^|/)Js$", P("grid")),
        (r"(^|/)Ks$", P()),
        (r"(^|/)(spreads|spread_valid|net)$", P("grid", None, None)),
    )


def panel_asset_rules():
    """``[A, ...]`` panels and per-asset vectors, asset-axis sharded
    (stream reconcile, histrank labels, the event engine's five
    arrays)."""
    P = _P()
    return (
        (r"(^|/)(prices|values|volumes|price|valid|score|mask)$",
         P("assets")),
        (r"(^|/)(shares|adv|vol)$", P("assets")),
        (r"(^|/)labels$", P("assets")),
    )


def match_partition_rules(rules, tree, sep: str = "/"):
    """Resolve a named tree of arrays/abstract values to PartitionSpecs.

    ``tree`` is nested dicts/lists/tuples with array-like leaves (real
    arrays or ``ShapeDtypeStruct``\\ s).  Leaf names join their dict
    path with ``sep`` (list/tuple indices stringify), and the FIRST
    rule whose regex searches the name wins — order the tables
    specific-first.  Scalars and one-element leaves get ``P()``
    (never partitioned); a non-scalar leaf with no matching rule
    raises, naming the leaf.
    """
    P = _P()

    def spec_for(name, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if len(shape) == 0 or math.prod(shape) == 1:
            return P()
        for rule, ps in rules:
            if re.search(rule, name):
                return ps
        raise ValueError(
            f"no partition rule matches leaf {name!r} (shape {shape}); "
            "add a rule to csmom_tpu/mesh/rules.py or pass an explicit "
            "spec")

    def walk(name, node):
        if isinstance(node, dict):
            return {k: walk(f"{name}{sep}{k}" if name else str(k), v)
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            out = [walk(f"{name}{sep}{i}" if name else str(i), v)
                   for i, v in enumerate(node)]
            return type(node)(out) if isinstance(node, tuple) else out
        return spec_for(name, node)

    return walk("", tree)


def named_mesh(axis: str, n_shards: int, devices=None):
    """A 1-D mesh named ``axis`` over the first ``n_shards`` devices."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    devices = tuple(devices) if devices is not None else tuple(jax.devices())
    if n_shards > len(devices):
        raise ValueError(
            f"{n_shards} shards > {len(devices)} visible devices")
    return Mesh(np.asarray(devices[:n_shards]), (axis,))


def grid_asset_mesh(grid_shards: int, asset_shards: int, devices=None):
    """The ``(grid, assets)`` mesh for the J x K backtest — the
    :func:`csmom_tpu.parallel.mesh.make_mesh` placement, sized
    explicitly (grid cells on the collective-free axis, assets on the
    ICI axis)."""
    import jax

    from csmom_tpu.parallel.mesh import make_mesh

    devices = tuple(devices) if devices is not None else tuple(jax.devices())
    need = grid_shards * asset_shards
    if need > len(devices):
        raise ValueError(
            f"grid {grid_shards} x assets {asset_shards} = {need} devices "
            f"> {len(devices)} visible")
    return make_mesh(list(devices[:need]), grid_axis=grid_shards)
