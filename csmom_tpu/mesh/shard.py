"""Shard/gather helpers + the ``shard_map`` wrapper the variants build on.

Thin by design: the partition DECISIONS live in :mod:`~csmom_tpu.mesh.
rules`, the ENGINE constructions in :mod:`~csmom_tpu.mesh.variants`;
this module owns only the mechanical layer — placing host arrays onto a
mesh per spec, gathering results back, and wrapping a local function
with :func:`csmom_tpu.parallel.compat.shard_map` (the one import site
for the jax 0.4/0.6 API split).

Degenerate path: every helper accepts a one-device mesh and produces
the single-device program — ``shard_map`` over one device makes
``all_gather``/``psum`` identities and the local slice the whole array,
and :func:`sharded_call` skips the wrapper entirely when the mesh is
trivial, so the sharded entry IS the unsharded entry (identical by
construction, which is what lets the parity tests assert bitwise
equality instead of tolerances).
"""

from __future__ import annotations

__all__ = ["gather", "mesh_size", "shard_args", "sharded_call"]


def mesh_size(mesh) -> int:
    import math

    return math.prod(mesh.shape.values())


def shard_args(mesh, specs, *arrays):
    """Place host arrays onto ``mesh`` per their PartitionSpecs (one
    spec per array, e.g. from :func:`~csmom_tpu.mesh.rules.
    match_partition_rules`).  Pre-placing inputs keeps a hot loop from
    re-transferring per call; passing host arrays straight to the
    compiled fn also works (jit shards them per the program)."""
    import jax
    from jax.sharding import NamedSharding

    if len(specs) != len(arrays):
        raise ValueError(f"{len(specs)} specs for {len(arrays)} arrays")
    return tuple(jax.device_put(a, NamedSharding(mesh, s))
                 for a, s in zip(arrays, specs))


def gather(x):
    """One fully-replicated/host numpy view of a (possibly sharded)
    array — the evidence-writing side of the shard/gather pair."""
    import numpy as np

    import jax

    return np.asarray(jax.device_get(x))


def sharded_call(fn, mesh, in_specs, out_specs, *, check_vma: bool = False,
                 jit: bool = True, collective_free: bool = False):
    """``shard_map(fn)`` on ``mesh``, jitted.

    With ``collective_free`` (the caller's declaration that ``fn`` uses
    no ``lax`` collectives or axis queries), a one-device mesh skips
    the wrapper entirely and returns ``jit(fn)`` — the degenerate-path
    contract: a 1-device environment runs the LITERAL single-device
    program, not a 1-shard emulation of it.  A collective-using local
    fn keeps the wrapper at every size (``all_gather``/``psum`` over
    one device are identities, so the degeneracy still holds — just
    inside the mapped program).  ``check_vma=False`` matches the repo's
    collectives engines (the replication checker predates several of
    the patterns they use).
    """
    import jax

    from csmom_tpu.parallel.compat import shard_map

    if collective_free and mesh_size(mesh) == 1:
        return jax.jit(fn) if jit else fn
    wrapped = shard_map(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=check_vma)
    return jax.jit(wrapped) if jit else wrapped
