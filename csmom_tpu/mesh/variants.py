"""Sharded variants for every registered engine — surface (e), filled.

``registry/core.py`` declared a ``sharded()`` hook on every engine at
r14 and stubbed it; this module supplies the implementations, resolved
by a RULE TABLE over ``kind:name`` (the same pattern
:mod:`~csmom_tpu.mesh.rules` applies to array leaves, one level up):
:func:`resolve_sharded` is what :meth:`csmom_tpu.registry.core.
EngineSpec.sharded` falls back to when no explicit ``sharded_fn`` was
registered — so a toy engine registered at runtime gets the generic
batch-axis serve variant with no edit anywhere, exactly like the
donated surface.

Placements (all parity-pinned by ``tests/test_mesh.py``):

- **serve endpoints** — :func:`sharded_serve_entry_fn`: the micro-batch
  entry ``fn(values f[B, A, M], mask) -> f[B, A] | f[B, k]`` with the
  batch axis sharded across devices (rows are independent, so the
  split is bitwise-neutral), or the ASSET axis for the per-asset-
  independent signals (``rules.serve_axis_for``) — large universes
  split with zero communication.  Shard counts are the largest divisor
  of the bucket axis <= device count (``pinning.shards_for``); a
  non-dividing axis degenerates to the literal single-device program.
- **the J x K grid** — :func:`sharded_grid_fn`: grid cells across the
  collective-free ``grid`` axis, assets across ``assets`` (the
  ``parallel/collectives.py`` engine, now behind a cached callable the
  ``bench-mesh`` manifest profile AOT-warms).
- **the netting pass / monthly kernels / event panel / histrank /
  online ridge / stream reconcile signals** — each gets the placement
  its axis structure admits (grid-cell, asset, asset, asset, time,
  asset respectively), reusing the existing ``parallel/`` engines
  where they exist rather than forking the math.

jax imports live inside functions: importing this module (which the
registry does lazily, per ``sharded()`` call) costs nothing jax-side.
"""

from __future__ import annotations

import re
from functools import lru_cache, partial

__all__ = [
    "resolve_sharded",
    "sharded_grid_fn",
    "sharded_grid_net_fn",
    "sharded_serve_entry_fn",
    "sharded_serve_jit_for",
    "sharded_stream_signals_fn",
]


def _devices(devices=None) -> tuple:
    """The device tuple a variant builds its mesh over: an explicit
    list, the worker's pinned slice (:mod:`~csmom_tpu.mesh.pinning`
    env contract), or every visible device."""
    import os

    import jax

    from csmom_tpu.mesh.pinning import DEVICE_SLICE_ENV, parse_device_slice

    if devices is not None:
        return tuple(devices)
    all_devices = tuple(jax.devices())
    env = os.environ.get(DEVICE_SLICE_ENV)
    if env:
        start, count = parse_device_slice(env)
        if start + count > len(all_devices):
            raise ValueError(
                f"pinned device slice {env!r} exceeds the {len(all_devices)}"
                " visible devices (is --xla_force_host_platform_device_"
                "count / the TPU topology smaller than the pool assumed?)")
        return all_devices[start:start + count]
    return all_devices


# --------------------------------------------------------------- serve ----

@lru_cache(maxsize=128)
def _sharded_serve_jit(surface, lookback: int, skip: int, n_bins: int,
                       mode: str, axis: str, n_shards: int, devices: tuple):
    """One compiled sharded micro-batch entry (process-shared, keyed on
    the SURFACE object like ``serve/engine._jit_entry`` — re-registering
    an endpoint rebuilds the sharded scorer too)."""
    import jax

    from csmom_tpu.mesh import rules, shard

    one = surface.batch_fn(dict(lookback=lookback, skip=skip,
                                n_bins=n_bins, mode=mode))
    batched = jax.vmap(one)

    def entry(values, mask):
        return batched(values, mask)

    if n_shards == 1:
        # the degenerate path IS the single-device program
        return jax.jit(entry)
    P = rules._P()
    if axis == "batch":
        in_spec = P("batch", None, None)
        out_spec = P("batch", None)
    else:
        in_spec = P(None, "assets", None)
        out_spec = P(None, "assets")
    mesh = rules.named_mesh(axis, n_shards, devices)
    return shard.sharded_call(entry, mesh, (in_spec, in_spec), out_spec,
                              collective_free=True)


class ShardedServeEntry:
    """The dispatchable sharded entry for one (endpoint, params).

    Callable like the single-device ``serve_entry_fn`` product —
    ``fn(values f[B, A, M], mask bool[B, A, M])`` — with the shard
    count chosen PER BUCKET SHAPE (largest divisor of the sharded axis
    <= device count), so the closed bucket world stays closed: every
    (endpoint, bucket, device-count) program is enumerable, which is
    what lets the ``serve-mesh`` manifest profile AOT-warm all of them.
    """

    def __init__(self, kind: str, surface, lookback: int, skip: int,
                 n_bins: int, mode: str, axis: str, devices: tuple):
        self.kind = kind
        self.surface = surface
        self.params = (lookback, skip, n_bins, mode)
        self.axis = axis
        self.devices = devices

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    def shards_for_shape(self, B: int, A: int) -> int:
        from csmom_tpu.mesh.pinning import shards_for

        return shards_for(B if self.axis == "batch" else A,
                          self.n_devices)

    def __call__(self, values, mask):
        B, A = values.shape[0], values.shape[1]
        n = self.shards_for_shape(B, A)
        fn = _sharded_serve_jit(self.surface, *self.params, self.axis, n,
                                self.devices)
        return fn(values, mask)


def sharded_serve_jit_for(kind: str, B: int, A: int, lookback: int = 12,
                          skip: int = 1, n_bins: int = 10,
                          mode: str = "rank", devices=None):
    """``(jitted entry, shard count)`` for ONE bucket shape — the exact
    compiled callable :class:`ShardedServeEntry` dispatches at that
    shape, which is what the ``serve-mesh`` manifest profile lowers so
    ``csmom warmup`` and the live mesh engine share byte-identical
    HLO through the serialized-executable cache."""
    entry = sharded_serve_entry_fn(kind, lookback, skip, n_bins, mode,
                                   devices=devices)
    n = entry.shards_for_shape(B, A)
    return _sharded_serve_jit(entry.surface, lookback, skip, n_bins, mode,
                              entry.axis, n, entry.devices), n


def sharded_serve_entry_fn(kind: str, lookback: int = 12, skip: int = 1,
                           n_bins: int = 10, mode: str = "rank", *,
                           devices=None, axis: str | None = None):
    """Surface (e) for a servable engine: the sharded micro-batch entry.

    ``axis`` defaults to the endpoint's placement rule
    (:func:`csmom_tpu.mesh.rules.serve_axis_for`); ``devices`` defaults
    to the pinned slice / all visible devices (:func:`_devices`).
    """
    from csmom_tpu.mesh.rules import serve_axis_for
    from csmom_tpu.registry import serve_surface

    surface = serve_surface(kind)
    if axis is None:
        axis = serve_axis_for(kind)
    if axis == "assets" and surface.output == "summary":
        raise ValueError(
            f"endpoint {kind!r} reduces over the cross-section "
            "(summary output): asset-axis sharding would change "
            "reduction order; use the batch axis")
    return ShardedServeEntry(kind, surface, lookback, skip, n_bins, mode,
                             axis, _devices(devices))


# ---------------------------------------------------------------- grid ----

def _grid_mesh(n_J: int, A: int, devices: tuple, grid_shards=None,
               asset_shards=None):
    """The (grid, assets) mesh for a J x K run: grid cells first (zero
    communication), remaining capacity to the asset axis — both clamped
    to divisors so nothing pads implicitly."""
    from csmom_tpu.mesh.pinning import shards_for
    from csmom_tpu.mesh.rules import grid_asset_mesh

    g = grid_shards or shards_for(n_J, len(devices))
    a = asset_shards or shards_for(A, max(1, len(devices) // g))
    return grid_asset_mesh(g, a, devices)


def sharded_grid_fn(devices=None, *, impl: str = "xla", grid_shards=None,
                    asset_shards=None):
    """The grid-cell x asset sharded J x K backtest.

    Returns ``fn(prices f[A, M], mask, Js, Ks, **kw) -> GridResult`` —
    the drop-in sharded twin of :func:`csmom_tpu.backtest.grid.
    jk_grid_backtest`, built on the cached
    :func:`csmom_tpu.parallel.collectives.grid_shard_fn` callable (the
    one the ``bench-mesh`` manifest profile AOT-warms).
    """
    devs = _devices(devices)

    def fn(prices, mask, Js, Ks, skip: int = 1, n_bins: int = 10,
           mode: str = "qcut", max_hold=None, freq: int = 12):
        import numpy as np

        from csmom_tpu.parallel.collectives import sharded_jk_grid_backtest

        mesh = _grid_mesh(len(np.asarray(Js)), prices.shape[0], devs,
                          grid_shards, asset_shards)
        return sharded_jk_grid_backtest(
            prices, mask, Js, Ks, mesh, skip=skip, n_bins=n_bins,
            mode=mode, max_hold=max_hold, freq=freq, impl=impl)

    return fn


def sharded_grid_net_fn(devices=None, *, grid_shards=None):
    """Grid-cell sharded ``--tc-bps`` netting pass.

    The per-cell cost pipeline (momentum -> labels -> weights -> cost)
    is J-independent, so the net grid computes shard-locally per J
    slice — zero communication — and the replicated summary stats are
    rebuilt OUTSIDE the mapped program from the gathered net planes
    with the same formulas the single-device engine uses.
    """
    devs = _devices(devices)

    def fn(prices, mask, Js, spreads, spread_valid, half_spread,
           Ks_c: tuple, skip: int = 1, n_bins: int = 10,
           mode: str = "qcut", freq: int = 12):
        import jax.numpy as jnp

        from csmom_tpu.analytics.stats import (
            masked_mean,
            nw_t_stat,
            sharpe,
            t_stat,
        )
        from csmom_tpu.backtest.grid import GridResult, _grid_net_core_impl
        from csmom_tpu.mesh import rules, shard
        from csmom_tpu.mesh.pinning import shards_for

        Js = jnp.asarray(Js)
        g = grid_shards or shards_for(int(Js.shape[0]), len(devs))
        mesh = rules.named_mesh("grid", g, devs)
        P = rules._P()

        def local(p, m, Js_l, spreads_l, valid_l):
            gr = _grid_net_core_impl(p, m, Js_l, spreads_l, valid_l,
                                     half_spread, Ks_c, skip, n_bins,
                                     mode, freq)
            # the per-cell planes are exact on the local slice; the
            # local summary stats are partial and discarded
            return gr.spreads

        net = shard.sharded_call(
            local, mesh,
            (P(), P(), P("grid"), P("grid", None, None),
             P("grid", None, None)),
            P("grid", None, None),
            collective_free=True,
        )(prices, mask, Js, spreads, spread_valid)
        Ks_arr = jnp.asarray(Ks_c)
        return GridResult(
            spreads=net,
            spread_valid=spread_valid,
            mean_spread=masked_mean(net, spread_valid),
            ann_sharpe=sharpe(net, spread_valid, freq_per_year=freq),
            tstat=t_stat(net, spread_valid),
            tstat_nw=nw_t_stat(net, spread_valid, lags=Ks_arr[None, :],
                               max_lag=max(Ks_c)),
            Js=Js,
            Ks=Ks_arr,
            skip=jnp.asarray(skip),
            n_bins=n_bins,
            mode=mode,
        )

    return fn


# ------------------------------------------------- asset-axis engines -----

def _asset_mesh_2d(A: int, devices: tuple):
    """The 1-grid x N-assets mesh the collectives engines expect, sized
    to the largest asset divisor."""
    from csmom_tpu.mesh.pinning import shards_for
    from csmom_tpu.mesh.rules import grid_asset_mesh

    return grid_asset_mesh(1, shards_for(A, len(devices)), devices)


def _sharded_monthly_fn(devices=None):
    devs = _devices(devices)

    def fn(prices, mask, **kwargs):
        from csmom_tpu.parallel.collectives import (
            sharded_monthly_spread_backtest,
        )

        mesh = _asset_mesh_2d(prices.shape[0], devs)
        return sharded_monthly_spread_backtest(prices, mask, mesh,
                                               **kwargs)

    return fn


def _sharded_event_fn(devices=None):
    devs = _devices(devices)

    def fn(price, valid, score, adv, vol, **kwargs):
        from csmom_tpu.parallel.event import sharded_event_backtest

        mesh = _asset_mesh_2d(price.shape[0], devs)
        return sharded_event_backtest(price, valid, score, adv, vol,
                                      mesh, **kwargs)

    return fn


def _sharded_histrank_fn(n_bins: int = 10, devices=None):
    devs = _devices(devices)

    def fn(x, valid):
        from csmom_tpu.mesh import rules, shard
        from csmom_tpu.mesh.pinning import shards_for
        from csmom_tpu.parallel.histrank import histogram_rank_labels

        n = shards_for(x.shape[0], len(devs))
        mesh = rules.named_mesh("assets", n, devs)
        P = rules._P()

        def local(x_l, v_l):
            return histogram_rank_labels(
                x_l, v_l, n_bins, "assets" if n > 1 else None)

        return shard.sharded_call(
            local, mesh, (P("assets", None), P("assets", None)),
            P("assets", None))(x, valid)

    return fn


def _sharded_online_ridge_fn(devices=None):
    devs = _devices(devices)

    def fn(features, y, valid, **kwargs):
        from csmom_tpu.mesh.rules import named_mesh
        from csmom_tpu.parallel.online_ridge import (
            time_sharded_online_ridge_scores,
        )

        # rows pad internally (the engine's own contract), so the time
        # mesh takes every pinned device rather than a divisor
        mesh = named_mesh("time", len(devs), devs)
        return time_sharded_online_ridge_scores(features, y, valid, mesh,
                                                **kwargs)

    return fn


def sharded_stream_signals_fn(devices=None):
    """Asset-sharded twins of the stream reconcile kernels: per-asset-
    independent rolling signals over ``[A, bars]`` panels, split with
    zero communication (bitwise-equal to the jitted single-device
    ``signals`` engines — the property the incremental layer's
    reconciliation depends on)."""
    devs = _devices(devices)

    def make(which):
        @lru_cache(maxsize=16)
        def jit_for(n_shards, lookback, skip):
            from csmom_tpu.mesh import rules, shard
            from csmom_tpu.signals.momentum import momentum
            from csmom_tpu.signals.turnover import turnover_features

            P = rules._P()
            if which == "momentum":
                def local(p, m):
                    return momentum(p, m, lookback=lookback, skip=skip)
            else:
                def local(p, m):
                    import jax.numpy as jnp

                    shares = jnp.ones((p.shape[0],), p.dtype)
                    return turnover_features(
                        p, m, shares, lookback=lookback)["turn_avg"]
            mesh = rules.named_mesh("assets", n_shards, devs)
            spec = P("assets", None)
            return shard.sharded_call(local, mesh, (spec, spec),
                                      (spec, spec), collective_free=True)

        def fn(panel, mask, lookback: int = 12, skip: int = 1):
            from csmom_tpu.mesh.pinning import shards_for

            return jit_for(shards_for(panel.shape[0], len(devs)),
                           lookback, skip)(panel, mask)

        return fn

    return {"momentum": make("momentum"), "turn_avg": make("turn_avg")}


# ------------------------------------------------------- the rule table ---

def _serve_factory(spec):
    return partial(sharded_serve_entry_fn, spec.name)


def _grid_factory(spec):
    return sharded_grid_fn


def _grid_net_factory(spec):
    return sharded_grid_net_fn


def _monthly_factory(spec):
    return _sharded_monthly_fn


def _event_factory(spec):
    return _sharded_event_fn


def _histrank_factory(spec):
    return _sharded_histrank_fn


def _online_ridge_factory(spec):
    return _sharded_online_ridge_fn


def _serve_buckets_factory(spec):
    # the bucket-grid feeder's sharded surface is the per-endpoint entry
    # resolver itself: sharded(kind, **params) -> the dispatchable entry
    return sharded_serve_entry_fn


def _stream_signals_factory(spec):
    return sharded_stream_signals_fn


# kind:name -> factory(spec) -> the engine's sharded_fn.  First match
# wins; no match = the pointed NotImplementedError in registry/core
# (strategy plugins legitimately have no mesh variant — their serve
# adapters do, via the catch-all serve rule).
_SHARDED_RULES = (
    (r"^compile:grid\.jk$", _grid_factory),
    (r"^compile:grid\.net_core$", _grid_net_factory),
    (r"^compile:monthly\.kernels$", _monthly_factory),
    (r"^compile:event\.panel$", _event_factory),
    (r"^compile:parallel\.histrank$", _histrank_factory),
    (r"^compile:parallel\.online_ridge$", _online_ridge_factory),
    (r"^compile:serve\.buckets$", _serve_buckets_factory),
    (r"^compile:stream\.signals$", _stream_signals_factory),
    # the mesh feeders' own sharded surface IS what they feed: the
    # per-endpoint entry resolver / the sharded grid engine
    (r"^compile:mesh\.serve$", _serve_buckets_factory),
    (r"^compile:mesh\.grid$", _grid_factory),
    (r"^serve:", _serve_factory),
)


def resolve_sharded(spec):
    """The sharded-variant factory for one registered engine, or None
    when no rule matches (the registry then raises its pointed error).
    The catch-all ``serve:`` rule is what gives a runtime-registered
    engine (a plugin, a test's toy) its sharded surface for free —
    batch-axis sharding is placement-safe for ANY per-request scorer.
    """
    key = f"{spec.kind}:{spec.name}"
    for rule, factory in _SHARDED_RULES:
        if re.search(rule, key):
            return factory(spec)
    return None
