"""Predictive models: closed-form linear/ridge regression with time-series CV."""

from csmom_tpu.models.ridge import ridge_time_series_cv, RidgeFit

__all__ = ["ridge_time_series_cv", "RidgeFit"]
