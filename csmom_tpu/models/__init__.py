"""Predictive models: linear family (ridge closed-form, elastic-net/lasso
via FISTA, online ridge via Sherman-Morrison scan) and a small MLP
(full-batch AdamW).  The batch models share one expanding-window
time-series-CV harness; the online model is its leak-free walk-forward
counterpart (strictly-causal scores, prequential MSE)."""

from csmom_tpu.models.ridge import ridge_time_series_cv, RidgeFit
from csmom_tpu.models.elastic_net import (
    ElasticNetFit,
    as_ridge_fit,
    elastic_net_time_series_cv,
)
from csmom_tpu.models.mlp import MLPFit, mlp_time_series_cv
from csmom_tpu.models.online_ridge import OnlineRidgeFit, online_ridge_scores

__all__ = [
    "ridge_time_series_cv",
    "RidgeFit",
    "elastic_net_time_series_cv",
    "ElasticNetFit",
    "as_ridge_fit",
    "MLPFit",
    "mlp_time_series_cv",
    "OnlineRidgeFit",
    "online_ridge_scores",
]
