"""Predictive models: linear family (ridge closed-form, elastic-net/lasso
via FISTA) and a small MLP (full-batch AdamW), all on one shared
expanding-window time-series-CV harness."""

from csmom_tpu.models.ridge import ridge_time_series_cv, RidgeFit
from csmom_tpu.models.elastic_net import (
    ElasticNetFit,
    as_ridge_fit,
    elastic_net_time_series_cv,
)
from csmom_tpu.models.mlp import MLPFit, mlp_time_series_cv

__all__ = [
    "ridge_time_series_cv",
    "RidgeFit",
    "elastic_net_time_series_cv",
    "ElasticNetFit",
    "as_ridge_fit",
    "MLPFit",
    "mlp_time_series_cv",
]
