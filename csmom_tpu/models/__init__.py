"""Predictive models: linear family (ridge closed-form, elastic-net/lasso
via FISTA) with expanding-window time-series CV."""

from csmom_tpu.models.ridge import ridge_time_series_cv, RidgeFit
from csmom_tpu.models.elastic_net import (
    ElasticNetFit,
    as_ridge_fit,
    elastic_net_time_series_cv,
)

__all__ = [
    "ridge_time_series_cv",
    "RidgeFit",
    "elastic_net_time_series_cv",
    "ElasticNetFit",
    "as_ridge_fit",
]
