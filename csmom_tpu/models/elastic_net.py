"""Elastic-net / lasso regression via FISTA, same CV harness as ridge.

The reference's model layer is a single ridge regression
(``/root/reference/src/models.py:8-22``); this extends the family with the
sparse linear models a reference user would reach for next (lasso feature
selection over the minute-bar features), without leaving the compiled
panel world.

TPU-native form: the smooth part of the elastic-net objective reduces to
the same masked Gram/moment einsums as ridge (F=5 features -> tiny FxF
system), and the l1 part is a soft-threshold proximal step.  The solver is
FISTA with a fixed iteration count under ``lax.scan`` — no data-dependent
stopping, so one trace, one executable; the step size comes from
``eigvalsh`` of the FxF Gram (exact Lipschitz constant, cheaper than any
line search at this width).

Objective (sklearn's parameterization, so their solutions match):

    (1/2n)||y - Xw - b||^2 + alpha*l1_ratio*||w||_1
                           + (alpha*(1-l1_ratio)/2)*||w||^2
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from csmom_tpu.models.ridge import RidgeFit


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ElasticNetFit:
    coef: jnp.ndarray        # f[F] on scaled features
    intercept: jnp.ndarray   # f[]
    scale_mean: jnp.ndarray  # f[F]
    scale_std: jnp.ndarray   # f[F]
    cv_mse: jnp.ndarray      # f[n_splits]
    scores: jnp.ndarray      # f[A, R]
    n_train: jnp.ndarray     # i32
    n_nonzero: jnp.ndarray   # i32 selected features in the final model


def _soft(v, t):
    return jnp.sign(v) * jnp.maximum(jnp.abs(v) - t, 0.0)


def _masked_enet(Xs, y, w, alpha, l1_ratio, n_iter):
    """Elastic net over rows weighted by w (0/1), intercept by centering.

    Returns (coef f[F], intercept f[]).
    """
    n = jnp.maximum(jnp.sum(w), 1.0)
    xbar = jnp.einsum("r,rf->f", w, Xs) / n
    ybar = jnp.sum(w * y) / n
    Xc = (Xs - xbar) * w[:, None]
    yc = (y - ybar) * w

    G = (Xc.T @ Xc) / n                       # FxF smooth Hessian (l2 apart)
    b = (Xc.T @ yc) / n
    l2 = alpha * (1.0 - l1_ratio)
    l1 = alpha * l1_ratio
    L = jnp.linalg.eigvalsh(G)[-1] + l2       # exact Lipschitz constant
    step = 1.0 / jnp.maximum(L, 1e-30)

    def fista(carry, _):
        wk, zk, tk = carry
        grad = G @ zk - b + l2 * zk
        w_next = _soft(zk - step * grad, step * l1)
        t_next = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tk * tk))
        z_next = w_next + ((tk - 1.0) / t_next) * (w_next - wk)
        return (w_next, z_next, t_next), None

    w0 = jnp.zeros(Xs.shape[1], dtype=Xs.dtype)
    (coef, _, _), _ = jax.lax.scan(
        fista, (w0, w0, jnp.asarray(1.0, Xs.dtype)), None, length=n_iter
    )
    intercept = ybar - xbar @ coef
    return coef, intercept


@partial(jax.jit, static_argnames=("n_splits", "n_iter", "train_frac_small"))
def elastic_net_time_series_cv(
    features,
    y,
    valid,
    n_splits: int = 3,
    alpha: float = 1e-4,
    l1_ratio: float = 0.5,
    n_iter: int = 500,
    train_frac: float = 0.7,
    train_frac_small: float = 0.6,
    small_threshold: int = 100,
) -> ElasticNetFit:
    """Scale -> expanding-window CV -> final elastic net -> score everything.

    Runs on the shared reference-pipeline scaffold
    (:func:`csmom_tpu.models.ridge.time_series_cv_harness` — one
    implementation of the scaler/fold/score layout for every linear model)
    with the ridge solve swapped for the FISTA proximal loop.
    ``l1_ratio=1`` is lasso, ``l1_ratio=0`` is (iterative) ridge.
    """
    from csmom_tpu.models.ridge import time_series_cv_harness

    (coef, icept), mean, std, cv_mse, scores, n_train, _ = time_series_cv_harness(
        features, y, valid,
        solver=lambda Xs, yf, w: _masked_enet(Xs, yf, w, alpha, l1_ratio, n_iter),
        n_splits=n_splits, train_frac=train_frac,
        train_frac_small=train_frac_small, small_threshold=small_threshold,
    )
    return ElasticNetFit(
        coef=coef,
        intercept=icept,
        scale_mean=mean,
        scale_std=std,
        cv_mse=cv_mse,
        scores=scores,
        n_train=n_train,
        n_nonzero=jnp.sum(coef != 0).astype(jnp.int32),
    )


def as_ridge_fit(fit: ElasticNetFit) -> RidgeFit:
    """View an elastic-net fit through the RidgeFit schema (drop-in for the
    intraday pipeline's downstream consumers)."""
    return RidgeFit(
        coef=fit.coef,
        intercept=fit.intercept,
        scale_mean=fit.scale_mean,
        scale_std=fit.scale_std,
        cv_mse=fit.cv_mse,
        scores=fit.scores,
        n_train=fit.n_train,
    )
