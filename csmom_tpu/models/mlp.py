"""MLP score model on the shared time-series-CV harness.

The reference's model layer is a single linear ridge regression
(``/root/reference/src/models.py:8-22``); this adds the nonlinear model a
reference user would reach for next — a small multilayer perceptron over
the same five minute-bar features — without changing anything around it:
the scaler / expanding-fold / score-everything scaffold is the one shared
implementation in :func:`csmom_tpu.models.ridge.time_series_cv_harness`,
so the fold layout, train split, and leakage semantics are identical to
the reference pipeline by construction.

TPU-native form: with F=5 features and ~10^4-10^5 rows, full-batch
gradient descent is a handful of tiny matmuls per step — the whole
training loop (AdamW under ``lax.scan`` for a fixed step count) is one
XLA program with no host round-trips, and the fit for every CV fold plus
the final model runs inside a single jit call.  No data-dependent
stopping: a fixed ``n_steps`` keeps one trace/one executable, the same
design rule as the FISTA loop in :mod:`csmom_tpu.models.elastic_net`.

Determinism and shard-invariance: parameters are initialized from an
explicit ``jax.random.PRNGKey(seed)``; masked rows enter the loss with
weight zero, so the fit depends only on the (ordered) set of valid rows —
not on padding layout or device partitioning.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MLPFit:
    params: list              # [(W f[in,out], b f[out]) per layer] pytree
    scale_mean: jnp.ndarray   # f[F]
    scale_std: jnp.ndarray    # f[F]
    cv_mse: jnp.ndarray       # f[n_splits]
    scores: jnp.ndarray       # f[A, R]
    n_train: jnp.ndarray      # i32
    train_mse: jnp.ndarray    # f[] final-model MSE on its training rows


def _init_params(key, sizes, dtype):
    """He-normal hidden weights, zero biases — and a zero output layer, so
    the initial prediction is exactly 0 and the initial loss is var(y).
    With ~1e-4-scale return labels, a random head starts the loss several
    orders of magnitude above the signal and wastes the whole step budget
    shrinking itself; zero-init makes every step spent on structure."""
    params = []
    n_layers = len(sizes) - 1
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        if i == n_layers - 1 and n_layers > 1:
            w = jnp.zeros((fan_in, fan_out), dtype)
        else:
            w = jax.random.normal(sub, (fan_in, fan_out), dtype) * jnp.sqrt(
                jnp.asarray(2.0 / fan_in, dtype)
            )
        params.append((w, jnp.zeros((fan_out,), dtype)))
    return params


def _forward(params, X):
    """ReLU MLP; last layer linear, squeezed to one score per row."""
    h = X
    for w, b in params[:-1]:
        h = jax.nn.relu(h @ w + b)
    w, b = params[-1]
    return (h @ w + b)[:, 0]


def _fit_mlp(Xs, y, w, key, hidden, n_steps, learning_rate, weight_decay):
    """Full-batch AdamW for a fixed step count on rows weighted by w (0/1).

    Returns the trained parameter pytree.
    """
    # optax is an optional dependency (pyproject extra 'mlp'); importing it
    # here keeps `import csmom_tpu.models` working for linear-model users
    import optax

    dtype = Xs.dtype
    sizes = (Xs.shape[1],) + tuple(hidden) + (1,)
    params = _init_params(key, sizes, dtype)
    opt = optax.adamw(learning_rate, weight_decay=weight_decay)
    n = jnp.maximum(jnp.sum(w), 1.0)

    def loss_fn(p):
        pred = _forward(p, Xs)
        return jnp.sum(w * (pred - y) ** 2) / n

    def step(carry, _):
        p, opt_state = carry
        grads = jax.grad(loss_fn)(p)
        updates, opt_state = opt.update(grads, opt_state, p)
        return (optax.apply_updates(p, updates), opt_state), None

    (params, _), _ = jax.lax.scan(
        step, (params, opt.init(params)), None, length=n_steps
    )
    return params


@partial(
    jax.jit,
    static_argnames=("n_splits", "hidden", "n_steps", "train_frac_small", "seed"),
)
def mlp_time_series_cv(
    features,
    y,
    valid,
    n_splits: int = 3,
    hidden: tuple = (32, 16),
    n_steps: int = 500,
    learning_rate: float = 1e-2,
    weight_decay: float = 1e-4,
    seed: int = 0,
    train_frac: float = 0.7,
    train_frac_small: float = 0.6,
    small_threshold: int = 100,
) -> MLPFit:
    """Scale -> expanding-window CV -> final MLP -> score full history.

    Args:
      features: f[A, R, F] compacted feature tensor (padded rows arbitrary).
      y: f[A, R] next-row return labels.
      valid: bool[A, R] modeling rows.
      hidden: hidden-layer widths; ``()`` degenerates to a linear model
        trained by gradient descent (a useful sanity anchor against ridge).
      n_steps: fixed full-batch AdamW steps per fit (per fold + final).

    Returns :class:`MLPFit`; ``scores`` covers every valid row, matching
    the reference demo's score-the-training-span-too behaviour.
    """
    from csmom_tpu.models.ridge import time_series_cv_harness

    key = jax.random.PRNGKey(seed)
    solver = lambda Xs, yf, w: _fit_mlp(
        Xs, yf, w, key, hidden, n_steps, learning_rate, weight_decay
    )
    params, mean, std, cv_mse, scores, n_train, w_tr = time_series_cv_harness(
        features, y, valid,
        solver=solver,
        n_splits=n_splits, train_frac=train_frac,
        train_frac_small=train_frac_small, small_threshold=small_threshold,
        predict=_forward,
    )

    # final-model training error, for the fit-quality diagnostic the linear
    # models get from their closed forms — derived from the scores and the
    # train mask the harness itself produced, so it cannot drift from the
    # model or the fold layout
    A, R = y.shape
    sf = jnp.nan_to_num(scores.reshape(A * R))
    yf = jnp.nan_to_num(y.reshape(A * R))
    train_mse = jnp.sum(w_tr * (sf - yf) ** 2) / jnp.maximum(jnp.sum(w_tr), 1.0)

    return MLPFit(
        params=params,
        scale_mean=mean,
        scale_std=std,
        cv_mse=cv_mse,
        scores=scores,
        n_train=n_train,
        train_mse=train_mse,
    )
