"""Online (recursive) ridge: strictly-causal walk-forward scores in one scan.

The reference's modeling scaffold scores its own training rows by design
(``/root/reference/run_demo.py:139-147``; SURVEY §2.1.4 documents the
leak), and the rebuild replicates that for parity (``models/ridge.py``).
This module is the leak-free counterpart the reference never had: every
row t is scored by a model fit ONLY on rows seen before t, and the whole
walk-forward — scaler, fit, one-step-ahead prediction at every row — is
ONE ``lax.scan``, not R refits.

Recursions (rank-1 Sherman–Morrison on the regularized inverse Gram):

    P_t = P_{t-1} - (P_{t-1} x_t x_t^T P_{t-1}) / (1 + x_t^T P_{t-1} x_t)
    b_t = b_{t-1} + x_t y_t            =>   w_t = P_t b_t

with ``P_0 = I/alpha`` so ``P_t = (X_{1..t}^T X_{1..t} + alpha I)^{-1}``
exactly.  Each step is O(F^2) on a (F+1)-sized augmented state — this is
the recursive-least-squares filter family (same sequential structure as a
Kalman update), expressed as a scan carry so XLA compiles one kernel for
the whole history.

Design choices, stated plainly:

- **Intercept is a penalized augmented column.**  ``x_aug = [x, 1]`` and
  the SAME alpha applies to the intercept weight (sklearn's
  ``fit_intercept=True`` centers instead and does not penalize it).
  Minute-return labels are ~1e-4, so the intercept is ~0 and the
  deviation is immaterial; the batch-parity test pins the augmented
  formulation exactly.
- **Causal standardization.**  With ``standardize=True`` each row is
  scaled by the running mean/std of the rows BEFORE it (Welford moments
  carried in the same scan).  The representation therefore drifts early
  on — standard online-learning behaviour; the oracle test replays the
  identical recursion sequentially, so parity is exact, and the
  ``standardize=False`` path is additionally pinned against the batch
  closed form.
- **Row-blocked time order.**  The scan iterates over rows r; at each
  step EVERY asset's row r is scored with the state from rows < r, and
  only then do row r's (x, y) pairs update the state (a static inner
  fold of rank-1 updates).  Scoring asset B's row r after updating with
  asset A's row r would leak: y[A, r] is the r -> r+1 return —
  unknowable at decision time r, and cross-sectionally correlated with
  y[B, r] through the market factor.  The running scaler moments update
  after the row for the same reason of determinism (features at r are
  observable at r, so either order is causal for x; labels are not).
  Asset-major flattening (the reference's (ticker, datetime) TRAIN/TEST
  split order, fine for a static split) would be worse still — asset
  B's early rows scored by a model that has seen asset A's late rows.
- **Prequential quality.**  ``cv_mse[i]`` is the mean squared one-step-
  ahead error over the i-th of ``n_splits`` contiguous blocks of scored
  rows — the online analogue of the expanding-window fold MSEs, except
  every row is out-of-sample by construction.

Masked rows (``valid == False``) are true no-ops: they neither update the
state nor receive a score.

The building blocks (`_causal_scale`, `_row_sm_update`,
`_row_moment_update`, `_make_row_step`, `_prequential_fit`) are module-
level so the time-sharded sequence-parallel variant
(:mod:`csmom_tpu.parallel.online_ridge`) runs the SAME per-row math
inside each shard — only the carry seeding differs there.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class OnlineRidgeFit:
    coef: jnp.ndarray        # f[F] final weights on (causally) scaled features
    intercept: jnp.ndarray   # f[] final augmented-intercept weight
    scale_mean: jnp.ndarray  # f[F] final running mean (causal scaler state)
    scale_std: jnp.ndarray   # f[F] final running std
    cv_mse: jnp.ndarray      # f[n_splits] prequential MSE per contiguous block
    scores: jnp.ndarray      # f[A, R] strictly-causal one-step-ahead predictions
    n_train: jnp.ndarray     # i32 rows ever updated on (== n valid rows)


def _causal_scale(X, cnt, mean, M2, standardize: bool):
    """Scale a row's features by the moments of rows strictly before it."""
    if not standardize:
        return X
    std = jnp.sqrt(jnp.maximum(M2 / jnp.maximum(cnt, 1.0), 1e-24))
    std = jnp.where(std > 1e-12, std, 1.0)
    return (X - mean) / std


def _row_sm_update(P, b, Xa, yt, w):
    """Fold one row's per-asset rank-1 Sherman-Morrison updates (masked)."""
    def upd(a, Pb):
        P_, b_ = Pb
        xw = Xa[a] * w[a]  # w=0 zeroes the update exactly (Px=0, denom=1)
        Px = P_ @ xw
        return (P_ - jnp.outer(Px, Px) / (1.0 + xw @ Px), b_ + xw * yt[a])

    return jax.lax.fori_loop(0, Xa.shape[0], upd, (P, b))


def _row_moment_update(cnt, mean, M2, X, w):
    """Fold one row's per-asset Welford updates on the RAW features."""
    def upd_m(a, state):
        cnt_, mean_, M2_ = state
        cnt2 = cnt_ + w[a]
        delta = X[a] - mean_
        mean2 = mean_ + w[a] * delta / jnp.maximum(cnt2, 1.0)
        M22 = M2_ + w[a] * delta * (X[a] - mean2)
        return cnt2, mean2, M22

    return jax.lax.fori_loop(0, X.shape[0], upd_m, (cnt, mean, M2))


def _make_row_step(A: int, dt, burn_in: int, standardize: bool):
    """The per-row scan step: score the whole row with the prior state,
    then apply the row's updates.  Carry: ``(P, b, cnt, mean, M2)``."""
    def step(carry, inp):
        P, b, cnt, mean, M2 = carry
        X, yt, w = inp  # X f[A, F], yt f[A], w f[A]
        Xs = _causal_scale(X, cnt, mean, M2, standardize)
        Xa = jnp.concatenate([Xs, jnp.ones((A, 1), dt)], axis=1)
        # EVERY asset's row scored with the prior weights, before any of
        # this row's labels touch the state (y[., r] is the r -> r+1
        # return — updating asset A then scoring asset B would leak the
        # contemporaneous future through cross-sectional correlation)
        preds = Xa @ (P @ b)
        P_new, b_new = _row_sm_update(P, b, Xa, yt, w)
        cnt_new, mean_new, M2_new = _row_moment_update(cnt, mean, M2, X, w)
        seen_enough = cnt >= burn_in  # prior count: the model behind preds
        return (
            (P_new, b_new, cnt_new, mean_new, M2_new),
            (preds, jnp.broadcast_to(seen_enough, (A,))),
        )

    return step


def _prequential_fit(
    preds, seen, wr, yr, n_splits: int, w_final, cnt, mean, M2
) -> OnlineRidgeFit:
    """Assemble OnlineRidgeFit from scan outputs + final state.

    ``preds/seen/wr/yr`` are time-major ``[R, A]``; ``w_final`` the final
    augmented weights; ``(cnt, mean, M2)`` the final raw-feature moments.
    """
    R, A = preds.shape
    dt = preds.dtype
    F = mean.shape[0]

    scored = (wr > 0) & seen  # bool[R, A]
    preds = jnp.where(scored, preds, jnp.nan)
    scores = jnp.swapaxes(preds, 0, 1)

    # prequential MSE over n_splits contiguous blocks of scored rows
    scored_f = scored.reshape(R * A)
    yf = yr.reshape(R * A)
    preds_f = preds.reshape(R * A)
    ordinal = jnp.cumsum(scored_f) - 1
    n_scored = jnp.sum(scored_f)
    block = jnp.minimum(
        (ordinal * n_splits) // jnp.maximum(n_scored, 1), n_splits - 1
    )
    err2 = jnp.where(scored_f, (jnp.nan_to_num(preds_f) - yf) ** 2, 0.0)

    def block_mse(i):
        wb = (scored_f & (block == i)).astype(dt)
        return jnp.sum(wb * err2) / jnp.maximum(jnp.sum(wb), 1.0)

    cv_mse = jnp.stack([block_mse(i) for i in range(n_splits)])

    std = jnp.sqrt(jnp.maximum(M2 / jnp.maximum(cnt, 1.0), 1e-24))
    std = jnp.where(std > 1e-12, std, 1.0)
    return OnlineRidgeFit(
        coef=w_final[:F],
        intercept=w_final[F],
        scale_mean=mean,
        scale_std=std,
        cv_mse=cv_mse,
        scores=scores,
        n_train=jnp.sum(wr).astype(jnp.int32),
    )


@partial(jax.jit, static_argnames=("n_splits", "burn_in", "standardize"))
def online_ridge_scores(
    features,
    y,
    valid,
    alpha: float = 1.0,
    n_splits: int = 3,
    burn_in: int = 30,
    standardize: bool = True,
) -> OnlineRidgeFit:
    """Walk-forward ridge scores for every valid row, in one compiled scan.

    Args:
      features: f[A, R, F] compacted feature tensor (padded rows arbitrary).
      y: f[A, R] next-row return labels.
      valid: bool[A, R] modeling rows (features and label all defined).
      alpha: ridge penalty (applies to the augmented intercept too — see
        module docstring).
      n_splits: number of contiguous prequential-MSE blocks reported.
      burn_in: rows that must have updated the state before scores start
        (earlier rows update but score NaN — a 5-row model is noise).
      standardize: causally standardize features by prior running moments.

    Returns OnlineRidgeFit; ``scores[a, r]`` used none of row (a, r) itself
    nor any row at a later scan position.
    """
    A, R, F = features.shape
    dt = features.dtype
    # row-blocked time order: scan over rows, [R, A, ...] leading axis
    Xr = jnp.nan_to_num(jnp.swapaxes(features, 0, 1))  # f[R, A, F]
    yr = jnp.nan_to_num(jnp.swapaxes(y, 0, 1))         # f[R, A]
    wr = jnp.swapaxes(valid, 0, 1).astype(dt)          # f[R, A]

    carry0 = (
        jnp.eye(F + 1, dtype=dt) / jnp.asarray(alpha, dt),
        jnp.zeros(F + 1, dt),
        jnp.zeros((), dt),
        jnp.zeros(F, dt),
        jnp.zeros(F, dt),
    )
    step = _make_row_step(A, dt, burn_in, standardize)
    (P, b, cnt, mean, M2), (preds, seen) = jax.lax.scan(
        step, carry0, (Xr, yr, wr)
    )
    return _prequential_fit(preds, seen, wr, yr, n_splits, P @ b, cnt, mean, M2)
