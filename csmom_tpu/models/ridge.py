"""Closed-form ridge regression with expanding-window time-series CV.

Reference: ``train_ridge_time_series`` (``/root/reference/src/models.py:8-22``)
— StandardScaler fit on the full passed-in X (pre-CV, so folds share scaling
stats; SURVEY §2.1.4 documents the leak as by-design), sklearn
``TimeSeriesSplit(n_splits)`` expanding-window folds collecting per-fold MSE,
and a final ``Ridge(alpha)`` refit on everything.

TPU-native form: no sklearn, no row iteration.  With 5 features the normal
equations are a 6x6 solve; every reduction (scaler moments, Gram matrices,
fold MSEs) is a masked einsum over the padded ``[A, R, F]`` feature tensor.
Fold membership is pure index arithmetic on the *global row ordinal* — the
position each valid row would occupy in the reference's
sort-by-(ticker, datetime) flattening — so the expanding folds are masks,
not slices, and the whole fit (scaler + n_splits folds + final model +
full-history scoring) is one jit call.

Matches sklearn numerically to ~1e-12 in f64: Ridge(alpha, fit_intercept
=True) solves the centered system ``(Xc'Xc + alpha*I) w = Xc'y``; the
TimeSeriesSplit fold layout is ``test_size = n // (n_splits+1)`` with fold i
testing ``[n - (n_splits-i)*test_size, +test_size)``.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RidgeFit:
    coef: jnp.ndarray        # f[F] on scaled features
    intercept: jnp.ndarray   # f[] scalar
    scale_mean: jnp.ndarray  # f[F] scaler mean (ddof=0 std below)
    scale_std: jnp.ndarray   # f[F]
    cv_mse: jnp.ndarray      # f[n_splits]
    scores: jnp.ndarray      # f[A, R] predictions over every valid row
    n_train: jnp.ndarray     # i32 number of training rows


def _masked_ridge(Xs, y, w, alpha):
    """Solve Ridge(alpha, fit_intercept=True) over rows weighted by w (0/1).

    Returns (coef f[F], intercept f[]).
    """
    n = jnp.maximum(jnp.sum(w), 1.0)
    xbar = jnp.einsum("r,rf->f", w, Xs) / n
    ybar = jnp.sum(w * y) / n
    Xc = (Xs - xbar) * w[:, None]
    yc = (y - ybar) * w
    G = Xc.T @ Xc + alpha * jnp.eye(Xs.shape[1], dtype=Xs.dtype)
    b = Xc.T @ yc
    coef = jnp.linalg.solve(G, b)
    intercept = ybar - xbar @ coef
    return coef, intercept


def time_series_cv_harness(
    features,
    y,
    valid,
    solver,
    n_splits: int,
    train_frac: float,
    train_frac_small: float,
    small_threshold: int,
    predict=None,
):
    """Shared prepare -> scale -> expanding-CV -> final-fit -> score harness.

    The one implementation of the reference pipeline's modeling scaffold
    (``run_demo.py:139-147`` + ``models.py:8-22``) used by every score
    model: flatten to the global (ticker, datetime) row order, train on the
    leading ``train_frac`` of valid rows, fit the scaler on that training
    block, run ``TimeSeriesSplit``-layout expanding folds, refit on the
    full training block, score the entire history.

    ``solver(Xs, yf, w)`` fits one model on rows weighted by w (0/1) and
    returns its parameters — any pytree; it is called per fold and for the
    final fit, so any model that can fit a weighted row set plugs in.
    ``predict(params, Xs)`` maps those parameters to per-row predictions;
    the default treats ``params`` as ``(coef f[F], intercept f[])``, the
    linear-model case.

    Returns ``(params, mean, std, cv_mse, scores, n_train, train_w)``;
    ``train_w f[A*R]`` is the final fit's 0/1 row weights, so callers that
    need training-block diagnostics use the harness's own mask rather than
    re-deriving the ordinal arithmetic.
    """
    if predict is None:
        predict = lambda params, Xs: Xs @ params[0] + params[1]
    A, R, F = features.shape
    Xf = jnp.nan_to_num(features.reshape(A * R, F))
    yf = jnp.nan_to_num(y.reshape(A * R))
    vf = valid.reshape(A * R)

    # global row ordinal in (asset, row) order == reference row order
    ordinal = jnp.cumsum(vf) - 1
    n_total = jnp.sum(vf)
    frac = jnp.where(n_total > small_threshold, train_frac, train_frac_small)
    n_train = jnp.floor(n_total * frac).astype(jnp.int32)
    train = vf & (ordinal < n_train)

    # scaler fit on the training block only (models.py:9-10 receives X[:split])
    w_tr = train.astype(Xf.dtype)
    n_tr = jnp.maximum(jnp.sum(w_tr), 1.0)
    mean = jnp.einsum("r,rf->f", w_tr, Xf) / n_tr
    var = jnp.einsum("r,rf->f", w_tr, (Xf - mean) ** 2) / n_tr
    std = jnp.sqrt(var)
    # sklearn maps zero-variance features to scale 1; a constant column can
    # leave ~eps**2 variance from float accumulation, so compare relative to
    # the feature magnitude rather than exact zero
    tiny = 1e-12 * jnp.maximum(jnp.abs(mean), 1.0)
    std = jnp.where(std > tiny, std, 1.0)
    Xs = (Xf - mean) / std

    # sklearn TimeSeriesSplit over the n_train training rows
    test_size = n_train // (n_splits + 1)

    def fold(i):
        test_start = n_train - (n_splits - i) * test_size
        tr = train & (ordinal < test_start)
        te = train & (ordinal >= test_start) & (ordinal < test_start + test_size)
        params = solver(Xs, yf, tr.astype(Xf.dtype))
        pred = predict(params, Xs)
        wte = te.astype(Xf.dtype)
        mse = jnp.sum(wte * (pred - yf) ** 2) / jnp.maximum(jnp.sum(wte), 1.0)
        return mse

    cv_mse = jnp.stack([fold(i) for i in range(n_splits)])

    params = solver(Xs, yf, w_tr)
    scores = predict(params, Xs).reshape(A, R)
    scores = jnp.where(valid, scores, jnp.nan)
    return params, mean, std, cv_mse, scores, n_train, w_tr


@partial(jax.jit, static_argnames=("n_splits", "train_frac_small"))
def ridge_time_series_cv(
    features,
    y,
    valid,
    n_splits: int = 3,
    alpha: float = 1.0,
    train_frac: float = 0.7,
    train_frac_small: float = 0.6,
    small_threshold: int = 100,
) -> RidgeFit:
    """Scale -> expanding-window CV -> final ridge -> score full history.

    Args:
      features: f[A, R, F] compacted feature tensor (padded rows arbitrary).
      y: f[A, R] next-row return labels.
      valid: bool[A, R] modeling rows (features and label all defined).
      n_splits: CV folds (reference runs 3, models.py called at run_demo:140).
      alpha: ridge penalty.
      train_frac: leading fraction of rows used for training — the driver
        trains on the first 70% (60% when n <= 100) of rows in
        (ticker, datetime) order and scores everything (run_demo.py:139-147).

    Returns RidgeFit; ``scores`` covers every valid row (the by-design
    "score the training span too" behaviour of the demo).
    """
    (coef, icept), mean, std, cv_mse, scores, n_train, _ = time_series_cv_harness(
        features, y, valid,
        solver=lambda Xs, yf, w: _masked_ridge(Xs, yf, w, alpha),
        n_splits=n_splits, train_frac=train_frac,
        train_frac_small=train_frac_small, small_threshold=small_threshold,
    )
    return RidgeFit(
        coef=coef,
        intercept=icept,
        scale_mean=mean,
        scale_std=std,
        cv_mse=cv_mse,
        scores=scores,
        n_train=n_train,
    )
