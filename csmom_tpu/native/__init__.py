"""Native runtime components (C++ via ctypes).

The compute path is JAX/XLA; the runtime around it — here, the CSV ingest
hot loop — is native C++ (``fastcsv.cpp``), compiled on first use with the
system toolchain into a per-version cached shared object and bound through
``ctypes`` (this image ships no pybind11).  Every native entry point has a
pure-Python fallback, so the package works even without a compiler;
``parse_price_csv_native`` returns None in that case and callers fall back.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

from csmom_tpu.utils.logging import get_logger

log = get_logger("native")

_SRC = os.path.join(os.path.dirname(__file__), "fastcsv.cpp")
_LIB = None
_LIB_FAILED = False


def _cache_dir() -> str:
    base = os.environ.get("CSMOM_NATIVE_CACHE") or os.path.join(
        tempfile.gettempdir(), "csmom_native"
    )
    os.makedirs(base, exist_ok=True)
    return base


def _build() -> str | None:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"fastcsv_{tag}.so")
    if os.path.exists(out):
        return out
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", out]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception as e:  # no compiler / failed build -> Python fallback
        log.warning("native build failed (%s); using Python ingest fallback", e)
        return None
    return out


def get_lib():
    """Load (building if needed) the native library; None when unavailable."""
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    path = _build()
    if path is None:
        _LIB_FAILED = True
        return None
    lib = ctypes.CDLL(path)
    lib.fastcsv_count_rows.argtypes = [ctypes.c_char_p]
    lib.fastcsv_count_rows.restype = ctypes.c_longlong
    lib.fastcsv_parse.argtypes = [
        ctypes.c_char_p,
        ctypes.c_longlong,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.fastcsv_parse.restype = ctypes.c_longlong
    _LIB = lib
    return _LIB


def parse_price_csv_native(path: str, n_cols: int):
    """Parse a price CSV's data rows natively.

    Returns ``(epoch_ns i64[R], values f64[R, n_cols])`` or None when the
    native library is unavailable (callers use the pandas path then).
    Preamble/junk rows (both reference cache dialects) are skipped by the
    same first-cell-is-a-date rule as ``panel.ingest.read_price_csv``.
    """
    lib = get_lib()
    if lib is None:
        return None
    cap = lib.fastcsv_count_rows(path.encode())
    if cap < 0:
        raise FileNotFoundError(path)
    cap = max(int(cap), 1)
    epochs = np.empty(cap, dtype=np.int64)
    values = np.empty((cap, n_cols), dtype=np.float64)
    rows = lib.fastcsv_parse(
        path.encode(),
        cap,
        n_cols,
        epochs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if rows < 0:
        raise OSError(f"native parse failed for {path}")
    return epochs[:rows], values[:rows]
