// Fast CSV price-bar parser (native runtime component).
//
// The reference's ingest is pandas read_csv + defensive renaming
// (/root/reference/src/data_io.py:23-129).  This parser covers the hot
// ingest path of the rebuild — fixed-layout price CSVs (a timestamp first
// column, numeric columns after) in either cache dialect — in a single
// pass with zero Python-object churn, feeding numpy buffers directly.
//
// Contract (mirrors panel/ingest.py::read_price_csv semantics, and is
// parity-tested cell-for-cell against the pandas engine incl. a CSV
// fuzzer, tests/test_native.py):
//   - rows whose first cell (after unquoting/trimming) does not start with
//     a digit are preamble/junk and are skipped (dialect A junk ticker
//     row, dialect B Ticker/Date rows, the header itself);
//   - timestamps: "YYYY-MM-DD", optionally " HH:MM[:SS[.frac]]",
//     optionally a "+HH:MM"/"-HH:MM" UTC offset (normalized to UTC) — the
//     formats yfinance caches actually contain.  The whole cell must
//     parse (pandas' to_datetime(errors='coerce') semantics: trailing
//     junk -> dropped row, not a half-parsed date);
//   - cells split on commas OUTSIDE double quotes (RFC-4180 quoting, the
//     part of it price CSVs can contain; embedded newlines unsupported);
//   - empty/unparseable numeric cells become NaN; the whole cell must
//     parse (strtod prefix-parses "12abc" to 12, pandas' to_numeric
//     coerces it to NaN — full consumption keeps the engines identical);
//   - short rows are padded with NaN, long rows truncated to n_cols.
//
// Exposed via a C ABI for ctypes (no pybind11 in this image).

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// days from civil date to days since 1970-01-01 (Howard Hinnant's algorithm)
inline int64_t days_from_civil(int y, int m, int d) {
    y -= m <= 2;
    const int era_base = (y >= 0 ? y : y - 399) / 400;
    const unsigned yoe = static_cast<unsigned>(y - era_base * 400);
    const unsigned doy = (153u * (m + (m > 2 ? -3 : 9)) + 2u) / 5u + d - 1u;
    const unsigned doe = yoe * 365u + yoe / 4u - yoe / 100u + doy;
    return static_cast<int64_t>(era_base) * 146097 + static_cast<int64_t>(doe) - 719468;
}

// parse up to `width` digits; returns -1 on non-digit
inline int parse_digits(const char*& p, const char* end, int width) {
    int v = 0, n = 0;
    while (p < end && n < width && *p >= '0' && *p <= '9') {
        v = v * 10 + (*p - '0');
        ++p;
        ++n;
    }
    return n ? v : -1;
}

// Cell trimming with pandas' quote semantics: a double quote is special
// ONLY at field start (its C parser treats mid-field quotes as literal
// text).  Strip trailing CR/spaces, then one wrapping quote pair if the
// field begins with a quote, then surrounding spaces.
inline void trim_cell(const char*& s, const char*& end) {
    while (end > s && (end[-1] == '\r' || end[-1] == ' ')) --end;
    if (end - s >= 2 && *s == '"' && end[-1] == '"') {
        ++s;
        --end;
    }
    while (s < end && *s == ' ') ++s;
    while (end > s && end[-1] == ' ') --end;
}

// next field separator; a field OPENING with a double quote protects
// commas until its closing quote ("" escapes a literal quote), matching
// pandas' parser — a quote later in the field is literal and protects
// nothing
inline const char* next_sep(const char* p, const char* line_end) {
    if (p < line_end && *p == '"') {
        const char* q = p + 1;
        while (q < line_end) {
            if (*q == '"') {
                if (q + 1 < line_end && q[1] == '"') {
                    q += 2;  // escaped quote
                    continue;
                }
                ++q;  // closing quote
                break;
            }
            ++q;
        }
        p = q;
    }
    const char* c = static_cast<const char*>(memchr(p, ',', line_end - p));
    return c ? c : line_end;
}

// calendar-valid day count (pandas to_datetime rejects e.g. Feb 31)
inline int days_in_month(int y, int m) {
    static const int dm[] = {31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31};
    if (m == 2)
        return ((y % 4 == 0 && y % 100 != 0) || y % 400 == 0) ? 29 : 28;
    return dm[m - 1];
}

// timestamp cell -> epoch nanoseconds (UTC); returns false unless the
// whole cell is a date (pandas to_datetime coerce semantics)
bool parse_timestamp(const char* s, const char* end, int64_t* out_ns) {
    const char* p = s;
    int y = parse_digits(p, end, 4);
    if (y < 1000 || p >= end || *p != '-') return false;
    ++p;
    int mo = parse_digits(p, end, 2);
    if (mo < 1 || mo > 12 || p >= end || *p != '-') return false;
    ++p;
    int d = parse_digits(p, end, 2);
    if (d < 1 || d > days_in_month(y, mo)) return false;

    int64_t sec = days_from_civil(y, mo, d) * 86400;
    int64_t frac_ns = 0;
    if (p < end && (*p == ' ' || *p == 'T')) {
        ++p;
        int hh = parse_digits(p, end, 2);
        if (hh < 0 || hh > 23 || p >= end || *p != ':') return false;
        ++p;
        int mi = parse_digits(p, end, 2);
        if (mi < 0 || mi > 59) return false;
        int ss = 0;
        if (p < end && *p == ':') {
            ++p;
            ss = parse_digits(p, end, 2);
            if (ss < 0 || ss > 59) return false;
        }
        sec += hh * 3600 + mi * 60 + ss;
        // fractional seconds, kept at ns precision (pandas keeps them too;
        // dropping them would silently desynchronize the two engines)
        if (p < end && *p == '.') {
            ++p;
            int64_t scale = 100000000;  // first digit is 1e8 ns
            bool any = false;
            while (p < end && *p >= '0' && *p <= '9') {
                if (scale > 0) {
                    frac_ns += (*p - '0') * scale;
                    scale /= 10;
                }
                ++p;
                any = true;
            }
            if (!any) return false;
        }
        // UTC offset (strict: out-of-range offsets are not timestamps)
        if (p < end && (*p == '+' || *p == '-')) {
            int sign = (*p == '-') ? -1 : 1;
            ++p;
            int oh = parse_digits(p, end, 2);
            if (oh < 0 || oh > 23) return false;
            int om = 0;
            if (p < end && *p == ':') {
                ++p;
                om = parse_digits(p, end, 2);
                if (om < 0 || om > 59) return false;
            }
            sec -= sign * (oh * 3600 + om * 60);
        }
    }
    if (p != end) return false;  // trailing junk -> not a timestamp
    *out_ns = sec * 1000000000LL + frac_ns;
    return true;
}

// one numeric cell [s, end) -> double (NaN on empty/garbage).  The whole
// cell must be consumed: strtod prefix-parses ("12abc" -> 12) where
// pandas' to_numeric coerces to NaN, and strtod accepts hex ("0x1f")
// where pandas does not — both are rejected here for engine parity.
inline double parse_cell(const char* s, const char* end) {
    trim_cell(s, end);
    if (s >= end) return NAN;
    char buf[64];
    size_t n = static_cast<size_t>(end - s);
    if (n >= sizeof(buf)) return NAN;
    memcpy(buf, s, n);
    buf[n] = '\0';
    for (const char* h = buf; *h; ++h)
        if (*h == 'x' || *h == 'X') return NAN;  // hex (strtod-only) -> NaN
    char* q = nullptr;
    double v = strtod(buf, &q);
    if (q == buf) return NAN;
    while (*q == ' ') ++q;
    if (*q != '\0') return NAN;
    return v;
}

}  // namespace

extern "C" {

// Upper bound on data rows (= newline count); -1 if the file can't be read.
long long fastcsv_count_rows(const char* path) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    long long lines = 0;
    char buf[1 << 16];
    size_t got;
    while ((got = fread(buf, 1, sizeof(buf), f)) > 0)
        for (size_t i = 0; i < got; ++i)
            if (buf[i] == '\n') ++lines;
    fclose(f);
    return lines + 1;
}

// Parse `path` into epoch_ns[max_rows] and values[max_rows * n_cols]
// (row-major).  Returns the number of data rows written, or -1 on I/O
// error.  Preamble rows (first cell not starting with a digit) and '#'
// comment lines are skipped.
long long fastcsv_parse(const char* path, long long max_rows, int n_cols,
                        int64_t* epoch_ns, double* values) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    char* data = static_cast<char*>(malloc(static_cast<size_t>(sz) + 1));
    if (!data) {
        fclose(f);
        return -1;
    }
    size_t got = fread(data, 1, static_cast<size_t>(sz), f);
    fclose(f);
    data[got] = '\0';

    long long rows = 0;
    const char* p = data;
    const char* file_end = data + got;
    while (p < file_end && rows < max_rows) {
        const char* line_end = static_cast<const char*>(memchr(p, '\n', file_end - p));
        if (!line_end) line_end = file_end;

        if (p < line_end && *p != '#') {
            const char* cell_end = next_sep(p, line_end);
            const char* ts = p;
            const char* ts_end = cell_end;
            trim_cell(ts, ts_end);  // pandas unquotes before parsing dates
            int64_t ns;
            if (ts < ts_end && *ts >= '0' && *ts <= '9' &&
                parse_timestamp(ts, ts_end, &ns)) {
                epoch_ns[rows] = ns;
                double* row = values + rows * n_cols;
                const char* q = (cell_end < line_end) ? cell_end + 1 : line_end;
                for (int c = 0; c < n_cols; ++c) {
                    if (q > line_end) {
                        row[c] = NAN;
                        continue;
                    }
                    const char* next = next_sep(q, line_end);
                    row[c] = parse_cell(q, next);
                    q = next + 1;
                }
                ++rows;
            }
        }
        p = line_end + 1;
    }
    free(data);
    return rows;
}

}  // extern "C"
